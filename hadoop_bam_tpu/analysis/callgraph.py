"""Shared interprocedural engine for the hbam-lint analyzers.

Three analyzers need more than single-function AST walks: trace safety
(TS1xx) propagates tracer-ness along project-internal calls, the obs
rules (OB6xx) need to know which nested functions a dispatcher hands to
the decode pool, and the thread-safety rules (TH1xx/LK2xx) need the
whole thread topology — which functions run on which threads, what
shared state each can reach, and which locks are held on the way.
This module is the one place that machinery lives:

- ``ModuleIndex``: per-module function/import/alias index (extracted
  from ``trace_safety``'s private ``_ModuleIndex``).
- ``InterproceduralWorklist``: the generic (module path, qualname) →
  param-set propagation fixpoint that trace safety's taint pass runs on,
  including cross-module ``import`` key resolution and positional
  (``#N``) argument markers.
- ``CallGraphEngine``: call resolution (lexical names, ``self.m()``
  methods, dotted imports), **thread-root discovery**
  (``threading.Thread(target=...)`` — including the ``ctx.run``
  and ``lambda: ctx.run(f)`` indirections the repo uses to carry
  contextvars onto worker threads — executor/pool ``submit``/``map``
  callables, ``add_done_callback``, and the named ``handle_stream``
  TCP-handler root), per-root reachability, shared-state access
  collection (``self`` attributes, module globals, closure cells), and
  interprocedural **guard inference**: the set of locks provably held
  at every access, combining lexical ``with <lock>:`` context with an
  intersection-over-call-sites entry-guard fixpoint.

The engine is deliberately conservative in both directions that matter
for an empty-baseline gate: unresolvable calls (dynamic dispatch,
callables in variables) silently end a reachability edge rather than
guessing, and accesses through receivers other than ``self`` are
skipped rather than alias-analyzed — precision over recall, so the
repo gate stays actionable.
"""
from __future__ import annotations

import ast
import dataclasses
from typing import (
    Callable, Dict, FrozenSet, Iterator, List, Optional, Sequence, Set,
    Tuple,
)

from hadoop_bam_tpu.analysis.astutil import (
    FuncInfo, collect_functions, dotted_name, enclosing_function,
    import_aliases, last_segment, resolve_name,
)
from hadoop_bam_tpu.analysis.core import Project

# (module path, qualname) — the identity every interprocedural pass keys on
FuncKey = Tuple[str, str]

# Access / lock identities.  Tuples, not classes, so they hash and sort:
#   ('attr',    class qualname, attr)          self.X on a known class
#   ('global',  module path, name)             module-level variable
#   ('closure', module path, owner qualname, name)   cell of an enclosing fn
#   ('local',   module path, owner qualname, name)   function-local (locks)
AccessId = Tuple[str, ...]

# -- shared vocabulary -------------------------------------------------------

# dispatcher entry points that hand a callable to the shared decode pool
# (used by obsrules' OB602 and by thread-root discovery)
POOL_DISPATCHERS = {"_iter_windowed", "submit", "pool_submit", "map"}

# constructors whose instances ARE locks for guard purposes: holding one
# in a `with` block establishes mutual exclusion
_LOCK_CONSTRUCTORS = {"Lock", "RLock", "Condition"}

# constructors whose instances are internally thread-safe: mutating them
# without a guard is fine (their own locking is the guard)
_THREADSAFE_CONSTRUCTORS = {
    "Queue", "SimpleQueue", "LifoQueue", "PriorityQueue", "Event",
    "Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore",
    "Barrier", "ContextVar", "local", "Thread", "Timer",
}

# container-mutating method names: receiver.m(...) writes the receiver
_MUTATOR_METHODS = {
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "remove", "discard", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse", "move_to_end", "rotate",
}

# module-level functions that mutate their first argument
_MUTATOR_FUNCS = {"heappush", "heappop", "heapify", "heappushpop",
                  "heapreplace"}

# functions with this name are thread roots by convention: each TCP
# connection gets its own ThreadingTCPServer handler thread running them
NAMED_ROOTS = {"handle_stream"}


# ---------------------------------------------------------------------------
# per-module index (extracted from trace_safety._ModuleIndex)
# ---------------------------------------------------------------------------

class ModuleIndex:
    """Function table + import aliases for one parsed module."""

    def __init__(self, module, numpy_modules: Sequence[str] = ("numpy",)):
        self.module = module
        self.top, self.every = collect_functions(module.tree, module.path)
        self.aliases = import_aliases(module.tree)
        # local names referring to numpy the module
        self.np_names = {local for local, target in self.aliases.items()
                         if target.split(".")[0] in numpy_modules}
        self.from_imports = {
            local: target for local, target in self.aliases.items()
            if "." in target}
        self.by_qualname: Dict[str, FuncInfo] = {
            fi.qualname: fi for fi in self.every}
        # names assigned at module top level (module globals)
        self.global_names: Set[str] = set()
        for node in module.tree.body:
            for name in _stored_names(node):
                self.global_names.add(name)
        self._locals: Dict[str, Set[str]] = {}

    def locals_of(self, fi: FuncInfo) -> Set[str]:
        """Names bound directly in ``fi``'s body (params + assignments,
        minus ``global``/``nonlocal`` declarations), excluding nested
        function bodies."""
        got = self._locals.get(fi.qualname)
        if got is not None:
            return got
        names: Set[str] = set(fi.params())
        a = fi.node.args
        if a.vararg:
            names.add(a.vararg.arg)
        if a.kwarg:
            names.add(a.kwarg.arg)
        escaped: Set[str] = set()
        for node in _walk_no_nested(fi.node):
            if isinstance(node, (ast.Global, ast.Nonlocal)):
                escaped.update(node.names)
            else:
                names.update(_stored_names(node))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fi.node:
                names.add(node.name)
            elif isinstance(node, ast.ClassDef):
                names.add(node.name)
        got = names - escaped
        self._locals[fi.qualname] = got
        return got


def _walk_no_nested(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk ``fn``'s body without descending into nested function defs."""
    yield fn
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            yield node
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _stored_names(node: ast.AST) -> Set[str]:
    """Bare names a single statement binds (no attribute/subscript)."""
    out: Set[str] = set()

    def targets_of(n: ast.AST) -> Iterator[ast.AST]:
        if isinstance(n, ast.Assign):
            yield from n.targets
        elif isinstance(n, (ast.AnnAssign, ast.AugAssign, ast.NamedExpr,
                            ast.For)):
            yield n.target
        elif isinstance(n, (ast.With, ast.AsyncWith)):
            for item in n.items:
                if item.optional_vars is not None:
                    yield item.optional_vars
        elif isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.ClassDef)):
            yield ast.Name(id=n.name, ctx=ast.Store())
        elif isinstance(n, (ast.Import, ast.ImportFrom)):
            for a in n.names:
                if a.name != "*":
                    yield ast.Name(id=a.asname or a.name.split(".")[0],
                                   ctx=ast.Store())

    def flatten(t: ast.AST) -> None:
        if isinstance(t, ast.Name):
            out.add(t.id)
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                flatten(e)
        elif isinstance(t, ast.Starred):
            flatten(t.value)

    for t in targets_of(node):
        flatten(t)
    return out


# ---------------------------------------------------------------------------
# reachability helpers shared with obsrules (migrated from there)
# ---------------------------------------------------------------------------

def iter_func_defs(tree: ast.AST) -> Iterator[ast.AST]:
    """Every FunctionDef/AsyncFunctionDef under ``tree`` (incl. nested)."""
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def direct_calls(fn: ast.AST) -> Iterator[ast.Call]:
    """Call nodes within ``fn`` but not within a nested function def."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            yield node
        stack.extend(ast.iter_child_nodes(node))


def pooled_callee_names(fn: ast.AST) -> Set[str]:
    """Names of functions ``fn`` hands to the decode pool: arguments of
    ``_iter_windowed`` / ``submit`` / ``pool_submit`` / ``.map`` calls."""
    names: Set[str] = set()
    for call in ast.walk(fn):
        if not isinstance(call, ast.Call):
            continue
        f = call.func
        fname = f.id if isinstance(f, ast.Name) else (
            f.attr if isinstance(f, ast.Attribute) else "")
        if fname not in POOL_DISPATCHERS:
            continue
        for arg in call.args:
            if isinstance(arg, ast.Name):
                names.add(arg.id)
    return names


# ---------------------------------------------------------------------------
# generic interprocedural worklist (extracted from trace_safety.analyze)
# ---------------------------------------------------------------------------

class InterproceduralWorklist:
    """(module path, qualname) → parameter-set propagation fixpoint.

    A *checker* callback analyzes one function under its current param
    set and returns the parameter sets it induces on its callees, keyed
    by FuncKey — or by ``("import", "dotted.target")`` for cross-module
    calls, whose parameter names may be positional markers (``"#0"``)
    resolved here against the callee's real signature.  The worklist
    re-enqueues any function whose set grew (monotone, so it
    terminates)."""

    def __init__(self, project: Project,
                 indices: Dict[str, ModuleIndex]):
        self.project = project
        self.indices = indices
        self.info_of: Dict[FuncKey, Tuple[ModuleIndex, FuncInfo]] = {}
        for idx in indices.values():
            for fi in idx.every:
                self.info_of[(idx.module.path, fi.qualname)] = (idx, fi)
        self.taint_of: Dict[FuncKey, Set[str]] = {}
        self.work: List[FuncKey] = []

    def add_taint(self, key: FuncKey, params: Set[str]) -> None:
        if key not in self.info_of:
            return
        cur = self.taint_of.setdefault(key, set())
        if not params <= cur:
            cur.update(params)
            if key not in self.work:
                self.work.append(key)

    def resolve_import_key(self, target: str) -> Optional[FuncKey]:
        """'hadoop_bam_tpu.ops.unpack_bam.unpack_fixed_fields' ->
        (module path, top-level qualname) when in scope."""
        mod, _, name = target.rpartition(".")
        m = self.project.by_dotted.get(mod)
        if m is None or m.path not in self.indices:
            return None
        idx = self.indices[m.path]
        if name in idx.top:
            return (m.path, name)
        return None

    def run(self, check: Callable[[ModuleIndex, FuncInfo, Set[str]],
                                  Dict[Tuple[str, str], Set[str]]],
            max_rounds: int = 10000) -> None:
        rounds = 0
        while self.work and rounds < max_rounds:
            rounds += 1
            key = self.work.pop()
            idx, fi = self.info_of[key]
            callee_taints = check(idx, fi, self.taint_of.get(key, set()))
            for callee_key, params in callee_taints.items():
                if callee_key[0] == "import":
                    resolved = self.resolve_import_key(callee_key[1])
                    if resolved is None:
                        continue
                    # positional markers -> real parameter names
                    _, cfi = self.info_of[resolved]
                    cparams = cfi.params()
                    real: Set[str] = set()
                    for p in params:
                        if p.startswith("#"):
                            i = int(p[1:])
                            if i < len(cparams):
                                real.add(cparams[i])
                        else:
                            real.add(p)
                    self.add_taint(resolved, real)
                else:
                    self.add_taint(callee_key, params)


# ---------------------------------------------------------------------------
# thread topology
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ThreadRoot:
    """One function that runs on its own thread (or pool/handler thread).

    ``name`` is the stable human identity used in findings (sorted and
    deduped); ``key`` the entry function; ``kind`` how it was spawned."""
    name: str
    key: FuncKey
    kind: str              # thread | pool | callback | handler | client
    path: str
    line: int


@dataclasses.dataclass(frozen=True)
class Access:
    """One read/write of shared state with the locks held at the site."""
    kind: str              # "read" | "write"
    target: AccessId
    func: FuncKey
    path: str
    line: int
    guards: FrozenSet[AccessId]


@dataclasses.dataclass(frozen=True)
class Acquisition:
    """One ``with <lock>:`` entry with the locks already held outside."""
    lock: AccessId
    func: FuncKey
    path: str
    line: int
    held: FrozenSet[AccessId]


class CallGraphEngine:
    """Call resolution, thread roots, reachability and guard inference
    over the modules selected by ``scope``."""

    def __init__(self, project: Project, scope: Sequence[str]):
        self.project = project
        self.indices: Dict[str, ModuleIndex] = {
            m.path: ModuleIndex(m) for m in project.select(scope)}
        self.info_of: Dict[FuncKey, Tuple[ModuleIndex, FuncInfo]] = {}
        for idx in self.indices.values():
            for fi in idx.every:
                self.info_of[(idx.module.path, fi.qualname)] = (idx, fi)
        self._callees: Dict[FuncKey, List[FuncKey]] = {}
        self._lock_ids: Optional[Set[AccessId]] = None
        self._safe_ids: Optional[Set[AccessId]] = None
        self._accesses: Dict[FuncKey, List[Access]] = {}
        self._acquisitions: Dict[FuncKey, List[Acquisition]] = {}
        self._entry_guards: Optional[Dict[FuncKey, FrozenSet[AccessId]]] \
            = None
        self._roots: Optional[List[ThreadRoot]] = None

    # -- identity resolution ------------------------------------------------

    def class_prefix(self, fi: FuncInfo) -> Optional[str]:
        """'Fleet' for qualname 'Fleet.start' when it looks like a
        method (first parameter named self); None otherwise."""
        if "." not in fi.qualname:
            return None
        params = fi.params()
        if not params or params[0] != "self":
            return None
        return fi.qualname.rpartition(".")[0]

    def resolve_value_id(self, idx: ModuleIndex, fi: FuncInfo,
                         node: ast.AST) -> Optional[AccessId]:
        """The shared-state identity a Name/Attribute refers to, or None
        when it is unresolvable / not shared-shaped."""
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self":
                cls = self.class_prefix(fi)
                if cls is not None:
                    return ("attr", cls, node.attr)
            return None
        if isinstance(node, ast.Name):
            name = node.id
            scope: Optional[FuncInfo] = fi
            while scope is not None:
                if name in idx.locals_of(scope):
                    if scope is fi:
                        return ("local", idx.module.path, scope.qualname,
                                name)
                    return ("closure", idx.module.path, scope.qualname,
                            name)
                scope = scope.parent
            if name in idx.global_names:
                return ("global", idx.module.path, name)
        return None

    def resolve_func_ref(self, idx: ModuleIndex, ctx: Optional[FuncInfo],
                         node: ast.AST) -> Optional[FuncKey]:
        """Resolve a *reference* to a project function: a bare name
        (lexically), ``self._method``, or a ``from``-imported name."""
        if isinstance(node, ast.Name):
            fi = resolve_name(node.id, ctx, idx.top)
            if fi is not None:
                return (idx.module.path, fi.qualname)
            target = idx.from_imports.get(node.id)
            if target:
                return self._resolve_import(target)
            return None
        if isinstance(node, ast.Attribute):
            base = node.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and ctx is not None:
                cls = self.class_prefix(ctx)
                if cls is not None:
                    qn = f"{cls}.{node.attr}"
                    if (idx.module.path, qn) in self.info_of:
                        return (idx.module.path, qn)
            target = dotted_name(node)
            if target:
                head = target.split(".")[0]
                alias = idx.aliases.get(head)
                if alias:
                    full = alias + target[len(head):]
                    return self._resolve_import(full)
        return None

    def _resolve_import(self, target: str) -> Optional[FuncKey]:
        mod, _, name = target.rpartition(".")
        m = self.project.by_dotted.get(mod)
        if m is None or m.path not in self.indices:
            return None
        idx = self.indices[m.path]
        if name in idx.top:
            return (m.path, name)
        return None

    def resolve_call(self, idx: ModuleIndex, ctx: Optional[FuncInfo],
                     call: ast.Call) -> Optional[FuncKey]:
        return self.resolve_func_ref(idx, ctx, call.func)

    # -- call graph ---------------------------------------------------------

    def callees_of(self, key: FuncKey) -> List[FuncKey]:
        got = self._callees.get(key)
        if got is not None:
            return got
        idx, fi = self.info_of[key]
        out: List[FuncKey] = []
        for node in _walk_no_nested(fi.node):
            if isinstance(node, ast.Call):
                callee = self.resolve_call(idx, fi, node)
                if callee is not None and callee != key:
                    out.append(callee)
        self._callees[key] = out
        return out

    def reachable(self, entries: Sequence[FuncKey]) -> Set[FuncKey]:
        seen: Set[FuncKey] = set()
        stack = [k for k in entries if k in self.info_of]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            stack.extend(self.callees_of(key))
        return seen

    # -- thread roots -------------------------------------------------------

    def _thread_target(self, idx: ModuleIndex, ctx: Optional[FuncInfo],
                       call: ast.Call) -> Optional[FuncKey]:
        """The function a ``threading.Thread(...)`` will run, looking
        through the repo's contextvar-carrying indirections:
        ``Thread(target=ctx.run, args=(f, ...))`` and
        ``Thread(target=lambda: ctx.run(f))``."""
        target = None
        args_kw = None
        for kw in call.keywords:
            if kw.arg == "target":
                target = kw.value
            elif kw.arg == "args":
                args_kw = kw.value
        if target is None and call.args:
            target = call.args[0]
        if target is None:
            return None
        if isinstance(target, ast.Lambda):
            body = target.body
            if isinstance(body, ast.Call):
                # lambda: ctx.run(f)  ->  f ;  lambda: f()  ->  f
                if last_segment(body.func) == "run" and body.args:
                    return self.resolve_func_ref(idx, ctx, body.args[0])
                return self.resolve_func_ref(idx, ctx, body.func)
            return None
        if last_segment(target) == "run" and args_kw is not None \
                and isinstance(args_kw, (ast.Tuple, ast.List)) \
                and args_kw.elts:
            # Thread(target=ctx.run, args=(f, ...))
            return self.resolve_func_ref(idx, ctx, args_kw.elts[0])
        return self.resolve_func_ref(idx, ctx, target)

    def thread_roots(self) -> List[ThreadRoot]:
        """Every discovered thread entry point, deduped by entry
        function (two spawn sites of the same loop are one root)."""
        if self._roots is not None:
            return self._roots
        found: Dict[FuncKey, ThreadRoot] = {}

        def note(key: Optional[FuncKey], kind: str, idx: ModuleIndex,
                 node: ast.AST) -> None:
            if key is None or key in found or key not in self.info_of:
                return
            short = key[0].split("/", 1)[-1]
            found[key] = ThreadRoot(
                name=f"{short}:{key[1]}", key=key, kind=kind,
                path=idx.module.path,
                line=getattr(node, "lineno", 1))

        for idx in self.indices.values():
            for node in ast.walk(idx.module.tree):
                if not isinstance(node, ast.Call):
                    continue
                seg = last_segment(node.func)
                ctx = enclosing_function(idx.every, node)
                if seg == "Thread":
                    note(self._thread_target(idx, ctx, node), "thread",
                         idx, node)
                elif seg == "Timer" and len(node.args) >= 2:
                    note(self.resolve_func_ref(idx, ctx, node.args[1]),
                         "thread", idx, node)
                elif seg == "submit" and isinstance(node.func,
                                                    ast.Attribute):
                    # executor.submit(f, ...) / pool.submit(ctx.run,
                    # _timed_task, f, ...): any argument that resolves
                    # to a project function may run on a pool thread
                    for arg in node.args:
                        key = self.resolve_func_ref(idx, ctx, arg)
                        note(key, "pool", idx, node)
                elif seg in ("submit", "pool_submit", "_iter_windowed") \
                        and isinstance(node.func, ast.Name):
                    for arg in node.args:
                        key = self.resolve_func_ref(idx, ctx, arg)
                        note(key, "pool", idx, node)
                elif seg == "map" and isinstance(node.func, ast.Attribute) \
                        and node.args:
                    note(self.resolve_func_ref(idx, ctx, node.args[0]),
                         "pool", idx, node)
                elif seg == "add_done_callback" and node.args:
                    note(self.resolve_func_ref(idx, ctx, node.args[0]),
                         "callback", idx, node)
            for fi in idx.every:
                if fi.name in NAMED_ROOTS:
                    note((idx.module.path, fi.qualname), "handler", idx,
                         fi.node)
        self._roots = sorted(found.values(), key=lambda r: r.name)
        return self._roots

    def client_entries(self) -> List[FuncKey]:
        """The public surface: top-level functions and methods a caller
        thread invokes directly.  They form ONE implicit 'client' root —
        a single API-driving thread — so two public methods writing the
        same attribute is not, by itself, a cross-thread conflict."""
        root_keys = {r.key for r in self.thread_roots()}
        out: List[FuncKey] = []
        for idx in self.indices.values():
            for fi in idx.every:
                key = (idx.module.path, fi.qualname)
                if key in root_keys:
                    continue
                if fi.parent is not None:      # nested: not an API surface
                    continue
                name = fi.name
                if name.startswith("__") and name.endswith("__"):
                    if name in ("__init__", "__new__", "__del__"):
                        continue
                elif name.startswith("_"):
                    continue
                out.append(key)
        return out

    # -- locks, safety, accesses --------------------------------------------

    def _scan_constructed(self) -> Tuple[Set[AccessId], Set[AccessId]]:
        lock_ids: Set[AccessId] = set()
        safe_ids: Set[AccessId] = set()

        def classify(seg: Optional[str], tid: Optional[AccessId]) -> None:
            if tid is None:
                return
            if seg in _LOCK_CONSTRUCTORS:
                lock_ids.add(tid)
            if seg in _THREADSAFE_CONSTRUCTORS:
                safe_ids.add(tid)

        for idx in self.indices.values():
            # module-level constructions: _LOCK = threading.Lock()
            for node in idx.module.tree.body:
                if not (isinstance(node, ast.Assign)
                        and isinstance(node.value, ast.Call)):
                    continue
                seg = last_segment(node.value.func)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        classify(seg, ("global", idx.module.path, t.id))
            for fi in idx.every:
                for node in _walk_no_nested(fi.node):
                    if not isinstance(node, ast.Assign):
                        continue
                    if not isinstance(node.value, ast.Call):
                        continue
                    seg = last_segment(node.value.func)
                    for t in node.targets:
                        classify(seg, self.resolve_value_id(idx, fi, t))
        return lock_ids, safe_ids

    @property
    def lock_ids(self) -> Set[AccessId]:
        if self._lock_ids is None:
            self._lock_ids, self._safe_ids = self._scan_constructed()
        return self._lock_ids

    @property
    def safe_ids(self) -> Set[AccessId]:
        if self._safe_ids is None:
            self._lock_ids, self._safe_ids = self._scan_constructed()
        return self._safe_ids

    def _base_id(self, idx: ModuleIndex, fi: FuncInfo,
                 node: ast.AST) -> Optional[AccessId]:
        """Identity of the object a store/mutation ultimately lands in:
        peel subscripts and trailing attributes down to ``self.X`` or a
        bare name (``self._peers[pid].last = t`` mutates ``self._peers``).
        """
        while True:
            if isinstance(node, ast.Subscript):
                node = node.value
                continue
            if isinstance(node, ast.Attribute):
                got = self.resolve_value_id(idx, fi, node)
                if got is not None:
                    return got
                node = node.value
                continue
            break
        if isinstance(node, ast.Name):
            return self.resolve_value_id(idx, fi, node)
        return None

    def _collect_accesses(self, key: FuncKey) -> Tuple[List[Access],
                                                       List[Acquisition]]:
        idx, fi = self.info_of[key]
        path = idx.module.path
        accesses: List[Access] = []
        acqs: List[Acquisition] = []
        in_init = fi.name == "__init__"

        def note_write(node: ast.AST, target: ast.AST,
                       guards: FrozenSet[AccessId]) -> None:
            if isinstance(target, (ast.Tuple, ast.List)):
                for e in target.elts:
                    note_write(node, e, guards)
                return
            if isinstance(target, ast.Starred):
                note_write(node, target.value, guards)
                return
            if isinstance(target, ast.Name):
                # bare-name store: a write only when it escapes the
                # function (module global via `global`, or nonlocal)
                tid = self.resolve_value_id(idx, fi, target)
            else:
                tid = self._base_id(idx, fi, target)
            if tid is None or tid[0] == "local":
                return
            if in_init and tid[0] == "attr":
                return     # pre-publication: object not yet shared
            accesses.append(Access(
                "write", tid, key, path, getattr(node, "lineno", 1),
                guards))

        def note_read(node: ast.AST,
                      guards: FrozenSet[AccessId]) -> None:
            tid = self.resolve_value_id(idx, fi, node)
            if tid is None or tid[0] == "local":
                return
            accesses.append(Access(
                "read", tid, key, path, getattr(node, "lineno", 1),
                guards))

        def visit(node: ast.AST, guards: FrozenSet[AccessId]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)) and node is not fi.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                new_guards = guards
                for item in node.items:
                    expr = item.context_expr
                    if isinstance(expr, (ast.Name, ast.Attribute)):
                        lid = self.resolve_value_id(idx, fi, expr)
                        if lid is not None and lid in self.lock_ids:
                            acqs.append(Acquisition(
                                lid, key, path, node.lineno, new_guards))
                            new_guards = new_guards | {lid}
                for child in node.body:
                    visit(child, new_guards)
                for item in node.items:
                    visit(item.context_expr, guards)
                return
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    note_write(node, t, guards)
                visit(node.value, guards)
                return
            if isinstance(node, ast.AugAssign):
                note_write(node, node.target, guards)
                visit(node.value, guards)
                return
            if isinstance(node, ast.AnnAssign):
                if node.value is not None:
                    note_write(node, node.target, guards)
                    visit(node.value, guards)
                return
            if isinstance(node, ast.Delete):
                for t in node.targets:
                    note_write(node, t, guards)
                return
            if isinstance(node, ast.Call):
                f = node.func
                seg = last_segment(f)
                if isinstance(f, ast.Attribute) \
                        and seg in _MUTATOR_METHODS:
                    note_write(node, f.value, guards)
                elif seg in _MUTATOR_FUNCS and node.args:
                    note_write(node, node.args[0], guards)
                for child in ast.iter_child_nodes(node):
                    visit(child, guards)
                return
            if isinstance(node, ast.Attribute) \
                    and isinstance(node.ctx, ast.Load):
                note_read(node, guards)
                # keep walking: chained attributes read their base too
            if isinstance(node, ast.Name) \
                    and isinstance(node.ctx, ast.Load):
                note_read(node, guards)
            for child in ast.iter_child_nodes(node):
                visit(child, guards)

        for stmt in fi.node.body:
            visit(stmt, frozenset())
        return accesses, acqs

    def accesses_of(self, key: FuncKey) -> List[Access]:
        if key not in self._accesses:
            self._accesses[key], self._acquisitions[key] = \
                self._collect_accesses(key)
        return self._accesses[key]

    def acquisitions_of(self, key: FuncKey) -> List[Acquisition]:
        if key not in self._acquisitions:
            self._accesses[key], self._acquisitions[key] = \
                self._collect_accesses(key)
        return self._acquisitions[key]

    # -- interprocedural guard inference ------------------------------------

    def entry_guards(self) -> Dict[FuncKey, FrozenSet[AccessId]]:
        """Locks provably held at EVERY call of each function: the
        intersection over all resolvable call sites of (caller's entry
        guards ∪ locks lexically held at the site).  Roots and client
        entries start at ∅; unreached functions stay at ⊤ (None here),
        reported as ∅ by the getter so they never launder a guard."""
        if self._entry_guards is not None:
            return self._entry_guards
        TOP = None
        entry: Dict[FuncKey, Optional[FrozenSet[AccessId]]] = {
            k: TOP for k in self.info_of}
        work: List[FuncKey] = []

        def lower(key: FuncKey, guards: FrozenSet[AccessId]) -> None:
            cur = entry.get(key)
            if cur is None:
                entry[key] = guards
            else:
                new = cur & guards
                if new == cur:
                    return
                entry[key] = new
            if key not in work:
                work.append(key)

        for r in self.thread_roots():
            lower(r.key, frozenset())
        for key in self.client_entries():
            lower(key, frozenset())

        rounds = 0
        while work and rounds < 100000:
            rounds += 1
            key = work.pop()
            base = entry[key] or frozenset()
            idx, fi = self.info_of[key]
            for node in _walk_no_nested(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                callee = self.resolve_call(idx, fi, node)
                if callee is None or callee == key:
                    continue
                site = base | self._lexical_guards_at(key, node)
                lower(callee, site)
        self._entry_guards = {
            k: (v if v is not None else frozenset())
            for k, v in entry.items()}
        return self._entry_guards

    def _lexical_guards_at(self, key: FuncKey,
                           node: ast.AST) -> FrozenSet[AccessId]:
        """Locks lexically held at ``node`` inside function ``key``."""
        idx, fi = self.info_of[key]
        target_line = getattr(node, "lineno", None)
        if target_line is None:
            return frozenset()
        held: Set[AccessId] = set()
        for stmt in ast.walk(fi.node):
            if not isinstance(stmt, (ast.With, ast.AsyncWith)):
                continue
            end = getattr(stmt, "end_lineno", stmt.lineno)
            if not (stmt.lineno <= target_line <= end):
                continue
            for item in stmt.items:
                expr = item.context_expr
                if isinstance(expr, (ast.Name, ast.Attribute)):
                    lid = self.resolve_value_id(idx, fi, expr)
                    if lid is not None and lid in self.lock_ids:
                        held.add(lid)
        return frozenset(held)

    def closure_escapes_to_thread(self, tid: AccessId) -> bool:
        """A closure cell is per-invocation of its owning function, so
        it is cross-thread state only when some thread root's entry
        function is lexically nested inside the owner — the spawn is
        what hands the cell to another thread.  (Two roots that each
        *call* the owner get two distinct cells.)  Non-closure ids are
        always shareable."""
        if tid[0] != "closure":
            return True
        _, path, owner, _name = tid
        prefix = owner + "."
        return any(r.key[0] == path and r.key[1].startswith(prefix)
                   for r in self.thread_roots())

    def effective_guards(self, access: Access) -> FrozenSet[AccessId]:
        """Lexical guards at the access ∪ guards held at function entry."""
        return access.guards | self.entry_guards().get(access.func,
                                                       frozenset())

    # -- per-root access summaries ------------------------------------------

    def root_accesses(self) -> Dict[str, List[Access]]:
        """Root name -> accesses of every function reachable from it,
        including the synthetic 'client' root for the public surface."""
        out: Dict[str, List[Access]] = {}
        for r in self.thread_roots():
            acc: List[Access] = []
            for key in sorted(self.reachable([r.key])):
                acc.extend(self.accesses_of(key))
            out[r.name] = acc
        client: List[Access] = []
        for key in sorted(self.reachable(self.client_entries())):
            client.extend(self.accesses_of(key))
        out["client"] = client
        return out

    # -- lock-order graph ---------------------------------------------------

    def lock_order_edges(self) -> Dict[Tuple[AccessId, AccessId],
                                       Tuple[str, int]]:
        """(held lock, acquired lock) -> one representative (path, line).
        Edges combine lexical nesting with interprocedural entry guards:
        acquiring B while A is held anywhere orders A before B."""
        entry = self.entry_guards()
        reach: Set[FuncKey] = set()
        for r in self.thread_roots():
            reach |= self.reachable([r.key])
        reach |= self.reachable(self.client_entries())
        edges: Dict[Tuple[AccessId, AccessId], Tuple[str, int]] = {}
        for key in sorted(reach):
            base = entry.get(key, frozenset())
            for acq in self.acquisitions_of(key):
                held = base | acq.held
                for h in held:
                    if h == acq.lock:
                        continue
                    edges.setdefault((h, acq.lock), (acq.path, acq.line))
        return edges


def find_lock_cycles(edges: Dict[Tuple[AccessId, AccessId],
                                 Tuple[str, int]]
                     ) -> List[List[AccessId]]:
    """Elementary cycles in the lock-order digraph (each reported once,
    rotated to start at its smallest lock, sorted for determinism)."""
    graph: Dict[AccessId, Set[AccessId]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    cycles: Dict[Tuple[AccessId, ...], List[AccessId]] = {}

    def dfs(start: AccessId, node: AccessId,
            path: List[AccessId], on_path: Set[AccessId]) -> None:
        for nxt in sorted(graph.get(node, ())):
            if nxt == start:
                cyc = list(path)
                i = cyc.index(min(cyc))
                rot = tuple(cyc[i:] + cyc[:i])
                cycles.setdefault(rot, list(rot))
            elif nxt not in on_path and nxt > start:
                # only explore nodes > start: each cycle found exactly
                # once, from its smallest node
                path.append(nxt)
                on_path.add(nxt)
                dfs(start, nxt, path, on_path)
                on_path.discard(nxt)
                path.pop()

    for start in sorted(graph):
        dfs(start, start, [start], {start})
    return [cycles[k] for k in sorted(cycles)]


def format_access_id(aid: AccessId) -> str:
    """Human-stable rendering used in findings: 'Fleet.self._lock',
    'utils/pools.py::_BG_QUEUE', 'staging.py::stream.errs'."""
    kind = aid[0]
    if kind == "attr":
        return f"{aid[1]}.self.{aid[2]}"
    if kind == "global":
        return f"{aid[1]}::{aid[2]}"
    if kind in ("closure", "local"):
        return f"{aid[1]}::{aid[2]}.{aid[3]}"
    return repr(aid)
