"""CL2xx — collective lockstep: every host must reach every collective.

Multi-host collectives (``broadcast_one_to_all``, ``process_allgather``,
and this repo's wrappers ``broadcast_plan`` / ``merge_quarantine_manifests``
/ ``_run_collective`` / ``_agree_round_geometry`` / ``_multihost_reduce``)
are rendezvous points: a host that skips one strands every other host in
it forever.  The repo's discipline (see ``_multihost_reduce``'s
failure-flag convention) is that collectives sit at the top level of a
function's control flow — host-dependent *data* may ride a collective,
but the collective call itself must be unconditional.

Rules:

- CL201 collective nested under a host-index / rank conditional
  (``if jax.process_index() == 0: ... allgather(...)``) — a structural
  deadlock.  ``process_count``-based tests are uniform across hosts and
  are not flagged.
- CL202 sibling ``if``/``else`` branches carry *different* collective
  sequences — hosts taking different branches rendezvous in different
  orders (or counts), which deadlocks or mismatches payloads.
"""
from __future__ import annotations

import ast
from typing import List, Optional, Set

from hadoop_bam_tpu.analysis.astutil import (
    collect_functions, last_segment,
)
from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/parallel",)

# call names (last segment) that are host-level rendezvous points
COLLECTIVES = {
    "broadcast_one_to_all", "process_allgather", "sync_global_devices",
    "broadcast_plan", "merge_quarantine_manifests", "_run_collective",
    "_agree_round_geometry", "_multihost_reduce",
}

# rank sources: expressions of these produce host-divergent values
_RANK_CALLS = {"process_index", "local_process_index"}


def _collective_name(node: ast.Call) -> Optional[str]:
    seg = last_segment(node.func)
    if seg in COLLECTIVES:
        return seg
    return None


def _mentions_rank(node: ast.AST, rank_vars: Set[str]) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Call) \
                and last_segment(sub.func) in _RANK_CALLS:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in _RANK_CALLS:
            return True
        if isinstance(sub, ast.Name) and sub.id in rank_vars:
            return True
    return False


def _rank_vars(fn: ast.AST) -> Set[str]:
    """Names assigned (directly) from a process_index()-derived value."""
    out: Set[str] = set()
    for _ in range(4):   # tiny fixpoint for pid -> alias chains
        before = len(out)
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and _mentions_rank(node.value,
                                                               out):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        out.add(t.id)
            elif isinstance(node, ast.AnnAssign) and node.value \
                    and isinstance(node.target, ast.Name) \
                    and _mentions_rank(node.value, out):
                out.add(node.target.id)
        if len(out) == before:
            break
    return out


def _walk_own(root: ast.AST):
    """ast.walk that does not descend into nested function definitions —
    each function is analyzed exactly once (nested defs get their own
    pass, with the parent chain's rank vars in scope)."""
    stack = [root]
    while stack:
        node = stack.pop()
        yield node
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and child is not root:
                continue
            stack.append(child)


def _collectives_in(node: ast.AST) -> List[ast.Call]:
    return [n for n in ast.walk(node)
            if isinstance(n, ast.Call) and _collective_name(n)]


def _sequence(stmts: List[ast.stmt]) -> List[str]:
    names: List[str] = []
    for stmt in stmts:
        for n in ast.walk(stmt):
            if isinstance(n, ast.Call):
                c = _collective_name(n)
                if c:
                    names.append(c)
    return names


@register("lockstep")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        _top, every = collect_functions(m.tree, m.path)
        for fi in every:
            # rank vars of the whole lexical chain: a nested def closing
            # over the parent's `pid = jax.process_index()` is still
            # rank-conditioned by it
            rank_vars: Set[str] = set()
            scope = fi
            while scope is not None:
                rank_vars |= _rank_vars(scope.node)
                scope = scope.parent

            for node in _walk_own(fi.node):
                # CL201: collective under a rank conditional
                if isinstance(node, (ast.If, ast.While)) \
                        and _mentions_rank(node.test, rank_vars):
                    for branch, stmts in (("body", node.body),
                                          ("else", node.orelse)):
                        for call in _collectives_in(
                                ast.Module(body=stmts, type_ignores=[])):
                            # no line numbers in the MESSAGE: the baseline
                            # fingerprint hashes it and must stay
                            # line-insensitive (core.py contract)
                            findings.append(Finding(
                                rule="CL201", severity="error", path=m.path,
                                line=call.lineno,
                                message=f"collective "
                                        f"'{_collective_name(call)}' is "
                                        f"nested under a host-index "
                                        f"conditional ({branch} branch) "
                                        f"in '{fi.qualname}' — hosts "
                                        f"that skip it strand the "
                                        f"others"))
                elif isinstance(node, ast.IfExp) \
                        and _mentions_rank(node.test, rank_vars):
                    for part in (node.body, node.orelse):
                        for call in _collectives_in(part):
                            findings.append(Finding(
                                rule="CL201", severity="error", path=m.path,
                                line=call.lineno,
                                message=f"collective "
                                        f"'{_collective_name(call)}' "
                                        f"evaluated under a host-index "
                                        f"ternary in '{fi.qualname}'"))
                # CL202: divergent collective order across siblings
                if isinstance(node, ast.If) and node.orelse:
                    a = _sequence(node.body)
                    b = _sequence(node.orelse)
                    if a and b and a != b:
                        findings.append(Finding(
                            rule="CL202", severity="error", path=m.path,
                            line=node.lineno,
                            message=f"sibling branches of the conditional "
                                    f"in '{fi.qualname}' run different "
                                    f"collective sequences "
                                    f"({a} vs {b}) — hosts taking "
                                    f"different branches deadlock"))
    return findings
