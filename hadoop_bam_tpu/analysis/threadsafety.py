"""TH1xx/LK2xx — thread-topology races and lock discipline.

The runtime is deeply multithreaded: the serve dispatcher, the staging
packer, the parallel-BGZF committer, the fleet heartbeat, per-connection
TCP handler threads and the shared decode pool all touch shared state.
TSan polices the native layer at runtime; these rules police the Python
layer statically, on the interprocedural engine in ``callgraph.py``:
thread roots are discovered from the spawn sites themselves, each
root's reachable read/write set over ``self`` attributes, module
globals and closure cells is computed, and ``with <lock>:`` guards are
tracked across calls (a helper only ever invoked under a lock counts as
guarded — the intersection-over-call-sites entry-guard fixpoint).

Rules (scope: ``serve/``, ``parallel/``, ``write/``, ``jobs/``,
``resilience/``, ``utils/pools.py``):

- TH101 unguarded cross-thread write: shared state written from ≥2
  thread roots (the public API surface counts as one implicit 'client'
  root) where at least one write site holds no lock.  Objects that are
  internally thread-safe (``queue.Queue``, ``threading.Event``, locks
  themselves, ...) and ``__init__``-time writes (pre-publication) are
  exempt.
- TH102 check-then-act outside a guard: a membership/emptiness test on
  shared multi-root state followed by a write to it inside the same
  ``if`` body, with no lock held at the *check* — the classic TOCTOU
  (guarding only the write does not make the decision atomic).
- LK201 lock-order cycle: two locks acquired in opposite nesting
  orders somewhere in the thread topology (lexically or via calls) —
  a static deadlock candidate.  Fix by acquiring in one global order.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Set, Tuple

from hadoop_bam_tpu.analysis.callgraph import (
    Access, AccessId, CallGraphEngine, find_lock_cycles, format_access_id,
)
from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/serve", "hadoop_bam_tpu/parallel",
         "hadoop_bam_tpu/write", "hadoop_bam_tpu/jobs",
         "hadoop_bam_tpu/resilience", "hadoop_bam_tpu/utils/pools.py",
         "hadoop_bam_tpu/prep")


def _roots_phrase(names: List[str]) -> str:
    return ", ".join(f"'{n}'" for n in sorted(names))


def _th101(eng: CallGraphEngine,
           root_acc: Dict[str, List[Access]]) -> List[Finding]:
    writers: Dict[AccessId, Dict[str, List[Access]]] = {}
    for rname, accs in root_acc.items():
        for a in accs:
            if a.kind != "write" or a.target in eng.safe_ids:
                continue
            if not eng.closure_escapes_to_thread(a.target):
                continue
            writers.setdefault(a.target, {}).setdefault(rname, []) \
                .append(a)

    findings: List[Finding] = []
    for tid in sorted(writers):
        by_root = writers[tid]
        if len(by_root) < 2:
            continue
        root_names = sorted(by_root)
        seen_sites: Set[Tuple[str, int]] = set()
        for rname in root_names:
            for a in by_root[rname]:
                if eng.effective_guards(a):
                    continue
                site = (a.path, a.line)
                if site in seen_sites:
                    continue
                seen_sites.add(site)
                findings.append(Finding(
                    rule="TH101", severity="error", path=a.path,
                    line=a.line,
                    message=f"unguarded write to {format_access_id(tid)}"
                            f", which is written from multiple threads "
                            f"({_roots_phrase(root_names)}) — hold one "
                            "lock around every write (a helper called "
                            "only under a lock counts as guarded)"))
    return findings


def _membership_container(test: ast.AST) -> List[ast.AST]:
    """Expressions whose membership/emptiness the test inspects:
    ``k in S`` / ``k not in S`` comparators, and ``not S``."""
    out: List[ast.AST] = []
    for node in ast.walk(test):
        if isinstance(node, ast.Compare):
            for op, comp in zip(node.ops, node.comparators):
                if isinstance(op, (ast.In, ast.NotIn)):
                    out.append(comp)
        elif isinstance(node, ast.UnaryOp) \
                and isinstance(node.op, ast.Not) \
                and isinstance(node.operand, (ast.Name, ast.Attribute)):
            out.append(node.operand)
    return out


def _th102(eng: CallGraphEngine,
           root_acc: Dict[str, List[Access]]) -> List[Finding]:
    accessors: Dict[AccessId, Set[str]] = {}
    for rname, accs in root_acc.items():
        for a in accs:
            accessors.setdefault(a.target, set()).add(rname)

    all_keys: Set = set()
    for r in eng.thread_roots():
        all_keys |= eng.reachable([r.key])
    all_keys |= eng.reachable(eng.client_entries())

    entry = eng.entry_guards()
    findings: List[Finding] = []
    for key in sorted(all_keys):
        idx, fi = eng.info_of[key]
        writes = [a for a in eng.accesses_of(key) if a.kind == "write"]
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.If):
                continue
            guards = entry.get(key, frozenset()) \
                | eng._lexical_guards_at(key, node)
            if guards:
                continue
            end = getattr(node, "end_lineno", node.lineno)
            for container in _membership_container(node.test):
                tid = eng.resolve_value_id(idx, fi, container)
                if tid is None or tid[0] == "local" \
                        or tid in eng.safe_ids \
                        or not eng.closure_escapes_to_thread(tid):
                    continue
                if len(accessors.get(tid, ())) < 2:
                    continue
                if any(a.target == tid
                       and node.lineno < a.line <= end
                       for a in writes):
                    findings.append(Finding(
                        rule="TH102", severity="error",
                        path=idx.module.path, line=node.lineno,
                        message="check-then-act on shared "
                                f"{format_access_id(tid)} outside a "
                                "guard: the test and the write inside "
                                "this branch are not atomic across "
                                "threads — hold the lock around both "
                                "(guarding only the write leaves the "
                                "decision racy)"))
                    break
    return findings


def _lk201(eng: CallGraphEngine) -> List[Finding]:
    edges = eng.lock_order_edges()
    findings: List[Finding] = []
    for cycle in find_lock_cycles(edges):
        ring = cycle + cycle[:1]
        order = " -> ".join(format_access_id(lid) for lid in ring)
        path, line = edges[(cycle[0], cycle[1] if len(cycle) > 1
                            else cycle[0])]
        findings.append(Finding(
            rule="LK201", severity="error", path=path, line=line,
            message=f"lock-order cycle {order}: these locks are "
                    "acquired in conflicting nesting orders across the "
                    "thread topology — a static deadlock candidate; "
                    "pick one global acquisition order"))
    return findings


@register("threadsafety")
def analyze(project: Project) -> List[Finding]:
    eng = CallGraphEngine(project, SCOPE)
    if not eng.thread_roots():
        # single-threaded scope: nothing is shared across threads, and
        # LK201 cannot deadlock one thread using `with` (re-entry of a
        # plain Lock hangs, but that is not an ORDER cycle)
        return []
    root_acc = eng.root_accesses()
    findings = _th101(eng, root_acc)
    findings.extend(_th102(eng, root_acc))
    findings.extend(_lk201(eng))
    return findings
