"""WR10x — write-path discipline: atomic publication, pooled deflate.

The write subsystem (``hadoop_bam_tpu/write/``) has two invariants that
read like style but are correctness at scale:

- outputs are PUBLISHED atomically: data is written to a temp name and
  ``os.replace``d into place, so a crashed writer never leaves a
  plausible-looking truncated file under the final name (the multi-host
  merger would concatenate it; the serve tier would cache it by a stale
  identity).  A bare ``open(final_path, "wb")`` in ``write/`` is the
  regression vector — WR101 flags any write-mode ``open`` whose path
  expression carries no temp-ish name (tmp/temp/part/shard/scratch)
  inside a function that never calls ``os.replace``/``os.rename``.

- block deflate runs on the shared pool, committed in order by ONE
  committer: a ``deflate_block`` call inside a loop anywhere in
  ``write/`` outside the committer/submit machinery is the serial
  bottleneck creeping back (the exact shape the subsystem exists to
  remove) — WR102.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/write",)

_TMPISH = ("tmp", "temp", "part", "shard", "scratch")
_WRITE_MODES = ("w", "wb", "xb", "x", "wb+", "w+b", "ab")
_ATOMIC_CALLS = {"replace", "rename"}
_COMMITTERISH = ("commit", "submit", "deflate")


def _func_defs(tree: ast.AST) -> Iterator[ast.AST]:
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            yield node


def _identifiers(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr
        elif isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            yield sub.value


def _is_write_open(call: ast.Call) -> bool:
    fn = call.func
    if not (isinstance(fn, ast.Name) and fn.id == "open"):
        return False
    mode = None
    if len(call.args) >= 2 and isinstance(call.args[1], ast.Constant):
        mode = call.args[1].value
    for kw in call.keywords:
        if kw.arg == "mode" and isinstance(kw.value, ast.Constant):
            mode = kw.value.value
    return isinstance(mode, str) and mode in _WRITE_MODES


def _calls_atomic_rename(fn: ast.AST) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _ATOMIC_CALLS:
            return True
    return False


def _loops_of(fn: ast.AST) -> Iterator[ast.AST]:
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue                       # nested defs analyzed on their own
        if isinstance(node, (ast.For, ast.While)):
            yield node
        stack.extend(ast.iter_child_nodes(node))


@register("writepath")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        for fn in _func_defs(m.tree):
            atomic = _calls_atomic_rename(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if _is_write_open(node) and not atomic:
                    path_arg = node.args[0] if node.args else node
                    names = [n.lower() for n in _identifiers(path_arg)]
                    if not any(t in n for n in names for t in _TMPISH):
                        findings.append(Finding(
                            rule="WR101", severity="error", path=m.path,
                            line=node.lineno,
                            message="non-atomic output publication: "
                                    "write-mode open() of a final path "
                                    "with no temp name and no os.replace "
                                    "in the function — a crashed writer "
                                    "leaves a truncated file readers "
                                    "will trust; write to <path>.tmp and "
                                    "os.replace into place"))
            if any(c in fn.name for c in _COMMITTERISH):
                continue                   # the committer/submit machinery
            for loop in _loops_of(fn):
                for node in ast.walk(loop):
                    if isinstance(node, ast.Call):
                        callee = node.func
                        name = callee.id if isinstance(callee, ast.Name) \
                            else (callee.attr
                                  if isinstance(callee, ast.Attribute)
                                  else "")
                        if name == "deflate_block":
                            findings.append(Finding(
                                rule="WR102", severity="error",
                                path=m.path, line=node.lineno,
                                message="serial deflate_block loop "
                                        "outside the committer: block "
                                        "compression in write/ must ride "
                                        "the shared pool through "
                                        "ParallelBGZFWriter, not a "
                                        "caller-thread loop"))
    return findings
