"""Declarative binary-layout contracts for the LC4xx analyzer.

Every fixed-struct field the decoders hand-address is declared ONCE here
— name, byte offset, width, dtype, with the provenance tag the repo uses
in code comments ([SPEC] = stated by the format spec).  The layout
analyzer cross-checks three things against this table:

1. every ``struct.pack/unpack`` *literal* format string in ``formats/``
   and ``split/`` is registered in ``KNOWN_FORMATS`` (an unknown format
   means a new layout grew without a contract);
2. hard-coded offsets in the functions listed in ``OFFSET_CONTRACTS``
   land exactly on declared fields (multi-byte reads must cover whole
   contiguous field runs; single-byte reads must fall inside a field);
3. the table itself is self-consistent (contiguous fields, widths sum
   to the struct size, format strings calcsize-match) and agrees with
   the runtime mirror ``ops/unpack_bam.FIXED_FIELDS``.

Sources: SAMv1 spec section 4.2 (BAM), RFC1952 + SAMv1 section 4.1
(BGZF), VCFv4.x spec section 6.3 (BCF record encoding), CRAMv3 spec
section 6 (file definition).
"""
from __future__ import annotations

import dataclasses
import struct
from typing import Dict, Optional, Tuple


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    offset: int
    width: int
    dtype: str          # "u8"/"i32"/"u16"/"f32"/"bytes"/...


@dataclasses.dataclass(frozen=True)
class LayoutSpec:
    name: str
    doc: str
    fields: Tuple[Field, ...]
    fmt: Optional[str] = None     # struct format covering the whole layout
    tag: str = "[SPEC]"

    @property
    def size(self) -> int:
        return sum(f.width for f in self.fields)

    def field_at(self, offset: int) -> Optional[Field]:
        """The field containing byte ``offset`` (for single-byte reads)."""
        for f in self.fields:
            if f.offset <= offset < f.offset + f.width:
                return f
        return None

    def run_at(self, offset: int, width: int) -> Optional[Tuple[Field, ...]]:
        """The contiguous field run exactly covering [offset, offset+width),
        or None when the span misaligns field boundaries."""
        run = []
        pos = offset
        end = offset + width
        for f in sorted(self.fields, key=lambda f: f.offset):
            if f.offset == pos and f.offset + f.width <= end:
                run.append(f)
                pos = f.offset + f.width
                if pos == end:
                    return tuple(run)
        return None


def _spec(name: str, doc: str, fields, fmt=None, tag="[SPEC]") -> LayoutSpec:
    return LayoutSpec(name=name, doc=doc, fmt=fmt, tag=tag,
                      fields=tuple(Field(*f) for f in fields))


SPECS: Dict[str, LayoutSpec] = {s.name: s for s in [
    _spec(
        "bam.record_prefix",
        "BAM alignment record fixed 36-byte prefix (SAMv1 section 4.2); "
        "runtime mirror: ops/unpack_bam.FIXED_FIELDS",
        [("block_size", 0, 4, "i32"), ("refid", 4, 4, "i32"),
         ("pos", 8, 4, "i32"), ("l_read_name", 12, 1, "u8"),
         ("mapq", 13, 1, "u8"), ("bin", 14, 2, "u16"),
         ("n_cigar", 16, 2, "u16"), ("flag", 18, 2, "u16"),
         ("l_seq", 20, 4, "i32"), ("mate_refid", 24, 4, "i32"),
         ("mate_pos", 28, 4, "i32"), ("tlen", 32, 4, "i32")],
        fmt="<iiiBBHHHiiii"),
    _spec(
        "bam.header_prefix",
        "BAM file header: magic + l_text (SAMv1 section 4.2)",
        [("magic", 0, 4, "bytes"), ("l_text", 4, 4, "i32")]),
    _spec(
        "bgzf.header",
        "BGZF block header fixed bytes before FEXTRA (RFC1952 + SAMv1 "
        "section 4.1)",
        [("id1", 0, 1, "u8"), ("id2", 1, 1, "u8"), ("cm", 2, 1, "u8"),
         ("flg", 3, 1, "u8"), ("mtime", 4, 4, "u32"), ("xfl", 8, 1, "u8"),
         ("os", 9, 1, "u8"), ("xlen", 10, 2, "u16")],
        fmt="<BBBBIBBH"),
    _spec(
        "bgzf.bc_subfield",
        "BGZF BC extra subfield: SI1 SI2 SLEN BSIZE (SAMv1 section 4.1)",
        [("si1", 0, 1, "u8"), ("si2", 1, 1, "u8"), ("slen", 2, 2, "u16"),
         ("bsize", 4, 2, "u16")],
        fmt="<BBHH"),
    _spec(
        "bgzf.footer",
        "BGZF block trailer: CRC32 + ISIZE (RFC1952)",
        [("crc32", 0, 4, "u32"), ("isize", 4, 4, "u32")],
        fmt="<II"),
    _spec(
        "bcf.record",
        "BCF record frame + 24-byte fixed shared prefix (VCFv4.x "
        "section 6.3.1); bcf_columns gathers bytes 8..32 as one tile",
        [("l_shared", 0, 4, "u32"), ("l_indiv", 4, 4, "u32"),
         ("chrom", 8, 4, "i32"), ("pos", 12, 4, "i32"),
         ("rlen", 16, 4, "i32"), ("qual", 20, 4, "f32"),
         ("n_info", 24, 2, "u16"), ("n_allele", 26, 2, "u16"),
         ("n_sample24", 28, 3, "u24"), ("n_fmt", 31, 1, "u8")]),
    _spec(
        "cram.file_definition",
        "CRAM file definition block (CRAMv3 section 6)",
        [("magic", 0, 4, "bytes"), ("major", 4, 1, "u8"),
         ("minor", 5, 1, "u8"), ("file_id", 6, 20, "bytes")]),
]}


# Every *literal* struct format string formats/ and split/ are allowed to
# use, with what layout it belongs to.  A format not in this registry is
# an LC401 finding: a new hand-addressed layout grew without a contract.
KNOWN_FORMATS: Dict[str, str] = {
    "<iiBBHHHiiii": "bam.record_prefix fields after block_size "
                    "(formats/bam.py record encode)",
    "<i": "single int32 scalar (BAM block_size / l_text / n_ref / "
          "l_name / counts)",
    "<I": "single uint32 scalar (CRC32 / ISIZE / BGZF bsize / "
          "tok3 ulen)",
    "<f": "single float32 scalar (BCF QUAL / typed value)",
    "<H": "single uint16 scalar (BGZF XLEN/SLEN/BSIZE, rANS freq, "
          "fqzcomp len)",
    "<HH": "BCF n_info + n_allele pair (bcf.record bytes 24..28)",
    "<ii": "BCF chrom + pos pair (bcf.record bytes 8..16)",
    "<iii": "BCF chrom + pos + rlen (bcf.record bytes 8..20) / "
            "BAI interval triple",
    "<II": "BCF l_shared + l_indiv frame (bcf.record bytes 0..8) / "
           "BGZF footer / vcf_planners frame peek",
    "<Ii": "BAI/tabix n_bin or bin id + count pairs",
    "<IQi": "BAI pseudo-bin: bin id + voffset + count (split/bai.py)",
    "<Q": "single uint64 virtual offset (BAI/tabix/splitting-index)",
    "<QQ": "virtual-offset pair (BAI chunk / tabix chunk / "
           "splitting-index span)",
    "<QQQ": "splitting-index record triple (split/splitting_index.py)",
    ">Q": "splitting-index big-endian magic/version stamp",
    "<8i": "tabix header int block: n_ref..l_nm (split/tabix.py)",
}


@dataclasses.dataclass(frozen=True)
class OffsetContract:
    """Function whose hard-coded offsets are checked against a spec.

    ``cursors`` maps local variable names that act as record-base
    cursors to (spec name, base offset added to every literal offset);
    ``tiles`` maps variables holding a gathered [n, w] byte tile to
    (spec name, absolute offset of tile column 0).
    """
    path: str
    function: str                      # qualname ('Cls.meth' for methods)
    cursors: Dict[str, Tuple[str, int]] = dataclasses.field(
        default_factory=dict)
    tiles: Dict[str, Tuple[str, int]] = dataclasses.field(
        default_factory=dict)


OFFSET_CONTRACTS: Tuple[OffsetContract, ...] = (
    OffsetContract(
        path="hadoop_bam_tpu/split/bam_guesser.py",
        function="BAMSplitGuesser._record_ok",
        cursors={"p": ("bam.record_prefix", 0)}),
    OffsetContract(
        path="hadoop_bam_tpu/split/bam_guesser.py",
        function="BAMSplitGuesser._chain_ok",
        cursors={"p": ("bam.record_prefix", 0)}),
    OffsetContract(
        path="hadoop_bam_tpu/parallel/pipeline.py",
        function="decode_span_payload_host",
        cursors={"p": ("bam.record_prefix", 0)}),
    OffsetContract(
        path="hadoop_bam_tpu/formats/bgzf.py",
        function="parse_block_header",
        cursors={"offset": ("bgzf.header", 0),
                 "p": ("bgzf.bc_subfield", 0)}),
    OffsetContract(
        path="hadoop_bam_tpu/formats/bcf.py",
        function="plausible_record_start",
        cursors={"off": ("bcf.record", 0)}),
    OffsetContract(
        path="hadoop_bam_tpu/formats/bcf.py",
        function="peek_record_sizes",
        cursors={"off": ("bcf.record", 0)}),
    OffsetContract(
        path="hadoop_bam_tpu/formats/bcf_columns.py",
        function="_decode_columns",
        tiles={"fixed": ("bcf.record", 8)}),
)

# (path, top-level assignment name) of runtime field tables that must
# mirror a spec exactly — parsed from the AST, no import needed
RUNTIME_MIRRORS: Tuple[Tuple[str, str, str], ...] = (
    ("hadoop_bam_tpu/ops/unpack_bam.py", "FIXED_FIELDS",
     "bam.record_prefix"),
)


def spec_self_check(spec: LayoutSpec) -> Tuple[str, ...]:
    """Internal-consistency problems of one spec row (empty = clean)."""
    problems = []
    pos = 0
    for f in sorted(spec.fields, key=lambda f: f.offset):
        if f.width <= 0:
            problems.append(f"field {f.name} has non-positive width")
        if f.offset != pos:
            problems.append(
                f"field {f.name} at offset {f.offset}, expected {pos} "
                f"(gap or overlap)")
        pos = f.offset + f.width
    if spec.fmt is not None:
        try:
            want = struct.calcsize(spec.fmt)
        except struct.error as e:
            problems.append(f"bad format {spec.fmt!r}: {e}")
        else:
            if want != spec.size:
                problems.append(
                    f"format {spec.fmt!r} calcsize {want} != declared "
                    f"size {spec.size}")
    return tuple(problems)
