"""QE5xx — query-cache key discipline: keys must carry file identity.

The chunk cache (``query/cache.py``) serves DECODED bytes; a key that
identifies a chunk only by its path and offsets keeps serving the old
decode after the file on disk is replaced — the classic stale-cache
corruption, invisible until a consumer diffs results against a fresh
read.  The engine therefore keys every entry on ``file_identity(path)``
(abspath + size + mtime_ns).  This analyzer keeps that contract:

- QE501: inside ``query/``, a cache ``get``/``put``/``pop`` call whose
  key expression mentions a path-like name but carries no identity
  component (no ``file_identity``/``identity`` call, no ``ident``/
  ``mtime``/``size`` name) is a raw-path key.  Build the key from
  ``file_identity(path)`` instead.
"""
from __future__ import annotations

import ast
from typing import Iterator, List

from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/query",)

_CACHE_METHODS = {"get", "put", "pop", "setdefault"}
_PATHISH = ("path", "filename", "fname", "file_name")
_IDENTITY = ("ident", "identity", "mtime", "size", "fingerprint")


def _identifiers(node: ast.AST) -> Iterator[str]:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            yield sub.id
        elif isinstance(sub, ast.Attribute):
            yield sub.attr


def _is_cache_call(call: ast.Call) -> bool:
    func = call.func
    if not isinstance(func, ast.Attribute) or func.attr not in _CACHE_METHODS:
        return False
    recv = func.value
    name = recv.id if isinstance(recv, ast.Name) else (
        recv.attr if isinstance(recv, ast.Attribute) else "")
    return "cache" in name.lower()


@register("querycache")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call) or not _is_cache_call(node):
                continue
            if not node.args:
                continue
            key = node.args[0]
            names = [n.lower() for n in _identifiers(key)]
            has_path = any(p in n for n in names for p in _PATHISH)
            has_ident = any(i in n for n in names for i in _IDENTITY)
            if has_path and not has_ident:
                findings.append(Finding(
                    rule="QE501", severity="error", path=m.path,
                    line=node.lineno,
                    message="cache key built from a raw path without file "
                            "identity — a replaced file would keep serving "
                            "stale decoded chunks; key on "
                            "file_identity(path) (abspath + size + "
                            "mtime_ns) from query/cache.py instead"))
    return findings
