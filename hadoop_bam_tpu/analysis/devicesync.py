"""DV9xx — device-decode-plane sync discipline: no per-iteration host
syncs on device arrays.

The round-11 device decode plane exists so inflated bytes never touch the
host on the stats paths: LZ77 resolve, the record walk and the
fixed-field unpack all run on the mesh, and the ONLY things that come
back are psum'd counters plus three walk scalars per device, drained in
one bulk ``jax.device_get`` at the end.  Its founding anti-pattern is the
prototype it replaced: a per-block ``np.asarray(resolve_tokens(...))``
copy loop that synced the device once per 64 KiB block and serialized the
whole plane behind the link.

- DV901: inside the device decode plane (``ops/inflate_device.py`` and
  ``parallel/pipeline.py``), a host-sync call — ``np.asarray``,
  ``jax.device_get``, ``.item()``, ``.tolist()`` — in a ``for``/``while``
  loop body.  Each iteration's sync is a full pipeline stall; batch the
  fetch outside the loop (one ``device_get`` of the collected handles)
  or keep the value on device.

``inflate_span_device`` is exempt by name: its CONTRACT is returning
host bytes (the library span-inflate entry point), so its chunk-granular
``np.asarray`` is the API boundary, not a leak — the driver paths the
plane actually runs through must never sync per iteration.  Loop context
does not cross a nested function boundary (a closure defined inside a
loop is dispatched later, not per iteration).
"""
from __future__ import annotations

import ast
from typing import List

from hadoop_bam_tpu.analysis.astutil import last_segment
from hadoop_bam_tpu.analysis.core import Finding, Project, register

SCOPE = ("hadoop_bam_tpu/ops/inflate_device.py",
         "hadoop_bam_tpu/parallel/pipeline.py",
         # round 21: the plane grew the variant and cold-serve-tile
         # families — their drivers carry the same discipline
         "hadoop_bam_tpu/parallel/variant_pipeline.py",
         "hadoop_bam_tpu/serve/tiles.py")

# host-boundary functions whose contract IS a host copy
EXEMPT_FUNCTIONS = ("inflate_span_device",)

# attribute-call names that force a device->host sync
_SYNC_ATTRS = {"item", "tolist"}
# module-function calls that force one: np.asarray(x), jax.device_get(x)
_SYNC_CALLS = {"asarray": ("np", "numpy"), "device_get": ("jax",)}


def _sync_call(node: ast.AST) -> str:
    """Return a human name when ``node`` is a host-sync call, else ''."""
    if not isinstance(node, ast.Call):
        return ""
    fn = node.func
    if not isinstance(fn, ast.Attribute):
        return ""
    name = fn.attr
    if name in _SYNC_ATTRS:
        return f".{name}()"
    roots = _SYNC_CALLS.get(name)
    if roots and isinstance(fn.value, ast.Name) and fn.value.id in roots:
        return f"{fn.value.id}.{name}()"
    return ""


def _finding(path: str, node: ast.AST, sync: str, ctx: str) -> Finding:
    return Finding(
        rule="DV901", severity="error", path=path, line=node.lineno,
        message=f"per-iteration host sync '{sync}' inside a loop in the "
                f"device decode plane ('{ctx}') — every iteration's sync "
                f"stalls the token-feed pipeline; batch the fetch outside "
                f"the loop (one jax.device_get of the collected handles) "
                f"or keep the value on device")


@register("devicesync")
def analyze(project: Project) -> List[Finding]:
    findings: List[Finding] = []
    for m in project.select(SCOPE):

        def visit(node: ast.AST, in_loop: bool, exempt: bool,
                  where: str) -> None:
            sync = _sync_call(node)
            if sync and in_loop and not exempt:
                findings.append(_finding(m.path, node, sync, where))
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # fresh scope: an enclosing loop does not make a nested
                # function body per-iteration code
                ex = node.name in EXEMPT_FUNCTIONS
                for child in ast.iter_child_nodes(node):
                    visit(child, False, ex, node.name)
            elif isinstance(node, (ast.For, ast.AsyncFor)):
                # the iterator expression evaluates ONCE — a bulk
                # device_get fed to a for loop is the approved idiom
                visit(node.iter, in_loop, exempt, where)
                for part in (node.target, *node.body, *node.orelse):
                    visit(part, True, exempt, where)
            elif isinstance(node, ast.While):
                for child in ast.iter_child_nodes(node):
                    visit(child, True, exempt, where)
            else:
                for child in ast.iter_child_nodes(node):
                    visit(child, in_loop, exempt, where)

        visit(m.tree, False, False, "<module>")
    return findings
