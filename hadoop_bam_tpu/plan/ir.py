"""Plan IR: the declarative middle layer between drivers and execution.

Every driver family used to hand-wire the same pipeline shape — plan
spans, pick a decode plane, feed the staging ring, retry/quarantine bad
spans, reduce on the mesh — and the gating conditions (`intervals`,
`skip_bad_spans`, `inflate_backend`, `fixed_shape`) were re-implemented
per path.  The IR makes that shape EXPLICIT:

    Source -> Spans -> [DecodePlane] -> TensorOps DAG -> Sink

as frozen dataclasses with a stable, canonical serialization
(``PlanIR.to_doc``) and a content digest (``PlanIR.digest``) built with
the same recipe as ``jobs.journal.plan_digest`` — canonical sorted-key
JSON, path spellings canonicalized to abspath, sha256 truncated to 24
hex chars — so a plan digest can sit next to a span-plan digest in a
job journal's refuse-to-resume contract.

The decode PLANE is deliberately *not* part of the IR: plane selection
is a property of the process (probed backends, native availability,
breaker state), not of the work, and is decided in exactly one place —
``plan.executor.select_plane`` — at execution time.  ``hbam explain``
prints both: the plan (portable) and the decision (local).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
from typing import Dict, List, Optional, Tuple

IR_VERSION = 1

# JSON-able parameter scalar types accepted by op_node / SinkIR.of
_SCALARS = (str, int, float, bool, type(None))


def _norm_value(v):
    """Normalize one op/sink parameter value to a hashable, JSON-stable
    form (tuples for sequences, scalars pass through)."""
    if isinstance(v, _SCALARS):
        return v
    if isinstance(v, (list, tuple)):
        return tuple(_norm_value(x) for x in v)
    raise TypeError(
        f"plan IR parameters must be JSON-able scalars/sequences, got "
        f"{type(v).__name__}: {v!r}")


def _params_tuple(params: Dict) -> Tuple[Tuple[str, object], ...]:
    return tuple((k, _norm_value(params[k])) for k in sorted(params))


def _params_doc(params: Tuple[Tuple[str, object], ...]) -> Dict:
    def unroll(v):
        return list(unroll(x) for x in v) if isinstance(v, tuple) else v
    return {k: unroll(v) for k, v in params}


@dataclasses.dataclass(frozen=True)
class SourceIR:
    """What the plan reads.  ``role`` distinguishes the three access
    shapes: "scan" (whole-file span plan), "chunk" (pinned virtual-offset
    ranges out of a genomic index), "join" (k-way cohort merge keyed by a
    manifest)."""
    path: str
    fmt: str            # "bam" | "vcf" | "bcf" | "cram" | "fastq" | ...
    role: str = "scan"  # "scan" | "chunk" | "join"

    def to_doc(self) -> Dict:
        return {"path": os.path.abspath(self.path), "fmt": self.fmt,
                "role": self.role}


@dataclasses.dataclass(frozen=True)
class SpansIR:
    """How the source cuts into retryable decode units.  ``mode="auto"``
    defers to the family's span planner (the digest then covers the
    requested grain, not the data-dependent cuts — pinned span GEOMETRY
    is ``jobs.journal.plan_digest``'s job); ``mode="pinned"`` carries
    explicit (path, start_voffset, end_voffset) triples, e.g. the
    coalesced chunk ranges of a region query."""
    mode: str = "auto"                 # "auto" | "pinned"
    n_spans: Optional[int] = None
    span_bytes: Optional[int] = None
    pinned: Tuple[Tuple[str, int, int], ...] = ()

    @classmethod
    def auto(cls, n_spans: Optional[int] = None,
             span_bytes: Optional[int] = None) -> "SpansIR":
        return cls(mode="auto", n_spans=n_spans, span_bytes=span_bytes)

    @classmethod
    def pin(cls, triples) -> "SpansIR":
        return cls(mode="pinned",
                   pinned=tuple((str(p), int(s), int(e))
                                for p, s, e in triples))

    def to_doc(self) -> Dict:
        doc: Dict = {"mode": self.mode}
        if self.n_spans is not None:
            doc["n_spans"] = int(self.n_spans)
        if self.span_bytes is not None:
            doc["span_bytes"] = int(self.span_bytes)
        if self.pinned:
            doc["pinned"] = [[os.path.abspath(p), s, e]
                             for p, s, e in self.pinned]
        return doc

    def summary(self) -> str:
        if self.mode == "pinned":
            return f"pinned n={len(self.pinned)}"
        bits = []
        if self.n_spans is not None:
            bits.append(f"n_spans={self.n_spans}")
        if self.span_bytes is not None:
            bits.append(f"span_bytes={self.span_bytes}")
        return "auto" + (f" ({', '.join(bits)})" if bits else "")


@dataclasses.dataclass(frozen=True)
class TensorOpIR:
    """One node of the tensor-op DAG (linear for every current family:
    a pack/projection stage followed by a reduce or filter)."""
    op: str
    params: Tuple[Tuple[str, object], ...] = ()

    def to_doc(self) -> Dict:
        doc: Dict = {"op": self.op}
        if self.params:
            doc["params"] = _params_doc(self.params)
        return doc

    def render(self) -> str:
        if not self.params:
            return self.op
        inner = ", ".join(f"{k}={v!r}" for k, v in self.params)
        return f"{self.op}({inner})"


def op_node(op: str, **params) -> TensorOpIR:
    """TensorOpIR constructor with keyword params (sorted + normalized,
    so two spellings of the same op always digest identically)."""
    return TensorOpIR(op=op, params=_params_tuple(params))


@dataclasses.dataclass(frozen=True)
class SinkIR:
    """Where the op DAG's output lands: "stats" (a reduced host dict),
    "tensor_batches" (sharded device dicts), "chunk_columns" (host
    predicate columns for the query/serve tiers)."""
    kind: str
    params: Tuple[Tuple[str, object], ...] = ()

    @classmethod
    def of(cls, kind: str, **params) -> "SinkIR":
        return cls(kind=kind, params=_params_tuple(params))

    def to_doc(self) -> Dict:
        doc: Dict = {"kind": self.kind}
        if self.params:
            doc["params"] = _params_doc(self.params)
        return doc


@dataclasses.dataclass(frozen=True)
class PlanIR:
    """The whole plan.  Frozen and hashable; ``digest()`` is the stable
    identity the journal seam records (``jobs.runner.plan_journal_params``)
    and ``hbam explain`` prints."""
    source: SourceIR
    spans: SpansIR
    ops: Tuple[TensorOpIR, ...]
    sink: SinkIR

    def to_doc(self) -> Dict:
        return {
            "v": IR_VERSION,
            "source": self.source.to_doc(),
            "spans": self.spans.to_doc(),
            "ops": [o.to_doc() for o in self.ops],
            "sink": self.sink.to_doc(),
        }

    def digest(self) -> str:
        """sha256 over the canonical serialization, truncated to 24 hex
        chars — the ``jobs.journal.plan_digest`` recipe, so IR digests
        and span-plan digests share one format in journal headers."""
        blob = json.dumps(self.to_doc(), sort_keys=True,
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()[:24]

    def render(self) -> List[str]:
        """Human-readable lines (the ``hbam explain`` text body)."""
        return [
            f"plan    {self.digest()}",
            f"source  path={self.source.path} fmt={self.source.fmt} "
            f"role={self.source.role}",
            f"spans   {self.spans.summary()}",
            "ops     " + " -> ".join(o.render() for o in self.ops),
            f"sink    {self.sink.kind}",
        ]
