"""hadoop_bam_tpu.plan — the declarative plan/execute layer.

- ``ir``:        Source -> Spans -> TensorOps DAG -> Sink frozen
                 dataclasses with a stable ``plan_digest``-compatible
                 serialization.
- ``builders``:  drivers compile to plans here (one catalogue of what
                 each workload is).
- ``executor``:  ``select_plane`` (the single plane-gating predicate —
                 PL101 keeps gates out of every other package) and
                 ``execute`` (the one entry the rewired drivers funnel
                 through).
"""
from hadoop_bam_tpu.plan.ir import (  # noqa: F401
    PlanIR, SinkIR, SourceIR, SpansIR, TensorOpIR, op_node,
)
from hadoop_bam_tpu.plan.executor import (  # noqa: F401
    PlaneDecision, execute, plane_report, select_plane,
)
