"""The one executor: plane selection, dispatch, and the driver seam.

Two jobs live here, and ONLY here:

1. **Plane selection** (``select_plane``): the single predicate table
   that decides which decode plane a plan runs on and why every other
   plane was rejected.  The gates — ``intervals``, ``skip_bad_spans``,
   ``inflate_backend``, fused availability — used to be re-implemented
   per driver (three independent copies in ``parallel/pipeline.py``
   alone); the ``planroute`` lint analyzer (PL101) now keeps
   plane-gating conditionals out of every package but this one.

2. **Execution** (``execute``): the uniform entry the rewired drivers
   funnel through.  A driver is a thin plan *builder*
   (``plan/builders.py``); ``execute`` dispatches the compiled plan to
   its family runner, counting executions and stamping the
   ``plan.execute_wall`` span, and owns the generic wiring — the cohort
   tensor feed is wired HERE (FeedPipeline + sharded device_put), and
   the query-chunk runner owns ``decode_with_retry`` + the
   ``query.decode_wall``/chunk metrics taxonomy.  Family runners that
   need the mesh-feed machinery of ``parallel/pipeline.py`` delegate to
   its ``_*_impl`` functions, which consume the decision this module
   computed instead of re-deriving gates.

Decode planes (``config.DECODE_PLANES``): "device" (token-feed on-mesh
inflate; flagstat is the pilot DAG), "native" (host C++ inflate, with
the fused single-pass sweep as a MODE when eligible), "zlib" (portable
Python).  ``resolve_inflate_backend`` (config.py) turns "auto" into a
concrete starting rung once per process; the ``DemotionLadder``
(resilience/domains.py) may still demote mid-run.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Iterator, Optional, Tuple

from hadoop_bam_tpu.config import (
    DECODE_PLANES, DEFAULT_CONFIG, HBamConfig, resolve_inflate_backend,
)
from hadoop_bam_tpu.plan.ir import PlanIR, SourceIR, TensorOpIR, op_node
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.metrics import METRICS


# ---------------------------------------------------------------------------
# plane selection — THE predicate table
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class PlaneDecision:
    """One plan's resolved routing: the selected plane, the backend
    strings the span-level decoders consume, fused-mode eligibility,
    and — for ``hbam explain`` — why each rejected plane/mode failed
    its gate."""
    plane: str            # selected decode plane for this op DAG
    backend: str          # resolve_inflate_backend(config) result
    host_backend: str     # what host span decoders pass as backend
    use_fused: bool       # fused single-pass native sweep eligible
    stream_fused: bool    # chunk-streamed fused decode eligible
    rejected: Tuple[Tuple[str, str], ...]   # (plane_or_mode, reason)

    def to_doc(self) -> Dict:
        return {"plane": self.plane, "backend": self.backend,
                "host_backend": self.host_backend,
                "use_fused": self.use_fused,
                "stream_fused": self.stream_fused,
                "rejected": {p: r for p, r in self.rejected}}


def _use_fused(config: Optional[HBamConfig],
               inflate_backend: str = "auto") -> bool:
    """Fused-path eligibility: the config knob (default on), a native
    backend choice, and the fused entry points actually loadable.  The
    span-level decoders (``decode_span_*``) consult this directly —
    they run under per-span ladder demotion, below the plan grain."""
    from hadoop_bam_tpu.ops import inflate as inflate_ops

    cfg = config if config is not None else DEFAULT_CONFIG
    return (bool(cfg.use_fused_decode)
            and inflate_backend in ("auto", "native")
            and inflate_ops.fused_available())


def _fused_stream_gate(config: Optional[HBamConfig], intervals) -> bool:
    """Chunk-streaming eligibility, shared by every driver that feeds
    fused chunks to the FeedPipeline (ONE place, so a new
    streaming-incompatible condition cannot be added to one driver and
    missed in another): fused on, no interval filtering (the row mask
    needs the whole span's offsets), and no skip_bad_spans (quarantine
    is span-granular; a streamed span's early chunks would already be
    dispatched when a late chunk turns out corrupt)."""
    cfg = config if config is not None else DEFAULT_CONFIG
    return (_use_fused(cfg) and intervals is None
            and not cfg.skip_bad_spans)


def host_backend_for(config: Optional[HBamConfig]) -> str:
    """The backend string host span decoders take: the resolved plane,
    with "device" mapped to "auto" (families ride the host planes
    wherever the token-feed plane does not apply)."""
    backend = resolve_inflate_backend(config)
    return "auto" if backend == "device" else backend


# which op DAGs the token-feed device plane implements, per source
# format — THE capability table (ROADMAP item 1).  An op anywhere in the
# DAG from the format's set marks the whole DAG device-capable; the
# reduce/sink op is the stable discriminator across the parameterized
# builder DAGs and the minimal twins below.  Text VCF deliberately has
# no row: the device plane rides the BGZF token feed, and text variant
# lines have no gather-shaped record layout to unpack on-mesh.
_DEVICE_DAGS = {
    "bam": frozenset({"flagstat_reduce", "seq_stats_reduce",
                      "tile_build"}),
    "bcf": frozenset({"variant_unpack_device", "variant_stats_reduce"}),
}


def _device_capable(source: SourceIR, ops: Tuple[TensorOpIR, ...]) -> bool:
    """Does the token-feed device plane implement this op DAG?"""
    fam = _DEVICE_DAGS.get(getattr(source, "fmt", None))
    if not fam:
        return False
    return any(getattr(o, "op", None) in fam for o in ops)


# canonical op DAGs of the in-repo scan/serve families (plan/builders.py
# carries the fully-parameterized versions; these minimal twins are what
# the mesh-feed impls pass to select_plane when invoked directly)
FLAGSTAT_DAG = (op_node("project"), op_node("flagstat_reduce"))
PAYLOAD_DAG = (op_node("payload_pack"), op_node("seq_stats_reduce"))
VARIANT_DAG = (op_node("variant_pack"), op_node("variant_stats_reduce"))
SERVE_TILE_DAG = (op_node("chunk_decode"), op_node("tile_build"))


def select_plane(source: SourceIR, ops: Tuple[TensorOpIR, ...],
                 config: Optional[HBamConfig], *,
                 intervals=None, ladder=None) -> PlaneDecision:
    """THE plane-selection predicate table (module docstring).

    ``intervals`` is the parsed interval filter (None = no filtering —
    the gates test identity, matching the drivers' historical
    ``intervals is None``).  ``ladder`` is the file's ``DemotionLadder``
    when adaptive planes are on; its device breaker is consulted LAST,
    only when every other device gate passed, because ``allow_plane``
    consumes a half-open probe slot.

    Native-library absence deliberately does NOT gate the device plane
    here: an explicit ``inflate_backend="device"`` without the native
    tokenizer is a configuration fault and must surface as PlanError
    from the device runner, not silently reroute.  It DOES gate the
    fused mode (``fused_available`` implies native)."""
    from hadoop_bam_tpu.ops import inflate as inflate_ops

    cfg = config if config is not None else DEFAULT_CONFIG
    backend = resolve_inflate_backend(cfg)
    host_backend = "auto" if backend == "device" else backend
    rejected = []

    fused = True
    if not cfg.use_fused_decode:
        fused = False
        rejected.append(("fused", "config.use_fused_decode is off"))
    elif host_backend not in ("auto", "native"):
        fused = False
        rejected.append(
            ("fused", f"backend {host_backend!r} disables the native "
                      f"fused sweep"))
    elif not inflate_ops.fused_available():
        fused = False
        rejected.append(
            ("fused", "native fused entry points unavailable"))

    plane = None
    if backend != "device":
        rejected.append(
            ("device", f"inflate_backend resolved to {backend!r}"))
    elif not _device_capable(source, ops):
        rejected.append(
            ("device", "no device decode plane for this op DAG "
                       "(token-feed families: BAM flagstat/payload/"
                       "serve-tile, BCF variant)"))
    elif intervals is not None:
        rejected.append(
            ("device", "interval filtering needs whole-span offsets "
                       "on the host"))
    elif cfg.skip_bad_spans:
        rejected.append(
            ("device", "skip_bad_spans needs span-granular quarantine"))
    elif ladder is not None and not ladder.allow_plane("device"):
        rejected.append(
            ("device", "device fault-domain breaker is OPEN"))
    else:
        plane = "device"

    if plane is None:
        if backend == "zlib":
            rejected.append(
                ("native", "inflate_backend='zlib' pins the portable "
                           "plane"))
            plane = "zlib"
        else:
            plane = "native"

    stream = fused and intervals is None and not cfg.skip_bad_spans
    if fused and not stream:
        rejected.append(
            ("fused-stream",
             "interval filtering needs the whole span's offsets"
             if intervals is not None
             else "skip_bad_spans needs span-granular quarantine"))
    assert plane in DECODE_PLANES
    return PlaneDecision(plane=plane, backend=backend,
                         host_backend=host_backend, use_fused=fused,
                         stream_fused=stream, rejected=tuple(rejected))


def select_chunk_source(*, tile_cached: bool, fleet_owned: bool,
                        degraded: bool, want_records: bool,
                        peer_ready: bool) -> Tuple[str, str]:
    """THE chunk-source predicate for the serving fleet: which plane
    answers one chunk of a region query — ``"tile"`` (device-resident
    tile, no work), ``"local"`` (host fetch+inflate+decode on this
    replica), or ``"peer"`` (fetch the decoded columns from the chunk's
    rendezvous owner, so a warm peer beats local host decode).

    Lives HERE for the same reason ``select_plane`` does: the serving
    loop consumes a decision instead of re-deriving routing gates, and
    ``hbam explain``/health surfaces can show why a chunk went where.
    Returns ``(source, reason)``."""
    if tile_cached:
        return "tile", "device-resident tile hit"
    if degraded:
        # quorum lost: serve what we own locally rather than erroring —
        # peers we cannot see cannot be owners we can reach
        return "local", "degraded partition mode (no quorum)"
    if want_records:
        # record materialization reads the host chunk anyway; a peer
        # round trip would be pure overhead on top of the local decode
        return "local", "records mode needs the local host chunk"
    if fleet_owned:
        return "local", "this replica is a rendezvous owner"
    if not peer_ready:
        return "local", "no reachable peer owner (breakers/eviction)"
    return "peer", "peer-owned chunk: fetch decoded columns"


def plane_report(config: Optional[HBamConfig] = None) -> Dict[str, Dict]:
    """Display-only decision table per driver family for this process +
    config — the ``hbam serve`` health surface.  Never consumes breaker
    probes (ladder=None) and never touches files; the interval gate is
    approximated by whether ``config.bam_intervals`` is set."""
    cfg = config if config is not None else DEFAULT_CONFIG
    intervals = () if getattr(cfg, "bam_intervals", None) else None
    # the SAME DAG constants the drivers route with — rebuilding them
    # here would be exactly the per-surface drift this module removes
    fams = {
        "flagstat": (SourceIR("<bam>", "bam"), FLAGSTAT_DAG),
        "payload": (SourceIR("<bam>", "bam"), PAYLOAD_DAG),
        "variant": (SourceIR("<bcf>", "bcf"), VARIANT_DAG),
        "serve": (SourceIR("<bam>", "bam"), SERVE_TILE_DAG),
    }
    return {name: select_plane(src, ops, cfg,
                               intervals=intervals).to_doc()
            for name, (src, ops) in fams.items()}


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

def execute(plan: PlanIR, *, config: Optional[HBamConfig] = None,
            **kw):
    """Run a compiled plan.  ``kw`` carries the family runner's
    execution-time context (mesh, header, pinned spans, geometry,
    quarantine manifest, prefetch depth, and family extras like the
    query runner's ``decode_fn`` or the cohort runner's ``dataset``).

    Returns whatever the sink promises: a stats dict, a lazy tensor
    batch iterator, or the query tier's (columns, cache-cost) pair."""
    cfg = config if config is not None else DEFAULT_CONFIG
    runner = _runner_for(plan)
    METRICS.count("plan.executions")
    if getattr(runner, "lazy_sink", False):
        # generator sinks: a span here would close at dispatch,
        # microseconds in — a mixed-semantics series next to the eager
        # sinks' full-run walls.  The iteration's own stage spans
        # (cohort.*) already cover the work.
        return runner(plan, cfg, kw)
    with METRICS.span("plan.execute_wall", sink=plan.sink.kind,
                      fmt=plan.source.fmt):
        return runner(plan, cfg, kw)


def _runner_for(plan: PlanIR):
    kind = plan.sink.kind
    if kind == "flagstat":
        return _run_flagstat
    if kind == "seq_stats":
        return _run_seq_stats
    if kind == "variant_stats":
        return _run_variant_stats
    if kind == "chunk_columns":
        return _run_chunk_columns
    if kind == "tensor_batches" and plan.source.role == "join":
        return _run_cohort_batches
    if kind == "bam_file":
        return _run_mkdup
    raise PlanError(
        f"no executor runner for sink {kind!r} "
        f"(source role {plan.source.role!r}) — known sinks: flagstat, "
        f"seq_stats, variant_stats, chunk_columns, join/tensor_batches, "
        f"bam_file")


def _run_flagstat(plan: PlanIR, cfg: HBamConfig, kw: Dict):
    from hadoop_bam_tpu.parallel import pipeline

    return pipeline._flagstat_impl(
        plan.source.path, mesh=kw.get("mesh"), config=cfg,
        geometry=kw.get("geometry"), header=kw.get("header"),
        spans=kw.get("spans"), prefetch=kw.get("prefetch", 2),
        quarantine=kw.get("quarantine"))


def _run_seq_stats(plan: PlanIR, cfg: HBamConfig, kw: Dict):
    from hadoop_bam_tpu.parallel import pipeline

    return pipeline._seq_stats_impl(
        plan.source.path, mesh=kw.get("mesh"), config=cfg,
        geometry=kw.get("geometry"), header=kw.get("header"),
        spans=kw.get("spans"), prefetch=kw.get("prefetch", 2),
        quarantine=kw.get("quarantine"))


def _run_variant_stats(plan: PlanIR, cfg: HBamConfig, kw: Dict):
    from hadoop_bam_tpu.parallel import variant_pipeline

    return variant_pipeline._variant_stats_impl(
        plan.source.path, mesh=kw.get("mesh"), config=cfg,
        geometry=kw.get("geometry"), header=kw.get("header"),
        spans=kw.get("spans"), prefetch=kw.get("prefetch", 2))


def _run_mkdup(plan: PlanIR, cfg: HBamConfig, kw: Dict):
    """The fused preprocessing pipeline: the ``bam_file`` sink names the
    output, the ``markdup`` op node carries the output-affecting
    options (both under the plan digest the journal pins)."""
    from hadoop_bam_tpu.prep.pipeline import markdup_bam_mesh

    md = dict(next(op for op in plan.ops if op.op == "markdup").params)
    sink = dict(plan.sink.params)
    return markdup_bam_mesh(
        plan.source.path, sink["path"], mesh=kw.get("mesh"),
        config=cfg, header=kw.get("header"),
        remove_duplicates=bool(md.get("remove_duplicates", False)),
        library_from=md.get("library_from", "none"),
        round_records=kw.get("round_records"),
        journal_path=kw.get("journal_path"))


def _run_chunk_columns(plan: PlanIR, cfg: HBamConfig, kw: Dict):
    """Query-engine chunk decode: ONE pinned span through
    ``decode_with_retry`` under the query metrics taxonomy.  Returns
    the ``(columns, cache_cost)`` pair ``ChunkCache.get_or_compute``
    stores — cost None on a quarantined chunk, so a healed transient
    fault re-decodes on the next query instead of caching emptiness."""
    import time

    import numpy as np

    from hadoop_bam_tpu.parallel.pipeline import decode_with_retry
    from hadoop_bam_tpu.split.spans import FileVirtualSpan

    decode_fn = kw["decode_fn"]
    (path, s, e), = plan.spans.pinned
    span = FileVirtualSpan(path, s, e)
    t0 = time.perf_counter()
    with METRICS.span("query.decode_wall", kind=plan.source.fmt):
        value = decode_with_retry(decode_fn, span, cfg)
    # per-chunk fetch+decode latency/size distributions: cache misses
    # only — the p99 here is what a cold region costs
    METRICS.observe("query.chunk_fetch_s", time.perf_counter() - t0)
    if value is None:
        # config.skip_bad_spans quarantined the chunk: serve it as
        # empty (the scan drivers' skip semantics), and do NOT cache
        METRICS.count("query.chunks_skipped")
        return ({"rid": np.empty(0, np.int32),
                 "pos1": np.empty(0, np.int32),
                 "end1": np.empty(0, np.int32),
                 "records": [], "n": 0, "nbytes": 0}, None)
    METRICS.observe("query.chunk_bytes", int(value["nbytes"]))
    METRICS.count("query.chunks_decoded")
    return (value, int(value["nbytes"]))


def _run_cohort_batches(plan: PlanIR, cfg: HBamConfig,
                        kw: Dict) -> Iterator[Dict]:
    """The cohort tensor feed, wired by the executor: joined site
    chunks through the shared ``variant_feed``/FeedPipeline with the
    sharded device_put emit whose returned dict doubles as the ring
    slot's in-flight handle.  A generator, so a dataset whose
    ``tensor_batches`` is built but never iterated starts no join (and
    opens no journal)."""
    dataset = kw["dataset"]

    def gen():
        import jax
        import numpy as np
        from jax.sharding import NamedSharding, PartitionSpec as P

        from hadoop_bam_tpu.parallel.mesh import make_mesh
        from hadoop_bam_tpu.parallel.variant_pipeline import variant_feed

        mesh = kw.get("mesh")
        if mesh is None:
            mesh = make_mesh()
        geometry = kw.get("geometry")
        if geometry is None:
            geometry = dataset.geometry
        n_dev = int(np.prod(mesh.devices.shape))
        sharding = NamedSharding(mesh, P("data"))

        keys, fp, tuples = variant_feed(dataset.site_chunks(), n_dev,
                                        geometry.tile_records, cfg,
                                        fixed_shape=True, fmt="cohort")
        if fp is None:
            return

        def emit(arrays, counts) -> Dict:
            # the device dict doubles as the slot's in-flight handle
            out = {k: jax.device_put(a, sharding)
                   for k, a in zip(keys, arrays)}
            out["n_records"] = jax.device_put(counts, sharding)
            return out

        yield from fp.stream(tuples, emit)

    return gen()


_run_cohort_batches.lazy_sink = True   # see execute(): no dispatch span
