"""Plan builders: drivers compile to IR here.

Each public driver is now a thin wrapper: build the plan, hand it to
``plan.executor.execute``.  The builders are the one catalogue of what
each workload IS — source format, span grain, tensor-op DAG, sink — so
a new workload (markdup, pileup windows, query-then-analyze fusion)
starts as a new builder composing existing ops, not a sixth hand-wired
pipeline.

Builders never touch the filesystem beyond what identity requires (the
cohort builder reads the manifest's identity digest); expensive
planning — span cutting, header reads — stays execution-time, so
``hbam explain`` can print any plan cheaply.
"""
from __future__ import annotations

import os
from typing import Optional, Union

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.plan.ir import (
    PlanIR, SinkIR, SourceIR, SpansIR, op_node,
)

# the whole-file scan span grains the drivers plan at when the caller
# didn't pin spans (values lifted from the drivers they replaced; the
# flagstat 4 MiB sweep result is recorded in parallel/pipeline.py)
FLAGSTAT_SPAN_BYTES = 4 << 20
PAYLOAD_SPAN_BYTES = 8 << 20


def flagstat_plan(path: str,
                  config: Optional[HBamConfig] = None) -> PlanIR:
    """BAM flagstat: project the flagstat columns, reduce with one psum
    per tile group.  The only DAG the token-feed device plane currently
    implements (``executor._device_capable``)."""
    from hadoop_bam_tpu.ops.unpack_bam import FLAGSTAT_PROJECTION

    cfg = config if config is not None else DEFAULT_CONFIG
    return PlanIR(
        source=SourceIR(path, "bam"),
        spans=SpansIR.auto(span_bytes=FLAGSTAT_SPAN_BYTES),
        ops=(op_node("project", projection=FLAGSTAT_PROJECTION,
                     intervals=cfg.bam_intervals),
             op_node("flagstat_reduce")),
        sink=SinkIR.of("flagstat"))


def seq_stats_plan(path: str, config: Optional[HBamConfig] = None,
                   geometry=None) -> PlanIR:
    """BAM payload stats: pack prefix + 4-bit seq + qual row tiles,
    reduce through the fused Pallas payload kernel."""
    from hadoop_bam_tpu.parallel.pipeline import PayloadGeometry

    cfg = config if config is not None else DEFAULT_CONFIG
    g = geometry if geometry is not None else PayloadGeometry()
    return PlanIR(
        source=SourceIR(path, "bam"),
        spans=SpansIR.auto(span_bytes=PAYLOAD_SPAN_BYTES),
        ops=(op_node("payload_pack", max_len=g.max_len,
                     seq_stride=g.seq_stride, qual_stride=g.qual_stride,
                     tile_records=g.tile_records,
                     fixed_shape=g.fixed_shape,
                     intervals=cfg.bam_intervals),
             op_node("seq_stats_reduce")),
        sink=SinkIR.of("seq_stats"))


def variant_stats_plan(path: str, config: Optional[HBamConfig] = None,
                       geometry=None) -> PlanIR:
    """VCF/BCF variant stats: pack (chrom, pos, flags, dosage) tiles,
    reduce counts + allele frequency + per-sample call rates.

    A BCF source compiled under the device backend routes its unpack
    through the mesh (``variant_unpack_device``) — and that op is part
    of the plan IDENTITY: a journaled job compiled for the device route
    refuses to resume against a host-plane journal and vice versa
    (``jobs.runner.plan_journal_params``), because the two routes
    partition work differently (device-plane span grain vs the host
    span plan)."""
    from hadoop_bam_tpu.config import resolve_inflate_backend

    cfg = config if config is not None else DEFAULT_CONFIG
    fmt = "bcf" if path.lower().endswith(".bcf") else "vcf"
    params = {}
    if geometry is not None:
        params = dict(n_samples=geometry.n_samples,
                      tile_records=geometry.tile_records)
    ops = [op_node("variant_pack", **params)]
    if fmt == "bcf" and resolve_inflate_backend(cfg) == "device":
        ops.append(op_node("variant_unpack_device"))
    ops.append(op_node("variant_stats_reduce"))
    return PlanIR(
        source=SourceIR(path, fmt),
        spans=SpansIR.auto(),
        ops=tuple(ops),
        sink=SinkIR.of("variant_stats"))


def serve_tile_plan(path: str, kind: str = "bam",
                    start_voffset: int = 0,
                    end_voffset: int = 0) -> PlanIR:
    """One cold serve-tile build: decode a coalesced chunk's virtual-
    offset range and pack the (rid, pos1, end1) interval tile the
    region-serve filter consumes (serve/tiles.py).  The serving loop
    consumes ``select_plane`` on this DAG directly (a tile build is not
    an executor sink — the loop owns ring/cache placement); the builder
    exists for the ``hbam explain serve-tile`` surface and the digest
    contract."""
    return PlanIR(
        source=SourceIR(path, kind, role="chunk"),
        spans=SpansIR.pin([(path, start_voffset, end_voffset)]),
        ops=(op_node("chunk_decode"), op_node("tile_build")),
        sink=SinkIR.of("serve_tiles"))


def query_chunk_plan(path: str, kind: str, start_voffset: int,
                     end_voffset: int) -> PlanIR:
    """One index-resolved, coalesced query chunk: decode the pinned
    virtual-offset range into host predicate columns for the mesh
    overlap filter (query/engine.py)."""
    return PlanIR(
        source=SourceIR(path, kind, role="chunk"),
        spans=SpansIR.pin([(path, start_voffset, end_voffset)]),
        ops=(op_node("chunk_decode"),),
        sink=SinkIR.of("chunk_columns"))


def query_region_plan(path: str, kind: str, region: str,
                      chunks) -> PlanIR:
    """A whole region query (the ``hbam explain query`` surface): every
    coalesced chunk the index resolved for ``region``, pinned."""
    return PlanIR(
        source=SourceIR(path, kind, role="chunk"),
        spans=SpansIR.pin([(path, s, e) for s, e in chunks]),
        ops=(op_node("chunk_decode"),
             op_node("overlap_filter", region=region)),
        sink=SinkIR.of("chunk_columns"))


def mkdup_plan(input_path: str, output_path: str,
               config: Optional[HBamConfig] = None, *,
               remove_duplicates: bool = False,
               library_from: str = "none") -> PlanIR:
    """The fused preprocessing pipeline (prep/): decode -> mesh sort
    exchange -> duplicate marking -> flag-patched indexed write, as ONE
    plan — records never re-inflate between the ops.

    The output-affecting markdup options ride the op node (they are
    part of the plan digest the journal refuses to resume across);
    the output path is the sink's identity."""
    return PlanIR(
        source=SourceIR(input_path, "bam"),
        spans=SpansIR.auto(span_bytes=PAYLOAD_SPAN_BYTES),
        ops=(op_node("sort_exchange"),
             op_node("markdup",
                     remove_duplicates=bool(remove_duplicates),
                     library_from=library_from),
             op_node("flag_patch_write")),
        sink=SinkIR.of("bam_file", path=os.path.abspath(output_path)))


def cohort_plan(manifest, config: Optional[HBamConfig] = None,
                geometry=None) -> PlanIR:
    """Cohort tensor batches: k single-sample call sets k-way
    position-joined, allele-harmonized, packed into
    [variants, samples] dosage/qual mesh tiles.

    The plan digest covers the manifest IDENTITY (anchor + per-input
    file identity digest) plus the JOIN-affecting knobs — exactly what
    the journaled join's refuse-to-resume contract needs
    (``jobs.runner.plan_journal_params``).  Feed-only geometry
    (tile_records) is deliberately NOT part of the identity: the
    journaled chunk artifacts are cut by chunk_sites and shaped by
    samples_pad, and a changed mesh-feed tile height replays them
    byte-identically."""
    from hadoop_bam_tpu.cohort.manifest import as_manifest

    cfg = config if config is not None else DEFAULT_CONFIG
    m = as_manifest(manifest)
    anchor, k, digest = m.identity()
    if geometry is None:
        from hadoop_bam_tpu.parallel.variant_pipeline import (
            VariantGeometry,
        )
        geometry = VariantGeometry(n_samples=k)
    return PlanIR(
        source=SourceIR(anchor or "<inline-manifest>", "cohort",
                        role="join"),
        spans=SpansIR.auto(),
        ops=(op_node("kway_join", samples=k, manifest_digest=digest,
                     chunk_sites=cfg.cohort_chunk_sites),
             op_node("variant_pack",
                     samples_pad=geometry.samples_pad)),
        sink=SinkIR.of("tensor_batches"))
