"""Genomic interval filtering for BAM reads.

Rebuild of the reference's ``hadoopbam.bam.intervals`` support
(hb/BAMInputFormat.java, upstream 7.7+ [VER?]): a job restricted to a set of
``chr:start-end`` intervals only surfaces records whose alignment span
overlaps one of them.  The reference trims InputSplits via the BAI linear
index and filters records in the reader; we filter record-aligned spans at
batch granularity with vectorized overlap tests (pos + CIGAR reference span),
which yields the same record set.

Interval grammar (samtools-style, 1-based inclusive):
``chr`` (whole contig), ``chr:start``, ``chr:start-``, ``chr:start-end``;
multiple intervals comma-separated.
"""
from __future__ import annotations

import re
from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from hadoop_bam_tpu.formats.bam import BamBatch, SAMHeader

_MAX_POS = (1 << 31) - 1


class IntervalError(ValueError):
    pass


@dataclass(frozen=True)
class Interval:
    rname: str
    start: int = 1            # 1-based inclusive
    end: int = _MAX_POS       # 1-based inclusive

    def __str__(self) -> str:
        return f"{self.rname}:{self.start}-{self.end}"


_INTERVAL_RE = re.compile(
    r"^(?P<chr>[^:]+?)(?::(?P<start>[\d,]+)(?P<dash>-(?P<end>[\d,]+)?)?)?$")


def parse_interval(text: str) -> Interval:
    m = _INTERVAL_RE.match(text.strip())
    if not m:
        raise IntervalError(f"cannot parse interval {text!r}")
    start = int(m.group("start").replace(",", "")) if m.group("start") else 1
    if m.group("end"):
        end = int(m.group("end").replace(",", ""))
    elif m.group("start") and not m.group("dash"):
        end = start       # "chr:pos" is a single position
    else:
        end = _MAX_POS
    if start < 1 or end < start:
        raise IntervalError(f"bad interval bounds in {text!r}")
    return Interval(m.group("chr"), start, end)


def resolve_interval(text: str,
                     ref_names: Optional[Sequence[str]] = None
                     ) -> Interval:
    """One region with samtools-style resolution against a reference
    dictionary: a verbatim contig name is a whole-contig interval even
    when it contains ':' (GRCh38 ALT/HLA names); otherwise the LONGEST
    known contig name followed by ':range' wins; otherwise the plain
    chr:start-end grammar applies."""
    t = text.strip()
    known = set(ref_names or ())
    if t in known:
        return Interval(t)
    if known and ":" in t:
        best = None
        for n in known:
            if t.startswith(n + ":") and (best is None
                                          or len(n) > len(best)):
                best = n
        if best is not None:
            try:
                rng = parse_interval("x:" + t[len(best) + 1:])
            except IntervalError as e:
                # re-raise naming the user's region, not the synthetic
                # "x:"-prefixed range used for parsing; keep the specific
                # cause (bad syntax vs bad bounds)
                raise IntervalError(
                    f"bad range in interval {t!r} (contig {best!r}): "
                    + str(e).replace(repr("x:" + t[len(best) + 1:]),
                                     "range")) from None
            return Interval(best, rng.start, rng.end)
    return parse_interval(t)


def parse_intervals(text: str,
                    ref_names: Optional[Sequence[str]] = None
                    ) -> List[Interval]:
    """Parse a comma-separated interval list.  When ``ref_names`` is given,
    samtools-style resolution applies: a piece that matches a contig name
    verbatim is a whole-contig interval even if it contains ':' (GRCh38
    ALT/HLA contigs like "HLA-A*01:01" would otherwise misparse)."""
    known = set(ref_names) if ref_names else ()
    out = []
    for t in text.split(","):
        t = t.strip()
        if not t:
            continue
        if t in known:
            out.append(Interval(t))
        else:
            out.append(parse_interval(t))
    return out


def batch_overlap_mask(batch: BamBatch, intervals: Sequence[Interval],
                       header: Optional[SAMHeader] = None) -> np.ndarray:
    """Boolean row mask: does each record's reference span overlap any
    interval?  Fully vectorized; CIGAR spans are computed once per batch."""
    header = header or batch.header
    if header is None:
        raise IntervalError("interval filtering needs a header to resolve "
                            "reference names")
    rid_of = {n: i for i, n in enumerate(header.ref_names)}
    mask = np.zeros(len(batch), dtype=bool)
    if not len(batch):
        return mask
    pos1 = batch.pos.astype(np.int64) + 1          # [SPEC] BAM pos is 0-based
    end1 = pos1 + np.maximum(batch.reference_span(), 1) - 1
    refid = batch.refid
    for iv in intervals:
        rid = rid_of.get(iv.rname)
        if rid is None:
            raise IntervalError(
                f"interval contig {iv.rname!r} is not in the header "
                f"reference dictionary")
        mask |= (refid == rid) & (pos1 <= iv.end) & (end1 >= iv.start)
    return mask


def filter_batch(batch: BamBatch, intervals: Sequence[Interval],
                 header: Optional[SAMHeader] = None) -> BamBatch:
    return batch.select(np.nonzero(
        batch_overlap_mask(batch, intervals, header))[0])
