"""BAI genomic index: build, read, and query for interval split trimming.

The reference's interval support (hb/BAMInputFormat.java, upstream 7.7+)
trims InputSplits with the BAM's `.bai` sidecar so only file regions that
can contain overlapping records are read; records are then filtered
exactly in the reader.  This module is both halves without htsjdk: a BAI
builder (we have no external indexer in this environment) and a reader +
query that turns intervals into merged virtual-offset ranges.

Format [SPEC SAMv1 section 5.2]: magic "BAI\\1"; per reference a binning
index (R-tree bins over 16 KiB..512 Mbp regions, each bin holding chunks
of (begin, end) virtual offsets) plus a linear index of the smallest
virtual offset overlapping each 16 KiB window.  Bin numbering follows the
standard reg2bin/reg2bins arithmetic reproduced here.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

BAI_MAGIC = b"BAI\x01"
BAI_SUFFIX = ".bai"
_LINEAR_SHIFT = 14          # 16 KiB windows
_METADATA_BIN = 37450       # pseudo-bin some writers emit; skipped on read


def reg2bin(beg: int, end: int) -> int:
    """Bin for a 0-based half-open region [SPEC section 5.3 C code]."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


def reg2bins(beg: int, end: int) -> List[int]:
    """All bins that may hold records overlapping [beg, end) [SPEC]."""
    end -= 1
    out = [0]
    for shift, off in ((26, 1), (23, 9), (20, 73), (17, 585), (14, 4681)):
        out.extend(range(off + (beg >> shift), off + (end >> shift) + 1))
    return out


@dataclass
class RefIndex:
    bins: Dict[int, List[Tuple[int, int]]] = field(default_factory=dict)
    linear: List[int] = field(default_factory=list)  # voffsets, 0 = unset


@dataclass
class BaiIndex:
    refs: List[RefIndex]

    # -- serialization ------------------------------------------------------
    def to_bytes(self) -> bytes:
        out = [BAI_MAGIC, struct.pack("<i", len(self.refs))]
        for ref in self.refs:
            out.append(struct.pack("<i", len(ref.bins)))
            for bin_no in sorted(ref.bins):
                chunks = ref.bins[bin_no]
                out.append(struct.pack("<Ii", bin_no, len(chunks)))
                for beg, end in chunks:
                    out.append(struct.pack("<QQ", beg, end))
            out.append(struct.pack("<i", len(ref.linear)))
            for v in ref.linear:
                out.append(struct.pack("<Q", v))
        return b"".join(out)

    @classmethod
    def from_bytes(cls, raw: bytes) -> "BaiIndex":
        if raw[:4] != BAI_MAGIC:
            raise ValueError("not a BAI index (bad magic)")
        off = 4
        (n_ref,) = struct.unpack_from("<i", raw, off)
        off += 4
        refs: List[RefIndex] = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", raw, off)
            off += 4
            bins: Dict[int, List[Tuple[int, int]]] = {}
            for _ in range(n_bin):
                bin_no, n_chunk = struct.unpack_from("<Ii", raw, off)
                off += 8
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", raw, off)
                    off += 16
                    chunks.append((beg, end))
                if bin_no != _METADATA_BIN:
                    bins[bin_no] = chunks
            (n_intv,) = struct.unpack_from("<i", raw, off)
            off += 4
            linear = list(struct.unpack_from(f"<{n_intv}Q", raw, off))
            off += 8 * n_intv
            refs.append(RefIndex(bins=bins, linear=linear))
        return cls(refs=refs)

    # -- query --------------------------------------------------------------
    def query(self, rid: int, beg: int, end: int) -> List[Tuple[int, int]]:
        """Merged (start, end) virtual-offset ranges that can contain
        records overlapping the 0-based half-open region [beg, end)."""
        if rid < 0 or rid >= len(self.refs):
            return []
        ref = self.refs[rid]
        win = beg >> _LINEAR_SHIFT
        min_off = ref.linear[win] if win < len(ref.linear) else 0
        chunks: List[Tuple[int, int]] = []
        for bin_no in reg2bins(beg, end):
            for cbeg, cend in ref.bins.get(bin_no, ()):
                if cend > min_off:
                    chunks.append((max(cbeg, min_off), cend))
        chunks.sort()
        merged: List[Tuple[int, int]] = []
        for cbeg, cend in chunks:
            if merged and cbeg <= merged[-1][1]:
                if cend > merged[-1][1]:
                    merged[-1] = (merged[-1][0], cend)
            else:
                merged.append((cbeg, cend))
        return merged


CSI_MAGIC = b"CSI\x01"
CSI_SUFFIX = ".csi"


def csi_reg2bins(beg: int, end: int, min_shift: int, depth: int
                 ) -> List[int]:
    """Bins possibly overlapping [beg, end) for a CSI index with the given
    geometry [SPEC CSIv1] — the generalized reg2bins."""
    out: List[int] = []
    end -= 1
    s = min_shift + depth * 3
    t = 0
    for level in range(depth + 1):
        b = t + (beg >> s)
        e = t + (end >> s)
        out.extend(range(b, e + 1))
        s -= 3
        t += 1 << (level * 3)
    return out


@dataclass
class CsiIndex:
    """CSI (.csi) sidecar: BAI generalized to configurable bin geometry,
    stored BGZF-compressed.  Read/write + the same query contract as
    BaiIndex; per-bin ``loffset`` replaces the 16 KiB linear index."""
    min_shift: int
    depth: int
    refs: List[Dict[int, Tuple[int, List[Tuple[int, int]]]]]
    # refs[rid]: bin -> (loffset, chunks)

    def to_bytes(self) -> bytes:
        body = [CSI_MAGIC,
                struct.pack("<iii", self.min_shift, self.depth, 0),
                struct.pack("<i", len(self.refs))]
        for bins in self.refs:
            body.append(struct.pack("<i", len(bins)))
            for bin_no in sorted(bins):
                loffset, chunks = bins[bin_no]
                body.append(struct.pack("<IQi", bin_no, loffset,
                                        len(chunks)))
                for beg, end in chunks:
                    body.append(struct.pack("<QQ", beg, end))
        from hadoop_bam_tpu.formats import bgzf
        return bgzf.compress_bytes(b"".join(body))

    @classmethod
    def from_bytes(cls, raw: bytes) -> "CsiIndex":
        from hadoop_bam_tpu.formats import bgzf
        if raw[:2] == b"\x1f\x8b":
            raw = bgzf.decompress_bytes(raw)
        if raw[:4] != CSI_MAGIC:
            raise ValueError("not a CSI index (bad magic)")
        min_shift, depth, l_aux = struct.unpack_from("<iii", raw, 4)
        off = 16 + l_aux
        (n_ref,) = struct.unpack_from("<i", raw, off)
        off += 4
        refs = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", raw, off)
            off += 4
            bins: Dict[int, Tuple[int, List[Tuple[int, int]]]] = {}
            for _ in range(n_bin):
                bin_no, loffset, n_chunk = struct.unpack_from("<IQi", raw,
                                                              off)
                off += 16
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", raw, off)
                    off += 16
                    chunks.append((beg, end))
                if bin_no != _METADATA_BIN:
                    bins[bin_no] = (loffset, chunks)
            refs.append(bins)
        return cls(min_shift=min_shift, depth=depth, refs=refs)

    def _min_offset(self, bins, beg: int) -> int:
        """Smallest virtual offset that can hold records overlapping
        positions >= ``beg``: the loffset of the nearest present bin at or
        before beg, walking previous-sibling-then-parent from the leaf bin
        (the CSI analog of BAI's linear-index pruning)."""
        bin_no = ((1 << (3 * self.depth)) - 1) // 7 + \
            (beg >> self.min_shift)
        while bin_no:
            entry = bins.get(bin_no)
            if entry is not None:
                return entry[0]
            first_sibling = (((bin_no - 1) >> 3) << 3) + 1
            bin_no = bin_no - 1 if bin_no > first_sibling \
                else (bin_no - 1) >> 3
        entry = bins.get(0)
        return entry[0] if entry is not None else 0

    def query(self, rid: int, beg: int, end: int) -> List[Tuple[int, int]]:
        if rid < 0 or rid >= len(self.refs):
            return []
        bins = self.refs[rid]
        min_off = self._min_offset(bins, beg)
        chunks: List[Tuple[int, int]] = []
        for bin_no in csi_reg2bins(beg, end, self.min_shift, self.depth):
            entry = bins.get(bin_no)
            if entry is None:
                continue
            _loffset, bin_chunks = entry
            for cbeg, cend in bin_chunks:
                if cend > min_off:
                    chunks.append((max(cbeg, min_off), cend))
        chunks.sort()
        merged: List[Tuple[int, int]] = []
        for cbeg, cend in chunks:
            if merged and cbeg <= merged[-1][1]:
                if cend > merged[-1][1]:
                    merged[-1] = (merged[-1][0], cend)
            else:
                merged.append((cbeg, cend))
        return merged

    @classmethod
    def from_bai(cls, bai: "BaiIndex", min_shift: int = 14,
                 depth: int = 5) -> "CsiIndex":
        """Re-express a BAI as CSI (same 16 KiB / depth-5 geometry —
        BAI bin numbers are exactly CSI bins at these parameters)."""
        refs = []
        for ref in bai.refs:
            bins: Dict[int, Tuple[int, List[Tuple[int, int]]]] = {}
            for bin_no, chunks in ref.bins.items():
                # loffset must lower-bound the start of ANY record
                # overlapping the bin's region — records assigned to
                # ancestor bins included.  The BAI linear index has
                # exactly that for the bin's first 16 KiB window; the
                # bin's own min chunk start alone could overestimate.
                level = 0
                while level < depth and \
                        ((1 << (3 * (level + 1))) - 1) // 7 <= bin_no:
                    level += 1
                region_start = (bin_no - ((1 << (3 * level)) - 1) // 7) \
                    << (min_shift + 3 * (depth - level))
                win = region_start >> _LINEAR_SHIFT
                lin = ref.linear[win] if win < len(ref.linear) else 0
                # lin == 0 (window unset) stays 0: "no pruning" is the
                # only safe fallback — the bin's own min chunk start can
                # exceed the start of an ancestor-bin record overlapping
                # this bin's region
                bins[bin_no] = (lin, list(chunks))
            refs.append(bins)
        return cls(min_shift=min_shift, depth=depth, refs=refs)


class IncrementalBinningCore:
    """Shared chunk/linear machinery of ``BAIBuilder`` and
    ``split/tabix.TabixBuilder`` — BAI and tabix use the same 14/5 bin
    arithmetic, the same deferred chunk ends, and the same 16 KiB
    linear index, so the logic lives ONCE here (the PR-8 chunk-end bug
    lived in exactly this code; two hand-synced copies would let the
    index families silently diverge on the next fix).

    Subclasses own ``self.refs`` (a list of ``RefIndex``) and call
    ``_observe`` per mapped record after their own rid resolution.

    Chunk ENDS are deferred: record i's chunk closes at record i+1's
    start voffset (or at ``finalize``'s end voffset for the last
    record), so every stored end carries a real block-boundary coffset.
    The old fallback packed (coffset+1, 0), one BYTE past the block
    start: BGZFReader-based chunk reads tolerated that by accident, but
    block-table consumers (plan_interval_spans -> coverage's
    _fetch_span_raw) need end coffsets on real block boundaries and
    died mid-block with "truncated BGZF header".
    """

    refs: List[RefIndex]

    def __init__(self):
        self._pending: Optional[Tuple[int, int, int]] = None

    def _close(self, v1: int) -> None:
        if self._pending is None:
            return
        rid, b, v0 = self._pending
        self._pending = None
        chunks = self.refs[rid].bins.setdefault(b, [])
        if chunks and chunks[-1][1] >= v0:          # adjacent: extend
            chunks[-1] = (chunks[-1][0], v1)
        else:
            chunks.append((v0, v1))

    def _observe(self, rid: int, beg: int, end: int, voffset: int) -> None:
        """Record one mapped observation: open its (deferred-end) chunk
        and fold it into the linear index."""
        ref = self.refs[rid]
        self._pending = (rid, reg2bin(beg, end), voffset)
        w0 = beg >> _LINEAR_SHIFT
        w1 = max(end - 1, beg) >> _LINEAR_SHIFT
        if len(ref.linear) <= w1:
            ref.linear.extend([0] * (w1 + 1 - len(ref.linear)))
        for w in range(w0, w1 + 1):
            if ref.linear[w] == 0 or voffset < ref.linear[w]:
                ref.linear[w] = voffset


class BAIBuilder(IncrementalBinningCore):
    """Incremental BAI construction: one ``add`` per coordinate-sorted
    record, ``finalize`` closes the trailing chunk — the reusable core
    behind both the whole-file ``build_bai`` rescan and the write path's
    index-during-write sink (``write/indexing.IndexingSink``), which
    cannot afford a second pass over the file it just produced.
    """

    def __init__(self, n_ref: int):
        super().__init__()
        self.refs = [RefIndex() for _ in range(n_ref)]

    def add(self, rid: int, beg: int, end: int, voffset: int) -> None:
        """Observe one record: 0-based half-open [beg, end) on reference
        ``rid`` (negative = unmapped, indexed only as a chunk closer),
        starting at packed virtual offset ``voffset``."""
        self._close(voffset)
        if rid < 0:
            return
        self._observe(rid, beg, end, voffset)

    def finalize(self, end_voffset: int) -> BaiIndex:
        """Close the trailing chunk at ``end_voffset`` (end-of-data
        position — block-aligned by construction) and return the index."""
        self._close(end_voffset)
        return BaiIndex(refs=self.refs)


def _reg2bin_vec(beg: np.ndarray, end: np.ndarray) -> np.ndarray:
    """Vectorized ``reg2bin`` over int64 column arrays."""
    e = end - 1
    return np.select(
        [beg >> 14 == e >> 14, beg >> 17 == e >> 17,
         beg >> 20 == e >> 20, beg >> 23 == e >> 23,
         beg >> 26 == e >> 26],
        [4681 + (beg >> 14), 585 + (beg >> 17), 73 + (beg >> 20),
         9 + (beg >> 23), 1 + (beg >> 26)],
        default=0)


def bai_from_columns(n_ref: int, refid: np.ndarray, beg: np.ndarray,
                     end: np.ndarray, voffsets: np.ndarray,
                     end_voffset: int) -> BaiIndex:
    """Vectorized twin of feeding the same file-ordered columns through
    ``BAIBuilder.add`` row by row (bit-identical output; the fuzz test
    pins it).  The write path's indexing sink already holds these
    columns, and a per-record Python loop over 10^8 records would put
    minutes of interpreter time on the critical path between the pooled
    deflate and publication — here bins come from one ``np.select``,
    chunks from same-(rid,bin) run detection, and the linear index from
    ``np.minimum.at`` per window stride.
    """
    refid = np.asarray(refid, np.int64)
    beg = np.asarray(beg, np.int64)
    end = np.asarray(end, np.int64)
    voffs = np.asarray(voffsets, np.uint64)
    n = refid.size
    refs = [RefIndex() for _ in range(n_ref)]
    if not n:
        return BaiIndex(refs=refs)

    mapped = refid >= 0
    bins = _reg2bin_vec(beg, end)
    # record i's chunk closes at record i+1's start (see the core's
    # deferred-end note); the last closes at end_voffset
    cend = np.empty(n, np.uint64)
    cend[:-1] = voffs[1:]
    cend[-1] = np.uint64(end_voffset)

    # a chunk extends exactly over a run of CONSECUTIVE mapped records
    # sharing (rid, bin): any break (bin change, ref change, unmapped
    # record between) moves the next start voffset past the closed
    # chunk's end, so the serial builder never merges across it
    prev_mapped = np.empty(n, bool)
    prev_mapped[0] = False
    prev_mapped[1:] = mapped[:-1]
    same = np.zeros(n, bool)
    same[1:] = (refid[1:] == refid[:-1]) & (bins[1:] == bins[:-1])
    new_run = mapped & ~(same & prev_mapped)

    midx = np.flatnonzero(mapped)
    run_of = np.cumsum(new_run)[midx] - 1        # run id per mapped row
    n_runs = int(run_of[-1]) + 1 if midx.size else 0
    run_ids = np.arange(n_runs)
    first = midx[np.searchsorted(run_of, run_ids, side="left")]
    last = midx[np.searchsorted(run_of, run_ids, side="right") - 1]
    run_rid = refid[first]
    run_bin = bins[first]
    run_v0 = voffs[first]
    run_v1 = cend[last]
    for k in range(n_runs):
        refs[int(run_rid[k])].bins.setdefault(int(run_bin[k]), []).append(
            (int(run_v0[k]), int(run_v1[k])))

    unset = np.uint64(0xFFFFFFFFFFFFFFFF)
    for rid in np.unique(refid[mapped]):
        m = mapped & (refid == rid)
        w0 = beg[m] >> _LINEAR_SHIFT
        w1 = np.maximum(end[m] - 1, beg[m]) >> _LINEAR_SHIFT
        lin = np.full(int(w1.max()) + 1, unset, np.uint64)
        v = voffs[m]
        span = w1 - w0
        for k in range(int(span.max()) + 1):
            sel = span >= k
            np.minimum.at(lin, w0[sel] + k, v[sel])
        lin[lin == unset] = 0
        refs[int(rid)].linear = [int(x) for x in lin]
    return BaiIndex(refs=refs)


def build_bai(bam_path: str, header=None) -> BaiIndex:
    """Build a BAI from a coordinate-sorted BAM in one streaming pass
    (the htsjdk/samtools `index` equivalent) — a thin wrapper over the
    incremental ``BAIBuilder``; bins and reference spans come from
    vectorized batch columns.  Spans are record-aligned and contiguous,
    so the builder's next-record chunk ends coincide with the per-span
    end voffsets the pre-builder implementation used."""
    from hadoop_bam_tpu.api.dataset import open_bam

    ds = open_bam(bam_path)
    header = header or ds.header
    builder = BAIBuilder(len(header.ref_names))
    end_v = 0

    for span in ds.spans():
        from hadoop_bam_tpu.split.planners import read_bam_span
        batch = read_bam_span(bam_path, span, header=header)
        end_v = (int(span.end[0]) << 16) | int(span.end[1])
        n = len(batch)
        if not n:
            continue
        voffs = batch.voffsets
        if voffs is None:
            raise ValueError("BAI build needs record voffsets from the "
                             "span reader")
        refid = batch.refid
        pos = batch.pos.astype(np.int64)            # 0-based
        span_len = np.maximum(batch.reference_span(), 1).astype(np.int64)
        end = pos + span_len                        # half-open
        for i in range(n):
            builder.add(int(refid[i]), int(pos[i]), int(end[i]),
                        int(voffs[i]))
    return builder.finalize(end_v)


def write_bai(bam_path: str, out_path: Optional[str] = None) -> str:
    out_path = out_path or bam_path + BAI_SUFFIX
    idx = build_bai(bam_path)
    with open(out_path, "wb") as f:
        f.write(idx.to_bytes())
    return out_path


def load_bai_for(bam_path: str):
    """Load a genomic index sidecar: .bai preferred, .csi fallback (both
    answer the same query contract)."""
    import os
    p = bam_path + BAI_SUFFIX
    if os.path.exists(p):
        return BaiIndex.from_bytes(open(p, "rb").read())
    p = bam_path + CSI_SUFFIX
    if os.path.exists(p):
        return CsiIndex.from_bytes(open(p, "rb").read())
    return None


def plan_interval_spans(bam_path: str, intervals, header,
                        bai: Optional[BaiIndex] = None):
    """Interval list -> record-region FileVirtualSpans via the BAI (the
    reference's split-trimming).  Callers still row-filter for exactness;
    this only bounds what gets read and inflated."""
    from hadoop_bam_tpu.split.spans import FileVirtualSpan

    bai = bai or load_bai_for(bam_path)
    if bai is None:
        return None
    rid_of = {n: i for i, n in enumerate(header.ref_names)}
    ranges: List[Tuple[int, int]] = []
    for iv in intervals:
        rid = rid_of.get(iv.rname)
        if rid is None:
            continue
        beg0 = max(iv.start - 1, 0)
        end0 = iv.end
        ranges.extend(bai.query(rid, beg0, end0))
    ranges.sort()
    merged: List[Tuple[int, int]] = []
    for beg, end in ranges:
        if merged and beg <= merged[-1][1]:
            if end > merged[-1][1]:
                merged[-1] = (merged[-1][0], end)
        else:
            merged.append((beg, end))
    return [FileVirtualSpan(bam_path, beg, end) for beg, end in merged]
