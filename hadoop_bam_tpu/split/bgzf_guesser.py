"""BGZF split guesser: find the next BGZF block start from an arbitrary offset.

Rebuild of hb/BGZFSplitGuesser.java.  Semantics [SPEC + SURVEY.md 2.2]: scan
forward from the given offset for the gzip magic ``1f 8b 08 04``, require the
FEXTRA BC subfield (SI1=66, SI2=67, SLEN=2) carrying BSIZE, and *confirm* the
candidate by inflating the block (a magic match inside compressed data is
common; a clean inflate with matching ISIZE at a consistent chain position is
not).  The scan window is bounded: a true block start must appear within
MAX_BLOCK_SIZE bytes of any offset inside a valid BGZF stream, so we scan a
couple of windows and give up (returns None) beyond that.

Design shift vs the reference: the byte scan is a *vectorized* NumPy pass over
the whole window (formats/bgzf.find_block_starts_numpy) instead of a per-byte
loop, and confirmation inflates at most a handful of surviving candidates.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.utils.seekable import ByteSource, as_byte_source


class BGZFSplitGuesser:

    # One max-size block guarantees a start in-window; use 2 for slack against
    # candidates that fail confirmation near the window edge.
    WINDOW = 2 * bgzf.MAX_BLOCK_SIZE

    def __init__(self, source, confirm_blocks: int = 2):
        self._src: ByteSource = as_byte_source(source)
        # how many consecutive blocks must parse+inflate to accept a candidate
        self._confirm_blocks = confirm_blocks

    def guess_next_block_start(self, offset: int) -> Optional[int]:
        """Smallest confirmed BGZF block start >= offset, or None."""
        end = self._src.size
        if offset >= end:
            return None
        window_off = offset
        # scan up to 2 windows (block starts must occur within one max block)
        for _ in range(2):
            win = self._src.pread(window_off, self.WINDOW + bgzf.HEADER_SIZE)
            arr = np.frombuffer(win, dtype=np.uint8)
            for cand in bgzf.find_block_starts_numpy(arr):
                abs_off = window_off + int(cand)
                if abs_off < offset:
                    continue
                if self._confirm(abs_off):
                    return abs_off
            if window_off + len(win) >= end:
                return None
            window_off += self.WINDOW
        return None

    def _confirm(self, coffset: int) -> bool:
        """Inflate up to confirm_blocks consecutive blocks starting here."""
        for _ in range(self._confirm_blocks):
            head = self._src.pread(coffset, bgzf.MAX_BLOCK_SIZE)
            if not head:
                return True  # chain ran off EOF cleanly
            try:
                info = bgzf.parse_block_header(head, 0)
                bgzf.inflate_block(head, info, check_crc=True)
            except bgzf.BGZFError:
                return False
            coffset += info.block_size
            if coffset == self._src.size:
                return True
            if coffset > self._src.size:
                return False
        return True
