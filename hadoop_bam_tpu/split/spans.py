"""FileVirtualSpan — the unit of distributable work.

Rebuild of hb/FileVirtualSplit.java: a Hadoop ``InputSplit`` subclass carrying
(path, start virtual offset, end virtual offset, hosts).  Ours is a plain
dataclass with a compact dict/JSON form so the multi-host planner can compute
spans once (host 0) and broadcast them (SURVEY.md section 2.9); "locations"
generalize HDFS block hosts to an optional host/device placement hint.

A span is *self-describing*: any host can decode any span independently, which
is also the failure-recovery mechanism (SURVEY.md section 5) — retry is simply
re-decoding the span, exactly as MapReduce re-runs a map task.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from hadoop_bam_tpu.formats.virtual_offset import split_voffset


@dataclass(frozen=True)
class FileVirtualSpan:
    path: str
    start_voffset: int  # packed (coffset << 16 | uoffset), inclusive
    end_voffset: int    # exclusive
    locations: Tuple[str, ...] = ()

    @property
    def start(self) -> Tuple[int, int]:
        return tuple(int(x) for x in split_voffset(self.start_voffset))

    @property
    def end(self) -> Tuple[int, int]:
        return tuple(int(x) for x in split_voffset(self.end_voffset))

    @property
    def compressed_size(self) -> int:
        """Approximate compressed byte extent (for load balancing)."""
        return max(0, self.end[0] - self.start[0])

    def to_dict(self) -> dict:
        return {"path": self.path, "start": int(self.start_voffset),
                "end": int(self.end_voffset), "locations": list(self.locations)}

    @classmethod
    def from_dict(cls, d: dict) -> "FileVirtualSpan":
        return cls(d["path"], int(d["start"]), int(d["end"]),
                   tuple(d.get("locations", ())))


@dataclass(frozen=True)
class FileByteSpan:
    """A plain byte-range split (text formats: SAM, VCF, FASTQ, QSEQ, FASTA) —
    the analog of Hadoop ``FileSplit`` before virtual-offset conversion."""
    path: str
    start: int
    end: int
    locations: Tuple[str, ...] = ()

    def to_dict(self) -> dict:
        return {"path": self.path, "start": self.start, "end": self.end,
                "locations": list(self.locations)}

    @classmethod
    def from_dict(cls, d: dict) -> "FileByteSpan":
        return cls(d["path"], int(d["start"]), int(d["end"]),
                   tuple(d.get("locations", ())))
