"""Tabix (.tbi) index: build, read, query — random access into BGZF VCF.

The reference had no VCF interval machinery (hb/VCFRecordReader.java scans
whole splits); this extends the BAI-style binning scheme to BGZF text
(hts-specs Tabix paper format): same 14/5 bin arithmetic and 16 KiB linear
index as BAI, wrapped BGZF-compressed, plus the text-format config block
(sequence/begin/end columns, comment char) and the reference-name
dictionary.  `VcfDataset.query()` uses it to read only the file regions
that can contain overlapping variants.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Tuple

from hadoop_bam_tpu.split.bai import (
    IncrementalBinningCore, RefIndex, _LINEAR_SHIFT, _METADATA_BIN,
    reg2bins,
)

TBI_MAGIC = b"TBI\x01"
TBI_SUFFIX = ".tbi"
TBX_VCF = 2                      # preset: VCF (seq col 1, begin col 2)


@dataclass
class TabixIndex:
    names: List[str]
    refs: List[RefIndex]
    fmt: int = TBX_VCF
    col_seq: int = 1
    col_beg: int = 2
    col_end: int = 0
    meta_char: int = ord("#")
    skip: int = 0

    def to_bytes(self) -> bytes:
        nm = b"".join(n.encode() + b"\x00" for n in self.names)
        out = [TBI_MAGIC,
               struct.pack("<8i", len(self.refs), self.fmt, self.col_seq,
                           self.col_beg, self.col_end, self.meta_char,
                           self.skip, len(nm)), nm]
        for ref in self.refs:
            out.append(struct.pack("<i", len(ref.bins)))
            for bin_no in sorted(ref.bins):
                chunks = ref.bins[bin_no]
                out.append(struct.pack("<Ii", bin_no, len(chunks)))
                for beg, end in chunks:
                    out.append(struct.pack("<QQ", beg, end))
            out.append(struct.pack("<i", len(ref.linear)))
            for v in ref.linear:
                out.append(struct.pack("<Q", v))
        from hadoop_bam_tpu.formats import bgzf
        return bgzf.compress_bytes(b"".join(out))

    @classmethod
    def from_bytes(cls, raw: bytes) -> "TabixIndex":
        from hadoop_bam_tpu.formats import bgzf
        if raw[:2] == b"\x1f\x8b":
            raw = bgzf.decompress_bytes(raw)
        if raw[:4] != TBI_MAGIC:
            raise ValueError("not a tabix index (bad magic)")
        (n_ref, fmt, col_seq, col_beg, col_end, meta, skip,
         l_nm) = struct.unpack_from("<8i", raw, 4)
        off = 36
        names = [n.decode() for n in raw[off:off + l_nm].split(b"\x00")
                 if n]
        off += l_nm
        refs: List[RefIndex] = []
        for _ in range(n_ref):
            (n_bin,) = struct.unpack_from("<i", raw, off)
            off += 4
            bins: Dict[int, List[Tuple[int, int]]] = {}
            for _ in range(n_bin):
                bin_no, n_chunk = struct.unpack_from("<Ii", raw, off)
                off += 8
                chunks = []
                for _ in range(n_chunk):
                    beg, end = struct.unpack_from("<QQ", raw, off)
                    off += 16
                    chunks.append((beg, end))
                if bin_no != _METADATA_BIN:
                    bins[bin_no] = chunks
            (n_intv,) = struct.unpack_from("<i", raw, off)
            off += 4
            linear = list(struct.unpack_from(f"<{n_intv}Q", raw, off))
            off += 8 * n_intv
            refs.append(RefIndex(bins=bins, linear=linear))
        return cls(names=names, refs=refs, fmt=fmt, col_seq=col_seq,
                   col_beg=col_beg, col_end=col_end, meta_char=meta,
                   skip=skip)

    def query(self, rname: str, beg: int, end: int
              ) -> List[Tuple[int, int]]:
        """Merged (start, end) virtual-offset ranges for the 0-based
        half-open region [beg, end) on ``rname``."""
        try:
            rid = self.names.index(rname)
        except ValueError:
            return []
        ref = self.refs[rid]
        win = beg >> _LINEAR_SHIFT
        min_off = ref.linear[win] if win < len(ref.linear) else 0
        chunks: List[Tuple[int, int]] = []
        for bin_no in reg2bins(beg, end):
            for cbeg, cend in ref.bins.get(bin_no, ()):
                if cend > min_off:
                    chunks.append((max(cbeg, min_off), cend))
        chunks.sort()
        merged: List[Tuple[int, int]] = []
        for cbeg, cend in chunks:
            if merged and cbeg <= merged[-1][1]:
                if cend > merged[-1][1]:
                    merged[-1] = (merged[-1][0], cend)
            else:
                merged.append((cbeg, cend))
        return merged


class TabixBuilder(IncrementalBinningCore):
    """Incremental tabix construction — the text/BCF sibling of
    ``split/bai.BAIBuilder``: one ``add`` per coordinate-sorted record,
    ``finalize`` closes the trailing chunk.  Shared by the whole-file
    builders below and the write path's index-during-write sink
    (``write/indexing.IndexingSink``), which observes records as they
    are written instead of rescanning the output.  The chunk/linear
    machinery itself lives in ``IncrementalBinningCore``; this class
    only adds contig-name interning and the tabix format block."""

    def __init__(self, fmt: int = TBX_VCF, col_seq: int = 1,
                 col_beg: int = 2, col_end: int = 0,
                 meta_char: int = ord("#"), skip: int = 0):
        super().__init__()
        self.names: List[str] = []
        self.refs: List[RefIndex] = []
        self._rid_of: Dict[str, int] = {}
        self._fmt_args = dict(fmt=fmt, col_seq=col_seq, col_beg=col_beg,
                              col_end=col_end, meta_char=meta_char,
                              skip=skip)

    def add(self, rname: str, beg0: int, end0: int, voffset: int) -> None:
        """Observe one record: 0-based half-open [beg0, end0) on contig
        ``rname``, starting at packed virtual offset ``voffset``."""
        self._close(voffset)
        rid = self._rid_of.get(rname)
        if rid is None:
            rid = self._rid_of[rname] = len(self.names)
            self.names.append(rname)
            self.refs.append(RefIndex())
        self._observe(rid, beg0, end0, voffset)

    def finalize(self, end_voffset: int) -> TabixIndex:
        self._close(end_voffset)
        return TabixIndex(names=self.names, refs=self.refs,
                          **self._fmt_args)


def build_tabix(vcf_gz_path: str) -> TabixIndex:
    """Build a .tbi for a coordinate-sorted BGZF VCF in one streaming
    pass.  Line voffsets are tracked exactly by re-reading with a
    per-line reader (BGZFReader.read through line boundaries)."""
    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.utils.seekable import as_byte_source

    src = as_byte_source(vcf_gz_path)
    builder = TabixBuilder()
    try:
        r = bgzf.BGZFReader(src)

        def read_line() -> Tuple[int, bytes]:
            v0 = r.voffset()
            parts = []
            while True:
                b = r.read(1)
                if not b:
                    break
                if b == b"\n":
                    break
                parts.append(b)
            return v0, b"".join(parts)

        # NOTE: byte-at-a-time is acceptable for index BUILD (one-off,
        # host-side); queries never pay this cost.
        while True:
            v0, line = read_line()
            if not line:
                break
            if line[:1] == b"#":
                continue
            parts = line.split(b"\t", 8)
            rname = parts[0].decode()
            pos1 = int(parts[1])
            ref_allele = parts[3] if len(parts) > 3 else b"N"
            end1 = pos1 + max(len(ref_allele), 1) - 1
            # INFO END= extends deletions/SVs [VCF spec]
            if len(parts) > 7 and b"END=" in parts[7]:
                for item in parts[7].split(b";"):
                    if item.startswith(b"END="):
                        try:
                            end1 = max(end1, int(item[4:]))
                        except ValueError:
                            pass
                        break
            # the builder closes this record's chunk at the NEXT record's
            # v0 (== this line's end position: lines are contiguous), so
            # chunk ends equal the old explicit per-line v1 tracking
            builder.add(rname, pos1 - 1, end1, v0)
        final_v = r.voffset()
    finally:
        src.close()
    return builder.finalize(final_v)


def build_bcf_tabix(bcf_path: str) -> TabixIndex:
    """Build a tabix-shaped index over a coordinate-sorted BGZF BCF: the
    same bins/linear-index/voffset-chunk structure, keyed by each
    record's (CHROM, POS, rlen) from the binary codec instead of text
    columns.  Serves the query engine's BCF random access (htsjdk used
    CSI for BCF; the bin arithmetic is identical at 14/5 geometry)."""
    import struct as _struct

    from hadoop_bam_tpu.formats import bgzf
    from hadoop_bam_tpu.formats.bcf import BCFRecordCodec
    from hadoop_bam_tpu.formats.bcfio import read_bcf_header
    from hadoop_bam_tpu.utils.seekable import as_byte_source

    src = as_byte_source(bcf_path)
    try:
        header, first_voffset, is_bgzf = read_bcf_header(src)
        if not is_bgzf:
            from hadoop_bam_tpu.utils.errors import PlanError
            raise PlanError(
                f"{bcf_path} is a raw (non-BGZF) BCF — virtual-offset "
                f"indexing needs the BGZF container")
        codec = BCFRecordCodec(header)
        builder = TabixBuilder()
        r = bgzf.BGZFReader(src)
        r.seek_voffset(first_voffset)
        while True:
            v0 = r.voffset()
            head = r.read(8)
            if len(head) < 8:
                break
            l_shared, l_indiv = _struct.unpack("<II", head)
            body = r.read(l_shared + l_indiv)
            rec, _ = codec.decode(head + body, 0)
            beg0 = rec.pos - 1
            builder.add(rec.chrom, beg0, beg0 + max(rec.rlen, 1), v0)
        final_v = r.voffset()
    finally:
        src.close()
    return builder.finalize(final_v)


def write_tabix(path: str, out_path: Optional[str] = None) -> str:
    """Write a .tbi sidecar for a BGZF VCF (text build) or a BGZF BCF
    (binary build — build_bcf_tabix)."""
    out_path = out_path or path + TBI_SUFFIX
    idx = (build_bcf_tabix(path) if path.lower().endswith(".bcf")
           else build_tabix(path))
    with open(out_path, "wb") as f:
        f.write(idx.to_bytes())
    return out_path


def load_tabix_for(path: str) -> Optional[TabixIndex]:
    import os
    p = path + TBI_SUFFIX
    if not os.path.exists(p):
        return None
    return TabixIndex.from_bytes(open(p, "rb").read())
