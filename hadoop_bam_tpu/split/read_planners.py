"""Span planning + readers for read formats: FASTQ, QSEQ, FASTA.

Rebuild of the getSplits/RecordReader behavior of hb/FastqInputFormat.java,
hb/QseqInputFormat.java, hb/FastaInputFormat.java (SURVEY.md section 2.3):

- FASTQ: plain byte splits; record alignment at read time via the @/+ record
  heuristic (formats/fastq.find_fastq_record_start) — each record belongs to
  the span its first byte starts in.
- QSEQ: one record per line; LineRecordReader semantics
  (split/planners.read_text_span).
- FASTA: splits snapped to ``>`` sequence starts at plan time, so every span
  holds whole contigs and per-fragment positions are well-defined.
"""
from __future__ import annotations

from typing import List, Optional

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.fasta import find_sequence_start
from hadoop_bam_tpu.formats.fastq import (
    find_fastq_record_start, record_fully_visible,
)
from hadoop_bam_tpu.split.planners import plan_byte_ranges
from hadoop_bam_tpu.split.spans import FileByteSpan
from hadoop_bam_tpu.utils.seekable import as_byte_source, scoped_byte_source

_CHUNK = 1 << 20


def read_fastq_span(source, span: FileByteSpan) -> bytes:
    """Bytes of all FASTQ records *starting* in [span.start, span.end)."""
    with scoped_byte_source(source) as src:
        start, end = span.start, span.end
        size = src.size

        # Window from start-1 (line-start context) extended until it contains
        # a record start past `end` (the stop boundary) or EOF.
        lo = max(0, start - 1)
        buf = bytearray()
        fetch_pos = lo
        first_rel: Optional[int] = None
        stop_rel: Optional[int] = None
        while True:
            got = src.pread(fetch_pos, _CHUNK)
            buf += got
            fetch_pos += len(got)
            at_eof = fetch_pos >= size or not got
            if first_rel is None:
                cand = find_fastq_record_start(buf, start - lo)
                # trust a candidate only once its record is fully in view
                # (a truncated tail can validate a false start) — unless EOF
                if cand is not None and (at_eof
                                         or record_fully_visible(buf, cand)):
                    first_rel = cand
                elif not at_eof:
                    continue
            if first_rel is not None and fetch_pos >= end:
                stop_rel = find_fastq_record_start(buf,
                                                   max(end - lo, first_rel))
                if stop_rel is not None and not at_eof \
                        and not record_fully_visible(buf, stop_rel):
                    stop_rel = None
                    continue  # fetch more before trusting the stop boundary
                if stop_rel is not None or at_eof:
                    break
            if at_eof:
                break
        if first_rel is None or first_rel >= end - lo:
            return b""
        if stop_rel is None:
            out = bytes(buf[first_rel:])
            if not out.endswith(b"\n"):
                out += b"\n"
            return out
        return bytes(buf[first_rel:stop_rel])


def plan_fasta_spans(path: str, *, num_spans: Optional[int] = None,
                     span_bytes: Optional[int] = None,
                     config: HBamConfig = DEFAULT_CONFIG) -> List[FileByteSpan]:
    """Byte ranges snapped forward to ``>`` header-line starts."""
    src = as_byte_source(path)
    try:
        size = src.size
        ranges = plan_byte_ranges(size, num_spans=num_spans,
                                  span_bytes=span_bytes if span_bytes
                                  else (None if num_spans else config.split_size))
        bounds: List[int] = []
        for (bstart, _bend) in ranges:
            if bstart == 0:
                bounds.append(0)
                continue
            # scan forward for "\n>" (whole-file read windows)
            snapped = size
            pos = bstart
            while pos < size:
                win = src.pread(max(0, pos - 1), _CHUNK + 1)
                rel = find_sequence_start(win, pos - max(0, pos - 1))
                if rel is not None:
                    snapped = max(0, pos - 1) + rel
                    break
                pos += _CHUNK
            bounds.append(snapped)
        bounds.append(size)
        spans = []
        for i in range(len(bounds) - 1):
            s, e = bounds[i], bounds[i + 1]
            if s < e:
                spans.append(FileByteSpan(path, s, e))
        return spans
    finally:
        src.close()


def read_fasta_span(source, span: FileByteSpan) -> bytes:
    """Raw bytes of a sequence-aligned FASTA span (whole contigs)."""
    with scoped_byte_source(source) as src:
        out = bytearray()
        pos = span.start
        while pos < span.end:
            got = src.pread(pos, min(_CHUNK, span.end - pos))
            if not got:
                break
            out += got
            pos += len(got)
        return bytes(out)
