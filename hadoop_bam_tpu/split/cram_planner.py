"""CRAM split planning: container-boundary-aligned spans.

Rebuild of hb/CRAMInputFormat.java's ``getSplits``: the reference scans CRAM
container headers (htsjdk ``CramContainerIterator``) and snaps Hadoop's byte
splits to container starts, because containers are CRAM's independently
decodable unit (SURVEY.md sections 2.3 and 5 — the long-context analog: the
container grid is the parallelism axis).  Same idea here: one cheap header
scan yields every container offset; spans are container runs balanced by
compressed size.
"""
from __future__ import annotations

import struct
from typing import List, Optional, Tuple

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.cram import (
    CRAMError, FileDefinition, read_container, scan_container_offsets,
)
from hadoop_bam_tpu.formats.cramio import decode_container, read_cram_header
from hadoop_bam_tpu.split.spans import FileByteSpan


def scan_cram_containers(source) -> List[Tuple[int, int, int]]:
    """[(offset, byte length, n_records)] for every data container (header
    container included with n_records=0; EOF container excluded).

    Path sources walk container HEADERS with seeks — a few KB of reads
    per container, never the file body — so a whole-file count
    (`hbam view -c`) touches ~0.01% of the bytes."""
    if isinstance(source, (bytes, bytearray)):
        buf = bytes(source)
        FileDefinition.from_bytes(buf)
        out = []
        for off, hdr in scan_container_offsets(buf):
            if hdr.is_eof:
                break
            # container total size = header size + block section length
            end = _container_end(buf, off, hdr)
            out.append((off, end - off, hdr.n_records))
        return out

    import os

    from hadoop_bam_tpu.formats.cram import ContainerHeader

    out = []
    with open(source, "rb") as f:
        FileDefinition.from_bytes(f.read(FileDefinition.SIZE))
        fsize = os.fstat(f.fileno()).st_size
        pos = FileDefinition.SIZE
        while pos < fsize:
            f.seek(pos)
            chunk_size = 1 << 16      # per container: one oversized
            while True:               # header must not tax the rest
                chunk = f.read(chunk_size)
                try:
                    hdr, after = ContainerHeader.from_buffer(chunk, 0)
                    break
                except (IndexError, ValueError, struct.error) as e:
                    # header longer than the probe (huge landmark array):
                    # widen, bounded so garbage can't loop forever; a
                    # truncated tail surfaces as CRAMError so callers
                    # (and the CLI) see the normal error type
                    if chunk_size >= (1 << 24) or len(chunk) < chunk_size:
                        raise CRAMError(
                            f"truncated or corrupt container header at "
                            f"offset {pos}: {e}") from e
                    chunk_size <<= 2
                    f.seek(pos)
            if hdr.is_eof:
                break
            end = pos + after + hdr.length
            out.append((pos, end - pos, hdr.n_records))
            pos = end
    return out


def _container_end(buf: bytes, off: int, hdr) -> int:
    from hadoop_bam_tpu.formats.cram import ContainerHeader
    _, after = ContainerHeader.from_buffer(buf, off)
    return after + hdr.length


def plan_cram_spans(path: str, *, num_spans: Optional[int] = None,
                    config: HBamConfig = DEFAULT_CONFIG
                    ) -> List[FileByteSpan]:
    """Group data containers into spans; each span starts and ends exactly on
    container boundaries (the hb/CRAMInputFormat.java contract)."""
    containers = scan_cram_containers(path)
    data = [(off, size) for off, size, n_rec in containers[1:]]
    if not data:
        return []
    total = sum(s for _, s in data)
    if num_spans is None:
        span_bytes = config.split_size
        num_spans = max(1, -(-total // span_bytes))
    num_spans = min(num_spans, len(data))
    target = total / num_spans
    spans: List[FileByteSpan] = []
    cur_start = data[0][0]
    acc = 0
    for i, (off, size) in enumerate(data):
        acc += size
        last = i == len(data) - 1
        if acc >= target * (len(spans) + 1) - 1e-9 or last:
            end = off + size
            spans.append(FileByteSpan(path, cur_start, end))
            if not last:
                cur_start = data[i + 1][0]
    return spans


def _iter_span_containers(source, span: FileByteSpan):
    """Containers whose start lies in [span.start, span.end) — the shared
    walk behind both the SAM and the pre-SAM span readers.

    Spans are container-aligned (plan_cram_spans ends every span exactly
    on a container boundary), so only the span's own byte range is read
    — a whole-file read per span would make total I/O quadratic in file
    size once a file is planned into many pipeline-grain spans."""
    if isinstance(source, (bytes, bytearray)):
        buf = bytes(source)[span.start:span.end]
    else:
        with open(source, "rb") as f:
            f.seek(span.start)
            buf = f.read(max(0, span.end - span.start))
    pos = 0
    n = len(buf)
    while pos < n:
        cont, pos = read_container(buf, pos)
        if cont.header.is_eof:
            break
        yield cont


def read_cram_span(source, span: FileByteSpan, *, header: SAMHeader,
                   ref_source=None):
    """Decode every container whose start lies in [span.start, span.end) —
    the per-span idempotent unit of work (hb/CRAMRecordReader.java)."""
    out = []
    for cont in _iter_span_containers(source, span):
        out.extend(decode_container(cont, header, ref_source))
    return out


def read_cram_span_raw(source, span: FileByteSpan, *, header: SAMHeader,
                       ref_source=None):
    """Pre-SAM CramRecords of the span's containers (features resolved,
    mates unlinked) — the stats tensor path's input; seq/qual/length are
    final at this stage, so SamRecord materialization is skipped."""
    from hadoop_bam_tpu.formats.cramio import decode_container_slices
    out = []
    for cont in _iter_span_containers(source, span):
        for _base, records in decode_container_slices(cont, header,
                                                      ref_source):
            out.extend(records)
    return out


def read_cram_span_columns(source, span: FileByteSpan, *,
                           header: SAMHeader, ref_source=None,
                           want_names: bool = False) -> dict:
    """One span as columns (cram_columns.decode_slice_columns layout):
    the vectorized slice decoder where the layout allows, the record
    path (converted) where it doesn't — output identical either way."""
    from hadoop_bam_tpu.formats.cram_columns import (
        concat_columns, decode_slice_columns, records_to_columns,
    )
    from hadoop_bam_tpu.formats.cram_decode import decode_slice_records
    from hadoop_bam_tpu.formats.cramio import iter_container_slices

    parts = []
    for cont in _iter_span_containers(source, span):
        for comp, slice_hdr, core, external, codec_lens \
                in iter_container_slices(cont):
            cols = decode_slice_columns(comp, slice_hdr, core, external,
                                        header.ref_names, ref_source,
                                        want_names=want_names,
                                        codec_rec_lens=codec_lens)
            if cols is None:
                cols = records_to_columns(
                    decode_slice_records(comp, slice_hdr, core, external,
                                         header.ref_names, ref_source,
                                         codec_rec_lens=codec_lens),
                    want_names=want_names)
            parts.append(cols)
    return concat_columns(parts)
