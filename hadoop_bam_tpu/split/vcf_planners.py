"""VCF/BCF span planning + span readers: the getSplits layer for variants.

Rebuild of hb/VCFInputFormat.java's split behavior (SURVEY.md section 3.4):

- text ``.vcf``: plain byte splits, line-aligned at read time (LineRecordReader
  semantics — split/planners.read_text_span).
- ``.vcf.gz`` (BGZF): splittable via BGZF block alignment — the
  hb/util/BGZFCodec.java [VER? 7.8] + LineRecordReader path.  Spans are
  *compressed* byte ranges snapped to confirmed BGZF block starts; ownership
  of a line that starts exactly on a block boundary is resolved by probing the
  previous block's final byte, so the union of all spans yields each line
  exactly once at every possible boundary.
- ``.bcf`` (BGZF or raw): record-aligned virtual-offset spans via
  hb/BCFSplitGuesser (split/bcf_guesser.py).
"""
from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bcf import BCFRecordCodec
from hadoop_bam_tpu.formats.bcfio import read_bcf_header
from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
from hadoop_bam_tpu.split.bcf_guesser import BCFSplitGuesser
from hadoop_bam_tpu.split.bgzf_guesser import BGZFSplitGuesser
from hadoop_bam_tpu.split.planners import plan_byte_ranges
from hadoop_bam_tpu.split.spans import FileByteSpan, FileVirtualSpan
from hadoop_bam_tpu.utils.seekable import as_byte_source


# ---------------------------------------------------------------------------
# BGZF-compressed text (.vcf.gz): block-aligned spans
# ---------------------------------------------------------------------------

def plan_bgzf_text_spans(path: str, *, num_spans: Optional[int] = None,
                         span_bytes: Optional[int] = None,
                         config: HBamConfig = DEFAULT_CONFIG
                         ) -> List[FileByteSpan]:
    """Compressed byte ranges snapped to confirmed BGZF block starts."""
    src = as_byte_source(path)
    try:
        size = src.size
        ranges = plan_byte_ranges(size, num_spans=num_spans,
                                  span_bytes=span_bytes if span_bytes
                                  else (None if num_spans else config.split_size))
        guesser = BGZFSplitGuesser(src)
        bounds: List[int] = []
        for (bstart, _bend) in ranges:
            if bstart == 0:
                bounds.append(0)
                continue
            b = guesser.guess_next_block_start(bstart)
            bounds.append(size if b is None else b)
        bounds.append(size)
        spans = []
        for i in range(len(bounds) - 1):
            s, e = bounds[i], bounds[i + 1]
            if s < e:
                spans.append(FileByteSpan(path, s, e))
        return spans
    finally:
        src.close()


def _prev_block_last_byte(src, coffset: int) -> Optional[int]:
    """Final inflated byte of the BGZF block that ends exactly at
    ``coffset`` (None when it cannot be located or is empty)."""
    lo = max(0, coffset - bgzf.MAX_BLOCK_SIZE)
    win = src.pread(lo, coffset - lo + bgzf.HEADER_SIZE)
    arr = np.frombuffer(win[:coffset - lo], dtype=np.uint8)
    for cand in bgzf.find_block_starts_numpy(arr):
        c = lo + int(cand)
        try:
            info = bgzf.parse_block_header(win, int(cand))
        except bgzf.BGZFError:
            continue
        if c + info.block_size == coffset:
            try:
                data = bgzf.inflate_block(win, info, check_crc=False)
            except bgzf.BGZFError:
                continue
            return data[-1] if data else None
    return None


def read_bgzf_text_span(source, span: FileByteSpan) -> bytes:
    """All text lines *starting* within the span's compressed block range.

    A line starts in the span iff its first inflated byte lies in a block
    whose compressed offset is in [span.start, span.end) — with the partial
    line carried over a boundary owned by the previous span."""
    src = as_byte_source(source)
    start, end = span.start, span.end

    chunks: List[bytes] = []
    base_len = 0          # inflated bytes belonging to in-span blocks
    coffset = start
    while coffset < min(end, src.size):
        head = src.pread(coffset, bgzf.MAX_BLOCK_SIZE)
        info = bgzf.parse_block_header(head, 0)
        chunks.append(bgzf.inflate_block(head, info, check_crc=False))
        base_len += len(chunks[-1])
        coffset += info.block_size
    buf = b"".join(chunks)
    # extend past the end until the final in-span line is complete
    while (len(buf) == 0 or not buf.endswith(b"\n")) and coffset < src.size:
        head = src.pread(coffset, bgzf.MAX_BLOCK_SIZE)
        info = bgzf.parse_block_header(head, 0)
        ext = bgzf.inflate_block(head, info, check_crc=False)
        coffset += info.block_size
        if not ext:
            continue
        nl = ext.find(b"\n")
        if nl >= 0:
            buf += ext[:nl + 1]
            break
        buf += ext

    skip_first = False
    if start > 0:
        prev = _prev_block_last_byte(src, start)
        skip_first = prev is not None and prev != 0x0A
    out = bytearray()
    pos = 0
    n = len(buf)
    first = True
    while pos < base_len and pos < n:
        nl = buf.find(b"\n", pos)
        line_end = n if nl < 0 else nl + 1
        if not (first and skip_first):
            out += buf[pos:line_end]
        first = False
        pos = line_end
    return bytes(out)


# ---------------------------------------------------------------------------
# BCF: record-aligned virtual-offset spans
# ---------------------------------------------------------------------------

def plan_bcf_spans(path: str, *, num_spans: Optional[int] = None,
                   config: HBamConfig = DEFAULT_CONFIG,
                   header: Optional[VCFHeader] = None,
                   ) -> List[FileVirtualSpan]:
    """hb/VCFInputFormat BCF path: BCFSplitGuesser-aligned virtual spans."""
    src = as_byte_source(path)
    try:
        size = src.size
        hdr, first_voffset, is_bgzf = read_bcf_header(src)
        if header is None:
            header = hdr
        ranges = plan_byte_ranges(size, num_spans=num_spans,
                                  span_bytes=None if num_spans
                                  else config.split_size)
        guesser = BCFSplitGuesser(src, header, is_bgzf=is_bgzf)
        boundaries: List[int] = []
        for (bstart, _bend) in ranges:
            if bstart == 0:
                boundaries.append(first_voffset)
                continue
            v = guesser.guess_next_record_start(bstart)
            boundaries.append(size << 16 if v is None
                              else max(v, first_voffset))
        boundaries.append(size << 16)
        spans: List[FileVirtualSpan] = []
        for i in range(len(boundaries) - 1):
            s, e = boundaries[i], boundaries[i + 1]
            if s < e:
                spans.append(FileVirtualSpan(path, s, e))
        return spans
    finally:
        src.close()


def read_bcf_span(source, span: FileVirtualSpan,
                  header: Optional[VCFHeader] = None,
                  is_bgzf: Optional[bool] = None) -> List[VcfRecord]:
    """hb/BCFRecordReader semantics: every record whose start virtual offset
    is in [span.start_voffset, span.end_voffset)."""
    src = as_byte_source(source)
    if header is None or is_bgzf is None:
        header, _, is_bgzf = read_bcf_header(src)
    codec = BCFRecordCodec(header)
    out: List[VcfRecord] = []
    if is_bgzf:
        r = bgzf.BGZFReader(src)
        r.seek_voffset(span.start_voffset)
        while True:
            v = r.voffset()
            if v >= span.end_voffset:
                break
            head = r.read(8)
            if len(head) < 8:
                break
            l_shared, l_indiv = struct.unpack("<II", head)
            body = r.read(l_shared + l_indiv)
            rec, _ = codec.decode(head + body, 0)
            out.append(rec)
    else:
        pos = span.start[0]
        end_byte = span.end[0]
        while pos < min(end_byte, src.size):
            head = src.pread(pos, 8)
            if len(head) < 8:
                break
            l_shared, l_indiv = struct.unpack("<II", head)
            body = src.pread(pos + 8, l_shared + l_indiv)
            rec, _ = codec.decode(head + body, 0)
            out.append(rec)
            pos += 8 + l_shared + l_indiv
    return out


def read_bcf_span_bytes(source, span: FileVirtualSpan,
                        is_bgzf: Optional[bool] = None) -> bytes:
    """Raw concatenated record bytes of a BCF span (no decode) — the input
    of the fast column scanner (formats/bcf.py scan_variant_columns)."""
    return read_bcf_span_frames(source, span, is_bgzf)[0]


def read_bcf_span_frames(source, span: FileVirtualSpan,
                         is_bgzf: Optional[bool] = None
                         ) -> Tuple[bytes, np.ndarray]:
    """(concatenated record bytes, per-record start offsets) of a BCF
    span — the input of the columnar decoder
    (formats/bcf_columns.decode_bcf_columns).

    The span's whole inflated range is read in BULK (block-granular,
    not per-record — two tiny ``BGZFReader.read`` calls per record were
    2.5x the columnar decode itself), then the record framing the
    decoder needs comes from one cursor chase over the ``l_shared``/
    ``l_indiv`` prefixes, which also extends the tail record past the
    span end exactly like the per-record reader did: a record belongs
    to the span iff its first byte does.  A record cut off by EOF is
    kept (the decoder raises ``BCFError`` on it, matching the record
    path); a bare header stub at EOF is dropped (the record path never
    emitted it either)."""
    src = as_byte_source(source)
    if is_bgzf is None:
        _, _, is_bgzf = read_bcf_header(src)
    unpack = struct.Struct("<II").unpack_from
    if is_bgzf:
        r = bgzf.BGZFReader(src)
        r.seek_voffset(span.start_voffset)
        buf = bytearray(r.read_to_voffset(span.end_voffset))

        def read_more(k: int) -> bytes:
            return r.read(k)
    else:
        pos0 = span.start[0]
        n_raw = max(0, min(span.end[0], src.size) - pos0)
        buf = bytearray(src.pread(pos0, n_raw) if n_raw else b"")

        def read_more(k: int) -> bytes:
            return src.pread(pos0 + len(buf), k)

    n0 = len(buf)
    starts: List[int] = []
    p = 0
    while p < n0:
        if p + 8 > len(buf):
            buf += read_more(p + 8 - len(buf))
            if p + 8 > len(buf):
                del buf[p:]                     # EOF mid-header stub
                break
        l_shared, l_indiv = unpack(buf, p)
        end = p + 8 + l_shared + l_indiv
        if end > len(buf):
            buf += read_more(end - len(buf))
            if end > len(buf):                  # EOF mid-body: keep the
                starts.append(p)                # partial; decode raises
                break
        starts.append(p)
        p = end
    return bytes(buf), np.asarray(starts, np.int64)
