"""Span planners: the ``getSplits()`` layer.

Rebuild of the reference's InputFormat split planning (SURVEY.md section 3.1):
byte ranges at a target split size are converted to record-aligned spans —
via a sidecar splitting index when present (hb/SplittingBAMIndex.java path) or
the split guessers otherwise (hb/BAMSplitGuesser.java path) — then empty spans
are dropped.  Planning runs once on one host and the resulting span list is
broadcast (hadoop_bam_tpu/parallel/distributed.py), mirroring client-side
``Job.getSplits()`` at submission time.

Also provides the span *readers* (RecordReader equivalents): given a span,
produce the records whose start lies inside it — the reference's contract that
makes the union of all splits yield each record exactly once
(hb/BAMRecordReader.java: decode until the record's virtual pointer passes the
split's end voffset; text readers: skip the partial first line unless at file
start, read past the end to finish the last line).
"""
from __future__ import annotations

import os
from typing import List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import BamBatch, SAMHeader, walk_record_offsets
from hadoop_bam_tpu.formats.bamio import read_bam_header
from hadoop_bam_tpu.formats.virtual_offset import make_voffset
from hadoop_bam_tpu.split.bam_guesser import BAMSplitGuesser
from hadoop_bam_tpu.split.spans import FileByteSpan, FileVirtualSpan
from hadoop_bam_tpu.split.splitting_index import SplittingIndex
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.seekable import as_byte_source


def plan_byte_ranges(size: int, *, num_spans: Optional[int] = None,
                     span_bytes: Optional[int] = None) -> List[Tuple[int, int]]:
    """Uniform byte ranges — the FileInputFormat.getSplits starting point.

    Invalid split parameters raise ``PlanError`` (the PLAN failure class):
    a bad plan request is a configuration fault that must never be retried
    or quarantined as if the data were corrupt."""
    if num_spans is not None and num_spans <= 0:
        raise PlanError(f"num_spans must be positive, got {num_spans}")
    if span_bytes is not None and span_bytes <= 0:
        raise PlanError(f"span_bytes must be positive, got {span_bytes}")
    if size <= 0:
        return []
    if num_spans is not None:
        num_spans = max(1, min(num_spans, size))
        bounds = np.linspace(0, size, num_spans + 1, dtype=np.int64)
    else:
        sb = span_bytes or DEFAULT_CONFIG.split_size
        bounds = np.arange(0, size + sb, sb, dtype=np.int64)
        bounds[-1] = size
        bounds = np.unique(bounds)
    return [(int(bounds[i]), int(bounds[i + 1])) for i in range(len(bounds) - 1)]


# ---------------------------------------------------------------------------
# BAM
# ---------------------------------------------------------------------------

def plan_bam_spans(path: str, *, num_spans: Optional[int] = None,
                   config: HBamConfig = DEFAULT_CONFIG,
                   header: Optional[SAMHeader] = None,
                   index: Optional[SplittingIndex] = None,
                   ) -> List[FileVirtualSpan]:
    """hb/BAMInputFormat.getSplits: byte ranges -> record-aligned virtual
    spans, snapped by the splitting index when available, guessed otherwise."""
    src = as_byte_source(path)
    try:
        size = src.size
        if header is None:
            header, first_voffset = read_bam_header(src)
        else:
            _, first_voffset = read_bam_header(src)
        if index is None and config.use_splitting_index:
            index = SplittingIndex.load_for(path)
        ranges = plan_byte_ranges(size, num_spans=num_spans,
                                  span_bytes=None if num_spans else config.split_size)
        boundaries: List[int] = []
        guesser = None if index is not None else BAMSplitGuesser(src, header)
        for (bstart, _bend) in ranges:
            if bstart == 0:
                boundaries.append(first_voffset)
                continue
            if index is not None:
                boundaries.append(index.first_record_at_or_after(bstart))
            else:
                v = guesser.guess_next_record_start(bstart)
                boundaries.append(size << 16 if v is None else
                                  max(v, first_voffset))
        end_sentinel = size << 16
        if config.keep_paired_reads_together:
            boundaries = [boundaries[0]] + [
                _next_name_group_start(path, b, header, first_voffset,
                                       end_sentinel, index, guesser)
                for b in boundaries[1:]]
        boundaries.append(end_sentinel)
        spans: List[FileVirtualSpan] = []
        for i in range(len(boundaries) - 1):
            s, e = boundaries[i], boundaries[i + 1]
            if s < e:  # drop empty spans (duplicate boundaries merge here)
                spans.append(FileVirtualSpan(path, s, e))
        return spans
    finally:
        src.close()


def plan_bam_spans_balanced(path: str, num_spans: int, *,
                            header: Optional[SAMHeader] = None,
                            index: Optional[SplittingIndex] = None,
                            granularity: int = 0,
                            ) -> List[FileVirtualSpan]:
    """Record-balanced spans via the splitting index: partition sampled
    record voffsets into ``num_spans`` contiguous runs of near-equal record
    count.  Unlike hb/BAMInputFormat.getSplits' byte-range snapping (which
    cannot cut inside a BGZF block, so a small file yields fewer spans than
    devices), the boundaries here are full virtual offsets — in-block cuts
    are allowed, so even a one-block BAM saturates an n-device mesh.

    When no sidecar index exists one is built in memory; ``granularity``
    0 picks a sampling step fine enough for ~8 samples per span."""
    from hadoop_bam_tpu.split.splitting_index import build_splitting_index
    if index is None:
        index = SplittingIndex.load_for(path)
    if index is None:
        if granularity <= 0:
            # one cheap counting pass (granularity=1 keeps every voffset;
            # acceptable for the small files this planner exists for)
            granularity = 1
        index = build_splitting_index(path, granularity=granularity)
    samples = index.voffsets[:-1]           # drop the end sentinel
    end_sentinel = index.voffsets[-1]
    if not samples:
        return []
    num_spans = max(1, min(num_spans, len(samples)))
    bounds = np.linspace(0, len(samples), num_spans + 1).astype(np.int64)
    bounds = np.unique(bounds)
    spans: List[FileVirtualSpan] = []
    for i in range(len(bounds) - 1):
        s = samples[int(bounds[i])]
        e = (end_sentinel if i == len(bounds) - 2
             else samples[int(bounds[i + 1])])
        if s < e:
            spans.append(FileVirtualSpan(path, s, e))
    return spans


def _next_name_group_start(path: str, boundary: int, header: SAMHeader,
                           first_voffset: int, end_sentinel: int,
                           index, guesser) -> int:
    """Move a split boundary forward so it never separates records sharing a
    query name (hb/BAMInputFormat.java keep-paired-reads-together, upstream
    7.9+): on a queryname-grouped BAM, the record at the boundary stays with
    its pair when both share the name of the record just before the boundary.

    Strategy: recover the name of the record preceding the boundary by
    decoding a small window ending at the boundary, then walk forward from
    the boundary until the name changes.
    """
    if boundary <= first_voffset or boundary >= end_sentinel:
        return boundary
    coffset = boundary >> 16
    back_c = max(first_voffset >> 16, coffset - (1 << 18))
    if index is not None:
        back_v = index.first_record_at_or_after(back_c)
    else:
        back_v = guesser.guess_next_record_start(back_c)
        back_v = first_voffset if back_v is None else max(back_v,
                                                          first_voffset)
    prev_name = None
    if back_v < boundary:
        ctx = read_bam_span(path, FileVirtualSpan(path, back_v, boundary),
                            header=header)
        if len(ctx):
            prev_name = ctx.read_name(len(ctx) - 1)
    if prev_name is None:
        return boundary
    # forward window: 256 KiB compressed is far beyond any real name group
    fwd_end = min(end_sentinel, (coffset + (1 << 18)) << 16)
    fwd = read_bam_span(path, FileVirtualSpan(path, boundary, fwd_end),
                        header=header)
    for i in range(len(fwd)):
        if fwd.read_name(i) != prev_name:
            return int(fwd.voffsets[i])
    if fwd_end >= end_sentinel:
        return end_sentinel   # the group runs to EOF: merge the tail
    return boundary   # name group exceeds the window: leave the boundary


_PLAN_CACHE: "dict[tuple, list]" = {}
_PLAN_CACHE_MAX = 32


def plan_spans_cached(path: str, header, config,
                      num_spans: Optional[int] = None):
    """plan_spans_maybe_intervals memoized per (file identity, request).

    The reference computes ``getSplits()`` ONCE per job on the client
    (SURVEY.md section 3.1); repeated driver calls over an unchanged file
    should not re-run the split guessers, whose probe I/O and inflation
    are a measurable share of a whole-file stats pass on fast paths.
    The key includes file size + mtime of the BAM AND of every index
    sidecar the planners may consult (.splitting-bai/.sbi/.bai/.csi —
    a rebuilt sidecar must replan even when the BAM is unchanged,
    ADVICE r4), plus a canonical serialization of the config (field
    dict, not repr formatting)."""
    import dataclasses

    def _stat_sig(p):
        try:
            st = os.stat(p)
            return (st.st_size, st.st_mtime_ns)
        except OSError:
            return None
    try:
        st = os.stat(path)
        try:
            cfg_sig = repr(sorted(dataclasses.asdict(config).items()))
        except TypeError:
            cfg_sig = repr(config)
        key = (os.path.abspath(path), st.st_size, st.st_mtime_ns,
               num_spans, cfg_sig,
               tuple(_stat_sig(path + suf) for suf in
                     (".splitting-bai", ".sbi", ".bai", ".csi")))
    except (OSError, TypeError):       # non-path sources: no caching
        return plan_spans_maybe_intervals(path, header, config,
                                          num_spans=num_spans)
    hit = _PLAN_CACHE.get(key)
    if hit is not None:
        return list(hit)
    plan = plan_spans_maybe_intervals(path, header, config,
                                      num_spans=num_spans)
    if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
        _PLAN_CACHE.pop(next(iter(_PLAN_CACHE)))
    _PLAN_CACHE[key] = list(plan)
    return list(plan)


def plan_spans_maybe_intervals(path: str, header, config,
                               num_spans: Optional[int] = None):
    """plan_bam_spans, but when ``config.bam_intervals`` is set and a
    ``.bai`` sidecar exists, trim the plan to the index's chunk ranges —
    the reference's BAI split trimming (hb/BAMInputFormat.java 7.7+): only
    file regions that can contain overlapping records are read at all;
    exact row filtering still happens in the decoders."""
    if getattr(config, "bam_intervals", None):
        from hadoop_bam_tpu.split.bai import plan_interval_spans
        from hadoop_bam_tpu.split.intervals import parse_intervals
        try:
            ivs = parse_intervals(config.bam_intervals, header.ref_names)
        except PlanError:
            raise
        except ValueError as e:
            # user-supplied interval syntax: PLAN class, never retried or
            # skip_bad_spans-eaten downstream (still a ValueError)
            raise PlanError(f"bad bam_intervals "
                            f"{config.bam_intervals!r}: {e}") from e
        spans = plan_interval_spans(path, ivs, header)
        if spans is not None:
            return spans
    return plan_bam_spans(path, num_spans=num_spans, config=config,
                          header=header)


def read_bam_span(source, span: FileVirtualSpan,
                  header: Optional[SAMHeader] = None,
                  check_crc: bool = False) -> BamBatch:
    """hb/BAMRecordReader semantics: every record whose start virtual offset
    is in [span.start, span.end) — even if its body extends past the end.

    Batched implementation: inflate the span's block range in one pass, walk
    record boundaries in memory, and extend with following blocks only if the
    final record is cut (instead of the reference's per-record stream loop).
    """
    src = as_byte_source(source)
    if header is None:
        header, _ = read_bam_header(src)
    start_c, start_u = span.start
    end_c, end_u = span.end

    r = bgzf.BGZFReader(src, check_crc=check_crc)
    r.seek_voffset(span.start_voffset)

    chunks: List[bytes] = []
    # inflated offset (within our chunk buffer) of each block start, and the
    # coffset of each block, so record offsets map back to virtual offsets
    block_bases: List[Tuple[int, int]] = []  # (inflated_base, coffset)
    total = 0
    # First (possibly partial) block chunk:
    coffset = start_c
    while coffset < src.size:
        head = src.pread(coffset, bgzf.MAX_BLOCK_SIZE)
        info = bgzf.parse_block_header(head, 0)
        if coffset > end_c or (coffset == end_c and end_u == 0):
            break
        data = bgzf.inflate_block(head, info, check_crc=check_crc)
        if coffset == start_c and start_u:
            data = data[start_u:]
            block_bases.append((total - start_u, coffset))
        else:
            block_bases.append((total, coffset))
        chunks.append(data)
        total += len(data)
        coffset += info.block_size  # info offsets are window-relative

    buf = b"".join(chunks)
    data_arr = np.frombuffer(buf, dtype=np.uint8)

    # end limit within the inflated buffer: records starting at voffset >= end
    # are excluded.  Find the inflated offset corresponding to (end_c, end_u).
    if end_c >= coffset and coffset >= src.size:
        end_inflated = len(buf)
    else:
        end_inflated = len(buf)
        for base, c in block_bases:
            if c == end_c:
                # base already accounts for a trimmed first block (it is
                # stored as total - start_u), so base + end_u is the buffer
                # offset of in-block offset end_u in every case.
                end_inflated = base + end_u
                break

    offs = walk_record_offsets(buf, 0, None)
    offs = offs[offs < max(end_inflated, 1)] if len(offs) else offs

    # If the last in-range record is truncated in ``buf``, pull more blocks.
    if offs.size:
        last = int(offs[-1])
        bs = int.from_bytes(buf[last:last + 4], "little", signed=True)
        need = last + 4 + bs
        while need > len(buf) and coffset < src.size:
            head = src.pread(coffset, bgzf.MAX_BLOCK_SIZE)
            info = bgzf.parse_block_header(head, 0)
            chunks.append(bgzf.inflate_block(head, info, check_crc=check_crc))
            block_bases.append((len(buf), coffset))
            buf = b"".join(chunks)
            coffset += info.block_size
        data_arr = np.frombuffer(buf, dtype=np.uint8)
        offs = walk_record_offsets(buf, 0, None)
        offs = offs[offs < end_inflated]
    # also: records may have been cut at end_inflated boundary mid-walk —
    # ensure completeness: re-walk already covers it since buf grew.

    voffs = _inflated_to_voffsets(offs, block_bases, start_c, start_u)
    return BamBatch(data_arr, offs, header=header, voffsets=voffs)


def _inflated_to_voffsets(offs: np.ndarray, block_bases: List[Tuple[int, int]],
                          start_c: int, start_u: int) -> np.ndarray:
    """Map inflated-buffer offsets back to packed virtual offsets."""
    if offs.size == 0:
        return np.empty(0, dtype=np.uint64)
    bases = np.asarray([b for b, _ in block_bases], dtype=np.int64)
    coffs = np.asarray([c for _, c in block_bases], dtype=np.int64)
    idx = np.searchsorted(bases, offs, side="right") - 1
    idx = np.clip(idx, 0, len(bases) - 1)
    uoff = offs - bases[idx]
    return make_voffset(coffs[idx], uoff)


# ---------------------------------------------------------------------------
# Text formats (SAM, VCF, QSEQ, ...): newline-aligned spans
# ---------------------------------------------------------------------------

def plan_text_spans(path: str, *, num_spans: Optional[int] = None,
                    span_bytes: Optional[int] = None) -> List[FileByteSpan]:
    """Plain byte splits; alignment happens at read time via LineRecordReader
    semantics (skip partial first line unless at 0, finish last line past
    end) — exactly how hb/SAMInputFormat and text VCF splits behave."""
    src = as_byte_source(path)
    try:
        ranges = plan_byte_ranges(src.size, num_spans=num_spans,
                                  span_bytes=span_bytes)
        return [FileByteSpan(path, s, e) for s, e in ranges]
    finally:
        src.close()


def read_text_span(source, span: FileByteSpan, *, skip_prefix_lines_at_zero=0,
                   chunk: int = 1 << 20) -> bytes:
    """Return the bytes of all lines *starting* in [span.start, span.end).

    LineRecordReader contract: if start > 0, the (possibly partial) line in
    progress at ``start`` belongs to the previous span — skip to the first
    newline; read past ``end`` to complete the final line."""
    from hadoop_bam_tpu.utils.seekable import scoped_byte_source
    with scoped_byte_source(source) as src:
        start, end = span.start, span.end
        if start > 0:
            # Find the first newline at/after start-1: a line starting
            # exactly at ``start`` is ours only if byte start-1 is a newline,
            # which this probe handles uniformly.
            probe_off = start - 1
            probe = b""
            while True:
                got = src.pread(probe_off + len(probe), chunk)
                if not got:
                    return b""
                probe += got
                nl = probe.find(b"\n")
                if nl >= 0:
                    start = probe_off + nl + 1
                    break
        if start >= end:
            return b""  # no line *starts* inside this span
        out = bytearray()
        pos = start
        while pos < end:
            got = src.pread(pos, min(chunk, end - pos))
            if not got:
                break
            out += got
            pos += len(got)
        # finish the final line
        while not out.endswith(b"\n") and pos < src.size:
            got = src.pread(pos, chunk)
            if not got:
                break
            nl = got.find(b"\n")
            if nl >= 0:
                out += got[:nl + 1]
                break
            out += got
            pos += len(got)
        return bytes(out)
