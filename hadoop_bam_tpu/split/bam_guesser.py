"""BAM split guesser: find the next BAM *record* boundary from an arbitrary
file offset, as a virtual offset.

Rebuild of hb/BAMSplitGuesser.java.  Semantics (SURVEY.md 2.2, [SPEC] record
layout): starting at a byte offset, locate candidate BGZF block starts
(BGZFSplitGuesser); within the first confirmed block's inflated payload, test
every in-block offset as a potential record start; a candidate is accepted
when a chain of consecutive records decodes cleanly — fields plausible against
the header's reference dictionary (refID/pos in range, l_read_name in [1,255],
CIGAR op codes <= 8, block_size self-consistent) — spanning at least
MIN_CHAIN records or reaching the end of the inspection window.

Design shift vs the reference: the per-offset plausibility test is a single
vectorized NumPy pass over *all* 2^16 in-block offsets at once (the reference
loops per offset, decoding with htsjdk and catching exceptions); only the few
surviving offsets get the serial chain walk.
"""
from __future__ import annotations

from typing import Optional

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import (
    CORE_AFTER_BLOCKSIZE, FIXED_RECORD_PREFIX, SAMHeader, parse_tags,
)
from hadoop_bam_tpu.formats.virtual_offset import make_voffset
from hadoop_bam_tpu.split.bgzf_guesser import BGZFSplitGuesser
from hadoop_bam_tpu.utils.seekable import as_byte_source

# Plausibility bounds (reference uses similar order-of-magnitude caps; exact
# upstream constants unverifiable — SURVEY.md section 0).
MAX_PLAUSIBLE_BLOCK_SIZE = 1 << 26   # 64 MiB single record cap
MAX_PLAUSIBLE_SEQ_LEN = 1 << 26
MIN_CHAIN = 3                        # consecutive records required to accept
INSPECT_BLOCKS = 4                   # inflated blocks examined per candidate


class BAMSplitGuesser:

    def __init__(self, source, header: SAMHeader):
        self._src = as_byte_source(source)
        self._header = header
        self._bgzf = BGZFSplitGuesser(self._src)
        self._n_ref = header.n_ref
        self._ref_lengths = np.asarray(header.ref_lengths or [0], dtype=np.int64)

    def guess_next_record_start(self, offset: int) -> Optional[int]:
        """Smallest confirmed record-start virtual offset at or after byte
        ``offset``; None if no record is found before EOF."""
        coffset = offset
        while True:
            coffset = self._bgzf.guess_next_block_start(coffset)
            if coffset is None:
                return None
            # Inflate an inspection window: the candidate block + a few more.
            raw = self._src.pread(coffset, INSPECT_BLOCKS * bgzf.MAX_BLOCK_SIZE)
            blocks, data, first_len = self._inflate_chain(raw)
            if first_len > 0:
                u = self._find_record_in_block(data, first_len,
                                               partial=len(blocks) < INSPECT_BLOCKS
                                               and coffset + sum(b.block_size for b in blocks) >= self._src.size)
                if u is not None:
                    return make_voffset(coffset, u)
            elif first_len == 0 and blocks:
                # empty block (EOF terminator); step over it
                coffset += blocks[0].block_size
                if coffset >= self._src.size:
                    return None
                continue
            # No record starts in this block: try the next block start.
            if not blocks:
                return None
            coffset += blocks[0].block_size
            if coffset >= self._src.size:
                return None

    def _inflate_chain(self, raw: bytes):
        blocks, chunks = [], []
        off = 0
        while off < len(raw) and len(blocks) < INSPECT_BLOCKS:
            try:
                info = bgzf.parse_block_header(raw, off)
                chunks.append(bgzf.inflate_block(raw, info, check_crc=False))
            except bgzf.BGZFError:
                break
            blocks.append(info)
            off = info.next_coffset
        if not blocks:
            return [], b"", -1
        return blocks, b"".join(chunks), len(chunks[0])

    def _find_record_in_block(self, data: bytes, first_len: int,
                              partial: bool) -> Optional[int]:
        """Vectorized plausibility over every offset in the first block, then
        serial chain confirmation of survivors.  ``partial``: the inspection
        window reaches EOF, so a chain may legitimately end early."""
        cand = self._plausible_offsets(data, first_len)
        for u in cand:
            if self._chain_ok(data, int(u), partial):
                return int(u)
        return None

    def _plausible_offsets(self, data: bytes, first_len: int) -> np.ndarray:
        b = np.frombuffer(data, dtype=np.uint8)
        n = b.size
        hi = min(first_len, n - FIXED_RECORD_PREFIX)
        if hi <= 0:
            return np.empty(0, dtype=np.int64)
        offs = np.arange(hi, dtype=np.int64)

        def i32(shift):
            v = (b[offs + shift].astype(np.uint32)
                 | (b[offs + shift + 1].astype(np.uint32) << 8)
                 | (b[offs + shift + 2].astype(np.uint32) << 16)
                 | (b[offs + shift + 3].astype(np.uint32) << 24))
            return v.astype(np.int32).astype(np.int64)

        def u16(shift):
            return (b[offs + shift].astype(np.int64)
                    | (b[offs + shift + 1].astype(np.int64) << 8))

        bs = i32(0)
        refid = i32(4)
        pos = i32(8)
        l_read_name = b[offs + 12].astype(np.int64)
        n_cigar = u16(16)
        l_seq = i32(20)
        mate_refid = i32(24)
        mate_pos = i32(28)

        ref_len = np.where((refid >= 0) & (refid < self._n_ref),
                           self._ref_lengths[np.clip(refid, 0, self._n_ref - 1)],
                           np.int64(2 ** 31 - 1))
        mate_ref_len = np.where((mate_refid >= 0) & (mate_refid < self._n_ref),
                                self._ref_lengths[np.clip(mate_refid, 0, self._n_ref - 1)],
                                np.int64(2 ** 31 - 1))
        min_bs = (CORE_AFTER_BLOCKSIZE + l_read_name + 4 * n_cigar
                  + (l_seq + 1) // 2 + l_seq)
        mask = (
            (bs >= CORE_AFTER_BLOCKSIZE + 2)  # name >= "x\0"
            & (bs <= MAX_PLAUSIBLE_BLOCK_SIZE)
            & (refid >= -1) & (refid < self._n_ref)
            & (pos >= -1) & (pos < ref_len)
            & (l_read_name >= 2) & (l_read_name <= 255)
            & (l_seq >= 0) & (l_seq <= MAX_PLAUSIBLE_SEQ_LEN)
            & (mate_refid >= -1) & (mate_refid < self._n_ref)
            & (mate_pos >= -1) & (mate_pos < mate_ref_len)
            & (bs >= min_bs)
        )
        # read name is NUL-terminated exactly at its end and NUL-free before
        name_end = offs + FIXED_RECORD_PREFIX + l_read_name - 1
        ok_end = name_end < n
        name_end_c = np.where(ok_end, name_end, 0)
        mask &= ok_end & (b[name_end_c] == 0)
        return offs[mask]

    def _chain_ok(self, data: bytes, u: int, partial: bool) -> bool:
        """Serially validate a chain of records starting at inflated offset u."""
        n = len(data)
        count = 0
        p = u
        while count < MIN_CHAIN:
            if p + FIXED_RECORD_PREFIX > n:
                # ran out of inspection window mid-prefix
                return count >= 1 if partial else count >= MIN_CHAIN or p == n
            if not self._record_ok(data, p, n):
                return False
            bs = int.from_bytes(data[p:p + 4], "little", signed=True)
            nxt = p + 4 + bs
            if nxt > n:
                # record extends past window: fields were plausible; in
                # partial (EOF) windows that's acceptable evidence
                return True if count >= 1 or partial else True
            p = nxt
            count += 1
            if p == n:
                return True
        return True

    def _record_ok(self, data: bytes, p: int, n: int) -> bool:
        bs = int.from_bytes(data[p:p + 4], "little", signed=True)
        if not (CORE_AFTER_BLOCKSIZE + 2 <= bs <= MAX_PLAUSIBLE_BLOCK_SIZE):
            return False
        refid = int.from_bytes(data[p + 4:p + 8], "little", signed=True)
        pos = int.from_bytes(data[p + 8:p + 12], "little", signed=True)
        l_read_name = data[p + 12]
        n_cigar = int.from_bytes(data[p + 16:p + 18], "little")
        l_seq = int.from_bytes(data[p + 20:p + 24], "little", signed=True)
        mate_refid = int.from_bytes(data[p + 24:p + 28], "little", signed=True)
        mate_pos = int.from_bytes(data[p + 28:p + 32], "little", signed=True)
        if not (-1 <= refid < self._n_ref) or not (-1 <= mate_refid < self._n_ref):
            return False
        if refid >= 0 and not (-1 <= pos < self._header.ref_lengths[refid]):
            return False
        if refid < 0 and pos != -1:
            return False
        if mate_refid >= 0 and not (-1 <= mate_pos < self._header.ref_lengths[mate_refid]):
            return False
        if not (2 <= l_read_name <= 255) or l_seq < 0:
            return False
        min_bs = (CORE_AFTER_BLOCKSIZE + l_read_name + 4 * n_cigar
                  + (l_seq + 1) // 2 + l_seq)
        if bs < min_bs:
            return False
        name_end = p + FIXED_RECORD_PREFIX + l_read_name
        if name_end <= n and data[name_end - 1] != 0:
            return False
        # CIGAR op codes <= 8 [SPEC]
        cig_off = p + FIXED_RECORD_PREFIX + l_read_name
        cig_end = min(cig_off + 4 * n_cigar, n)
        for q in range(cig_off, cig_end - 3, 4):
            v = int.from_bytes(data[q:q + 4], "little")
            if (v & 0xF) > 8:
                return False
        return True
