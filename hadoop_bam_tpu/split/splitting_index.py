"""Splitting indexes: precomputed record-boundary samples for exact splits.

Rebuild of hb/SplittingBAMIndex.java (read) + hb/SplittingBAMIndexer.java
(write).  A splitting index samples the virtual offset of every Nth
(granularity) record plus an end sentinel, so planners can snap an arbitrary
byte range to exact record-aligned virtual offsets with a binary search —
eliminating split guessing entirely.

Two on-disk flavors are supported:

- ``.splitting-bai`` (legacy Hadoop-BAM sidecar): a sequence of big-endian
  u64 virtual offsets, last entry = file_size << 16.  [MED — SURVEY.md section
  2.2 flags the exact layout as unverifiable with the reference mount empty;
  this reconstruction is self-consistent read+write.]
- ``.sbi`` (the modern htsjdk/GATK format that superseded it): little-endian;
  magic "SBI\\x01", file_length u64, md5[16], uuid[16], total_records u64,
  granularity u64, n_offsets u64, then the offsets.  [MED likewise.]

Both flavors are read transparently; ``build_splitting_index`` can emit either.
"""
from __future__ import annotations

import bisect
import struct
from dataclasses import dataclass
from typing import List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import SAMHeader, walk_record_offsets
from hadoop_bam_tpu.formats.virtual_offset import make_voffset
from hadoop_bam_tpu.utils.seekable import as_byte_source

SBI_MAGIC = b"SBI\x01"
SPLITTING_BAI_SUFFIX = ".splitting-bai"
SBI_SUFFIX = ".sbi"


@dataclass
class SplittingIndex:
    """In-memory model: sorted virtual offsets of sampled records + end
    sentinel (file_size << 16)."""

    voffsets: List[int]           # sorted, includes end sentinel as last entry
    granularity: int = 0          # 0 = unknown (legacy files don't store it)
    total_records: int = 0        # 0 = unknown

    @property
    def end_voffset(self) -> int:
        return self.voffsets[-1]

    def first_record_at_or_after(self, file_offset: int) -> int:
        """Smallest indexed voffset whose compressed offset >= file_offset
        (hb/SplittingBAMIndex.nextAlignment semantics); returns the end
        sentinel when the range contains no sampled record."""
        key = file_offset << 16
        i = bisect.bisect_left(self.voffsets, key)
        return self.voffsets[min(i, len(self.voffsets) - 1)]

    def span_bounds(self, byte_start: int, byte_end: int) -> Tuple[int, int]:
        """Snap a plain byte range to (start_voffset, end_voffset)."""
        return (self.first_record_at_or_after(byte_start),
                self.first_record_at_or_after(byte_end))

    # ------------------------------------------------------------------ I/O
    def to_splitting_bai_bytes(self) -> bytes:
        return b"".join(struct.pack(">Q", v) for v in self.voffsets)

    def to_sbi_bytes(self, file_length: int) -> bytes:
        head = SBI_MAGIC + struct.pack("<Q", file_length) + b"\x00" * 32
        head += struct.pack("<QQQ", self.total_records, self.granularity,
                            len(self.voffsets))
        return head + np.asarray(self.voffsets, dtype="<u8").tobytes()

    @classmethod
    def from_bytes(cls, raw: bytes) -> "SplittingIndex":
        if raw[:4] == SBI_MAGIC:
            (file_length,) = struct.unpack_from("<Q", raw, 4)
            total, gran, n = struct.unpack_from("<QQQ", raw, 44)
            offs = np.frombuffer(raw, dtype="<u8", count=n, offset=68)
            return cls(voffsets=[int(v) for v in offs], granularity=int(gran),
                       total_records=int(total))
        if len(raw) % 8:
            raise ValueError("malformed splitting index")
        offs = np.frombuffer(raw, dtype=">u8")
        return cls(voffsets=[int(v) for v in offs])

    @classmethod
    def load_for(cls, bam_path: str) -> Optional["SplittingIndex"]:
        """Find and read a sidecar index next to ``bam_path`` (legacy first,
        then .sbi), as hb/BAMInputFormat.getSplits does."""
        import os
        for suffix in (SPLITTING_BAI_SUFFIX, SBI_SUFFIX):
            p = bam_path + suffix
            if os.path.exists(p):
                with open(p, "rb") as f:
                    return cls.from_bytes(f.read())
        return None


def build_splitting_index(bam_source, granularity: int = 4096,
                          ) -> SplittingIndex:
    """Stream a BAM once and sample every Nth record's virtual offset —
    hb/SplittingBAMIndexer.java's standalone mode (SURVEY.md section 3.5):
    per record, read block_size, skip the body, count; emit every Nth record's
    virtual offset plus the end sentinel."""
    src = as_byte_source(bam_source)
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    _, first_voffset = read_bam_header(src)
    r = bgzf.BGZFReader(src)
    r.seek_voffset(first_voffset)
    voffsets: List[int] = []
    count = 0
    while True:
        v = r.voffset()
        head = r.read(4)
        if len(head) < 4:
            break
        bs = int.from_bytes(head, "little", signed=True)
        body = r.read(bs)
        if len(body) < bs:
            raise ValueError("truncated BAM record while indexing")
        if count % granularity == 0:
            voffsets.append(v)
        count += 1
    return SplittingIndex(voffsets=voffsets + [src.size << 16],
                          granularity=granularity, total_records=count)


def write_splitting_index(bam_path: str, granularity: int = 4096,
                          flavor: str = "splitting-bai") -> str:
    """Build and write a sidecar index; returns the sidecar path."""
    idx = build_splitting_index(bam_path, granularity)
    src = as_byte_source(bam_path)
    if flavor == "sbi":
        out = bam_path + SBI_SUFFIX
        data = idx.to_sbi_bytes(src.size)
    else:
        out = bam_path + SPLITTING_BAI_SUFFIX
        data = idx.to_splitting_bai_bytes()
    with open(out, "wb") as f:
        f.write(data)
    return out
