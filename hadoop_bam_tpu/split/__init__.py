"""Split planning: record-aligned spans from arbitrary byte ranges.

Rebuild of the reference's crown jewels (SURVEY.md section 2.2):
hb/BGZFSplitGuesser.java, hb/BAMSplitGuesser.java, hb/BCFSplitGuesser.java,
hb/SplittingBAMIndex(er).java, hb/FileVirtualSplit.java and the
``getSplits()`` logic of the InputFormats.  All host-side (NumPy), stateless,
and idempotent per span — the property that makes the whole framework
embarrassingly data-parallel.
"""
from hadoop_bam_tpu.split.spans import FileVirtualSpan  # noqa: F401
from hadoop_bam_tpu.split.bgzf_guesser import BGZFSplitGuesser  # noqa: F401
from hadoop_bam_tpu.split.bam_guesser import BAMSplitGuesser  # noqa: F401
from hadoop_bam_tpu.split.splitting_index import (  # noqa: F401
    SplittingIndex, build_splitting_index,
)
