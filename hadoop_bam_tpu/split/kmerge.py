"""k-way streaming merge: the reusable heap core.

Extracted from the mesh-sort spill exchange (parallel/mesh_sort.py
``_merge_bucket_runs``, which previously reached for ``heapq.merge``
inline) so the cohort variant plane can reuse the exact same merge
discipline for joining thousands of single-sample VCF/BCF site streams
on position — SURVEY.md section 2.9's "distributed external merge"
core, now a first-class component.

Contracts (all pinned by tests/test_kmerge.py):

- **Heap order**: the output is sorted by ``key`` given each input
  stream is individually sorted by ``key``.  Inputs are streamed — one
  buffered item per live stream, never materialized.
- **Tie-breaking**: equal keys yield in STREAM order (stream 0's item
  before stream 1's), matching ``heapq.merge``'s stability — this is
  what makes the mesh-sort byte identity hold after the extraction,
  and what gives the cohort join a deterministic per-site sample
  order.
- **Exhausted streams** drop out of the heap without disturbing the
  rest; **empty inputs** (no streams, or all streams empty) yield
  nothing.
"""
from __future__ import annotations

import heapq
from typing import Callable, Iterable, Iterator, List, Optional, Tuple

_IDENT = object()


def _merge_entries(streams: Iterable[Iterable], key: Optional[Callable]
                   ) -> Iterator[Tuple[object, int, object]]:
    """The shared heap core: yield ``(key(item), stream_index, item)``
    in globally sorted order — the key rides along so consumers that
    group on it (``kmerge_grouped``) never recompute it."""
    keyf = (lambda x: x) if key is None else key
    # heap entries are (key, stream_index, item, iterator); the stream
    # index is unique per entry, so comparison never falls through to
    # the item (which may not be orderable)
    heap: List[Tuple[object, int, object, Iterator]] = []
    for si, s in enumerate(streams):
        it = iter(s)
        for item in it:               # at most once: prime the stream
            heap.append((keyf(item), si, item, it))
            break
    heapq.heapify(heap)
    while heap:
        k, si, item, it = heap[0]
        yield k, si, item
        nxt = next(it, _IDENT)
        if nxt is _IDENT:
            heapq.heappop(heap)       # stream exhausted: drop out
        else:
            heapq.heapreplace(heap, (keyf(nxt), si, nxt, it))


def kmerge_indexed(streams: Iterable[Iterable], key: Optional[Callable] = None
                   ) -> Iterator[Tuple[int, object]]:
    """Merge sorted ``streams``; yield ``(stream_index, item)`` in
    globally sorted order (ties in stream-index order).

    The stream index is what the cohort join keys sample columns on:
    a site group knows WHICH sample contributed each record without
    the records carrying it themselves.
    """
    for _k, si, item in _merge_entries(streams, key):
        yield si, item


def kmerge(streams: Iterable[Iterable], key: Optional[Callable] = None
           ) -> Iterator:
    """Merge sorted ``streams`` into one sorted stream of items
    (``heapq.merge`` semantics: stable, streaming, ties in stream
    order).  The mesh-sort spill merge runs on this."""
    for _si, item in kmerge_indexed(streams, key=key):
        yield item


def kmerge_grouped(streams: Iterable[Iterable], key: Callable
                   ) -> Iterator[Tuple[object, List[Tuple[int, object]]]]:
    """Merge sorted ``streams`` and group runs of EQUAL keys: yields
    ``(key, [(stream_index, item), ...])`` with the group's members in
    stream order — the cohort join's unit of work (one joined site =
    every sample's record at one (contig, pos)).

    A stream that emits several items with the same key contributes
    them all to one group (the "duplicate positions within one input"
    case — the consumer decides which wins)."""
    group: List[Tuple[int, object]] = []
    cur = _IDENT
    # the heap core already computed every item's key: group on it
    # instead of paying the key function a second time per record
    for k, si, item in _merge_entries(streams, key):
        if k != cur and group:
            yield cur, group
            group = []
        cur = k
        group.append((si, item))
    if group:
        yield cur, group
