"""BCF split guesser: find the next BCF2 *record* boundary from an arbitrary
file offset, as a virtual offset.

Rebuild of hb/BCFSplitGuesser.java (SURVEY.md section 2.2): works on both
containers — BGZF-compressed BCF (candidate = BGZF block start × in-block
offset, like the BAM guesser) and raw/uncompressed BCF (candidate = plain byte
offset, virtual offset = ``offset << 16``).  A candidate record start is
accepted when a chain of consecutive records validates: sane ``l_shared`` /
``l_indiv`` block lengths, CHROM index within the header's contig dictionary,
0-based POS >= -1, non-negative rlen (formats/bcf.plausible_record_start),
for MIN_CHAIN records or until the inspection window/EOF ends.
"""
from __future__ import annotations

import struct
from typing import Optional

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bcf import plausible_record_start
from hadoop_bam_tpu.formats.vcf import VCFHeader
from hadoop_bam_tpu.formats.virtual_offset import make_voffset
from hadoop_bam_tpu.split.bgzf_guesser import BGZFSplitGuesser
from hadoop_bam_tpu.utils.seekable import as_byte_source

MIN_CHAIN = 3
INSPECT_BLOCKS = 4
RAW_WINDOW = 1 << 20  # inspection window for uncompressed BCF


class BCFSplitGuesser:

    def __init__(self, source, header: VCFHeader, *, is_bgzf: bool = True):
        self._src = as_byte_source(source)
        self._header = header
        self._n_contigs = max(header.n_contigs, 1)
        self._is_bgzf = is_bgzf
        self._bgzf = BGZFSplitGuesser(self._src) if is_bgzf else None

    def guess_next_record_start(self, offset: int) -> Optional[int]:
        """Smallest confirmed record-start virtual offset at or after byte
        ``offset``; None if none found before EOF."""
        if self._is_bgzf:
            return self._guess_bgzf(offset)
        return self._guess_raw(offset)

    # -- BGZF container ------------------------------------------------------
    def _guess_bgzf(self, offset: int) -> Optional[int]:
        coffset = offset
        while True:
            coffset = self._bgzf.guess_next_block_start(coffset)
            if coffset is None:
                return None
            raw = self._src.pread(coffset, INSPECT_BLOCKS * bgzf.MAX_BLOCK_SIZE)
            blocks, data, first_len = self._inflate_chain(raw)
            if first_len > 0:
                at_eof = (coffset + sum(b.block_size for b in blocks)
                          >= self._src.size)
                u = self._find_record(data, first_len, partial=at_eof)
                if u is not None:
                    return make_voffset(coffset, u)
            if not blocks:
                return None
            coffset += blocks[0].block_size
            if coffset >= self._src.size:
                return None

    def _inflate_chain(self, raw: bytes):
        blocks, chunks = [], []
        off = 0
        while off < len(raw) and len(blocks) < INSPECT_BLOCKS:
            try:
                info = bgzf.parse_block_header(raw, off)
                chunks.append(bgzf.inflate_block(raw, info, check_crc=False))
            except bgzf.BGZFError:
                break
            blocks.append(info)
            off = info.next_coffset
        if not blocks:
            return [], b"", -1
        return blocks, b"".join(chunks), len(chunks[0])

    # -- raw container -------------------------------------------------------
    def _guess_raw(self, offset: int) -> Optional[int]:
        size = self._src.size
        while offset < size:
            data = self._src.pread(offset, RAW_WINDOW)
            at_eof = offset + len(data) >= size
            u = self._find_record(data, len(data), partial=at_eof)
            if u is not None:
                return make_voffset(offset + u, 0)
            if at_eof:
                return None
            # overlap windows so a boundary record isn't missed
            offset += RAW_WINDOW - 64
        return None

    # -- shared chain validation ---------------------------------------------
    def _find_record(self, data: bytes, first_len: int,
                     partial: bool) -> Optional[int]:
        for u in self._plausible_offsets(data, first_len):
            if self._chain_ok(data, int(u), partial):
                return int(u)
        return None

    def _plausible_offsets(self, data: bytes, first_len: int) -> np.ndarray:
        """Vectorized plausibility over every candidate offset in the first
        block (the design shift vs the reference's per-offset decode loop)."""
        b = np.frombuffer(data, dtype=np.uint8)
        n = b.size
        hi = min(first_len, n - 32)
        if hi <= 0:
            return np.empty(0, dtype=np.int64)
        offs = np.arange(hi, dtype=np.int64)

        def u32(shift):
            return (b[offs + shift].astype(np.int64)
                    | (b[offs + shift + 1].astype(np.int64) << 8)
                    | (b[offs + shift + 2].astype(np.int64) << 16)
                    | (b[offs + shift + 3].astype(np.int64) << 24))

        def i32(shift):
            return u32(shift).astype(np.uint32).astype(np.int32).astype(np.int64)

        l_shared = u32(0)
        l_indiv = u32(4)
        chrom = i32(8)
        pos0 = i32(12)
        rlen = i32(16)
        mask = (
            (l_shared >= 24) & (l_shared < (1 << 24))
            & (l_indiv < (1 << 24))
            & (chrom >= 0) & (chrom < self._n_contigs)
            & (pos0 >= -1) & (rlen >= 0)
        )
        return offs[mask]

    def _chain_ok(self, data: bytes, u: int, partial: bool) -> bool:
        """``partial`` means the window reaches EOF: then the chain must end
        exactly at the window end (a valid file ends on a record boundary),
        which kills false positives whose fake record runs past the tail."""
        n = len(data)
        count = 0
        p = u
        while count < MIN_CHAIN:
            if p == n:
                return count >= 1 or partial
            if p + 32 > n:
                return False if partial else count >= 1
            if not plausible_record_start(data, p, self._n_contigs):
                return False
            l_shared, l_indiv = struct.unpack_from("<II", data, p)
            nxt = p + 8 + l_shared + l_indiv
            if nxt > n:
                return False if partial else count >= 1
            p = nxt
            count += 1
        return True
