"""CRAM 3.0 slice/container encoder: SAM records → CRAM bytes.

Writer policy (all spec-legal choices, [SPEC] CRAM 3.0 sections 8, 10):

- one slice per container; landmarks = [0]; no embedded reference;
- reference-free encoding (``RR=false``): match stretches of the CIGAR are
  stored verbatim through the ``b`` (bases) feature, insertions/soft-clips
  through ``I``/``S``, so decode needs no FASTA — the same policy htslib uses
  when writing CRAM without a reference;
- every record is mate-detached (CF bit 0x2): NS/NP/TS carried explicitly,
  giving exact RNEXT/PNEXT/TLEN round-trips;
- read names preserved (``RN=true``), absolute alignment positions
  (``AP=false``);
- integer series as EXTERNAL/ITF8 blocks, byte-array series as
  BYTE_ARRAY_LEN(EXTERNAL, EXTERNAL), read names as BYTE_ARRAY_STOP(0x00);
- block compression: gzip, except quality scores which go through our
  rANS-4x8 order-1 codec (cram_codecs.py) like htslib's default profile.

Reference-side equivalent: htsjdk's CRAM writer as driven by
hb/KeyIgnoringCRAMOutputFormat.java / hb/KeyIgnoringCRAMRecordWriter.java
(SURVEY.md section 2.4, [VER? 7.3+]).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from hadoop_bam_tpu.formats.bam import (
    SAMHeader, encode_tag, parse_cigar_string,
)
from hadoop_bam_tpu.formats.cram import (
    Block, CRAMError, COMPRESSION_HEADER, CORE_DATA, EXTERNAL_DATA, GZIP,
    MAPPED_SLICE_HEADER, RANS4x8, RAW, build_container, write_itf8,
)
from hadoop_bam_tpu.formats.cram_decode import (
    ByteArrayLenEncoding, ByteArrayStopEncoding, CF_DETACHED, CF_QUAL_STORED,
    CF_UNKNOWN_BASES, CompressionHeader, ExternalEncoding, MATE_REVERSE,
    MATE_UNMAPPED, SliceHeader, tag_key,
)
from hadoop_bam_tpu.formats.sam import SamRecord

# content-id assignments for this writer (any distinct ids are legal)
_INT_SERIES = ["BF", "CF", "RI", "RL", "AP", "RG", "MF", "NS", "NP", "TS",
               "TL", "FN", "FP", "MQ", "DL", "RS", "PD", "HC"]
_BYTE_SERIES = ["FC", "BA", "QS", "BS"]
_ARRAY_SERIES = ["BB", "IN", "SC"]   # BYTE_ARRAY_LEN(len ext, val ext)
_RN_STOP = 0x00


class _Streams:
    """Per-series byte accumulators for one slice."""

    def __init__(self):
        self.ints: Dict[str, bytearray] = {k: bytearray() for k in _INT_SERIES}
        self.bytes_: Dict[str, bytearray] = {k: bytearray()
                                             for k in _BYTE_SERIES}
        self.arr_len: Dict[str, bytearray] = {k: bytearray()
                                              for k in _ARRAY_SERIES}
        self.arr_val: Dict[str, bytearray] = {k: bytearray()
                                              for k in _ARRAY_SERIES}
        self.names = bytearray()
        self.tag_len: Dict[int, bytearray] = {}
        self.tag_val: Dict[int, bytearray] = {}
        self.qual_lens: List[int] = []     # per-record QS lengths (fqzcomp)

    def put_int(self, key: str, v: int):
        self.ints[key] += write_itf8(v)

    def put_byte(self, key: str, v: int):
        self.bytes_[key].append(v & 0xFF)

    def put_array(self, key: str, data: bytes):
        self.arr_len[key] += write_itf8(len(data))
        self.arr_val[key] += data

    def put_name(self, name: bytes):
        if bytes([_RN_STOP]) in name:
            raise CRAMError("read name contains the RN stop byte")
        self.names += name + bytes([_RN_STOP])

    def put_tag(self, key: int, raw: bytes):
        self.tag_len.setdefault(key, bytearray())
        self.tag_val.setdefault(key, bytearray())
        self.tag_len[key] += write_itf8(len(raw))
        self.tag_val[key] += raw


def _ref_span(cigar: List[Tuple[int, str]]) -> int:
    return sum(n for n, op in cigar if op in "MDN=X")


def encode_container(records: List[SamRecord], header: SAMHeader,
                     record_counter: int,
                     version: Tuple[int, int] = (3, 0)) -> bytes:
    """Encode one container holding one slice of ``records``.

    ``version`` selects the entropy codecs: (3, 0) uses rANS 4x8 /
    gzip; (3, 1) upgrades byte series to rANS Nx16 with PACK/RLE
    transforms [SPEC CRAM 3.1]."""
    name_to_id = {n: i for i, n in enumerate(header.ref_names)}
    rg_ids = _read_group_ids(header)

    def rid_of(rname: str) -> int:
        if rname == "*":
            return -1
        if rname not in name_to_id:
            raise CRAMError(f"record reference {rname!r} not in header")
        return name_to_id[rname]

    streams = _Streams()
    tag_dict: List[bytes] = []
    tag_dict_index: Dict[bytes, int] = {}
    mapped = [r for r in records if not r.flag & 0x4 and r.pos > 0]
    multi_ref = len({rid_of(r.rname) for r in records}) > 1
    if multi_ref:
        slice_ref = -2
        slice_start = slice_span = 0
    elif records and rid_of(records[0].rname) >= 0:
        slice_ref = rid_of(records[0].rname)
        starts = [r.pos for r in mapped] or [0]
        ends = [r.pos + max(0, _ref_span(parse_cigar_string(r.cigar))
                            if r.cigar != "*" else len(r.seq)) - 1
                for r in mapped] or [0]
        slice_start = min(starts)
        slice_span = max(ends) - slice_start + 1 if mapped else 0
    else:
        slice_ref, slice_start, slice_span = -1, 0, 0

    n_bases = 0
    for rec in records:
        n_bases += _encode_record(rec, streams, rid_of, rg_ids, multi_ref,
                                  tag_dict, tag_dict_index)

    comp = _build_compression_header(streams, tag_dict)

    # blocks: compression header, slice header, core, externals
    ext_blocks: List[Block] = []
    content_ids: List[int] = []
    for cid, data, method, aux in _external_payloads(streams, version):
        if data:
            ext_blocks.append(Block(EXTERNAL_DATA, cid, bytes(data), method,
                                    aux=aux))
            content_ids.append(cid)

    slice_hdr = SliceHeader(
        ref_seq_id=slice_ref, start=slice_start, span=slice_span,
        n_records=len(records), record_counter=record_counter,
        n_blocks=1 + len(ext_blocks), content_ids=content_ids,
        embedded_ref_id=-1)
    comp_block = Block(COMPRESSION_HEADER, 0, comp.to_bytes(), GZIP)
    slice_block = Block(MAPPED_SLICE_HEADER, 0, slice_hdr.to_bytes(), RAW)
    core_block = Block(CORE_DATA, 0, b"", RAW)

    comp_bytes = comp_block.to_bytes()
    blocks = [comp_block, slice_block, core_block] + ext_blocks
    return build_container(
        blocks, ref_seq_id=slice_ref, start=slice_start, span=slice_span,
        n_records=len(records), record_counter=record_counter, bases=n_bases,
        landmarks=[len(comp_bytes)])


def _read_group_ids(header: SAMHeader) -> List[str]:
    ids = []
    for line in header.text.splitlines():
        if line.startswith("@RG"):
            for f in line.split("\t")[1:]:
                if f.startswith("ID:"):
                    ids.append(f[3:])
    return ids


def _encode_record(rec: SamRecord, s: _Streams, rid_of, rg_ids: List[str],
                   multi_ref: bool, tag_dict: List[bytes],
                   tag_dict_index: Dict[bytes, int]) -> int:
    """Append one record to the slice streams; returns its base count."""
    flag = rec.flag
    bf = flag & ~(MATE_REVERSE | MATE_UNMAPPED)
    has_qual = rec.qual != "*" and rec.qual != ""
    has_seq = rec.seq != "*" and rec.seq != ""
    rl = len(rec.seq) if has_seq else 0
    cf = CF_DETACHED
    if has_qual:
        cf |= CF_QUAL_STORED
        s.qual_lens.append(len(rec.qual))
    if not has_seq and not flag & 0x4:
        cf |= CF_UNKNOWN_BASES
    s.put_int("BF", bf)
    s.put_int("CF", cf)
    if multi_ref:
        s.put_int("RI", rid_of(rec.rname))
    s.put_int("RL", rl)
    s.put_int("AP", rec.pos)
    rg = -1
    for tag, typ, val in rec.tags:
        if tag == "RG" and typ == "Z" and val in rg_ids:
            rg = rg_ids.index(val)
    s.put_int("RG", rg)
    s.put_name(rec.qname.encode("ascii"))
    # detached mate fields
    mf = ((1 if flag & MATE_REVERSE else 0)
          | (2 if flag & MATE_UNMAPPED else 0))
    s.put_int("MF", mf)
    if rec.rnext == "=":
        s.put_int("NS", rid_of(rec.rname))
    else:
        s.put_int("NS", rid_of(rec.rnext))
    s.put_int("NP", rec.pnext)
    s.put_int("TS", rec.tlen)
    # tags (RG kept inline too when it was an inline tag: we re-emit all tags
    # except RG which rides its series when resolvable)
    out_tags = [(t, ty, v) for (t, ty, v) in rec.tags
                if not (t == "RG" and ty == "Z" and rg >= 0)]
    sig = b"".join(t.encode() + ty.encode() for t, ty, v in out_tags)
    if sig not in tag_dict_index:
        tag_dict_index[sig] = len(tag_dict)
        tag_dict.append(sig)
    s.put_int("TL", tag_dict_index[sig])
    for t, ty, v in out_tags:
        raw = encode_tag(t, ty, v)[3:]
        s.put_tag(tag_key(t, ty), raw)

    if not flag & 0x4:
        _encode_mapped(rec, s, has_seq, has_qual, rl)
    else:
        if has_seq:
            for ch in rec.seq:
                s.put_byte("BA", ord(ch))
        if has_qual:
            for ch in rec.qual:
                s.put_byte("QS", ord(ch) - 33)
    return rl


def _encode_mapped(rec: SamRecord, s: _Streams, has_seq: bool,
                   has_qual: bool, rl: int) -> None:
    features: List[Tuple[int, str, object]] = []
    if has_seq and rec.cigar != "*":
        rp = 1
        for n, op in parse_cigar_string(rec.cigar):
            if op in "M=X":
                features.append((rp, "b",
                                 rec.seq[rp - 1:rp - 1 + n].encode()))
                rp += n
            elif op == "I":
                features.append((rp, "I",
                                 rec.seq[rp - 1:rp - 1 + n].encode()))
                rp += n
            elif op == "S":
                features.append((rp, "S",
                                 rec.seq[rp - 1:rp - 1 + n].encode()))
                rp += n
            elif op == "D":
                features.append((rp, "D", n))
            elif op == "N":
                features.append((rp, "N", n))
            elif op == "P":
                features.append((rp, "P", n))
            elif op == "H":
                features.append((rp, "H", n))
            else:
                raise CRAMError(f"unsupported CIGAR op {op!r}")
        if rp - 1 != rl:
            raise CRAMError(
                f"CIGAR consumes {rp - 1} read bases but SEQ has {rl}")
    elif has_seq:
        # mapped record with '*' CIGAR: store bases as one stretch
        features.append((1, "b", rec.seq.encode()))
    s.put_int("FN", len(features))
    prev = 0
    for fpos, code, val in features:
        s.put_byte("FC", ord(code))
        s.put_int("FP", fpos - prev)
        prev = fpos
        if code in ("b", "I", "S"):
            s.put_array({"b": "BB", "I": "IN", "S": "SC"}[code], val)
        else:
            s.put_int({"D": "DL", "N": "RS", "P": "PD", "H": "HC"}[code], val)
    s.put_int("MQ", rec.mapq)
    if has_qual:
        for ch in rec.qual:
            s.put_byte("QS", ord(ch) - 33)


# content-id layout: ints 1..18, bytes 20..23, array len 30../val 40..,
# names 50, tags 100+k
_CID_INT = {k: 1 + i for i, k in enumerate(_INT_SERIES)}
_CID_BYTE = {k: 20 + i for i, k in enumerate(_BYTE_SERIES)}
_CID_ALEN = {k: 30 + i for i, k in enumerate(_ARRAY_SERIES)}
_CID_AVAL = {k: 40 + i for i, k in enumerate(_ARRAY_SERIES)}
_CID_NAMES = 50


def _tag_cids(key: int) -> Tuple[int, int]:
    return 100 + 2 * key, 101 + 2 * key


def _external_payloads(s: _Streams, version: Tuple[int, int] = (3, 0)):
    import os

    from hadoop_bam_tpu.formats.cram import NAME_TOK, RANSNx16
    # qualities through rANS like htslib's default; rest gzip.  3.1
    # upgrades the rANS series to Nx16 (+PACK/RLE) and tokenizes read
    # names (tok3), matching htslib's 3.1 defaults [SPEC CRAM 3.1].
    # The tok3 frame layout is [SPEC-recalled] and has never been
    # cross-validated against htscodecs output (reference mount empty —
    # SURVEY.md section 0), so HBAM_CRAM31_NAMES=gzip keeps 3.1 names on
    # the well-understood GZIP method for interop-critical output.
    rans = RANSNx16 if version >= (3, 1) else RANS4x8
    names_method = GZIP
    qual_method, qual_aux = rans, None
    if version >= (3, 1):
        knob = os.environ.get("HBAM_CRAM31_NAMES", "tok3").strip().lower()
        if knob not in ("tok3", "gzip"):   # fail closed, not open to tok3
            raise ValueError(
                f"HBAM_CRAM31_NAMES={knob!r}: expected 'tok3' or 'gzip'")
        if knob == "tok3":
            names_method = NAME_TOK
        # EXPERIMENTAL opt-in: quality series through the fqzcomp codec
        # (decode is the supported direction; the layout caveat in
        # cram_fqzcomp's docstring applies doubly to writes)
        qknob = os.environ.get("HBAM_CRAM31_QUAL", "rans").strip().lower()
        if qknob not in ("rans", "fqzcomp"):
            raise ValueError(
                f"HBAM_CRAM31_QUAL={qknob!r}: expected 'rans' or "
                f"'fqzcomp'")
        if qknob == "fqzcomp":
            from hadoop_bam_tpu.formats.cram import FQZCOMP
            qual_method, qual_aux = FQZCOMP, list(s.qual_lens)
    for k, data in s.ints.items():
        yield _CID_INT[k], data, GZIP, None
    for k, data in s.bytes_.items():
        # QS = qualities, BA = literal bases: the two bulk byte series
        if k == "QS":
            yield _CID_BYTE[k], data, qual_method, qual_aux
        else:
            yield _CID_BYTE[k], data, (rans if k == "BA" else GZIP), None
    for k in _ARRAY_SERIES:
        yield _CID_ALEN[k], s.arr_len[k], GZIP, None
        yield _CID_AVAL[k], s.arr_val[k], GZIP, None
    yield _CID_NAMES, s.names, names_method, None
    for key in s.tag_len:
        lo, hi = _tag_cids(key)
        yield lo, s.tag_len[key], GZIP, None
        yield hi, s.tag_val[key], GZIP, None


def _build_compression_header(s: _Streams, tag_dict: List[bytes]
                              ) -> CompressionHeader:
    comp = CompressionHeader(
        read_names_included=True, ap_delta=False, reference_required=False,
        tag_dict=[_sig_to_line(sig) for sig in tag_dict] or [[]])
    for k in _INT_SERIES:
        comp.data_series[k] = ExternalEncoding(_CID_INT[k])
    for k in _BYTE_SERIES:
        comp.data_series[k] = ExternalEncoding(_CID_BYTE[k])
    for k in _ARRAY_SERIES:
        comp.data_series[k] = ByteArrayLenEncoding(
            ExternalEncoding(_CID_ALEN[k]), ExternalEncoding(_CID_AVAL[k]))
    comp.data_series["RN"] = ByteArrayStopEncoding(_RN_STOP, _CID_NAMES)
    for key in s.tag_len:
        lo, hi = _tag_cids(key)
        comp.tag_encodings[key] = ByteArrayLenEncoding(
            ExternalEncoding(lo), ExternalEncoding(hi))
    return comp


def _sig_to_line(sig: bytes) -> List[Tuple[str, str]]:
    return [(sig[i:i + 2].decode(), chr(sig[i + 2]))
            for i in range(0, len(sig), 3)]
