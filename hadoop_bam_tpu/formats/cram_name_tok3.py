"""CRAM 3.1 name tokenizer codec ("tok3", block compression method 8).

[SPEC] CRAMcodecs "Name tokenisation": read names are highly structured
(instrument:run:flowcell:lane:tile:x:y), so the codec splits each name
into typed tokens (alpha runs, digit runs with/without leading zeros,
single chars), expresses each name as a reference to a previous name
(whole-name duplicate, or a token-by-token diff), and entropy-codes each
<token position, token type> stream independently with rANS Nx16
(cram_codecs_nx16.py) — small deltas in the hot fields collapse to
near-zero entropy.

Serialized layout::

    uint32 LE  ulen        total uncompressed bytes (names + separators)
    uint32 LE  nnames
    byte       flags       bit0 = arithmetic coder (unsupported here),
                           bit1 = names are '\\n'-separated (else '\\0')
    repeated stream frames:
        byte   descriptor  low 4 bits token type; 0x80 = first stream of
                           the next token position; 0x40 reserved
                           (htscodecs' duplicate-stream flag — rejected
                           loudly, never produced)
        uint7  clen        compressed length
        bytes  rANS Nx16 stream (carries its own uncompressed size)

Token types (values follow the public htscodecs enum)::

    TYPE 0   per-position type selector stream
    ALPHA 1  non-digit run, '\\0'-terminated in its data stream
    CHAR 2   single byte
    DZLEN 3  zero-padded digit-run length byte (companion of DIGITS0)
    DIGITS0 4  digit run with leading zeros: uint32 LE value + DZLEN
    DUP 5    whole name identical to the name <dist> back (uint32 LE)
    DIFF 6   name diffs against the name <dist> back (uint32 LE; 0 for
             the first name = no reference, every token fresh)
    DIGITS 7 digit run, no leading zeros, value < 2^32 (uint32 LE)
    DDELTA 11  digits delta to the reference token, one byte in [0,255]
    DDELTA0 12 zero-padded variant (same pad width as the reference)
    MATCH 13 token identical to the reference token
    NOP 14   nothing (accepted on decode, never produced)
    END 15   end of this name's token list

Provenance: the token model, type values, and 9-byte header follow the
public htscodecs layout; the stream-frame descriptor bits and the
separator flag (bit1) are [SPEC-recalled]/[LAYOUT-CHOICE] reconstructions
pinned by round-trip + frozen-golden tests (tests/test_cram_tok3.py) —
no htslib exists in this image to cross-validate (SURVEY.md section 0).
Reference-side equivalent: htscodecs tokenise_name3 reached through CRAM
3.1 RN-series decode (SURVEY.md section 2.8 CRAM codecs row).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

from hadoop_bam_tpu.formats.cram_codecs import (
    RansError, normalize_truncation,
)
from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
    NX16_ORDER1, rans_nx16_decode, rans_nx16_encode, var_get_u32,
    var_put_u32,
)

T_TYPE = 0
T_ALPHA = 1
T_CHAR = 2
T_DZLEN = 3
T_DIGITS0 = 4
T_DUP = 5
T_DIFF = 6
T_DIGITS = 7
T_DDELTA = 11
T_DDELTA0 = 12
T_MATCH = 13
T_NOP = 14
T_END = 15

MAX_TOKENS = 128               # token positions per name (spec bound)

F_ARITH = 0x01
F_NEWLINE_SEP = 0x02           # [LAYOUT-CHOICE] see module docstring

_D_NEW_POS = 0x80
_D_DUP_STREAM = 0x40


class Tok3Error(RansError):
    pass


# ---------------------------------------------------------------------------
# tokenization
# ---------------------------------------------------------------------------

def _tokenize(name: bytes) -> List[Tuple[int, bytes]]:
    """Split a name into (type, text) tokens: digit runs become DIGITS /
    DIGITS0, everything else ALPHA (multi-byte) or CHAR (single byte).
    Digit runs too long for uint32 degrade to ALPHA."""
    toks: List[Tuple[int, bytes]] = []
    i, n = 0, len(name)
    while i < n:
        c = name[i]
        if 0x30 <= c <= 0x39:                      # digit run
            j = i + 1
            while j < n and 0x30 <= name[j] <= 0x39:
                j += 1
            run = name[i:j]
            if len(run) > 9 or int(run) > 0xFFFFFFFF:
                toks.append((T_ALPHA, run))
            elif run[0] == 0x30 and len(run) > 1:
                toks.append((T_DIGITS0, run))
            else:
                toks.append((T_DIGITS, run))
            i = j
        else:                                      # non-digit run
            j = i + 1
            while j < n and not (0x30 <= name[j] <= 0x39):
                j += 1
            run = name[i:j]
            toks.append((T_CHAR, run) if len(run) == 1
                        else (T_ALPHA, run))
            i = j
    if len(toks) >= MAX_TOKENS:                    # overflow tail -> ALPHA
        head, tail = toks[:MAX_TOKENS - 1], toks[MAX_TOKENS - 1:]
        head.append((T_ALPHA, b"".join(t for _, t in tail)))
        toks = head
    return toks


class _Streams:
    """B[token position][token type] byte streams under construction."""

    def __init__(self):
        self.b: Dict[Tuple[int, int], bytearray] = {}
        self.max_pos = 0

    def put(self, pos: int, ttype: int, data: bytes):
        self.b.setdefault((pos, ttype), bytearray()).extend(data)
        self.max_pos = max(self.max_pos, pos)


def _encode_token(s: _Streams, pos: int, tok: Tuple[int, bytes],
                  ref: Optional[Tuple[int, bytes]]) -> None:
    ttype, text = tok
    if ref is not None and ref[1] == text:
        s.put(pos, T_TYPE, bytes([T_MATCH]))
        return
    if ttype == T_DIGITS and ref is not None and ref[0] == T_DIGITS:
        delta = int(text) - int(ref[1])
        if 0 <= delta <= 255:
            s.put(pos, T_TYPE, bytes([T_DDELTA]))
            s.put(pos, T_DDELTA, bytes([delta]))
            return
    if ttype == T_DIGITS0 and ref is not None and ref[0] == T_DIGITS0 \
            and len(ref[1]) == len(text):
        delta = int(text) - int(ref[1])
        if 0 <= delta <= 255:
            s.put(pos, T_TYPE, bytes([T_DDELTA0]))
            s.put(pos, T_DDELTA0, bytes([delta]))
            return
    s.put(pos, T_TYPE, bytes([ttype]))
    if ttype == T_ALPHA:
        s.put(pos, T_ALPHA, text + b"\0")
    elif ttype == T_CHAR:
        s.put(pos, T_CHAR, text)
    elif ttype == T_DIGITS:
        s.put(pos, T_DIGITS, struct.pack("<I", int(text)))
    elif ttype == T_DIGITS0:
        s.put(pos, T_DIGITS0, struct.pack("<I", int(text)))
        s.put(pos, T_DZLEN, bytes([len(text)]))
    else:                                          # pragma: no cover
        raise Tok3Error(f"internal: unexpected token type {ttype}")


def _compress_stream(data: bytes) -> bytes:
    """Smallest of order-0 / order-1 Nx16 (both auto-fall back to CAT for
    tiny inputs)."""
    enc = rans_nx16_encode(data, 0)
    if len(data) >= 64:
        enc1 = rans_nx16_encode(data, NX16_ORDER1)
        if len(enc1) < len(enc):
            enc = enc1
    return enc


def tok3_encode(payload: bytes) -> bytes:
    """Compress a '\\0'- or '\\n'-separated name block.

    The payload must be a sequence of names each followed by the
    separator (the exact shape of a CRAM RN external block, see
    cram_encode.py::_RN_STOP) — anything else raises Tok3Error and the
    block writer falls back to a general codec."""
    if not payload:
        raise Tok3Error("empty name block")
    sep = payload[-1]
    if sep not in (0x00, 0x0A):
        raise Tok3Error("name block does not end with a separator")
    names = payload.split(bytes([sep]))
    if names[-1] != b"":
        raise Tok3Error("trailing bytes after the last separator")
    names = names[:-1]
    if any(len(n) == 0 for n in names):
        raise Tok3Error("empty name in block")
    if sep != 0x00 and any(b"\0" in n for n in names):
        # ALPHA data streams are NUL-terminated; a NUL inside a name
        # cannot be represented — let the caller fall back
        raise Tok3Error("name contains a NUL byte")

    s = _Streams()
    prev_tokens: List[List[Tuple[int, bytes]]] = []
    last_seen: Dict[bytes, int] = {}
    for i, name in enumerate(names):
        dup = last_seen.get(name)
        if dup is not None:
            s.put(0, T_TYPE, bytes([T_DUP]))
            s.put(0, T_DUP, struct.pack("<I", i - dup))
            prev_tokens.append(prev_tokens[dup])
        else:
            dist = 1 if i > 0 else 0
            s.put(0, T_TYPE, bytes([T_DIFF]))
            s.put(0, T_DIFF, struct.pack("<I", dist))
            toks = _tokenize(name)
            ref = prev_tokens[i - dist] if dist else []
            for pos, tok in enumerate(toks, start=1):
                rtok = ref[pos - 1] if pos - 1 < len(ref) else None
                _encode_token(s, pos, tok, rtok)
            s.put(len(toks) + 1, T_TYPE, bytes([T_END]))
            prev_tokens.append(toks)
        last_seen[name] = i

    flags = F_NEWLINE_SEP if sep == 0x0A else 0
    out = bytearray(struct.pack("<II", len(payload), len(names)))
    out.append(flags)
    for pos in range(s.max_pos + 1):
        first = True
        for ttype in range(16):
            stream = s.b.get((pos, ttype))
            if stream is None:
                continue
            out.append(ttype | (_D_NEW_POS if first and pos > 0 else 0))
            first = False
            comp = _compress_stream(bytes(stream))
            out += var_put_u32(len(comp))
            out += comp
    return bytes(out)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

class _Cursor:
    __slots__ = ("data", "pos")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise Tok3Error("token stream exhausted")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def take_cstr(self) -> bytes:
        end = self.data.find(b"\0", self.pos)
        if end < 0:
            raise Tok3Error("unterminated ALPHA token")
        out = self.data[self.pos:end]
        self.pos = end + 1
        return out


def tok3_decode(payload: bytes, rsize: Optional[int] = None) -> bytes:
    """Decompress a tok3 name block back to its exact original bytes."""
    with normalize_truncation("tok3"):
        return _tok3_decode(payload, rsize)


def _tok3_decode(payload: bytes, rsize: Optional[int]) -> bytes:
    if len(payload) < 9:
        raise Tok3Error("tok3 payload shorter than its 9-byte header")
    ulen, nnames = struct.unpack_from("<II", payload, 0)
    flags = payload[8]
    if flags & F_ARITH:
        raise Tok3Error(
            "tok3 stream uses the adaptive arithmetic coder, which is "
            "not supported — re-encode with rANS (use_arith=0)")
    sep = b"\n" if flags & F_NEWLINE_SEP else b"\0"
    if rsize is not None and rsize != ulen:
        raise Tok3Error(f"tok3 header says {ulen} bytes, "
                        f"block header says {rsize}")

    streams: Dict[Tuple[int, int], _Cursor] = {}
    i, pos = 9, 0
    while i < len(payload):
        desc = payload[i]
        i += 1
        if desc & _D_DUP_STREAM:
            raise Tok3Error(
                "tok3 duplicate-stream frames are not supported (never "
                "produced by this encoder; layout unverified)")
        if desc & _D_NEW_POS:
            pos += 1
        ttype = desc & 0x0F
        clen, i = var_get_u32(payload, i)
        if i + clen > len(payload):
            raise Tok3Error("truncated tok3 stream frame")
        streams[(pos, ttype)] = _Cursor(
            rans_nx16_decode(payload[i:i + clen]))
        i += clen

    def cur(p: int, t: int) -> _Cursor:
        c = streams.get((p, t))
        if c is None:
            raise Tok3Error(f"missing tok3 stream (pos {p}, type {t})")
        return c

    names: List[bytes] = []
    out = bytearray()
    for _ in range(nnames):
        sel = cur(0, T_TYPE).take(1)[0]
        if sel == T_DUP:
            (dist,) = struct.unpack("<I", cur(0, T_DUP).take(4))
            if not 0 < dist <= len(names):
                raise Tok3Error(f"DUP distance {dist} out of range")
            name = names[len(names) - dist]
        elif sel == T_DIFF:
            (dist,) = struct.unpack("<I", cur(0, T_DIFF).take(4))
            if dist > len(names):
                raise Tok3Error(f"DIFF distance {dist} out of range")
            ref = (_tokenize(names[len(names) - dist]) if dist else [])
            parts: List[bytes] = []
            p = 1
            while True:
                t = cur(p, T_TYPE).take(1)[0]
                if t == T_END:
                    break
                if t == T_NOP:
                    p += 1
                    continue
                rtok = ref[p - 1] if p - 1 < len(ref) else None
                if t == T_MATCH:
                    if rtok is None:
                        raise Tok3Error("MATCH token without a reference")
                    parts.append(rtok[1])
                elif t == T_ALPHA:
                    parts.append(cur(p, T_ALPHA).take_cstr())
                elif t == T_CHAR:
                    parts.append(cur(p, T_CHAR).take(1))
                elif t == T_DIGITS:
                    (v,) = struct.unpack("<I", cur(p, T_DIGITS).take(4))
                    parts.append(b"%d" % v)
                elif t == T_DIGITS0:
                    (v,) = struct.unpack("<I", cur(p, T_DIGITS0).take(4))
                    w = cur(p, T_DZLEN).take(1)[0]
                    parts.append(b"%0*d" % (w, v))
                elif t == T_DDELTA:
                    if rtok is None or rtok[0] != T_DIGITS:
                        raise Tok3Error("DDELTA without a DIGITS reference")
                    d = cur(p, T_DDELTA).take(1)[0]
                    parts.append(b"%d" % (int(rtok[1]) + d))
                elif t == T_DDELTA0:
                    if rtok is None or rtok[0] != T_DIGITS0:
                        raise Tok3Error(
                            "DDELTA0 without a DIGITS0 reference")
                    d = cur(p, T_DDELTA0).take(1)[0]
                    parts.append(b"%0*d" % (len(rtok[1]),
                                            int(rtok[1]) + d))
                else:
                    raise Tok3Error(f"unknown tok3 token type {t}")
                p += 1
            name = b"".join(parts)
        else:
            raise Tok3Error(f"name selector {sel} is neither DUP nor DIFF")
        names.append(name)
        out += name + sep
    if len(out) != ulen:
        raise Tok3Error(f"tok3 decoded {len(out)} bytes, header says {ulen}")
    return bytes(out)
