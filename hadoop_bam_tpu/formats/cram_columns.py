"""Vectorized (columnar) CRAM slice decode: arrays out, no record objects.

The stats/tensor path needs columns — flags, positions, lengths, one
concatenated seq/qual byte run — not ``CramRecord`` objects.  This module
decodes a whole slice into exactly those columns with NumPy batch ops:

* every fixed int series arrives predecoded by
  ``cram_decode._predecode_fixed`` (native batch ITF8);
* the payload series (QS/BA/BS, the BB/QQ/IN/SC arrays, DL/RS/PD/HC)
  are consumed by *computed offsets* instead of sequential cursors: the
  byte order of each EXTERNAL stream is a pure function of the predecoded
  BF/CF/RL/FN/FC columns, so one pass of cumsums yields every record's
  slice of every stream;
* seq/qual reconstruction (gap fill from the reference, feature overlay)
  is NumPy scatter/gather over flat base arrays instead of the
  per-record/per-base loop in ``cram_decode._decode_mapped``.

Eligibility mirrors the htslib-default layout the predecode already
requires (external or constant series, exclusive content ids, core block
unused).  Anything else — shared blocks, core-bit codecs, malformed
geometry (overlapping features, out-of-range positions) — returns None
and the caller falls back to the record-serial path, which reproduces
the exact reference error behavior.  Parity between both paths is pinned
by tests/test_cram_columns.py.

Reference-side equivalent: the htsjdk CRAM slice decode reached from
hb/CRAMInputFormat.java (SURVEY.md section 2.3); the columnar design is
the TPU-shaped replacement for its per-record object assembly.
"""
from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from hadoop_bam_tpu.formats.cram_decode import (
    ByteArrayLenEncoding, ByteArrayStopEncoding, CF_DETACHED,
    CF_QUAL_STORED, CF_UNKNOWN_BASES, CompressionHeader, CRAMError,
    ExternalEncoding, HuffmanEncoding, NullEncoding, ReferenceSource,
    SliceHeader, _EmbeddedReference, _predecode_fixed, _BASES,
)

_ARRAY_FEATURE_SERIES = {0x62: "BB", 0x71: "QQ", 0x49: "IN", 0x53: "SC"}
_INT_FEATURE_SERIES = {0x44: "DL", 0x4E: "RS", 0x50: "PD", 0x48: "HC"}
_KNOWN_CODES = (frozenset(_ARRAY_FEATURE_SERIES)
                | frozenset(_INT_FEATURE_SERIES)
                | frozenset(b"XBiQ"))

# read-consuming codes and their length source: arrays consume len(val),
# X/B/i consume 1, everything else consumes 0 read bases
_ONE_BASE_CODES = frozenset(b"XBi")


class _Ineligible(Exception):
    """Slice cannot take the columnar path; caller falls back."""


def _core_free(enc) -> bool:
    if isinstance(enc, (ExternalEncoding, ByteArrayStopEncoding,
                        NullEncoding)):
        return True
    if isinstance(enc, HuffmanEncoding):
        return enc._const is not None        # 0-bit constant reads no core
    if isinstance(enc, ByteArrayLenEncoding):
        return (_core_free(enc.len_encoding)
                and _core_free(enc.val_encoding))
    return False


def _ragged_targets(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    """Concatenated [start_i, start_i+len_i) index runs (the scatter and
    gather workhorse for every ragged copy below).  Built with one
    cumsum over the output instead of repeat+arange temporaries: the
    output is +1 steps everywhere except at run boundaries, where it
    jumps to the next start."""
    lens = lens.astype(np.int64)
    nz = lens > 0
    if not bool(nz.any()):
        return np.empty(0, np.int64)
    starts = starts.astype(np.int64)[nz]
    lens = lens[nz]
    total = int(lens.sum())
    out = np.ones(total, np.int64)
    firsts = np.cumsum(lens) - lens
    out[0] = starts[0]
    if starts.size > 1:
        out[firsts[1:]] = starts[1:] - (starts[:-1] + lens[:-1]) + 1
    return np.cumsum(out)


def _ragged_copy(dst: np.ndarray, starts: np.ndarray, lens: np.ndarray,
                 vals: np.ndarray) -> None:
    """dst[start_i:start_i+len_i] = next len_i vals, in run order — with a
    straight memcpy when the runs tile dst contiguously in order (the
    overwhelmingly common slice layout)."""
    lens = lens.astype(np.int64)
    ecs = np.cumsum(lens) - lens
    if vals.size == dst.size and np.array_equal(starts, ecs):
        dst[:] = vals
        return
    dst[_ragged_targets(starts, lens)] = vals


def _ragged_gather(src: np.ndarray, starts: np.ndarray, lens: np.ndarray
                   ) -> np.ndarray:
    """Concatenation of src[start_i:start_i+len_i] runs, with a zero-copy
    slice when the runs are contiguous in order from offset 0."""
    lens = lens.astype(np.int64)
    total = int(lens.sum())
    ecs = np.cumsum(lens) - lens
    if np.array_equal(starts, ecs):
        return src[:total]
    return src[_ragged_targets(starts, lens)]


def _seg_exclusive_cumsum(vals: np.ndarray, seg_firsts: np.ndarray,
                          seg_lens: np.ndarray) -> np.ndarray:
    """Per-segment exclusive cumsum of ``vals`` (segments given by their
    first flat index and length, covering vals exactly, in order)."""
    ecs = np.cumsum(vals, dtype=np.int64) - vals
    if ecs.size == 0:
        return ecs
    base = ecs[seg_firsts]
    return ecs - np.repeat(base, seg_lens)


class _Bulk:
    """Computed-offset access to one slice's EXTERNAL payload streams."""

    def __init__(self, comp: CompressionHeader, external: Dict[int, bytes],
                 cid_users: Dict[int, int]):
        self.comp = comp
        self.external = external
        self.cid_users = cid_users

    def _exclusive_block(self, enc: ExternalEncoding) -> bytes:
        cid = enc.content_id
        if self.cid_users.get(cid, 0) != 1 or cid not in self.external:
            raise _Ineligible(f"content id {cid} shared or missing")
        return self.external[cid]

    def _series(self, name: str):
        enc = self.comp.data_series.get(name)
        if enc is None:
            raise _Ineligible(f"series {name} absent")
        return enc

    def ints(self, name: str, count: int) -> np.ndarray:
        """count ITF8 ints of one series, in stream order."""
        if count == 0:
            return np.zeros(0, np.int64)
        enc = self._series(name)
        if isinstance(enc, HuffmanEncoding) and enc._const is not None:
            return np.full(count, enc._const, np.int64)
        if isinstance(enc, ExternalEncoding):
            from hadoop_bam_tpu.utils import native
            if not native.available():
                raise _Ineligible("native ITF8 batch decoder unavailable")
            block = self._exclusive_block(enc)
            try:
                vals, _ = native.itf8_decode_batch(
                    np.frombuffer(block, np.uint8), count)
            except ValueError:
                raise _Ineligible("ITF8 stream truncated")
            return vals.astype(np.int64)
        raise _Ineligible(f"series {name}: unsupported encoding")

    def raw(self, name: str, count: int) -> np.ndarray:
        """count single raw bytes of one series (the decode_byte contract)."""
        if count == 0:
            return np.zeros(0, np.uint8)
        enc = self._series(name)
        if isinstance(enc, HuffmanEncoding) and enc._const is not None:
            return np.full(count, enc._const & 0xFF, np.uint8)
        if isinstance(enc, ExternalEncoding):
            block = self._exclusive_block(enc)
            if len(block) < count:
                raise _Ineligible("byte stream truncated")
            return np.frombuffer(block, np.uint8, count)
        raise _Ineligible(f"series {name}: unsupported encoding")

    def stream(self, name: str, total: int) -> np.ndarray:
        """The series' whole byte stream, of which ``total`` bytes will be
        consumed at computed offsets."""
        enc = self._series(name)
        if not isinstance(enc, ExternalEncoding):
            raise _Ineligible(f"series {name}: not a plain external stream")
        block = self._exclusive_block(enc)
        if len(block) < total:
            raise _Ineligible("byte stream truncated")
        return np.frombuffer(block, np.uint8)

    def arrays(self, name: str, count: int):
        """(lens int64[count], vals uint8[sum lens]) of one byte-array
        series, in stream order."""
        if count == 0:
            return np.zeros(0, np.int64), np.zeros(0, np.uint8)
        enc = self._series(name)
        if isinstance(enc, ByteArrayLenEncoding):
            le, ve = enc.len_encoding, enc.val_encoding
            if isinstance(le, HuffmanEncoding) and le._const is not None:
                lens = np.full(count, le._const, np.int64)
            elif isinstance(le, ExternalEncoding):
                from hadoop_bam_tpu.utils import native
                if not native.available():
                    raise _Ineligible("native ITF8 decoder unavailable")
                try:
                    vals32, _ = native.itf8_decode_batch(
                        np.frombuffer(self._exclusive_block(le), np.uint8),
                        count)
                except ValueError:
                    raise _Ineligible("array len stream truncated")
                lens = vals32.astype(np.int64)
            else:
                raise _Ineligible(f"{name}: unsupported len encoding")
            if lens.size and int(lens.min()) < 0:
                raise _Ineligible(f"{name}: negative array length")
            total = int(lens.sum())
            if not isinstance(ve, ExternalEncoding):
                raise _Ineligible(f"{name}: unsupported val encoding")
            block = self._exclusive_block(ve)
            if len(block) < total:
                raise _Ineligible(f"{name}: val stream truncated")
            return lens, np.frombuffer(block, np.uint8, total)
        if isinstance(enc, ByteArrayStopEncoding):
            block = self._exclusive_block(enc)
            stops = np.flatnonzero(
                np.frombuffer(block, np.uint8) == enc.stop)
            if stops.size < count:
                raise _Ineligible(f"{name}: stop byte not found")
            ends = stops[:count]
            starts = np.concatenate(([0], ends[:-1] + 1))
            lens = (ends - starts).astype(np.int64)
            arr = np.frombuffer(block, np.uint8)
            vals = arr[_ragged_targets(starts, lens)]
            return lens, vals
        raise _Ineligible(f"{name}: unsupported array encoding")


def decode_slice_columns(comp: CompressionHeader, slice_hdr: SliceHeader,
                         core: bytes, external: Dict[int, bytes],
                         ref_names: List[str],
                         ref_source: Optional[ReferenceSource] = None,
                         want_names: bool = False,
                         codec_rec_lens=None) -> Optional[dict]:
    """One slice as columns, or None when only the record path can decode it.

    Returns {n, bf, cf, ref_id, rl, pos, mapq, read_group, seq_cat,
    seq_lens, qual_cat, qual_lens[, name_cat, name_lens]}: int arrays are
    per-record; seq/qual are concatenated per-record byte runs whose
    lengths are ``seq_lens``/``qual_lens`` (0 encodes "*").  Output is
    byte-identical to assembling the same columns from
    ``decode_slice_records`` — tests/test_cram_columns.py pins this.
    """
    try:
        return _decode_columns(comp, slice_hdr, core, external, ref_names,
                               ref_source, want_names, codec_rec_lens)
    except _Ineligible:
        return None


def _decode_columns(comp, slice_hdr, core, external, ref_names, ref_source,
                    want_names, codec_rec_lens=None):
    pre = _predecode_fixed(comp, slice_hdr, external)
    if pre is None:
        raise _Ineligible("fixed series not batch-decodable")
    n = slice_hdr.n_records

    if slice_hdr.embedded_ref_id >= 0 and ref_source is None:
        ref_source = _EmbeddedReference(
            external[slice_hdr.embedded_ref_id], slice_hdr.start)

    # skipped series (names unless wanted, all tags) and decoded payload
    # series must never touch the CORE bit stream: only then is skipping
    # or offset-computed consumption equivalent to cursor consumption
    for key, enc in comp.tag_encodings.items():
        if not _core_free(enc):
            raise _Ineligible("tag encoding reads core bits")
    rn = comp.data_series.get("RN")
    if rn is not None and not _core_free(rn):
        raise _Ineligible("RN reads core bits")
    for name in ("QS", "BA", "BS", "BB", "QQ", "IN", "SC"):
        enc = comp.data_series.get(name)
        if enc is not None and not _core_free(enc):
            raise _Ineligible(f"{name} reads core bits")

    bf, cf = pre["BF"].astype(np.int64), pre["CF"].astype(np.int64)
    rl = pre["RL"].astype(np.int64)
    if rl.size and int(rl.min()) < 0:
        raise _Ineligible("negative read length")
    pos = pre["POS"].astype(np.int64)
    rg = pre["RG"].astype(np.int64)
    ri = pre.get("RI")
    ref_id = (ri.astype(np.int64) if ri is not None
              else np.full(n, slice_hdr.ref_seq_id, np.int64))

    mapped = (bf & 0x4) == 0
    mapped_idx = np.flatnonzero(mapped)
    unmapped_idx = np.flatnonzero(~mapped)
    fn = pre["FN"].astype(np.int64)          # per mapped record
    total_fn = int(fn.sum())
    if total_fn and "FC" not in pre:
        raise _Ineligible("feature streams not batch-decodable")
    fc = (pre["FC"].astype(np.uint8) if total_fn
          else np.zeros(0, np.uint8))
    fp = (pre["FP"].astype(np.int64) if total_fn
          else np.zeros(0, np.int64))

    mapq = np.zeros(n, np.int64)
    if mapped_idx.size:
        mapq[mapped_idx] = pre["MQ"].astype(np.int64)

    unknown = set(int(c) for c in np.unique(fc)) - set(_KNOWN_CODES)
    if unknown:
        raise CRAMError(
            f"unknown feature code {chr(sorted(unknown)[0])!r}")

    bulk = _Bulk(comp, external, _cid_user_counts(comp))

    # ---- per-feature geometry -------------------------------------------
    rec_of_feat = np.repeat(mapped_idx, fn)          # sorted ascending
    seg_firsts = (np.cumsum(fn) - fn)[fn > 0]
    seg_lens = fn[fn > 0]
    fpos = _seg_exclusive_cumsum(fp, seg_firsts, seg_lens) + fp  # inclusive

    masks = {c: fc == c for c in
             (0x62, 0x71, 0x49, 0x53, 0x58, 0x42, 0x69, 0x51,
              0x44, 0x4E, 0x50, 0x48)}

    arr_lens = {}
    arr_vals = {}
    for code, series in _ARRAY_FEATURE_SERIES.items():
        cnt = int(masks[code].sum())
        arr_lens[code], arr_vals[code] = bulk.arrays(series, cnt)
    int_vals = {}
    for code, series in _INT_FEATURE_SERIES.items():
        cnt = int(masks[code].sum())
        int_vals[code] = bulk.ints(series, cnt)
        if code in (0x44, 0x4E) and int_vals[code].size \
                and int(int_vals[code].min()) < 0:
            raise _Ineligible("negative deletion/skip length")

    # read-consumed length of every feature
    read_len = np.zeros(total_fn, np.int64)
    for code in _ARRAY_FEATURE_SERIES:
        if code != 0x71:                    # 'q' consumes no read bases
            read_len[masks[code]] = arr_lens[code]
    for code in _ONE_BASE_CODES:
        read_len[masks[code]] = 1
    # ref-consumed length of every feature
    ref_len = np.zeros(total_fn, np.int64)
    ref_len[masks[0x62]] = arr_lens[0x62]            # 'b'
    ref_len[masks[0x58]] = 1                         # 'X'
    ref_len[masks[0x42]] = 1                         # 'B'
    ref_len[masks[0x44]] = int_vals[0x44]            # 'D'
    ref_len[masks[0x4E]] = int_vals[0x4E]            # 'N'

    # gaps between features (match runs filled from the reference)
    prev_end = np.empty(total_fn, np.int64)
    if total_fn:
        prev_end[0] = 1
        prev_end[1:] = fpos[:-1] + read_len[:-1]
        prev_end[seg_firsts] = 1
    gap = fpos - prev_end
    if total_fn and int(gap.min()) < 0:
        raise _Ineligible("overlapping features")
    rl_mapped = rl[mapped_idx]
    # coverage is contiguous from read position 1 (gaps close the holes),
    # so covered = end of the last feature
    covered = np.zeros(mapped_idx.size, np.int64)
    if total_fn:
        seg_last = seg_firsts + seg_lens - 1
        covered[fn > 0] = fpos[seg_last] + read_len[seg_last] - 1
    tail = rl_mapped - covered
    if tail.size and int(tail.min()) < 0:
        raise _Ineligible("features overrun read length")
    # per-base write positions must stay inside the record
    if total_fn:
        ends = fpos - 1 + np.maximum(read_len, 1)
        if int((ends - np.repeat(rl_mapped, fn)).max(initial=0)) > 0 \
                or int(fpos.min()) < 1:
            raise _Ineligible("feature position outside read")
        qmask = masks[0x71]
        if qmask.any():
            # 'q' writes arr_lens qual bytes from fpos-1
            qends = fpos[qmask] - 1 + arr_lens[0x71]
            if int((qends - np.repeat(rl_mapped, fn)[qmask]).max(
                    initial=0)) > 0:
                raise _Ineligible("qual feature outside read")

    # ---- QS / BA stream layout ------------------------------------------
    qual_stored = (cf & CF_QUAL_STORED) != 0
    qs_feat = masks[0x42] | masks[0x51]              # 'B', 'Q'
    qs_feat_per_rec = np.bincount(rec_of_feat[qs_feat], minlength=n)
    qs_per_rec = qs_feat_per_rec + rl * qual_stored
    qs_rec_start = np.cumsum(qs_per_rec) - qs_per_rec
    qs_total = int(qs_per_rec.sum())
    qs_stream = (bulk.stream("QS", qs_total) if qs_total
                 else np.zeros(0, np.uint8))

    # fqzcomp desync tripwire — shared with the record path
    if codec_rec_lens:
        from hadoop_bam_tpu.formats.cram_decode import check_fqz_rec_lens
        check_fqz_rec_lens(
            comp, codec_rec_lens,
            [int(v) for v in qs_per_rec[qs_per_rec > 0]],
            qs_feat_bytes=int(qs_feat.sum()) if total_fn else 0)

    ba_feat = masks[0x42] | masks[0x69]              # 'B', 'i'
    ba_feat_per_rec = np.bincount(rec_of_feat[ba_feat], minlength=n)
    ba_per_rec = ba_feat_per_rec + rl * ~mapped
    ba_rec_start = np.cumsum(ba_per_rec) - ba_per_rec
    ba_total = int(ba_per_rec.sum())
    ba_stream = (bulk.stream("BA", ba_total) if ba_total
                 else np.zeros(0, np.uint8))

    def _stream_offsets(mask: np.ndarray, rec_start: np.ndarray
                        ) -> np.ndarray:
        """Stream offset of each masked feature: record base + rank among
        this record's masked features (features are already in stream
        order, so rank = index - first index of the record's run)."""
        sub = rec_of_feat[mask]
        if sub.size == 0:
            return np.zeros(0, np.int64)
        rank = np.arange(sub.size, dtype=np.int64) \
            - np.searchsorted(sub, sub, side="left")
        return rec_start[sub] + rank

    qs_feat_off = _stream_offsets(qs_feat, qs_rec_start)
    ba_feat_off = _stream_offsets(ba_feat, ba_rec_start)

    # ---- seq assembly ----------------------------------------------------
    seq_starts = np.cumsum(rl) - rl
    total_bases = int(rl.sum())
    seq_flat = np.full(total_bases, ord("?"), np.uint8)

    # unmapped records: BA block verbatim
    if unmapped_idx.size:
        vals = _ragged_gather(ba_stream,
                              ba_rec_start[unmapped_idx]
                              + ba_feat_per_rec[unmapped_idx],
                              rl[unmapped_idx])
        _ragged_copy(seq_flat, seq_starts[unmapped_idx],
                     rl[unmapped_idx], vals)

    # reference fill for gaps/tails + 'X' substitution bases
    unknown_bases = (cf & CF_UNKNOWN_BASES) != 0
    _fill_reference(
        seq_flat, seq_starts, comp, slice_hdr, ref_names, ref_source,
        mapped_idx, rl_mapped, pos, ref_id, unknown_bases,
        fn, seg_firsts, seg_lens, rec_of_feat, fpos, gap, read_len,
        ref_len, tail, masks, bulk)

    # feature payload overlay (after ref fill, matching loop order)
    for code in (0x62, 0x49, 0x53):                  # 'b', 'I', 'S'
        m = masks[code]
        if not m.any():
            continue
        _ragged_copy(seq_flat, seq_starts[rec_of_feat[m]] + fpos[m] - 1,
                     arr_lens[code], arr_vals[code])
    for code in (0x42, 0x69):                         # 'B'/'i': base ← BA
        m = masks[code]
        if m.any():
            seq_flat[seq_starts[rec_of_feat[m]] + fpos[m] - 1] = \
                ba_stream[_mask_pick(ba_feat, m, ba_feat_off)]

    # ---- qual assembly ---------------------------------------------------
    qual_lens = rl * qual_stored
    qual_starts = np.cumsum(qual_lens) - qual_lens
    total_quals = int(qual_lens.sum())
    qual_flat = np.empty(total_quals, np.uint8)
    stored_idx = np.flatnonzero(qual_stored)
    if stored_idx.size:
        vals = _ragged_gather(qs_stream,
                              qs_rec_start[stored_idx]
                              + qs_feat_per_rec[stored_idx],
                              rl[stored_idx])
        _ragged_copy(qual_flat, qual_starts[stored_idx], rl[stored_idx],
                     vals)
    # overlays: only records with stored quals surface a qual column, so
    # scatter only into those segments.  Overlay writes CAN collide (a
    # 'Q' then an overlapping zero-advance 'q'), and the record path
    # resolves collisions by feature order — so all overlay writes are
    # merged and applied in one feature-order-stable scatter (NumPy
    # fancy assignment is last-write-wins in index order).
    feat_stored = (qual_stored[rec_of_feat] if total_fn
                   else np.zeros(0, bool))
    ov_fidx, ov_dst, ov_val = [], [], []
    m = masks[0x71] & feat_stored                     # 'q' from QQ
    if m.any():
        qq_sel = m[masks[0x71]]          # aligned with the QQ arrays
        qq_lens = arr_lens[0x71]
        qq_starts = np.cumsum(qq_lens) - qq_lens
        ov_fidx.append(np.repeat(np.flatnonzero(m), qq_lens[qq_sel]))
        ov_dst.append(_ragged_targets(
            qual_starts[rec_of_feat[m]] + fpos[m] - 1, qq_lens[qq_sel]))
        ov_val.append(arr_vals[0x71][
            _ragged_targets(qq_starts[qq_sel], qq_lens[qq_sel])])
    for code in (0x51, 0x42):                         # 'Q'/'B' from QS
        m = masks[code] & feat_stored
        if m.any():
            ov_fidx.append(np.flatnonzero(m))
            ov_dst.append(qual_starts[rec_of_feat[m]] + fpos[m] - 1)
            ov_val.append(qs_stream[_mask_pick(qs_feat, m, qs_feat_off)])
    if ov_fidx:
        fidx = np.concatenate(ov_fidx)
        dst = np.concatenate(ov_dst)
        val = np.concatenate(ov_val)
        o = np.argsort(fidx, kind="stable")
        qual_flat[dst[o]] = val[o]

    # ---- output compaction ----------------------------------------------
    seq_lens = rl.copy()
    # CF_UNKNOWN_BASES yields seq='*' for MAPPED records only (the record
    # path's unmapped branch keeps the BA bases regardless of the flag)
    drop = (unknown_bases & mapped) | (rl == 0)
    seq_lens[drop] = 0
    if drop.any():
        keep_mask = np.repeat(~drop, rl)
        seq_cat = seq_flat[keep_mask].tobytes()
        # seq starts must be recomputed by the consumer from seq_lens
    else:
        seq_cat = seq_flat.tobytes()

    out = {
        "n": n, "bf": bf, "cf": cf, "ref_id": ref_id, "rl": rl,
        "pos": pos, "mapq": mapq, "read_group": rg,
        "seq_cat": seq_cat, "seq_lens": seq_lens,
        "qual_cat": qual_flat.tobytes(), "qual_lens": qual_lens,
    }
    if want_names:
        out.update(_decode_names(comp, bulk, n, cf))
    return out


def records_to_columns(records, want_names: bool = False) -> dict:
    """The same column dict built from decoded CramRecords — the fallback
    for slices the vectorized path declines, so span-level output is
    identical either way."""
    n = len(records)
    bf = np.fromiter((r.bf for r in records), np.int64, n)
    cf = np.fromiter((r.cf for r in records), np.int64, n)
    seqs = [r.seq if r.seq != "*" else "" for r in records]
    quals = [bytes(r.qual) if r.cf & CF_QUAL_STORED else b""
             for r in records]
    out = {
        "n": n, "bf": bf, "cf": cf,
        "ref_id": np.fromiter((r.ref_id for r in records), np.int64, n),
        "rl": np.fromiter((r.read_length for r in records), np.int64, n),
        "pos": np.fromiter((r.pos for r in records), np.int64, n),
        "mapq": np.fromiter(
            (r.mapq if not r.bf & 0x4 else 0 for r in records),
            np.int64, n),
        "read_group": np.fromiter((r.read_group for r in records),
                                  np.int64, n),
        "seq_cat": "".join(seqs).encode("latin-1"),
        "seq_lens": np.fromiter(map(len, seqs), np.int64, n),
        "qual_cat": b"".join(quals),
        "qual_lens": np.fromiter(map(len, quals), np.int64, n),
    }
    if want_names:
        out["name_cat"] = b"".join(r.name for r in records)
        out["name_lens"] = np.fromiter(
            (len(r.name) for r in records), np.int64, n)
    return out


def concat_columns(parts: List[dict]) -> dict:
    """Concatenate per-slice column dicts into one span-level dict."""
    if not parts:
        return {"n": 0,
                **{k: np.zeros(0, np.int64) for k in
                   ("bf", "cf", "ref_id", "rl", "pos", "mapq",
                    "read_group", "seq_lens", "qual_lens", "name_lens")},
                "seq_cat": b"", "qual_cat": b"", "name_cat": b""}
    if len(parts) == 1:
        return parts[0]
    out = {"n": sum(p["n"] for p in parts)}
    for k in parts[0]:
        if k == "n":
            continue
        v = parts[0][k]
        if isinstance(v, bytes):
            out[k] = b"".join(p[k] for p in parts)
        else:
            out[k] = np.concatenate([p[k] for p in parts])
    return out


def _mask_pick(superset_mask: np.ndarray, sub_mask: np.ndarray,
               offsets: np.ndarray) -> np.ndarray:
    """offsets is aligned with superset_mask's True positions; select the
    entries where sub_mask (a subset of superset_mask) is also True."""
    return offsets[sub_mask[superset_mask]]


def _cid_user_counts(comp: CompressionHeader) -> Dict[int, int]:
    from hadoop_bam_tpu.formats.cram_decode import _encoding_cids
    users: Dict[int, int] = {}
    for enc in list(comp.data_series.values()) \
            + list(comp.tag_encodings.values()):
        for cid in _encoding_cids(enc):
            users[cid] = users.get(cid, 0) + 1
    return users


def _decode_names(comp, bulk: _Bulk, n: int, cf: np.ndarray) -> dict:
    """RN column.  With read_names_included every record carries a name;
    otherwise only detached records do (the rest get generated names at
    the SAM layer, which the caller owns)."""
    if comp.read_names_included:
        cnt = n
        carriers = np.arange(n)
    else:
        carriers = np.flatnonzero((cf & CF_DETACHED) != 0)
        cnt = carriers.size
    lens, vals = bulk.arrays("RN", int(cnt))
    name_lens = np.zeros(n, np.int64)
    name_lens[carriers] = lens
    return {"name_cat": vals.tobytes(), "name_lens": name_lens}


def _fill_reference(seq_flat, seq_starts, comp, slice_hdr, ref_names,
                    ref_source, mapped_idx, rl_mapped, pos, ref_id,
                    unknown_bases, fn, seg_firsts, seg_lens, rec_of_feat,
                    fpos, gap, read_len, ref_len, tail, masks, bulk):
    """Fill match-run gaps/tails from the reference and apply 'X'
    substitutions — vectorized over all mapped records of the slice."""
    total_fn = rec_of_feat.size
    # cumulative ref offset consumed before each feature's gap starts
    adv = gap + ref_len
    ref_before_gap = _seg_exclusive_cumsum(adv, seg_firsts, seg_lens)
    # ref offset at the feature itself (its gap consumed)
    ref_at_feat = ref_before_gap + gap
    # per-record total ref consumed: fn==0 records are one whole-read match
    ref_consumed = np.zeros(mapped_idx.size, np.int64)
    if total_fn:
        seg_last = seg_firsts + seg_lens - 1
        ref_consumed[fn > 0] = (ref_before_gap + adv)[seg_last]
    ref_consumed += tail
    ref_consumed[fn == 0] = rl_mapped[fn == 0]

    x_mask = masks[0x58]
    need_gap = total_fn and bool((gap > 0).any())
    need_tail = bool((tail > 0).any())
    need_x = bool(x_mask.any())
    if not (need_gap or need_tail or need_x):
        return

    unk_mapped = unknown_bases[mapped_idx]
    # map each feature to its position on the mapped-record axis
    feat_mpos = (np.searchsorted(mapped_idx, rec_of_feat) if total_fn
                 else np.zeros(0, np.int64))

    if ref_source is None:
        # CF_UNKNOWN_BASES records surface seq='*' anyway; any other
        # record needing reference bases must go down the record path,
        # which raises the canonical CRAMError
        per_rec_need = tail > 0
        if total_fn:
            per_rec_need = per_rec_need.copy()
            per_rec_need[feat_mpos[gap > 0]] = True
            per_rec_need[feat_mpos[x_mask]] = True
        if bool((per_rec_need & ~unk_mapped).any()):
            raise _Ineligible("reference required but not provided")
        if need_x:
            # every remaining X feature sits on a CF_UNKNOWN_BASES
            # record; the record path still decodes its BS code and
            # substitutes against the 'N' placeholder row — a malformed
            # code must raise CRAMError identically here, not vanish
            # with the dropped seq
            codes = bulk.raw("BS", int(x_mask.sum()))
            _substitute_vec(comp.substitution_matrix,
                            np.full(codes.size, ord("N"), np.uint8),
                            codes)
        return

    pos_mapped = pos[mapped_idx]
    rid_mapped = ref_id[mapped_idx]
    take = ~unk_mapped & (ref_consumed > 0)
    bs_codes = (bulk.raw("BS", int(x_mask.sum())) if need_x
                else np.zeros(0, np.uint8))
    if need_x:
        # X features on CF_UNKNOWN_BASES-skipped records never reach the
        # per-reference substitution below, but the record path decodes
        # and validates their BS codes against the 'N' placeholder row
        # (their seq is discarded as '*', so it never fetches reference
        # bases for them either); malformed codes must raise CRAMError
        # identically here
        unk_codes = bs_codes[(unk_mapped[feat_mpos] & x_mask)[x_mask]]
        if unk_codes.size:
            _substitute_vec(comp.substitution_matrix,
                            np.full(unk_codes.size, ord("N"), np.uint8),
                            unk_codes)
    for rid in np.unique(rid_mapped[take]):
        sel = take & (rid_mapped == rid)
        name = ref_names[rid] if 0 <= rid < len(ref_names) else "*"
        lo = int(pos_mapped[sel].min())
        hi = int((pos_mapped[sel] + ref_consumed[sel]).max())
        if hi - lo > (1 << 31):
            raise _Ineligible("reference window too large")
        chunk = ref_source.get(name, lo, hi - lo)
        ref_arr = np.frombuffer(chunk.encode("latin-1"), np.uint8)
        base_of_rec = pos_mapped - lo        # junk outside sel, never used
        sel_feat = sel[feat_mpos] if total_fn else np.zeros(0, bool)

        def gather(ref_offs, dst_idx):
            if ref_offs.size == 0:
                return
            if bool(((ref_offs < 0) | (ref_offs >= ref_arr.size)).any()):
                raise _Ineligible("reference run out of range")
            seq_flat[dst_idx] = ref_arr[ref_offs]

        if need_gap:
            gm = (gap > 0) & sel_feat
            if bool(gm.any()):
                # the gap spans read positions [fpos-gap, fpos)
                dst = _ragged_targets(
                    seq_starts[rec_of_feat[gm]] + (fpos - gap)[gm] - 1,
                    gap[gm])
                roff = _ragged_targets(
                    base_of_rec[feat_mpos[gm]] + ref_before_gap[gm],
                    gap[gm])
                gather(roff, dst)
        if need_tail:
            tm = sel & (tail > 0)
            if bool(tm.any()):
                dst = _ragged_targets(
                    seq_starts[mapped_idx[tm]] + rl_mapped[tm] - tail[tm],
                    tail[tm])
                roff = _ragged_targets(
                    base_of_rec[tm] + ref_consumed[tm] - tail[tm],
                    tail[tm])
                gather(roff, dst)
        if need_x:
            xm = x_mask & sel_feat
            if bool(xm.any()):
                roff = base_of_rec[feat_mpos[xm]] + ref_at_feat[xm]
                if bool(((roff < 0) | (roff >= ref_arr.size)).any()):
                    raise _Ineligible("reference run out of range")
                seq_flat[seq_starts[rec_of_feat[xm]] + fpos[xm] - 1] = \
                    _substitute_vec(comp.substitution_matrix,
                                    ref_arr[roff], bs_codes[xm[x_mask]])


def _substitute_vec(matrix: bytes, ref_bases: np.ndarray,
                    codes: np.ndarray) -> np.ndarray:
    """Vectorized substitution-matrix application [SPEC section 10.6]."""
    # base byte -> row index (A/C/G/T/N, everything else N)
    row_of = np.full(256, 4, np.uint8)
    for i, b in enumerate(_BASES):
        row_of[ord(b)] = i
        row_of[ord(b.lower())] = i
    # table[row, code] -> substituted base byte; 0 marks a code the matrix
    # byte never produces (malformed), matching substitute_base's raise.
    # Reversed j so the FIRST matching j wins on duplicate codes, exactly
    # like the scalar loop.
    table = np.zeros((5, 4), np.uint8)
    for ri in range(5):
        byte = matrix[ri]
        candidates = [b for b in _BASES if b != _BASES[ri]]
        for j in range(3, -1, -1):
            code = (byte >> (6 - 2 * j)) & 3
            table[ri, code] = ord(candidates[j])
    if codes.size and int(codes.max(initial=0)) > 3:
        raise CRAMError("invalid substitution code")
    out = table[row_of[ref_bases], codes]
    if bool((out == 0).any()):
        raise CRAMError("invalid substitution code")
    return out
