"""Whole-file BCF read/write helpers (BGZF-wrapped or raw streams).

Host-side single-stream paths for BCF, mirroring formats/bamio.py: fixture
generation, golden tests, the CLI, and writers.  The scaled path (BCF span
planning + guesser) lives in split/.

Reference equivalents: htsjdk BCF2 reader/writer plumbing as used by
hb/BCFRecordReader.java and hb/BCFRecordWriter (SURVEY.md section 2.3/2.4).
BCF files come in two containers [SPEC]: BGZF-compressed (the default,
extension .bcf) and raw/uncompressed streams; both start with the
``BCF\\2\\2`` magic in the *inflated* byte stream.
"""
from __future__ import annotations

import io
import struct
from typing import Iterable, Iterator, List, Optional, Tuple, Union

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bcf import (
    BCFRecordCodec, decode_header, encode_header,
)
from hadoop_bam_tpu.formats.vcf import VCFHeader, VcfRecord
from hadoop_bam_tpu.formats.virtual_offset import make_voffset
from hadoop_bam_tpu.utils.seekable import as_byte_source


def is_bgzf_bcf(head: bytes) -> bool:
    return bgzf.is_bgzf(head)


class BcfWriter:
    """Streaming BCF writer (BGZF by default, raw with ``compress=False``).

    hb/BCFRecordWriter semantics: header emission and the BGZF EOF terminator
    are optional so headerless shards can be concatenated by the merger
    (hb/util/VCFFileMerger.java)."""

    def __init__(self, sink, header: VCFHeader, *, write_header: bool = True,
                 write_eof: bool = True, compress: bool = True,
                 level: int = 6, track_voffsets: bool = False):
        self._own = False
        if isinstance(sink, (str, bytes)):
            sink = open(sink, "wb")
            self._own = True
        self._sink = sink
        self.header = header
        self.codec = BCFRecordCodec(header)
        self._compress = compress
        self._voffsets: List[int] = []
        self._track = track_voffsets
        self.records_written = 0
        if compress:
            self._w = bgzf.BGZFWriter(sink, level=level, write_eof=write_eof)
        else:
            self._w = None
            self._raw_pos = 0
        if write_header:
            self._write_bytes(encode_header(header))

    def _write_bytes(self, data: bytes) -> None:
        if self._w is not None:
            self._w.write(data)
        else:
            self._sink.write(data)
            self._raw_pos += len(data)

    def tell_voffset(self) -> int:
        if self._w is not None:
            return self._w.tell_voffset()
        return self._raw_pos << 16

    def write_record(self, rec: VcfRecord) -> int:
        v = self.tell_voffset()
        if self._track:
            self._voffsets.append(v)
        self._write_bytes(self.codec.encode(rec))
        self.records_written += 1
        return v

    def record_voffsets(self) -> List[int]:
        return self._voffsets

    def close(self) -> None:
        if self._w is not None:
            self._w.close()
        if self._own:
            self._sink.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_bcf(path_or_sink, header: VCFHeader,
              records: Iterable[VcfRecord], **kw) -> None:
    with BcfWriter(path_or_sink, header, **kw) as w:
        for r in records:
            w.write_record(r)


def read_bcf_header(source) -> Tuple[VCFHeader, int, bool]:
    """Read the header of a BCF file (either container).

    Returns (header, first-record virtual offset, is_bgzf) — the BCF
    equivalent of hb/util/VCFHeaderReader.java.  For raw streams the
    "virtual offset" is ``byte_offset << 16`` (uoffset always 0)."""
    src = as_byte_source(source)
    head = src.pread(0, bgzf.MAX_BLOCK_SIZE)
    if bgzf.is_bgzf(head):
        r = bgzf.BGZFReader(src)
        size = 1 << 16
        while True:
            r.seek_voffset(0)
            buf = r.read(size)
            try:
                header, after = decode_header(buf, 0)
                break
            except Exception:
                if len(buf) < size:
                    raise
                size *= 4
        # plain inflated offset -> virtual offset (walk the blocks)
        coff, remaining = 0, after
        while True:
            bh = src.pread(coff, bgzf.MAX_BLOCK_SIZE)
            info = bgzf.parse_block_header(bh, 0)
            if remaining < info.isize or (remaining == info.isize
                                          and info.isize > 0):
                if remaining == info.isize:
                    return header, make_voffset(coff + info.block_size, 0), True
                return header, make_voffset(coff, remaining), True
            remaining -= info.isize
            coff += info.block_size
    else:
        buf = head
        off = 0
        while True:
            try:
                header, after = decode_header(buf, 0)
                return header, after << 16, False
            except Exception:
                more = src.pread(len(buf), 1 << 20)
                if not more:
                    raise
                buf += more


def read_bcf(source) -> Tuple[VCFHeader, List[VcfRecord]]:
    """Decode a whole BCF file into (header, records)."""
    src = as_byte_source(source)
    head = src.pread(0, bgzf.MAX_BLOCK_SIZE)
    if bgzf.is_bgzf(head):
        data = bgzf.BGZFReader(src).read_all_from(0)
    else:
        chunks = []
        off = 0
        while True:
            got = src.pread(off, 1 << 22)
            if not got:
                break
            chunks.append(got)
            off += len(got)
        data = b"".join(chunks)
    header, off = decode_header(data, 0)
    codec = BCFRecordCodec(header)
    records: List[VcfRecord] = []
    while off < len(data):
        rec, off = codec.decode(data, off)
        records.append(rec)
    return header, records
