"""CRAM 3.1 adaptive arithmetic codec (block compression method 6).

Rebuild of the "Adaptive arithmetic coding" codec from the CRAM 3.1
compression-codecs spec (hts-specs CRAMcodecs; upstream analog
htscodecs/arith_dynamic.c, reached from hb via htsjdk's CRAM 3.1 reader
per SURVEY.md §2.3).  The frame shares the rANS Nx16 transform container
— the same flag byte layout and PACK/RLE/STRIPE/CAT transforms — with
two differences [SPEC]:

* bit 0x04 means EXT (the payload is a bzip2 stream) instead of Nx16's
  X32 interleave;
* the entropy stage is the fqzcomp adaptive range coder + per-context
  ``SimpleModel`` frequencies (cram_fqzcomp.py) instead of static-table
  rANS: a ``max_sym`` byte (0 encodes 256), then order-0 (one model) or
  order-1 (one model per previous symbol) symbol coding.

Provenance, honestly labelled: the flag layout, EXT semantics and the
order-0/order-1 adaptive model structure follow the public spec; the
RLE run-model arrangement (runs through a 3-deep chain of 256-symbol
models with 255-extension, literals through the normal models) and the
PACK/STRIPE metadata bytes mirror this module's Nx16 sibling and are
[SPEC-recalled] — pinned by same-module round-trips (no htslib in the
image to cross-validate, SURVEY.md §0).  Decode is the supported
direction; encode exists to exercise decode.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from hadoop_bam_tpu.formats.cram import CRAMError
from hadoop_bam_tpu.formats.cram_codecs import normalize_truncation
from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
    RansError, _pack_decode, _pack_encode, _packed_size, var_get_u32,
    var_put_u32,
)
from hadoop_bam_tpu.formats.cram_fqzcomp import (
    RangeDecoder, RangeEncoder, SimpleModel,
)

# flag bits [SPEC] — Nx16 layout with 0x04 repurposed as EXT
ARITH_ORDER1 = 0x01
ARITH_EXT = 0x04
ARITH_STRIPE = 0x08
ARITH_NOSZ = 0x10
ARITH_CAT = 0x20
ARITH_RLE = 0x40
ARITH_PACK = 0x80

_RUN_CTXS = 3        # run-length model chain depth [SPEC-recalled]


class ArithError(RansError, CRAMError):
    """Malformed/desynced arith stream.  Also a ``CRAMError`` so container
    callers see the canonical corruption class (CorruptDataError via the
    ValueError fallback in classify_error either way)."""


# ---------------------------------------------------------------------------
# entropy stage
# ---------------------------------------------------------------------------

def _models(max_sym: int, order1: bool):
    if order1:
        return [SimpleModel(max_sym) for _ in range(max_sym)]
    return [SimpleModel(max_sym)]


def _decode_symbols(payload: bytes, pos: int, out_size: int,
                    order1: bool) -> Tuple[bytes, int]:
    max_sym = payload[pos]
    pos += 1
    if max_sym == 0:
        max_sym = 256
    models = _models(max_sym, order1)
    rc = RangeDecoder(payload, pos)
    out = bytearray(out_size)
    prev = 0
    for i in range(out_size):
        sym = models[prev if order1 else 0].decode(rc)
        out[i] = sym
        prev = sym
    return bytes(out), rc.pos


def _encode_symbols(data: bytes, order1: bool) -> bytes:
    max_sym = (max(data) + 1) if data else 1
    models = _models(max_sym, order1)
    rc = RangeEncoder()
    prev = 0
    for b in data:
        models[prev if order1 else 0].encode(rc, b)
        prev = b
    return bytes([max_sym & 0xFF]) + rc.finish()


def _decode_rle(payload: bytes, pos: int, out_size: int,
                order1: bool) -> Tuple[bytes, int]:
    """Literals through the normal models, run lengths through a chain of
    256-symbol models (255 extends the run) [SPEC-recalled]."""
    max_sym = payload[pos]
    pos += 1
    if max_sym == 0:
        max_sym = 256
    lit_models = _models(max_sym, order1)
    run_models = [SimpleModel(256) for _ in range(_RUN_CTXS)]
    rc = RangeDecoder(payload, pos)
    out = bytearray()
    prev = 0
    while len(out) < out_size:
        sym = lit_models[prev if order1 else 0].decode(rc)
        prev = sym
        run = 0
        ctx = 0
        while True:
            part = run_models[ctx].decode(rc)
            run += part
            if part != 255:
                break
            ctx = min(ctx + 1, _RUN_CTXS - 1)
        out += bytes([sym]) * (run + 1)
    if len(out) != out_size:
        raise ArithError(
            f"arith RLE expanded to {len(out)}, expected {out_size}")
    return bytes(out), rc.pos


def _encode_rle(data: bytes, order1: bool) -> bytes:
    max_sym = (max(data) + 1) if data else 1
    lit_models = _models(max_sym, order1)
    run_models = [SimpleModel(256) for _ in range(_RUN_CTXS)]
    rc = RangeEncoder()
    arr = np.frombuffer(data, np.uint8)
    starts = np.concatenate([[0], np.nonzero(np.diff(arr))[0] + 1]) \
        if arr.size else np.zeros(0, np.int64)
    lens = np.diff(np.concatenate([starts, [arr.size]])) if arr.size \
        else np.zeros(0, np.int64)
    prev = 0
    for s, ln in zip(arr[starts].tolist() if arr.size else [],
                     lens.tolist()):
        lit_models[prev if order1 else 0].encode(rc, s)
        prev = s
        run = ln - 1
        ctx = 0
        while True:
            part = min(run, 255)
            run_models[ctx].encode(rc, part)
            run -= part
            if part != 255:
                break
            ctx = min(ctx + 1, _RUN_CTXS - 1)
    return bytes([max_sym & 0xFF]) + rc.finish()


# ---------------------------------------------------------------------------
# public stream API (frame layout mirrors rans_nx16_*)
# ---------------------------------------------------------------------------

def arith_encode(data: bytes, flags: int = 0) -> bytes:
    """Encode with the requested flag set; PACK is dropped when it does
    not apply, tiny payloads fall back to CAT, STRIPE recurses into
    X=4 NOSZ sub-streams."""
    n = len(data)

    if flags & ARITH_STRIPE:
        X = 4
        out = bytearray([ARITH_STRIPE])
        out += var_put_u32(n)
        subs = [arith_encode(bytes(data[j::X]),
                             (flags & ~ARITH_STRIPE) | ARITH_NOSZ)
                for j in range(X)]
        out.append(X)
        for s in subs:
            out += var_put_u32(len(s))
        for s in subs:
            out += s
        return bytes(out)

    payload = data
    pack_meta = None
    if flags & ARITH_PACK:
        packed = _pack_encode(payload)
        if packed is None:
            flags &= ~ARITH_PACK
        else:
            pack_meta, payload = packed
    if len(payload) < 16 and not flags & ARITH_EXT:
        flags |= ARITH_CAT
        flags &= ~(ARITH_ORDER1 | ARITH_RLE)

    out = bytearray([flags])
    if not (flags & ARITH_NOSZ):
        out += var_put_u32(n)
    if flags & ARITH_PACK:
        out += pack_meta                     # nsym byte + symbol map
    if flags & ARITH_CAT:
        out += payload
    elif flags & ARITH_EXT:
        import bz2
        out += bz2.compress(payload)
    elif flags & ARITH_RLE:
        out += _encode_rle(payload, bool(flags & ARITH_ORDER1))
    else:
        out += _encode_symbols(payload, bool(flags & ARITH_ORDER1))
    return bytes(out)


def arith_decode(payload: bytes, out_size: Optional[int] = None) -> bytes:
    """Decode one adaptive-arithmetic stream.  ``out_size`` is required
    when the stream carries the NOSZ flag (the CRAM block header
    supplies it).

    Consistency tripwire: decode must consume EXACTLY the compressed
    extent.  The range coder reads lazily, so a desynced stream (model
    drift, trailing garbage, a truncated tail hidden by the decoder's
    zero-padding) can otherwise produce right-sized wrong bytes that
    only fail much later — or never.  The encoder/decoder renorm
    schedules mirror 1:1 (5-byte init vs 5-shift finish), so on a clean
    stream the final read position equals the payload length; anything
    else raises ``ArithError`` (a ``CRAMError``) at the block boundary.
    """
    with normalize_truncation("arith"):
        data, consumed = _arith_decode(payload, out_size)
        if consumed != len(payload):
            raise ArithError(
                f"arith stream desync: consumed {consumed} of "
                f"{len(payload)} compressed bytes")
        return data


def _arith_decode(payload: bytes, out_size: Optional[int] = None
                  ) -> Tuple[bytes, int]:
    """(decoded bytes, compressed bytes consumed)."""
    if not payload:
        raise ArithError("empty arith stream")
    pos = 0
    flags = payload[pos]
    pos += 1
    if not (flags & ARITH_NOSZ):
        out_size, pos = var_get_u32(payload, pos)
    if out_size is None:
        raise ArithError("NOSZ stream needs an external size")
    if out_size == 0 and pos == len(payload):
        # sizeless empty frame (no entropy stream follows); a non-empty
        # tail for out_size 0 still decodes below so the exact-extent
        # tripwire sees the true consumption
        return b"", pos

    if flags & ARITH_STRIPE:
        X = payload[pos]
        pos += 1
        clens = []
        for _ in range(X):
            c, pos = var_get_u32(payload, pos)
            clens.append(c)
        outs = []
        for j in range(X):
            sub_len = (out_size - j + X - 1) // X
            # each sub-stream is its own framed arith stream: the
            # public decoder applies the exact-extent tripwire to it
            outs.append(arith_decode(payload[pos:pos + clens[j]], sub_len))
            pos += clens[j]
        out = np.zeros(out_size, dtype=np.uint8)
        for j in range(X):
            out[j::X] = np.frombuffer(outs[j], dtype=np.uint8)
        return out.tobytes(), pos

    pack_syms = None
    if flags & ARITH_PACK:
        nsym = payload[pos]
        pos += 1
        pack_syms = payload[pos:pos + nsym]
        pos += nsym

    stage_size = (_packed_size(out_size, len(pack_syms))
                  if flags & ARITH_PACK else out_size)

    if flags & ARITH_CAT:
        stage = payload[pos:pos + stage_size]
        if len(stage) != stage_size:
            raise ArithError("truncated CAT payload")
        end = pos + stage_size
    elif flags & ARITH_EXT:
        import bz2
        d = bz2.BZ2Decompressor()
        try:
            stage = d.decompress(payload[pos:])
        except OSError as e:
            raise ArithError(f"bad EXT (bzip2) payload: {e}")
        if not d.eof:
            raise ArithError("truncated EXT (bzip2) payload")
        end = len(payload) - len(d.unused_data)
    elif flags & ARITH_RLE:
        stage, end = _decode_rle(payload, pos, stage_size,
                                 bool(flags & ARITH_ORDER1))
    else:
        stage, end = _decode_symbols(payload, pos, stage_size,
                                     bool(flags & ARITH_ORDER1))

    if flags & ARITH_PACK:
        stage = _pack_decode(stage, pack_syms, out_size)
    if len(stage) != out_size:
        raise ArithError(
            f"arith decoded {len(stage)} bytes, expected {out_size}")
    return stage, end
