"""Pure-spec format codecs (host reference implementations, NumPy-vectorized).

Everything in this package is implementable directly from the public
specifications (SAMv1/BGZF, VCFv4.x, BCF2, CRAM3, FASTQ/QSEQ conventions) —
tagged [SPEC] in SURVEY.md — and is therefore the contract layer of the
framework regardless of the reference snapshot.
"""
from hadoop_bam_tpu.formats.virtual_offset import (  # noqa: F401
    make_voffset,
    split_voffset,
    VirtualOffset,
)
