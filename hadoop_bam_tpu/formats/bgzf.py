"""BGZF block-compression layer.

[SPEC] SAMv1 section 4.1: BGZF is a series of concatenated gzip members, each
with an FEXTRA subfield ``SI1=66 ('B'), SI2=67 ('C'), SLEN=2`` whose payload is
``BSIZE`` (u16) = total block size minus one.  Each member's inflated payload
is at most 65536 bytes (0x10000); the file ends with a fixed 28-byte empty
block (the EOF terminator).  Because members are independent DEFLATE streams,
BGZF gives *position-invariant random access*: any block can be inflated
without its neighbors — the property both Hadoop-BAM's split machinery and our
TPU batch-inflate pipeline exploit (SURVEY.md section 5, long-context analog).

Reference equivalents: htsjdk ``BlockCompressedInputStream`` /
``BlockCompressedOutputStream`` (external dependency of the reference), plus
the scan logic of hb/BGZFSplitGuesser.java (rebuilt in
hadoop_bam_tpu/split/bgzf_guesser.py on top of this module's primitives).

This module is the host (NumPy + zlib) reference implementation; the batched
decode path lives in hadoop_bam_tpu/ops/inflate.py and the native C++
multithreaded inflate in native/.
"""
from __future__ import annotations

import struct
import zlib
from dataclasses import dataclass
from typing import Iterator, List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.utils.errors import CorruptDataError

# [SPEC] gzip member header: ID1 ID2 CM FLG, with FLG.FEXTRA set.
GZIP_MAGIC = b"\x1f\x8b\x08\x04"
# [SPEC] BGZF extra subfield identifiers.
BGZF_SI1 = 66   # 'B'
BGZF_SI2 = 67   # 'C'
BGZF_SLEN = 2
# [SPEC] fixed 12-byte BGZF header prefix through XLEN for blocks we *write*:
# magic, MTIME=0, XFL=0, OS=255(unknown), XLEN=6.
_BLOCK_HEADER_FMT = "<4sIBBH"  # magic, mtime, xfl, os, xlen
_XTRA_FMT = "<BBHH"            # SI1, SI2, SLEN, BSIZE
HEADER_SIZE = 18               # fixed header size for blocks with only the BC subfield
FOOTER_SIZE = 8                # CRC32 + ISIZE
MAX_BLOCK_SIZE = 0x10000       # max *compressed* total block size (65536)
MAX_UNCOMPRESSED = 0x10000     # max inflated payload per block
# Payload budget so that worst-case deflate expansion still fits MAX_BLOCK_SIZE.
# htsjdk uses 0xff00 for the same reason.
WRITE_PAYLOAD_SIZE = 0xFF00

# [SPEC] the 28-byte BGZF EOF terminator block (empty payload, fixed bytes).
EOF_BLOCK = bytes.fromhex(
    "1f8b08040000000000ff0600424302001b0003000000000000000000")


class BGZFError(CorruptDataError):
    """Malformed BGZF bytes — classified CORRUPT (still a ValueError for
    pre-taxonomy callers): re-reading the same bytes never heals it."""


@dataclass(frozen=True)
class BlockInfo:
    """Metadata of one BGZF block located in a file/buffer."""
    coffset: int        # compressed offset of the block start
    block_size: int     # total compressed size (BSIZE + 1)
    isize: int          # inflated payload length (from the block footer)
    cdata_offset: int   # offset of the DEFLATE payload within the file/buffer
    cdata_size: int     # DEFLATE payload length

    @property
    def next_coffset(self) -> int:
        return self.coffset + self.block_size

    @property
    def is_eof_block(self) -> bool:
        return self.isize == 0


def parse_block_header(buf: bytes, offset: int = 0) -> BlockInfo:
    """Parse one BGZF block header at ``offset`` (without inflating).

    Walks all FEXTRA subfields looking for the BC subfield [SPEC]; raises
    BGZFError if the bytes are not a BGZF block start.
    """
    if len(buf) - offset < HEADER_SIZE:
        raise BGZFError("truncated BGZF header")
    if buf[offset:offset + 4] != GZIP_MAGIC:
        raise BGZFError("not a BGZF block: bad gzip magic/flags")
    xlen = struct.unpack_from("<H", buf, offset + 10)[0]
    xtra_start = offset + 12
    xtra_end = xtra_start + xlen
    if len(buf) < xtra_end:
        raise BGZFError("truncated FEXTRA")
    bsize = None
    p = xtra_start
    while p + 4 <= xtra_end:
        si1, si2, slen = buf[p], buf[p + 1], struct.unpack_from("<H", buf, p + 2)[0]
        if si1 == BGZF_SI1 and si2 == BGZF_SI2 and slen == BGZF_SLEN:
            bsize = struct.unpack_from("<H", buf, p + 4)[0]
            break
        p += 4 + slen
    if bsize is None:
        raise BGZFError("gzip member without BGZF BC subfield")
    block_size = bsize + 1
    if block_size < xtra_end - offset + FOOTER_SIZE:
        raise BGZFError("BSIZE smaller than header+footer")
    if len(buf) - offset < block_size:
        raise BGZFError("truncated BGZF block body")
    isize = struct.unpack_from("<I", buf, offset + block_size - 4)[0]
    if isize > MAX_UNCOMPRESSED:
        raise BGZFError("ISIZE exceeds 64 KiB — not a valid BGZF block")
    cdata_offset = xtra_end
    cdata_size = block_size - (xtra_end - offset) - FOOTER_SIZE
    return BlockInfo(coffset=offset, block_size=block_size, isize=isize,
                     cdata_offset=cdata_offset, cdata_size=cdata_size)


def inflate_block(buf: bytes, info: Optional[BlockInfo] = None,
                  offset: int = 0, check_crc: bool = True) -> bytes:
    """Inflate one BGZF block; verifies CRC32 and ISIZE [SPEC] by default."""
    if info is None:
        info = parse_block_header(buf, offset)
    raw = bytes(buf[info.cdata_offset:info.cdata_offset + info.cdata_size])
    try:
        data = zlib.decompress(raw, wbits=-15)
    except zlib.error as e:
        raise BGZFError(f"corrupt DEFLATE payload at coffset "
                        f"{info.coffset}: {e}") from e
    if len(data) != info.isize:
        raise BGZFError(f"ISIZE mismatch: {len(data)} != {info.isize}")
    if check_crc:
        crc = struct.unpack_from("<I", buf, info.coffset + info.block_size - 8)[0]
        if zlib.crc32(data) & 0xFFFFFFFF != crc:
            raise BGZFError("BGZF block CRC32 mismatch")
    return data


def deflate_block(payload: bytes, level: int = 6) -> bytes:
    """Build one complete BGZF block around ``payload`` (≤ WRITE_PAYLOAD_SIZE)."""
    if len(payload) > MAX_UNCOMPRESSED:
        raise BGZFError("payload exceeds 64 KiB BGZF limit")
    cdata = None
    from hadoop_bam_tpu.utils import native
    if native.available():
        cdata = native.deflate_raw(payload, level)  # ~3x zlib (libdeflate)
    if cdata is None:
        co = zlib.compressobj(level, zlib.DEFLATED, -15)
        cdata = co.compress(payload) + co.flush()
    if HEADER_SIZE + len(cdata) + FOOTER_SIZE > MAX_BLOCK_SIZE:
        # Incompressible data at high payload sizes: store uncompressed.
        co = zlib.compressobj(0, zlib.DEFLATED, -15)
        cdata = co.compress(payload) + co.flush()
    block_size = HEADER_SIZE + len(cdata) + FOOTER_SIZE
    if block_size > MAX_BLOCK_SIZE:
        raise BGZFError("deflated block exceeds 64 KiB — reduce payload size")
    header = struct.pack(_BLOCK_HEADER_FMT, GZIP_MAGIC, 0, 0, 255, 6) + \
        struct.pack(_XTRA_FMT, BGZF_SI1, BGZF_SI2, BGZF_SLEN, block_size - 1)
    footer = struct.pack("<II", zlib.crc32(payload) & 0xFFFFFFFF, len(payload))
    return header + cdata + footer


def scan_blocks(buf: bytes, offset: int = 0, limit: Optional[int] = None) -> List[BlockInfo]:
    """Walk consecutive BGZF blocks from a known block start."""
    out: List[BlockInfo] = []
    end = len(buf) if limit is None else min(len(buf), limit)
    while offset < end:
        info = parse_block_header(buf, offset)
        out.append(info)
        offset = info.next_coffset
    return out


def find_block_starts_numpy(buf: np.ndarray, require_valid_bsize: bool = True
                            ) -> np.ndarray:
    """Vectorized candidate scan for BGZF block starts in a byte buffer.

    Rebuild of the scan loop of hb/BGZFSplitGuesser.java, but SIMD-style: one
    vectorized pass finds every offset whose bytes match the gzip magic
    ``1f 8b 08 04`` and (optionally) whose XLEN/BC subfield layout is
    consistent.  Candidates still need confirmation by inflating (the guesser
    does that); this just prunes 99.99% of offsets in O(n) NumPy time.
    """
    b = np.frombuffer(buf, dtype=np.uint8) if not isinstance(buf, np.ndarray) else buf
    n = b.size
    if n < HEADER_SIZE:
        return np.empty(0, dtype=np.int64)
    hits = (b[:-3] == 0x1F) & (b[1:-2] == 0x8B) & (b[2:-1] == 0x08) & (b[3:] == 0x04)
    cand = np.nonzero(hits)[0]
    cand = cand[cand + HEADER_SIZE <= n]
    if cand.size and require_valid_bsize:
        # XLEN at +10 (u16 LE) must be >= 6; check the standard layout where
        # the BC subfield comes first (how htsjdk and we write it); fall back
        # to the full subfield walk only for nonstandard writers.
        xlen = b[cand + 10].astype(np.int32) | (b[cand + 11].astype(np.int32) << 8)
        si_ok = (b[cand + 12] == BGZF_SI1) & (b[cand + 13] == BGZF_SI2) & \
                (b[cand + 14] == BGZF_SLEN) & (b[cand + 15] == 0)
        standard = (xlen == 6) & si_ok
        nonstandard = (xlen > 6) & (xlen < 256)
        keep = standard | nonstandard
        cand = cand[keep]
    return cand.astype(np.int64)


class BGZFReader:
    """Random-access reader over a BGZF file: seek by virtual offset, read
    inflated bytes across block boundaries.

    Host-side equivalent of htsjdk ``BlockCompressedInputStream`` as used by
    hb/BAMRecordReader.java (seek to split-start voffset, stream records).
    Works over any object with ``pread(offset, size) -> bytes`` and ``size``
    (see hadoop_bam_tpu/utils/seekable.py).
    """

    def __init__(self, source, check_crc: bool = False):
        from hadoop_bam_tpu.utils.seekable import as_byte_source
        self._src = as_byte_source(source)
        self._check_crc = check_crc
        self._block_coffset = -1
        self._block_data = b""
        self._uoffset = 0
        self._next_coffset = 0

    @property
    def file_size(self) -> int:
        return self._src.size

    def voffset(self) -> int:
        """Current position as a packed virtual offset."""
        coff = self._block_coffset if self._block_coffset >= 0 else self._next_coffset
        if self._uoffset == len(self._block_data) and self._block_coffset >= 0:
            # Normalized position: start of next block (matches htsjdk).
            return (self._next_coffset << 16)
        return (coff << 16) | self._uoffset

    def seek_voffset(self, v: int) -> None:
        coffset, uoffset = v >> 16, v & 0xFFFF
        self._load_block(coffset)
        if uoffset > len(self._block_data):
            raise BGZFError("virtual offset beyond block payload")
        self._uoffset = uoffset

    def _load_block(self, coffset: int) -> bool:
        if coffset == self._block_coffset:
            self._uoffset = 0
            return True
        if coffset >= self._src.size:
            self._block_coffset = -1
            self._block_data = b""
            self._uoffset = 0
            self._next_coffset = coffset
            return False
        head = self._src.pread(coffset, MAX_BLOCK_SIZE)
        info = parse_block_header(head, 0)
        self._block_data = inflate_block(head, info, check_crc=self._check_crc)
        self._block_coffset = coffset
        self._next_coffset = coffset + info.block_size
        self._uoffset = 0
        return True

    def read_to_voffset(self, v_end: int) -> bytes:
        """Read inflated bytes from the current position up to exactly
        ``v_end`` (exclusive) — the primitive index-range readers need to
        avoid overshooting into a neighboring chunk's records."""
        out = bytearray()
        c_end, u_end = v_end >> 16, v_end & 0xFFFF
        while self.voffset() < v_end:
            if self._block_coffset == c_end:
                out += self.read(u_end - self._uoffset)
                break
            avail = len(self._block_data) - self._uoffset
            got = self.read(avail if avail > 0 else 1)
            if not got:
                break
            out += got
        return bytes(out)

    def read(self, n: int) -> bytes:
        """Read exactly n inflated bytes (fewer only at EOF)."""
        out = bytearray()
        while n > 0:
            avail = len(self._block_data) - self._uoffset
            if avail == 0:
                if not self._load_block(self._next_coffset):
                    break
                if len(self._block_data) == 0:  # EOF/empty block: keep walking
                    continue
                avail = len(self._block_data)
            take = min(avail, n)
            out += self._block_data[self._uoffset:self._uoffset + take]
            self._uoffset += take
            n -= take
        return bytes(out)

    def read_all_from(self, voffset: int = 0) -> bytes:
        self.seek_voffset(voffset)
        chunks = [self.read(1 << 20)]
        while chunks[-1]:
            chunks.append(self.read(1 << 20))
        return b"".join(chunks)


class BGZFWriter:
    """Streaming BGZF writer (htsjdk ``BlockCompressedOutputStream`` analog).

    Buffers up to WRITE_PAYLOAD_SIZE bytes per block; ``tell_voffset`` returns
    the virtual offset of the *next* byte written — the hook the splitting-bai
    indexer (hb/SplittingBAMIndexer.java) needs.
    """

    def __init__(self, sink, level: int = 6, write_eof: bool = True):
        self._sink = sink  # file-like with .write
        self._level = level
        self._write_eof = write_eof
        self._buf = bytearray()
        self._coffset = 0
        self._closed = False

    def tell_voffset(self) -> int:
        return (self._coffset << 16) | len(self._buf)

    def write(self, data: bytes) -> None:
        self._buf += data
        while len(self._buf) >= WRITE_PAYLOAD_SIZE:
            self._flush_block(WRITE_PAYLOAD_SIZE)

    def _flush_block(self, n: int) -> None:
        payload = bytes(self._buf[:n])
        del self._buf[:n]
        block = deflate_block(payload, self._level)
        self._sink.write(block)
        self._coffset += len(block)

    def flush(self) -> None:
        if self._buf:
            self._flush_block(len(self._buf))

    def close(self) -> None:
        if self._closed:
            return
        self.flush()
        if self._write_eof:
            self._sink.write(EOF_BLOCK)
            self._coffset += len(EOF_BLOCK)
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def compress_bytes(data: bytes, level: int = 6, write_eof: bool = True) -> bytes:
    """One-shot: BGZF-compress ``data`` into a sequence of blocks."""
    import io
    sink = io.BytesIO()
    w = BGZFWriter(sink, level=level, write_eof=write_eof)
    w.write(data)
    w.close()
    return sink.getvalue()


def decompress_bytes(data: bytes, check_crc: bool = True) -> bytes:
    """One-shot: inflate a whole BGZF byte string."""
    out = []
    for info in scan_blocks(data):
        out.append(inflate_block(data, info, check_crc=check_crc))
    return b"".join(out)


def is_bgzf(head: bytes) -> bool:
    """Magic sniff used by format dispatch (hb/SAMFormat.java semantics)."""
    try:
        parse_block_header(head[:MAX_BLOCK_SIZE], 0)
        return True
    except BGZFError:
        return False
