"""rANS Nx16 entropy codec (CRAM 3.1 block method 5).

[SPEC] CRAMcodecs "rANS Nx16": the CRAM 3.1 evolution of rANS 4x8 —
N interleaved 32-bit states (N = 4, or 32 with the X32 flag), **16-bit**
renormalization (lower bound 2^15, one little-endian u16 read per step at
most), 12-bit normalized frequencies, plus optional byte-stream
transforms applied before entropy coding:

    PACK (0x80)   bit-pack when <= 16 distinct symbols (0/1/2/4 bits each)
    RLE  (0x40)   run-length split into literal + run-length streams
    CAT  (0x20)   stored uncompressed
    NOSZ (0x10)   uncompressed size omitted (caller knows it)
    STRIPE (0x08) bytes striped over X independent sub-streams
    X32  (0x04)   32-way state interleave (SIMD-friendly)
    ORDER (0x01)  order-1 (context = previous byte) vs order-0

Encode pipeline: PACK -> RLE -> rANS; decode runs the inverse order.
Frequency tables: same ascending-symbol RLE alphabet as 4x8
(cram_codecs.py); frequencies are uint7 varints; order-1 tables carry a
leading byte (high nibble = frequency shift, bit 0 = "tables themselves
are order-0-compressed") and each context total normalizes to
``1 << shift``.

Provenance note: the container-level flag values and the core N-state /
16-bit-renorm entropy coder follow the public htscodecs layout; the
PACK/RLE/STRIPE *metadata* byte layouts are reconstructed from knowledge
of that library ([SPEC-recalled]) and are pinned by round-trip tests
against this module's own encoder — the in-image environment has no
htslib to cross-validate against (SURVEY.md section 0 fallback).

Reference-side equivalent: htsjdk/htslib rANSNx16 reached through CRAM
3.1 decode (SURVEY.md section 2.8).
"""
from __future__ import annotations

import struct
from typing import List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.formats.cram_codecs import (
    RansError, _check_final_states, _normalize_freqs, _read_symbol_table,
    _write_symbol_table, normalize_truncation,
)

# flag bits [SPEC]
NX16_ORDER1 = 0x01
NX16_X32 = 0x04
NX16_STRIPE = 0x08
NX16_NOSZ = 0x10
NX16_CAT = 0x20
NX16_RLE = 0x40
NX16_PACK = 0x80

RANS_LOW_16 = 1 << 15           # 16-bit renormalization lower bound


# ---------------------------------------------------------------------------
# uint7 varints (big-endian 7-bit groups, high bit = continuation) [SPEC]
# ---------------------------------------------------------------------------

def var_put_u32(v: int) -> bytes:
    out = bytearray()
    if v >= (1 << 28):
        out.append(0x80 | ((v >> 28) & 0x7F))
    if v >= (1 << 21):
        out.append(0x80 | ((v >> 21) & 0x7F))
    if v >= (1 << 14):
        out.append(0x80 | ((v >> 14) & 0x7F))
    if v >= (1 << 7):
        out.append(0x80 | ((v >> 7) & 0x7F))
    out.append(v & 0x7F)
    return bytes(out)


def var_get_u32(buf: bytes, pos: int) -> Tuple[int, int]:
    v = 0
    while True:
        b = buf[pos]
        pos += 1
        v = (v << 7) | (b & 0x7F)
        if not (b & 0x80):
            return v, pos


# ---------------------------------------------------------------------------
# Frequency tables
# ---------------------------------------------------------------------------

def _write_freqs_nx16(freqs: np.ndarray) -> bytes:
    """Alphabet (shared RLE grammar) followed by uint7 frequencies."""
    out = bytearray(_write_symbol_table(freqs, emit_freq=False))
    for j in range(256):
        if freqs[j] > 0:
            out += var_put_u32(int(freqs[j]))
    return bytes(out)


def _read_alphabet(buf: bytes, pos: int) -> Tuple[List[int], int]:
    syms: List[int] = []

    def read_value(sym, p):
        syms.append(sym)
        return p

    _, pos = _read_symbol_table(buf, pos, read_value)
    return syms, pos


def _read_freqs_nx16(buf: bytes, pos: int, shift: int
                     ) -> Tuple[np.ndarray, int]:
    syms, pos = _read_alphabet(buf, pos)
    freqs = np.zeros(256, dtype=np.int64)
    for s in syms:
        f, pos = var_get_u32(buf, pos)
        freqs[s] = f
    total = int(freqs.sum())
    want = 1 << shift
    if total != want and total > 0:
        # [SPEC] stored frequencies may be un-normalized; renormalize
        freqs = _normalize_freqs(freqs, want)
    return freqs, pos


def _tables(freqs: np.ndarray, shift: int
            ) -> Tuple[np.ndarray, np.ndarray]:
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(freqs, out=cum[1:])
    slot2sym = np.zeros(1 << shift, dtype=np.uint8)
    for s in range(256):
        if freqs[s]:
            slot2sym[cum[s]:cum[s + 1]] = s
    return cum, slot2sym


# ---------------------------------------------------------------------------
# Core N-state entropy coder (16-bit renormalization)
# ---------------------------------------------------------------------------

def _enc_put16(x: int, freq: int, cum: int, shift: int,
               out: bytearray) -> int:
    x_max = ((RANS_LOW_16 >> shift) << 16) * freq
    if x >= x_max:
        out += struct.pack("<H", x & 0xFFFF)
        x >>= 16
    return ((x // freq) << shift) + (x % freq) + cum


def _encode_order0_core(data: bytes, N: int, shift: int = 12) -> bytes:
    counts = np.bincount(np.frombuffer(data, dtype=np.uint8),
                         minlength=256)
    freqs = _normalize_freqs(counts, 1 << shift)
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(freqs, out=cum[1:])
    table = _write_freqs_nx16(freqs)

    n = len(data)
    states = [RANS_LOW_16] * N
    rev = bytearray()
    for i in range(n - 1, -1, -1):
        s = data[i]
        states[i % N] = _enc_put16(states[i % N], int(freqs[s]),
                                   int(cum[s]), shift, rev)
    body = b"".join(struct.pack("<I", st) for st in states)
    # rev holds little-endian u16 words emitted in reverse order
    words = bytes(rev)
    out = bytearray(table + body)
    for w in range(len(words) - 2, -1, -2):
        out += words[w:w + 2]
    return bytes(out)


def _decode_order0_core(buf: bytes, pos: int, out_size: int, N: int,
                        shift: int = 12) -> bytes:
    freqs, pos = _read_freqs_nx16(buf, pos, shift)
    cum, slot2sym = _tables(freqs, shift)
    states = list(struct.unpack_from(f"<{N}I", buf, pos))
    pos += 4 * N
    out = np.zeros(out_size, dtype=np.uint8)
    mask = (1 << shift) - 1
    for i in range(out_size):
        j = i % N
        x = states[j]
        m = x & mask
        s = int(slot2sym[m])
        out[i] = s
        x = int(freqs[s]) * (x >> shift) + m - int(cum[s])
        if x < RANS_LOW_16:
            x = (x << 16) | (buf[pos] | (buf[pos + 1] << 8))
            pos += 2
        states[j] = x
    _check_final_states(states, RANS_LOW_16, "rANS Nx16")
    return out.tobytes()


def _slices(n: int, N: int) -> Tuple[List[int], List[int]]:
    """Order-1 fragment boundaries: N slices of n//N, last takes the
    remainder (the 4x8 quarters generalized)."""
    q = n // N
    starts = [j * q for j in range(N)]
    ends = [*(starts[1:]), n]
    return starts, ends


def _encode_order1_core(data: bytes, N: int, shift: int = 12) -> bytes:
    n = len(data)
    arr = np.frombuffer(data, dtype=np.uint8)
    starts, ends = _slices(n, N)
    prev = np.concatenate([[0], arr[:-1]])
    for st in starts:
        prev[st] = 0
    counts = np.zeros((256, 256), dtype=np.int64)
    np.add.at(counts, (prev, arr), 1)

    freqs = np.zeros((256, 256), dtype=np.int64)
    cums = np.zeros((256, 257), dtype=np.int64)
    for c in range(256):
        if counts[c].sum():
            freqs[c] = _normalize_freqs(counts[c], 1 << shift)
            np.cumsum(freqs[c], out=cums[c][1:])

    ctx_present = counts.sum(axis=1) > 0
    tbl = bytearray()
    # leading byte: high nibble = shift, bit 0 = tables-compressed (we
    # always write them plain)
    tbl.append((shift << 4) | 0)
    # outer context alphabet, same RLE grammar
    ctx_freqs = np.zeros(256, dtype=np.int64)
    ctx_freqs[ctx_present] = 1
    tbl += _write_symbol_table(ctx_freqs, emit_freq=False)
    for c in range(256):
        if ctx_present[c]:
            tbl += _write_freqs_nx16(freqs[c])

    states = [RANS_LOW_16] * N
    rev = bytearray()
    lens = [ends[j] - starts[j] for j in range(N)]
    maxlen = max(lens) if n else 0
    for step in range(maxlen - 1, -1, -1):
        for j in range(N - 1, -1, -1):
            if step < lens[j]:
                i = starts[j] + step
                ctx = int(prev[i])
                s = int(arr[i])
                states[j] = _enc_put16(states[j], int(freqs[ctx][s]),
                                       int(cums[ctx][s]), shift, rev)
    body = b"".join(struct.pack("<I", st) for st in states)
    words = bytes(rev)
    out = bytearray(bytes(tbl) + body)
    for w in range(len(words) - 2, -1, -2):
        out += words[w:w + 2]
    return bytes(out)


def _read_order1_tables_nx16(buf: bytes, pos: int
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                        int, int]:
    lead = buf[pos]
    pos += 1
    shift = lead >> 4
    if lead & 1:
        # tables themselves are order-0 Nx16 compressed [SPEC]
        ulen, pos = var_get_u32(buf, pos)
        clen, pos = var_get_u32(buf, pos)
        tbl = _decode_order0_core(buf[pos:pos + clen], 0, ulen, 4, shift=12)
        pos += clen
        f, c, s, _ = _read_order1_ctx_tables(tbl, 0, shift)
        return f, c, s, shift, pos
    f, c, s, pos = _read_order1_ctx_tables(buf, pos, shift)
    return f, c, s, shift, pos


def _read_order1_ctx_tables(buf: bytes, pos: int, shift: int
                            ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                       int]:
    ctxs, pos = _read_alphabet(buf, pos)
    freqs = np.zeros((256, 256), dtype=np.int64)
    cums = np.zeros((256, 257), dtype=np.int64)
    slot2sym = np.zeros((256, 1 << shift), dtype=np.uint8)
    for c in ctxs:
        f, pos = _read_freqs_nx16(buf, pos, shift)
        freqs[c] = f
        np.cumsum(f, out=cums[c][1:])
        for s in range(256):
            if f[s]:
                slot2sym[c, cums[c][s]:cums[c][s + 1]] = s
    return freqs, cums, slot2sym, pos


def _decode_order1_core(buf: bytes, pos: int, out_size: int, N: int
                        ) -> bytes:
    freqs, cums, slot2sym, shift, pos = _read_order1_tables_nx16(buf, pos)
    states = list(struct.unpack_from(f"<{N}I", buf, pos))
    pos += 4 * N
    starts, ends = _slices(out_size, N)
    out = np.zeros(out_size, dtype=np.uint8)
    mask = (1 << shift) - 1
    ctxs = [0] * N
    idx = list(starts)
    done = [idx[j] >= ends[j] for j in range(N)]
    while not all(done):
        for j in range(N):
            if done[j]:
                continue
            x = states[j]
            m = x & mask
            ctx = ctxs[j]
            s = int(slot2sym[ctx, m])
            out[idx[j]] = s
            x = int(freqs[ctx][s]) * (x >> shift) + m - int(cums[ctx][s])
            if x < RANS_LOW_16:
                x = (x << 16) | (buf[pos] | (buf[pos + 1] << 8))
                pos += 2
            states[j] = x
            ctxs[j] = s
            idx[j] += 1
            if idx[j] >= ends[j]:
                done[j] = True
    _check_final_states(states, RANS_LOW_16, "rANS Nx16")
    return out.tobytes()


# ---------------------------------------------------------------------------
# Byte-stream transforms
# ---------------------------------------------------------------------------

def _pack_encode(data: bytes) -> Optional[Tuple[bytes, bytes]]:
    """Bit-pack when <= 16 distinct symbols; returns (meta, packed) or
    None when not packable.  meta = nsym, symbol map."""
    syms = sorted(set(data))
    nsym = len(syms)
    if nsym > 16 or len(data) == 0:
        return None
    inv = np.zeros(256, dtype=np.uint8)
    inv[list(syms)] = np.arange(nsym, dtype=np.uint8)
    arr = np.frombuffer(data, dtype=np.uint8)
    mapped = inv[arr]
    if nsym <= 1:
        packed = b""
    elif nsym <= 2:
        pad = (-len(mapped)) % 8
        m = np.concatenate([mapped, np.zeros(pad, np.uint8)]).reshape(-1, 8)
        packed = (m << np.arange(8, dtype=np.uint8)).sum(
            axis=1, dtype=np.uint16).astype(np.uint8).tobytes()
    elif nsym <= 4:
        pad = (-len(mapped)) % 4
        m = np.concatenate([mapped, np.zeros(pad, np.uint8)]).reshape(-1, 4)
        packed = (m << (2 * np.arange(4, dtype=np.uint8))).sum(
            axis=1, dtype=np.uint16).astype(np.uint8).tobytes()
    else:
        pad = (-len(mapped)) % 2
        m = np.concatenate([mapped, np.zeros(pad, np.uint8)]).reshape(-1, 2)
        packed = (m[:, 0] | (m[:, 1] << 4)).astype(np.uint8).tobytes()
    meta = bytes([nsym]) + bytes(syms)
    return meta, packed


def _pack_decode(packed: bytes, meta_syms: bytes, out_size: int) -> bytes:
    nsym = len(meta_syms)
    table = np.zeros(256, dtype=np.uint8)
    table[:nsym] = np.frombuffer(meta_syms, dtype=np.uint8)
    if nsym <= 1:
        return bytes(meta_syms[:1]) * out_size if nsym else b""
    arr = np.frombuffer(packed, dtype=np.uint8)
    if nsym <= 2:
        bits = (arr[:, None] >> np.arange(8, dtype=np.uint8)) & 1
        vals = bits.reshape(-1)[:out_size]
    elif nsym <= 4:
        bits = (arr[:, None] >> (2 * np.arange(4, dtype=np.uint8))) & 3
        vals = bits.reshape(-1)[:out_size]
    else:
        bits = np.stack([arr & 0xF, arr >> 4], axis=1)
        vals = bits.reshape(-1)[:out_size]
    return table[vals].tobytes()


def _rle_encode(data: bytes) -> Optional[Tuple[bytes, bytes]]:
    """Split into (meta = rle symbol set + run lengths, literals).

    Symbols chosen: any byte whose total run savings are positive."""
    if not data:
        return None
    arr = np.frombuffer(data, dtype=np.uint8)
    # run starts
    starts = np.concatenate([[0], np.nonzero(np.diff(arr))[0] + 1])
    lens = np.diff(np.concatenate([starts, [arr.size]]))
    run_syms = arr[starts]
    savings = np.zeros(256, dtype=np.int64)
    np.add.at(savings, run_syms, lens - 2)  # ~1 literal + ~1 run byte kept
    use = savings > 0
    if not use.any():
        return None
    lits = bytearray()
    runs = bytearray()
    for s, ln in zip(run_syms.tolist(), lens.tolist()):
        if use[s]:
            lits.append(s)
            runs += var_put_u32(ln - 1)
        else:
            lits += bytes([s]) * ln
    n_use = int(use.sum())
    meta = bytes([n_use & 0xFF]) + bytes(np.nonzero(use)[0].astype(
        np.uint8).tolist()) + bytes(runs)
    return meta, bytes(lits)


def _rle_decode(lits: bytes, meta: bytes, out_size: int) -> bytes:
    pos = 0
    n_use = meta[pos]
    pos += 1
    if n_use == 0:
        n_use = 256
    use = np.zeros(256, dtype=bool)
    for _ in range(n_use):
        use[meta[pos]] = True
        pos += 1
    out = bytearray()
    for s in lits:
        if use[s]:
            run, pos = var_get_u32(meta, pos)
            out += bytes([s]) * (run + 1)
        else:
            out.append(s)
    if len(out) != out_size:
        raise RansError(f"RLE expanded to {len(out)}, expected {out_size}")
    return bytes(out)


# ---------------------------------------------------------------------------
# Public stream API
# ---------------------------------------------------------------------------

def rans_nx16_encode(data: bytes, flags: int = 0) -> bytes:
    """Encode with the requested flag set; PACK/RLE are dropped
    automatically when they don't apply, tiny payloads fall back to CAT.
    STRIPE recurses into X=4 NOSZ sub-streams."""
    n = len(data)

    if flags & NX16_STRIPE:
        X = 4
        out = bytearray([NX16_STRIPE])
        out += var_put_u32(n)
        subs = [rans_nx16_encode(bytes(data[j::X]),
                                 (flags & ~NX16_STRIPE) | NX16_NOSZ)
                for j in range(X)]
        out.append(X)
        for s in subs:
            out += var_put_u32(len(s))
        for s in subs:
            out += s
        return bytes(out)

    payload = data
    pack_meta = rle_meta = None
    if flags & NX16_PACK:
        packed = _pack_encode(payload)
        if packed is None:
            flags &= ~NX16_PACK
        else:
            pack_meta, payload = packed
    if flags & NX16_RLE:
        rled = _rle_encode(payload)
        if rled is None:
            flags &= ~NX16_RLE
        else:
            rle_meta, payload = rled

    N = 32 if flags & NX16_X32 else 4
    if len(payload) < 32:
        flags |= NX16_CAT            # entropy tables cost more than CAT
    if flags & NX16_CAT or len(payload) < N:
        flags &= ~NX16_ORDER1
        if not (flags & NX16_CAT):
            flags |= NX16_CAT

    out = bytearray([flags])
    if not (flags & NX16_NOSZ):
        out += var_put_u32(n)
    if flags & NX16_PACK:
        out += pack_meta                     # nsym byte + symbol map
    if flags & NX16_RLE:
        # meta stored raw: (len << 1) | 1, meta bytes, literal length
        out += var_put_u32((len(rle_meta) << 1) | 1)
        out += rle_meta
        out += var_put_u32(len(payload))
    if flags & NX16_CAT:
        out += payload
    elif flags & NX16_ORDER1:
        out += _encode_order1_core(payload, N)
    else:
        out += _encode_order0_core(payload, N)
    return bytes(out)


def rans_nx16_decode(payload: bytes, out_size: Optional[int] = None
                     ) -> bytes:
    """Decode one rANS Nx16 stream.  ``out_size`` is required when the
    stream carries the NOSZ flag (the CRAM block header supplies it)."""
    with normalize_truncation("rANS Nx16"):
        return _rans_nx16_decode(payload, out_size)


def _rans_nx16_decode(payload: bytes, out_size: Optional[int] = None
                      ) -> bytes:
    if not payload:
        raise RansError("empty rANS Nx16 stream")
    pos = 0
    flags = payload[pos]
    pos += 1
    if not (flags & NX16_NOSZ):
        out_size, pos = var_get_u32(payload, pos)
    if out_size is None:
        raise RansError("NOSZ stream needs an external size")
    if out_size == 0:
        return b""

    if flags & NX16_STRIPE:
        X = payload[pos]
        pos += 1
        clens = []
        for _ in range(X):
            c, pos = var_get_u32(payload, pos)
            clens.append(c)
        outs = []
        for j in range(X):
            sub_len = (out_size - j + X - 1) // X
            outs.append(rans_nx16_decode(
                payload[pos:pos + clens[j]], sub_len))
            pos += clens[j]
        out = np.zeros(out_size, dtype=np.uint8)
        for j in range(X):
            out[j::X] = np.frombuffer(outs[j], dtype=np.uint8)
        return out.tobytes()

    pack_syms = None
    if flags & NX16_PACK:
        nsym = payload[pos]
        pos += 1
        pack_syms = payload[pos:pos + nsym]
        pos += nsym

    rle_meta = None
    lit_len = None
    if flags & NX16_RLE:
        mlen, pos = var_get_u32(payload, pos)
        if mlen & 1:
            mlen >>= 1
            rle_meta = payload[pos:pos + mlen]
            pos += mlen
        else:
            mlen >>= 1
            clen, pos = var_get_u32(payload, pos)
            rle_meta = _decode_order0_core(payload, pos, mlen, 4)
            pos += clen
        lit_len, pos = var_get_u32(payload, pos)

    # size entering the entropy stage
    if flags & NX16_RLE:
        stage_size = lit_len
    elif flags & NX16_PACK:
        stage_size = _packed_size(out_size, len(pack_syms))
    else:
        stage_size = out_size

    if flags & NX16_CAT:
        stage = payload[pos:pos + stage_size]
        if len(stage) != stage_size:
            raise RansError("truncated CAT payload")
    else:
        N = 32 if flags & NX16_X32 else 4
        if flags & NX16_ORDER1:
            stage = _decode_order1_core(payload, pos, stage_size, N)
        else:
            stage = _decode_order0_core(payload, pos, stage_size, N)

    if flags & NX16_RLE:
        target = (_packed_size(out_size, len(pack_syms))
                  if flags & NX16_PACK else out_size)
        stage = _rle_decode(stage, rle_meta, target)
    if flags & NX16_PACK:
        stage = _pack_decode(stage, pack_syms, out_size)
    if len(stage) != out_size:
        raise RansError(
            f"rANS Nx16 decoded {len(stage)} bytes, expected {out_size}")
    return stage


def _packed_size(n: int, nsym: int) -> int:
    if nsym <= 1:
        return 0
    if nsym <= 2:
        return (n + 7) // 8
    if nsym <= 4:
        return (n + 3) // 4
    return (n + 1) // 2
