"""CRAM file orchestration: writer, header reader, record iteration.

File shape [SPEC CRAM 3.0 section 6]: file definition, a first container
holding the SAM header (FILE_HEADER block: i32 text length + text), data
containers (one slice each, cram_encode.py), and the fixed 38-byte EOF
container.

Reference equivalents: htsjdk ``CramContainerIterator`` / CRAM writer as used
by hb/CRAMInputFormat.java, hb/CRAMRecordReader.java and
hb/KeyIgnoringCRAMRecordWriter.java (SURVEY.md sections 2.3/2.4).
"""
from __future__ import annotations

import struct
from typing import BinaryIO, Dict, Iterator, List, Optional, Tuple, Union

from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.formats.cram import (
    Block, CRAMError, COMPRESSION_HEADER, Container, CORE_DATA,
    EOF_CONTAINER, EXTERNAL_DATA, FILE_HEADER, FileDefinition, GZIP,
    MAPPED_SLICE_HEADER, read_container, scan_container_offsets,
)
from hadoop_bam_tpu.formats.cram_decode import (
    CF_DETACHED, CF_QUAL_STORED, CompressionHeader, CramRecord,
    MATE_REVERSE, MATE_UNMAPPED, ReferenceSource, SliceHeader,
    decode_slice_records,
)
from hadoop_bam_tpu.formats.cram_encode import encode_container
from hadoop_bam_tpu.formats.sam import SamRecord

# Phred -> ASCII(+33) translation table (bulk qual rendering)
_Q33 = bytes(min(q + 33, 255) for q in range(256))

DEFAULT_RECORDS_PER_CONTAINER = 10_000


# ---------------------------------------------------------------------------
# Writer
# ---------------------------------------------------------------------------

class CramWriter:
    """Streaming CRAM writer; buffers records into containers.

    ``write_header``/``write_eof`` knobs mirror the reference's shard-writer
    options (hb/KeyIgnoringCRAMRecordWriter.java): headerless, terminator-less
    shards can later be concatenated by the merger
    (hadoop_bam_tpu/utils/mergers.py).
    """

    def __init__(self, path_or_sink: Union[str, BinaryIO], header: SAMHeader,
                 records_per_container: int = DEFAULT_RECORDS_PER_CONTAINER,
                 write_header: bool = True, write_eof: bool = True,
                 version: Tuple[int, int] = (3, 0)):
        if version not in ((3, 0), (3, 1)):
            raise ValueError(f"unsupported CRAM write version {version}")
        if isinstance(path_or_sink, str):
            self._sink: BinaryIO = open(path_or_sink, "wb")
            self._owns = True
        else:
            self._sink = path_or_sink
            self._owns = False
        self.header = header
        self.version = version
        self.records_per_container = records_per_container
        self._write_eof = write_eof
        self._pending: List[SamRecord] = []
        self._record_counter = 0
        self._closed = False
        if write_header:
            self._sink.write(FileDefinition(
                major=version[0], minor=version[1]).to_bytes())
            self._sink.write(_header_container_bytes(header))

    def write_record(self, rec: SamRecord) -> None:
        self._pending.append(rec)
        if len(self._pending) >= self.records_per_container:
            self.flush_container()

    def write_records(self, recs) -> None:
        for r in recs:
            self.write_record(r)

    def flush_container(self) -> None:
        if not self._pending:
            return
        # split runs so each container's slice is single-ref where possible
        self._sink.write(encode_container(
            self._pending, self.header, self._record_counter,
            version=self.version))
        self._record_counter += len(self._pending)
        self._pending = []

    def close(self) -> None:
        if self._closed:
            return
        self.flush_container()
        if self._write_eof:
            self._sink.write(EOF_CONTAINER)
        if self._owns:
            self._sink.close()
        self._closed = True

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _header_container_bytes(header: SAMHeader) -> bytes:
    text = header.to_sam_text().encode("ascii") if hasattr(
        header, "to_sam_text") else header.text.encode("ascii")
    payload = struct.pack("<i", len(text)) + text
    from hadoop_bam_tpu.formats.cram import build_container
    blk = Block(FILE_HEADER, 0, payload, GZIP)
    return build_container([blk], ref_seq_id=-1, start=0, span=0,
                           n_records=0, record_counter=0, bases=0,
                           landmarks=[0])


def write_cram(path_or_sink, header: SAMHeader, records) -> None:
    with CramWriter(path_or_sink, header) as w:
        w.write_records(records)


# ---------------------------------------------------------------------------
# Reader
# ---------------------------------------------------------------------------

def _read_all(source) -> bytes:
    if isinstance(source, (bytes, bytearray)):
        return bytes(source)
    with open(source, "rb") as f:
        return f.read()


def read_cram_header(source) -> Tuple[SAMHeader, int]:
    """Returns (header, offset of the first data container)."""
    buf = _read_all(source)
    FileDefinition.from_bytes(buf)
    cont, after = read_container(buf, FileDefinition.SIZE)
    for blk in cont.blocks:
        if blk.content_type == FILE_HEADER:
            (l_text,) = struct.unpack_from("<i", blk.data, 0)
            text = blk.data[4:4 + l_text].decode("ascii", "replace")
            return SAMHeader.from_sam_text(text.rstrip("\x00")), after
    raise CRAMError("first container carries no FILE_HEADER block")


def iter_container_slices(cont: Container):
    """(comp, slice_hdr, core, external, codec_rec_lens) for each slice
    of one data container — the shared walk under both the record-object
    and the columnar slice decoders.  ``codec_rec_lens`` maps content id
    -> the block codec's own per-record lengths for codecs that model
    record boundaries (fqzcomp), for the RL-series desync tripwire."""
    if cont.header.is_eof or not cont.blocks:
        return
    if cont.blocks[0].content_type != COMPRESSION_HEADER:
        raise CRAMError("container does not start with a compression header")
    comp = CompressionHeader.from_bytes(cont.blocks[0].data)
    i = 1
    while i < len(cont.blocks):
        blk = cont.blocks[i]
        if blk.content_type != MAPPED_SLICE_HEADER:
            raise CRAMError(f"expected slice header block, got type "
                            f"{blk.content_type}")
        slice_hdr = SliceHeader.from_bytes(blk.data)
        body = cont.blocks[i + 1:i + 1 + slice_hdr.n_blocks]
        if len(body) != slice_hdr.n_blocks:
            raise CRAMError("slice block count overruns container")
        core = b""
        external: Dict[int, bytes] = {}
        codec_rec_lens: Dict[int, list] = {}
        for b in body:
            if b.content_type == CORE_DATA:
                core = b.data
            elif b.content_type == EXTERNAL_DATA:
                external[b.content_id] = b.data
                if b.aux:
                    codec_rec_lens[b.content_id] = b.aux
        yield comp, slice_hdr, core, external, codec_rec_lens
        i += 1 + slice_hdr.n_blocks


def decode_container_slices(cont: Container, header: SAMHeader,
                            ref_source: Optional[ReferenceSource] = None
                            ) -> List[Tuple[int, List["CramRecord"]]]:
    """Decode one data container into per-slice pre-SAM CramRecord lists
    (features resolved, mates NOT linked), each paired with its slice's
    record-counter base.  The columnar stats path consumes these directly
    — seq/qual/length are final here — skipping mate resolution and
    SamRecord materialization; decode_container builds on this for the
    full SAM view."""
    out: List[Tuple[int, List["CramRecord"]]] = []
    for comp, slice_hdr, core, external, codec_lens \
            in iter_container_slices(cont):
        records = decode_slice_records(comp, slice_hdr, core, external,
                                       header.ref_names, ref_source,
                                       codec_rec_lens=codec_lens)
        out.append((slice_hdr.record_counter, records))
    return out


def decode_container(cont: Container, header: SAMHeader,
                     ref_source: Optional[ReferenceSource] = None
                     ) -> List[SamRecord]:
    """Decode every slice of one data container into SAM records."""
    out: List[SamRecord] = []
    for base, records in decode_container_slices(cont, header, ref_source):
        _resolve_mates(records)      # NF chains never cross slices [SPEC]
        out.extend(_to_sam(r, header, base + j)
                   for j, r in enumerate(records))
    return out


def iter_cram_records(source, header: Optional[SAMHeader] = None,
                      ref_source: Optional[ReferenceSource] = None
                      ) -> Iterator[SamRecord]:
    buf = _read_all(source)
    hdr, pos = read_cram_header(buf)
    header = header or hdr
    n = len(buf)
    while pos < n:
        cont, pos = read_container(buf, pos)
        if cont.header.is_eof:
            break
        yield from decode_container(cont, header, ref_source)


def read_cram(source, ref_source: Optional[ReferenceSource] = None
              ) -> Tuple[SAMHeader, List[SamRecord]]:
    buf = _read_all(source)
    header, _ = read_cram_header(buf)
    return header, list(iter_cram_records(buf, header, ref_source))


# ---------------------------------------------------------------------------
# CramRecord → SamRecord
# ---------------------------------------------------------------------------

def _resolve_mates(records: List[CramRecord]) -> None:
    """Link NF (mate-downstream) chains the way htsjdk does: each record's
    mate is the next in the chain; the last points back to the first."""
    seen = set()
    for i, r in enumerate(records):
        if i in seen or r.next_fragment < 0:
            continue
        chain = [i]
        j = i
        while records[j].next_fragment >= 0:
            j = j + records[j].next_fragment + 1
            if j >= len(records):
                raise CRAMError("NF mate link points past the slice")
            chain.append(j)
        seen.update(chain)
        for k, idx in enumerate(chain):
            mate = records[chain[(k + 1) % len(chain)]]
            rec = records[idx]
            rec.mate_ref_id = mate.ref_id
            rec.mate_pos = mate.pos
            rec.mate_flags = ((1 if mate.bf & 0x10 else 0)
                              | (2 if mate.bf & 0x4 else 0))
        # template size: leftmost..rightmost span, sign by position
        mapped = [records[idx] for idx in chain if not records[idx].bf & 0x4]
        if len(mapped) >= 2:
            starts = [m.pos for m in mapped]
            ends = [m.pos + _cigar_ref_len(m.cigar) - 1 for m in mapped]
            tlen = max(ends) - min(starts) + 1
            leftmost = min(range(len(mapped)), key=lambda k: starts[k])
            for k, m in enumerate(mapped):
                m.template_size = tlen if k == leftmost else -tlen


def _cigar_ref_len(cigar: str) -> int:
    if cigar == "*":
        return 0
    from hadoop_bam_tpu.formats.bam import parse_cigar_string
    return sum(n for n, op in parse_cigar_string(cigar) if op in "MDN=X")


def _to_sam(r: CramRecord, header: SAMHeader, counter: int) -> SamRecord:
    flag = r.bf
    if r.mate_flags & 1:
        flag |= MATE_REVERSE
    if r.mate_flags & 2:
        flag |= MATE_UNMAPPED
    names = header.ref_names
    rname = names[r.ref_id] if 0 <= r.ref_id < len(names) else "*"
    if r.mate_ref_id < 0:
        rnext = "*"
    elif r.mate_ref_id == r.ref_id:
        rnext = "="
    else:
        rnext = names[r.mate_ref_id] if r.mate_ref_id < len(names) else "*"
    if r.cf & CF_QUAL_STORED and r.qual:
        qual = bytes(r.qual).translate(_Q33).decode("latin-1")
    else:
        qual = "*"
    tags = list(r.tags)
    if r.read_group >= 0 and not any(t == "RG" for t, _, _ in tags):
        rg_ids = _rg_ids(header)
        if r.read_group < len(rg_ids):
            tags.append(("RG", "Z", rg_ids[r.read_group]))
    name = r.name.decode("ascii") if r.name else f"cram-{counter}"
    return SamRecord(
        qname=name, flag=flag, rname=rname, pos=r.pos,
        mapq=r.mapq if not r.bf & 0x4 else 0,
        cigar=r.cigar if not r.bf & 0x4 else "*",
        rnext=rnext, pnext=r.mate_pos, tlen=r.template_size,
        seq=r.seq if r.seq else "*", qual=qual, tags=tags)


def _rg_ids(header: SAMHeader) -> List[str]:
    ids = []
    for line in header.text.splitlines():
        if line.startswith("@RG"):
            for f in line.split("\t")[1:]:
                if f.startswith("ID:"):
                    ids.append(f[3:])
    return ids
