"""Whole-file BAM read/write helpers tying BGZF + BAM codecs together.

These are the host-side, single-stream paths (the equivalents of "just use
htsjdk SamReader/SAMFileWriter"): fixture generation, golden tests, the CLI,
and writers use them.  The scaled decode path (span planning + batched device
inflate/unpack) lives in split/ + ops/ + parallel/.
"""
from __future__ import annotations

import io
from typing import Iterable, Iterator, List, Optional, Tuple, Union

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.formats.bam import (
    BamBatch, SAMHeader, walk_record_offsets,
)
from hadoop_bam_tpu.formats.sam import SamRecord
from hadoop_bam_tpu.formats.virtual_offset import make_voffset
from hadoop_bam_tpu.utils.errors import PlanError


class BamWriter:
    """Streaming BAM writer (header + records -> BGZF file).

    Mirrors hb/KeyIgnoringBAMRecordWriter.java semantics: header emission and
    the BGZF EOF terminator are both optional so that headerless shards can be
    concatenated by the merger (hb/util/SAMFileMerger.java).
    ``record_voffsets()`` exposes per-record virtual offsets for the
    splitting-bai indexer (hb/SplittingBAMIndexer.java's MR-integrated mode).
    """

    def __init__(self, sink, header: SAMHeader, *, write_header: bool = True,
                 write_eof: bool = True, level: int = 6,
                 track_voffsets: bool = False,
                 index_granularity: int = 0,
                 index_flavor: str = "splitting-bai"):
        self._own = False
        self._path = sink if isinstance(sink, (str, bytes)) else None
        if isinstance(sink, (str, bytes)):
            sink = open(sink, "wb")
            self._own = True
        self._sink = sink
        self.header = header
        self._w = bgzf.BGZFWriter(sink, level=level, write_eof=write_eof)
        self._voffsets: List[int] = []
        # index-on-write (hb/SplittingBAMIndexer.java's MR-integrated mode):
        # sample every Nth record's voffset during output and emit the
        # sidecar on close — no second pass over the file
        self._index_granularity = int(index_granularity)
        self._index_flavor = index_flavor
        if self._index_granularity and self._path is None:
            # PLAN class (still a ValueError): a writer misconfiguration,
            # not bad bytes — must never be retried or quarantine-eaten
            raise PlanError("index_granularity needs a path sink (the "
                            "sidecar is written next to the BAM)")
        self._track = track_voffsets or bool(self._index_granularity)
        self.records_written = 0
        if write_header:
            self._w.write(header.to_bam_bytes())

    def write_record_bytes(self, rec: bytes) -> int:
        v = self._w.tell_voffset()
        if self._track:
            self._voffsets.append(v)
        self._w.write(rec)
        self.records_written += 1
        return v

    def write_sam_record(self, rec: SamRecord) -> int:
        return self.write_record_bytes(rec.to_bam_bytes(self.header))

    def write_raw(self, data: bytes, n_records: int = 0) -> None:
        """Append pre-encoded, already-concatenated record bytes (bulk
        path for writers that assemble records off to the side; the BGZF
        stream is identical to per-record write_record_bytes calls).

        Incompatible with voffset tracking / index-on-write: per-record
        boundaries are not visible here, so a sidecar built from this
        stream would point at wrong offsets."""
        if self._track:
            # PLAN class: incompatible writer options, a caller bug
            raise PlanError(
                "write_raw cannot be used with track_voffsets / "
                "index_granularity — record boundaries are not visible; "
                "use write_record_bytes")
        self._w.write(data)
        self.records_written += n_records

    def record_voffsets(self) -> List[int]:
        return self._voffsets

    def close(self) -> None:
        self._w.close()
        if self._own:
            self._sink.close()
        if self._index_granularity and self.records_written:
            self._write_sidecar()

    def _write_sidecar(self) -> None:
        import os

        from hadoop_bam_tpu.split.splitting_index import (
            SBI_SUFFIX, SPLITTING_BAI_SUFFIX, SplittingIndex,
        )
        g = self._index_granularity
        path = self._path if isinstance(self._path, str) \
            else self._path.decode()
        size = os.path.getsize(path)
        idx = SplittingIndex(
            voffsets=self._voffsets[::g] + [size << 16],
            granularity=g, total_records=self.records_written)
        if self._index_flavor == "sbi":
            out, data = path + SBI_SUFFIX, idx.to_sbi_bytes(size)
        else:
            out, data = (path + SPLITTING_BAI_SUFFIX,
                         idx.to_splitting_bai_bytes())
        with open(out, "wb") as f:
            f.write(data)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def write_bam(path_or_sink, header: SAMHeader,
              records: Iterable[Union[SamRecord, bytes]], **kw) -> None:
    with BamWriter(path_or_sink, header, **kw) as w:
        for r in records:
            if isinstance(r, SamRecord):
                w.write_sam_record(r)
            else:
                w.write_record_bytes(r)


def read_bam_header(source) -> Tuple[SAMHeader, int]:
    """Read the header; returns (header, first-record virtual offset).

    Equivalent of hb/util/SAMHeaderReader.java for BAM containers (and of the
    header step of hb/BAMRecordReader.initialize)."""
    from hadoop_bam_tpu.utils.errors import CorruptDataError

    r = bgzf.BGZFReader(source)
    # Headers are typically < a few MB; read blocks until parse succeeds.
    # Transient read faults surface from r.read() itself (outside this
    # try) with their own class; what the handler sees is always a parse
    # failure over an in-memory buffer — deterministic corruption.
    size = 1 << 16
    while True:
        r.seek_voffset(0)
        buf = r.read(size)
        try:
            header, after = SAMHeader.from_bam_bytes(buf, 0)
            break
        except (IndexError, Exception) as e:
            if len(buf) < size:  # EOF — really malformed
                raise CorruptDataError(
                    f"malformed BAM header: {type(e).__name__}: {e}") from e
            size *= 4
    # Convert the plain offset-after-header into a virtual offset by walking
    # blocks again (cheap: headers span few blocks).
    r.seek_voffset(0)
    remaining = after
    coff = 0
    while True:
        head = r._src.pread(coff, bgzf.MAX_BLOCK_SIZE)
        info = bgzf.parse_block_header(head, 0)
        if remaining < info.isize or (remaining == info.isize and info.isize > 0):
            # position is inside (or exactly at end of) this block
            if remaining == info.isize:
                return header, make_voffset(coff + info.block_size, 0)
            return header, make_voffset(coff, remaining)
        remaining -= info.isize
        coff += info.block_size  # info offsets are window-relative


def read_bam(source, header: Optional[SAMHeader] = None) -> Tuple[SAMHeader, BamBatch]:
    """Inflate a whole BAM and return (header, SoA batch of all records)."""
    r = bgzf.BGZFReader(source)
    data = r.read_all_from(0)
    hdr, after = SAMHeader.from_bam_bytes(data, 0)
    arr = np.frombuffer(data, dtype=np.uint8)
    offs = walk_record_offsets(data, start=after)
    return hdr, BamBatch(arr, offs, header=hdr)


def iter_sam_lines(source) -> Iterator[str]:
    """Decode a BAM to SAM lines (CLI `view` path; golden-test oracle hook)."""
    hdr, batch = read_bam(source)
    for i in range(len(batch)):
        yield batch.to_sam_line(i)
