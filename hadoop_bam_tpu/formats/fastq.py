"""FASTQ format: SequencedFragment model, 4-line codec, record-start scanner.

Reference equivalents: hb/SequencedFragment.java (the FASTQ/QSEQ value type
with Illumina read metadata), hb/FastqInputFormat.java + its record-boundary
heuristic, hb/FastqOutputFormat.java, and the quality-encoding constants of
hb/FormatConstants.java (SURVEY.md sections 2.3/2.4/2.5).

[SPEC] FASTQ record = 4 lines: ``@name``, sequence, ``+[name]``, quality
(same length as sequence).  Base qualities are ASCII Phred+33 (Sanger) or
Phred+64 (Illumina 1.3-1.7) — config selects; internal canonical form is
always Sanger (+33), mirroring the reference's normalization.

Boundary disambiguation: '@' may legally open a *quality* line, so "line
starts with '@'" does not identify a record start.  The scanner requires the
reference's stronger pattern: '@'-line, plausible sequence line, '+'-line,
and (when visible) a quality line whose length matches the sequence line.
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import BaseQualityEncoding


class FastqError(ValueError):
    pass


# Casava 1.8+: @instrument:run:flowcell:lane:tile:x:y[ read:filter:control:index]
_NAME_18_RE = re.compile(
    r"^(?P<instrument>[^:]+):(?P<run>\d+):(?P<flowcell>[^:]+):(?P<lane>\d+):"
    r"(?P<tile>\d+):(?P<x>-?\d+):(?P<y>-?\d+)"
    r"(?:\s+(?P<read>\d+):(?P<filter>[YN]):(?P<control>\d+):(?P<index>\S*))?$")
# pre-1.8: @machine:lane:tile:x:y#index/read
_NAME_OLD_RE = re.compile(
    r"^(?P<instrument>[^:]+):(?P<lane>\d+):(?P<tile>\d+):(?P<x>-?\d+):"
    r"(?P<y>-?\d+)(?:#(?P<index>\S+?))?(?:/(?P<read>\d+))?$")


@dataclass
class SequencedFragment:
    """One sequenced read + its (optional) Illumina run metadata —
    hb/SequencedFragment.java field-for-field."""

    sequence: str = ""
    quality: str = ""            # canonical Sanger (+33) ASCII
    instrument: Optional[str] = None
    run_number: Optional[int] = None
    flowcell_id: Optional[str] = None
    lane: Optional[int] = None
    tile: Optional[int] = None
    xpos: Optional[int] = None
    ypos: Optional[int] = None
    read: Optional[int] = None           # 1 or 2 (mate number)
    filter_passed: Optional[bool] = None  # False = failed QC
    control_number: Optional[int] = None
    index_sequence: Optional[str] = None
    name: str = ""               # raw name (without '@'), round-trip safe

    def read_name(self) -> str:
        return self.name

    @classmethod
    def from_name(cls, name: str, sequence: str = "", quality: str = ""
                  ) -> "SequencedFragment":
        f = cls(sequence=sequence, quality=quality, name=name)
        m = _NAME_18_RE.match(name)
        if m:
            f.instrument = m.group("instrument")
            f.run_number = int(m.group("run"))
            f.flowcell_id = m.group("flowcell")
            f.lane = int(m.group("lane"))
            f.tile = int(m.group("tile"))
            f.xpos = int(m.group("x"))
            f.ypos = int(m.group("y"))
            if m.group("read"):
                f.read = int(m.group("read"))
                f.filter_passed = m.group("filter") == "N"  # Y = filtered OUT
                f.control_number = int(m.group("control"))
                f.index_sequence = m.group("index") or None
            return f
        m = _NAME_OLD_RE.match(name)
        if m:
            f.instrument = m.group("instrument")
            f.lane = int(m.group("lane"))
            f.tile = int(m.group("tile"))
            f.xpos = int(m.group("x"))
            f.ypos = int(m.group("y"))
            f.index_sequence = m.group("index")
            if m.group("read"):
                f.read = int(m.group("read"))
        return f

    def to_fastq(self) -> str:
        return f"@{self.name}\n{self.sequence}\n+\n{self.quality}\n"


def convert_quality(q: str, src: BaseQualityEncoding,
                    dst: BaseQualityEncoding = BaseQualityEncoding.SANGER
                    ) -> str:
    """Re-base quality ASCII between Phred+33 and Phred+64 [SPEC offsets]."""
    if src is dst:
        return q
    delta = dst.value - src.value
    arr = np.frombuffer(q.encode("latin-1"), dtype=np.uint8).astype(np.int16)
    arr = arr + delta
    if arr.min(initial=127) < 33 or arr.max(initial=0) > 126:
        raise FastqError("quality out of range after re-encoding — wrong "
                         "base-quality-encoding config?")
    return arr.astype(np.uint8).tobytes().decode("latin-1")


_SEQ_CHARS = frozenset(b"ACGTNUKSYMWRBDHVacgtnuksymwrbdhv.-=")


def _is_seq_line(line: bytes) -> bool:
    line = line.rstrip(b"\r")  # tolerate CRLF files
    return len(line) > 0 and all(c in _SEQ_CHARS for c in line)


def parse_fastq(text: bytes,
                encoding: BaseQualityEncoding = BaseQualityEncoding.SANGER,
                filter_failed_qc: bool = False) -> List[SequencedFragment]:
    """Strict 4-line FASTQ parse of a span's text (hb/FastqRecordReader)."""
    out: List[SequencedFragment] = []
    lines = [l.rstrip(b"\r") for l in text.split(b"\n")]  # CRLF-safe
    if lines and lines[-1] == b"":
        lines.pop()
    if len(lines) % 4:
        raise FastqError(f"FASTQ span has {len(lines)} lines (not 4n)")
    for i in range(0, len(lines), 4):
        name_l, seq_l, plus_l, qual_l = lines[i:i + 4]
        if not name_l.startswith(b"@") or not plus_l.startswith(b"+"):
            raise FastqError(f"malformed FASTQ record at line {i}")
        if len(seq_l) != len(qual_l):
            raise FastqError("SEQ/QUAL length mismatch")
        q = qual_l.decode("latin-1")
        if encoding is not BaseQualityEncoding.SANGER:
            q = convert_quality(q, encoding)
        frag = SequencedFragment.from_name(
            name_l[1:].decode(), seq_l.decode(), q)
        if filter_failed_qc and frag.filter_passed is False:
            continue
        out.append(frag)
    return out


def find_fastq_record_start(buf: bytes, offset: int = 0) -> Optional[int]:
    """Offset of the first byte of the first *complete* FASTQ record at or
    after ``offset`` — the split-alignment heuristic of
    hb/FastqInputFormat.java: a line starting '@' whose +1 line is sequence
    and +2 line starts '+' (and +3 matches +1's length when visible)."""
    pos = offset
    n = len(buf)
    while pos < n:
        if pos == 0 or buf[pos - 1:pos] == b"\n":
            line_start = pos
        else:
            nl = buf.find(b"\n", pos)
            if nl < 0:
                return None
            line_start = nl + 1
        # examine up to 4 lines from line_start
        ls = line_start
        lines: List[Tuple[int, bytes]] = []
        while len(lines) < 4 and ls <= n:
            nl = buf.find(b"\n", ls)
            if nl < 0:
                lines.append((ls, buf[ls:]))
                ls = n + 1
            else:
                lines.append((ls, buf[ls:nl]))
                ls = nl + 1
        if not lines:
            return None
        l0 = lines[0][1]
        if l0.startswith(b"@"):
            seq_ok = len(lines) < 2 or _is_seq_line(lines[1][1])
            plus_ok = len(lines) < 3 or lines[2][1].startswith(b"+")
            len_ok = (len(lines) < 4 or ls > n  # 4th line may be cut short
                      or len(lines[3][1]) == len(lines[1][1]))
            if seq_ok and plus_ok and len_ok and len(lines) >= 3:
                return line_start
        pos = lines[0][0] + len(l0) + 1
    return None


def record_fully_visible(buf, pos: int) -> bool:
    """True when 4 complete lines (record-sized evidence) follow ``pos`` in
    ``buf`` — callers must not trust a candidate record start validated on a
    truncated tail unless the buffer reaches EOF."""
    n = len(buf)
    seen = 0
    p = pos
    while seen < 4:
        nl = buf.find(b"\n", p)
        if nl < 0:
            return False
        seen += 1
        p = nl + 1
    return True
