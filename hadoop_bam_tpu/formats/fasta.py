"""FASTA format: ReferenceFragment model and sequence-aligned spans.

Reference equivalents: hb/FastaInputFormat.java + hb/ReferenceFragment.java
(SURVEY.md section 2.3/2.5): reference FASTA split at ``>`` sequence starts;
the value type carries (sequence text, contig name, 1-based position within
the contig) so downstream tasks know where each fragment maps.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple


class FastaError(ValueError):
    pass


@dataclass
class ReferenceFragment:
    """One chunk of reference sequence — hb/ReferenceFragment.java."""
    sequence: str
    contig: str
    position: int   # 1-based position of sequence[0] within the contig

    def __len__(self) -> int:
        return len(self.sequence)


def parse_fasta(text: bytes, line_fragments: bool = True
                ) -> List[ReferenceFragment]:
    """Parse FASTA text into fragments.

    ``line_fragments=True`` mirrors the reference reader: one fragment per
    sequence line (with running position); False merges whole contigs."""
    out: List[ReferenceFragment] = []
    contig: Optional[str] = None
    position = 1
    merged: List[str] = []
    for raw in text.split(b"\n"):
        line = raw.strip()
        if not line:
            continue
        if line.startswith(b">"):
            if contig is not None and not line_fragments and merged:
                out.append(ReferenceFragment("".join(merged), contig, 1))
            name_parts = line[1:].split()
            if not name_parts:
                raise FastaError("empty contig name in FASTA header")
            contig = name_parts[0].decode()
            position = 1
            merged = []
            continue
        if contig is None:
            raise FastaError("sequence data before any '>' header")
        seq = line.decode()
        if line_fragments:
            out.append(ReferenceFragment(seq, contig, position))
        else:
            merged.append(seq)
        position += len(seq)
    if contig is not None and not line_fragments and merged:
        out.append(ReferenceFragment("".join(merged), contig, 1))
    return out


def find_sequence_start(buf: bytes, offset: int = 0) -> Optional[int]:
    """Offset of the next ``>`` header-line start at or after ``offset`` —
    the split-snapping rule of hb/FastaInputFormat.getSplits."""
    if offset == 0 and buf[:1] == b">":
        return 0
    pos = max(offset - 1, 0)
    while True:
        hit = buf.find(b"\n>", pos)
        if hit < 0:
            return None
        if hit + 1 >= offset:
            return hit + 1
        pos = hit + 1


def format_fasta(fragments: List[ReferenceFragment], width: int = 60) -> str:
    """Emit FASTA text (contig headers inserted when the name changes)."""
    out: List[str] = []
    last: Optional[str] = None
    for f in fragments:
        if f.contig != last:
            out.append(f">{f.contig}\n")
            last = f.contig
        seq = f.sequence
        for i in range(0, len(seq), width):
            out.append(seq[i:i + width] + "\n")
    return "".join(out)
