"""VCF text format: header model, line codec, SoA variant batches.

Reference equivalents: htsjdk ``VCFHeader`` / ``VCFCodec`` as consumed by
hb/VCFRecordReader.java and hb/util/VCFHeaderReader.java (SURVEY.md section
2.3/2.6), plus the header dictionaries that the BCF2 codec
(hadoop_bam_tpu/formats/bcf.py ~ htsjdk ``BCF2Codec``) keys records against.

[SPEC] VCFv4.x: ``##``-prefixed meta lines, one ``#CHROM`` column line
(8 fixed columns, optional FORMAT + per-sample columns), then one
tab-separated data line per variant.  BCF2 defines two dictionaries derived
from the header: the *dictionary of strings* (FILTER/INFO/FORMAT IDs in order
of appearance, "PASS" always index 0, explicit ``IDX=`` overrides) and the
*dictionary of contigs* (``##contig`` lines in order) [SPEC BCF2].
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np


class VCFError(ValueError):
    pass


MISSING = "."

_META_DEF_RE = re.compile(r"^##(?P<kind>FILTER|INFO|FORMAT|contig)=<(?P<body>.*)>\s*$")


def _parse_meta_fields(body: str) -> Dict[str, str]:
    """Parse the ``ID=DP,Number=1,Type=Integer,Description="..."`` body of a
    structured meta line, honoring quoted values with embedded commas."""
    fields: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            break
        key = body[i:eq].strip()
        j = eq + 1
        if j < n and body[j] == '"':
            k = j + 1
            while k < n and body[k] != '"':
                k += 2 if body[k] == "\\" else 1
            value = body[j + 1:k]
            i = k + 2  # past quote and comma
        else:
            k = body.find(",", j)
            k = n if k < 0 else k
            value = body[j:k]
            i = k + 1
        fields[key] = value
    return fields


@dataclass
class VCFHeaderLine:
    """One structured ##FILTER/##INFO/##FORMAT/##contig line."""
    kind: str                     # FILTER | INFO | FORMAT | contig
    id: str
    fields: Dict[str, str]        # all key=value pairs, including ID
    raw: str                      # the original line (round-trip safe)

    @property
    def number(self) -> Optional[str]:
        return self.fields.get("Number")

    @property
    def type(self) -> Optional[str]:
        return self.fields.get("Type")

    @property
    def idx(self) -> Optional[int]:
        v = self.fields.get("IDX")
        return int(v) if v is not None else None


@dataclass
class VCFHeader:
    """Parsed VCF header: raw meta text (round-trip safe) + the derived
    dictionaries BCF2 and the split machinery need."""

    meta_lines: List[str] = field(default_factory=list)   # the ## lines, raw
    samples: List[str] = field(default_factory=list)
    filters: Dict[str, VCFHeaderLine] = field(default_factory=dict)
    infos: Dict[str, VCFHeaderLine] = field(default_factory=dict)
    formats: Dict[str, VCFHeaderLine] = field(default_factory=dict)
    contigs: List[str] = field(default_factory=list)
    contig_lengths: Dict[str, int] = field(default_factory=dict)

    # --- derived dictionaries ------------------------------------------------
    @property
    def n_contigs(self) -> int:
        return len(self.contigs)

    @property
    def n_samples(self) -> int:
        return len(self.samples)

    def contig_index(self, name: str) -> int:
        try:
            return self.contigs.index(name)
        except ValueError:
            return -1

    def string_dictionary(self) -> List[str]:
        """BCF2 dictionary of strings [SPEC BCF2 section 6.2.1]: "PASS" at
        index 0, then FILTER/INFO/FORMAT IDs in order of first appearance;
        explicit IDX= fields override positions."""
        explicit: Dict[int, str] = {}
        implicit: List[str] = []
        seen = {"PASS"}

        def add(line: VCFHeaderLine) -> None:
            if line.id in seen:
                return
            seen.add(line.id)
            if line.idx is not None:
                explicit[line.idx] = line.id
            else:
                implicit.append(line.id)
        for raw in self.meta_lines:   # order of appearance across kinds
            m = _META_DEF_RE.match(raw)
            if m and m.group("kind") in ("FILTER", "INFO", "FORMAT"):
                kind = m.group("kind")
                f = _parse_meta_fields(m.group("body"))
                table = {"FILTER": self.filters, "INFO": self.infos,
                         "FORMAT": self.formats}[kind]
                line = table.get(f.get("ID", ""))
                if line is not None:
                    add(line)
        out: List[str] = ["PASS"]
        for s in implicit:
            out.append(s)
        for idx in sorted(explicit):
            while len(out) <= idx:
                out.append("")
            out[idx] = explicit[idx]
        return out

    # --- text round-trip -----------------------------------------------------
    def to_text(self) -> str:
        cols = ["#CHROM", "POS", "ID", "REF", "ALT", "QUAL", "FILTER", "INFO"]
        if self.samples:
            cols += ["FORMAT"] + list(self.samples)
        return "".join(l if l.endswith("\n") else l + "\n"
                       for l in self.meta_lines) + "\t".join(cols) + "\n"

    @classmethod
    def from_text(cls, text: str) -> "VCFHeader":
        h = cls()
        for line in text.splitlines():
            if line.startswith("##"):
                h._add_meta_line(line)
            elif line.startswith("#CHROM"):
                parts = line.rstrip("\n").split("\t")
                if len(parts) > 9:
                    h.samples = parts[9:]
            elif line.strip():
                break
        if not h.meta_lines:
            raise VCFError("no ## meta lines — not a VCF header")
        return h

    def _add_meta_line(self, line: str) -> None:
        line = line.rstrip("\n")
        self.meta_lines.append(line)
        m = _META_DEF_RE.match(line)
        if not m:
            return
        kind = m.group("kind")
        f = _parse_meta_fields(m.group("body"))
        hid = f.get("ID")
        if hid is None:
            return
        hl = VCFHeaderLine(kind=kind, id=hid, fields=f, raw=line)
        if kind == "FILTER":
            self.filters[hid] = hl
        elif kind == "INFO":
            self.infos[hid] = hl
        elif kind == "FORMAT":
            self.formats[hid] = hl
        elif kind == "contig":
            self.contigs.append(hid)
            if "length" in f:
                try:
                    self.contig_lengths[hid] = int(f["length"])
                except ValueError:
                    pass

    def ensure_contig(self, name: str) -> int:
        """Register a contig seen only in data lines (legal in VCF; BCF needs
        an index for it)."""
        idx = self.contig_index(name)
        if idx >= 0:
            return idx
        self.meta_lines.append(f"##contig=<ID={name}>")
        self.contigs.append(name)
        return len(self.contigs) - 1


@dataclass
class VcfRecord:
    """One variant line in VCF-field terms (POS 1-based; "." sentinels kept
    as None/empty so text round-trips exactly)."""

    chrom: str
    pos: int                       # 1-based
    id: Optional[str] = None       # None = '.'
    ref: str = "N"
    alts: Tuple[str, ...] = ()     # () = '.'
    qual: Optional[float] = None   # None = '.'
    filters: Optional[Tuple[str, ...]] = None  # None='.', () invalid, ('PASS',)
    info: "OrderedInfo" = field(default_factory=lambda: {})  # id -> str | True
    fmt: Tuple[str, ...] = ()      # FORMAT keys; () = no genotype block
    genotypes: List[str] = field(default_factory=list)  # raw colon-joined

    @property
    def rlen(self) -> int:
        """Length of the record on the reference: END-POS+1 if INFO/END is
        set, else len(REF) [SPEC BCF2 rlen]."""
        end = self.info.get("END")
        if isinstance(end, str):
            try:
                return int(end) - self.pos + 1
            except ValueError:
                pass
        return len(self.ref)

    @property
    def n_allele(self) -> int:
        return 1 + len(self.alts)

    def to_line(self) -> str:
        info_parts = []
        for k, v in self.info.items():
            info_parts.append(k if v is True else f"{k}={v}")
        fields = [
            self.chrom, str(self.pos),
            self.id if self.id is not None else MISSING,
            self.ref,
            ",".join(self.alts) if self.alts else MISSING,
            _fmt_qual(self.qual),
            ";".join(self.filters) if self.filters else MISSING,
            ";".join(info_parts) if info_parts else MISSING,
        ]
        if self.fmt:
            fields.append(":".join(self.fmt))
            fields.extend(self.genotypes)
        return "\t".join(fields)

    @classmethod
    def from_line(cls, line: str) -> "VcfRecord":
        parts = line.rstrip("\n").split("\t")
        if len(parts) < 8:
            raise VCFError(f"VCF line has {len(parts)} fields, need >= 8")
        info: Dict[str, Union[str, bool]] = {}
        if parts[7] != MISSING:
            for item in parts[7].split(";"):
                if not item:
                    continue
                if "=" in item:
                    k, v = item.split("=", 1)
                    info[k] = v
                else:
                    info[item] = True
        fmt: Tuple[str, ...] = ()
        genotypes: List[str] = []
        if len(parts) > 8:
            fmt = tuple(parts[8].split(":"))
            genotypes = parts[9:]
        return cls(
            chrom=parts[0], pos=int(parts[1]),
            id=None if parts[2] == MISSING else parts[2],
            ref=parts[3],
            alts=() if parts[4] == MISSING else tuple(parts[4].split(",")),
            qual=None if parts[5] == MISSING else float(parts[5]),
            filters=None if parts[6] == MISSING
            else tuple(parts[6].split(";")),
            info=info, fmt=fmt, genotypes=genotypes,
        )


def _fmt_qual(q: Optional[float]) -> str:
    if q is None:
        return MISSING
    if q == int(q) and abs(q) < 1e15:
        return str(int(q))
    # shortest text that round-trips the float32 the wire format stores
    return np.format_float_positional(np.float32(q), unique=True, trim="0")


def read_vcf_header_text(read_chunk) -> Tuple[VCFHeader, int]:
    """Read header lines from the start of a text VCF stream.

    ``read_chunk(offset, size) -> bytes`` (see utils/seekable).  Returns
    (header, byte offset of the first data line) — the rebuild of
    hb/util/VCFHeaderReader.java, which every task re-reads from file start.
    """
    buf = bytearray()
    off = 0
    while True:
        got = read_chunk(off, 1 << 16)
        if not got:
            break
        buf += got
        off += len(got)
        # stop once a complete non-# line exists
        end = _header_end(buf)
        if end is not None:
            return VCFHeader.from_text(buf[:end].decode()), end
    end = _header_end(buf, at_eof=True)
    if end is None:
        raise VCFError("no #CHROM line found")
    return VCFHeader.from_text(buf[:end].decode()), end


def _header_end(buf: bytes, at_eof: bool = False) -> Optional[int]:
    pos = 0
    n = len(buf)
    while pos < n:
        nl = buf.find(b"\n", pos)
        if nl < 0:
            if at_eof and buf[pos:pos + 1] != b"#":
                return pos
            if at_eof:
                return n
            return None
        if buf[pos:pos + 1] != b"#":
            return pos
        pos = nl + 1
    return n if at_eof else None


# ---------------------------------------------------------------------------
# SoA batch: numeric columns for device-side variant ops
# ---------------------------------------------------------------------------

class VariantBatch:
    """Structure-of-arrays view over a list of variants: the numeric columns
    (contig index, POS, rlen, QUAL, n_allele, PASS flag) feed device ops the
    same way BamBatch's fixed fields do; full records stay host-side."""

    def __init__(self, records: Sequence[VcfRecord], header: VCFHeader):
        self.records = list(records)
        self.header = header
        n = len(self.records)
        self.chrom = np.full(n, -1, dtype=np.int32)
        self.pos = np.zeros(n, dtype=np.int64)
        self.rlen = np.zeros(n, dtype=np.int32)
        self.qual = np.full(n, np.nan, dtype=np.float32)
        self.n_allele = np.zeros(n, dtype=np.int16)
        self.is_pass = np.zeros(n, dtype=bool)
        self.is_snp = np.zeros(n, dtype=bool)
        for i, r in enumerate(self.records):
            self.chrom[i] = header.contig_index(r.chrom)
            self.pos[i] = r.pos
            self.rlen[i] = r.rlen
            if r.qual is not None:
                self.qual[i] = r.qual
            self.n_allele[i] = r.n_allele
            self.is_pass[i] = bool(r.filters) and r.filters == ("PASS",)
            self.is_snp[i] = (len(r.ref) == 1 and len(r.alts) > 0 and
                              all(len(a) == 1 and a in "ACGTN"
                                  for a in r.alts))

    def __len__(self) -> int:
        return len(self.records)

    def dosage_matrix(self) -> np.ndarray:
        """ALT-allele dosage per (variant, sample): 0/1/2 for diploid GTs,
        summed alt count for polyploid, -1 for missing ('./.' or no GT
        field) — the genotype tensor of the variant device feed."""
        S = self.header.n_samples
        out = np.full((len(self), S), -1, dtype=np.int8)
        for i, r in enumerate(self.records):
            if not r.fmt or r.fmt[0] != "GT":
                continue
            for s, g in enumerate(r.genotypes[:S]):
                gt = g.split(":", 1)[0]
                if not gt or gt.startswith("."):
                    continue
                dose = 0
                ok = True
                for a in gt.replace("|", "/").split("/"):
                    if not a.isdigit():
                        ok = False
                        break
                    dose += 1 if int(a) > 0 else 0
                if ok:
                    out[i, s] = min(dose, 127)
        return out
