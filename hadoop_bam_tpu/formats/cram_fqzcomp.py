"""CRAM 3.1 fqzcomp quality codec (block compression method 7).

Rebuild of the fqzcomp_qual codec from the CRAM 3.1 compression-codecs
spec (hts-specs CRAMcodecs: adaptive range coder + context-mixing
quality model; upstream analog htscodecs/fqzcomp_qual.c, reached from
hb via htsjdk's CRAM 3.1 reader per SURVEY.md §2.3).  Decode is the
supported direction — it lets real 3.1 files whose quality blocks use
method 7 read end-to-end (VERDICT r3 #8).  Encode exists primarily to
exercise decode and as an EXPERIMENTAL opt-in for 3.1 writes
(HBAM_CRAM31_QUAL=fqzcomp).

Layout notes, honestly labelled:
- The stream structure (vers=5, gflags/pflags bits, parameter block,
  per-record sel/len/dup decoding, per-base context update) follows the
  spec pseudocode [SPEC-recalled].
- The adaptive-model constants (STEP, rescale bound) and the table
  run-length serialization are [SPEC-recalled] reconstructions that have
  NEVER been cross-validated against htscodecs output (no htslib in the
  image — SURVEY.md §0).  They are centralized below so a later
  calibration against a real file is a constants-only change.  Until
  then 3.1 quality blocks default to rANS Nx16 on write.

Model: per-context adaptive frequency coding.  Contexts mix the last
few quantized qualities (qtab/qshift/qbits), position along the read
(ptab), a running delta count (dtab) and the parameter selector, each
shifted into a 16-bit context word — the fqzcomp design.
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

FQZ_VERS = 5

# gflags [SPEC]
GFLAG_MULTI_PARAM = 1
GFLAG_HAVE_STAB = 2
GFLAG_DO_REV = 4

# pflags [SPEC]
PFLAG_DO_DEDUP = 2
PFLAG_DO_LEN = 4
PFLAG_DO_SEL = 8
PFLAG_HAVE_QMAP = 16
PFLAG_HAVE_PTAB = 32
PFLAG_HAVE_DTAB = 64
PFLAG_HAVE_QTAB = 128

CTX_SIZE = 1 << 16
CTX_MASK = CTX_SIZE - 1

# adaptive-model constants [SPEC-recalled — see module docstring]
MODEL_STEP = 8
MODEL_MAX_TOTAL = (1 << 16) - MODEL_STEP


class FqzError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Adaptive range coder (LZMA-style carry handling: the encoder keeps a
# 64-bit low with a cache byte + pending-0xFF run; the first output byte
# is the initial zero cache, which the decoder skips) [SPEC-recalled]
# ---------------------------------------------------------------------------

class RangeEncoder:
    __slots__ = ("low", "range", "cache", "cache_size", "out")

    def __init__(self) -> None:
        self.low = 0
        self.range = 0xFFFFFFFF
        self.cache = 0
        self.cache_size = 1
        self.out = bytearray()

    def _shift_low(self) -> None:
        carry = self.low >> 32
        low32 = self.low & 0xFFFFFFFF
        if low32 < 0xFF000000 or carry:
            self.out.append((self.cache + carry) & 0xFF)
            while self.cache_size > 1:
                self.out.append((0xFF + carry) & 0xFF)
                self.cache_size -= 1
            self.cache = (low32 >> 24) & 0xFF
            self.cache_size = 0
        self.cache_size += 1
        self.low = (low32 << 8) & 0xFFFFFFFF

    def encode(self, cum: int, freq: int, tot: int) -> None:
        r = self.range // tot
        self.low += cum * r
        self.range = r * freq
        while self.range < (1 << 24):
            self.range = (self.range << 8) & 0xFFFFFFFF
            self._shift_low()

    def finish(self) -> bytes:
        for _ in range(5):
            self._shift_low()
        return bytes(self.out)


class RangeDecoder:
    __slots__ = ("buf", "pos", "code", "range")

    def __init__(self, buf: bytes, pos: int = 0) -> None:
        if len(buf) - pos < 5:
            raise FqzError("fqzcomp stream truncated in range-coder init")
        self.buf = buf
        self.pos = pos + 1                 # skip the initial cache byte
        self.code = int.from_bytes(buf[self.pos:self.pos + 4], "big")
        self.pos += 4
        self.range = 0xFFFFFFFF

    def get_freq(self, tot: int) -> int:
        self.range //= tot
        f = self.code // self.range
        if f >= tot:
            raise FqzError("corrupt fqzcomp stream: frequency out of range")
        return f

    def advance(self, cum: int, freq: int) -> None:
        self.code -= cum * self.range
        self.range *= freq
        buf, n = self.buf, len(self.buf)
        while self.range < (1 << 24):
            self.range <<= 8
            b = buf[self.pos] if self.pos < n else 0
            self.code = ((self.code << 8) | b) & 0xFFFFFFFF
            self.pos += 1


class SimpleModel:
    """Adaptive frequency model: freqs start at 1, bump by MODEL_STEP on
    use, halve when the total crosses MODEL_MAX_TOTAL; a used symbol
    swaps one slot toward the front when it overtakes its neighbour
    (fqzcomp's cheap approximate sort) [SPEC-recalled]."""
    __slots__ = ("total", "freqs", "syms")

    def __init__(self, nsym: int) -> None:
        self.total = nsym
        self.freqs = [1] * nsym
        self.syms = list(range(nsym))

    def _bump(self, i: int) -> None:
        self.freqs[i] += MODEL_STEP
        self.total += MODEL_STEP
        if i > 0 and self.freqs[i] > self.freqs[i - 1]:
            f, s = self.freqs, self.syms
            f[i - 1], f[i] = f[i], f[i - 1]
            s[i - 1], s[i] = s[i], s[i - 1]
        if self.total > MODEL_MAX_TOTAL:
            t = 0
            f = self.freqs
            for j in range(len(f)):
                f[j] -= f[j] >> 1
                t += f[j]
            self.total = t

    def decode(self, rc: RangeDecoder) -> int:
        f = rc.get_freq(self.total)
        acc = 0
        freqs = self.freqs
        i = 0
        while acc + freqs[i] <= f:
            acc += freqs[i]
            i += 1
        rc.advance(acc, freqs[i])
        sym = self.syms[i]
        self._bump(i)
        return sym

    def encode(self, rc: RangeEncoder, sym: int) -> None:
        i = self.syms.index(sym)
        acc = sum(self.freqs[:i])
        rc.encode(acc, self.freqs[i], self.total)
        self._bump(i)


# ---------------------------------------------------------------------------
# table (de)serialization: quantizer tables are step functions over
# consecutive small values, stored as a run length per value 0,1,2,...
# with 255-extension [SPEC-recalled — see module docstring]
# ---------------------------------------------------------------------------

def _read_array(buf: bytes, p: int, n: int) -> Tuple[List[int], int]:
    a = [0] * n
    i = 0
    v = 0
    while i < n:
        run = 0
        while True:
            if p >= len(buf):
                raise FqzError("fqzcomp table truncated")
            b = buf[p]
            p += 1
            run += b
            if b != 255:
                break
        if i + run > n:
            raise FqzError("fqzcomp table run overflows")
        for _ in range(run):
            a[i] = v
            i += 1
        v += 1
    return a, p


def _store_array(a: Sequence[int]) -> bytes:
    out = bytearray()
    i = 0
    v = 0
    n = len(a)
    while i < n:
        if a[i] < v:
            raise FqzError("fqzcomp tables must be non-decreasing")
        run = 0
        while i < n and a[i] == v:
            i += 1
            run += 1
        while run >= 255:
            out.append(255)
            run -= 255
        out.append(run)
        v += 1
    return bytes(out)


# ---------------------------------------------------------------------------
# parameter sets
# ---------------------------------------------------------------------------

class FqzParam:
    __slots__ = ("context", "pflags", "max_sym", "qbits", "qshift", "qloc",
                 "sloc", "ploc", "dloc", "qmap", "qtab", "ptab", "dtab",
                 "qmask")

    def __init__(self) -> None:
        self.context = 0
        self.pflags = 0
        self.max_sym = 64
        self.qbits = 9
        self.qshift = 3
        self.qloc = 0
        self.sloc = 14
        self.ploc = 9
        self.dloc = 12
        self.qmap: Optional[List[int]] = None
        self.qtab = list(range(256))
        self.ptab = [0] * 1024
        self.dtab = [0] * 256
        self.qmask = (1 << self.qbits) - 1

    @property
    def do_dedup(self) -> bool:
        return bool(self.pflags & PFLAG_DO_DEDUP)

    @property
    def do_len(self) -> bool:
        return bool(self.pflags & PFLAG_DO_LEN)

    @property
    def do_sel(self) -> bool:
        return bool(self.pflags & PFLAG_DO_SEL)

    @property
    def do_pos(self) -> bool:
        return bool(self.pflags & PFLAG_HAVE_PTAB)

    @property
    def do_delta(self) -> bool:
        return bool(self.pflags & PFLAG_HAVE_DTAB)


def _read_param(buf: bytes, p: int) -> Tuple[FqzParam, int]:
    pm = FqzParam()
    if p + 7 > len(buf):
        raise FqzError("fqzcomp parameter block truncated")
    pm.context = struct.unpack_from("<H", buf, p)[0]
    pm.pflags = buf[p + 2]
    pm.max_sym = buf[p + 3]
    x = buf[p + 4]
    pm.qbits, pm.qshift = x >> 4, x & 15
    x = buf[p + 5]
    pm.qloc, pm.sloc = x >> 4, x & 15
    x = buf[p + 6]
    pm.ploc, pm.dloc = x >> 4, x & 15
    pm.qmask = (1 << pm.qbits) - 1
    p += 7
    if pm.pflags & PFLAG_HAVE_QMAP:
        if p + pm.max_sym > len(buf):
            raise FqzError("fqzcomp qmap truncated")
        pm.qmap = list(buf[p:p + pm.max_sym])
        p += pm.max_sym
    if pm.pflags & PFLAG_HAVE_QTAB:
        pm.qtab, p = _read_array(buf, p, 256)
    if pm.pflags & PFLAG_HAVE_PTAB:
        pm.ptab, p = _read_array(buf, p, 1024)
    if pm.pflags & PFLAG_HAVE_DTAB:
        pm.dtab, p = _read_array(buf, p, 256)
    return pm, p


def _write_param(pm: FqzParam) -> bytes:
    out = bytearray(struct.pack("<H", pm.context))
    out.append(pm.pflags)
    out.append(pm.max_sym)
    out.append((pm.qbits << 4) | pm.qshift)
    out.append((pm.qloc << 4) | pm.sloc)
    out.append((pm.ploc << 4) | pm.dloc)
    if pm.pflags & PFLAG_HAVE_QMAP:
        assert pm.qmap is not None and len(pm.qmap) == pm.max_sym
        out += bytes(pm.qmap)
    if pm.pflags & PFLAG_HAVE_QTAB:
        out += _store_array(pm.qtab)
    if pm.pflags & PFLAG_HAVE_PTAB:
        out += _store_array(pm.ptab)
    if pm.pflags & PFLAG_HAVE_DTAB:
        out += _store_array(pm.dtab)
    return bytes(out)


class _Models:
    """All adaptive models of one stream, created lazily per context."""

    def __init__(self, nsym: int, max_sel: int) -> None:
        self.nsym = nsym
        self.qual: Dict[int, SimpleModel] = {}
        self.len = [SimpleModel(256) for _ in range(4)]
        self.rev = SimpleModel(2)
        self.dup = SimpleModel(2)
        self.sel = SimpleModel(max_sel + 1)

    def qual_model(self, ctx: int) -> SimpleModel:
        m = self.qual.get(ctx)
        if m is None:
            m = self.qual[ctx] = SimpleModel(self.nsym)
        return m


def _update_ctx(pm: FqzParam, state: dict, q: int) -> int:
    """One context step [SPEC-recalled]: mix quantized-quality history,
    position, delta and selector into a 16-bit context."""
    last = pm.context
    state["qctx"] = ((state["qctx"] << pm.qshift) + pm.qtab[q]) & 0xFFFFFFFF
    last += (state["qctx"] & pm.qmask) << pm.qloc
    if pm.do_pos:
        state["p"] -= 1
        last += pm.ptab[min(1023, state["p"])] << pm.ploc
    if pm.do_delta:
        last += pm.dtab[min(255, state["delta"])] << pm.dloc
        state["delta"] += 1 if state["prevq"] != q else 0
        state["prevq"] = q
    if pm.do_sel:
        last += state["s"] << pm.sloc
    return last & CTX_MASK


def _decode_length(models: _Models, rc: RangeDecoder) -> int:
    b0 = models.len[0].decode(rc)
    b1 = models.len[1].decode(rc)
    b2 = models.len[2].decode(rc)
    b3 = models.len[3].decode(rc)
    return b0 | (b1 << 8) | (b2 << 16) | (b3 << 24)


def _encode_length(models: _Models, rc: RangeEncoder, ln: int) -> None:
    models.len[0].encode(rc, ln & 0xFF)
    models.len[1].encode(rc, (ln >> 8) & 0xFF)
    models.len[2].encode(rc, (ln >> 16) & 0xFF)
    models.len[3].encode(rc, (ln >> 24) & 0xFF)


# ---------------------------------------------------------------------------
# decode
# ---------------------------------------------------------------------------

def fqz_decode(buf: bytes, out_size: int,
               lens_out: Optional[list] = None) -> bytes:
    """Decode one fqzcomp quality stream into ``out_size`` bytes of
    concatenated per-record quality values (CRAM QS series).

    Returns raw quality values (no +33 offset), the series' own domain.
    ``lens_out``, when given, receives the codec's own decoded
    per-record lengths — the desync tripwire: the slice decoder compares
    them against the RL series, because a [SPEC-recalled] constant
    mismatch desyncs the range coder into silently wrong values with a
    perfectly valid-looking stream (ADVICE r4).
    """
    try:
        return _fqz_decode(buf, out_size, lens_out)
    except (IndexError, struct.error) as e:
        # any out-of-range read/model index on a corrupt stream must
        # surface as the module's error type, not a bare IndexError
        raise FqzError(f"corrupt fqzcomp stream: {e}") from e


def _fqz_decode(buf: bytes, out_size: int,
                lens_out: Optional[list] = None) -> bytes:
    if len(buf) < 2:
        raise FqzError("fqzcomp stream too short")
    if buf[0] != FQZ_VERS:
        raise FqzError(f"fqzcomp version {buf[0]} unsupported "
                       f"(expected {FQZ_VERS})")
    gflags = buf[1]
    p = 2
    nparam = 1
    if gflags & GFLAG_MULTI_PARAM:
        nparam = buf[p]
        p += 1
        if nparam < 1:
            raise FqzError("fqzcomp: zero parameter sets")
    if gflags & GFLAG_HAVE_STAB:
        max_sel = buf[p]
        p += 1
        stab, p = _read_array(buf, p, 256)
    else:
        max_sel = nparam - 1
        stab = [min(i, nparam - 1) for i in range(256)]
    params: List[FqzParam] = []
    for _ in range(nparam):
        pm, p = _read_param(buf, p)
        params.append(pm)
    max_nsym = max(pm.max_sym for pm in params) + 1
    models = _Models(max_nsym, max(max_sel, 0))
    rc = RangeDecoder(buf, p)

    out = bytearray(out_size)
    rev_flags: List[Tuple[int, int]] = []   # (start, len) of reversed recs
    i = 0
    last_len = 0
    rec_start = 0
    pm = params[0]
    state = {"qctx": 0, "p": 0, "delta": 0, "prevq": 0, "s": 0}
    while i < out_size:
        # --- record header ---
        s = models.sel.decode(rc) if max_sel > 0 else 0
        x = stab[s] if s < 256 else 0
        if x >= nparam:
            raise FqzError("fqzcomp: selector exceeds parameter sets")
        pm = params[x]
        if pm.do_len or last_len == 0:
            last_len = _decode_length(models, rc)
        if last_len <= 0 or i + last_len > out_size:
            raise FqzError("fqzcomp: record length out of bounds")
        if lens_out is not None:
            lens_out.append(last_len)
        rec_start = i
        if gflags & GFLAG_DO_REV:
            if models.rev.decode(rc):
                rev_flags.append((rec_start, last_len))
        if pm.do_dedup and models.dup.decode(rc):
            if rec_start < last_len:
                raise FqzError("fqzcomp: dup of nonexistent record")
            out[rec_start:rec_start + last_len] = \
                out[rec_start - last_len:rec_start]
            i = rec_start + last_len
            continue
        # --- per-base ---
        state = {"qctx": 0, "p": last_len, "delta": 0, "prevq": 0, "s": s}
        ctx = pm.context
        if pm.do_sel:
            ctx = (ctx + (s << pm.sloc)) & CTX_MASK
        qmap = pm.qmap
        for _ in range(last_len):
            q = models.qual_model(ctx).decode(rc)
            if qmap is not None:
                if q >= len(qmap):
                    raise FqzError("corrupt fqzcomp stream: symbol "
                                   "outside qmap")
                out[i] = qmap[q]
            else:
                out[i] = q
            i += 1
            ctx = _update_ctx(pm, state, q)
    for start, ln in rev_flags:
        out[start:start + ln] = out[start:start + ln][::-1]
    return bytes(out)


# ---------------------------------------------------------------------------
# encode (EXPERIMENTAL: round-trip driver for decode + 3.1 opt-in)
# ---------------------------------------------------------------------------

def _default_param(quals: bytes, lens: Sequence[int]) -> Tuple[int, FqzParam]:
    """Single default parameter set in the spirit of fqz_pick_parameters:
    qmap when the alphabet is sparse, position + delta contexts on."""
    seen = sorted(set(quals)) if quals else [0]
    pm = FqzParam()
    pm.pflags = PFLAG_HAVE_PTAB | PFLAG_HAVE_DTAB | PFLAG_HAVE_QTAB
    if len(set(lens)) > 1:
        pm.pflags |= PFLAG_DO_LEN
    if len(seen) <= 16 and seen[-1] > len(seen) - 1:
        # sparse alphabet: decoded symbols are indices into qmap
        pm.pflags |= PFLAG_HAVE_QMAP
        pm.qmap = list(seen)
        pm.max_sym = len(seen)
    else:
        pm.max_sym = seen[-1]
    # context layout (16 bits): q history bits 0-8, pos 9-12, delta 13-15
    pm.qbits, pm.qshift, pm.qloc = 9, 3, 0
    pm.qmask = (1 << pm.qbits) - 1
    pm.qtab = [min(v, (1 << pm.qshift) - 1) for v in range(256)]
    pm.ptab = [min(15, pos >> 6) for pos in range(1024)]
    pm.ploc = 9
    pm.dtab = [min(7, d >> 2) for d in range(256)]
    pm.dloc = 13
    return 0, pm


def fqz_encode(quals: bytes, lens: Sequence[int]) -> bytes:
    """Encode concatenated per-record quality bytes (lengths ``lens``)
    as one fqzcomp stream decodable by :func:`fqz_decode`."""
    if sum(lens) != len(quals):
        raise FqzError("record lengths do not sum to the payload size")
    if any(l <= 0 for l in lens):
        raise FqzError("record lengths must be positive")
    gflags, pm = _default_param(quals, lens)
    head = bytearray([FQZ_VERS, gflags])
    head += _write_param(pm)
    models = _Models(pm.max_sym + 1, 0)
    rc = RangeEncoder()
    if pm.qmap is not None:
        inv = {v: i for i, v in enumerate(pm.qmap)}
    else:
        inv = None
    i = 0
    last_len = 0
    for ln in lens:
        if pm.do_len or last_len == 0:
            _encode_length(models, rc, ln)
        elif ln != last_len:
            raise FqzError("varying lengths need PFLAG_DO_LEN")
        last_len = ln
        state = {"qctx": 0, "p": ln, "delta": 0, "prevq": 0, "s": 0}
        ctx = pm.context
        for _ in range(ln):
            v = quals[i]
            q = inv[v] if inv is not None else v
            if q >= pm.max_sym + 1:
                raise FqzError(f"quality {v} exceeds max_sym")
            models.qual_model(ctx).encode(rc, q)
            i += 1
            ctx = _update_ctx(pm, state, q)
    return bytes(head) + rc.finish()
