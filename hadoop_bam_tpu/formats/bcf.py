"""BCF2 binary codec: header, typed values, record encode/decode.

Reference equivalents: htsjdk ``BCF2Codec`` / ``BCF2Encoder`` as consumed by
hb/BCFRecordReader.java and hb/BCFSplitGuesser.java (SURVEY.md section 2.3),
plus hb/util/VariantContextCodec.java which reuses this wire format for
shuffle serialization.

[SPEC] BCF2.2 (hts-specs VCFv4.x section 6):

- file = BGZF-compressed (or raw) stream: magic ``BCF\\2\\2``, header block
  (l_text u32 + VCF header text, NUL-terminated), then records.
- record = l_shared u32, l_indiv u32, then the shared block
  (CHROM i32, POS i32 0-based, rlen i32, QUAL f32, n_info u16, n_allele u16,
  n_sample u24 | n_fmt<<24, ID, alleles, FILTER, INFO key/value pairs)
  and the per-sample block (n_fmt × (FORMAT key, per-sample vectors)).
- typed values: one descriptor byte ``(count << 4) | type``; count 15 means
  the real count follows as a typed scalar int.  Types: 1=int8, 2=int16,
  3=int32, 5=float32, 7=char, 0=MISSING (no payload — used for Flag).
- sentinel values: int8 0x80 missing / 0x81 end-of-vector (and the int16/
  int32/float equivalents); string dictionary + contig dictionary derived
  from the header (formats/vcf.py ``VCFHeader.string_dictionary``).
"""
from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from hadoop_bam_tpu.formats.vcf import (
    MISSING, VCFError, VCFHeader, VcfRecord,
)

BCF_MAGIC = b"BCF\x02\x02"
BCF_MAGIC_21 = b"BCF\x02\x01"

# typed-value type codes [SPEC]
T_MISSING, T_INT8, T_INT16, T_INT32, T_FLOAT, T_CHAR = 0, 1, 2, 3, 5, 7

INT8_MISSING, INT8_EOV = -128, -127
INT16_MISSING, INT16_EOV = -32768, -32767
INT32_MISSING, INT32_EOV = -2147483648, -2147483647
FLOAT_MISSING_BITS, FLOAT_EOV_BITS = 0x7F800001, 0x7F800002

# NB: the sentinels are NaNs with a specific payload; they must be written as
# raw bits (a float64 round-trip would quiet the NaN and corrupt the payload).
FLOAT_MISSING_BYTES = struct.pack("<I", FLOAT_MISSING_BITS)
FLOAT_EOV_BYTES = struct.pack("<I", FLOAT_EOV_BITS)


class BCFError(VCFError):
    pass


# ---------------------------------------------------------------------------
# typed-value primitives
# ---------------------------------------------------------------------------

def _descriptor(count: int, typ: int) -> bytes:
    if count < 15:
        return bytes([(count << 4) | typ])
    return bytes([(15 << 4) | typ]) + encode_typed_ints([count])


def _int_type_for(values: Sequence[int]) -> int:
    """Smallest int type whose non-reserved domain holds every value.
    [SPEC] reserves the bottom 8 values of each width for sentinels."""
    lo = min(values, default=0)
    hi = max(values, default=0)
    if lo >= -120 and hi <= 127:
        return T_INT8
    if lo >= -32760 and hi <= 32767:
        return T_INT16
    return T_INT32


_INT_FMT = {T_INT8: "b", T_INT16: "<h", T_INT32: "<i"}
_INT_MISSING = {T_INT8: INT8_MISSING, T_INT16: INT16_MISSING,
                T_INT32: INT32_MISSING}
_INT_EOV = {T_INT8: INT8_EOV, T_INT16: INT16_EOV, T_INT32: INT32_EOV}
_INT_SIZE = {T_INT8: 1, T_INT16: 2, T_INT32: 4}


def encode_typed_ints(values: Sequence[Optional[int]],
                      pad_to: Optional[int] = None) -> bytes:
    """Typed int vector; None encodes MISSING; padding uses END_OF_VECTOR."""
    concrete = [v for v in values if v is not None]
    typ = _int_type_for(concrete)
    n = len(values) if pad_to is None else pad_to
    out = bytearray(_descriptor(n, typ))
    fmt, miss, eov = _INT_FMT[typ], _INT_MISSING[typ], _INT_EOV[typ]
    for v in values:
        out += struct.pack(fmt, miss if v is None else v)
    for _ in range(n - len(values)):
        out += struct.pack(fmt, eov)
    return bytes(out)


def encode_typed_floats(values: Sequence[Optional[float]],
                        pad_to: Optional[int] = None) -> bytes:
    n = len(values) if pad_to is None else pad_to
    out = bytearray(_descriptor(n, T_FLOAT))
    for v in values:
        out += FLOAT_MISSING_BYTES if v is None else struct.pack("<f", v)
    for _ in range(n - len(values)):
        out += FLOAT_EOV_BYTES
    return bytes(out)


def encode_typed_string(s: Optional[str], pad_to: Optional[int] = None) -> bytes:
    data = b"" if s is None else s.encode()
    if s is None:
        data = b"."
    n = len(data) if pad_to is None else pad_to
    return _descriptor(n, T_CHAR) + data + b"\x00" * (n - len(data))


def encode_typed_int_scalar(v: int) -> bytes:
    return encode_typed_ints([v])


def skip_typed(buf: bytes, off: int) -> int:
    """Advance past one typed value without decoding it (fast-scan path)."""
    desc = buf[off]
    off += 1
    count, typ = desc >> 4, desc & 0x0F
    if count == 15:
        _, cv, off = read_typed(buf, off)
        count = int(cv[0])
    if typ == T_MISSING:
        return off
    size = 1 if typ == T_CHAR else (4 if typ == T_FLOAT
                                    else _INT_SIZE.get(typ, 4))
    return off + size * count


def read_typed(buf: bytes, off: int) -> Tuple[int, List, int]:
    """Read one typed value: returns (type, values list, new offset).
    Chars come back as one Python str; sentinels as None (missing) with
    EOV padding stripped."""
    desc = buf[off]
    off += 1
    count, typ = desc >> 4, desc & 0x0F
    if count == 15:
        _, cv, off = read_typed(buf, off)
        count = int(cv[0])
    if typ == T_MISSING:
        return typ, [], off
    if typ == T_CHAR:
        raw = buf[off:off + count]
        off += count
        return typ, [raw.rstrip(b"\x00").decode()], off
    if typ == T_FLOAT:
        vals: List = []
        for i in range(count):
            bits = struct.unpack_from("<I", buf, off + 4 * i)[0]
            if bits == FLOAT_EOV_BITS:
                vals.append(Ellipsis)
            elif bits == FLOAT_MISSING_BITS:
                vals.append(None)
            else:
                vals.append(struct.unpack_from("<f", buf, off + 4 * i)[0])
        off += 4 * count
        while vals and vals[-1] is Ellipsis:
            vals.pop()
        vals = [None if v is Ellipsis else v for v in vals]
        return typ, vals, off
    if typ in _INT_FMT:
        fmt, size = _INT_FMT[typ], _INT_SIZE[typ]
        miss, eov = _INT_MISSING[typ], _INT_EOV[typ]
        vals = []
        for i in range(count):
            v = struct.unpack_from(fmt, buf, off + size * i)[0]
            vals.append(Ellipsis if v == eov else (None if v == miss else v))
        off += size * count
        while vals and vals[-1] is Ellipsis:
            vals.pop()
        vals = [None if v is Ellipsis else v for v in vals]
        return typ, vals, off
    raise BCFError(f"unknown typed-value type {typ}")


# ---------------------------------------------------------------------------
# header block
# ---------------------------------------------------------------------------

def encode_header(header: VCFHeader) -> bytes:
    text = header.to_text().encode() + b"\x00"
    return BCF_MAGIC + struct.pack("<I", len(text)) + text


def decode_header(buf: bytes, off: int = 0) -> Tuple[VCFHeader, int]:
    magic = buf[off:off + 5]
    if magic not in (BCF_MAGIC, BCF_MAGIC_21):
        raise BCFError(f"bad BCF magic {magic!r}")
    l_text = struct.unpack_from("<I", buf, off + 5)[0]
    start = off + 9
    text = bytes(buf[start:start + l_text]).rstrip(b"\x00").decode()
    return VCFHeader.from_text(text), start + l_text


# ---------------------------------------------------------------------------
# per-field typing from the header
# ---------------------------------------------------------------------------

def _field_type(header: VCFHeader, table: str, key: str) -> str:
    defs = header.infos if table == "INFO" else header.formats
    line = defs.get(key)
    if line is not None and line.type:
        return line.type
    return "String"


def _parse_values(raw: Union[str, bool], vtype: str
                  ) -> Tuple[int, List]:
    """Split a raw VCF value string into typed values per header Type."""
    if raw is True or vtype == "Flag":
        return T_MISSING, []
    items = str(raw).split(",")
    if vtype == "Integer":
        vals = [None if x == MISSING else int(x) for x in items]
        return T_INT32, vals
    if vtype == "Float":
        vals = [None if x == MISSING else float(x) for x in items]
        return T_FLOAT, vals
    return T_CHAR, [str(raw)]


def _format_values(typ: int, vals: List, vtype: str) -> Union[str, bool]:
    if typ == T_MISSING:
        return True
    if typ == T_CHAR:
        return vals[0] if vals else MISSING
    parts = []
    for v in vals:
        if v is None:
            parts.append(MISSING)
        elif typ == T_FLOAT:
            parts.append(_fmt_float(v))
        else:
            parts.append(str(int(v)))
    return ",".join(parts)


def _fmt_float(v: float) -> str:
    if v == int(v) and abs(v) < 1e15:
        return str(int(v))
    # shortest text that round-trips the float32 the wire format stores
    return np.format_float_positional(np.float32(v), unique=True, trim="0")


# ---------------------------------------------------------------------------
# genotype (GT) packing
# ---------------------------------------------------------------------------

def _encode_gt(gt: str) -> List[Optional[int]]:
    """'0/1' -> [2, 4]; '.' -> [0]; phased '0|1' -> [2, 5] [SPEC]:
    allele value = (index + 1) << 1, bit 0 = phased-with-previous."""
    out: List[Optional[int]] = []
    tok = ""
    phased_next = False
    for ch in gt + "/":
        if ch in "/|":
            if tok == MISSING or tok == "":
                val = 0
            else:
                val = (int(tok) + 1) << 1
            if phased_next:
                val |= 1
            out.append(val)
            phased_next = ch == "|"
            tok = ""
        else:
            tok += ch
    return out


def _decode_gt(vals: List[Optional[int]]) -> str:
    parts: List[str] = []
    seps: List[str] = []
    for i, v in enumerate(vals):
        if v is None:
            continue  # EOV padding for mixed ploidy
        allele = (int(v) >> 1) - 1
        parts.append(MISSING if allele < 0 else str(allele))
        if i > 0:
            seps.append("|" if int(v) & 1 else "/")
    if not parts:
        return MISSING
    out = parts[0]
    for sep, p in zip(seps, parts[1:]):
        out += sep + p
    return out


# ---------------------------------------------------------------------------
# record encode
# ---------------------------------------------------------------------------

class BCFRecordCodec:
    """Encode/decode VcfRecord <-> BCF2 record bytes against one header."""

    def __init__(self, header: VCFHeader):
        self.header = header
        self.strings = header.string_dictionary()
        self.string_idx = {s: i for i, s in enumerate(self.strings) if s}

    def _sidx(self, key: str) -> int:
        idx = self.string_idx.get(key)
        if idx is None:
            raise BCFError(f"{key!r} not in header dictionary — add a "
                           f"##INFO/##FORMAT/##FILTER line for it")
        return idx

    def encode(self, rec: VcfRecord) -> bytes:
        h = self.header
        chrom_idx = h.contig_index(rec.chrom)
        if chrom_idx < 0:
            raise BCFError(f"contig {rec.chrom!r} not in header "
                           f"(##contig lines are mandatory for BCF)")
        shared = bytearray()
        shared += struct.pack("<iii", chrom_idx, rec.pos - 1, rec.rlen)
        shared += (FLOAT_MISSING_BYTES if rec.qual is None
                   else struct.pack("<f", rec.qual))
        n_fmt = len(rec.fmt)
        n_sample = len(rec.genotypes)
        shared += struct.pack("<HH", len(rec.info), rec.n_allele)
        shared += struct.pack("<I", (n_sample & 0xFFFFFF) | (n_fmt << 24))
        shared += encode_typed_string(rec.id)
        shared += encode_typed_string(rec.ref)
        for alt in rec.alts:
            shared += encode_typed_string(alt)
        if rec.filters is None:
            shared += encode_typed_ints([])
        else:
            shared += encode_typed_ints([self._sidx(f) if f != "PASS" else 0
                                         for f in rec.filters])
        for key, raw in rec.info.items():
            shared += encode_typed_int_scalar(self._sidx(key))
            typ, vals = _parse_values(raw, _field_type(h, "INFO", key))
            shared += self._encode_vals(typ, vals)

        indiv = bytearray()
        if n_fmt:
            per_sample = [g.split(":") for g in rec.genotypes]
            for fi, key in enumerate(rec.fmt):
                indiv += encode_typed_int_scalar(self._sidx(key))
                vtype = _field_type(h, "FORMAT", key)
                if key == "GT":
                    vecs = [_encode_gt(s[fi] if fi < len(s) else MISSING)
                            for s in per_sample]
                    width = max((len(v) for v in vecs), default=1)
                    flat: List[Optional[int]] = []
                    ints: List[int] = []
                    for v in vecs:
                        ints += [x for x in v if x is not None]
                    typ = _int_type_for(ints)
                    fmtc, eov = _INT_FMT[typ], _INT_EOV[typ]
                    indiv += _descriptor(width, typ)
                    for v in vecs:
                        for x in v:
                            indiv += struct.pack(fmtc, x)
                        for _ in range(width - len(v)):
                            indiv += struct.pack(fmtc, eov)
                else:
                    raws = [s[fi] if fi < len(s) else MISSING
                            for s in per_sample]
                    indiv += self._encode_sample_matrix(raws, vtype)
        return (struct.pack("<II", len(shared), len(indiv))
                + bytes(shared) + bytes(indiv))

    def _encode_vals(self, typ: int, vals: List) -> bytes:
        if typ == T_MISSING:
            return bytes([T_MISSING])
        if typ == T_CHAR:
            return encode_typed_string(vals[0] if vals else None)
        if typ == T_FLOAT:
            return encode_typed_floats(vals)
        return encode_typed_ints(vals)

    def _encode_sample_matrix(self, raws: List[str], vtype: str) -> bytes:
        """FORMAT field across samples: one shared descriptor, fixed width,
        short vectors padded with EOV (ints/floats) or NULs (chars)."""
        if vtype == "Integer":
            vecs = [[None if x == MISSING else int(x)
                     for x in (r.split(",") if r != MISSING else [MISSING])]
                    for r in raws]
            width = max((len(v) for v in vecs), default=1)
            ints = [x for v in vecs for x in v if x is not None]
            typ = _int_type_for(ints)
            fmtc, miss, eov = _INT_FMT[typ], _INT_MISSING[typ], _INT_EOV[typ]
            out = bytearray(_descriptor(width, typ))
            for v in vecs:
                for x in v:
                    out += struct.pack(fmtc, miss if x is None else x)
                for _ in range(width - len(v)):
                    out += struct.pack(fmtc, eov)
            return bytes(out)
        if vtype == "Float":
            vecs = [[None if x == MISSING else float(x)
                     for x in (r.split(",") if r != MISSING else [MISSING])]
                    for r in raws]
            width = max((len(v) for v in vecs), default=1)
            out = bytearray(_descriptor(width, T_FLOAT))
            for v in vecs:
                for x in v:
                    out += (FLOAT_MISSING_BYTES if x is None
                        else struct.pack("<f", x))
                for _ in range(width - len(v)):
                    out += FLOAT_EOV_BYTES
            return bytes(out)
        # Character/String: fixed-width char matrix, NUL-padded
        datas = [r.encode() for r in raws]
        width = max((len(d) for d in datas), default=1)
        out = bytearray(_descriptor(width, T_CHAR))
        for d in datas:
            out += d + b"\x00" * (width - len(d))
        return bytes(out)

    # -- decode --------------------------------------------------------------
    def decode(self, buf: bytes, off: int = 0) -> Tuple[VcfRecord, int]:
        l_shared, l_indiv = struct.unpack_from("<II", buf, off)
        base = off + 8
        end_shared = base + l_shared
        end = end_shared + l_indiv
        if end > len(buf):
            raise BCFError("truncated BCF record")
        chrom_idx, pos0, rlen = struct.unpack_from("<iii", buf, base)
        qual_bits = struct.unpack_from("<I", buf, base + 12)[0]
        qual = struct.unpack_from("<f", buf, base + 12)[0]
        n_info, n_allele = struct.unpack_from("<HH", buf, base + 16)
        ns_nf = struct.unpack_from("<I", buf, base + 20)[0]
        n_sample, n_fmt = ns_nf & 0xFFFFFF, ns_nf >> 24
        p = base + 24
        _, idv, p = read_typed(buf, p)
        rid = idv[0] if idv else None
        alleles: List[str] = []
        for _ in range(n_allele):
            _, av, p = read_typed(buf, p)
            alleles.append(av[0] if av else "")
        _, fv, p = read_typed(buf, p)
        filters: Optional[Tuple[str, ...]]
        if not fv:
            filters = None
        else:
            filters = tuple(self.strings[int(i)] if int(i) else "PASS"
                            for i in fv)
        info: Dict[str, Union[str, bool]] = {}
        for _ in range(n_info):
            _, kv, p = read_typed(buf, p)
            key = self.strings[int(kv[0])]
            typ, vals, p = read_typed(buf, p)
            info[key] = _format_values(typ, vals,
                                       _field_type(self.header, "INFO", key))
        if p != end_shared:
            p = end_shared  # tolerate writer padding
        fmt_keys: List[str] = []
        sample_fields: List[List[str]] = [[] for _ in range(n_sample)]
        while p < end and len(fmt_keys) < n_fmt:
            _, kv, p = read_typed(buf, p)
            key = self.strings[int(kv[0])]
            fmt_keys.append(key)
            desc = buf[p]
            count, typ = desc >> 4, desc & 0x0F
            p += 1
            if count == 15:
                _, cv, p = read_typed(buf, p)
                count = int(cv[0])
            vtype = _field_type(self.header, "FORMAT", key)
            for s in range(n_sample):
                if typ == T_CHAR:
                    raw = buf[p:p + count]
                    p += count
                    sample_fields[s].append(
                        raw.rstrip(b"\x00").decode() or MISSING)
                else:
                    fmtc = _INT_FMT.get(typ)
                    size = _INT_SIZE.get(typ, 4)
                    vals: List = []
                    for i in range(count):
                        if typ == T_FLOAT:
                            bits = struct.unpack_from("<I", buf, p)[0]
                            if bits == FLOAT_EOV_BITS:
                                v: object = Ellipsis
                            elif bits == FLOAT_MISSING_BITS:
                                v = None
                            else:
                                v = struct.unpack_from("<f", buf, p)[0]
                        else:
                            iv = struct.unpack_from(fmtc, buf, p)[0]
                            v = (Ellipsis if iv == _INT_EOV[typ]
                                 else None if iv == _INT_MISSING[typ] else iv)
                        vals.append(v)
                        p += size
                    while vals and vals[-1] is Ellipsis:
                        vals.pop()
                    vals = [None if v is Ellipsis else v for v in vals]
                    if key == "GT":
                        sample_fields[s].append(_decode_gt(vals))
                    else:
                        sample_fields[s].append(
                            str(_format_values(typ, vals, vtype)))
        rec = VcfRecord(
            chrom=(self.header.contigs[chrom_idx]
                   if 0 <= chrom_idx < len(self.header.contigs)
                   else str(chrom_idx)),
            pos=pos0 + 1,
            id=rid,
            ref=alleles[0] if alleles else "N",
            alts=tuple(alleles[1:]),
            qual=None if qual_bits == FLOAT_MISSING_BITS else float(qual),
            filters=filters, info=info,
            fmt=tuple(fmt_keys),
            genotypes=[":".join(f) for f in sample_fields],
        )
        return rec, end


def peek_record_sizes(buf: bytes, off: int) -> Tuple[int, int]:
    l_shared, l_indiv = struct.unpack_from("<II", buf, off)
    return l_shared, l_indiv


def plausible_record_start(buf: bytes, off: int, n_contigs: int,
                           max_len: int = 1 << 24) -> bool:
    """Cheap plausibility check for a candidate BCF record start — the
    validation core of hb/BCFSplitGuesser.java: sane block lengths, CHROM
    within the contig dictionary, non-negative 0-based POS (or -1 for
    telomere), sane counts."""
    if off + 32 > len(buf):
        return False
    l_shared, l_indiv = struct.unpack_from("<II", buf, off)
    if l_shared < 24 or l_shared > max_len or l_indiv > max_len:
        return False
    chrom_idx, pos0, rlen = struct.unpack_from("<iii", buf, off + 8)
    if not (0 <= chrom_idx < max(n_contigs, 1)):
        return False
    if pos0 < -1 or rlen < 0:
        return False
    n_info, n_allele = struct.unpack_from("<HH", buf, off + 24)
    if n_allele == 0 and n_info == 0 and l_shared == 24:
        return True
    if n_allele > 1024:
        return False
    return True


# ---------------------------------------------------------------------------
# Fast column scan (the binary twin of the text tokenizer in
# parallel/variant_pipeline.py): chrom/pos/flags + GT dosage straight from
# record bytes, skipping ID/INFO entirely and non-GT FORMAT fields by size
# arithmetic — no VcfRecord objects.  Semantics match BCFRecordCodec
# (asserted by tests).
# ---------------------------------------------------------------------------

_SNP_BASES = frozenset(b"ACGTN")
_GT_NP_DTYPES = {T_INT8: np.dtype("i1"), T_INT16: np.dtype("<i2"),
                 T_INT32: np.dtype("<i4")}


def scan_variant_columns(buf: bytes, header: VCFHeader, samples_pad: int
                         ) -> Dict[str, "np.ndarray"]:
    """All records in ``buf`` (concatenated BCF record bytes) -> typed
    columns {chrom i32, pos i32 (1-based), flags u8, dosage i8
    [n, samples_pad]}.  FLAG bits follow the variant pipeline: 1 = PASS,
    2 = SNP."""

    strings = header.string_dictionary()
    try:
        gt_key = strings.index("GT")
    except ValueError:
        gt_key = -1
    n_samples = header.n_samples

    chroms: List[int] = []
    poss: List[int] = []
    flags: List[int] = []
    dosages: List[np.ndarray] = []
    p = 0
    n_buf = len(buf)
    while p + 8 <= n_buf:
        l_shared, l_indiv = struct.unpack_from("<II", buf, p)
        base = p + 8
        end_shared = base + l_shared
        end = end_shared + l_indiv
        if end > n_buf:
            raise BCFError("truncated BCF record in scan")
        chrom_idx, pos0 = struct.unpack_from("<ii", buf, base)
        n_info, n_allele = struct.unpack_from("<HH", buf, base + 16)
        ns_nf = struct.unpack_from("<I", buf, base + 20)[0]
        n_sample, n_fmt = ns_nf & 0xFFFFFF, ns_nf >> 24
        q = skip_typed(buf, base + 24)          # ID
        # alleles: need lengths/content for the SNP flag
        snp = n_allele >= 2
        for k in range(n_allele):
            desc = buf[q]
            q += 1
            count, typ = desc >> 4, desc & 0x0F
            if count == 15:
                _, cv, q = read_typed(buf, q)
                count = int(cv[0])
            if typ != T_CHAR:
                raise BCFError("allele is not a char vector")
            # REF (k == 0) only needs length 1; ALTs must also be bases
            # (matches VariantBatch.is_snp)
            if count != 1 or (k > 0 and buf[q] not in _SNP_BASES):
                snp = False
            q += count
        # FILTER: typed int vector; PASS == exactly [0]
        f_typ, f_vals, q = read_typed(buf, q)
        is_pass = (len(f_vals) == 1 and int(f_vals[0]) == 0)
        # INFO is skipped wholesale: jump to the indiv block
        q = end_shared
        dose = np.full(samples_pad, -1, dtype=np.int8)
        seen_fmt = 0
        while q < end and seen_fmt < n_fmt:
            k_typ, k_vals, q = read_typed(buf, q)
            key = int(k_vals[0])
            desc = buf[q]
            q += 1
            count, typ = desc >> 4, desc & 0x0F
            if count == 15:
                _, cv, q = read_typed(buf, q)
                count = int(cv[0])
            size = 1 if typ == T_CHAR else (4 if typ == T_FLOAT
                                            else _INT_SIZE.get(typ, 4))
            data_len = size * count * n_sample
            if key == gt_key and typ in _GT_NP_DTYPES and n_sample:
                # GT vectors may be int8/int16/int32 (high allele counts
                # widen the encoding); all three share the same semantics.
                g = np.frombuffer(buf, _GT_NP_DTYPES[typ],
                                  count * n_sample, q
                                  ).reshape(n_sample, count).astype(np.int64)
                present = (g != _INT_EOV[typ])          # pre-EOV entries
                # allele index = (g >> 1) - 1; masking the phase bit is
                # required: a phased missing allele ('0|.') encodes as 1
                missing = present & (((g >> 1) == 0)
                                     | (g == _INT_MISSING[typ]))
                alt = present & (((g >> 1) - 1) > 0)
                # Any missing allele ('./.', '0/.') -> -1, matching
                # VariantBatch.dosage_matrix and the text tokenizer.
                d = np.where(present.any(axis=1) & ~missing.any(axis=1),
                             alt.sum(axis=1), -1)
                dose[:n_sample] = np.minimum(d, 127).astype(np.int8)
            q += data_len
            seen_fmt += 1
        chroms.append(chrom_idx)
        poss.append(pos0 + 1)
        flags.append((1 if is_pass else 0) | (2 if snp else 0))
        dosages.append(dose)
        p = end
    return {
        "chrom": np.asarray(chroms, dtype=np.int32),
        "pos": np.asarray(poss, dtype=np.int32),
        "flags": np.asarray(flags, dtype=np.uint8),
        "dosage": (np.stack(dosages) if dosages
                   else np.empty((0, samples_pad), np.int8)),
    }
