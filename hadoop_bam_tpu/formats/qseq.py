"""QSEQ (Illumina qseq) format: tab-line codec over SequencedFragment.

Reference equivalents: hb/QseqInputFormat.java + hb/QseqOutputFormat.java
(SURVEY.md section 2.3/2.4): 11 tab-separated fields per line —
machine, run, lane, tile, x, y, index, read, sequence, quality, filter —
with ``.`` standing for ``N`` in the sequence and base qualities encoded
Illumina Phred+64 by default (hb/FormatConstants.java).
"""
from __future__ import annotations

from typing import List

from hadoop_bam_tpu.config import BaseQualityEncoding
from hadoop_bam_tpu.formats.fastq import (
    FastqError, SequencedFragment, convert_quality,
)

N_FIELDS = 11


def parse_qseq_line(line: str,
                    encoding: BaseQualityEncoding = BaseQualityEncoding.ILLUMINA
                    ) -> SequencedFragment:
    parts = line.rstrip("\n").split("\t")
    if len(parts) != N_FIELDS:
        raise FastqError(f"qseq line has {len(parts)} fields, need {N_FIELDS}")
    (machine, run, lane, tile, x, y, index, read, seq, qual, filt) = parts
    if len(seq) != len(qual):
        raise FastqError(f"qseq SEQ/QUAL length mismatch "
                         f"({len(seq)} vs {len(qual)})")
    q = qual
    if encoding is not BaseQualityEncoding.SANGER:
        q = convert_quality(q, encoding)
    frag = SequencedFragment(
        sequence=seq.replace(".", "N"),
        quality=q,
        instrument=machine or None,
        run_number=int(run) if run else None,
        lane=int(lane) if lane else None,
        tile=int(tile) if tile else None,
        xpos=int(x) if x else None,
        ypos=int(y) if y else None,
        read=int(read) if read else None,
        filter_passed=filt == "1",
        index_sequence=None if index in ("", "0") else index,
    )
    frag.name = (f"{machine}_{run}:{lane}:{tile}:{x}:{y}"
                 f"#{index or 0}/{read or 1}")
    return frag


def format_qseq_line(f: SequencedFragment,
                     encoding: BaseQualityEncoding = BaseQualityEncoding.ILLUMINA
                     ) -> str:
    q = f.quality
    if encoding is not BaseQualityEncoding.SANGER:
        q = convert_quality(q, BaseQualityEncoding.SANGER, encoding)
    return "\t".join([
        f.instrument or "",
        str(f.run_number or 0),
        str(f.lane or 0),
        str(f.tile or 0),
        str(f.xpos or 0),
        str(f.ypos or 0),
        f.index_sequence or "0",
        str(f.read or 1),
        f.sequence.replace("N", "."),
        q,
        # unknown QC status must not be emitted as "failed" — default passed
        "0" if f.filter_passed is False else "1",
    ])


def parse_qseq(text: bytes,
               encoding: BaseQualityEncoding = BaseQualityEncoding.ILLUMINA,
               filter_failed_qc: bool = False) -> List[SequencedFragment]:
    out: List[SequencedFragment] = []
    for line in text.decode("latin-1").splitlines():
        if not line:
            continue
        frag = parse_qseq_line(line, encoding)
        if filter_failed_qc and frag.filter_passed is False:
            continue
        out.append(frag)
    return out
