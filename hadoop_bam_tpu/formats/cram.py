"""CRAM 3.0 container layer: varints, blocks, containers, file definition.

[SPEC] CRAM 3.0 specification (hts-specs CRAMv3.pdf).  A CRAM file is::

    file definition (26 bytes: "CRAM", major, minor, 20-byte file id)
    container*                       # first container holds the SAM header
    EOF container (38 bytes, fixed)

Each container = container header (lengths, alignment metadata, landmarks,
CRC32) + a series of blocks.  Each block = method, content type, content id,
sizes, payload, CRC32.  Blocks are independently compressed (raw / gzip /
bzip2 / lzma / rANS-4x8) — CRAM's analog of BGZF's position-invariant random
access: containers are the split grain, exactly how hb/CRAMInputFormat.java
aligns Hadoop splits to container boundaries via htsjdk's
``CramContainerIterator``.

This module is the structural layer only; entropy codecs live in
cram_codecs.py, record semantics in cram_decode.py / cram_encode.py, file
orchestration in cramio.py.
"""
from __future__ import annotations

import os
import struct
import zlib
from dataclasses import dataclass, field
from typing import BinaryIO, Iterator, List, Optional, Tuple

CRAM_MAGIC = b"CRAM"
CRAM_MAJOR = 3
CRAM_MINOR = 0

# Block compression methods [SPEC section 8; CRAM 3.1 adds 5-8]
RAW, GZIP, BZIP2, LZMA, RANS4x8 = 0, 1, 2, 3, 4
RANSNx16, ARITH, FQZCOMP, NAME_TOK = 5, 6, 7, 8

# every 3.1 block method decodes: 5 rANS Nx16 (cram_codecs_nx16),
# 6 adaptive arithmetic (cram_arith), 7 fqzcomp (cram_fqzcomp),
# 8 name tokenizer (cram_name_tok3)

# Block content types [SPEC section 8.1]
FILE_HEADER = 0
COMPRESSION_HEADER = 1
MAPPED_SLICE_HEADER = 2
EXTERNAL_DATA = 4
CORE_DATA = 5

# Sentinel used as the alignment start of the EOF container: "EOF" read as a
# 24-bit big-endian integer.  [SPEC section 9]
EOF_ALIGNMENT_START = 0x454F46


class CRAMError(ValueError):
    pass


# ---------------------------------------------------------------------------
# ITF8 / LTF8 variable-length integers [SPEC section 2.3]
# ---------------------------------------------------------------------------

def read_itf8(buf: bytes, pos: int) -> Tuple[int, int]:
    """Decode one ITF8 (32-bit) value; returns (signed value, new pos)."""
    b0 = buf[pos]
    if b0 < 0x80:
        v, pos = b0, pos + 1
    elif b0 < 0xC0:
        v = ((b0 & 0x3F) << 8) | buf[pos + 1]
        pos += 2
    elif b0 < 0xE0:
        v = ((b0 & 0x1F) << 16) | (buf[pos + 1] << 8) | buf[pos + 2]
        pos += 3
    elif b0 < 0xF0:
        v = ((b0 & 0x0F) << 24) | (buf[pos + 1] << 16) | (buf[pos + 2] << 8) \
            | buf[pos + 3]
        pos += 4
    else:
        # 5-byte form: only the LOW 4 bits of the final byte are used [SPEC]
        v = ((b0 & 0x0F) << 28) | (buf[pos + 1] << 20) | (buf[pos + 2] << 12) \
            | (buf[pos + 3] << 4) | (buf[pos + 4] & 0x0F)
        pos += 5
    if v & 0x80000000:
        v -= 1 << 32
    return v, pos


def write_itf8(v: int) -> bytes:
    v &= 0xFFFFFFFF
    if v < 0x80:
        return bytes([v])
    if v < 0x4000:
        return bytes([0x80 | (v >> 8), v & 0xFF])
    if v < 0x200000:
        return bytes([0xC0 | (v >> 16), (v >> 8) & 0xFF, v & 0xFF])
    if v < 0x10000000:
        return bytes([0xE0 | (v >> 24), (v >> 16) & 0xFF, (v >> 8) & 0xFF,
                      v & 0xFF])
    return bytes([0xF0 | ((v >> 28) & 0x0F), (v >> 20) & 0xFF,
                  (v >> 12) & 0xFF, (v >> 4) & 0xFF, v & 0x0F])


def read_ltf8(buf: bytes, pos: int) -> Tuple[int, int]:
    """Decode one LTF8 (64-bit) value; returns (signed value, new pos)."""
    b0 = buf[pos]
    if b0 < 0x80:
        n = 0
    elif b0 < 0xC0:
        n = 1
    elif b0 < 0xE0:
        n = 2
    elif b0 < 0xF0:
        n = 3
    elif b0 < 0xF8:
        n = 4
    elif b0 < 0xFC:
        n = 5
    elif b0 < 0xFE:
        n = 6
    elif b0 < 0xFF:
        n = 7
    else:
        n = 8
    mask = (1 << (7 - n)) - 1 if n < 8 else 0
    v = b0 & mask
    for i in range(n):
        v = (v << 8) | buf[pos + 1 + i]
    pos += 1 + n
    if v & (1 << 63):
        v -= 1 << 64
    return v, pos


def write_ltf8(v: int) -> bytes:
    v &= 0xFFFFFFFFFFFFFFFF
    if v < (1 << 7):
        return bytes([v])
    for n in range(1, 8):
        if v < (1 << (7 * (n + 1))):
            prefix = (0xFF << (8 - n)) & 0xFF
            out = [prefix | (v >> (8 * n))]
            for i in range(n - 1, -1, -1):
                out.append((v >> (8 * i)) & 0xFF)
            return bytes(out)
    out = [0xFF]
    for i in range(7, -1, -1):
        out.append((v >> (8 * i)) & 0xFF)
    return bytes(out)


def read_itf8_array(buf: bytes, pos: int) -> Tuple[List[int], int]:
    n, pos = read_itf8(buf, pos)
    out = []
    for _ in range(n):
        v, pos = read_itf8(buf, pos)
        out.append(v)
    return out, pos


def write_itf8_array(vals) -> bytes:
    out = [write_itf8(len(vals))]
    out += [write_itf8(v) for v in vals]
    return b"".join(out)


# ---------------------------------------------------------------------------
# File definition [SPEC section 6]
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class FileDefinition:
    major: int = CRAM_MAJOR
    minor: int = CRAM_MINOR
    file_id: bytes = b"\x00" * 20

    SIZE = 26

    def to_bytes(self) -> bytes:
        fid = (self.file_id + b"\x00" * 20)[:20]
        return CRAM_MAGIC + bytes([self.major, self.minor]) + fid

    @classmethod
    def from_bytes(cls, buf: bytes) -> "FileDefinition":
        if buf[:4] != CRAM_MAGIC:
            raise CRAMError("not a CRAM file (bad magic)")
        major, minor = buf[4], buf[5]
        if major != 3:
            raise CRAMError(f"unsupported CRAM version {major}.{minor} "
                            "(this reader implements CRAM 3.0)")
        return cls(major, minor, bytes(buf[6:26]))


# ---------------------------------------------------------------------------
# Blocks [SPEC section 8]
# ---------------------------------------------------------------------------

@dataclass
class Block:
    """One CRAM block; ``data`` is always the UNCOMPRESSED payload."""
    content_type: int
    content_id: int = 0
    data: bytes = b""
    method: int = RAW          # method to use when serializing
    # method-specific serialization context: for FQZCOMP, the per-record
    # lengths of the concatenated quality payload (the codec models
    # record boundaries; a plain byte blob has none)
    aux: Optional[list] = None

    def to_bytes(self) -> bytes:
        raw = self.data
        method = self.method
        if method == GZIP:
            co = zlib.compressobj(6, zlib.DEFLATED, 31)
            comp = co.compress(raw) + co.flush()
        elif method == RANS4x8:
            from hadoop_bam_tpu.formats.cram_codecs import rans4x8_encode
            comp = rans4x8_encode(raw, order=0)
        elif method == RANSNx16:
            from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
                NX16_PACK, NX16_RLE, rans_nx16_encode,
            )
            comp = rans_nx16_encode(raw, NX16_PACK | NX16_RLE)
        elif method == NAME_TOK:
            from hadoop_bam_tpu.formats.cram_name_tok3 import (
                Tok3Error, tok3_encode,
            )
            try:
                comp = tok3_encode(raw)
            except Tok3Error:
                # payload isn't a clean name block; general codec instead
                from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
                    NX16_PACK, NX16_RLE, rans_nx16_encode,
                )
                method = RANSNx16
                comp = rans_nx16_encode(raw, NX16_PACK | NX16_RLE)
        elif method == ARITH:
            from hadoop_bam_tpu.formats.cram_arith import (
                ARITH_ORDER1, arith_encode,
            )
            comp = arith_encode(raw, ARITH_ORDER1)
        elif method == FQZCOMP:
            from hadoop_bam_tpu.formats.cram_fqzcomp import fqz_encode
            # no rANS fallback here: fqz_encode only raises when the
            # per-record lengths disagree with the payload — a writer
            # bug that must surface at write time, not ship as a
            # silently-downgraded block
            comp = fqz_encode(raw, self.aux if self.aux else [len(raw)])
        elif method == RAW:
            comp = raw
        else:
            raise CRAMError(f"unsupported write method {method}")
        # don't let a poorly-compressing payload grow the file
        if method != RAW and len(comp) >= len(raw):
            method, comp = RAW, raw
        body = bytes([method, self.content_type]) + write_itf8(self.content_id) \
            + write_itf8(len(comp)) + write_itf8(len(raw)) + comp
        return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)

    @classmethod
    def from_buffer(cls, buf: bytes, pos: int) -> Tuple["Block", int]:
        raw, pos = parse_raw_block(buf, pos)
        return cls.from_raw(raw), pos

    @classmethod
    def from_raw(cls, raw: "RawBlock",
                 data: Optional[bytes] = None) -> "Block":
        """Materialize from a parsed-but-compressed block; ``data``
        overrides decompression (the batched rANS path)."""
        aux = None
        if data is None:
            if raw.method == FQZCOMP:
                # capture the codec's own per-record lengths: the slice
                # decoder cross-checks them against the RL series (the
                # fqzcomp desync tripwire)
                from hadoop_bam_tpu.formats.cram_fqzcomp import fqz_decode
                aux = []
                data = fqz_decode(raw.payload, raw.rsize, lens_out=aux)
            else:
                data = decompress_block_payload(raw.method, raw.payload,
                                                raw.rsize)
        if len(data) != raw.rsize:
            raise CRAMError(
                f"block inflated to {len(data)} bytes, expected "
                f"{raw.rsize}")
        return cls(raw.content_type, raw.content_id, data, raw.method,
                   aux)


@dataclass
class RawBlock:
    """A block header + still-compressed payload (CRC already checked) —
    the unit the batched entropy decoders consume."""
    method: int
    content_type: int
    content_id: int
    payload: bytes
    rsize: int


def parse_raw_block(buf: bytes, pos: int) -> Tuple[RawBlock, int]:
    start = pos
    method = buf[pos]
    ctype = buf[pos + 1]
    pos += 2
    cid, pos = read_itf8(buf, pos)
    csize, pos = read_itf8(buf, pos)
    rsize, pos = read_itf8(buf, pos)
    payload = bytes(buf[pos:pos + csize])
    if len(payload) != csize:
        raise CRAMError("truncated block payload")
    pos += csize
    (crc,) = struct.unpack_from("<I", buf, pos)
    pos += 4
    if zlib.crc32(buf[start:pos - 4]) & 0xFFFFFFFF != crc:
        raise CRAMError("block CRC32 mismatch")
    return RawBlock(method, ctype, cid, payload, rsize), pos


def decompress_block_payload(method: int, payload: bytes, rsize: int) -> bytes:
    if method == RAW:
        return payload
    if method == GZIP:
        return zlib.decompress(payload, wbits=31)
    if method == BZIP2:
        import bz2
        return bz2.decompress(payload)
    if method == LZMA:
        import lzma
        return lzma.decompress(payload)
    if method == RANS4x8:
        from hadoop_bam_tpu.formats.cram_codecs import rans4x8_decode
        return rans4x8_decode(payload)
    if method == RANSNx16:
        from hadoop_bam_tpu.formats.cram_codecs_nx16 import rans_nx16_decode
        return rans_nx16_decode(payload, rsize)
    if method == NAME_TOK:
        from hadoop_bam_tpu.formats.cram_name_tok3 import tok3_decode
        return tok3_decode(payload, rsize)
    if method == FQZCOMP:
        from hadoop_bam_tpu.formats.cram_fqzcomp import fqz_decode
        return fqz_decode(payload, rsize)
    if method == ARITH:
        from hadoop_bam_tpu.formats.cram_arith import arith_decode
        return arith_decode(payload, rsize)
    raise CRAMError(f"unknown block compression method {method}")


# ---------------------------------------------------------------------------
# Container header [SPEC section 7]
# ---------------------------------------------------------------------------

@dataclass
class ContainerHeader:
    length: int                 # byte length of the blocks section
    ref_seq_id: int = -1        # -1 unmapped, -2 multi-ref
    start: int = 0
    span: int = 0
    n_records: int = 0
    record_counter: int = 0
    bases: int = 0
    n_blocks: int = 0
    landmarks: List[int] = field(default_factory=list)

    def to_bytes(self) -> bytes:
        body = struct.pack("<i", self.length)
        body += write_itf8(self.ref_seq_id) + write_itf8(self.start)
        body += write_itf8(self.span) + write_itf8(self.n_records)
        body += write_ltf8(self.record_counter) + write_ltf8(self.bases)
        body += write_itf8(self.n_blocks) + write_itf8_array(self.landmarks)
        return body + struct.pack("<I", zlib.crc32(body) & 0xFFFFFFFF)

    @classmethod
    def from_buffer(cls, buf: bytes, pos: int) -> Tuple["ContainerHeader", int]:
        start0 = pos
        (length,) = struct.unpack_from("<i", buf, pos)
        pos += 4
        ref_seq_id, pos = read_itf8(buf, pos)
        start, pos = read_itf8(buf, pos)
        span, pos = read_itf8(buf, pos)
        n_records, pos = read_itf8(buf, pos)
        record_counter, pos = read_ltf8(buf, pos)
        bases, pos = read_ltf8(buf, pos)
        n_blocks, pos = read_itf8(buf, pos)
        landmarks, pos = read_itf8_array(buf, pos)
        (crc,) = struct.unpack_from("<I", buf, pos)
        pos += 4
        if zlib.crc32(buf[start0:pos - 4]) & 0xFFFFFFFF != crc:
            raise CRAMError("container header CRC32 mismatch")
        return cls(length, ref_seq_id, start, span, n_records, record_counter,
                   bases, n_blocks, landmarks), pos

    @property
    def is_eof(self) -> bool:
        return (self.n_records == 0 and self.ref_seq_id == -1
                and self.start == EOF_ALIGNMENT_START)


@dataclass
class Container:
    header: ContainerHeader
    blocks: List[Block]
    offset: int = 0             # absolute file offset of the container start


def build_container(blocks: List[Block], *, ref_seq_id: int, start: int,
                    span: int, n_records: int, record_counter: int,
                    bases: int, landmarks: List[int]) -> bytes:
    payload = b"".join(b.to_bytes() for b in blocks)
    hdr = ContainerHeader(
        length=len(payload), ref_seq_id=ref_seq_id, start=start, span=span,
        n_records=n_records, record_counter=record_counter, bases=bases,
        n_blocks=len(blocks), landmarks=landmarks)
    return hdr.to_bytes() + payload


def eof_container() -> bytes:
    """The CRAM 3.0 EOF container: an empty container whose alignment start
    spells "EOF".  Constructed (not pasted) — the result must be exactly the
    38-byte marker the spec fixes; cramio asserts that at import time."""
    empty_maps = b"\x01\x00" * 3   # three empty maps: size=1, count=0
    blk = Block(COMPRESSION_HEADER, 0, empty_maps, RAW)
    return build_container(
        [blk], ref_seq_id=-1, start=EOF_ALIGNMENT_START, span=0, n_records=0,
        record_counter=0, bases=0, landmarks=[])


EOF_CONTAINER = eof_container()
assert len(EOF_CONTAINER) == 38, len(EOF_CONTAINER)


# ---------------------------------------------------------------------------
# Scanning (the split grain — hb/CRAMInputFormat.java's container iterator)
# ---------------------------------------------------------------------------

def read_container(buf: bytes, pos: int,
                   rans_backend: Optional[str] = None
                   ) -> Tuple[Container, int]:
    """Parse one container.  All rANS blocks decode in ONE batch — the
    intra-container block parallelism the device decoder (ops/rans.py)
    exploits; ``rans_backend`` (default env HBAM_RANS_BACKEND or "host")
    picks where."""
    offset = pos
    hdr, pos = ContainerHeader.from_buffer(buf, pos)
    end = pos + hdr.length
    raws: List[RawBlock] = []
    while pos < end:
        raw, pos = parse_raw_block(buf, pos)
        raws.append(raw)
    if pos != end:
        raise CRAMError("container blocks overran the declared length")

    backend = rans_backend or os.environ.get("HBAM_RANS_BACKEND", "host")
    if backend not in ("host", "device", "auto"):
        raise CRAMError(f"unknown rANS backend {backend!r} "
                        "(expected host/device/auto)")
    decoded: dict = {}
    rans_idx = [i for i, r in enumerate(raws) if r.method == RANS4x8]
    if backend == "device" and rans_idx:
        from hadoop_bam_tpu.ops.rans import rans_decode_batch
        outs = rans_decode_batch([raws[i].payload for i in rans_idx],
                                 backend=backend)
        decoded = dict(zip(rans_idx, outs))
    blocks = [Block.from_raw(r, decoded.get(i))
              for i, r in enumerate(raws)]
    return Container(hdr, blocks, offset), pos


def scan_container_offsets(buf: bytes, pos: int = FileDefinition.SIZE
                           ) -> Iterator[Tuple[int, ContainerHeader]]:
    """Yield (absolute offset, header) of every container without inflating
    any block — the cheap pass split planning needs."""
    n = len(buf)
    while pos < n:
        offset = pos
        hdr, after = ContainerHeader.from_buffer(buf, pos)
        yield offset, hdr
        pos = after + hdr.length
