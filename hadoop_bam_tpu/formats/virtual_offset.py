"""BGZF virtual file offsets.

[SPEC] SAMv1 section 4.1.1: a virtual offset packs the compressed-file offset
of a BGZF block start (48 bits) and the offset within the inflated block
(16 bits) into one 64-bit value::

    voffset = (compressed_block_start << 16) | offset_within_inflated_block

This convention is load-bearing across the whole reference library
(SURVEY.md section 2.2): hb/FileVirtualSplit.java carries start/end virtual
offsets, hb/BAMRecordReader.java keys every record by its virtual pointer, and
hb/SplittingBAMIndex.java stores sampled record voffsets.  We preserve it
exactly so .splitting-bai / .bai / .sbi sidecars interoperate.
"""
from __future__ import annotations

from typing import NamedTuple

import numpy as np

SHIFT = 16
UOFFSET_MASK = 0xFFFF


class VirtualOffset(NamedTuple):
    coffset: int  # compressed offset of the BGZF block start in the file
    uoffset: int  # offset within the inflated block contents

    @property
    def packed(self) -> int:
        return make_voffset(self.coffset, self.uoffset)

    @classmethod
    def from_packed(cls, v: int) -> "VirtualOffset":
        return cls(*split_voffset(v))

    def __int__(self) -> int:
        return self.packed


def make_voffset(coffset, uoffset):
    """Pack (block start, in-block offset) into a 64-bit virtual offset.
    Works on Python ints and NumPy arrays alike."""
    if isinstance(coffset, np.ndarray) or isinstance(uoffset, np.ndarray):
        return (np.asarray(coffset, dtype=np.uint64) << np.uint64(SHIFT)) | (
            np.asarray(uoffset, dtype=np.uint64) & np.uint64(UOFFSET_MASK))
    return (int(coffset) << SHIFT) | (int(uoffset) & UOFFSET_MASK)


def split_voffset(v):
    """Unpack a 64-bit virtual offset into (coffset, uoffset)."""
    if isinstance(v, np.ndarray):
        v = np.asarray(v, dtype=np.uint64)
        return v >> np.uint64(SHIFT), (v & np.uint64(UOFFSET_MASK)).astype(np.int64)
    v = int(v)
    return v >> SHIFT, v & UOFFSET_MASK
