"""BAM binary format: header, record layout, structure-of-arrays batches.

[SPEC] SAMv1 section 4.2.  A BAM file is a BGZF stream whose inflated contents
are::

    magic "BAM\\1" | l_text (i32) | text | n_ref (i32) |
    per ref: l_name (i32) | name\\0 | l_ref (i32) |
    records...

Each alignment record::

    block_size i32            # byte length of the rest of the record
    refID      i32            # -1 = unmapped
    pos        i32            # 0-based leftmost, -1 = unmapped
    l_read_name u8            # includes trailing NUL
    mapq       u8
    bin        u16
    n_cigar_op u16
    flag       u16
    l_seq      i32
    next_refID i32
    next_pos   i32
    tlen       i32
    read_name  char[l_read_name]          # NUL-terminated
    cigar      u32[n_cigar_op]            # op_len<<4 | op  (op in "MIDNSHP=X")
    seq        u8[(l_seq+1)/2]            # 4-bit "=ACMGRSVTWYHKDBN"
    qual       u8[l_seq]                  # 0xFF = absent
    tags       ...                        # two-char tag, type char, value

Reference equivalents: htsjdk ``BAMRecordCodec`` (decode/encode) and
hb/SAMRecordWritable.java (which serializes via the same layout);
hb/LazyBAMRecordFactory.java's deferred field parse is rebuilt here as the
columnar ``BamBatch``: fields are *gathered on first access* with vectorized
NumPy (and on device in hadoop_bam_tpu/ops/unpack_bam.py), so map-side filters
never pay full parse cost — same goal, SoA shape instead of per-object laziness.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

BAM_MAGIC = b"BAM\x01"
FIXED_RECORD_PREFIX = 36  # bytes from block_size through tlen inclusive
CORE_AFTER_BLOCKSIZE = 32

SEQ_NIBBLE = "=ACMGRSVTWYHKDBN"          # [SPEC] 4-bit base codes
CIGAR_OPS = "MIDNSHP=X"                  # [SPEC] op codes 0..8
_SEQ_NIBBLE_B = SEQ_NIBBLE.encode()
_CIGAR_OPS_B = CIGAR_OPS.encode()

# Flag bits [SPEC] section 1.4
FPAIRED, FPROPER_PAIR, FUNMAP, FMUNMAP = 0x1, 0x2, 0x4, 0x8
FREVERSE, FMREVERSE, FREAD1, FREAD2 = 0x10, 0x20, 0x40, 0x80
FSECONDARY, FQCFAIL, FDUP, FSUPPLEMENTARY = 0x100, 0x200, 0x400, 0x800


class BAMError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Header
# ---------------------------------------------------------------------------

@dataclass
class SAMHeader:
    """SAM/BAM header: raw @-line text plus the binary reference dictionary.

    The reference reads headers through htsjdk ``SAMFileHeader`` via
    hb/util/SAMHeaderReader.java; here the text is kept verbatim (round-trip
    safe) and the reference dictionary is exposed as parallel arrays because
    the split guesser (hb/BAMSplitGuesser.java) only needs ``n_ref`` and
    per-reference lengths for plausibility checks.
    """

    text: str = ""
    ref_names: List[str] = field(default_factory=list)
    ref_lengths: List[int] = field(default_factory=list)

    @property
    def n_ref(self) -> int:
        return len(self.ref_names)

    def ref_id(self, name: str) -> int:
        try:
            return self.ref_names.index(name)
        except ValueError:
            return -1

    def ref_name(self, rid: int) -> str:
        return "*" if rid < 0 or rid >= self.n_ref else self.ref_names[rid]

    # -- binary (BAM) encoding [SPEC] --
    def to_bam_bytes(self) -> bytes:
        out = bytearray()
        text = self.text.encode()
        out += BAM_MAGIC
        out += struct.pack("<i", len(text))
        out += text
        out += struct.pack("<i", self.n_ref)
        for name, length in zip(self.ref_names, self.ref_lengths):
            nb = name.encode() + b"\x00"
            out += struct.pack("<i", len(nb)) + nb + struct.pack("<i", length)
        return bytes(out)

    @classmethod
    def from_bam_bytes(cls, buf: bytes, offset: int = 0) -> Tuple["SAMHeader", int]:
        """Parse from inflated BAM bytes; returns (header, offset_after)."""
        if buf[offset:offset + 4] != BAM_MAGIC:
            raise BAMError("bad BAM magic")
        p = offset + 4
        (l_text,) = struct.unpack_from("<i", buf, p); p += 4
        text = bytes(buf[p:p + l_text]).rstrip(b"\x00").decode(); p += l_text
        (n_ref,) = struct.unpack_from("<i", buf, p); p += 4
        names, lengths = [], []
        for _ in range(n_ref):
            (l_name,) = struct.unpack_from("<i", buf, p); p += 4
            names.append(bytes(buf[p:p + l_name - 1]).decode()); p += l_name
            (l_ref,) = struct.unpack_from("<i", buf, p); p += 4
            lengths.append(l_ref)
        return cls(text=text, ref_names=names, ref_lengths=lengths), p

    # -- text (SAM) encoding --
    def to_sam_text(self) -> str:
        """Header text, synthesizing @SQ lines from the binary dictionary when
        the text lacks them (htsjdk does the same merge)."""
        if "@SQ" in self.text or not self.ref_names:
            return self.text
        sq = "".join(f"@SQ\tSN:{n}\tLN:{l}\n"
                     for n, l in zip(self.ref_names, self.ref_lengths))
        # @HD first if present, then @SQ, then the rest.
        lines = self.text.splitlines(keepends=True)
        hd = [l for l in lines if l.startswith("@HD")]
        rest = [l for l in lines if not l.startswith("@HD")]
        return "".join(hd) + sq + "".join(rest)

    @classmethod
    def from_sam_text(cls, text: str) -> "SAMHeader":
        names, lengths = [], []
        for line in text.splitlines():
            if line.startswith("@SQ"):
                fields = dict(f.split(":", 1) for f in line.split("\t")[1:]
                              if ":" in f)
                if "SN" in fields and "LN" in fields:
                    names.append(fields["SN"])
                    lengths.append(int(fields["LN"]))
        return cls(text=text if text.endswith("\n") or not text else text + "\n",
                   ref_names=names, ref_lengths=lengths)


def reg2bin(beg: int, end: int) -> int:
    """[SPEC] SAMv1 section 5.3: compute the UCSC binning-scheme bin."""
    end -= 1
    if beg >> 14 == end >> 14:
        return ((1 << 15) - 1) // 7 + (beg >> 14)
    if beg >> 17 == end >> 17:
        return ((1 << 12) - 1) // 7 + (beg >> 17)
    if beg >> 20 == end >> 20:
        return ((1 << 9) - 1) // 7 + (beg >> 20)
    if beg >> 23 == end >> 23:
        return ((1 << 6) - 1) // 7 + (beg >> 23)
    if beg >> 26 == end >> 26:
        return ((1 << 3) - 1) // 7 + (beg >> 26)
    return 0


# ---------------------------------------------------------------------------
# Record walking (boundary discovery) and SoA batch
# ---------------------------------------------------------------------------

def walk_record_offsets(buf, start: int = 0, end: Optional[int] = None,
                        max_records: Optional[int] = None) -> np.ndarray:
    """Serial record-boundary walk: offsets of each record's block_size field.

    The chain offsets[i+1] = offsets[i] + 4 + block_size[i] is inherently
    sequential (this is exactly why BAM is "unsplittable" and the reference
    needs split guessers).  The native C++ path (native/) does this walk at
    memory speed; this NumPy/Python version is the portable reference.
    """
    mv = memoryview(buf)
    n = len(mv) if end is None else end
    offs: List[int] = []
    p = start
    while p + 4 <= n:
        bs = int.from_bytes(mv[p:p + 4], "little", signed=True)
        if bs < CORE_AFTER_BLOCKSIZE:
            raise BAMError(f"bad block_size {bs} at offset {p}")
        if p + 4 + bs > n:
            break  # record truncated at span end (caller handles tail)
        offs.append(p)
        p += 4 + bs
        if max_records is not None and len(offs) >= max_records:
            break
    return np.asarray(offs, dtype=np.int64)


def _gather_u8(data: np.ndarray, idx: np.ndarray) -> np.ndarray:
    return data[idx]


def _gather_le(data: np.ndarray, offs: np.ndarray, nbytes: int, signed: bool
               ) -> np.ndarray:
    """Vectorized little-endian integer gather at arbitrary byte offsets."""
    acc = np.zeros(offs.shape, dtype=np.uint64)
    for i in range(nbytes):
        acc |= data[offs + i].astype(np.uint64) << np.uint64(8 * i)
    if signed:
        bits = 8 * nbytes
        acc = acc.astype(np.int64)
        sign = np.int64(1) << np.int64(bits - 1)
        acc = (acc ^ sign) - sign if nbytes < 8 else acc
        return acc
    return acc.astype(np.int64) if nbytes < 8 else acc


class BamBatch:
    """Structure-of-arrays view over the BAM records inside one inflated span.

    This is the framework's record currency — the analog of a stream of
    htsjdk SAMRecords, but columnar: the inflated bytes are kept as one
    uint8 array and every fixed field is a lazily-gathered NumPy column.
    Variable-length payloads (name/cigar/seq/qual/tags) stay in place in the
    byte buffer and are addressed by per-record offset columns — the SoA
    rebuild of hb/LazyBAMRecordFactory.java's lazy field decode.
    """

    def __init__(self, data: np.ndarray, offsets: np.ndarray,
                 header: Optional[SAMHeader] = None,
                 voffsets: Optional[np.ndarray] = None):
        self.data = np.asarray(data, dtype=np.uint8)
        self.offsets = np.asarray(offsets, dtype=np.int64)
        self.header = header
        # Per-record virtual offsets (the reference's LongWritable record key,
        # hb/BAMRecordReader.java); filled by the reader when known.
        self.voffsets = voffsets
        self._cache: Dict[str, np.ndarray] = {}

    def __len__(self) -> int:
        return int(self.offsets.size)

    def _col(self, name: str, off: int, nbytes: int, signed: bool) -> np.ndarray:
        if name not in self._cache:
            self._cache[name] = _gather_le(self.data, self.offsets + off,
                                           nbytes, signed)
        return self._cache[name]

    # Fixed fields [SPEC layout offsets]
    @property
    def block_size(self): return self._col("block_size", 0, 4, True)
    @property
    def refid(self): return self._col("refid", 4, 4, True)
    @property
    def pos(self): return self._col("pos", 8, 4, True)
    @property
    def l_read_name(self): return self._col("l_read_name", 12, 1, False)
    @property
    def mapq(self): return self._col("mapq", 13, 1, False)
    @property
    def bin(self): return self._col("bin", 14, 2, False)
    @property
    def n_cigar(self): return self._col("n_cigar", 16, 2, False)
    @property
    def flag(self): return self._col("flag", 18, 2, False)
    @property
    def l_seq(self): return self._col("l_seq", 20, 4, True)
    @property
    def mate_refid(self): return self._col("mate_refid", 24, 4, True)
    @property
    def mate_pos(self): return self._col("mate_pos", 28, 4, True)
    @property
    def tlen(self): return self._col("tlen", 32, 4, True)

    # Derived payload offset columns
    @property
    def name_offset(self): return self.offsets + FIXED_RECORD_PREFIX
    @property
    def cigar_offset(self): return self.name_offset + self.l_read_name
    @property
    def seq_offset(self): return self.cigar_offset + 4 * self.n_cigar
    @property
    def qual_offset(self): return self.seq_offset + (self.l_seq + 1) // 2
    @property
    def tags_offset(self): return self.qual_offset + self.l_seq
    @property
    def record_end(self): return self.offsets + 4 + self.block_size

    def reference_span(self) -> np.ndarray:
        """Per-record alignment span on the reference (bases consumed by
        M/D/N/=/X CIGAR ops), vectorized over the ragged cigar arrays.
        Records with '*' CIGAR fall back to l_seq (htsjdk's convention for
        computing an end when no cigar is present)."""
        if "ref_span" in self._cache:
            return self._cache["ref_span"]
        counts = self.n_cigar.astype(np.int64)
        total = int(counts.sum())
        span = np.where(self.l_seq > 0, self.l_seq, 0).astype(np.int64)
        if total:
            firsts = np.cumsum(counts) - counts
            flat = np.arange(total, dtype=np.int64) - np.repeat(firsts, counts)
            offs = np.repeat(self.cigar_offset, counts) + 4 * flat
            vals = _gather_le(self.data, offs, 4, False)
            oplen = vals >> 4
            op = vals & 0xF
            consumes = (op == 0) | (op == 2) | (op == 3) | (op == 7) | (op == 8)
            seg = np.repeat(np.arange(counts.size), counts)
            cig_span = np.zeros(counts.size, dtype=np.int64)
            np.add.at(cig_span, seg, (oplen * consumes).astype(np.int64))
            span = np.where(counts > 0, cig_span, span)
        self._cache["ref_span"] = span
        return span

    def select(self, indices: np.ndarray) -> "BamBatch":
        """Row subset sharing the same byte buffer (zero-copy on data)."""
        idx = np.asarray(indices)
        return BamBatch(
            self.data, self.offsets[idx], header=self.header,
            voffsets=None if self.voffsets is None else self.voffsets[idx])

    # Per-record accessors (scalar paths for tests/CLI; batch paths in ops/)
    def read_name(self, i: int) -> str:
        o = int(self.name_offset[i]); l = int(self.l_read_name[i])
        return self.data[o:o + l - 1].tobytes().decode()

    def cigar_string(self, i: int) -> str:
        n = int(self.n_cigar[i])
        if n == 0:
            return "*"
        o = int(self.cigar_offset[i])
        raw = self.data[o:o + 4 * n].view("<u4")
        return "".join(f"{int(v) >> 4}{CIGAR_OPS[int(v) & 0xF]}" for v in raw)

    def seq_string(self, i: int) -> str:
        l = int(self.l_seq[i])
        if l == 0:
            return "*"
        o = int(self.seq_offset[i])
        packed = self.data[o:o + (l + 1) // 2]
        hi = packed >> 4
        lo = packed & 0xF
        nibbles = np.empty(packed.size * 2, dtype=np.uint8)
        nibbles[0::2] = hi
        nibbles[1::2] = lo
        lut = np.frombuffer(_SEQ_NIBBLE_B, dtype=np.uint8)
        return lut[nibbles[:l]].tobytes().decode()

    def qual_string(self, i: int) -> str:
        l = int(self.l_seq[i])
        o = int(self.qual_offset[i])
        q = self.data[o:o + l]
        if l == 0 or (q.size and q[0] == 0xFF):
            return "*"
        return (q + 33).tobytes().decode()

    def tags_raw(self, i: int) -> bytes:
        return self.data[int(self.tags_offset[i]):int(self.record_end[i])].tobytes()

    def tags(self, i: int) -> List[Tuple[str, str, object]]:
        return parse_tags(self.tags_raw(i))

    def to_sam_line(self, i: int) -> str:
        h = self.header or SAMHeader()
        flag = int(self.flag[i])
        rid = int(self.refid[i])
        pos = int(self.pos[i])
        mrid = int(self.mate_refid[i])
        mpos = int(self.mate_pos[i])
        if mrid == rid and mrid >= 0:
            rnext = "="
        else:
            rnext = h.ref_name(mrid)
        fields = [
            self.read_name(i), str(flag), h.ref_name(rid), str(pos + 1),
            str(int(self.mapq[i])), self.cigar_string(i), rnext,
            str(mpos + 1), str(int(self.tlen[i])),
            self.seq_string(i), self.qual_string(i),
        ]
        fields += [format_tag(t) for t in self.tags(i)]
        return "\t".join(fields)

    def record_bytes(self, i: int) -> bytes:
        return self.data[int(self.offsets[i]):int(self.record_end[i])].tobytes()


# ---------------------------------------------------------------------------
# Tags [SPEC] section 4.2.4
# ---------------------------------------------------------------------------

_TAG_SCALAR = {"c": ("<b", 1), "C": ("<B", 1), "s": ("<h", 2), "S": ("<H", 2),
               "i": ("<i", 4), "I": ("<I", 4), "f": ("<f", 4), "A": None}
_ARRAY_ELEM = {"c": ("<b", 1), "C": ("<B", 1), "s": ("<h", 2), "S": ("<H", 2),
               "i": ("<i", 4), "I": ("<I", 4), "f": ("<f", 4)}


def parse_tags(raw: bytes) -> List[Tuple[str, str, object]]:
    out: List[Tuple[str, str, object]] = []
    p, n = 0, len(raw)
    while p + 3 <= n:
        tag = raw[p:p + 2].decode()
        typ = chr(raw[p + 2])
        p += 3
        if typ == "A":
            out.append((tag, "A", chr(raw[p]))); p += 1
        elif typ in _TAG_SCALAR and _TAG_SCALAR[typ]:
            fmt, sz = _TAG_SCALAR[typ]
            out.append((tag, typ, struct.unpack_from(fmt, raw, p)[0])); p += sz
        elif typ in ("Z", "H"):
            z = raw.index(b"\x00", p)
            out.append((tag, typ, raw[p:z].decode())); p = z + 1
        elif typ == "B":
            etyp = chr(raw[p]); p += 1
            (cnt,) = struct.unpack_from("<I", raw, p); p += 4
            fmt, sz = _ARRAY_ELEM[etyp]
            vals = list(struct.unpack_from(f"<{cnt}{fmt[1]}", raw, p)); p += cnt * sz
            out.append((tag, "B", (etyp, vals)))
        else:
            raise BAMError(f"unknown tag type {typ!r}")
    return out


def format_tag(t: Tuple[str, str, object]) -> str:
    tag, typ, val = t
    if typ in "cCsSiI":
        return f"{tag}:i:{val}"
    if typ == "f":
        return f"{tag}:f:{val:g}"
    if typ == "A":
        return f"{tag}:A:{val}"
    if typ in ("Z", "H"):
        return f"{tag}:{typ}:{val}"
    if typ == "B":
        etyp, vals = val
        body = ",".join(f"{v:g}" if etyp == "f" else str(v) for v in vals)
        return f"{tag}:B:{etyp},{body}"
    raise BAMError(f"unknown tag type {typ!r}")


def encode_tag(tag: str, typ: str, val) -> bytes:
    head = tag.encode() + typ.encode()
    if typ == "A":
        return head + val.encode()
    if typ in _TAG_SCALAR and _TAG_SCALAR[typ]:
        fmt, _ = _TAG_SCALAR[typ]
        return head + struct.pack(fmt, val)
    if typ in ("Z", "H"):
        return head + val.encode() + b"\x00"
    if typ == "B":
        etyp, vals = val
        fmt, _ = _ARRAY_ELEM[etyp]
        return head + etyp.encode() + struct.pack("<I", len(vals)) + \
            struct.pack(f"<{len(vals)}{fmt[1]}", *vals)
    raise BAMError(f"unknown tag type {typ!r}")


def tag_from_sam(text: str) -> Tuple[str, str, object]:
    tag, typ, val = text.split(":", 2)
    if typ == "i":
        v = int(val)
        return (tag, "i", v)  # write as i32; htsjdk narrows similarly on write
    if typ == "f":
        return (tag, "f", float(val))
    if typ == "A":
        return (tag, "A", val)
    if typ in ("Z", "H"):
        return (tag, typ, val)
    if typ == "B":
        parts = val.split(",")
        etyp = parts[0]
        conv = float if etyp == "f" else int
        return (tag, "B", (etyp, [conv(x) for x in parts[1:]]))
    raise BAMError(f"bad SAM tag {text!r}")


# ---------------------------------------------------------------------------
# Record encoding (writer path)
# ---------------------------------------------------------------------------

_SEQ_CODE: Dict[int, int] = {c: i for i, c in enumerate(_SEQ_NIBBLE_B)}
_CIGAR_CODE: Dict[int, int] = {c: i for i, c in enumerate(_CIGAR_OPS_B)}


def encode_record(*, name: str, flag: int, refid: int, pos: int, mapq: int,
                  cigar: Sequence[Tuple[int, str]] = (), mate_refid: int = -1,
                  mate_pos: int = -1, tlen: int = 0, seq: str = "*",
                  qual: str = "*", tags: Sequence[Tuple[str, str, object]] = (),
                  bin_: Optional[int] = None) -> bytes:
    """Encode one alignment record to BAM bytes (htsjdk BAMRecordCodec.encode
    analog).  ``pos``/``mate_pos`` are 0-based (BAM convention); ``cigar`` is
    a sequence of (length, op_char)."""
    nameb = name.encode() + b"\x00"
    if not 1 <= len(nameb) <= 255:
        raise BAMError("read name length out of range")
    cigar_raw = b"".join(struct.pack("<I", (l << 4) | _CIGAR_CODE[ord(op)])
                         for l, op in cigar)
    if seq == "*" or seq == "":
        l_seq, seq_raw = 0, b""
    else:
        sb = seq.upper().encode()
        l_seq = len(sb)
        codes = [_SEQ_CODE.get(c, 15) for c in sb]
        if l_seq % 2:
            codes.append(0)
        seq_raw = bytes((codes[i] << 4) | codes[i + 1]
                        for i in range(0, len(codes), 2))
    if l_seq == 0:
        qual_raw = b""
    elif qual == "*" or qual == "":
        qual_raw = b"\xff" * l_seq
    else:
        if len(qual) != l_seq:
            raise BAMError("qual length != seq length")
        qual_raw = bytes(ord(c) - 33 for c in qual)
    tags_raw = b"".join(encode_tag(*t) for t in tags)
    if bin_ is None:
        end = pos + _cigar_reference_span(cigar)
        bin_ = reg2bin(max(pos, 0), max(end, pos + 1)) if pos >= 0 else 4680
    body = struct.pack("<iiBBHHHiiii", refid, pos, len(nameb), mapq, bin_,
                       len(cigar), flag, l_seq, mate_refid, mate_pos, tlen)
    body += nameb + cigar_raw + seq_raw + qual_raw + tags_raw
    return struct.pack("<i", len(body)) + body


def _cigar_reference_span(cigar: Sequence[Tuple[int, str]]) -> int:
    span = sum(l for l, op in cigar if op in "MDN=X")
    return span if span > 0 else 1


def parse_cigar_string(s: str) -> List[Tuple[int, str]]:
    if s == "*" or not s:
        return []
    out: List[Tuple[int, str]] = []
    num = 0
    for ch in s:
        if ch.isdigit():
            num = num * 10 + ord(ch) - 48
        else:
            if ch not in CIGAR_OPS:
                raise BAMError(f"bad CIGAR op {ch!r}")
            out.append((num, ch))
            num = 0
    return out
