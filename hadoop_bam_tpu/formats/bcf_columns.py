"""Vectorized (columnar) BCF record decode: typed columns out, no
per-record Python objects and no per-typed-value ``struct`` calls.

The variant stats/tensor path needs columns — CHROM/POS/rlen/QUAL/
n_allele/n_fmt, the PASS/SNP flag byte, and the GT dosage matrix — not
``VcfRecord`` objects.  This module decodes a whole span of concatenated
BCF record bytes into exactly those columns with NumPy batch ops, the
BCF twin of ``formats/cram_columns.py``:

* record framing is one cheap cursor walk over the ``l_shared``/
  ``l_indiv`` length prefixes (or arrives precomputed from the span
  reader, which walks the same prefixes anyway to find the span end);
* the 24-byte fixed shared prefix of every record is one [n, 24]
  gather, so CHROM/POS/rlen/QUAL/n_info/n_allele/n_sample/n_fmt fall
  out as NumPy views;
* the variable typed-value region (ID, alleles, FILTER, FORMAT keys
  and descriptors) is decoded by a *lockstep cursor*: one int64 cursor
  per record advances through the same structural position of every
  record simultaneously, exploiting the length-prefixed typed-value
  encoding [SPEC BCF2.2] — each structural step is O(1) NumPy ops over
  all records instead of O(records) Python iterations.  The number of
  steps is max(n_allele) + max(n_fmt) + 3, which real call sets keep
  tiny (biallelic + GT:AD:DP-ish);
* INFO is never touched: the shared-block length prefix lets the
  cursor jump straight to the per-sample block;
* GT payloads are gathered per (width, ploidy, n_sample) group — one
  2-D byte gather + view per distinct layout (one group for the
  overwhelmingly common uniform-diploid case) — and reduced to the
  ALT-dosage matrix with the exact semantics of
  ``formats/bcf.scan_variant_columns`` / ``VariantBatch.dosage_matrix``.

Eligibility: pathological geometry that would make the lockstep rounds
degenerate (thousands of alleles or FORMAT fields per record, absurd
GT ploidy) returns None via ``decode_bcf_columns`` and the caller falls
back to the record-serial scanner, which handles anything.  Corruption
— truncated records, undefined typed-value codes, overrunning vectors —
raises ``BCFError`` loudly on BOTH paths; the columnar path never
mis-decodes silently (tests/test_bcf_columns.py fuzzes this).

Reference-side equivalent: htsjdk ``BCF2Codec`` as driven by
hb/BCFRecordReader.java (SURVEY.md section 2.3); the columnar design is
the TPU-shaped replacement for its per-record object assembly, the same
move ``cram_columns.py`` made for the CRAM slice decode.
"""
from __future__ import annotations

import struct
from typing import Dict, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.formats.bcf import (
    BCFError, FLOAT_MISSING_BITS, T_CHAR, T_FLOAT, T_INT8, T_INT16,
    T_INT32, T_MISSING, _INT_EOV, _INT_MISSING,
)
from hadoop_bam_tpu.formats.vcf import VCFHeader

# FLAG bits shared with parallel/variant_pipeline.py
FLAG_PASS = 1
FLAG_SNP = 2

# the stats/tensor tile schema (what the device feed ships)
STAT_KEYS = ("chrom", "pos", "flags", "dosage")

# element byte width per typed-value type code [SPEC BCF2.2 6.3.3];
# -1 marks the reserved codes — hitting one is corruption, not data
_ELEM_SIZE = np.full(16, -1, np.int64)
for _t, _w in ((T_MISSING, 0), (T_INT8, 1), (T_INT16, 2), (T_INT32, 4),
               (T_FLOAT, 4), (T_CHAR, 1)):
    _ELEM_SIZE[_t] = _w

_INT_TYPES = (T_INT8, T_INT16, T_INT32)
_GT_DTYPES = {T_INT8: np.dtype("i1"), T_INT16: np.dtype("<i2"),
              T_INT32: np.dtype("<i4")}
_SNP_BASE_VALS = np.frombuffer(b"ACGTN", np.uint8)

# lockstep-round guards: past these the vectorized passes degenerate
# into as many rounds as a scalar loop — fall back to the record scan
_MAX_ALLELE_ROUNDS = 512
_MAX_FMT_ROUNDS = 64
_MAX_GT_PLOIDY = 256


class _Ineligible(Exception):
    """Span cannot take the columnar path; caller falls back."""


def frame_record_starts(buf: bytes) -> np.ndarray:
    """Start offset of every record in concatenated BCF record bytes.

    One add-chase over the ``l_shared``/``l_indiv`` prefixes — the only
    sequentially dependent step of the columnar decode (span readers
    that walk records anyway hand their starts in instead).  Raises
    ``BCFError`` if the final record overruns or trailing bytes remain.
    """
    n = len(buf)
    starts = []
    unpack = struct.Struct("<II").unpack_from
    p = 0
    while p + 8 <= n:
        starts.append(p)
        l_shared, l_indiv = unpack(buf, p)
        p += 8 + l_shared + l_indiv
    if p != n:
        raise BCFError("truncated BCF record in columnar frame")
    return np.asarray(starts, np.int64)


def stat_columns(cols: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Subset a full column dict to the device-tile schema (STAT_KEYS)."""
    return {k: cols[k] for k in STAT_KEYS}


def decode_bcf_columns(buf: bytes, header: VCFHeader, samples_pad: int,
                       starts: Optional[np.ndarray] = None
                       ) -> Optional[Dict[str, np.ndarray]]:
    """All records in ``buf`` -> typed columns, or None when only the
    record-serial path should decode them (pathological geometry).

    Returns {chrom i32, pos i32 (1-based), rlen i32, qual f32 (NaN =
    missing), n_allele i16, n_fmt i16, flags u8 (bit0 PASS, bit1 SNP),
    dosage i8 [n, samples_pad]}.  ``STAT_KEYS`` columns are equal to
    ``formats/bcf.scan_variant_columns`` output and the extended columns
    to the ``VariantBatch`` view of ``BCFRecordCodec.decode`` —
    tests/test_bcf_columns.py pins both.  Corrupt input raises
    ``BCFError``; it is never decoded loosely.
    """
    try:
        return _decode_columns(buf, header, samples_pad, starts)
    except _Ineligible:
        return None


# ---------------------------------------------------------------------------
# lockstep typed-value primitives
# ---------------------------------------------------------------------------

def _gather_u32(b: np.ndarray, off: np.ndarray) -> np.ndarray:
    """Little-endian u32 at each offset (offsets must be in bounds)."""
    r = b[off[:, None] + np.arange(4)].astype(np.uint32)
    return r[:, 0] | r[:, 1] << 8 | r[:, 2] << 16 | r[:, 3] << 24


def _gather_ints(b: np.ndarray, off: np.ndarray, typ: np.ndarray,
                 mask: np.ndarray) -> np.ndarray:
    """Sign-extended typed int (width per-row from ``typ``) at ``off``
    for rows where ``mask``; other rows read clamped junk and return 0.
    Callers bounds-check masked rows beforehand."""
    idx = np.minimum(off[:, None] + np.arange(4), b.size - 1)
    r = b[idx].astype(np.int64)
    u = r[:, 0] | r[:, 1] << 8 | r[:, 2] << 16 | r[:, 3] << 24
    sx8 = ((u & 0xFF) ^ 0x80) - 0x80
    sx16 = ((u & 0xFFFF) ^ 0x8000) - 0x8000
    sx32 = ((u & 0xFFFFFFFF) ^ 0x80000000) - 0x80000000
    out = np.where(typ == T_INT8, sx8,
                   np.where(typ == T_INT16, sx16, sx32))
    return np.where(mask, out, 0)


def _elem_size(typ: np.ndarray, active: np.ndarray) -> np.ndarray:
    es = _ELEM_SIZE[typ]
    if bool((active & (es < 0)).any()):
        bad = int(typ[active & (es < 0)][0])
        raise BCFError(f"unknown typed-value type {bad}")
    return np.where(active, es, 0)


def _read_descriptor(b: np.ndarray, q: np.ndarray, active: np.ndarray,
                     rec_end: np.ndarray
                     ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Lockstep read of one typed-value descriptor: (count, typ,
    cursor-after-header) for rows where ``active`` (inactive rows pass
    through with count 0 / type MISSING / unchanged cursor)."""
    if bool((active & (q >= rec_end)).any()):
        raise BCFError("typed-value descriptor overruns record")
    safe = np.where(active, q, 0)
    desc = b[safe].astype(np.int64)
    count = desc >> 4
    typ = desc & 0x0F
    hdr = np.ones_like(q)
    ext = active & (count == 15)
    if bool(ext.any()):
        # real count follows as a typed scalar int [SPEC]
        q2 = safe + 1
        if bool((ext & (q2 >= rec_end)).any()):
            raise BCFError("extended count overruns record")
        d2 = b[np.where(ext, q2, 0)].astype(np.int64)
        etyp = d2 & 0x0F
        ecnt = d2 >> 4
        if bool((ext & ((ecnt != 1) | ~np.isin(etyp, _INT_TYPES))).any()):
            raise BCFError("malformed extended-count scalar")
        esize = np.where(ext, _ELEM_SIZE[etyp], 0)
        if bool((ext & (q2 + 1 + esize > rec_end)).any()):
            raise BCFError("extended count overruns record")
        val = _gather_ints(b, q2 + 1, etyp, ext)
        if bool((ext & (val < 0)).any()):
            raise BCFError("negative typed-value count")
        count = np.where(ext, val, count)
        hdr = np.where(ext, 2 + esize, hdr)
    count = np.where(active, count, 0)
    typ = np.where(active, typ, T_MISSING)
    return count, typ, q + np.where(active, hdr, 0)


def _skip_typed(b: np.ndarray, q: np.ndarray, active: np.ndarray,
                rec_end: np.ndarray) -> np.ndarray:
    count, typ, q2 = _read_descriptor(b, q, active, rec_end)
    q3 = q2 + _elem_size(typ, active) * count
    if bool((active & (q3 > rec_end)).any()):
        raise BCFError("typed value overruns record")
    return q3


# ---------------------------------------------------------------------------
# the decode
# ---------------------------------------------------------------------------

def _empty_columns(samples_pad: int) -> Dict[str, np.ndarray]:
    return {
        "chrom": np.zeros(0, np.int32), "pos": np.zeros(0, np.int32),
        "rlen": np.zeros(0, np.int32), "qual": np.zeros(0, np.float32),
        "n_allele": np.zeros(0, np.int16), "n_fmt": np.zeros(0, np.int16),
        "flags": np.zeros(0, np.uint8),
        "dosage": np.empty((0, samples_pad), np.int8),
    }


def _cursor_walk(b: np.ndarray, header: VCFHeader,
                 starts: np.ndarray) -> Dict[str, np.ndarray]:
    """The sequentially dependent half of the columnar decode: bounds
    checks, the fixed 24-byte prefix views, and the lockstep typed-value
    walk (alleles -> SNP test, FILTER -> PASS, FORMAT -> GT layout).

    Shared verbatim by the host columnar decode (``_decode_columns``,
    which adds the GT->dosage gather) and the device unpack route
    (``decode_bcf_cursor_meta``, which ships the GT layout to the mesh
    and gathers there).  Raises ``BCFError`` on corruption and
    ``_Ineligible`` on pathological geometry — identically for both
    consumers, so the planes agree on every input's outcome class."""
    n = starts.size
    if bool((starts < 0).any()) or int(starts.max()) + 32 > b.size:
        raise BCFError("BCF record start out of range")
    l_shared = _gather_u32(b, starts).astype(np.int64)
    l_indiv = _gather_u32(b, starts + 4).astype(np.int64)
    if bool((l_shared < 24).any()):
        raise BCFError("BCF shared block shorter than its fixed fields")
    end_shared = starts + 8 + l_shared
    rec_end = end_shared + l_indiv
    if int(rec_end.max()) > b.size:
        raise BCFError("truncated BCF record in columnar scan")

    # ---- fixed 24-byte shared prefix: one gather, then views ------------
    fixed = b[starts[:, None] + np.arange(8, 32)]
    chrom = fixed[:, 0:4].copy().view("<i4").ravel()
    pos0 = fixed[:, 4:8].copy().view("<i4").ravel()
    rlen = fixed[:, 8:12].copy().view("<i4").ravel()
    qual_bits = fixed[:, 12:16].copy().view("<u4").ravel()
    qual = fixed[:, 12:16].copy().view("<f4").ravel().copy()
    qual[qual_bits == FLOAT_MISSING_BITS] = np.nan
    n_allele = fixed[:, 18:20].copy().view("<u2").ravel().astype(np.int64)
    ns_nf = fixed[:, 20:24].copy().view("<u4").ravel()
    n_sample = (ns_nf & 0xFFFFFF).astype(np.int64)
    n_fmt = (ns_nf >> 24).astype(np.int64)

    max_allele = int(n_allele.max(initial=0))
    max_fmt = int(n_fmt.max(initial=0))
    if max_allele > _MAX_ALLELE_ROUNDS or max_fmt > _MAX_FMT_ROUNDS:
        raise _Ineligible("lockstep round count too large")

    all_rows = np.ones(n, bool)
    q = _skip_typed(b, starts + 32, all_rows, rec_end)      # ID

    # ---- alleles: SNP test in max(n_allele) lockstep rounds -------------
    snp = n_allele >= 2
    for k in range(max_allele):
        active = n_allele > k
        count, typ, q2 = _read_descriptor(b, q, active, rec_end)
        if bool((active & (typ != T_CHAR)).any()):
            raise BCFError("allele is not a char vector")
        q3 = q2 + count
        if bool((active & (q3 > rec_end)).any()):
            raise BCFError("allele overruns record")
        # REF (k == 0) only needs length 1; ALTs must also be bases
        # (matches VariantBatch.is_snp / scan_variant_columns)
        ok = active & (count == 1)
        if k > 0:
            base = b[np.where(ok, q2, 0)]
            ok &= np.isin(base, _SNP_BASE_VALS)
        snp &= ~active | ok
        q = q3

    # ---- FILTER: PASS == exactly the one int value 0 --------------------
    count, typ, q2 = _read_descriptor(b, q, all_rows, rec_end)
    es = _elem_size(typ, all_rows)
    if bool((q2 + es * count > rec_end).any()):
        raise BCFError("FILTER vector overruns record")
    int_filter = np.isin(typ, _INT_TYPES)
    one = int_filter & (count == 1)
    fval = _gather_ints(b, q2, typ, one)
    is_pass = one & (fval == 0)

    # ---- per-sample block (INFO is jumped over wholesale) ---------------
    strings = header.string_dictionary()
    try:
        gt_key = strings.index("GT")
    except ValueError:
        gt_key = -1
    q = end_shared
    gt_typ = np.zeros(n, np.int64)          # 0 = no GT seen
    gt_count = np.zeros(n, np.int64)
    gt_off = np.zeros(n, np.int64)
    for _j in range(max_fmt):
        # n_fmt overruns are tolerated exactly like the record path:
        # the walk stops at the block end, it does not raise
        active = (n_fmt > _j) & (q < rec_end)
        if not bool(active.any()):
            break
        kcnt, ktyp, q2 = _read_descriptor(b, q, active, rec_end)
        if bool((active & (~np.isin(ktyp, _INT_TYPES) | (kcnt != 1))).any()):
            raise BCFError("malformed FORMAT key")
        if bool((active & (q2 + _elem_size(ktyp, active) > rec_end)).any()):
            raise BCFError("FORMAT key overruns record")
        key = _gather_ints(b, q2, ktyp, active)
        q3 = q2 + _elem_size(ktyp, active) * kcnt
        fcnt, ftyp, q4 = _read_descriptor(b, q3, active, rec_end)
        data_len = _elem_size(ftyp, active) * fcnt * n_sample
        if bool((active & (q4 + data_len > rec_end)).any()):
            raise BCFError("FORMAT data overruns record")
        is_gt = (active & (key == gt_key) & np.isin(ftyp, _INT_TYPES)
                 & (n_sample > 0)) if gt_key >= 0 else np.zeros(n, bool)
        if bool(is_gt.any()):
            gt_typ[is_gt] = ftyp[is_gt]
            gt_count[is_gt] = fcnt[is_gt]
            gt_off[is_gt] = q4[is_gt]
        q = q4 + data_len

    return {
        "chrom": chrom, "pos0": pos0, "rlen": rlen, "qual": qual,
        "n_allele": n_allele, "n_fmt": n_fmt, "n_sample": n_sample,
        "snp": snp, "is_pass": is_pass,
        "gt_typ": gt_typ, "gt_count": gt_count, "gt_off": gt_off,
    }


def decode_bcf_cursor_meta(buf: bytes, header: VCFHeader,
                           samples_pad: int,
                           starts: Optional[np.ndarray] = None
                           ) -> Optional[Dict[str, object]]:
    """Host-side record metadata for the DEVICE variant unpack: the
    cursor walk runs here (it is serially dependent and branch-heavy —
    the half that does NOT vectorize), but the bulk byte work (the
    24-byte prefix assembly and the GT payload gathers) is left to the
    mesh, which reads them straight out of the resolved-bytes buffer via
    ``ops/inflate_device.variant_prefix_device`` /
    ``variant_gt_dosage_device``.

    Returns None when the span is ineligible for the columnar layout
    (same geometry guards as ``decode_bcf_columns``); raises the same
    ``BCFError`` taxonomy on corruption.  Dict:

    - ``n``: record count; ``starts`` i64 [n] record start offsets;
    - ``flags``: u8 [n] — the PASS|SNP byte, fully host-derived;
    - ``gt_groups``: list of (rows i64[], offs i64[], width, ploidy,
      n_sample) — one entry per distinct GT layout, the grouping the
      device gather is keyed by (rows not covered by any group keep the
      all-missing dosage row).
    """
    b = np.frombuffer(buf, np.uint8)
    if starts is None:
        starts = frame_record_starts(buf)
    starts = np.asarray(starts, np.int64)
    n = starts.size
    if n == 0:
        return {"n": 0, "starts": starts, "flags": np.zeros(0, np.uint8),
                "gt_groups": []}
    try:
        wk = _cursor_walk(b, header, starts)
        gt_typ, gt_count = wk["gt_typ"], wk["gt_count"]
        n_sample = wk["n_sample"]
        have = gt_typ > 0
        if bool((have & (gt_count > _MAX_GT_PLOIDY)).any()):
            raise _Ineligible("GT ploidy too large")
        if bool((have & (n_sample > samples_pad)).any()):
            raise _Ineligible("record carries more samples than the tile")
    except _Ineligible:
        return None
    groups = []
    if bool(have.any()):
        combo = (gt_typ << 48) | (gt_count << 24) | n_sample
        for c in np.unique(combo[have]):
            rows = np.flatnonzero(have & (combo == c))
            groups.append((rows, wk["gt_off"][rows],
                           _GT_DTYPES[int(gt_typ[rows[0]])].itemsize,
                           int(gt_count[rows[0]]),
                           int(n_sample[rows[0]])))
    flags = (wk["is_pass"].astype(np.uint8) * FLAG_PASS
             | wk["snp"].astype(np.uint8) * FLAG_SNP)
    return {"n": n, "starts": starts, "flags": flags, "gt_groups": groups}


def _decode_columns(buf: bytes, header: VCFHeader, samples_pad: int,
                    starts: Optional[np.ndarray]) -> Dict[str, np.ndarray]:
    b = np.frombuffer(buf, np.uint8)
    if starts is None:
        starts = frame_record_starts(buf)
    starts = np.asarray(starts, np.int64)
    n = starts.size
    if n == 0:
        return _empty_columns(samples_pad)
    wk = _cursor_walk(b, header, starts)
    chrom, pos0, rlen, qual = (wk["chrom"], wk["pos0"], wk["rlen"],
                               wk["qual"])
    n_allele, n_fmt, n_sample = (wk["n_allele"], wk["n_fmt"],
                                 wk["n_sample"])
    snp, is_pass = wk["snp"], wk["is_pass"]
    gt_typ, gt_count, gt_off = (wk["gt_typ"], wk["gt_count"],
                                wk["gt_off"])

    # ---- GT -> dosage, gathered per (width, ploidy, n_sample) group -----
    dosage = np.full((n, samples_pad), -1, np.int8)
    have = gt_typ > 0
    if bool((have & (gt_count > _MAX_GT_PLOIDY)).any()):
        raise _Ineligible("GT ploidy too large")
    if bool((have & (n_sample > samples_pad)).any()):
        raise _Ineligible("record carries more samples than the tile")
    if bool(have.any()):
        combo = (gt_typ << 48) | (gt_count << 24) | n_sample
        for c in np.unique(combo[have]):
            sel = have & (combo == c)
            rows = np.flatnonzero(sel)
            typ_g = int(gt_typ[rows[0]])
            cnt = int(gt_count[rows[0]])
            ns = int(n_sample[rows[0]])
            dt = _GT_DTYPES[typ_g]
            w = dt.itemsize
            raw = b[gt_off[rows, None] + np.arange(w * cnt * ns)]
            g = raw.view(dt).reshape(rows.size, ns, cnt).astype(np.int64)
            present = g != _INT_EOV[typ_g]          # pre-EOV entries
            # allele index = (g >> 1) - 1; masking the phase bit is
            # required: a phased missing allele ('0|.') encodes as 1
            missing = present & (((g >> 1) == 0)
                                 | (g == _INT_MISSING[typ_g]))
            alt = present & (((g >> 1) - 1) > 0)
            d = np.where(present.any(axis=2) & ~missing.any(axis=2),
                         alt.sum(axis=2), -1)
            dosage[rows[:, None], np.arange(ns)] = \
                np.minimum(d, 127).astype(np.int8)

    return {
        "chrom": chrom.astype(np.int32),
        "pos": (pos0 + 1).astype(np.int32),
        "rlen": rlen.astype(np.int32),
        "qual": qual.astype(np.float32),
        "n_allele": n_allele.astype(np.int16),
        "n_fmt": n_fmt.astype(np.int16),
        "flags": (is_pass.astype(np.uint8) * FLAG_PASS
                  | snp.astype(np.uint8) * FLAG_SNP),
        "dosage": dosage,
    }
