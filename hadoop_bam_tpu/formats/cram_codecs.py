"""rANS 4x8 entropy codec (CRAM 3.0 block method 4).

[SPEC] CRAMcodecs section "rANS codec": byte-wise range asymmetric numeral
system with four interleaved 32-bit states, 12-bit normalized frequencies
(total 4096), lower renormalization bound 0x800000.  Two flavours:

- order-0: one frequency table; state j decodes output positions j mod 4.
- order-1: 256 context tables keyed on the previous byte; each state decodes
  one quarter of the output (contexts start at 0 per quarter).

Stream layout::

    order (1) | compressed size of everything after this 9-byte prefix (u32 LE)
    | uncompressed size (u32 LE) | frequency table | 4 initial states (u32 LE
    each) interleaved with renormalization bytes

Frequency tables use the spec's run-length symbol encoding (a run byte follows
the second of two consecutive symbols) and 1-or-2-byte frequencies (values ≥
128 stored big-endian-ish as ``0x80|hi, lo``).

Reference-side equivalent: htsjdk/htslib's rANS implementations, reached from
Hadoop-BAM through htsjdk CRAM decode (SURVEY.md section 2.8: "Pallas rANS
decode kernel" is the TPU goal; ops/rans.py builds the batched device decode
on top of the table layout produced here).

The hot decode loop is vectorized with NumPy across the 4 states (order-0)
and across the 4 quarters (order-1); Python-level iteration is only over
output positions / 4.
"""
from __future__ import annotations

import contextlib
import struct
from typing import List, Tuple

import numpy as np

RANS_ORDER_0 = 0
RANS_ORDER_1 = 1

TF_SHIFT = 12
TOTFREQ = 1 << TF_SHIFT          # 4096
RANS_LOW = 1 << 23               # renormalization lower bound


class RansError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Frequency normalization + table serialization
# ---------------------------------------------------------------------------

def _normalize_freqs(counts: np.ndarray, total: int = TOTFREQ) -> np.ndarray:
    """Scale raw counts so they sum to exactly ``total``, keeping every
    present symbol's frequency >= 1."""
    counts = counts.astype(np.int64)
    n = int(counts.sum())
    if n == 0:
        return np.zeros(256, dtype=np.int64)
    freqs = (counts * total) // n
    freqs[(counts > 0) & (freqs == 0)] = 1
    # fix rounding drift by adjusting the largest bucket
    drift = total - int(freqs.sum())
    if drift != 0:
        j = int(np.argmax(freqs))
        if freqs[j] + drift < 1:
            raise RansError("cannot normalize frequency table")
        freqs[j] += drift
    return freqs


def _write_freq(f: int) -> bytes:
    if f < 128:
        return bytes([f])
    return bytes([0x80 | (f >> 8), f & 0xFF])


def _read_freq(buf: bytes, pos: int) -> Tuple[int, int]:
    b = buf[pos]
    if b < 0x80:
        return b, pos + 1
    return ((b & 0x7F) << 8) | buf[pos + 1], pos + 2


def _write_symbol_table(freqs: np.ndarray, emit_freq=True) -> bytes:
    """Symbols present, ascending, with the spec's RLE: after two consecutive
    present symbols, a run byte counts how many MORE consecutive follow."""
    out = bytearray()
    syms = [j for j in range(256) if freqs[j] > 0]
    rle = 0
    for idx, j in enumerate(syms):
        if rle > 0:
            rle -= 1
        else:
            out.append(j)
            if j > 0 and freqs[j - 1] > 0:
                # count consecutive symbols after j
                rle = 0
                k = j + 1
                while k < 256 and freqs[k] > 0:
                    rle += 1
                    k += 1
                out.append(rle)
        if emit_freq:
            out += _write_freq(int(freqs[j]))
    out.append(0)
    return bytes(out)


def _read_symbol_table(buf: bytes, pos: int, read_value) -> Tuple[dict, int]:
    """Inverse of _write_symbol_table; ``read_value(sym, pos) -> pos`` consumes
    the per-symbol payload and records it."""
    values = {}
    rle = 0
    j = buf[pos]
    pos += 1
    while True:
        pos = read_value(j, pos)
        values[j] = True
        if rle > 0:
            rle -= 1
            j += 1
        else:
            nxt = buf[pos]
            pos += 1
            if nxt == j + 1:
                rle = buf[pos]
                pos += 1
                j = nxt
            elif nxt == 0:
                break
            else:
                j = nxt
    return values, pos


# ---------------------------------------------------------------------------
# Order-0
# ---------------------------------------------------------------------------

def _enc_put(x: int, freq: int, cum: int, out: bytearray) -> int:
    x_max = ((RANS_LOW >> TF_SHIFT) << 8) * freq
    while x >= x_max:
        out.append(x & 0xFF)
        x >>= 8
    return ((x // freq) << TF_SHIFT) + (x % freq) + cum


def _encode_order0(data: bytes) -> bytes:
    counts = np.bincount(np.frombuffer(data, dtype=np.uint8), minlength=256)
    freqs = _normalize_freqs(counts)
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(freqs, out=cum[1:])
    table = _write_symbol_table(freqs)

    n = len(data)
    states = [RANS_LOW] * 4
    rev = bytearray()
    # encode in reverse; state j%4 owns position j
    for i in range(n - 1, -1, -1):
        s = data[i]
        states[i & 3] = _enc_put(states[i & 3], int(freqs[s]), int(cum[s]), rev)
    body = b"".join(struct.pack("<I", st) for st in states) + bytes(rev[::-1])
    return bytes([RANS_ORDER_0]) + struct.pack(
        "<II", len(table) + len(body), n) + table + body


def _read_freq_table_order0(buf: bytes, pos: int
                            ) -> Tuple[np.ndarray, int]:
    freqs = np.zeros(256, dtype=np.int64)

    def read_value(sym, p):
        f, p = _read_freq(buf, p)
        freqs[sym] = f
        return p

    _, pos = _read_symbol_table(buf, pos, read_value)
    return freqs, pos


def _check_final_states(states, low: int = RANS_LOW,
                        label: str = "rANS") -> None:
    """A well-formed stream decodes every state back to ``low`` (the
    encoder's initial value); anything else is corruption or a lying
    out_size.  Shared by the 4x8 NumPy decoders here, the Nx16 decoders
    (low=RANS_LOW_16), and mirrored by the native (-2) and device
    (ops/rans._check_final) decoders."""
    if any(int(x) != low for x in states):
        raise RansError(
            f"corrupt {label} stream (final-state integrity check "
            f"failed): {[int(x) for x in states]}")


@contextlib.contextmanager
def normalize_truncation(label: str):
    """Corrupt/truncated streams surface as RansError, never a bare
    IndexError (byte reads), struct.error (state words), or ValueError
    (frombuffer) — one normalization shared by every decoder path."""
    try:
        yield
    except RansError:
        raise
    except (IndexError, ValueError, struct.error) as e:
        raise RansError(f"truncated {label} stream: {e}") from e


def _decode_order0(buf: bytes, pos: int, out_size: int) -> bytes:
    freqs, cum, slot2sym, pos = read_order0_tables(buf, pos)

    from hadoop_bam_tpu.utils import native
    if native.available():
        return native.rans_decode(
            0, np.frombuffer(buf, dtype=np.uint8), pos,
            freqs.astype(np.uint32), cum[:256].astype(np.uint32),
            slot2sym, out_size).tobytes()

    data = np.frombuffer(buf, dtype=np.uint8)
    states = np.frombuffer(buf[pos:pos + 16], dtype="<u4").astype(np.int64)
    ptr = pos + 16
    out = np.zeros(out_size, dtype=np.uint8)
    freqs_i = freqs
    cum_i = cum[:256]

    # vectorized over the 4 interleaved states; serial over positions/4
    i = 0
    while i + 4 <= out_size:
        m = states & (TOTFREQ - 1)
        syms = slot2sym[m]
        out[i:i + 4] = syms
        states = freqs_i[syms] * (states >> TF_SHIFT) + m - cum_i[syms]
        # renormalize: each state consumes bytes until >= RANS_LOW
        for j in range(4):
            x = states[j]
            while x < RANS_LOW:
                x = (x << 8) | data[ptr]
                ptr += 1
            states[j] = x
        i += 4
    for j in range(out_size - i):
        x = states[j]
        m = x & (TOTFREQ - 1)
        s = slot2sym[m]
        out[i + j] = s
        x = freqs_i[s] * (x >> TF_SHIFT) + m - cum_i[s]
        while x < RANS_LOW:
            x = (x << 8) | data[ptr]
            ptr += 1
        states[j] = x
    _check_final_states(states)
    return out.tobytes()


# ---------------------------------------------------------------------------
# Order-1
# ---------------------------------------------------------------------------

def _encode_order1(data: bytes) -> bytes:
    n = len(data)
    arr = np.frombuffer(data, dtype=np.uint8)
    q = n >> 2
    # quarter starts; context of each quarter's first byte is 0
    starts = [0, q, 2 * q, 3 * q]
    counts = np.zeros((256, 256), dtype=np.int64)
    prev = np.concatenate([[0], arr[:-1]])
    for st in starts:
        prev[st] = 0
    np.add.at(counts, (prev, arr), 1)

    freqs = np.zeros((256, 256), dtype=np.int64)
    cums = np.zeros((256, 257), dtype=np.int64)
    for c in range(256):
        if counts[c].sum():
            freqs[c] = _normalize_freqs(counts[c])
            np.cumsum(freqs[c], out=cums[c][1:])

    # serialize: outer RLE over contexts, inner order-0-style table per ctx
    out = bytearray()
    ctx_present = counts.sum(axis=1) > 0

    def write_ctx_tables() -> bytes:
        buf = bytearray()
        rle = 0
        ctxs = [c for c in range(256) if ctx_present[c]]
        for c in ctxs:
            if rle > 0:
                rle -= 1
            else:
                buf.append(c)
                if c > 0 and ctx_present[c - 1]:
                    rle = 0
                    k = c + 1
                    while k < 256 and ctx_present[k]:
                        rle += 1
                        k += 1
                    buf.append(rle)
            buf += _write_symbol_table(freqs[c])
        buf.append(0)
        return bytes(buf)

    table = write_ctx_tables()

    # encode the 4 quarters in reverse, one state per quarter; the last
    # quarter (state 3) also covers the tail remainder
    ends = [q, 2 * q, 3 * q, n]
    states = [RANS_LOW] * 4
    rev = bytearray()
    # interleaved emission in reverse over the longest quarter
    lens = [ends[j] - starts[j] for j in range(4)]
    maxlen = max(lens) if n else 0
    for step in range(maxlen - 1, -1, -1):
        for j in (3, 2, 1, 0):
            if step < lens[j]:
                i = starts[j] + step
                ctx = int(prev[i])
                s = int(arr[i])
                states[j] = _enc_put(states[j], int(freqs[ctx][s]),
                                     int(cums[ctx][s]), rev)
    body = b"".join(struct.pack("<I", st) for st in states) + bytes(rev[::-1])
    return bytes([RANS_ORDER_1]) + struct.pack(
        "<II", len(table) + len(body), n) + table + body


def read_order0_tables(buf: bytes, pos: int
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Parse an order-0 frequency table: (freqs [256], cum [257],
    slot2sym [4096], next pos) — the host half shared by the NumPy,
    native, and device (ops/rans.py) decoders."""
    freqs, pos = _read_freq_table_order0(buf, pos)
    cum = np.zeros(257, dtype=np.int64)
    np.cumsum(freqs, out=cum[1:])
    slot2sym = np.zeros(TOTFREQ, dtype=np.uint8)
    for s in range(256):
        if freqs[s]:
            slot2sym[cum[s]:cum[s + 1]] = s
    return freqs, cum, slot2sym, pos


def read_order1_tables(buf: bytes, pos: int
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Parse the order-1 context tables: (freqs [256, 256],
    cums [256, 257], slot2sym [256, 4096], next pos)."""
    freqs = np.zeros((256, 256), dtype=np.int64)
    cums = np.zeros((256, 257), dtype=np.int64)
    slot2sym = np.zeros((256, TOTFREQ), dtype=np.uint8)
    # outer context table with the same RLE grammar
    rle = 0
    c = buf[pos]
    pos += 1
    while True:
        f, pos2 = _read_freq_table_order0(buf, pos)
        freqs[c] = f
        np.cumsum(f, out=cums[c][1:])
        for s in range(256):
            if f[s]:
                slot2sym[c, cums[c][s]:cums[c][s + 1]] = s
        pos = pos2
        if rle > 0:
            rle -= 1
            c += 1
        else:
            nxt = buf[pos]
            pos += 1
            if nxt == c + 1:
                rle = buf[pos]
                pos += 1
                c = nxt
            elif nxt == 0:
                break
            else:
                c = nxt
    return freqs, cums, slot2sym, pos


def _decode_order1(buf: bytes, pos: int, out_size: int) -> bytes:
    freqs, cums, slot2sym, pos = read_order1_tables(buf, pos)
    from hadoop_bam_tpu.utils import native
    if native.available():
        return native.rans_decode(
            1, np.frombuffer(buf, dtype=np.uint8), pos,
            np.ascontiguousarray(freqs.astype(np.uint32)),
            np.ascontiguousarray(cums[:, :256].astype(np.uint32)),
            np.ascontiguousarray(slot2sym), out_size).tobytes()

    data = np.frombuffer(buf, dtype=np.uint8)
    states = np.frombuffer(buf[pos:pos + 16], dtype="<u4").astype(np.int64)
    ptr = pos + 16

    q = out_size >> 2
    starts = [0, q, 2 * q, 3 * q]
    ends = [q, 2 * q, 3 * q, out_size]
    out = np.zeros(out_size, dtype=np.uint8)
    ctxs = [0, 0, 0, 0]
    idx = list(starts)
    # serial over the longest quarter; 4 states stepped together
    done = [idx[j] >= ends[j] for j in range(4)]
    while not all(done):
        for j in range(4):
            if done[j]:
                continue
            x = int(states[j])
            m = x & (TOTFREQ - 1)
            ctx = ctxs[j]
            s = int(slot2sym[ctx, m])
            out[idx[j]] = s
            x = int(freqs[ctx][s]) * (x >> TF_SHIFT) + m - int(cums[ctx][s])
            while x < RANS_LOW:
                x = (x << 8) | int(data[ptr])
                ptr += 1
            states[j] = x
            ctxs[j] = s
            idx[j] += 1
            if idx[j] >= ends[j]:
                done[j] = True
    _check_final_states(states)
    return out.tobytes()


# ---------------------------------------------------------------------------
# Public API
# ---------------------------------------------------------------------------

def rans4x8_encode(data: bytes, order: int = 0) -> bytes:
    if len(data) == 0:
        return bytes([order]) + struct.pack("<II", 0, 0)
    if order == RANS_ORDER_1 and len(data) >= 4:
        return _encode_order1(data)
    return _encode_order0(data)


def rans4x8_decode(payload: bytes) -> bytes:
    if len(payload) < 9:
        raise RansError("rANS stream shorter than its 9-byte prefix")
    order = payload[0]
    comp_size, out_size = struct.unpack_from("<II", payload, 1)
    if out_size == 0:
        return b""
    if len(payload) < 9 + comp_size:
        raise RansError("truncated rANS stream")
    with normalize_truncation("rANS"):
        if order == RANS_ORDER_0:
            return _decode_order0(payload, 9, out_size)
        if order == RANS_ORDER_1:
            return _decode_order1(payload, 9, out_size)
    raise RansError(f"unknown rANS order {order}")
