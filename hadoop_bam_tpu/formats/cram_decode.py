"""CRAM 3.0 record semantics: encodings, compression header, slice decode.

[SPEC] CRAM 3.0 spec sections 8.4 (compression header), 8.5 (slice header),
10 (record structure), 13 (encodings).  The compression header declares, per
data series (two-letter keys: BF bam flags, CF cram flags, RI ref id, RL read
length, AP alignment position, RG read group, RN read name, MF mate flags,
NS/NP/TS mate ref/pos/template size, NF next-fragment distance, TL tag-line,
FN/FC/FP feature count/code/position, DL/BB/QQ/BS/IN/RS/PD/HC/SC/MQ/BA/QS
feature payloads), which *encoding* produces its values, drawing bits from the
CORE block or bytes from EXTERNAL blocks.

Reference-side equivalent: htsjdk's cram.structure/cram.encoding packages,
reached from Hadoop-BAM via hb/CRAMInputFormat.java → htsjdk CRAM iterator
(SURVEY.md section 2.3).  This module is a fresh implementation from the
public spec — decode here, encode in cram_encode.py.
"""
from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.formats.cram import (
    CRAMError, read_itf8, read_itf8_array, write_itf8, write_itf8_array,
    read_ltf8, write_ltf8,
)

# Encoding codec ids [SPEC section 13]
E_NULL, E_EXTERNAL, E_GOLOMB, E_HUFFMAN = 0, 1, 2, 3
E_BYTE_ARRAY_LEN, E_BYTE_ARRAY_STOP, E_BETA = 4, 5, 6
E_SUBEXP, E_GOLOMB_RICE, E_GAMMA = 7, 8, 9

# SAM flag bits carried by the MF (mate flags) series instead of BF
MATE_REVERSE = 0x20
MATE_UNMAPPED = 0x08

# CF (CRAM bit flags) [SPEC section 10.2]
CF_QUAL_STORED = 0x1
CF_DETACHED = 0x2
CF_HAS_MATE_DOWNSTREAM = 0x4
CF_UNKNOWN_BASES = 0x8

DEFAULT_SUBS_MATRIX = bytes([0x1B] * 5)  # identity-ish ordering per ref base


# ---------------------------------------------------------------------------
# Bit/byte cursors
# ---------------------------------------------------------------------------

class BitReader:
    """MSB-first bit reader over the CORE block."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0          # byte position
        self.bit = 0          # bits consumed of data[pos]

    def read(self, nbits: int) -> int:
        v = 0
        for _ in range(nbits):
            byte = self.data[self.pos]
            v = (v << 1) | ((byte >> (7 - self.bit)) & 1)
            self.bit += 1
            if self.bit == 8:
                self.bit = 0
                self.pos += 1
        return v

    def read_unary(self, stop_bit: int = 0) -> int:
        n = 0
        while self.read(1) != stop_bit:
            n += 1
        return n


class ByteCursor:
    """Sequential reader over one EXTERNAL block."""

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def read_byte(self) -> int:
        b = self.data[self.pos]
        self.pos += 1
        return b

    def read_bytes(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) != n:
            raise CRAMError("external block exhausted")
        self.pos += n
        return b

    def read_itf8(self) -> int:
        v, self.pos = read_itf8(self.data, self.pos)
        return v

    def read_until(self, stop: int) -> bytes:
        end = self.data.find(bytes([stop]), self.pos)
        if end < 0:
            raise CRAMError("BYTE_ARRAY_STOP: stop byte not found")
        b = self.data[self.pos:end]
        self.pos = end + 1
        return b


@dataclass
class DecodeState:
    core: BitReader
    ext: Dict[int, ByteCursor]
    qs_feat_bytes: int = 0     # QS bytes consumed by B/Q features (the
                               # fqzcomp tripwire skips when nonzero)

    def cursor(self, cid: int) -> ByteCursor:
        try:
            return self.ext[cid]
        except KeyError:
            raise CRAMError(f"record references missing external block {cid}")


# ---------------------------------------------------------------------------
# Encodings
# ---------------------------------------------------------------------------

class Encoding:
    codec_id: int = E_NULL

    def decode_int(self, st: DecodeState) -> int:
        raise CRAMError(f"{type(self).__name__} cannot decode ints")

    def decode_byte(self, st: DecodeState) -> int:
        raise CRAMError(f"{type(self).__name__} cannot decode bytes")

    def decode_array(self, st: DecodeState) -> bytes:
        raise CRAMError(f"{type(self).__name__} cannot decode byte arrays")

    def decode_bytes(self, st: DecodeState, n: int) -> bytes:
        """n bytes of this series — the bulk fast path (EXTERNAL series
        read a slice in one call; others fall back to per-byte decode)."""
        return bytes(self.decode_byte(st) for _ in range(n))

    def params(self) -> bytes:
        raise NotImplementedError

    def serialize(self) -> bytes:
        p = self.params()
        return write_itf8(self.codec_id) + write_itf8(len(p)) + p


@dataclass
class NullEncoding(Encoding):
    codec_id = E_NULL

    def params(self) -> bytes:
        return b""


@dataclass
class ExternalEncoding(Encoding):
    """ints as ITF8 / bytes raw, from external block ``content_id``."""
    content_id: int
    codec_id = E_EXTERNAL

    def decode_int(self, st: DecodeState) -> int:
        return st.cursor(self.content_id).read_itf8()

    def decode_byte(self, st: DecodeState) -> int:
        return st.cursor(self.content_id).read_byte()

    def decode_bytes(self, st: DecodeState, n: int) -> bytes:
        return st.cursor(self.content_id).read_bytes(n)

    def params(self) -> bytes:
        return write_itf8(self.content_id)


@dataclass
class HuffmanEncoding(Encoding):
    """Canonical Huffman over the CORE block; the 0-bit single-symbol case is
    the spec's idiom for constant series."""
    symbols: List[int]
    lengths: List[int]
    codec_id = E_HUFFMAN

    def __post_init__(self):
        order = sorted(range(len(self.symbols)),
                       key=lambda i: (self.lengths[i], self.symbols[i]))
        self._table: Dict[Tuple[int, int], int] = {}
        code, prev_len = 0, 0
        for i in order:
            ln = self.lengths[i]
            if ln == 0:
                continue
            code <<= (ln - prev_len)
            self._table[(ln, code)] = self.symbols[i]
            code += 1
            prev_len = ln
        self._const = self.symbols[0] if (
            len(self.symbols) == 1 and self.lengths[0] == 0) else None

    def decode_int(self, st: DecodeState) -> int:
        if self._const is not None:
            return self._const
        code, ln = 0, 0
        for _ in range(32):
            code = (code << 1) | st.core.read(1)
            ln += 1
            sym = self._table.get((ln, code))
            if sym is not None:
                return sym
        raise CRAMError("bad Huffman code (no symbol within 32 bits)")

    decode_byte = decode_int

    def params(self) -> bytes:
        return write_itf8_array(self.symbols) + write_itf8_array(self.lengths)


@dataclass
class BetaEncoding(Encoding):
    offset: int
    nbits: int
    codec_id = E_BETA

    def decode_int(self, st: DecodeState) -> int:
        return st.core.read(self.nbits) - self.offset

    decode_byte = decode_int

    def params(self) -> bytes:
        return write_itf8(self.offset) + write_itf8(self.nbits)


@dataclass
class GammaEncoding(Encoding):
    offset: int
    codec_id = E_GAMMA

    def decode_int(self, st: DecodeState) -> int:
        n = st.core.read_unary(stop_bit=1)     # count zeros until the 1
        rest = st.core.read(n)
        return ((1 << n) | rest) - self.offset

    def params(self) -> bytes:
        return write_itf8(self.offset)


@dataclass
class SubexpEncoding(Encoding):
    offset: int
    k: int
    codec_id = E_SUBEXP

    def decode_int(self, st: DecodeState) -> int:
        u = st.core.read_unary(stop_bit=0)     # count ones until the 0
        if u == 0:
            v = st.core.read(self.k)
        else:
            n = self.k + u - 1
            v = (1 << n) | st.core.read(n)
        return v - self.offset

    def params(self) -> bytes:
        return write_itf8(self.offset) + write_itf8(self.k)


@dataclass
class ByteArrayLenEncoding(Encoding):
    len_encoding: Encoding
    val_encoding: Encoding
    codec_id = E_BYTE_ARRAY_LEN

    def decode_array(self, st: DecodeState) -> bytes:
        n = self.len_encoding.decode_int(st)
        return self.val_encoding.decode_bytes(st, n)

    def params(self) -> bytes:
        return self.len_encoding.serialize() + self.val_encoding.serialize()


@dataclass
class ByteArrayStopEncoding(Encoding):
    stop: int
    content_id: int
    codec_id = E_BYTE_ARRAY_STOP

    def decode_array(self, st: DecodeState) -> bytes:
        return st.cursor(self.content_id).read_until(self.stop)

    def params(self) -> bytes:
        return bytes([self.stop]) + write_itf8(self.content_id)


def parse_encoding(buf: bytes, pos: int) -> Tuple[Encoding, int]:
    codec, pos = read_itf8(buf, pos)
    plen, pos = read_itf8(buf, pos)
    p, end = pos, pos + plen
    if codec == E_NULL:
        enc = NullEncoding()
    elif codec == E_EXTERNAL:
        cid, p = read_itf8(buf, p)
        enc = ExternalEncoding(cid)
    elif codec == E_HUFFMAN:
        syms, p = read_itf8_array(buf, p)
        lens, p = read_itf8_array(buf, p)
        enc = HuffmanEncoding(syms, lens)
    elif codec == E_BYTE_ARRAY_LEN:
        len_enc, p = parse_encoding(buf, p)
        val_enc, p = parse_encoding(buf, p)
        enc = ByteArrayLenEncoding(len_enc, val_enc)
    elif codec == E_BYTE_ARRAY_STOP:
        stop = buf[p]
        cid, p = read_itf8(buf, p + 1)
        enc = ByteArrayStopEncoding(stop, cid)
    elif codec == E_BETA:
        off, p = read_itf8(buf, p)
        nbits, p = read_itf8(buf, p)
        enc = BetaEncoding(off, nbits)
    elif codec == E_GAMMA:
        off, p = read_itf8(buf, p)
        enc = GammaEncoding(off)
    elif codec == E_SUBEXP:
        off, p = read_itf8(buf, p)
        k, p = read_itf8(buf, p)
        enc = SubexpEncoding(off, k)
    else:
        raise CRAMError(f"unsupported encoding codec id {codec} "
                        "(GOLOMB/GOLOMB_RICE are not implemented)")
    return enc, end


# ---------------------------------------------------------------------------
# Compression header [SPEC section 8.4]
# ---------------------------------------------------------------------------

@dataclass
class CompressionHeader:
    read_names_included: bool = True
    ap_delta: bool = False
    reference_required: bool = True
    substitution_matrix: bytes = DEFAULT_SUBS_MATRIX
    tag_dict: List[List[Tuple[str, str]]] = field(default_factory=lambda: [[]])
    data_series: Dict[str, Encoding] = field(default_factory=dict)
    tag_encodings: Dict[int, Encoding] = field(default_factory=dict)

    @classmethod
    def from_bytes(cls, buf: bytes) -> "CompressionHeader":
        pos = 0
        hdr = cls()
        # preservation map
        _size, pos = read_itf8(buf, pos)
        n, pos = read_itf8(buf, pos)
        for _ in range(n):
            key = buf[pos:pos + 2].decode("ascii")
            pos += 2
            if key in ("RN", "AP", "RR"):
                val = bool(buf[pos])
                pos += 1
                if key == "RN":
                    hdr.read_names_included = val
                elif key == "AP":
                    hdr.ap_delta = val
                else:
                    hdr.reference_required = val
            elif key == "SM":
                hdr.substitution_matrix = bytes(buf[pos:pos + 5])
                pos += 5
            elif key == "TD":
                tdlen, pos = read_itf8(buf, pos)
                hdr.tag_dict = _parse_tag_dict(buf[pos:pos + tdlen])
                pos += tdlen
            else:
                raise CRAMError(f"unknown preservation map key {key!r}")
        # data series encodings
        _size, pos = read_itf8(buf, pos)
        n, pos = read_itf8(buf, pos)
        for _ in range(n):
            key = buf[pos:pos + 2].decode("ascii")
            pos += 2
            enc, pos = parse_encoding(buf, pos)
            hdr.data_series[key] = enc
        # tag encodings
        _size, pos = read_itf8(buf, pos)
        n, pos = read_itf8(buf, pos)
        for _ in range(n):
            key, pos = read_itf8(buf, pos)
            enc, pos = parse_encoding(buf, pos)
            hdr.tag_encodings[key] = enc
        return hdr

    def to_bytes(self) -> bytes:
        pres = bytearray()
        entries = [(b"RN", bytes([self.read_names_included])),
                   (b"AP", bytes([self.ap_delta])),
                   (b"RR", bytes([self.reference_required])),
                   (b"SM", self.substitution_matrix),
                   (b"TD", write_itf8(len(self._td_bytes())) +
                    self._td_bytes())]
        pres += write_itf8(len(entries))
        for k, v in entries:
            pres += k + v
        out = write_itf8(len(pres)) + bytes(pres)

        ds = bytearray()
        ds += write_itf8(len(self.data_series))
        for k, enc in self.data_series.items():
            ds += k.encode("ascii") + enc.serialize()
        out += write_itf8(len(ds)) + bytes(ds)

        te = bytearray()
        te += write_itf8(len(self.tag_encodings))
        for key, enc in self.tag_encodings.items():
            te += write_itf8(key) + enc.serialize()
        out += write_itf8(len(te)) + bytes(te)
        return out

    def _td_bytes(self) -> bytes:
        out = bytearray()
        for line in self.tag_dict:
            for tag, typ in line:
                out += tag.encode("ascii") + typ.encode("ascii")
            out.append(0)
        return bytes(out)

    def series(self, key: str) -> Encoding:
        enc = self.data_series.get(key)
        if enc is None:
            raise CRAMError(f"compression header lacks data series {key}")
        return enc


def _parse_tag_dict(buf: bytes) -> List[List[Tuple[str, str]]]:
    lines: List[List[Tuple[str, str]]] = []
    for raw in buf.split(b"\x00")[:-1]:
        line = []
        if len(raw) % 3:
            raise CRAMError("tag dictionary line not a multiple of 3 bytes")
        for i in range(0, len(raw), 3):
            line.append((raw[i:i + 2].decode("ascii"), chr(raw[i + 2])))
        lines.append(line)
    return lines or [[]]


def tag_key(tag: str, typ: str) -> int:
    return (ord(tag[0]) << 16) | (ord(tag[1]) << 8) | ord(typ)


# ---------------------------------------------------------------------------
# Slice header [SPEC section 8.5]
# ---------------------------------------------------------------------------

@dataclass
class SliceHeader:
    ref_seq_id: int = -1
    start: int = 0
    span: int = 0
    n_records: int = 0
    record_counter: int = 0
    n_blocks: int = 0
    content_ids: List[int] = field(default_factory=list)
    embedded_ref_id: int = -1
    ref_md5: bytes = b"\x00" * 16
    tags: bytes = b""

    @classmethod
    def from_bytes(cls, buf: bytes) -> "SliceHeader":
        pos = 0
        ref_seq_id, pos = read_itf8(buf, pos)
        start, pos = read_itf8(buf, pos)
        span, pos = read_itf8(buf, pos)
        n_records, pos = read_itf8(buf, pos)
        record_counter, pos = read_ltf8(buf, pos)
        n_blocks, pos = read_itf8(buf, pos)
        content_ids, pos = read_itf8_array(buf, pos)
        embedded_ref_id, pos = read_itf8(buf, pos)
        ref_md5 = bytes(buf[pos:pos + 16])
        pos += 16
        return cls(ref_seq_id, start, span, n_records, record_counter,
                   n_blocks, content_ids, embedded_ref_id, ref_md5,
                   bytes(buf[pos:]))

    def to_bytes(self) -> bytes:
        return (write_itf8(self.ref_seq_id) + write_itf8(self.start)
                + write_itf8(self.span) + write_itf8(self.n_records)
                + write_ltf8(self.record_counter) + write_itf8(self.n_blocks)
                + write_itf8_array(self.content_ids)
                + write_itf8(self.embedded_ref_id) + self.ref_md5 + self.tags)


# ---------------------------------------------------------------------------
# Substitution matrix [SPEC section 10.6]
# ---------------------------------------------------------------------------

_BASES = "ACGTN"


def substitute_base(matrix: bytes, ref_base: str, code: int) -> str:
    ri = _BASES.find(ref_base.upper())
    if ri < 0:
        ri = 4
    byte = matrix[ri]
    candidates = [b for b in _BASES if b != _BASES[ri]]
    for j in range(4):
        if (byte >> (6 - 2 * j)) & 3 == code:
            return candidates[j]
    raise CRAMError("invalid substitution code")


def substitution_code(matrix: bytes, ref_base: str, read_base: str) -> int:
    ri = _BASES.find(ref_base.upper())
    if ri < 0:
        ri = 4
    byte = matrix[ri]
    candidates = [b for b in _BASES if b != _BASES[ri]]
    j = candidates.index(read_base.upper())
    return (byte >> (6 - 2 * j)) & 3


# ---------------------------------------------------------------------------
# Record decode [SPEC section 10]
# ---------------------------------------------------------------------------

@dataclass
class CramRecord:
    """Decoded CRAM record, pre-SAM: feature-resolved but mate links raw."""
    bf: int = 0
    cf: int = 0
    ref_id: int = -1
    read_length: int = 0
    pos: int = 0
    read_group: int = -1
    name: bytes = b""
    mate_flags: int = 0
    mate_ref_id: int = -1
    mate_pos: int = 0
    template_size: int = 0
    next_fragment: int = -1
    tags: List[Tuple[str, str, object]] = field(default_factory=list)
    mapq: int = 0
    seq: str = "*"
    qual: bytes = b""
    cigar: str = "*"


class ReferenceSource:
    """Resolves reference bases for slices — the analog of the reference's
    ``hadoopbam.cram.reference-source-path`` config (hb/CRAMInputFormat.java)."""

    def get(self, ref_name: str, start: int, length: int) -> str:
        raise NotImplementedError


class FastaReferenceSource(ReferenceSource):
    def __init__(self, path_or_text):
        from hadoop_bam_tpu.formats.fasta import parse_fasta
        if isinstance(path_or_text, (bytes, bytearray)):
            data = bytes(path_or_text)
        else:
            with open(path_or_text, "rb") as f:
                data = f.read()
        self.seqs: Dict[str, str] = {}
        for frag in parse_fasta(data, line_fragments=False):
            self.seqs[frag.contig] = frag.sequence

    def get(self, ref_name: str, start: int, length: int) -> str:
        seq = self.seqs.get(ref_name)
        if seq is None:
            raise CRAMError(f"reference contig {ref_name!r} not in source")
        return seq[start - 1:start - 1 + length]


class _EmbeddedReference(ReferenceSource):
    def __init__(self, bases: bytes, offset: int):
        self.bases = bases.decode("ascii")
        self.offset = offset   # 1-based position of bases[0]

    def get(self, ref_name: str, start: int, length: int) -> str:
        i = start - self.offset
        return self.bases[i:i + length]


def _encoding_cids(enc: Encoding) -> List[int]:
    if isinstance(enc, ExternalEncoding):
        return [enc.content_id]
    if isinstance(enc, ByteArrayStopEncoding):
        return [enc.content_id]
    if isinstance(enc, ByteArrayLenEncoding):
        return _encoding_cids(enc.len_encoding) + _encoding_cids(
            enc.val_encoding)
    return []


def _predecode_fixed(comp: CompressionHeader, slice_hdr: SliceHeader,
                     external: Dict[int, bytes]) -> Optional[Dict]:
    """Batch-decode the fixed int series of one slice, or None.

    Eligible when the native ITF8 batch decoder is loadable and every
    fixed series is either a constant (0-bit Huffman, the spec idiom) or
    an EXTERNAL ITF8 stream whose content id no other series shares —
    the common htslib layout.  Ineligible slices fall back to the
    per-record path; output is identical either way (parity tests pin
    this)."""
    from hadoop_bam_tpu.utils import native

    if not native.available():
        return None
    n = slice_hdr.n_records
    if n == 0:
        return None
    multiref = slice_hdr.ref_seq_id == -2

    # content-id exclusivity across EVERY encoding in the header
    cid_users: Dict[int, int] = {}
    for enc in list(comp.data_series.values()) \
            + list(comp.tag_encodings.values()):
        for cid in _encoding_cids(enc):
            cid_users[cid] = cid_users.get(cid, 0) + 1

    def batch(name: str, count: int,
              raw_bytes: bool = False) -> Optional[np.ndarray]:
        """count values of one fixed series; None = not eligible.
        ``raw_bytes`` reads one raw byte per value (the decode_byte
        contract, e.g. FC) instead of one ITF8 varint."""
        if count == 0:
            return np.zeros(0, np.int32)
        enc = comp.data_series.get(name)
        if enc is None:
            return None
        if isinstance(enc, HuffmanEncoding) and enc._const is not None:
            return np.full(count, enc._const, np.int32)
        if isinstance(enc, ExternalEncoding):
            cid = enc.content_id
            if cid_users.get(cid, 0) != 1 or cid not in external:
                return None
            if raw_bytes:
                raw = external[cid]
                if len(raw) < count:
                    return None        # truncated: per-record path raises
                return np.frombuffer(raw[:count], np.uint8
                                     ).astype(np.int32)
            try:
                vals, _used = native.itf8_decode_batch(
                    np.frombuffer(external[cid], np.uint8), count)
            except ValueError:
                return None            # truncated: per-record path raises
            return vals
        return None                    # core-bit codec: record-serial

    out: Dict[str, np.ndarray] = {}
    for name in ("BF", "CF"):
        v = batch(name, n)
        if v is None:
            return None
        out[name] = v
    detached = (out["CF"] & CF_DETACHED) != 0
    downstream = ~detached & ((out["CF"] & CF_HAS_MATE_DOWNSTREAM) != 0)
    mapped = (out["BF"] & 0x4) == 0
    counts = {"RL": n, "AP": n, "RG": n, "TL": n,
              "MF": int(detached.sum()), "NS": int(detached.sum()),
              "NP": int(detached.sum()), "TS": int(detached.sum()),
              "NF": int(downstream.sum()),
              "MQ": int(mapped.sum()), "FN": int(mapped.sum())}
    if multiref:
        counts["RI"] = n
    for name, k in counts.items():
        v = batch(name, k)
        if v is None:
            return None
        out[name] = v
    tl = out["TL"]
    if tl.size and (int(tl.min()) < 0
                    or int(tl.max()) >= len(comp.tag_dict)):
        raise CRAMError(f"TL index {int(tl.max())} outside tag dictionary")
    if comp.ap_delta:
        out["POS"] = slice_hdr.start + np.cumsum(
            out["AP"], dtype=np.int64)
    else:
        out["POS"] = out["AP"].astype(np.int64)

    # feature streams: FC is one byte per feature and FP one ITF8 per
    # feature, totalling sum(FN) values each — batchable exactly like
    # the fixed series.  Optional: absence just keeps features on the
    # record-serial path.
    total_fn = int(out["FN"].sum())
    if total_fn:
        fc = batch("FC", total_fn, raw_bytes=True)
        fp = batch("FP", total_fn) if fc is not None else None
        if fc is not None and fp is not None:
            out["FC"] = fc
            out["FP"] = fp
    return out


def check_fqz_rec_lens(comp: CompressionHeader, codec_rec_lens,
                       expected: List[int],
                       qs_feat_bytes: int = 0) -> None:
    """fqzcomp desync tripwire, shared by both decode paths: the codec's
    own per-record lengths must match ``expected`` (each record's QS
    consumption per the RL series, stored-qual records only, >0).  A
    [SPEC-recalled] model constant mismatch desyncs the range coder into
    silently wrong values — this cheap invariant catches most desyncs
    loudly (ADVICE r4).  Skipped when B/Q feature bytes interleave into
    QS, or when QS shares its external block with another series (both
    make the per-record mapping ambiguous on a spec-valid file)."""
    if not codec_rec_lens or qs_feat_bytes:
        return
    enc = comp.data_series.get("QS")
    if not isinstance(enc, ExternalEncoding):
        return
    lens = codec_rec_lens.get(enc.content_id)
    if lens is None:
        return
    users = 0
    for e in list(comp.data_series.values()) \
            + list(comp.tag_encodings.values()):
        users += _encoding_cids(e).count(enc.content_id)
    if users != 1:
        return
    codec = [l for l in lens if l > 0]
    if codec != expected:
        raise CRAMError(
            "fqzcomp per-record quality lengths disagree with the "
            f"slice's RL series ({len(codec)} codec records vs "
            f"{len(expected)} stored-qual records) — desynced or "
            "miscalibrated quality stream")


def _check_codec_rec_lens(comp: CompressionHeader, codec_rec_lens,
                          records: List["CramRecord"],
                          st: DecodeState) -> None:
    if not codec_rec_lens:
        return
    expected = [r.read_length for r in records
                if r.cf & CF_QUAL_STORED and r.read_length > 0]
    check_fqz_rec_lens(comp, codec_rec_lens, expected, st.qs_feat_bytes)


def decode_slice_records(comp: CompressionHeader, slice_hdr: SliceHeader,
                         core: bytes, external: Dict[int, bytes],
                         ref_names: List[str],
                         ref_source: Optional[ReferenceSource] = None,
                         codec_rec_lens=None) -> List[CramRecord]:
    st = DecodeState(BitReader(core),
                     {cid: ByteCursor(d) for cid, d in external.items()})
    if slice_hdr.embedded_ref_id >= 0 and ref_source is None:
        ref_source = _EmbeddedReference(external[slice_hdr.embedded_ref_id],
                                        slice_hdr.start)

    pre = _predecode_fixed(comp, slice_hdr, external)
    if pre is not None:
        records = _decode_slice_records_fast(comp, slice_hdr, st, pre,
                                             ref_names, ref_source)
        _check_codec_rec_lens(comp, codec_rec_lens, records, st)
        return records

    records: List[CramRecord] = []
    prev_pos = slice_hdr.start
    for _ in range(slice_hdr.n_records):
        r = CramRecord()
        r.bf = comp.series("BF").decode_int(st)
        r.cf = comp.series("CF").decode_int(st)
        if slice_hdr.ref_seq_id == -2:
            r.ref_id = comp.series("RI").decode_int(st)
        else:
            r.ref_id = slice_hdr.ref_seq_id
        r.read_length = comp.series("RL").decode_int(st)
        ap = comp.series("AP").decode_int(st)
        if comp.ap_delta:
            r.pos = prev_pos + ap
            prev_pos = r.pos
        else:
            r.pos = ap
        r.read_group = comp.series("RG").decode_int(st)
        if comp.read_names_included:
            r.name = comp.series("RN").decode_array(st)
        if r.cf & CF_DETACHED:
            r.mate_flags = comp.series("MF").decode_int(st)
            if not comp.read_names_included:
                r.name = comp.series("RN").decode_array(st)
            r.mate_ref_id = comp.series("NS").decode_int(st)
            r.mate_pos = comp.series("NP").decode_int(st)
            r.template_size = comp.series("TS").decode_int(st)
        elif r.cf & CF_HAS_MATE_DOWNSTREAM:
            r.next_fragment = comp.series("NF").decode_int(st)
        tl = comp.series("TL").decode_int(st)
        if not 0 <= tl < len(comp.tag_dict):
            raise CRAMError(f"TL index {tl} outside tag dictionary")
        for tag, typ in comp.tag_dict[tl]:
            enc = comp.tag_encodings[tag_key(tag, typ)]
            raw = enc.decode_array(st)
            r.tags.append(_tag_from_raw(tag, typ, raw))
        if not r.bf & 0x4:
            _decode_mapped(comp, st, r, ref_names, ref_source)
        else:
            ba = comp.series("BA")
            r.seq = ba.decode_bytes(st, r.read_length).decode("latin-1")
            r.cigar = "*"
            if r.cf & CF_QUAL_STORED:
                qs = comp.series("QS")
                r.qual = qs.decode_bytes(st, r.read_length)
        records.append(r)
    _check_codec_rec_lens(comp, codec_rec_lens, records, st)
    return records


def _decode_slice_records_fast(comp: CompressionHeader,
                               slice_hdr: SliceHeader, st: "DecodeState",
                               pre: Dict, ref_names: List[str],
                               ref_source: Optional[ReferenceSource]
                               ) -> List[CramRecord]:
    """Record assembly over predecoded fixed arrays: the loop still walks
    names/tags/features through the cursors (their streams interleave
    record-serially), but every fixed int is an array index — the per
    record codec dispatch that dominated the profile is gone."""
    bf, cf = pre["BF"], pre["CF"]
    rl, pos, rg, tl = pre["RL"], pre["POS"], pre["RG"], pre["TL"]
    ri = pre.get("RI")
    mf, ns, np_, ts = (pre["MF"], pre["NS"], pre["NP"], pre["TS"])
    nf, mq, fn = pre["NF"], pre["MQ"], pre["FN"]
    names_inc = comp.read_names_included
    # series("RN") (not .get) so a header lacking RN fails with the same
    # CRAMError as the record-serial path, not an AttributeError on None
    # (ADVICE r4); resolved lazily — a slice may legitimately never need
    # names (names excluded, no detached records)
    rn = comp.data_series.get("RN")

    def read_name() -> bytes:
        return (rn if rn is not None else comp.series("RN")
                ).decode_array(st)
    tag_dict, tag_encodings = comp.tag_dict, comp.tag_encodings
    fc_all, fp_all = pre.get("FC"), pre.get("FP")
    records: List[CramRecord] = []
    di = wi = mi = fi = 0
    for i in range(slice_hdr.n_records):
        r = CramRecord()
        r.bf = int(bf[i])
        r.cf = int(cf[i])
        r.ref_id = int(ri[i]) if ri is not None else slice_hdr.ref_seq_id
        r.read_length = int(rl[i])
        r.pos = int(pos[i])
        r.read_group = int(rg[i])
        if names_inc:
            r.name = read_name()
        if r.cf & CF_DETACHED:
            r.mate_flags = int(mf[di])
            if not names_inc:
                r.name = read_name()
            r.mate_ref_id = int(ns[di])
            r.mate_pos = int(np_[di])
            r.template_size = int(ts[di])
            di += 1
        elif r.cf & CF_HAS_MATE_DOWNSTREAM:
            r.next_fragment = int(nf[wi])
            wi += 1
        for tag, typ in tag_dict[int(tl[i])]:
            enc = tag_encodings[tag_key(tag, typ)]
            r.tags.append(_tag_from_raw(tag, typ, enc.decode_array(st)))
        if not r.bf & 0x4:
            k = int(fn[mi])
            if fc_all is not None:
                _decode_mapped(comp, st, r, ref_names, ref_source,
                               fn=k, mq=int(mq[mi]),
                               fc=fc_all[fi:fi + k], fp=fp_all[fi:fi + k])
                fi += k
            else:
                _decode_mapped(comp, st, r, ref_names, ref_source,
                               fn=k, mq=int(mq[mi]))
            mi += 1
        else:
            ba = comp.series("BA")
            r.seq = ba.decode_bytes(st, r.read_length).decode("latin-1")
            r.cigar = "*"
            if r.cf & CF_QUAL_STORED:
                r.qual = comp.series("QS").decode_bytes(st, r.read_length)
        records.append(r)
    return records


def _tag_from_raw(tag: str, typ: str, raw: bytes) -> Tuple[str, str, object]:
    from hadoop_bam_tpu.formats.bam import parse_tags
    parsed = parse_tags(tag.encode("ascii") + typ.encode("ascii") + raw)
    if len(parsed) != 1:
        raise CRAMError(f"tag {tag}:{typ} value bytes did not parse cleanly")
    return parsed[0]


_FEATURE_HAS_ARRAY = {"b": "BB", "q": "QQ", "I": "IN", "S": "SC"}
_FEATURE_HAS_INT = {"D": "DL", "N": "RS", "P": "PD", "H": "HC"}


def _decode_mapped(comp: CompressionHeader, st: DecodeState, r: CramRecord,
                   ref_names: List[str],
                   ref_source: Optional[ReferenceSource],
                   fn: Optional[int] = None,
                   mq: Optional[int] = None,
                   fc=None, fp=None) -> None:
    # fn/mq (ints) and fc/fp (this record's feature-code/position
    # slices) arrive predecoded from the vectorized fast path; None
    # means decode them from the record-serial streams here
    if fn is None:
        fn = comp.series("FN").decode_int(st)
    if fc is None:
        fc_enc = comp.series("FC")
        fp_enc = comp.series("FP")
    features = []
    fpos = 0
    for j in range(fn):
        if fc is not None:             # predecoded feature streams
            code = chr(int(fc[j]))
            fpos += int(fp[j])
        else:
            code = chr(fc_enc.decode_byte(st))
            fpos += fp_enc.decode_int(st)
        if code in _FEATURE_HAS_ARRAY:
            val = comp.series(_FEATURE_HAS_ARRAY[code]).decode_array(st)
        elif code in _FEATURE_HAS_INT:
            val = comp.series(_FEATURE_HAS_INT[code]).decode_int(st)
        elif code == "X":
            val = comp.series("BS").decode_byte(st)
        elif code == "B":
            val = (comp.series("BA").decode_byte(st),
                   comp.series("QS").decode_byte(st))
            st.qs_feat_bytes += 1
        elif code == "i":
            val = comp.series("BA").decode_byte(st)
        elif code == "Q":
            val = comp.series("QS").decode_byte(st)
            st.qs_feat_bytes += 1
        else:
            raise CRAMError(f"unknown feature code {code!r}")
        features.append((fpos, code, val))
    r.mapq = comp.series("MQ").decode_int(st) if mq is None else mq
    quals = bytearray(b"\xff" * r.read_length)
    if r.cf & CF_QUAL_STORED:
        quals = bytearray(
            comp.series("QS").decode_bytes(st, r.read_length))

    # reconstruct seq + cigar from the feature list
    ref_base_at = _make_ref_lookup(r, ref_names, ref_source)
    seq = bytearray(b"?" * r.read_length)
    cigar: List[Tuple[int, str]] = []
    rp = 1           # 1-based read position
    ref_off = 0      # bases of reference consumed so far

    def emit(op: str, n: int):
        if n <= 0:
            return
        if cigar and cigar[-1][1] == op:
            cigar[-1] = (cigar[-1][0] + n, op)
        else:
            cigar.append((n, op))

    def fill_from_ref(read_at: int, n: int):
        nonlocal ref_off
        for i in range(n):
            seq[read_at - 1 + i] = ord(ref_base_at(ref_off + i))
        ref_off += n

    for fpos, code, val in features:
        gap = fpos - rp
        if gap > 0:
            emit("M", gap)
            fill_from_ref(rp, gap)
            rp += gap
        if code == "b":
            emit("M", len(val))
            seq[rp - 1:rp - 1 + len(val)] = val
            ref_off += len(val)
            rp += len(val)
        elif code == "X":
            emit("M", 1)
            seq[rp - 1] = ord(substitute_base(
                comp.substitution_matrix, ref_base_at(ref_off), val))
            ref_off += 1
            rp += 1
        elif code == "B":
            emit("M", 1)
            seq[rp - 1] = val[0]
            quals[rp - 1] = val[1]
            ref_off += 1
            rp += 1
        elif code == "I":
            emit("I", len(val))
            seq[rp - 1:rp - 1 + len(val)] = val
            rp += len(val)
        elif code == "i":
            emit("I", 1)
            seq[rp - 1] = val
            rp += 1
        elif code == "S":
            emit("S", len(val))
            seq[rp - 1:rp - 1 + len(val)] = val
            rp += len(val)
        elif code == "D":
            emit("D", val)
            ref_off += val
        elif code == "N":
            emit("N", val)
            ref_off += val
        elif code == "P":
            emit("P", val)
        elif code == "H":
            emit("H", val)
        elif code == "q":
            quals[rp - 1:rp - 1 + len(val)] = val
        elif code == "Q":
            quals[rp - 1] = val
    tail = r.read_length - (rp - 1)
    if tail > 0:
        emit("M", tail)
        fill_from_ref(rp, tail)

    r.seq = seq.decode("ascii") if r.read_length else "*"
    if r.cf & CF_UNKNOWN_BASES:
        r.seq = "*"
    r.cigar = "".join(f"{n}{op}" for n, op in cigar) if cigar else "*"
    r.qual = bytes(quals)


def _make_ref_lookup(r: CramRecord, ref_names: List[str],
                     ref_source: Optional[ReferenceSource]):
    cache = {}

    def ref_base_at(off: int) -> str:
        if r.cf & CF_UNKNOWN_BASES:
            # bases are declared unknown and the decoded seq is discarded
            # as '*' — the placeholder is output-equivalent WITH a
            # reference too, skips the pointless fetch, and keeps BS-code
            # validation deterministic (identical to the columnar path's
            # 'N'-row check) instead of depending on which reference base
            # happens to sit under the feature
            return "N"
        if ref_source is None:
            raise CRAMError(
                "slice requires reference bases but no reference source was "
                "provided (set cram_reference_source_path — the analog of "
                "hadoopbam.cram.reference-source-path)")
        if off not in cache:
            name = ref_names[r.ref_id] if 0 <= r.ref_id < len(ref_names) \
                else "*"
            chunk = ref_source.get(name, r.pos + off, 64)
            for i, b in enumerate(chunk):
                cache[off + i] = b
        return cache[off]

    return ref_base_at
