"""SAM text format: line codec and SAM<->BAM record conversion.

Reference equivalents: htsjdk ``SAMLineParser`` / ``SAMTextWriter`` as used by
hb/SAMInputFormat.java + hb/SAMRecordReader.java (line-split plain-text SAM,
parsed per line, header delivered out-of-band because splits that start
mid-file never see it) and hb/KeyIgnoringSAMRecordWriter.java.

[SPEC] SAMv1 section 1.4: 11 mandatory tab-separated fields
(QNAME FLAG RNAME POS MAPQ CIGAR RNEXT PNEXT TLEN SEQ QUAL) + optional
TAG:TYPE:VALUE fields.  POS/PNEXT are 1-based in SAM, 0-based in BAM.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from hadoop_bam_tpu.formats.bam import (
    SAMHeader, encode_record, parse_cigar_string, tag_from_sam, format_tag,
    BAMError,
)


@dataclass
class SamRecord:
    """One alignment in SAM-field terms (positions 1-based, '*' sentinels),
    the human-readable interchange type for tests, CLI `view`, and writers."""

    qname: str = "*"
    flag: int = 0
    rname: str = "*"
    pos: int = 0          # 1-based; 0 = unmapped
    mapq: int = 0
    cigar: str = "*"
    rnext: str = "*"
    pnext: int = 0
    tlen: int = 0
    seq: str = "*"
    qual: str = "*"
    tags: List[Tuple[str, str, object]] = field(default_factory=list)

    def to_line(self) -> str:
        fields = [self.qname, str(self.flag), self.rname, str(self.pos),
                  str(self.mapq), self.cigar, self.rnext, str(self.pnext),
                  str(self.tlen), self.seq, self.qual]
        fields += [format_tag(t) for t in self.tags]
        return "\t".join(fields)

    @classmethod
    def from_line(cls, line: str) -> "SamRecord":
        parts = line.rstrip("\n").split("\t")
        if len(parts) < 11:
            raise BAMError(f"SAM line has {len(parts)} fields, need 11")
        return cls(
            qname=parts[0], flag=int(parts[1]), rname=parts[2],
            pos=int(parts[3]), mapq=int(parts[4]), cigar=parts[5],
            rnext=parts[6], pnext=int(parts[7]), tlen=int(parts[8]),
            seq=parts[9], qual=parts[10],
            tags=[tag_from_sam(t) for t in parts[11:]],
        )

    def to_bam_bytes(self, header: SAMHeader) -> bytes:
        rid = -1 if self.rname == "*" else header.ref_id(self.rname)
        if self.rnext == "=":
            mrid = rid
        elif self.rnext == "*":
            mrid = -1
        else:
            mrid = header.ref_id(self.rnext)
        return encode_record(
            name=self.qname, flag=self.flag, refid=rid, pos=self.pos - 1,
            mapq=self.mapq, cigar=parse_cigar_string(self.cigar),
            mate_refid=mrid, mate_pos=self.pnext - 1, tlen=self.tlen,
            seq=self.seq, qual=self.qual, tags=self.tags)


def read_sam_text(text: str) -> Tuple[SAMHeader, List[SamRecord]]:
    """Parse a whole SAM document (header + alignments)."""
    header_lines: List[str] = []
    records: List[SamRecord] = []
    for line in text.splitlines():
        if not line:
            continue
        if line.startswith("@"):
            header_lines.append(line + "\n")
        else:
            records.append(SamRecord.from_line(line))
    return SAMHeader.from_sam_text("".join(header_lines)), records


def write_sam_text(header: SAMHeader, records) -> str:
    out = [header.to_sam_text()]
    for r in records:
        out.append(r.to_line() + "\n")
    return "".join(out)
