"""write_bam_records / write_bcf_records — the parallel write front door.

The OutputFormat half of the loop: mesh-sort buckets (or any other
record-stream producer) go straight to a sorted BAM / BGZF BCF whose
index sidecars are generated DURING the write, atomically published so a
partial output is never visible under the final name.  ``sort_bam_mesh``
and the ``hbam sort`` CLI route through here; the PR-5 ``QueryEngine``
can open the result cold using only the co-written sidecars.

Publication order is data-then-sidecars on purpose: a reader that races
the rename can see a BAM without its sidecar (it rebuilds or falls back
to scanning) but never a fresh sidecar pointing into a stale BAM.

Config knobs (``config.py``): ``write_compress_level`` (BGZF deflate
level, every producing path), ``write_parallel_workers`` (in-flight
deflate bound; 0 = serial in-line), ``write_index_kinds`` ("auto" /
"none" / comma list).
"""
from __future__ import annotations

import contextlib
import dataclasses
import os
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.write.indexing import (
    BamIndexingSink, BcfIndexingSink, resolve_index_kinds,
)
from hadoop_bam_tpu.write.parallel_bgzf import ParallelBGZFWriter

_TMP_SUFFIX = ".hbam-write-tmp"


@dataclasses.dataclass
class WriteResult:
    path: str
    records: int
    bytes_out: int
    sidecars: Dict[str, str]        # suffix -> sidecar path


def _writer_inflight(config: HBamConfig) -> Optional[int]:
    n = getattr(config, "write_parallel_workers", None)
    return None if n is None else int(n)


# sidecar suffixes a reader may resolve for each container — ALL of
# them are purged on overwrite, not just the kinds being rewritten: a
# stale index surviving next to fresh data would send readers to
# mid-block voffsets of the old file (the inverse of the data-first
# ordering guarantee)
_PURGE_SUFFIXES = {
    "bam": (".bai", ".csi", ".sbi", ".splitting-bai"),
    "bcf": (".tbi", ".csi"),
}


def _publish(tmp_path: str, path: str, sidecar_blobs: Dict[str, bytes],
             container: str) -> Dict[str, str]:
    """Atomic publication, ordered so no reader ever pairs an index
    with the wrong data AND a write failure never leaves the final name
    published: (1) write every fresh sidecar to its own temp file — any
    I/O failure (ENOSPC et al.) aborts here, before anything is
    visible; (2) unlink every pre-existing sidecar a reader could
    resolve (old data + no index is the harmless state); (3) rename the
    data file into place; (4) rename the sidecars after it — the only
    steps past the data rename are metadata-only renames."""
    side_tmps: list = []
    sidecars: Dict[str, str] = {}
    try:
        for suffix, blob in sorted(sidecar_blobs.items()):
            side_tmp = path + suffix + _TMP_SUFFIX
            side_tmps.append((suffix, side_tmp))
            with open(side_tmp, "wb") as f:
                f.write(blob)
        # the purge must precede the data rename: purge-first leaves a
        # window of old-data+no-index (harmless), rename-first would
        # leave new-data+old-index (readers seek into the wrong file)
        for suffix in _PURGE_SUFFIXES.get(container, ()):
            with contextlib.suppress(OSError):
                os.unlink(path + suffix)
        os.replace(tmp_path, path)
        for suffix, side_tmp in side_tmps:
            os.replace(side_tmp, path + suffix)
            sidecars[suffix] = path + suffix
    except BaseException:
        # already-renamed sidecar temps are gone (unlink no-ops); the
        # caller's handler owns tmp_path
        for _suffix, side_tmp in side_tmps:
            with contextlib.suppress(OSError):
                os.unlink(side_tmp)
        raise
    return sidecars


def write_bam_records(path: str, header, chunks: Iterable[Tuple],
                      *, config: HBamConfig = DEFAULT_CONFIG,
                      index_kinds: Optional[Sequence[str]] = None,
                      pool=None) -> WriteResult:
    """Write a BAM from record-aligned byte chunks.

    ``chunks`` yields ``(data, offsets)`` pairs: ``data`` is a uint8
    array (or bytes) of concatenated raw BAM records in file order,
    ``offsets`` the int64 start offset of every record within ``data``.
    The stream must be coordinate-sorted when a genomic index kind is
    requested (the sidecar is meaningless otherwise, exactly as with
    ``samtools index``).

    Byte-identical to streaming the same records through the serial
    ``BamWriter`` at the same compression level.
    """
    from hadoop_bam_tpu.formats.bam import BamBatch

    kinds = tuple(index_kinds) if index_kinds is not None \
        else resolve_index_kinds(config, "bam")
    sink_idx = BamIndexingSink(
        len(header.ref_names), kinds,
        granularity=int(getattr(config, "splitting_index_granularity",
                                4096))) if kinds else None
    tmp_path = path + _TMP_SUFFIX
    records = 0
    try:
        with open(tmp_path, "wb") as sink:
            w = ParallelBGZFWriter(
                sink, level=int(config.write_compress_level),
                max_inflight=_writer_inflight(config), pool=pool,
                config=config)
            with w:
                w.write(header.to_bam_bytes())
                for data, offs in chunks:
                    arr = np.frombuffer(data, dtype=np.uint8) \
                        if isinstance(data, (bytes, bytearray, memoryview)) \
                        else np.asarray(data, dtype=np.uint8)
                    offs = np.asarray(offs, dtype=np.int64)
                    if sink_idx is not None and offs.size:
                        batch = BamBatch(arr, offs, header=header)
                        pos0 = batch.pos.astype(np.int64)
                        end0 = pos0 + np.maximum(batch.reference_span(),
                                                 1).astype(np.int64)
                        sink_idx.observe(
                            batch.refid.astype(np.int64), pos0, end0,
                            w.tell_payload_offset() + offs)
                    records += int(offs.size)
                    w.write(arr)
        size = os.path.getsize(tmp_path)
        blobs = sink_idx.finalize(w.resolve_voffsets, w.data_end_voffset,
                                  size) if sink_idx is not None else {}
        sidecars = _publish(tmp_path, path, blobs, "bam")
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp_path)
        raise
    METRICS.count("write.records", records)
    return WriteResult(path=path, records=records, bytes_out=w.bytes_out,
                       sidecars=sidecars)


def write_bcf_records(path: str, header, records: Iterable,
                      *, config: HBamConfig = DEFAULT_CONFIG,
                      index_kinds: Optional[Sequence[str]] = None,
                      pool=None) -> WriteResult:
    """Write a BGZF BCF from ``VcfRecord``s with a co-written ``.tbi``.

    Byte-identical to the serial ``BcfWriter`` at the same level; the
    tabix sidecar is built from the same pass (positions observed as
    each record is encoded, voffsets resolved after close).

    ``config.write_header`` / ``config.write_terminator`` are honored
    exactly as the ``BcfShardWriter`` path this replaces honored them
    (headerless shard-style output / no BGZF EOF block)."""
    from hadoop_bam_tpu.formats.bcf import BCFRecordCodec, encode_header

    kinds = tuple(index_kinds) if index_kinds is not None \
        else resolve_index_kinds(config, "bcf")
    sink_idx = BcfIndexingSink(kinds) if kinds else None
    codec = BCFRecordCodec(header)
    tmp_path = path + _TMP_SUFFIX
    n = 0
    try:
        with open(tmp_path, "wb") as sink:
            w = ParallelBGZFWriter(
                sink, level=int(config.write_compress_level),
                write_eof=bool(getattr(config, "write_terminator", True)),
                max_inflight=_writer_inflight(config), pool=pool,
                config=config)
            with w:
                if getattr(config, "write_header", True):
                    w.write(encode_header(header))
                for rec in records:
                    if sink_idx is not None:
                        beg0 = rec.pos - 1
                        sink_idx.observe(rec.chrom, beg0,
                                         beg0 + max(rec.rlen, 1),
                                         w.tell_payload_offset())
                    w.write(codec.encode(rec))
                    n += 1
        size = os.path.getsize(tmp_path)
        blobs = sink_idx.finalize(w.resolve_voffsets, w.data_end_voffset,
                                  size) if sink_idx is not None else {}
        sidecars = _publish(tmp_path, path, blobs, "bcf")
    except BaseException:
        with contextlib.suppress(OSError):
            os.unlink(tmp_path)
        raise
    METRICS.count("write.records", n)
    return WriteResult(path=path, records=n, bytes_out=w.bytes_out,
                       sidecars=sidecars)


def write_bam_shards_concat(parts: Sequence[str], path: str, header,
                            *, config: HBamConfig = DEFAULT_CONFIG,
                            index_kinds: Optional[Sequence[str]] = None
                            ) -> WriteResult:
    """Re-block headerless record shards into ONE continuous BGZF stream
    through the parallel write path — the indexing, atomically-published
    successor of ``utils/mergers.merge_bam_shards_reblocked``: output
    bytes match writing the same records through a single streaming
    writer, and the sidecars ride along."""
    from hadoop_bam_tpu.formats.bam import walk_record_offsets
    from hadoop_bam_tpu.ops import inflate as inflate_ops

    def chunks():
        from hadoop_bam_tpu.utils.resilient import (
            call_with_retry, span_retry_policy,
        )
        from hadoop_bam_tpu.utils.seekable import scoped_byte_source

        policy = span_retry_policy(config)

        def read_part(p):
            # through as_byte_source, not a bare open(): part reads on a
            # shared filesystem fault like any other read — transient
            # faults retry with backoff, and the install_chaos registry
            # observes them (the audited shard-concat seam, pinned by
            # test)
            with scoped_byte_source(p) as src:
                return src.pread(0, src.size)

        for p in parts:
            raw = call_with_retry(lambda p=p: read_part(p), policy,
                                  what=f"shard part read {p}",
                                  counter="write.part_read_retries")
            if not raw:
                continue
            table = inflate_ops.block_table(raw)
            data, _ = inflate_ops.inflate_span(raw, table)
            if not data.size:
                continue
            yield data, walk_record_offsets(data)

    return write_bam_records(path, header, chunks(), config=config,
                             index_kinds=index_kinds)
