"""Mesh-parallel write path: sharded BGZF writers with self-indexing.

The OutputFormat side of the repo (PAPER.md §1: Hadoop-BAM ships
OutputFormats alongside InputFormats).  Pieces:

- ``parallel_bgzf.ParallelBGZFWriter`` — pool-parallel deflate with a
  single order-preserving committer; byte-identical to the serial
  ``formats/bgzf.BGZFWriter``.
- ``sharded.ShardedFileWriter`` — deterministic per-shard temp files +
  atomic final publication for multi-host producers.
- ``indexing`` — BAI / tabix / splitting-index sidecars generated during
  the write (no rescan).
- ``api.write_bam_records`` / ``api.write_bcf_records`` — the front door
  ``parallel/mesh_sort.py`` and the CLI route through.
"""
from hadoop_bam_tpu.write.api import (            # noqa: F401
    WriteResult, write_bam_records, write_bam_shards_concat,
    write_bcf_records,
)
from hadoop_bam_tpu.write.indexing import (       # noqa: F401
    BamIndexingSink, BcfIndexingSink, resolve_index_kinds,
)
from hadoop_bam_tpu.write.parallel_bgzf import (  # noqa: F401
    ParallelBGZFWriter,
)
from hadoop_bam_tpu.write.sharded import (        # noqa: F401
    ShardedFileWriter, write_shards_journaled,
)
