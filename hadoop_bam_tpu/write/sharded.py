"""ShardedFileWriter — deterministic shard files + atomic publication.

The multi-host write protocol of the mesh sort (and any future sharded
producer): shard k is written by the host that owns device position k
into a deterministic part file inside a sibling shard directory, hosts
barrier (the caller owns the collective — this class is I/O only), and
host 0 concatenates the parts into the final file.  Two atomicity rules,
both enforced here so no caller can get them wrong:

- each PART is written to ``part-NNNNN.tmp`` and renamed into place on
  successful close, so a crashed host never leaves a plausible-looking
  truncated part for the merger to concatenate;
- the FINAL file is produced by a builder callback that itself writes
  through a temp + ``os.replace`` (``write/api.py`` does), so a partial
  output is never visible under the final name — readers either see the
  old file or the complete new one.

The shard directory lives next to the final path (``<final><suffix>``)
— on a shared filesystem that is exactly the property multi-host needs
(every host writes into the same directory host 0 reads).
"""
from __future__ import annotations

import contextlib
import os
import shutil
from typing import Callable, Iterator, List, Sequence


class ShardedFileWriter:
    """Per-shard temp files + ordered concatenation (module docstring)."""

    def __init__(self, final_path: str, n_shards: int, *,
                 dir_suffix: str = ".hbam-shards"):
        self.final_path = final_path
        self.n_shards = int(n_shards)
        self.shard_dir = final_path + dir_suffix

    # -- shard side (every host) --------------------------------------------

    def prepare(self) -> None:
        """Remove stale parts from an earlier failed run.  Call on ONE
        host, BEFORE the barrier that precedes any shard write."""
        shutil.rmtree(self.shard_dir, ignore_errors=True)

    def shard_path(self, k: int) -> str:
        return os.path.join(self.shard_dir, f"part-{k:05d}")

    @contextlib.contextmanager
    def open_shard(self, k: int) -> Iterator:
        """Open shard ``k`` for writing; the part becomes visible under
        its deterministic name only when the block exits cleanly."""
        os.makedirs(self.shard_dir, exist_ok=True)
        part = self.shard_path(k)
        tmp_part = part + ".tmp"
        f = open(tmp_part, "wb")
        try:
            yield f
        except BaseException:
            f.close()
            with contextlib.suppress(OSError):
                os.unlink(tmp_part)
            raise
        f.close()
        os.replace(tmp_part, part)

    # -- merge side (host 0) -------------------------------------------------

    def parts(self) -> List[str]:
        return [self.shard_path(k) for k in range(self.n_shards)]

    def missing_parts(self) -> List[str]:
        return [p for p in self.parts() if not os.path.exists(p)]

    def concatenate(self, build: Callable[[Sequence[str]], object],
                    what: str = "sharded write",
                    cleanup: bool = True) -> object:
        """Run ``build(parts)`` — which must publish the final file
        atomically itself (``write_bam_records`` does) — then remove the
        shard directory (``cleanup=False`` preserves it, e.g. under a
        debug-keep flag).  Refuses on missing parts: every shard writes
        exactly one part (empty shards included), so absence means
        shared-filesystem lag or data loss, never a benign skip."""
        missing = self.missing_parts()
        if missing:
            # TRANSIENT class: on a shared filesystem a part that every
            # host barriered on is visible-soon lag, not corruption —
            # the retrying caller (or operator) should re-attempt the
            # merge, not classify the output as bad data
            from hadoop_bam_tpu.utils.errors import TransientIOError
            raise TransientIOError(
                f"{what}: shard(s) missing at merge time: {missing[:3]}"
                f"{'...' if len(missing) > 3 else ''} — is "
                f"{self.shard_dir} on a filesystem shared by all hosts?")
        result = build(self.parts())
        if cleanup:
            self.cleanup()
        return result

    def cleanup(self) -> None:
        shutil.rmtree(self.shard_dir, ignore_errors=True)
