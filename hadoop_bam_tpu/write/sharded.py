"""ShardedFileWriter — deterministic shard files + atomic publication.

The multi-host write protocol of the mesh sort (and any future sharded
producer): shard k is written by the host that owns device position k
into a deterministic part file inside a sibling shard directory, hosts
barrier (the caller owns the collective — this class is I/O only), and
host 0 concatenates the parts into the final file.  Two atomicity rules,
both enforced here so no caller can get them wrong:

- each PART is written to ``part-NNNNN.tmp`` and renamed into place on
  successful close, so a crashed host never leaves a plausible-looking
  truncated part for the merger to concatenate;
- the FINAL file is produced by a builder callback that itself writes
  through a temp + ``os.replace`` (``write/api.py`` does), so a partial
  output is never visible under the final name — readers either see the
  old file or the complete new one.

The shard directory lives next to the final path (``<final><suffix>``)
— on a shared filesystem that is exactly the property multi-host needs
(every host writes into the same directory host 0 reads).

Crash recovery (jobs/): the deterministic ``part-NNNNN`` names are what
make shard writes resumable — a journal (``jobs/journal.py``) records
each committed part's size + CRC, ``shard_committed`` verifies a part
against that record so a resumed run skips rewriting it, and
``sweep_stale_temps`` removes the ``*.tmp`` orphans of the write that
was in flight when the previous run died (they would otherwise leak
forever; a colliding name is harmless — ``open`` truncates — but a
crashed run's temps squatting in the directory are exactly the
plausible-looking garbage the ``.tmp`` discipline exists to fence off).
"""
from __future__ import annotations

import contextlib
import os
import shutil
from typing import Callable, Iterator, List, Optional, Sequence

from hadoop_bam_tpu.utils.metrics import METRICS


class ShardedFileWriter:
    """Per-shard temp files + ordered concatenation (module docstring).

    ``journal`` (a ``jobs.journal.JobJournal``) makes commits durable:
    every renamed part appends a verified ``("shard", k)`` unit;
    ``resume_state`` (the replayed ``JournalState`` of a prior attempt)
    lets ``shard_committed`` skip parts that prior attempt finished."""

    def __init__(self, final_path: str, n_shards: int, *,
                 dir_suffix: str = ".hbam-shards",
                 journal=None, resume_state=None):
        self.final_path = final_path
        self.n_shards = int(n_shards)
        self.shard_dir = final_path + dir_suffix
        self.journal = journal
        self.resume_state = resume_state

    # -- shard side (every host) --------------------------------------------

    def prepare(self) -> None:
        """Remove stale parts from an earlier failed run (sweeping —
        and counting — its orphaned temps first).  Call on ONE host,
        BEFORE the barrier that precedes any shard write."""
        self.sweep_stale_temps()
        shutil.rmtree(self.shard_dir, ignore_errors=True)

    def sweep_stale_temps(self) -> int:
        """Unlink ``*.tmp`` orphans a crashed previous run left in the
        shard directory; returns the count (also reported via the
        ``write.stale_temps_swept`` counter).  Resume paths call this
        INSTEAD of ``prepare`` — committed parts must survive, only the
        in-flight write's debris goes."""
        swept = 0
        try:
            names = os.listdir(self.shard_dir)
        except OSError:
            return 0
        for name in names:
            if not name.endswith(".tmp"):
                continue
            with contextlib.suppress(OSError):
                os.unlink(os.path.join(self.shard_dir, name))
                swept += 1
        if swept:
            METRICS.count("write.stale_temps_swept", swept)
        return swept

    def shard_path(self, k: int) -> str:
        return os.path.join(self.shard_dir, f"part-{k:05d}")

    def shard_committed(self, k: int) -> bool:
        """True iff a prior attempt's journal committed shard ``k`` AND
        the part file on disk still matches the recorded size + CRC —
        verification, not trust: a part the crash corrupted (or a
        filesystem that lost the rename) re-writes."""
        if self.resume_state is None:
            return False
        from hadoop_bam_tpu.jobs.journal import verify_artifact

        unit = self.resume_state.unit("shard", k)
        if unit is None:
            return False
        ok = verify_artifact(self.shard_path(k), unit.get("size", -1),
                             unit.get("crc", ""))
        if ok:
            METRICS.count("jobs.shards_skipped")
        return ok

    @contextlib.contextmanager
    def open_shard(self, k: int) -> Iterator:
        """Open shard ``k`` for writing; the part becomes visible under
        its deterministic name only when the block exits cleanly (and,
        with a journal, is recorded as committed only after the
        rename)."""
        os.makedirs(self.shard_dir, exist_ok=True)
        part = self.shard_path(k)
        tmp_part = part + ".tmp"
        f = open(tmp_part, "wb")
        try:
            yield f
        except BaseException:
            f.close()
            with contextlib.suppress(OSError):
                os.unlink(tmp_part)
            raise
        f.close()
        os.replace(tmp_part, part)
        if self.journal is not None:
            from hadoop_bam_tpu.jobs.journal import file_digest

            size, crc = file_digest(part)
            # abspath: the unit must verify from whatever cwd the
            # resuming process runs in
            self.journal.unit_done("shard", k,
                                   path=os.path.abspath(part),
                                   size=size, crc=crc)

    # -- merge side (host 0) -------------------------------------------------

    def parts(self) -> List[str]:
        return [self.shard_path(k) for k in range(self.n_shards)]

    def missing_parts(self) -> List[str]:
        return [p for p in self.parts() if not os.path.exists(p)]

    def concatenate(self, build: Callable[[Sequence[str]], object],
                    what: str = "sharded write",
                    cleanup: bool = True) -> object:
        """Run ``build(parts)`` — which must publish the final file
        atomically itself (``write_bam_records`` does) — then remove the
        shard directory (``cleanup=False`` preserves it, e.g. under a
        debug-keep flag).  Refuses on missing parts: every shard writes
        exactly one part (empty shards included), so absence means
        shared-filesystem lag or data loss, never a benign skip."""
        missing = self.missing_parts()
        if missing:
            # TRANSIENT class: on a shared filesystem a part that every
            # host barriered on is visible-soon lag, not corruption —
            # the retrying caller (or operator) should re-attempt the
            # merge, not classify the output as bad data
            from hadoop_bam_tpu.utils.errors import TransientIOError
            raise TransientIOError(
                f"{what}: shard(s) missing at merge time: {missing[:3]}"
                f"{'...' if len(missing) > 3 else ''} — is "
                f"{self.shard_dir} on a filesystem shared by all hosts?")
        result = build(self.parts())
        if cleanup:
            self.cleanup()
        return result

    def cleanup(self) -> None:
        shutil.rmtree(self.shard_dir, ignore_errors=True)


def write_shards_journaled(sw: ShardedFileWriter,
                           payloads: Sequence[bytes],
                           write_one: Optional[Callable] = None) -> int:
    """Write every not-yet-committed shard of ``payloads`` through
    ``sw`` — the journal-aware producer loop for resumable sharded
    jobs (pinned by the kill-and-resume tests): committed shards are
    verified and skipped, everything else is (re)written.  Returns the
    number of shards actually written this attempt.  The mesh sort's
    multi-host shard writes will route through this once journaling
    grows a per-host resume barrier protocol (today journaling is
    single-process; see ``sort_bam_mesh``)."""
    wrote = 0
    for k, payload in enumerate(payloads):
        if sw.shard_committed(k):
            continue
        with sw.open_shard(k) as f:
            if write_one is not None:
                write_one(f, k, payload)
            else:
                f.write(payload)
        wrote += 1
    return wrote
