"""IndexingSink — index sidecars built during the write, not after it.

Hadoop-BAM's splitting indexer had an MR-integrated mode (the indexer
rides the output writer, hb/SplittingBAMIndexer.java) precisely because
rescanning a file you just wrote doubles the I/O.  This sink generalizes
that to every sidecar the query engine consumes: it observes one
``(refid, pos, end, position-token)`` tuple per record as the writer
emits it, and at finalize — once the ``ParallelBGZFWriter`` knows every
block's compressed offset — resolves the tokens to packed virtual
offsets and emits:

- ``.bai``            genomic binning index (``split/bai.BAIBuilder``)
- ``.tbi``            tabix index for BGZF BCF (``split/tabix.TabixBuilder``)
- ``.sbi`` / ``.splitting-bai``   record-boundary splitting index

so a file written by the parallel write path is immediately re-queryable
by the PR-5 ``QueryEngine`` and the PR-8 serve tier with no rescan and
no ``build_bai``/``build_tabix`` call.
"""
from __future__ import annotations

import array
from typing import Callable, Dict, List, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.utils.errors import PlanError

BAM_INDEX_KINDS = ("bai", "sbi", "splitting-bai")
BCF_INDEX_KINDS = ("tbi",)


def resolve_index_kinds(config, container: str) -> Tuple[str, ...]:
    """``config.write_index_kinds`` -> concrete sidecar kinds for one
    container: "auto" picks the kinds the query engine needs cold
    (BAM: bai+sbi, BCF: tbi); "none" disables; otherwise a comma list
    validated against the container's legal kinds."""
    raw = getattr(config, "write_index_kinds", "auto") or "auto"
    legal = BAM_INDEX_KINDS if container == "bam" else BCF_INDEX_KINDS
    if raw == "none":
        return ()
    if raw == "auto":
        return ("bai", "sbi") if container == "bam" else ("tbi",)
    kinds = tuple(k.strip() for k in str(raw).split(",") if k.strip())
    bad = [k for k in kinds if k not in legal]
    if bad:
        raise PlanError(
            f"write_index_kinds {bad} unsupported for {container} "
            f"output; legal kinds: {legal} (or 'auto'/'none')")
    return kinds


class BamIndexingSink:
    """Accumulates per-record (refid, beg0, end0, payload-token) columns
    for a BAM write; ``finalize`` maps tokens to virtual offsets via the
    writer's resolver and renders the sidecar blobs."""

    def __init__(self, n_ref: int, kinds: Sequence[str],
                 granularity: int = 4096):
        self.kinds = tuple(kinds)
        self._n_ref = n_ref
        self._granularity = max(1, int(granularity))
        self._refid: List[np.ndarray] = []
        self._beg: List[np.ndarray] = []
        self._end: List[np.ndarray] = []
        self._tokens: List[np.ndarray] = []
        self.records = 0

    def observe(self, refid, beg0, end0, tokens) -> None:
        """One vectorized batch of records, in file order."""
        self._refid.append(np.asarray(refid, np.int64))
        self._beg.append(np.asarray(beg0, np.int64))
        self._end.append(np.asarray(end0, np.int64))
        self._tokens.append(np.asarray(tokens, np.int64))
        self.records += int(self._tokens[-1].size)

    def _concat(self):
        cat = (lambda xs: np.concatenate(xs) if xs
               else np.zeros(0, np.int64))
        return (cat(self._refid), cat(self._beg), cat(self._end),
                cat(self._tokens))

    def finalize(self, resolve: Callable[[np.ndarray], np.ndarray],
                 end_voffset: int, file_size: int) -> Dict[str, bytes]:
        """-> {sidecar suffix: serialized bytes} for every configured
        kind.  ``resolve`` maps payload tokens to packed voffsets
        (``ParallelBGZFWriter.resolve_voffsets``); ``end_voffset`` is
        the end-of-data position closing the last BAI chunk."""
        from hadoop_bam_tpu.split.bai import BAI_SUFFIX, bai_from_columns
        from hadoop_bam_tpu.split.splitting_index import (
            SBI_SUFFIX, SPLITTING_BAI_SUFFIX, SplittingIndex,
        )

        refid, beg, end, tokens = self._concat()
        voffs = resolve(tokens).astype(np.uint64)
        out: Dict[str, bytes] = {}
        if "bai" in self.kinds:
            # vectorized over the accumulated columns — a per-record
            # BAIBuilder loop here would serialize 10^8 interpreter
            # iterations between the pooled deflate and publication
            idx = bai_from_columns(self._n_ref, refid, beg, end, voffs,
                                   int(end_voffset))
            out[BAI_SUFFIX] = idx.to_bytes()
        if "sbi" in self.kinds or "splitting-bai" in self.kinds:
            g = self._granularity
            sampled = [int(v) for v in voffs[::g]] + [file_size << 16]
            idx = SplittingIndex(voffsets=sampled, granularity=g,
                                 total_records=self.records)
            if "sbi" in self.kinds:
                out[SBI_SUFFIX] = idx.to_sbi_bytes(file_size)
            if "splitting-bai" in self.kinds:
                out[SPLITTING_BAI_SUFFIX] = idx.to_splitting_bai_bytes()
        return out


class BcfIndexingSink:
    """The BCF sibling: per-record (contig, beg0, end0, token) feeding a
    tabix-shaped sidecar — what the query engine resolves BCF regions
    through.  Contigs are interned to small ints and the numeric columns
    accumulate in flat ``array`` buffers (~32 B/record), not per-record
    tuples — a cohort-scale BCF write must not hold gigabytes of index
    rows in Python objects."""

    def __init__(self, kinds: Sequence[str]):
        self.kinds = tuple(kinds)
        self._names: List[str] = []            # contig id -> name
        self._name_ids: Dict[str, int] = {}
        self._chrom = array.array("q")
        self._beg = array.array("q")
        self._end = array.array("q")
        self._tokens = array.array("q")
        self.records = 0

    def observe(self, chrom: str, beg0: int, end0: int,
                token: int) -> None:
        cid = self._name_ids.get(chrom)
        if cid is None:
            cid = self._name_ids[chrom] = len(self._names)
            self._names.append(chrom)
        self._chrom.append(cid)
        self._beg.append(beg0)
        self._end.append(end0)
        self._tokens.append(token)
        self.records += 1

    def finalize(self, resolve: Callable[[np.ndarray], np.ndarray],
                 end_voffset: int, file_size: int) -> Dict[str, bytes]:
        from hadoop_bam_tpu.split.tabix import TBI_SUFFIX, TabixBuilder

        out: Dict[str, bytes] = {}
        if "tbi" in self.kinds:
            voffs = resolve(np.frombuffer(self._tokens, np.int64)
                            if self.records else
                            np.zeros(0, np.int64)).astype(np.uint64)
            builder = TabixBuilder()
            names = self._names
            for cid, beg0, end0, v in zip(self._chrom, self._beg,
                                          self._end, voffs):
                builder.add(names[cid], beg0, end0, int(v))
            out[TBI_SUFFIX] = builder.finalize(int(end_voffset)).to_bytes()
        return out
