"""ParallelBGZFWriter — pipelined BGZF compression on the shared pool.

The serial ``formats/bgzf.BGZFWriter`` deflates every 0xFF00-byte payload
chunk inline on the caller's thread, so the write path of ``mesh_sort``
(and anything else producing sorted output) is bounded by one core's
deflate rate.  This writer keeps the exact same BLOCK GEOMETRY — payload
is cut at ``WRITE_PAYLOAD_SIZE`` boundaries, each chunk becomes one
``deflate_block`` member — but runs the deflates concurrently on the
process-wide decode pool (``utils/pools.py``, foreground priority) while
a single committer thread writes finished blocks to the sink strictly in
submission order.  Because chunking and ``deflate_block`` are both
deterministic, the output is byte-identical to the serial writer at the
same compression level, for any worker count and any ``write()`` call
split (the concurrency fuzz in ``tests/test_write.py`` pins this).

Virtual offsets are the one thing that cannot be answered synchronously:
a block's compressed start is unknown until every earlier block has been
deflated.  Callers therefore track PAYLOAD offsets (``tell_payload_offset``
— a plain count of uncompressed bytes accepted) as position tokens and
map them to packed virtual offsets after ``close()`` with
``resolve_voffsets`` — the hook ``write/indexing.IndexingSink`` uses to
build BAI/tabix/splitting-index sidecars in the same pass as the write.

Observability: ``write.deflate_wall`` (union wall of the pool deflates),
``write.commit_wall`` (committer sink time), ``write.bytes_out`` /
``write.blocks_out`` counters.
"""
from __future__ import annotations

import collections
import contextvars
import queue
import threading
from typing import List, Optional

import numpy as np

from hadoop_bam_tpu.formats import bgzf
from hadoop_bam_tpu.resilience import chaos
from hadoop_bam_tpu.utils.errors import PlanError
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.resilient import (
    call_with_retry, span_retry_policy,
)

_SENTINEL = object()


class ParallelBGZFWriter:
    """Order-preserving parallel BGZF writer (module docstring).

    ``max_inflight=0`` selects the serial in-line mode: same code path,
    same bytes, no pool and no committer thread — the "serial writer"
    arm of the bench row and the fallback for single-block outputs.
    """

    def __init__(self, sink, *, level: int = 6, write_eof: bool = True,
                 pool=None, max_inflight: Optional[int] = None,
                 config=None):
        self._sink = sink
        self._level = int(level)
        self._write_eof = write_eof
        self._buf = bytearray()
        self._accepted = 0          # payload bytes accepted by write()
        self._submitted = 0         # payload bytes cut into blocks so far
        self._block_starts: List[int] = []   # payload start per block
        self._block_coffs: List[int] = []    # compressed start per block
        self._coffset = 0           # compressed bytes committed so far
        self.bytes_out = 0
        self.data_end_coffset = 0   # set at close (before the EOF block)
        self._closed = False
        # orders committer-side bookkeeping (_err, _coffset, bytes_out,
        # _block_coffs) against producer-thread readers: _check_err polls
        # _err mid-write, and close/resolve_voffsets read the offsets the
        # committer thread produced.  Never contended on the hot path —
        # the committer is the only writer in flight.
        self._mu = threading.Lock()
        self._err: Optional[BaseException] = None
        if max_inflight is not None and max_inflight < 0:
            raise PlanError(f"max_inflight must be >= 0, "
                            f"got {max_inflight}")
        # deflate-worker fault recovery: transient-classified faults in
        # a worker (an injected write.deflate chaos fault, a wobbly
        # memory allocator) retry in place instead of poisoning the
        # writer — deflate is deterministic, so a healed retry keeps the
        # output byte-identical; corrupt/plan classes still fail fast
        self._retry = span_retry_policy(config)
        serial = max_inflight == 0
        self._pool = None
        self._committer = None
        if not serial:
            if pool is None:
                from hadoop_bam_tpu.utils import pools
                pool = pools.decode_pool(config)
                if max_inflight is None:
                    max_inflight = pools.decode_pool_size(config)
            if max_inflight is None:
                max_inflight = int(getattr(pool, "_max_workers", 4) or 4)
            self._pool = pool
            # bound on blocks in flight (submitted, not yet committed):
            # backpressure so a fast producer cannot queue the whole
            # file's payload in the shared pool and starve other work
            self._sem = threading.Semaphore(max(2, 2 * int(max_inflight)))
            self._q: "queue.Queue" = queue.Queue()
            ctx = contextvars.copy_context()
            self._committer = threading.Thread(
                target=ctx.run, args=(self._commit_loop,),
                name="hbam-write-commit", daemon=True)
            self._committer.start()

    # -- producer side -------------------------------------------------------

    def tell_payload_offset(self) -> int:
        """Uncompressed position token of the next byte written; map to a
        packed virtual offset with ``resolve_voffsets`` after close."""
        return self._accepted

    def write(self, data) -> None:
        if self._closed:
            raise PlanError("write after close on ParallelBGZFWriter")
        self._check_err()
        mv = memoryview(data) if not isinstance(data, (bytes, bytearray)) \
            else data
        self._buf += mv
        self._accepted += len(mv)
        while len(self._buf) >= bgzf.WRITE_PAYLOAD_SIZE:
            payload = bytes(self._buf[:bgzf.WRITE_PAYLOAD_SIZE])
            del self._buf[:bgzf.WRITE_PAYLOAD_SIZE]
            self._submit_block(payload)

    def flush(self) -> None:
        """Cut the buffered remainder into a (short) block.  Mid-stream
        flushes change the block geometry away from the serial writer's
        (which only flushes at close), so byte-identity callers must not
        flush until close — close() calls this itself."""
        if self._buf:
            payload = bytes(self._buf)
            self._buf.clear()
            self._submit_block(payload)

    def _submit_block(self, payload: bytes) -> None:
        self._block_starts.append(self._submitted)
        self._submitted += len(payload)
        if self._pool is None:
            self._commit(self._deflate(payload))
            return
        # acquire an in-flight permit BEFORE handing the pool the bytes;
        # poll so a dead committer surfaces as the stored error instead
        # of a silent hang
        while not self._sem.acquire(timeout=0.5):
            self._check_err()
        from hadoop_bam_tpu.utils import pools
        self._q.put(pools.submit(self._pool, self._deflate, payload))

    def _deflate(self, payload: bytes) -> bytes:
        def run() -> bytes:
            # chaos point: a fault inside the deflate worker — the
            # schedule decides whether it heals on retry (transient) or
            # poisons the writer (corrupt)
            chaos.fire("write.deflate", nbytes=len(payload))
            return bgzf.deflate_block(payload, self._level)

        with METRICS.span("write.deflate_wall", nbytes=len(payload)):
            return call_with_retry(run, self._retry, what="bgzf deflate",
                                   counter="write.deflate_retries")

    # -- committer side ------------------------------------------------------

    def _commit(self, block: bytes) -> None:
        with METRICS.span("write.commit_wall"):
            with self._mu:
                self._block_coffs.append(self._coffset)
            self._sink.write(block)
        with self._mu:
            self._coffset += len(block)
            self.bytes_out += len(block)
        METRICS.count("write.bytes_out", len(block))
        METRICS.count("write.blocks_out")

    def _commit_loop(self) -> None:
        while True:
            fut = self._q.get()
            if fut is _SENTINEL:
                return
            try:
                block = fut.result()
                with self._mu:
                    poisoned = self._err is not None
                if not poisoned:
                    self._commit(block)
            except BaseException as e:  # noqa: BLE001 — crosses threads
                # keep draining (and releasing permits) so the producer
                # never wedges on the semaphore; the first error wins
                with self._mu:
                    if self._err is None:
                        self._err = e
            finally:
                self._sem.release()

    def _check_err(self) -> None:
        with self._mu:
            err = self._err
        if err is not None:
            raise err

    # -- lifecycle -----------------------------------------------------------

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        try:
            if self._err is None:
                self.flush()
        finally:
            # ALWAYS stop the committer — error paths included, or the
            # daemon thread (and its in-flight permits) leak per writer
            if self._committer is not None:
                self._q.put(_SENTINEL)
                self._committer.join()
        with self._mu:
            err, self._err = self._err, None
        if err is not None:
            raise err
        self.data_end_coffset = self._coffset
        # end sentinel: payload positions at exactly end-of-data resolve
        # to the normalized (next-block) virtual offset, matching the
        # serial writer's tell_voffset at a block boundary
        self._block_starts.append(self._submitted)
        with self._mu:
            self._block_coffs.append(self._coffset)
        if self._write_eof:
            with METRICS.span("write.commit_wall"):
                self._sink.write(bgzf.EOF_BLOCK)
            with self._mu:
                self._coffset += len(bgzf.EOF_BLOCK)
                self.bytes_out += len(bgzf.EOF_BLOCK)
            METRICS.count("write.bytes_out", len(bgzf.EOF_BLOCK))

    @property
    def data_end_voffset(self) -> int:
        """Packed virtual offset just past the last record byte (before
        the EOF terminator); only valid after close."""
        return self.data_end_coffset << 16

    def resolve_voffsets(self, payload_offsets) -> np.ndarray:
        """Map payload-offset tokens to packed virtual offsets.  Only
        valid after ``close()`` — earlier, the compressed offsets of
        in-flight blocks are not yet known."""
        if not self._closed:
            raise PlanError("resolve_voffsets before close: compressed "
                            "block offsets are not final yet")
        u = np.asarray(payload_offsets, dtype=np.int64)
        if not self._block_starts:
            return (u.astype(np.uint64) << np.uint64(16))
        starts = np.asarray(self._block_starts, dtype=np.int64)
        coffs = np.asarray(self._block_coffs, dtype=np.int64)
        i = np.searchsorted(starts, u, side="right") - 1
        i = np.clip(i, 0, starts.size - 1)
        base = coffs[i].astype(np.uint64)
        uoff = (u - starts[i]).astype(np.uint64)
        return (base << np.uint64(16)) | uoff

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
