"""Mesh runtime: the distribution layer the reference delegated to Hadoop.

Hadoop-BAM itself does no networking (SURVEY.md section 2.9) — HDFS places
blocks, YARN schedules tasks, MR shuffles.  The TPU rebuild owns this layer:

- mesh.py         — device mesh construction (data axis; 1D by default)
- pipeline.py     — sharded decode steps (shard_map over the data axis) and
                    the host fetch/inflate -> device unpack pipeline with
                    prefetch overlap
- distributed.py  — multi-host init (jax.distributed), single-planner span
                    broadcast, per-host span assignment

Distributed correctness is tested on a virtual 8-device CPU mesh — the exact
analog of the reference testing InputFormats against local files with no
cluster (SURVEY.md section 4).
"""
from hadoop_bam_tpu.parallel.mesh import make_mesh  # noqa: F401
