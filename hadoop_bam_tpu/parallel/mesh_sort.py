"""Mesh bucketed sort — the MapReduce-shuffle analog on a device mesh.

The reference's CLI ``sort`` keyed records into the MR shuffle and let
Hadoop's distributed external merge do the work (SURVEY.md section 2.9
shuffle row).  This module is that shuffle as XLA collectives:

1. span planning assigns each device a record-balanced slice of the file
   (split/planners.py::plan_bam_spans_balanced);
2. each device extracts sort keys from its records ON DEVICE
   (ops/unpack_bam.py::unpack_fixed_fields over the shard's span tile);
3. keys are range-partitioned into per-device buckets (boundaries from a
   host-side key sample — the planner's job, like split guessing) and
   exchanged with ``lax.all_to_all`` over the data axis;
4. each device sorts its bucket with a multi-key ``lax.sort`` over
   (key_hi, key_lo, global input index) — the index key makes ties
   deterministic, reproducing a stable sort exactly;
5. hosts apply the resulting permutation to the record bytes and write
   bucket 0..n-1 sequentially — byte-identical output to the
   single-process spill-merge sort (utils/sort.py::sort_bam).

Device memory bound: one span tile + two [n_dev, records_cap] u32 bucket
matrices per device.  Host memory bound: the inflated input (spans stay
resident so the permutation can gather record bytes); for inputs larger
than host RAM use utils/sort.py, whose spill-merge bound is independent
of file size.  Single-host only for now: every span is decoded on the
calling host, so a multi-host mesh is rejected — sharding the decode per
host the way the stats drivers do (parallel/distributed.py) is the
extension point.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.bam import SAMHeader

_I32_SENTINEL = np.int32(2**31 - 1)


def _round_up(x: int, m: int) -> int:
    return ((max(x, 1) + m - 1) // m) * m


def _keys_of(data: np.ndarray, offs: np.ndarray) -> Tuple[np.ndarray,
                                                          np.ndarray]:
    """(hi, lo) uint32 coordinate keys from raw record bytes on host —
    used only for boundary sampling; the sharded step re-derives keys on
    device.  hi = refid (unmapped -> 2^32-1, sorting last, matching
    utils/sort.py::coordinate_key); lo = pos + 1 in uint32 wraparound."""
    base = offs.astype(np.int64)
    refid = (data[base[:, None] + np.arange(4, 8)]
             .view(np.int32).ravel())
    pos = (data[base[:, None] + np.arange(8, 12)]
           .view(np.int32).ravel())
    hi = np.where(refid < 0, np.uint32(0xFFFFFFFF),
                  refid.astype(np.uint32))
    lo = pos.astype(np.uint32) + np.uint32(1)
    return hi, lo


def _sample_bounds(his: List[np.ndarray], los: List[np.ndarray],
                   n_dev: int, max_sample: int = 1 << 16
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """n_dev - 1 lexicographic (hi, lo) bucket boundaries from a key
    sample: bucket b receives keys in [bound_{b-1}, bound_b)."""
    hi = np.concatenate(his) if his else np.zeros(0, np.uint32)
    lo = np.concatenate(los) if los else np.zeros(0, np.uint32)
    n = hi.size
    if n > max_sample:
        step = n // max_sample
        hi, lo = hi[::step], lo[::step]
        n = hi.size
    order = np.lexsort((lo, hi))
    hi, lo = hi[order], lo[order]
    picks = (np.arange(1, n_dev) * n) // n_dev if n else np.zeros(
        0, np.int64)
    bhi = hi[picks] if n else np.zeros(n_dev - 1, np.uint32)
    blo = lo[picks] if n else np.zeros(n_dev - 1, np.uint32)
    return bhi.astype(np.uint32), blo.astype(np.uint32)


def _make_sort_step(mesh, records_cap: int):
    """shard_map step: tiles -> device keys -> all_to_all bucket exchange
    -> per-device multi-key sort.  Returns per-device sorted global
    indices (sentinel-padded) as a [n_dev, n_dev * records_cap] array."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.ops.unpack_bam import unpack_fixed_fields

    n_dev = int(np.prod(mesh.devices.shape))
    R = records_cap

    def per_device(data, offsets, count, base, bhi, blo):
        data, offsets = data[0], offsets[0]
        count, base = count[0], base[0]
        cols = unpack_fixed_fields(data, offsets)
        valid = jnp.arange(R, dtype=jnp.int32) < count
        refid, pos = cols["refid"], cols["pos"]
        hi = jnp.where(refid < 0, jnp.uint32(0xFFFFFFFF),
                       refid.astype(jnp.uint32))
        lo = pos.astype(jnp.uint32) + jnp.uint32(1)
        hi = jnp.where(valid, hi, jnp.uint32(0xFFFFFFFF))
        lo = jnp.where(valid, lo, jnp.uint32(0xFFFFFFFF))
        gidx = jnp.where(valid, base + jnp.arange(R, dtype=jnp.int32),
                         _I32_SENTINEL)

        # lexicographic bucket id: how many boundaries are <= key
        ge = ((hi[:, None] > bhi[None, :])
              | ((hi[:, None] == bhi[None, :])
                 & (lo[:, None] >= blo[None, :])))
        bucket = jnp.sum(ge.astype(jnp.int32), axis=1)      # [R] 0..n_dev-1

        # pack per-destination rows: stable order within each bucket
        perm = jnp.argsort(bucket, stable=True)
        sb = bucket[perm]
        rank = jnp.arange(R, dtype=jnp.int32) - jnp.searchsorted(
            sb, sb, side="left").astype(jnp.int32)
        send_hi = jnp.full((n_dev, R), 0xFFFFFFFF, jnp.uint32
                           ).at[sb, rank].set(hi[perm])
        send_lo = jnp.full((n_dev, R), 0xFFFFFFFF, jnp.uint32
                           ).at[sb, rank].set(lo[perm])
        send_ix = jnp.full((n_dev, R), _I32_SENTINEL, jnp.int32
                           ).at[sb, rank].set(gidx[perm])

        # the shuffle: row b of each device goes to device b
        recv_hi = jax.lax.all_to_all(send_hi, "data", 0, 0, tiled=True)
        recv_lo = jax.lax.all_to_all(send_lo, "data", 0, 0, tiled=True)
        recv_ix = jax.lax.all_to_all(send_ix, "data", 0, 0, tiled=True)

        # bucket-local sort; the global-index key makes ties deterministic
        _, _, six = jax.lax.sort(
            (recv_hi.ravel(), recv_lo.ravel(), recv_ix.ravel()),
            num_keys=3)
        return six[None]

    return jax.jit(jax.shard_map(
        per_device, mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data"), P(), P()),
        out_specs=P("data"), check_vma=False))


def sort_bam_mesh(input_path: str, output_path: str, *,
                  mesh=None, config: HBamConfig = DEFAULT_CONFIG,
                  header: Optional[SAMHeader] = None) -> int:
    """Coordinate-sort a BAM over the mesh; byte-identical to
    utils/sort.py::sort_bam(by_name=False).  Returns the record count.

    Queryname sort keys are variable-length byte strings with no fixed-
    width device representation; use sort_bam for those.
    """
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.parallel.pipeline import _decode_span_core
    from hadoop_bam_tpu.split.planners import plan_bam_spans_balanced
    from hadoop_bam_tpu.utils.sort import _sorted_header

    if jax.process_count() > 1:
        raise NotImplementedError(
            "sort_bam_mesh decodes every span on the calling host; "
            "multi-host meshes are not supported yet — run per host or "
            "use utils.sort.sort_bam")
    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if header is None:
        header, _ = read_bam_header(input_path)

    spans = plan_bam_spans_balanced(input_path, n_dev, header=header)
    raw: List[Tuple[np.ndarray, np.ndarray]] = []   # (data, offsets)
    his: List[np.ndarray] = []
    los: List[np.ndarray] = []
    for s in spans:
        data, offs, _voffs, _ = _decode_span_core(input_path, s, False,
                                                  "auto")
        if data.size > 2**31 - 64:
            raise ValueError(
                f"span inflates to {data.size} bytes — offsets exceed "
                f"the device int32 tile layout; use utils.sort.sort_bam "
                f"for inputs this large")
        raw.append((data, offs.astype(np.int32)))
        h, l = _keys_of(data, offs)
        his.append(h)
        los.append(l)
    counts = [o.size for _, o in raw]
    total = int(sum(counts))
    base = np.zeros(n_dev, dtype=np.int32)
    if counts:
        base[1:len(counts)] = np.cumsum(counts[:-1])

    bytes_cap = _round_up(max((d.size for d, _ in raw), default=1), 256)
    records_cap = _round_up(max(counts, default=1), 8)
    datas = np.zeros((n_dev, bytes_cap), np.uint8)
    offsets = np.zeros((n_dev, records_cap), np.int32)
    cvec = np.zeros(n_dev, np.int32)
    for d, (dat, off) in enumerate(raw):
        datas[d, :dat.size] = dat
        offsets[d, :off.size] = off
        cvec[d] = off.size
    bhi, blo = _sample_bounds(his, los, n_dev)

    step = _make_sort_step(mesh, records_cap)
    sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    six = step(jax.device_put(datas, sharding),
               jax.device_put(offsets, sharding),
               jax.device_put(cvec, sharding),
               jax.device_put(base, sharding),
               jax.device_put(bhi, rep), jax.device_put(blo, rep))
    six = np.asarray(six)                     # [n_dev, n_dev * records_cap]
    del datas, offsets                        # padded copies; raw suffices

    # apply the permutation: buckets in device order ARE the global order.
    # Vectorized per bucket — per-record Python slicing would dominate the
    # whole sort at scale: gather each record's (source span, offset,
    # length), then assemble one contiguous output buffer with the same
    # repeat/arange scatter the decode paths use, and bulk-append it.
    span_of = np.searchsorted(
        np.cumsum(counts), np.arange(total), side="right")
    out_header = _sorted_header(header, by_name=False)
    written = 0
    with BamWriter(output_path, out_header) as w:
        for d in range(n_dev):
            idxs = six[d]
            idxs = idxs[idxs != _I32_SENTINEL].astype(np.int64)
            if not idxs.size:
                continue
            s_arr = span_of[idxs]
            o_arr = np.empty(idxs.size, np.int64)
            ln_arr = np.empty(idxs.size, np.int64)
            for sp in np.unique(s_arr):
                m = s_arr == sp
                data, offs = raw[sp]
                o = offs[idxs[m] - int(base[sp])].astype(np.int64)
                bs = (data[o[:, None] + np.arange(4)]
                      .view("<i4").ravel().astype(np.int64))
                o_arr[m] = o
                ln_arr[m] = bs + 4
            dst0 = np.cumsum(ln_arr) - ln_arr
            out = np.empty(int(ln_arr.sum()), np.uint8)
            for sp in np.unique(s_arr):
                m = s_arr == sp
                data, _ = raw[sp]
                nb = ln_arr[m]
                f = (np.arange(int(nb.sum()), dtype=np.int64)
                     - np.repeat(np.cumsum(nb) - nb, nb))
                out[np.repeat(dst0[m], nb) + f] = \
                    data[np.repeat(o_arr[m], nb) + f]
            w.write_raw(out.tobytes(), n_records=idxs.size)
            written += idxs.size
    if written != total:
        raise RuntimeError(
            f"mesh sort wrote {written} of {total} records — bucket "
            f"exchange lost data (capacity bug); output is invalid")
    return total
