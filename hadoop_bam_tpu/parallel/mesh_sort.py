"""Mesh bucketed sort — the MapReduce-shuffle analog on a device mesh.

The reference's CLI ``sort`` keyed records into the MR shuffle and let
Hadoop's distributed external merge do the work (SURVEY.md section 2.9
shuffle row).  This module is that shuffle as XLA collectives:

1. span planning assigns each device a record-balanced slice of the file
   (split/planners.py::plan_bam_spans_balanced);
2. each device extracts sort keys from its records ON DEVICE
   (ops/unpack_bam.py::unpack_fixed_fields over the shard's span tile);
3. keys are range-partitioned into per-device buckets (boundaries from a
   host-side key sample — the planner's job, like split guessing) and
   exchanged with ``lax.all_to_all`` over the data axis;
4. each device sorts its bucket with a multi-key ``lax.sort`` over
   (key_hi, key_lo, global input index) — the index key makes ties
   deterministic, reproducing a stable sort exactly;
5. hosts apply the resulting permutation to the record bytes and write
   bucket 0..n-1 sequentially — byte-identical output to the
   single-process spill-merge sort (utils/sort.py::sort_bam).

Two exchange modes:

``exchange="index"`` (default single-host): only keys + global indices
ride the all_to_all; hosts keep every decoded span resident and apply
the permutation by gathering record bytes locally.  Cheapest on one
host, impossible on many (a bucket's bytes may live on another host).

``exchange="bytes"`` (default multi-host): the record BYTES themselves
ride the all_to_all as fixed-stride rows — the literal MR shuffle.
Each process decodes only the spans owned by its local devices
(broadcast_plan/assign-by-device, parallel/distributed.py), devices
exchange (key, index, row) tuples, sort their bucket, and each host
writes only its devices' buckets as headerless shards which host 0
concatenates via utils/mergers.py — byte-identical to sort_bam.
Requires the input path to be readable from every host (the HDFS
analog) and the shard/output directory to be shared.

Device memory bound, index mode: one span tile + two [n_dev,
records_cap] u32 bucket matrices per device.  Bytes mode: two
[n_dev, records_cap, stride] u8 row matrices per device (send + recv)
— the shuffle's traffic, resident on device instead of host.  Host
memory bound, index mode: the inflated input; bytes mode: only the
process's own spans.

``round_records`` engages the MULTI-ROUND spill exchange (the MR
shuffle's spill-to-disk, _sort_bam_mesh_bytes_spill): the plan is cut
into ~round_records-record spans, each round ships one span per device
through the same all_to_all step, bucket-sorted rows spill to framed
run files, and a final per-bucket k-way merge reconstructs the exact
single-round order — device memory is then bounded by the ROUND tile,
not the file.  The int32 global-index layout still caps the total at
2^31-2 records (~a 150+ GB BAM); beyond that the sort fails over
cleanly to utils/sort.py with a clear error.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.utils.errors import PlanError

_I32_SENTINEL = np.int32(2**31 - 1)
GLOBAL_INDEX_CEILING = 2**31 - 2     # int32 global record indices


def check_global_index_ceiling(n_records: int, where: str) -> None:
    """Raise ``PlanError`` when a record count cannot fit the mesh sort's
    int32 global-index layout.  PlanError (never a bare ValueError): a
    too-large input is a configuration fault — the retry policy must
    neither re-attempt it nor quarantine it, and the message has to tell
    the operator what to do instead of letting indices silently wrap."""
    if n_records > GLOBAL_INDEX_CEILING:
        raise PlanError(
            f"{where}: {n_records} records exceed the mesh sort's int32 "
            f"global-index ceiling ({GLOBAL_INDEX_CEILING}). The spill "
            f"exchange (`--run-records N` / round_records=N) bounds "
            f"device memory but shares the same global index — sort the "
            f"input as <2^31-record chunks (each through the spill-mode "
            f"mesh sort), then merge the sorted chunks with "
            f"utils/mergers.py or utils.sort.sort_bam, or run "
            f"utils.sort.sort_bam directly.")


def _round_up(x: int, m: int) -> int:
    return ((max(x, 1) + m - 1) // m) * m


def _keys_of(data: np.ndarray, offs: np.ndarray) -> Tuple[np.ndarray,
                                                          np.ndarray]:
    """(hi, lo) uint32 coordinate keys from raw record bytes on host —
    used only for boundary sampling; the sharded step re-derives keys on
    device.  hi = refid (unmapped -> 2^32-1, sorting last, matching
    utils/sort.py::coordinate_key); lo = pos + 1 in uint32 wraparound."""
    base = offs.astype(np.int64)
    refid = (data[base[:, None] + np.arange(4, 8)]
             .view(np.int32).ravel())
    pos = (data[base[:, None] + np.arange(8, 12)]
           .view(np.int32).ravel())
    hi = np.where(refid < 0, np.uint32(0xFFFFFFFF),
                  refid.astype(np.uint32))
    lo = pos.astype(np.uint32) + np.uint32(1)
    return hi, lo


def _sample_bounds(his: List[np.ndarray], los: List[np.ndarray],
                   n_dev: int, max_sample: int = 1 << 16
                   ) -> Tuple[np.ndarray, np.ndarray]:
    """n_dev - 1 lexicographic (hi, lo) bucket boundaries from a key
    sample: bucket b receives keys in [bound_{b-1}, bound_b)."""
    hi = np.concatenate(his) if his else np.zeros(0, np.uint32)
    lo = np.concatenate(los) if los else np.zeros(0, np.uint32)
    n = hi.size
    if n > max_sample:
        step = n // max_sample
        hi, lo = hi[::step], lo[::step]
        n = hi.size
    order = np.lexsort((lo, hi))
    hi, lo = hi[order], lo[order]
    picks = (np.arange(1, n_dev) * n) // n_dev if n else np.zeros(
        0, np.int64)
    bhi = hi[picks] if n else np.zeros(n_dev - 1, np.uint32)
    blo = lo[picks] if n else np.zeros(n_dev - 1, np.uint32)
    return bhi.astype(np.uint32), blo.astype(np.uint32)


def _device_keys(refid, pos, valid, base, R):
    """(hi, lo, gidx) device sort keys — the single definition of the
    coordinate-key convention (unmapped refid<0 sorts last; pos+1 in
    uint32 wraparound, matching utils/sort.py::coordinate_key), shared
    by both exchange modes so they cannot drift apart."""
    import jax.numpy as jnp

    hi = jnp.where(refid < 0, jnp.uint32(0xFFFFFFFF),
                   refid.astype(jnp.uint32))
    lo = pos.astype(jnp.uint32) + jnp.uint32(1)
    hi = jnp.where(valid, hi, jnp.uint32(0xFFFFFFFF))
    lo = jnp.where(valid, lo, jnp.uint32(0xFFFFFFFF))
    gidx = jnp.where(valid, base + jnp.arange(R, dtype=jnp.int32),
                     _I32_SENTINEL)
    return hi, lo, gidx


def _bucket_pack(hi, lo, bhi, blo, R):
    """Range-partition bucket ids (how many boundaries <= key) plus the
    stable within-bucket scatter coordinates (perm, dest bucket, rank)
    for the per-destination send matrices."""
    import jax.numpy as jnp

    ge = ((hi[:, None] > bhi[None, :])
          | ((hi[:, None] == bhi[None, :])
             & (lo[:, None] >= blo[None, :])))
    bucket = jnp.sum(ge.astype(jnp.int32), axis=1)          # [R] 0..n_dev-1
    perm = jnp.argsort(bucket, stable=True)
    sb = bucket[perm]
    rank = jnp.arange(R, dtype=jnp.int32) - jnp.searchsorted(
        sb, sb, side="left").astype(jnp.int32)
    return perm, sb, rank


def _send_matrices(hi, lo, gidx, perm, sb, rank, n_dev, R):
    """Per-destination [n_dev, R] send matrices for the key triple —
    sentinel-filled so unreceived cells sort last and drop at write
    time.  Shared by both exchange modes (drift here would break their
    byte-identity contract)."""
    import jax.numpy as jnp

    send_hi = jnp.full((n_dev, R), 0xFFFFFFFF, jnp.uint32
                       ).at[sb, rank].set(hi[perm])
    send_lo = jnp.full((n_dev, R), 0xFFFFFFFF, jnp.uint32
                       ).at[sb, rank].set(lo[perm])
    send_ix = jnp.full((n_dev, R), _I32_SENTINEL, jnp.int32
                       ).at[sb, rank].set(gidx[perm])
    return send_hi, send_lo, send_ix


def _make_sort_step(mesh, records_cap: int):
    """shard_map step: tiles -> device keys -> all_to_all bucket exchange
    -> per-device multi-key sort.  Returns per-device sorted global
    indices (sentinel-padded) as a [n_dev, n_dev * records_cap] array."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map

    from hadoop_bam_tpu.ops.unpack_bam import unpack_fixed_fields

    n_dev = int(np.prod(mesh.devices.shape))
    R = records_cap

    def per_device(data, offsets, count, base, bhi, blo):
        data, offsets = data[0], offsets[0]
        count, base = count[0], base[0]
        cols = unpack_fixed_fields(data, offsets)
        valid = jnp.arange(R, dtype=jnp.int32) < count
        hi, lo, gidx = _device_keys(cols["refid"], cols["pos"], valid,
                                    base, R)
        perm, sb, rank = _bucket_pack(hi, lo, bhi, blo, R)
        send_hi, send_lo, send_ix = _send_matrices(hi, lo, gidx, perm,
                                                   sb, rank, n_dev, R)

        # the shuffle: row b of each device goes to device b
        recv_hi = jax.lax.all_to_all(send_hi, "data", 0, 0, tiled=True)
        recv_lo = jax.lax.all_to_all(send_lo, "data", 0, 0, tiled=True)
        recv_ix = jax.lax.all_to_all(send_ix, "data", 0, 0, tiled=True)

        # bucket-local sort; the global-index key makes ties deterministic
        _, _, six = jax.lax.sort(
            (recv_hi.ravel(), recv_lo.ravel(), recv_ix.ravel()),
            num_keys=3)
        return six[None]

    return jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data"), P(), P()),
        out_specs=P("data"), check_vma=False))


def _record_lens(data: np.ndarray, offs: np.ndarray) -> np.ndarray:
    """Per-record total byte lengths (block_size field + its own 4)."""
    base = offs.astype(np.int64)
    return (data[base[:, None] + np.arange(4)].view("<i4").ravel()
            .astype(np.int64) + 4)


def _pack_record_rows(data: np.ndarray, offs: np.ndarray, bs: np.ndarray,
                      records_cap: int, stride: int
                      ) -> Tuple[np.ndarray, np.ndarray]:
    """Zero-padded [records_cap, stride] u8 row tile + per-row lengths
    from walked record offsets + precomputed lengths — the fixed-shape
    unit the byte exchange ships through all_to_all."""
    rows = np.zeros((records_cap, stride), np.uint8)
    lens = np.zeros(records_cap, np.int32)
    n = offs.size
    if not n:
        return rows, lens
    if int(bs.max()) > stride:
        raise ValueError(f"record of {int(bs.max())} bytes exceeds the "
                         f"agreed row stride {stride}")
    lens[:n] = bs
    base = offs.astype(np.int64)
    f = (np.arange(int(bs.sum()), dtype=np.int64)
         - np.repeat(np.cumsum(bs) - bs, bs))
    rows[np.repeat(np.arange(n), bs), f] = data[np.repeat(base, bs) + f]
    return rows, lens


def _make_bytes_sort_step(mesh, records_cap: int, stride: int):
    """shard_map step for the byte exchange: rows -> device keys ->
    all_to_all of (key, index, length, row bytes) -> per-device bucket
    sort -> bucket-sorted rows.  Unlike the index step, the permutation
    is applied ON DEVICE (take along the row axis), so hosts never need
    remote spans."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from hadoop_bam_tpu.parallel.mesh import shard_map

    n_dev = int(np.prod(mesh.devices.shape))
    R = records_cap
    N = n_dev * R

    def le_i32(rows, col):
        b = rows[:, col:col + 4].astype(jnp.uint32)
        v = (b[:, 0] | (b[:, 1] << 8) | (b[:, 2] << 16) | (b[:, 3] << 24))
        return jax.lax.bitcast_convert_type(v, jnp.int32)

    def per_device(rows, lens, count, base, bhi, blo):
        rows, lens = rows[0], lens[0]
        count, base = count[0], base[0]
        refid = le_i32(rows, 4)          # BAM fixed fields live at the
        pos = le_i32(rows, 8)            # row head: refID @4, pos @8
        valid = jnp.arange(R, dtype=jnp.int32) < count
        hi, lo, gidx = _device_keys(refid, pos, valid, base, R)
        # capacity is structural (a source holds at most R records, so
        # no (src, dst) send cell can overflow)
        perm, sb, rank = _bucket_pack(hi, lo, bhi, blo, R)
        send_hi, send_lo, send_ix = _send_matrices(hi, lo, gidx, perm,
                                                   sb, rank, n_dev, R)
        send_ln = jnp.zeros((n_dev, R), jnp.int32
                            ).at[sb, rank].set(lens[perm])
        send_rows = jnp.zeros((n_dev, R, stride), jnp.uint8
                              ).at[sb, rank].set(rows[perm])

        recv_hi = jax.lax.all_to_all(send_hi, "data", 0, 0,
                                     tiled=True).ravel()
        recv_lo = jax.lax.all_to_all(send_lo, "data", 0, 0,
                                     tiled=True).ravel()
        recv_ix = jax.lax.all_to_all(send_ix, "data", 0, 0,
                                     tiled=True).ravel()
        recv_ln = jax.lax.all_to_all(send_ln, "data", 0, 0,
                                     tiled=True).ravel()
        recv_rows = jax.lax.all_to_all(send_rows, "data", 0, 0,
                                       tiled=True).reshape(N, stride)

        iota = jnp.arange(N, dtype=jnp.int32)
        _, _, six, order = jax.lax.sort(
            (recv_hi, recv_lo, recv_ix, iota), num_keys=3)
        sorted_rows = jnp.take(recv_rows, order, axis=0)
        sorted_ln = jnp.take(recv_ln, order)
        return sorted_rows[None], sorted_ln[None], six[None]

    return jax.jit(shard_map(
        per_device, mesh=mesh,
        in_specs=(P("data"), P("data"), P("data"), P("data"), P(), P()),
        out_specs=(P("data"), P("data"), P("data")), check_vma=False))


def _buckets(garr) -> dict:
    """Per-device bucket arrays of a sharded step output, keyed by device
    position — the one shard-extraction helper for both exchange
    flavors.  A 1-device mesh yields slice(None) indices: start is 0."""
    return {(sh.index[0].start or 0): np.asarray(sh.data)[0]
            for sh in garr.addressable_shards}


def _agree_round_geometry(counts_vec: np.ndarray, max_len: int,
                          his: List[np.ndarray], los: List[np.ndarray],
                          *, err: Optional[BaseException] = None,
                          want_sample: bool = True,
                          sample_cap: int = 4096,
                          timeout_s: Optional[float] = None):
    """Multi-host agreement on (counts, max record length[, key sample])
    with a decode-failure flag — the ONE collective protocol shared by
    the single-round bytes exchange and every round of the spill
    exchange, so the two paths cannot drift.  A raise on one host
    before the collective would strand the others in it, so a local
    ``err`` ships as a flag and re-raises only after every process has
    reached the allgather.  Single-process calls are a local
    passthrough.  Returns (counts_vec, max_len, shis, slos); the sample
    lists are None when ``want_sample`` is False (``want_sample`` must
    agree across processes — it changes the collective sequence)."""
    import jax

    if jax.process_count() == 1:
        # pure local passthrough, UNSAMPLED: _sample_bounds applies its
        # own (larger) cap, so pre-truncating here would silently
        # coarsen single-host bucket boundaries
        if err is not None:
            raise err
        return (counts_vec, max_len,
                list(his) if want_sample else None,
                list(los) if want_sample else None)

    hi_s = np.concatenate(his) if his else np.zeros(0, np.uint32)
    lo_s = np.concatenate(los) if los else np.zeros(0, np.uint32)
    if hi_s.size > sample_cap:
        step_ = -(-hi_s.size // sample_cap)
        hi_s, lo_s = hi_s[::step_], lo_s[::step_]

    from hadoop_bam_tpu.parallel.distributed import guarded_allgather

    n_proc = jax.process_count()
    n_dev = counts_vec.size
    meta = np.zeros(n_dev + 3, np.int64)
    meta[:n_dev] = counts_vec
    meta[n_dev] = max_len
    meta[n_dev + 1] = hi_s.size
    meta[n_dev + 2] = 0 if err is None else 1
    g_meta = guarded_allgather(meta, "mesh sort: round geometry",
                               timeout_s=timeout_s)
    if err is not None:
        raise err
    if int(g_meta[:, n_dev + 2].max()) > 0:
        raise RuntimeError("mesh sort: decode failed on another host")
    counts_out = g_meta[:, :n_dev].sum(axis=0)
    max_out = int(g_meta[:, n_dev].max())
    shis = slos = None
    if want_sample:
        sample = np.full((sample_cap, 2), 0xFFFFFFFF, np.uint32)
        sample[:hi_s.size, 0] = hi_s
        sample[:hi_s.size, 1] = lo_s
        g_sample = guarded_allgather(sample, "mesh sort: key sample",
                                     timeout_s=timeout_s)
        shis = [g_sample[p, :int(g_meta[p, n_dev + 1]), 0]
                .astype(np.uint32) for p in range(n_proc)]
        slos = [g_sample[p, :int(g_meta[p, n_dev + 1]), 1]
                .astype(np.uint32) for p in range(n_proc)]
    return counts_out, max_out, shis, slos


def _frame_run(rows: np.ndarray, lens: np.ndarray, six: np.ndarray,
               hi: np.ndarray, lo: np.ndarray) -> bytes:
    """Serialize one bucket-round's sorted records as framed bytes:
    per record <u32 hi><u32 lo><i32 gidx><i32 len><len payload bytes>.
    The frame carries the full sort key so the cross-round merge never
    re-derives anything from payload bytes."""
    k = int(lens.size)
    if not k:
        return b""
    hdr = np.empty((k, 16), np.uint8)
    hdr[:, 0:4] = hi.astype("<u4")[:, None].view(np.uint8)
    hdr[:, 4:8] = lo.astype("<u4")[:, None].view(np.uint8)
    hdr[:, 8:12] = six.astype("<i4")[:, None].view(np.uint8)
    hdr[:, 12:16] = lens.astype("<i4")[:, None].view(np.uint8)
    lens64 = lens.astype(np.int64)
    total = int(lens64.sum()) + 16 * k
    out = np.empty(total, np.uint8)
    # frame start offsets
    starts = np.cumsum(lens64 + 16) - (lens64 + 16)
    out[(starts[:, None] + np.arange(16)).ravel()] = hdr.ravel()
    body = _ragged_positions(starts + 16, lens64)
    src = _ragged_positions(np.zeros(k, np.int64) + np.arange(k)
                            * rows.shape[1], lens64)
    out[body] = rows.ravel()[src]
    return out.tobytes()


def _ragged_positions(starts: np.ndarray, lens: np.ndarray) -> np.ndarray:
    total = int(lens.sum())
    if not total:
        return np.empty(0, np.int64)
    firsts = np.cumsum(lens) - lens
    flat = np.arange(total, dtype=np.int64) - np.repeat(firsts, lens)
    return np.repeat(starts, lens) + flat


def _iter_run_frames(path: str):
    """Yield ((hi, lo, gidx), payload) frames of one spilled run file."""
    with open(path, "rb") as f:
        buf = f.read()
    pos = 0
    n = len(buf)
    while pos < n:
        hi = int.from_bytes(buf[pos:pos + 4], "little")
        lo = int.from_bytes(buf[pos + 4:pos + 8], "little")
        gidx = int.from_bytes(buf[pos + 8:pos + 12], "little", signed=True)
        ln = int.from_bytes(buf[pos + 12:pos + 16], "little", signed=True)
        pos += 16
        yield (hi, lo, gidx), buf[pos:pos + ln]
        pos += ln


def _merge_bucket_runs(run_paths: List[str]
                       ) -> Tuple[bytes, np.ndarray]:
    """k-way merge of one bucket's per-round sorted runs by the framed
    (hi, lo, gidx) key — the external-merge half of the MR shuffle,
    running on the shared ``split/kmerge.py`` heap core (ties break in
    run order, exactly ``heapq.merge``'s stability, so the extraction
    is byte-identical — re-pinned by tests/test_kmerge.py).
    Returns (concatenated record bytes, per-record lengths) so writers
    can recover record boundaries for index-during-write."""
    from hadoop_bam_tpu.split.kmerge import kmerge

    chunks: List[bytes] = []
    lens: List[int] = []
    for _key, payload in kmerge(
            (_iter_run_frames(p) for p in run_paths),
            key=lambda kv: kv[0]):
        chunks.append(payload)
        lens.append(len(payload))
    return b"".join(chunks), np.asarray(lens, dtype=np.int64)


def _sort_bam_mesh_bytes_spill(input_path: str, output_path: str, *, mesh,
                               config: HBamConfig,
                               header: Optional[SAMHeader],
                               round_records: int,
                               journal_path: Optional[str] = None) -> int:
    """Spill-exchange entry: runs the rounds and removes the
    ``.mesh-spill`` run directory afterwards — success or failure — so
    an exception mid-round/mid-merge cannot strand spilled runs that
    approach the input's size (ADVICE r5).  ``config.debug_keep_spill``
    preserves the directory for post-mortem.

    Under a JOURNAL the failure branch keeps the directory: the spilled
    runs of completed rounds are exactly the artifacts ``hbam resume``
    verifies and reuses — deleting them on an exception would turn
    every recoverable fault into a from-zero re-run (a SIGKILL never
    reaches this finally either way; this aligns the exception path
    with the crash path).  Success still cleans up: once ``job_done``
    is journaled, the runs have served their purpose.

    Multi-host note: removal happens on host 0 only, and every raise
    inside the impl is preceded by the round/merge error-flag
    allgathers, so by the time any host unwinds into this finally all
    hosts have stopped writing — host 0's rmtree cannot race a writer.
    """
    import shutil

    import jax

    ok = False
    try:
        n = _sort_bam_mesh_bytes_spill_impl(
            input_path, output_path, mesh=mesh, config=config,
            header=header, round_records=round_records,
            journal_path=journal_path)
        ok = True
        return n
    finally:
        keep = bool(getattr(config, "debug_keep_spill", False)) \
            or (journal_path is not None and not ok)
        if not keep and jax.process_index() == 0:
            shutil.rmtree(output_path + ".mesh-spill", ignore_errors=True)


def _sort_bam_mesh_bytes_spill_impl(input_path: str, output_path: str, *,
                                    mesh, config: HBamConfig,
                                    header: Optional[SAMHeader],
                                    round_records: int,
                                    journal_path: Optional[str] = None
                                    ) -> int:
    """Multi-round byte exchange (VERDICT r4 #6): device memory bounded
    by the ROUND tile, not the file.

    The plan is cut so each span holds ~``round_records`` records; round
    t ships spans [t*n_dev, (t+1)*n_dev) through the same all_to_all
    bucket step as the single-round path, each host appends its devices'
    bucket-sorted rows to per-(bucket, round) spill runs, and a final
    per-bucket k-way merge of the framed runs (sorted by the full
    (hi, lo, gidx) key) reconstructs exactly the single-round order —
    byte-identical to sort_bam.

    Bucket boundaries are sampled from ROUND 0's keys only (they affect
    balance, never order); a key-skewed first round costs balance, not
    correctness.  HBM per device: two [n_dev, R, stride] tiles with
    R ≈ round_records; host per merge: one bucket's frames.

    With a ``journal_path`` the run is CRASH-SAFE (jobs/journal.py):
    the journal records the job identity (input file identity + the
    output-affecting config fingerprint + a digest of the span plan),
    the round-0 bucket boundaries, and — per completed round — the
    spilled run files with size+CRC.  A resumed run verifies every
    recorded artifact, reuses the journaled boundaries (they were
    sampled from round 0, which may no longer be decoded), skips the
    completed rounds entirely (``jobs.rounds_skipped`` /
    ``jobs.spans_skipped``), sweeps the partial spill files of the
    in-flight round, and re-runs only the remainder — byte-identical
    output, strictly fewer spans decoded.  ``job_done`` records the
    published output's size+CRC so re-running a finished job is a
    verified no-op."""
    import os
    import shutil

    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam_header
    from hadoop_bam_tpu.parallel.distributed import (
        broadcast_plan, collective_timeout, guarded_allgather,
    )
    from hadoop_bam_tpu.parallel.pipeline import _decode_span_core
    from hadoop_bam_tpu.split.planners import plan_bam_spans_balanced
    from hadoop_bam_tpu.utils.metrics import METRICS
    from hadoop_bam_tpu.utils.sort import _sorted_header

    mesh_devs = list(mesh.devices.ravel())
    n_dev = len(mesh_devs)
    pid = jax.process_index()
    n_proc = jax.process_count()
    coll_timeout = collective_timeout(config)
    if header is None:
        header, _ = read_bam_header(input_path)

    jr = None
    resume = None
    if journal_path is not None:
        if n_proc > 1:
            raise PlanError(
                "mesh sort journaling is single-process for now: each "
                "host would need its own journal and a resume barrier "
                "protocol; run without journal_path under "
                "jax.distributed")
        from hadoop_bam_tpu.jobs import journal as jj
        from hadoop_bam_tpu.jobs.runner import (
            SORT_FINGERPRINT_FIELDS, sort_job_params,
        )
        jr, resume = jj.JobJournal.resume(
            journal_path, kind="mesh_sort_spill",
            inputs=[(os.path.abspath(input_path),
                     jj.file_identity_digest(input_path))],
            output=os.path.abspath(output_path),
            fingerprint=jj.config_fingerprint(config,
                                              SORT_FINGERPRINT_FIELDS),
            config_values=jj.fingerprint_values(config,
                                                SORT_FINGERPRINT_FIELDS),
            params=sort_job_params(input_path, output_path,
                                   exchange="bytes",
                                   round_records=int(round_records),
                                   n_dev=n_dev),
            fsync=bool(getattr(config, "journal_fsync", True)))
        if resume is not None and resume.done is not None:
            d = resume.done
            if jj.verify_artifact(output_path, d.get("size", -1),
                                  d.get("crc", "")):
                # committed job: re-running it is a verified no-op
                METRICS.count("jobs.jobs_skipped")
                jr.close()
                return int(d.get("records", 0))
            # output vanished/changed after job_done: fall through and
            # rebuild it from whatever units still verify

    def plan():
        from hadoop_bam_tpu.split.splitting_index import (
            SplittingIndex, build_splitting_index,
        )
        index = SplittingIndex.load_for(input_path)
        # a sidecar coarser than ~round_records/8 cannot cut spans small
        # enough to honor the round memory bound (num_spans is capped at
        # the sample count) — rebuild fine enough for ~8 samples/span
        fine = max(1, round_records // 8)
        if index is None or (index.granularity or 1) > fine:
            index = build_splitting_index(input_path, granularity=fine)
        # a sidecar index samples one voffset per GRANULARITY records:
        # estimate records from total_records (when stored) or samples x
        # granularity — len(voffsets) alone would undercount ~4096x on a
        # standard .sbi and balloon the round tile past the memory bound
        n_samples = max(1, len(index.voffsets) - 1)
        if index.total_records > 0:
            total_est = index.total_records
            # UP-FRONT ceiling check (VERDICT r5 #8): a stored exact
            # record count lets the overflow surface before any round
            # decodes, not 2^31 records into the run
            check_global_index_ceiling(total_est, "mesh spill sort plan")
        else:
            total_est = n_samples * max(1, index.granularity)
        want = -(-total_est // max(1, round_records))
        want = _round_up(want, n_dev)          # whole rounds of n_dev
        return plan_bam_spans_balanced(input_path, want, header=header,
                                       index=index)

    spans = broadcast_plan(plan() if pid == 0 else None,
                           timeout_s=coll_timeout)
    n_rounds = max(1, -(-len(spans) // n_dev))
    local_pos = [d for d, dev in enumerate(mesh_devs)
                 if dev.process_index == pid]
    local_set = set(local_pos)

    shard_dir = output_path + ".mesh-spill"
    resumed_rounds: dict = {}
    bounds_ev = None
    if jr is not None:
        # the plan digest is part of the resume contract: a changed
        # sidecar/planner state would re-cut spans under the recorded
        # rounds and silently mis-join old runs with new ones
        pd = jj.plan_digest(spans)
        plan_ev = resume.last_event("plan") if resume is not None else None
        if plan_ev is not None and plan_ev.get("digest") != pd:
            raise PlanError(
                f"refusing to resume {journal_path}: the span plan no "
                f"longer matches the journaled run (journal digest "
                f"{plan_ev.get('digest')!r}, now {pd!r}) — the input's "
                f"splitting-index state changed; delete the journal to "
                f"start over")
        if plan_ev is None:
            jr.event("plan", digest=pd, n_spans=len(spans),
                     n_rounds=int(n_rounds))
        if resume is not None:
            bounds_ev = resume.last_event("bounds")
            for t in range(n_rounds):
                u = resume.unit("round", t)
                if u is None:
                    continue
                runs = list(u.get("runs", []))
                if all(jj.verify_artifact(p, s, c) for _b, p, s, c
                       in runs):
                    resumed_rounds[t] = u
            recorded = [p for u in resumed_rounds.values()
                        for _b, p, s, c in u.get("runs", [])]
            # the in-flight round's partial spills (and anything else
            # the journal never committed) are debris, not state
            jj.sweep_unrecorded(shard_dir, recorded,
                                counter="jobs.stale_runs_swept")
            if resumed_rounds and bounds_ev is None:
                raise PlanError(
                    f"refusing to resume {journal_path}: completed "
                    f"rounds are recorded but the round-0 bucket "
                    f"boundaries are not — later rounds re-bucketed "
                    f"under fresh boundaries would break the global "
                    f"order; delete the journal to start over")
            spans_skipped = sum(
                min((t + 1) * n_dev, len(spans)) - t * n_dev
                for t in resumed_rounds)
            if resumed_rounds:
                METRICS.count("jobs.rounds_skipped", len(resumed_rounds))
                METRICS.count("jobs.spans_skipped", spans_skipped)
            jr.event("resume_plan", rounds_total=int(n_rounds),
                     rounds_skipped=len(resumed_rounds),
                     spans_skipped=int(spans_skipped))
    if not resumed_rounds:
        if pid == 0:
            shutil.rmtree(shard_dir, ignore_errors=True)
        if n_proc > 1:
            guarded_allgather(np.zeros(1, np.int32),
                              "mesh spill sort: prepare barrier",
                              timeout_s=coll_timeout)
    os.makedirs(shard_dir, exist_ok=True)

    sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    step_cache = {}
    bhi = blo = None
    prefix_total = 0
    run_files: dict = {}               # bucket -> [run paths]
    err: Optional[BaseException] = None

    # make_array_from_single_device_arrays grew its dtype kwarg after
    # jax 0.4; casting host-side before device_put is version-portable
    def sharded(shape, dtype, of_d):
        return jax.make_array_from_single_device_arrays(
            shape, sharding,
            [jax.device_put(np.asarray(of_d(d), dtype=dtype),
                            mesh_devs[d]) for d in local_pos])

    def replicated(arr, dtype):
        arr = np.asarray(arr, dtype=dtype)
        return jax.make_array_from_single_device_arrays(
            arr.shape, rep,
            [jax.device_put(arr, mesh_devs[d]) for d in local_pos])

    for t in range(n_rounds):
        if t in resumed_rounds:
            # journal-verified round: its sorted runs are already on
            # disk with matching size+CRC — reuse them, decode nothing
            u = resumed_rounds[t]
            for b, p, _s, _c in u.get("runs", []):
                run_files.setdefault(int(b), []).append(p)
            prefix_total += int(u.get("round_total", 0))
            continue
        # --- decode this round's local spans (streaming: only one
        # round's rows are ever resident) ---
        decoded = {}
        counts_vec = np.zeros(n_dev, np.int64)
        max_len = 0
        his: List[np.ndarray] = []
        los: List[np.ndarray] = []
        try:
            for d in local_pos:
                s = t * n_dev + d
                if s >= len(spans):
                    continue
                data, offs, _v, _ = _decode_span_core(
                    input_path, spans[s], False, "auto", want_voffs=False)
                lens_ = _record_lens(data, offs)
                decoded[d] = (data, offs, lens_)
                counts_vec[d] = offs.size
                if offs.size:
                    max_len = max(max_len, int(lens_.max()))
                if t == 0:
                    h, l = _keys_of(data, offs)
                    his.append(h)
                    los.append(l)
        except Exception as e:  # noqa: BLE001 — must reach the collective
            err = e

        # --- agree on round geometry (and boundaries, round 0) ---
        counts_vec, max_len, shis, slos = _agree_round_geometry(
            counts_vec, max_len, his, los, err=err, want_sample=(t == 0),
            timeout_s=coll_timeout)
        err = None     # consumed: the helper raised if any host failed
        if bhi is None:
            if bounds_ev is not None:
                # resumed run: boundaries MUST be the journaled ones —
                # the completed rounds' runs were bucketed under them,
                # and bucket assignment must agree across rounds for
                # the per-bucket merge to reconstruct the global order
                bhi = np.asarray(bounds_ev["bhi"], np.uint32)
                blo = np.asarray(bounds_ev["blo"], np.uint32)
            else:
                bhi, blo = _sample_bounds(shis, slos, n_dev)
                if jr is not None:
                    jr.event("bounds",
                             bhi=[int(x) for x in bhi],
                             blo=[int(x) for x in blo])
            # boundaries are fixed after round 0: ship them once
            bhi_g = replicated(bhi, jnp.uint32)
            blo_g = replicated(blo, jnp.uint32)

        round_total = int(counts_vec.sum())
        check_global_index_ceiling(prefix_total + round_total,
                                   "mesh spill sort (mid-run backstop)")
        base_vec = prefix_total + np.concatenate(
            [[0], np.cumsum(counts_vec[:-1])])
        prefix_total += round_total

        records_cap = _round_up(max(int(counts_vec.max()), 1), 1024)
        stride = 1 << max(6, int(max(max_len, 36) - 1).bit_length())
        key = (records_cap, stride)
        if key not in step_cache:
            step_cache[key] = _make_bytes_sort_step(mesh, records_cap,
                                                    stride)
        step = step_cache[key]

        _empty = (np.zeros(0, np.uint8), np.zeros(0, np.int64),
                  np.zeros(0, np.int64))
        packed = {}
        for d in local_pos:
            data, offs, lens_ = decoded.pop(d, _empty)
            packed[d] = _pack_record_rows(data, offs, lens_, records_cap,
                                          stride)
        del decoded

        rows_g = sharded((n_dev, records_cap, stride), jnp.uint8,
                         lambda d: packed[d][0][None])
        lens_g = sharded((n_dev, records_cap), jnp.int32,
                         lambda d: packed[d][1][None])
        count_g = sharded((n_dev,), jnp.int32,
                          lambda d: np.asarray([counts_vec[d]], np.int32))
        base_g = sharded((n_dev,), jnp.int32,
                         lambda d: np.asarray([base_vec[d]], np.int32))
        rows_s, lens_s, six_s = step(rows_g, lens_g, count_g, base_g,
                                     bhi_g, blo_g)

        # --- spill this round's local buckets as framed sorted runs ---
        b_rows, b_lens, b_six = (_buckets(rows_s), _buckets(lens_s),
                                 _buckets(six_s))
        round_runs: List[Tuple[int, str]] = []
        try:
            for b in sorted(b_rows):
                keep = b_six[b] != _I32_SENTINEL
                if not bool(keep.any()):
                    continue
                rows_k = b_rows[b][keep]
                lens_k = b_lens[b][keep]
                six_k = b_six[b][keep]
                # the ONE key-convention definition (_keys_of) — packed
                # rows are fixed-stride records, so row starts are the
                # offsets
                hi_k, lo_k = _keys_of(
                    np.ascontiguousarray(rows_k).ravel(),
                    np.arange(rows_k.shape[0], dtype=np.int64)
                    * rows_k.shape[1])
                path = os.path.join(shard_dir, f"b{b:05d}-r{t:05d}.run")
                with open(path, "wb") as f:
                    f.write(_frame_run(rows_k, lens_k, six_k, hi_k, lo_k))
                run_files.setdefault(b, []).append(path)
                round_runs.append((b, path))
        except Exception as e:  # noqa: BLE001 — flagged below
            err = e
        if n_proc > 1:
            ok = np.asarray([0 if err is not None else 1], np.int32)
            g_ok = guarded_allgather(ok, "mesh spill sort: round flag",
                                     timeout_s=coll_timeout)
            if err is not None:
                raise err
            if int(g_ok.min()) == 0:
                raise RuntimeError("mesh spill sort: run write failed on "
                                   "another host")
        elif err is not None:
            raise err
        if jr is not None:
            # the round's COMMIT record: every run file it produced,
            # verified by size+CRC on resume.  Written only after the
            # spills all landed — a crash mid-round leaves the round
            # unrecorded and its partial files get swept on resume
            jr.unit_done(
                "round", t,
                # abspath run files: `hbam resume` may run from a
                # different cwd than the (relative-pathed) killed run
                runs=[[b, os.path.abspath(p), *jj.file_digest(p)]
                      for b, p in round_runs],
                round_total=int(round_total))

    # --- final per-bucket merge ---
    total = prefix_total
    out_header = _sorted_header(header, by_name=False)
    written = 0
    merge_err: Optional[BaseException] = None
    if n_proc == 1:
        from hadoop_bam_tpu.write import write_bam_records

        def bucket_chunks():
            for b in range(n_dev):
                payload, lens = _merge_bucket_runs(run_files.get(b, []))
                if lens.size:
                    yield payload, np.cumsum(lens) - lens
        written = write_bam_records(output_path, out_header,
                                    bucket_chunks(), config=config).records
        # spill-dir removal lives in the caller's finally
        if jr is not None and written == total:
            size, crc = jj.file_digest(output_path)
            jr.job_done(records=int(written), size=size, crc=crc)
            jr.close()
    else:
        from hadoop_bam_tpu.write import (
            ShardedFileWriter, write_bam_shards_concat,
        )
        # parts live inside the existing .mesh-spill run dir (distinct
        # "part-*" names), so the caller's finally removes them with the
        # runs on every failure path
        sw = ShardedFileWriter(output_path, n_dev,
                               dir_suffix=".mesh-spill")
        try:
            for b in sorted(local_pos):
                payload, lens = _merge_bucket_runs(run_files.get(b, []))
                with sw.open_shard(b) as f:
                    with BamWriter(f, out_header, write_header=False,
                                   write_eof=False,
                                   level=config.write_compress_level) as w:
                        w.write_raw(payload, n_records=int(lens.size))
                written += int(lens.size)
        except Exception as e:  # noqa: BLE001 — flagged below
            merge_err = e
        g_written = guarded_allgather(
            np.asarray([written if merge_err is None else -1], np.int64),
            "mesh spill sort: merge counts", timeout_s=coll_timeout)
        if merge_err is not None:
            raise merge_err
        if (g_written < 0).any():
            raise RuntimeError("mesh spill sort: bucket merge failed on "
                               "another host; output is invalid")
        written = int(g_written.sum())
        if written != total:
            raise RuntimeError(
                f"mesh spill sort wrote {written} of {total} records — "
                f"output is invalid")
        final_err = None
        if pid == 0:
            try:
                # spill-dir removal (parts included) lives in the
                # caller's finally, which honors debug_keep_spill
                sw.concatenate(
                    lambda parts: write_bam_shards_concat(
                        parts, output_path, out_header, config=config),
                    what="mesh spill sort", cleanup=False)
            except Exception as e:  # noqa: BLE001 — must reach the barrier
                final_err = e
        ok = np.asarray([0 if final_err is not None else 1], np.int32)
        g_ok = guarded_allgather(ok, "mesh spill sort: publish flag",
                                 timeout_s=coll_timeout)
        if final_err is not None:
            raise final_err
        if int(g_ok.min()) == 0:
            raise RuntimeError("mesh spill sort merge failed on host 0; "
                               "output is invalid")
        return total
    if written != total:
        raise RuntimeError(
            f"mesh spill sort wrote {written} of {total} records — "
            f"output is invalid")
    return total


def _sort_bam_mesh_bytes(input_path: str, output_path: str, *, mesh,
                         config: HBamConfig,
                         header: Optional[SAMHeader]) -> int:
    """Byte-exchange mesh sort: works multi-host.  Each process decodes
    only its devices' spans; record bytes ride the all_to_all; each host
    writes its buckets as headerless shards; host 0 merges."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hadoop_bam_tpu.formats.bamio import BamWriter, read_bam_header
    from hadoop_bam_tpu.parallel.distributed import broadcast_plan
    from hadoop_bam_tpu.parallel.pipeline import _decode_span_core
    from hadoop_bam_tpu.split.planners import plan_bam_spans_balanced
    from hadoop_bam_tpu.utils.sort import _sorted_header

    mesh_devs = list(mesh.devices.ravel())
    n_dev = len(mesh_devs)
    pid = jax.process_index()
    n_proc = jax.process_count()
    if n_proc > 1:
        from jax.experimental import multihost_utils
    if header is None:
        header, _ = read_bam_header(input_path)

    # host 0 plans once (split guessing does real I/O); everyone receives
    spans = broadcast_plan(
        plan_bam_spans_balanced(input_path, n_dev, header=header)
        if pid == 0 else None)

    # decode ONLY the spans owned by this process's mesh devices
    local_pos = [d for d, dev in enumerate(mesh_devs)
                 if dev.process_index == pid]
    local = {}
    his: List[np.ndarray] = []
    los: List[np.ndarray] = []
    counts_vec = np.zeros(n_dev, np.int64)
    max_len = 0
    decode_err: Optional[BaseException] = None
    try:
        for d in local_pos:
            if d >= len(spans):
                continue
            data, offs, _voffs, _ = _decode_span_core(
                input_path, spans[d], False, "auto", want_voffs=False)
            lens_ = _record_lens(data, offs)
            local[d] = (data, offs, lens_)
            counts_vec[d] = offs.size
            if offs.size:
                max_len = max(max_len, int(lens_.max()))
            h, l = _keys_of(data, offs)
            his.append(h)
            los.append(l)
    except Exception as e:  # noqa: BLE001 — must reach the collective
        decode_err = e

    # agree on global geometry: counts/base, row stride, bucket bounds.
    # Boundary choice only affects balance, never order (buckets are a
    # range partition and every bucket is fully sorted), so a modest
    # fixed-size per-process sample is enough.  Same shared protocol as
    # the spill rounds (_agree_round_geometry), failure flag included.
    counts_vec, max_len, shis, slos = _agree_round_geometry(
        counts_vec, max_len, his, los, err=decode_err)
    total = int(counts_vec.sum())
    check_global_index_ceiling(total, "mesh sort (post-decode backstop)")
    bhi, blo = _sample_bounds(shis, slos, n_dev)

    records_cap = _round_up(int(counts_vec.max()) if total else 1, 8)
    stride = _round_up(max(max_len, 36), 64)
    base_vec = np.zeros(n_dev, np.int64)
    base_vec[1:] = np.cumsum(counts_vec[:-1])

    sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    _empty = (np.zeros(0, np.uint8), np.zeros(0, np.int64),
              np.zeros(0, np.int64))
    packed = {}
    for d in local_pos:
        data, offs, lens_ = local.pop(d, _empty)
        packed[d] = _pack_record_rows(data, offs, lens_, records_cap,
                                      stride)

    # make_array_from_single_device_arrays grew its dtype kwarg after
    # jax 0.4; casting host-side before device_put is version-portable
    def sharded(shape, dtype, of_d):
        return jax.make_array_from_single_device_arrays(
            shape, sharding,
            [jax.device_put(np.asarray(of_d(d), dtype=dtype),
                            mesh_devs[d]) for d in local_pos])

    def replicated(arr, dtype):
        arr = np.asarray(arr, dtype=dtype)
        return jax.make_array_from_single_device_arrays(
            arr.shape, rep,
            [jax.device_put(arr, mesh_devs[d]) for d in local_pos])

    rows_g = sharded((n_dev, records_cap, stride), jnp.uint8,
                     lambda d: packed[d][0][None])
    lens_g = sharded((n_dev, records_cap), jnp.int32,
                     lambda d: packed[d][1][None])
    count_g = sharded((n_dev,), jnp.int32,
                      lambda d: np.asarray([counts_vec[d]], np.int32))
    base_g = sharded((n_dev,), jnp.int32,
                     lambda d: np.asarray([base_vec[d]], np.int32))
    bhi_g = replicated(bhi, jnp.uint32)
    blo_g = replicated(blo, jnp.uint32)

    step = _make_bytes_sort_step(mesh, records_cap, stride)
    rows_s, lens_s, six_s = step(rows_g, lens_g, count_g, base_g,
                                 bhi_g, blo_g)

    # every host holds ONLY its devices' buckets; bucket order IS the
    # global order
    out_header = _sorted_header(header, by_name=False)

    b_rows, b_lens, b_six = (_buckets(rows_s), _buckets(lens_s),
                             _buckets(six_s))

    def bucket_payload(b):
        """(concatenated record bytes, record start offsets) of one
        bucket — record-aligned chunks the write path indexes."""
        keep = b_six[b] != _I32_SENTINEL
        n = int(keep.sum())
        if not n:
            return b"", np.zeros(0, np.int64)
        rows = b_rows[b][keep]
        lens = b_lens[b][keep].astype(np.int64)
        colmask = np.arange(stride)[None, :] < lens[:, None]
        return rows[colmask].tobytes(), np.cumsum(lens) - lens

    written = 0
    if n_proc == 1:
        # one continuous BGZF stream — byte-identical to sort_bam —
        # through the parallel write path, index sidecars co-written
        from hadoop_bam_tpu.write import write_bam_records

        def chunks():
            for b in sorted(b_rows):
                payload, offs = bucket_payload(b)
                if offs.size:
                    yield payload, offs
        written = write_bam_records(output_path, out_header, chunks(),
                                    config=config).records
    else:
        # parallel headerless shard writes (each host deflates its own
        # buckets), then host 0 re-blocks them into the continuous
        # stream so the merged file still matches sort_bam exactly
        from hadoop_bam_tpu.write import (
            ShardedFileWriter, write_bam_shards_concat,
        )
        sw = ShardedFileWriter(output_path, n_dev,
                               dir_suffix=".mesh-shards")
        if pid == 0:
            # stale parts from an earlier failed run must not survive
            # into this merge; barrier before anyone writes new ones
            sw.prepare()
        multihost_utils.process_allgather(np.zeros(1, np.int32))
        write_err = None
        try:
            for b in sorted(b_rows):
                payload, offs = bucket_payload(b)
                with sw.open_shard(b) as f:
                    with BamWriter(f, out_header, write_header=False,
                                   write_eof=False,
                                   level=config.write_compress_level) as w:
                        w.write_raw(payload, n_records=int(offs.size))
                written += int(offs.size)
        except Exception as e:  # noqa: BLE001 — must reach the collective
            # a raise here on one host only (ENOSPC, EIO, ...) would
            # strand the others in the allgather below; ship written=-1
            # as the failure flag instead
            write_err = e

    if n_proc > 1:
        g_written = np.asarray(multihost_utils.process_allgather(
            np.asarray([written if write_err is None else -1], np.int64)))
        if write_err is not None:
            raise write_err
        if (g_written < 0).any():
            raise RuntimeError("mesh sort shard write failed on another "
                               "host; output is invalid")
        written = int(g_written.sum())
    if written != total:
        raise RuntimeError(
            f"mesh sort wrote {written} of {total} records — bucket "
            f"exchange lost data; output is invalid")
    if n_proc > 1:
        merge_err = None
        if pid == 0:
            try:
                # every device position writes exactly one part (empty
                # buckets included), so a missing part means shared-FS
                # lag or data loss — refuse to merge a truncated file
                sw.concatenate(
                    lambda parts: write_bam_shards_concat(
                        parts, output_path, out_header, config=config),
                    what="mesh sort")
            except Exception as e:  # noqa: BLE001 — must reach the barrier
                merge_err = e
        # barrier doubling as failure broadcast: a raise before this
        # point on one process only would deadlock the others, so host
        # 0 always arrives here and ships ok/failed to everyone
        ok = np.asarray([0 if merge_err is not None else 1], np.int32)
        g_ok = np.asarray(multihost_utils.process_allgather(ok))
        if merge_err is not None:
            raise merge_err
        if int(g_ok.min()) == 0:
            raise RuntimeError("mesh sort merge failed on host 0; "
                               "output is invalid")
    return total


def sort_bam_mesh(input_path: str, output_path: str, *,
                  mesh=None, config: HBamConfig = DEFAULT_CONFIG,
                  header: Optional[SAMHeader] = None,
                  exchange: Optional[str] = None,
                  round_records: Optional[int] = None,
                  journal_path: Optional[str] = None) -> int:
    """Coordinate-sort a BAM over the mesh; byte-identical to
    utils/sort.py::sort_bam(by_name=False).  Returns the record count.

    ``exchange`` picks the shuffle flavor (module docstring): "index"
    (default single-host) or "bytes" (default — and required — when
    ``jax.process_count() > 1``).

    ``round_records`` engages the multi-round spill exchange
    (bytes-mode only): the shuffle streams ~that many records per
    device per round through the all_to_all, appending bucket-sorted
    runs to disk and k-way-merging per bucket at the end — device
    memory is then bounded by the round tile, not the file (the MR
    shuffle's spill, VERDICT r4 #6).  None keeps the single-round
    resident exchange.

    ``journal_path`` makes the sort CRASH-SAFE through a durable job
    journal (jobs/journal.py; ``hbam sort --journal``, resumed by
    ``hbam resume``).  Spill mode resumes at ROUND granularity — a
    SIGKILLed run re-decodes only the rounds whose runs never committed
    (see ``_sort_bam_mesh_bytes_spill_impl``); the resident single-round
    modes get job-level idempotence — a finished job's journal +
    verified output make the re-run a no-op, an unfinished one restarts
    (their whole exchange is one unit of work; use ``round_records``
    for mid-flight resume).  Mismatched input identity / config
    fingerprint / parameters refuse with ``PlanError``.

    Queryname sort keys are variable-length byte strings with no fixed-
    width device representation; use sort_bam for those.
    """
    import jax

    from hadoop_bam_tpu.parallel.mesh import make_mesh

    if round_records is not None and exchange is None:
        exchange = "bytes"
    if exchange is None:
        exchange = "bytes" if jax.process_count() > 1 else "index"
    if exchange not in ("index", "bytes"):
        raise ValueError(f"unknown exchange mode {exchange!r}; "
                         f"expected 'index' or 'bytes'")
    if round_records is not None and exchange != "bytes":
        raise ValueError("round_records (the spill exchange) requires "
                         "exchange='bytes'")
    # UP-FRONT int32 global-index ceiling (VERDICT r5 #8): when a
    # splitting-index sidecar records the exact total, refuse oversized
    # inputs BEFORE planning/decoding instead of wrapping mid-run
    from hadoop_bam_tpu.split.splitting_index import SplittingIndex
    _sidx = SplittingIndex.load_for(input_path)
    if _sidx is not None and _sidx.total_records > 0:
        check_global_index_ceiling(_sidx.total_records, "mesh sort plan")
    if mesh is None:
        mesh = make_mesh()
    if journal_path is not None and jax.process_count() > 1:
        raise PlanError(
            "mesh sort journaling is single-process for now: each host "
            "would need its own journal and a resume barrier protocol; "
            "run without journal_path under jax.distributed")
    if exchange == "bytes" and round_records is not None:
        return _sort_bam_mesh_bytes_spill(
            input_path, output_path, mesh=mesh, config=config,
            header=header, round_records=int(round_records),
            journal_path=journal_path)
    if exchange == "index" and jax.process_count() > 1:
        raise ValueError(
            "exchange='index' keeps every decoded span on the calling "
            "host and cannot run multi-host; use exchange='bytes'")
    if journal_path is not None:
        # resident exchanges are one unit of work: journal at JOB grain
        # (done + verified output -> no-op; anything else -> re-run)
        from hadoop_bam_tpu.jobs.runner import (
            run_job_level, sort_job_params,
        )

        return run_job_level(
            journal_path, kind="mesh_sort", config=config,
            inputs=[input_path], output=output_path,
            params=sort_job_params(input_path, output_path,
                                   exchange=exchange, round_records=None),
            run=lambda: (
                _sort_bam_mesh_bytes(input_path, output_path, mesh=mesh,
                                     config=config, header=header)
                if exchange == "bytes" else
                _sort_bam_mesh_index(input_path, output_path, mesh=mesh,
                                     config=config, header=header)))
    if exchange == "bytes":
        return _sort_bam_mesh_bytes(input_path, output_path, mesh=mesh,
                                    config=config, header=header)
    return _sort_bam_mesh_index(input_path, output_path, mesh=mesh,
                                config=config, header=header)


def _sort_bam_mesh_index(input_path: str, output_path: str, *, mesh,
                         config: HBamConfig,
                         header: Optional[SAMHeader]) -> int:
    """Index-exchange mesh sort (module docstring): only keys + global
    indices ride the all_to_all; the host applies the permutation by
    gathering record bytes from its resident decoded spans.  Single
    process only (the caller enforces it)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import _decode_span_core
    from hadoop_bam_tpu.split.planners import plan_bam_spans_balanced
    from hadoop_bam_tpu.utils.sort import _sorted_header

    n_dev = int(np.prod(mesh.devices.shape))
    if header is None:
        header, _ = read_bam_header(input_path)

    spans = plan_bam_spans_balanced(input_path, n_dev, header=header)
    raw: List[Tuple[np.ndarray, np.ndarray]] = []   # (data, offsets)
    his: List[np.ndarray] = []
    los: List[np.ndarray] = []
    for s in spans:
        data, offs, _voffs, _ = _decode_span_core(input_path, s, False,
                                                  "auto")
        if data.size > 2**31 - 64:
            raise ValueError(
                f"span inflates to {data.size} bytes — offsets exceed "
                f"the device int32 tile layout; use utils.sort.sort_bam "
                f"for inputs this large")
        raw.append((data, offs.astype(np.int32)))
        h, l = _keys_of(data, offs)
        his.append(h)
        los.append(l)
    counts = [o.size for _, o in raw]
    total = int(sum(counts))
    base = np.zeros(n_dev, dtype=np.int32)
    if counts:
        base[1:len(counts)] = np.cumsum(counts[:-1])

    bytes_cap = _round_up(max((d.size for d, _ in raw), default=1), 256)
    records_cap = _round_up(max(counts, default=1), 8)
    datas = np.zeros((n_dev, bytes_cap), np.uint8)
    offsets = np.zeros((n_dev, records_cap), np.int32)
    cvec = np.zeros(n_dev, np.int32)
    for d, (dat, off) in enumerate(raw):
        datas[d, :dat.size] = dat
        offsets[d, :off.size] = off
        cvec[d] = off.size
    bhi, blo = _sample_bounds(his, los, n_dev)

    step = _make_sort_step(mesh, records_cap)
    sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    six = step(jax.device_put(datas, sharding),
               jax.device_put(offsets, sharding),
               jax.device_put(cvec, sharding),
               jax.device_put(base, sharding),
               jax.device_put(bhi, rep), jax.device_put(blo, rep))
    six = np.asarray(six)                     # [n_dev, n_dev * records_cap]
    del datas, offsets                        # padded copies; raw suffices

    # apply the permutation: buckets in device order ARE the global order.
    # Vectorized per bucket — per-record Python slicing would dominate the
    # whole sort at scale: gather each record's (source span, offset,
    # length), then assemble one contiguous output buffer with the same
    # repeat/arange scatter the decode paths use, and bulk-append it.
    span_of = np.searchsorted(
        np.cumsum(counts), np.arange(total), side="right")
    out_header = _sorted_header(header, by_name=False)
    from hadoop_bam_tpu.write import write_bam_records

    def bucket_chunks():
        for d in range(n_dev):
            idxs = six[d]
            idxs = idxs[idxs != _I32_SENTINEL].astype(np.int64)
            if not idxs.size:
                continue
            s_arr = span_of[idxs]
            o_arr = np.empty(idxs.size, np.int64)
            ln_arr = np.empty(idxs.size, np.int64)
            for sp in np.unique(s_arr):
                m = s_arr == sp
                data, offs = raw[sp]
                o = offs[idxs[m] - int(base[sp])].astype(np.int64)
                bs = (data[o[:, None] + np.arange(4)]
                      .view("<i4").ravel().astype(np.int64))
                o_arr[m] = o
                ln_arr[m] = bs + 4
            dst0 = np.cumsum(ln_arr) - ln_arr
            out = np.empty(int(ln_arr.sum()), np.uint8)
            for sp in np.unique(s_arr):
                m = s_arr == sp
                data, _ = raw[sp]
                nb = ln_arr[m]
                f = (np.arange(int(nb.sum()), dtype=np.int64)
                     - np.repeat(np.cumsum(nb) - nb, nb))
                out[np.repeat(dst0[m], nb) + f] = \
                    data[np.repeat(o_arr[m], nb) + f]
            yield out, dst0

    written = write_bam_records(output_path, out_header, bucket_chunks(),
                                config=config).records
    if written != total:
        raise RuntimeError(
            f"mesh sort wrote {written} of {total} records — bucket "
            f"exchange lost data (capacity bug); output is invalid")
    return total
