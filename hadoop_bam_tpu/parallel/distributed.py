"""Multi-host coordination: init, single-planner broadcast, span assignment.

The reference's "distributed backend" was Hadoop's (SURVEY.md section 2.9):
HDFS for placement, YARN for scheduling, one client-side getSplits() whose
result rode the job config to every task.  The TPU rebuild keeps that shape:

- ``initialize()`` — jax.distributed bootstrap (no-op single-host);
- ``broadcast_plan()`` — host 0 plans spans (guessers/index probing do real
  I/O and inflation, so they must run once, not per host — the analog of
  client-side split planning at job submission), every host receives the
  JSON-serialized plan over the ICI/DCN collective fabric;
- ``assign_spans()`` — contiguous per-host slices (locality: each host
  fetches only its slice's byte ranges), then per-device groups inside
  parallel/pipeline.py.

Failure recovery mirrors the reference (SURVEY.md section 5): spans are
self-describing and decode is idempotent/side-effect-free, so any span can be
re-decoded anywhere; ``retry_span`` is a plain re-invoke.
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence

import jax
import numpy as np

from hadoop_bam_tpu.split.spans import FileVirtualSpan


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up jax.distributed when configured; safe no-op otherwise."""
    if coordinator_address is None and num_processes is None:
        return  # single-host / env-driven auto-init
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def serialize_plan(spans: Sequence, max_bytes: int = 1 << 24) -> bytes:
    """JSON payload of a span plan, class-tagged; raises if it exceeds
    the fixed broadcast buffer.  Exposed separately so callers under a
    failure-flag protocol can validate the size INSIDE their flagged
    phase (a raise mid-broadcast strands the receiving hosts)."""
    payload = json.dumps(
        [{"k": type(s).__name__, **s.to_dict()} for s in spans]).encode()
    if len(payload) + 8 > max_bytes:
        raise ValueError(f"plan of {len(spans)} spans serializes to "
                         f"{len(payload)} bytes — exceeds the "
                         f"{max_bytes}-byte broadcast buffer; raise "
                         f"max_bytes or plan coarser spans")
    return payload


def broadcast_plan(spans: Optional[Sequence],
                   max_bytes: int = 1 << 24) -> List:
    """Host 0 passes its plan; other hosts pass None and receive it.

    Uses a fixed-size uint8 buffer through broadcast_one_to_all (the payload
    must have identical shape on all hosts).  Both span flavors travel
    (virtual-offset BAM spans and plain byte spans for text formats),
    tagged with their class.
    """
    from hadoop_bam_tpu.split.spans import FileByteSpan

    span_classes = {"FileVirtualSpan": FileVirtualSpan,
                    "FileByteSpan": FileByteSpan}
    if jax.process_count() == 1:
        assert spans is not None
        return list(spans)
    from jax.experimental import multihost_utils

    if jax.process_index() == 0:
        payload = serialize_plan(spans, max_bytes)
        buf = np.zeros(max_bytes, dtype=np.uint8)
        buf[:8] = np.frombuffer(np.int64(len(payload)).tobytes(), np.uint8)
        buf[8:8 + len(payload)] = np.frombuffer(payload, np.uint8)
    else:
        buf = np.zeros(max_bytes, dtype=np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    out = np.asarray(out)
    n = int(np.frombuffer(out[:8].tobytes(), np.int64)[0])
    plan = json.loads(out[8:8 + n].tobytes().decode())
    return [span_classes[d.pop("k", "FileVirtualSpan")].from_dict(d)
            for d in plan]


def assign_spans(spans: Sequence[FileVirtualSpan],
                 index: Optional[int] = None,
                 count: Optional[int] = None) -> List[FileVirtualSpan]:
    """Contiguous per-host slice, balanced by compressed size."""
    index = jax.process_index() if index is None else index
    count = jax.process_count() if count is None else count
    if not spans:
        # a legitimately empty plan (e.g. a .bai-pruned region with no
        # aligned reads) assigns nothing everywhere — cum[-1] below
        # would IndexError on the empty array
        return []
    if count == 1:
        return list(spans)

    def size_of(s):
        sz = getattr(s, "compressed_size", None)   # virtual-offset spans
        if sz is None:
            sz = s.end - s.start                   # plain byte spans
        return max(int(sz), 1)

    sizes = np.asarray([size_of(s) for s in spans], dtype=np.float64)
    cum = np.cumsum(sizes)
    total = cum[-1]
    lo, hi = total * index / count, total * (index + 1) / count
    out = [s for s, c, z in zip(spans, cum, sizes)
           if lo < c - z / 2 <= hi]  # midpoint rule: every span exactly once
    return out


def _multihost_reduce(plan_builder, local_reducer, payload_len: int
                      ) -> np.ndarray:
    """Shared scaffold of the multi-host stat drivers.

    The reference shape (SURVEY.md sections 2.9/3.2): client-side
    ``getSplits()`` once, map tasks reduce their own splits, one final
    combine.  Host 0 runs ``plan_builder`` and broadcasts; each process
    runs ``local_reducer(assigned_spans)`` -> float64[payload_len] over
    ONLY its share; one allgather stacks the rows.

    Failure-flag convention (as in mesh_sort): a raise on one host
    before a collective would strand the others in it, so every phase
    reaches its collective and ships an ok/failed flag instead.
    Counters travel as float64 — exact up to 2^53, far beyond any
    record count here.  Returns the (n_hosts, payload_len) matrix.
    """
    from jax.experimental import multihost_utils

    plan = None
    err = None
    if jax.process_index() == 0:
        try:
            plan = plan_builder()
            serialize_plan(plan)   # size-check INSIDE the flagged phase
        except Exception as e:  # noqa: BLE001 — must reach the collective
            err = e
    ok = np.asarray([0 if err is not None else 1], np.int32)
    g_ok = np.asarray(multihost_utils.process_allgather(ok))
    if err is not None:
        raise err
    if int(g_ok.min()) == 0:
        raise RuntimeError("distributed reduce: span planning failed on "
                           "host 0")
    mine = assign_spans(broadcast_plan(plan))
    row = np.zeros(1 + payload_len, np.float64)
    try:
        row[1:] = local_reducer(mine)
        row[0] = 1.0
    except Exception as e:  # noqa: BLE001 — must reach the collective
        err = e
        row[:] = 0.0
    g = np.asarray(multihost_utils.process_allgather(row))
    if err is not None:
        raise err
    if (g[:, 0] < 1).any():
        raise RuntimeError("distributed reduce failed on another host")
    return g[:, 1:]


def _local_mesh():
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=jax.local_devices())


def distributed_flagstat(path: str, config=None, header=None):
    """Whole-file flagstat across a multi-host ``jax.distributed`` job;
    single-process calls degrade to plain flagstat_file.  Flagstat
    counters are sum-combinable, so the combine is one addition."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS
    from hadoop_bam_tpu.parallel.pipeline import (
        flagstat_file, pipeline_span_count,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached

    config = DEFAULT_CONFIG if config is None else config
    if header is None:
        header, _ = read_bam_header(path)
    if jax.process_count() == 1:
        return flagstat_file(path, config=config, header=header)

    def plan():
        n = pipeline_span_count(path, jax.device_count(), config)
        return plan_spans_cached(path, header, config, num_spans=n)

    def local(mine):
        stats = flagstat_file(path, mesh=_local_mesh(), config=config,
                              header=header, spans=mine)
        return np.asarray([stats[k] for k in FLAGSTAT_FIELDS], np.float64)

    tot = _multihost_reduce(plan, local, len(FLAGSTAT_FIELDS)).sum(axis=0)
    return {k: int(v) for k, v in zip(FLAGSTAT_FIELDS, tot)}


def distributed_seq_stats(path: str, config=None, header=None,
                          geometry=None):
    """Multi-host seq_stats_file: counts and histograms sum; the means
    combine weighted by each host's read count."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.seq_pallas import N_CODES
    from hadoop_bam_tpu.parallel.pipeline import (
        pipeline_span_count, seq_stats_file,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached

    config = DEFAULT_CONFIG if config is None else config
    if header is None:
        header, _ = read_bam_header(path)
    if jax.process_count() == 1:
        return seq_stats_file(path, config=config, header=header,
                              geometry=geometry)

    def plan():
        n = pipeline_span_count(path, jax.device_count(), config)
        return plan_spans_cached(path, header, config, num_spans=n)

    def local(mine):
        return _pack_seq_stats(seq_stats_file(
            path, mesh=_local_mesh(), config=config, header=header,
            spans=mine, geometry=geometry))

    return _combine_seq_stats(
        _multihost_reduce(plan, local, 3 + N_CODES))


def _pack_seq_stats(s) -> np.ndarray:
    """One host's seq stats as a sum-combinable row: counts plus
    n-weighted means (the exact inverse of _combine_seq_stats)."""
    n = float(s["n_reads"])
    return np.concatenate([
        [n, s["mean_gc"] * n, s["mean_qual"] * n],
        np.asarray(s["base_hist"], np.float64)])


def _combine_seq_stats(rows: np.ndarray) -> dict:
    g = rows.sum(axis=0)
    n = max(g[0], 1.0)
    return {"n_reads": int(g[0]), "mean_gc": float(g[1] / n),
            "mean_qual": float(g[2] / n),
            "base_hist": g[3:].astype(np.int64)}


def distributed_fastq_seq_stats(path: str, config=None, geometry=None):
    """Multi-host fastq_seq_stats_file (FASTQ/QSEQ): same weighted
    combine as distributed_seq_stats, over byte-span plans."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.ops.seq_pallas import N_CODES
    from hadoop_bam_tpu.parallel.pipeline import (
        QSEQ_EXTS, fastq_seq_stats_file, pipeline_span_count,
    )

    config = DEFAULT_CONFIG if config is None else config
    if jax.process_count() == 1:
        return fastq_seq_stats_file(path, config=config, geometry=geometry)

    def plan():   # runs on host 0 only
        from hadoop_bam_tpu.api.read_datasets import open_fastq, open_qseq
        opener = open_qseq if path.lower().endswith(QSEQ_EXTS) \
            else open_fastq
        n = pipeline_span_count(path, jax.device_count(), config)
        return opener(path, config).spans(num_spans=n)

    def local(mine):
        return _pack_seq_stats(fastq_seq_stats_file(
            path, mesh=_local_mesh(), config=config, geometry=geometry,
            spans=mine))

    return _combine_seq_stats(
        _multihost_reduce(plan, local, 3 + N_CODES))


def distributed_cram_seq_stats(path: str, config=None, geometry=None):
    """Multi-host cram_seq_stats_file: same weighted combine as the
    other seq-stats drivers, over container-aligned byte-span plans."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.ops.seq_pallas import N_CODES
    from hadoop_bam_tpu.parallel.pipeline import (
        cram_seq_stats_file, pipeline_span_count,
    )

    config = DEFAULT_CONFIG if config is None else config
    if jax.process_count() == 1:
        return cram_seq_stats_file(path, config=config, geometry=geometry)

    def plan():   # runs on host 0 only
        from hadoop_bam_tpu.api.cram_dataset import open_cram
        n = pipeline_span_count(path, jax.device_count(), config)
        return open_cram(path, config).spans(num_spans=n)

    def local(mine):
        return _pack_seq_stats(cram_seq_stats_file(
            path, mesh=_local_mesh(), config=config, geometry=geometry,
            spans=mine))

    return _combine_seq_stats(
        _multihost_reduce(plan, local, 3 + N_CODES))


def distributed_variant_stats(path: str, config=None, header=None):
    """Multi-host variant_stats_file: counts sum; mean_af combines
    weighted by n_af; per-sample call rates by n_variants."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.parallel.pipeline import pipeline_span_count
    from hadoop_bam_tpu.parallel.variant_pipeline import (
        variant_stats_file,
    )

    config = DEFAULT_CONFIG if config is None else config
    if jax.process_count() == 1:
        return variant_stats_file(path, config=config, header=header)
    ds = open_vcf(path, config)        # one open: header + span planner
    if header is None:
        header = ds.header
    n_samples = header.n_samples

    def plan():
        n = pipeline_span_count(path, jax.device_count(), config)
        return ds.spans(num_spans=n)

    def local(mine):
        s = variant_stats_file(path, mesh=_local_mesh(), config=config,
                               header=header, spans=mine)
        nv = float(s["n_variants"])
        return np.concatenate([
            [nv, s["n_snp"], s["n_pass"], s["n_af"],
             s["mean_af"] * s["n_af"]],
            np.asarray(s["sample_callrate"], np.float64) * nv])

    g = _multihost_reduce(plan, local, 5 + n_samples).sum(axis=0)
    nv = int(g[0])
    return {"n_variants": nv, "n_snp": int(g[1]), "n_pass": int(g[2]),
            "mean_af": float(g[4] / max(g[3], 1.0)), "n_af": int(g[3]),
            "sample_callrate": g[5:] / max(nv, 1)}


def distributed_coverage(path: str, region, config=None, header=None,
                         max_cigar: int = 64) -> np.ndarray:
    """Multi-host coverage_file: each host piles up only its assigned
    spans over the SAME window, and per-base depths sum exactly across
    hosts (each record is decoded on exactly one host).

    The combine allgathers one float64 row per host of ``window``
    entries, so the per-call window is capped at 2^24 bases (128 MB/row)
    — tile larger regions across calls exactly like the CLI does.
    Single-process calls degrade to plain coverage_file."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import coverage_file
    from hadoop_bam_tpu.split.intervals import Interval, resolve_interval

    config = DEFAULT_CONFIG if config is None else config
    if header is None:
        header, _ = read_bam_header(path)
    if jax.process_count() == 1:
        return coverage_file(path, region, config=config, header=header,
                             max_cigar=max_cigar)
    if not isinstance(region, Interval):
        region = resolve_interval(region, header.ref_names)
    if region.rname not in header.ref_names:
        raise ValueError(f"region reference {region.rname!r} not in header")
    ref_len = header.ref_lengths[header.ref_names.index(region.rname)]
    end = min(region.end, ref_len)
    window = end - region.start + 1
    if window <= 0:
        raise ValueError(f"empty region {region}")
    if window > (1 << 24):
        raise ValueError(f"distributed region spans {window} bases; the "
                         "per-call cap is 2^24 — tile larger regions "
                         "across calls")
    region = Interval(region.rname, region.start, end)

    def plan():
        # the same plan coverage_file builds itself: .bai-trimmed chunks
        # when a sidecar exists, whole-file pipeline-grain spans otherwise
        from hadoop_bam_tpu.parallel.pipeline import pipeline_span_count
        from hadoop_bam_tpu.split.bai import plan_interval_spans
        from hadoop_bam_tpu.split.planners import plan_spans_cached

        spans = plan_interval_spans(path, [region], header)
        if spans is None:
            n = pipeline_span_count(path, jax.device_count(), config)
            spans = plan_spans_cached(path, header, config, num_spans=n)
        return spans

    def local(mine):
        depth = coverage_file(path, region, mesh=_local_mesh(),
                              config=config, header=header, spans=mine,
                              max_cigar=max_cigar)
        return np.asarray(depth, np.float64)

    g = _multihost_reduce(plan, local, window).sum(axis=0)
    return g.astype(np.int32)


def retry_span(decode_fn, span: FileVirtualSpan, attempts: int = 3):
    """Span-level retry — the framework's failure-recovery unit."""
    last: Exception
    for _ in range(attempts):
        try:
            return decode_fn(span)
        except Exception as e:  # noqa: BLE001 — deliberate blanket retry
            last = e
    raise last
