"""Multi-host coordination: init, single-planner broadcast, span assignment.

The reference's "distributed backend" was Hadoop's (SURVEY.md section 2.9):
HDFS for placement, YARN for scheduling, one client-side getSplits() whose
result rode the job config to every task.  The TPU rebuild keeps that shape:

- ``initialize()`` — jax.distributed bootstrap (no-op single-host);
- ``broadcast_plan()`` — host 0 plans spans (guessers/index probing do real
  I/O and inflation, so they must run once, not per host — the analog of
  client-side split planning at job submission), every host receives the
  JSON-serialized plan over the ICI/DCN collective fabric;
- ``assign_spans()`` — contiguous per-host slices (locality: each host
  fetches only its slice's byte ranges), then per-device groups inside
  parallel/pipeline.py.

Failure recovery mirrors the reference (SURVEY.md section 5): spans are
self-describing and decode is idempotent/side-effect-free, so any span can be
re-decoded anywhere; ``retry_span`` is a plain re-invoke.
"""
from __future__ import annotations

import json
import logging
from typing import Callable, List, Optional, Sequence

import jax
import numpy as np

from hadoop_bam_tpu.split.spans import FileVirtualSpan
from hadoop_bam_tpu.utils.errors import (
    PlanError, TRANSIENT, TransientIOError, classify_error,
)
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.resilient import QuarantineManifest, RetryPolicy

logger = logging.getLogger(__name__)


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up jax.distributed when configured; safe no-op otherwise."""
    if coordinator_address is None and num_processes is None:
        return  # single-host / env-driven auto-init
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def serialize_plan(spans: Sequence, max_bytes: int = 1 << 24) -> bytes:
    """JSON payload of a span plan, class-tagged; raises if it exceeds
    the fixed broadcast buffer.  Exposed separately so callers under a
    failure-flag protocol can validate the size INSIDE their flagged
    phase (a raise mid-broadcast strands the receiving hosts)."""
    payload = json.dumps(
        [{"k": type(s).__name__, **s.to_dict()} for s in spans]).encode()
    if len(payload) + 8 > max_bytes:
        # PLAN class (still a ValueError): a mis-sized broadcast buffer is
        # a configuration fault, not retryable and not skippable
        raise PlanError(f"plan of {len(spans)} spans serializes to "
                        f"{len(payload)} bytes — exceeds the "
                        f"{max_bytes}-byte broadcast buffer; raise "
                        f"max_bytes or plan coarser spans")
    return payload


class _CollectiveTimeout(Exception):
    """Internal sentinel: the collective outlived timeout_s.  Distinct from
    TransientIOError so the retry clause below cannot confuse a hang (never
    safe to re-enter solo) with a failed-and-returned transient error
    (safe to retry in lockstep)."""


def _run_collective(fn: Callable[[], object], what: str,
                    retries: int = 0,
                    timeout_s: Optional[float] = None):
    """Classified retry/timeout wrapper for multihost collectives.

    Retries fire only on TRANSIENT-classified failures raised by the
    collective itself (transport resets, interrupted syscalls) — failures
    every participating host observes — and the schedule is deterministic
    (``jitter=0``), so all hosts re-enter the collective in lockstep.  A
    TIMEOUT is different: the operation may still be in flight on peer
    hosts, and a solo re-entry would deadlock the group, so it surfaces
    immediately as ``TransientIOError`` for the caller to abort on.  The
    timed body runs on a DAEMON thread: a hung collective cannot be
    cancelled from Python, but a daemon never blocks interpreter exit, so
    the abort actually terminates the job.

    Retries REQUIRE a timeout: a transport error is not guaranteed to be
    observed by every peer, and an unbounded solo re-entry into a
    collective the peers already left would hang forever — so with
    ``timeout_s=None`` transient failures fail fast (the pre-resilience
    behavior) and the retry budget is ignored."""
    import threading
    import time as _time

    if timeout_s is None:
        retries = 0

    def run_once():
        if timeout_s is None:
            return fn()
        box: dict = {}

        def runner():
            try:
                box["value"] = fn()
            except BaseException as e:  # noqa: BLE001 — relayed below
                box["error"] = e

        t = threading.Thread(target=runner, daemon=True,
                             name=f"collective:{what}")
        t0 = _time.perf_counter()
        t.start()
        # heartbeat-stamped wait: join in bounded slices, stamping a
        # liveness counter each wake, so an operator watching the
        # metrics stream can tell "still waiting on a peer" (heartbeats
        # advancing) from "this process is itself wedged" (no stamps) —
        # and the wait distribution lands in a mergeable histogram
        deadline = t0 + timeout_s
        while t.is_alive():
            remaining = deadline - _time.perf_counter()
            if remaining <= 0:
                break
            t.join(min(1.0, remaining))
            METRICS.count("distributed.heartbeats")
        METRICS.observe("distributed.collective_wait_s",
                        _time.perf_counter() - t0)
        if t.is_alive():
            raise _CollectiveTimeout
        if "error" in box:
            raise box["error"]
        return box["value"]

    policy = RetryPolicy(retries=retries, jitter=0.0)
    for attempt in range(retries + 1):
        try:
            return run_once()
        except _CollectiveTimeout:
            raise TransientIOError(
                f"{what} timed out after {timeout_s:g}s — peers may still "
                "be in the collective; aborting rather than re-entering "
                "solo") from None
        except Exception as e:  # noqa: BLE001 — policy boundary
            if classify_error(e) != TRANSIENT or attempt >= retries:
                raise
            METRICS.count("distributed.collective_retries")
            d = policy.delay(attempt)
            logger.warning("%s failed transiently (attempt %d/%d), "
                           "retrying in %.3fs: %s", what, attempt + 1,
                           retries + 1, d, e)
            policy.sleep(d)
    raise AssertionError("unreachable")  # loop always returns or raises


def collective_timeout(config) -> Optional[float]:
    """The config's multi-host loss-detection budget
    (``collective_timeout_s``): how long any barrier/allgather may block
    before a dead peer surfaces as classified ``TransientIOError``
    instead of hanging the survivors forever.  None (the default) keeps
    the pre-jobs unbounded-wait behavior."""
    t = getattr(config, "collective_timeout_s", None) \
        if config is not None else None
    return float(t) if t else None


def guarded_allgather(arr: np.ndarray, what: str,
                      timeout_s: Optional[float] = None) -> np.ndarray:
    """``process_allgather`` under the classified timeout/heartbeat
    wrapper — the one helper every barrier-shaped collective in the
    mesh pipelines routes through (mesh_sort's round/merge flags, the
    spill-round geometry agreement), so one dead host fails the
    collective fast everywhere instead of wherever someone remembered
    to wrap it."""
    from jax.experimental import multihost_utils

    return _run_collective(
        lambda: np.asarray(multihost_utils.process_allgather(arr)),
        what, timeout_s=timeout_s)


def broadcast_plan(spans: Optional[Sequence],
                   max_bytes: int = 1 << 24,
                   retries: int = 2,
                   timeout_s: Optional[float] = None) -> List:
    """Host 0 passes its plan; other hosts pass None and receive it.

    Uses a fixed-size uint8 buffer through broadcast_one_to_all (the payload
    must have identical shape on all hosts).  Both span flavors travel
    (virtual-offset BAM spans and plain byte spans for text formats),
    tagged with their class.

    Transient collective failures are retried ``retries`` times on a
    deterministic (jitter-free) backoff schedule so every host re-enters in
    lockstep; ``timeout_s`` bounds the wall-clock wait and surfaces a hang
    as ``TransientIOError`` instead of blocking the job forever.  Retries
    only engage when ``timeout_s`` is set — an unbounded solo re-entry
    could hang on peers that already left the collective (see
    ``_run_collective``); without a timeout, transient failures fail
    fast."""
    from hadoop_bam_tpu.split.spans import FileByteSpan

    span_classes = {"FileVirtualSpan": FileVirtualSpan,
                    "FileByteSpan": FileByteSpan}
    if jax.process_count() == 1:
        assert spans is not None
        return list(spans)
    from jax.experimental import multihost_utils

    if jax.process_index() == 0:
        payload = serialize_plan(spans, max_bytes)
        buf = np.zeros(max_bytes, dtype=np.uint8)
        buf[:8] = np.frombuffer(np.int64(len(payload)).tobytes(), np.uint8)
        buf[8:8 + len(payload)] = np.frombuffer(payload, np.uint8)
    else:
        buf = np.zeros(max_bytes, dtype=np.uint8)
    out = _run_collective(
        lambda: np.asarray(multihost_utils.broadcast_one_to_all(buf)),
        "broadcast_plan", retries=retries, timeout_s=timeout_s)
    # some jax/gloo versions widen uint8 payloads element-wise through the
    # collective; each element still holds one byte value, so cast back
    out = out.astype(np.uint8, copy=False)
    n = int(np.frombuffer(out[:8].tobytes(), np.int64)[0])
    plan = json.loads(out[8:8 + n].tobytes().decode())
    return [span_classes[d.pop("k", "FileVirtualSpan")].from_dict(d)
            for d in plan]


def merge_quarantine_manifests(manifest: QuarantineManifest,
                               max_bytes: int = 1 << 20,
                               timeout_s: Optional[float] = None
                               ) -> QuarantineManifest:
    """Reduce-side manifest merge: every host contributes its local
    quarantine entries over one fixed-size allgather, and all hosts return
    the identical deduplicated, canonically-ordered union — so "what was
    skipped" is a property of the JOB, not of whichever host happened to
    decode the bad span.  Single-process: returns the manifest unchanged."""
    if jax.process_count() == 1:
        return manifest
    from jax.experimental import multihost_utils

    # cheap pre-check (8 bytes/host): clean runs — the common case — skip
    # the max_bytes-sized payload allgather entirely
    counts = _run_collective(
        lambda: np.asarray(multihost_utils.process_allgather(
            np.asarray([len(manifest)], np.int64))),
        "merge_quarantine_manifests:counts", timeout_s=timeout_s)
    if int(np.sum(counts)) == 0:
        return manifest

    payload = manifest.to_json().encode()
    if len(payload) + 8 > max_bytes:
        raise PlanError(f"quarantine manifest of {len(manifest)} entries "
                        f"serializes to {len(payload)} bytes — exceeds the "
                        f"{max_bytes}-byte allgather buffer")
    buf = np.zeros(max_bytes, dtype=np.uint8)
    buf[:8] = np.frombuffer(np.int64(len(payload)).tobytes(), np.uint8)
    buf[8:8 + len(payload)] = np.frombuffer(payload, np.uint8)
    rows = _run_collective(
        lambda: np.asarray(multihost_utils.process_allgather(buf)),
        "merge_quarantine_manifests", timeout_s=timeout_s)
    rows = rows.astype(np.uint8, copy=False)  # see broadcast_plan: some
    #                                           collectives widen uint8
    per_host = []
    for host in range(rows.shape[0]):
        n = int(np.frombuffer(rows[host, :8].tobytes(), np.int64)[0])
        per_host.append(QuarantineManifest.from_json(
            rows[host, 8:8 + n].tobytes().decode()))
    # merge the allgathered ROWS only (this host's own row is among them):
    # merged_with sums total_spans, and each host must count exactly once
    return per_host[0].merged_with(per_host[1:])


def merge_metrics(metrics=None, max_bytes: int = 1 << 20,
                  timeout_s: Optional[float] = None):
    """Mesh-wide metric merge: every host contributes its local Metrics
    state over one fixed-size allgather, and all hosts return the SAME
    merged ``Metrics`` — the job-level view the reference's Hadoop
    counters gave for free and per-host stderr dumps cannot.

    Merge semantics (``Metrics.merge_dict``): counters and timers SUM
    (work adds across hosts); histograms merge by bucket addition —
    associative, so the fold order across hosts cannot change the
    result (pinned in tests/test_obs.py); wall spans take the MAX
    across hosts (each host's value is already its local union, and
    hosts run concurrently — the mesh-wide wall is the slowest host's,
    not the sum).  Single-process: returns a detached copy of the
    current state, so callers can render/export it uniformly."""
    from hadoop_bam_tpu.utils.metrics import Metrics, current_metrics

    if metrics is None:
        metrics = current_metrics()
    if jax.process_count() == 1:
        return Metrics.from_dict(metrics.to_dict())
    from jax.experimental import multihost_utils

    payload = json.dumps(metrics.to_dict()).encode()
    if len(payload) + 8 > max_bytes:
        raise PlanError(f"metrics snapshot serializes to {len(payload)} "
                        f"bytes — exceeds the {max_bytes}-byte allgather "
                        f"buffer; raise max_bytes")
    buf = np.zeros(max_bytes, dtype=np.uint8)
    buf[:8] = np.frombuffer(np.int64(len(payload)).tobytes(), np.uint8)
    buf[8:8 + len(payload)] = np.frombuffer(payload, np.uint8)
    rows = _run_collective(
        lambda: np.asarray(multihost_utils.process_allgather(buf)),
        "merge_metrics", timeout_s=timeout_s)
    rows = rows.astype(np.uint8, copy=False)  # see broadcast_plan: some
    #                                           collectives widen uint8
    merged = Metrics()
    for host in range(rows.shape[0]):
        n = int(np.frombuffer(rows[host, :8].tobytes(), np.int64)[0])
        merged.merge_dict(json.loads(rows[host, 8:8 + n].tobytes()
                                     .decode()))
    merged.count("obs.hosts_merged", int(rows.shape[0]))
    return merged


def assign_spans(spans: Sequence[FileVirtualSpan],
                 index: Optional[int] = None,
                 count: Optional[int] = None) -> List[FileVirtualSpan]:
    """Contiguous per-host slice, balanced by compressed size."""
    index = jax.process_index() if index is None else index
    count = jax.process_count() if count is None else count
    if not spans:
        # a legitimately empty plan (e.g. a .bai-pruned region with no
        # aligned reads) assigns nothing everywhere — cum[-1] below
        # would IndexError on the empty array
        return []
    if count == 1:
        return list(spans)

    def size_of(s):
        sz = getattr(s, "compressed_size", None)   # virtual-offset spans
        if sz is None:
            sz = s.end - s.start                   # plain byte spans
        return max(int(sz), 1)

    sizes = np.asarray([size_of(s) for s in spans], dtype=np.float64)
    cum = np.cumsum(sizes)
    total = cum[-1]
    lo, hi = total * index / count, total * (index + 1) / count
    out = [s for s, c, z in zip(spans, cum, sizes)
           if lo < c - z / 2 <= hi]  # midpoint rule: every span exactly once
    return out


def _multihost_reduce(plan_builder, local_reducer, payload_len: int,
                      timeout_s: Optional[float] = None) -> np.ndarray:
    """Shared scaffold of the multi-host stat drivers.

    The reference shape (SURVEY.md sections 2.9/3.2): client-side
    ``getSplits()`` once, map tasks reduce their own splits, one final
    combine.  Host 0 runs ``plan_builder`` and broadcasts; each process
    runs ``local_reducer(assigned_spans)`` -> float64[payload_len] over
    ONLY its share; one allgather stacks the rows.

    Failure-flag convention (as in mesh_sort): a raise on one host
    before a collective would strand the others in it, so every phase
    reaches its collective and ships an ok/failed flag instead.
    Counters travel as float64 — exact up to 2^53, far beyond any
    record count here.  Returns the (n_hosts, payload_len) matrix.

    ``timeout_s`` (``config.collective_timeout_s`` at the drivers):
    every flag/row allgather runs under the heartbeat-stamped timeout,
    so one dead host fails the whole reduce with classified
    ``TransientIOError`` instead of hanging the survivors.
    """
    plan = None
    err = None
    if jax.process_index() == 0:
        try:
            plan = plan_builder()
            serialize_plan(plan)   # size-check INSIDE the flagged phase
        except Exception as e:  # noqa: BLE001 — must reach the collective
            err = e
    ok = np.asarray([0 if err is not None else 1], np.int32)
    g_ok = guarded_allgather(ok, "distributed reduce: plan flag",
                             timeout_s=timeout_s)
    if err is not None:
        raise err
    if int(g_ok.min()) == 0:
        raise RuntimeError("distributed reduce: span planning failed on "
                           "host 0")
    mine = assign_spans(broadcast_plan(plan, timeout_s=timeout_s))
    row = np.zeros(1 + payload_len, np.float64)
    try:
        row[1:] = local_reducer(mine)
        row[0] = 1.0
    except Exception as e:  # noqa: BLE001 — must reach the collective
        err = e
        row[:] = 0.0
    g = guarded_allgather(row, "distributed reduce: result rows",
                          timeout_s=timeout_s)
    if err is not None:
        raise err
    if (g[:, 0] < 1).any():
        raise RuntimeError("distributed reduce failed on another host")
    return g[:, 1:]


def _local_mesh():
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    return make_mesh(devices=jax.local_devices())


def distributed_flagstat(path: str, config=None, header=None):
    """Whole-file flagstat across a multi-host ``jax.distributed`` job;
    single-process calls degrade to plain flagstat_file.  Flagstat
    counters are sum-combinable, so the combine is one addition."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS
    from hadoop_bam_tpu.parallel.pipeline import (
        flagstat_file, pipeline_span_count,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached

    config = DEFAULT_CONFIG if config is None else config
    if header is None:
        header, _ = read_bam_header(path)
    if jax.process_count() == 1:
        return flagstat_file(path, config=config, header=header)

    def plan():
        n = pipeline_span_count(path, jax.device_count(), config)
        return plan_spans_cached(path, header, config, num_spans=n)

    # the circuit breaker trips HOST-LOCALLY (fraction over this host's
    # assigned spans) — safe against stranding peers because local() runs
    # inside _multihost_reduce's failure-flag phase: a CircuitBreakerError
    # rides the ok/failed allgather and every host raises
    quarantine = QuarantineManifest()

    def local(mine):
        stats = flagstat_file(path, mesh=_local_mesh(), config=config,
                              header=header, spans=mine,
                              quarantine=quarantine)
        return np.asarray([stats[k] for k in FLAGSTAT_FIELDS], np.float64)

    tot = _multihost_reduce(plan, local, len(FLAGSTAT_FIELDS),
                            timeout_s=collective_timeout(config)
                            ).sum(axis=0)
    out = {k: int(v) for k, v in zip(FLAGSTAT_FIELDS, tot)}
    # reduce-side manifest merge: every host reports the same union of
    # skipped spans (runs as its own collective AFTER the stat reduce, in
    # the same order on all hosts)
    from hadoop_bam_tpu.parallel.pipeline import _attach_quarantine
    return _attach_quarantine(out, merge_quarantine_manifests(quarantine))


def distributed_seq_stats(path: str, config=None, header=None,
                          geometry=None):
    """Multi-host seq_stats_file: counts and histograms sum; the means
    combine weighted by each host's read count."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.seq_pallas import N_CODES
    from hadoop_bam_tpu.parallel.pipeline import (
        pipeline_span_count, seq_stats_file,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached

    config = DEFAULT_CONFIG if config is None else config
    if header is None:
        header, _ = read_bam_header(path)
    if jax.process_count() == 1:
        return seq_stats_file(path, config=config, header=header,
                              geometry=geometry)

    def plan():
        n = pipeline_span_count(path, jax.device_count(), config)
        return plan_spans_cached(path, header, config, num_spans=n)

    quarantine = QuarantineManifest()

    def local(mine):
        return _pack_seq_stats(seq_stats_file(
            path, mesh=_local_mesh(), config=config, header=header,
            spans=mine, geometry=geometry, quarantine=quarantine))

    out = _combine_seq_stats(_multihost_reduce(
        plan, local, 3 + N_CODES,
        timeout_s=collective_timeout(config)))
    from hadoop_bam_tpu.parallel.pipeline import _attach_quarantine
    return _attach_quarantine(out, merge_quarantine_manifests(quarantine))


def _pack_seq_stats(s) -> np.ndarray:
    """One host's seq stats as a sum-combinable row: counts plus
    n-weighted means (the exact inverse of _combine_seq_stats)."""
    n = float(s["n_reads"])
    return np.concatenate([
        [n, s["mean_gc"] * n, s["mean_qual"] * n],
        np.asarray(s["base_hist"], np.float64)])


def _combine_seq_stats(rows: np.ndarray) -> dict:
    g = rows.sum(axis=0)
    n = max(g[0], 1.0)
    return {"n_reads": int(g[0]), "mean_gc": float(g[1] / n),
            "mean_qual": float(g[2] / n),
            "base_hist": g[3:].astype(np.int64)}


def distributed_fastq_seq_stats(path: str, config=None, geometry=None):
    """Multi-host fastq_seq_stats_file (FASTQ/QSEQ): same weighted
    combine as distributed_seq_stats, over byte-span plans."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.ops.seq_pallas import N_CODES
    from hadoop_bam_tpu.parallel.pipeline import (
        QSEQ_EXTS, fastq_seq_stats_file, pipeline_span_count,
    )

    config = DEFAULT_CONFIG if config is None else config
    if jax.process_count() == 1:
        return fastq_seq_stats_file(path, config=config, geometry=geometry)

    def plan():   # runs on host 0 only
        from hadoop_bam_tpu.api.read_datasets import open_fastq, open_qseq
        opener = open_qseq if path.lower().endswith(QSEQ_EXTS) \
            else open_fastq
        n = pipeline_span_count(path, jax.device_count(), config)
        return opener(path, config).spans(num_spans=n)

    def local(mine):
        return _pack_seq_stats(fastq_seq_stats_file(
            path, mesh=_local_mesh(), config=config, geometry=geometry,
            spans=mine))

    return _combine_seq_stats(_multihost_reduce(
        plan, local, 3 + N_CODES,
        timeout_s=collective_timeout(config)))


def distributed_cram_seq_stats(path: str, config=None, geometry=None):
    """Multi-host cram_seq_stats_file: same weighted combine as the
    other seq-stats drivers, over container-aligned byte-span plans."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.ops.seq_pallas import N_CODES
    from hadoop_bam_tpu.parallel.pipeline import (
        cram_seq_stats_file, pipeline_span_count,
    )

    config = DEFAULT_CONFIG if config is None else config
    if jax.process_count() == 1:
        return cram_seq_stats_file(path, config=config, geometry=geometry)

    def plan():   # runs on host 0 only
        from hadoop_bam_tpu.api.cram_dataset import open_cram
        n = pipeline_span_count(path, jax.device_count(), config)
        return open_cram(path, config).spans(num_spans=n)

    def local(mine):
        return _pack_seq_stats(cram_seq_stats_file(
            path, mesh=_local_mesh(), config=config, geometry=geometry,
            spans=mine))

    return _combine_seq_stats(_multihost_reduce(
        plan, local, 3 + N_CODES,
        timeout_s=collective_timeout(config)))


def distributed_variant_stats(path: str, config=None, header=None):
    """Multi-host variant_stats_file: counts sum; mean_af combines
    weighted by n_af; per-sample call rates by n_variants."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.parallel.pipeline import pipeline_span_count
    from hadoop_bam_tpu.parallel.variant_pipeline import (
        variant_stats_file,
    )

    config = DEFAULT_CONFIG if config is None else config
    if jax.process_count() == 1:
        return variant_stats_file(path, config=config, header=header)
    ds = open_vcf(path, config)        # one open: header + span planner
    if header is None:
        header = ds.header
    n_samples = header.n_samples

    def plan():
        n = pipeline_span_count(path, jax.device_count(), config)
        return ds.spans(num_spans=n)

    def local(mine):
        s = variant_stats_file(path, mesh=_local_mesh(), config=config,
                               header=header, spans=mine)
        nv = float(s["n_variants"])
        return np.concatenate([
            [nv, s["n_snp"], s["n_pass"], s["n_af"],
             s["mean_af"] * s["n_af"]],
            np.asarray(s["sample_callrate"], np.float64) * nv])

    g = _multihost_reduce(plan, local, 5 + n_samples,
                          timeout_s=collective_timeout(config)).sum(axis=0)
    nv = int(g[0])
    return {"n_variants": nv, "n_snp": int(g[1]), "n_pass": int(g[2]),
            "mean_af": float(g[4] / max(g[3], 1.0)), "n_af": int(g[3]),
            "sample_callrate": g[5:] / max(nv, 1)}


def distributed_coverage(path: str, region, config=None, header=None,
                         max_cigar: int = 64) -> np.ndarray:
    """Multi-host coverage_file: each host piles up only its assigned
    spans over the SAME window, and per-base depths sum exactly across
    hosts (each record is decoded on exactly one host).

    The combine allgathers one float64 row per host of ``window``
    entries, so the per-call window is capped at 2^24 bases (128 MB/row)
    — tile larger regions across calls exactly like the CLI does.
    Single-process calls degrade to plain coverage_file."""
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.pipeline import coverage_file
    from hadoop_bam_tpu.split.intervals import Interval, resolve_interval

    config = DEFAULT_CONFIG if config is None else config
    if header is None:
        header, _ = read_bam_header(path)
    if jax.process_count() == 1:
        return coverage_file(path, region, config=config, header=header,
                             max_cigar=max_cigar)
    if not isinstance(region, Interval):
        region = resolve_interval(region, header.ref_names)
    if region.rname not in header.ref_names:
        raise ValueError(f"region reference {region.rname!r} not in header")
    ref_len = header.ref_lengths[header.ref_names.index(region.rname)]
    end = min(region.end, ref_len)
    window = end - region.start + 1
    if window <= 0:
        raise ValueError(f"empty region {region}")
    if window > (1 << 24):
        raise ValueError(f"distributed region spans {window} bases; the "
                         "per-call cap is 2^24 — tile larger regions "
                         "across calls")
    region = Interval(region.rname, region.start, end)

    def plan():
        # the same plan coverage_file builds itself: .bai-trimmed chunks
        # when a sidecar exists, whole-file pipeline-grain spans otherwise
        from hadoop_bam_tpu.parallel.pipeline import pipeline_span_count
        from hadoop_bam_tpu.split.bai import plan_interval_spans
        from hadoop_bam_tpu.split.planners import plan_spans_cached

        spans = plan_interval_spans(path, [region], header)
        if spans is None:
            n = pipeline_span_count(path, jax.device_count(), config)
            spans = plan_spans_cached(path, header, config, num_spans=n)
        return spans

    def local(mine):
        depth = coverage_file(path, region, mesh=_local_mesh(),
                              config=config, header=header, spans=mine,
                              max_cigar=max_cigar)
        return np.asarray(depth, np.float64)

    g = _multihost_reduce(plan, local, window,
                          timeout_s=collective_timeout(config)).sum(axis=0)
    return g.astype(np.int32)


def retry_span(decode_fn, span: FileVirtualSpan, attempts: int = 3,
               policy: Optional[RetryPolicy] = None):
    """Span-level retry — the framework's failure-recovery unit, now
    fault-classified via the shared ``call_with_retry`` core: only
    TRANSIENT failures are re-attempted (with the policy's backoff);
    corruption and plan errors raise on the first attempt (re-decoding
    the same corrupt bytes can never heal them)."""
    from hadoop_bam_tpu.utils.resilient import call_with_retry

    if policy is None:
        policy = RetryPolicy(retries=max(0, attempts - 1))
    return call_with_retry(lambda: decode_fn(span), policy,
                           what=f"decode of span {span}",
                           counter="pipeline.transient_retries")
