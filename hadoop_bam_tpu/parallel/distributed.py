"""Multi-host coordination: init, single-planner broadcast, span assignment.

The reference's "distributed backend" was Hadoop's (SURVEY.md section 2.9):
HDFS for placement, YARN for scheduling, one client-side getSplits() whose
result rode the job config to every task.  The TPU rebuild keeps that shape:

- ``initialize()`` — jax.distributed bootstrap (no-op single-host);
- ``broadcast_plan()`` — host 0 plans spans (guessers/index probing do real
  I/O and inflation, so they must run once, not per host — the analog of
  client-side split planning at job submission), every host receives the
  JSON-serialized plan over the ICI/DCN collective fabric;
- ``assign_spans()`` — contiguous per-host slices (locality: each host
  fetches only its slice's byte ranges), then per-device groups inside
  parallel/pipeline.py.

Failure recovery mirrors the reference (SURVEY.md section 5): spans are
self-describing and decode is idempotent/side-effect-free, so any span can be
re-decoded anywhere; ``retry_span`` is a plain re-invoke.
"""
from __future__ import annotations

import json
from typing import List, Optional, Sequence

import jax
import numpy as np

from hadoop_bam_tpu.split.spans import FileVirtualSpan


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> None:
    """Bring up jax.distributed when configured; safe no-op otherwise."""
    if coordinator_address is None and num_processes is None:
        return  # single-host / env-driven auto-init
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def process_count() -> int:
    return jax.process_count()


def process_index() -> int:
    return jax.process_index()


def broadcast_plan(spans: Optional[Sequence[FileVirtualSpan]],
                   max_bytes: int = 1 << 24) -> List[FileVirtualSpan]:
    """Host 0 passes its plan; other hosts pass None and receive it.

    Uses a fixed-size uint8 buffer through broadcast_one_to_all (the payload
    must have identical shape on all hosts).
    """
    if jax.process_count() == 1:
        assert spans is not None
        return list(spans)
    from jax.experimental import multihost_utils

    if jax.process_index() == 0:
        payload = json.dumps([s.to_dict() for s in spans]).encode()
        if len(payload) + 8 > max_bytes:
            raise ValueError("plan too large for broadcast buffer")
        buf = np.zeros(max_bytes, dtype=np.uint8)
        buf[:8] = np.frombuffer(np.int64(len(payload)).tobytes(), np.uint8)
        buf[8:8 + len(payload)] = np.frombuffer(payload, np.uint8)
    else:
        buf = np.zeros(max_bytes, dtype=np.uint8)
    out = multihost_utils.broadcast_one_to_all(buf)
    out = np.asarray(out)
    n = int(np.frombuffer(out[:8].tobytes(), np.int64)[0])
    plan = json.loads(out[8:8 + n].tobytes().decode())
    return [FileVirtualSpan.from_dict(d) for d in plan]


def assign_spans(spans: Sequence[FileVirtualSpan],
                 index: Optional[int] = None,
                 count: Optional[int] = None) -> List[FileVirtualSpan]:
    """Contiguous per-host slice, balanced by compressed size."""
    index = jax.process_index() if index is None else index
    count = jax.process_count() if count is None else count
    if count == 1:
        return list(spans)
    sizes = np.asarray([max(s.compressed_size, 1) for s in spans],
                       dtype=np.float64)
    cum = np.cumsum(sizes)
    total = cum[-1]
    lo, hi = total * index / count, total * (index + 1) / count
    out = [s for s, c, z in zip(spans, cum, sizes)
           if lo < c - z / 2 <= hi]  # midpoint rule: every span exactly once
    return out


def distributed_flagstat(path: str, config=None, header=None):
    """Whole-file flagstat across a multi-host ``jax.distributed`` job.

    The reference shape (SURVEY.md sections 2.9/3.2): client-side
    ``getSplits()`` once, map tasks reduce their own splits, one final
    combine.  Host 0 plans and broadcasts the span list; each process
    decodes ONLY its ``assign_spans`` share over its local devices
    (flagstat counters are sum-combinable, so no cross-host collective
    is needed until the end); the per-host vectors combine with one
    allgather.  Single-process calls degrade to plain flagstat_file.
    """
    from hadoop_bam_tpu.config import DEFAULT_CONFIG
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.parallel.pipeline import (
        flagstat_file, pipeline_span_count,
    )
    from hadoop_bam_tpu.split.planners import plan_spans_cached

    config = DEFAULT_CONFIG if config is None else config
    if header is None:
        header, _ = read_bam_header(path)
    if jax.process_count() == 1:
        return flagstat_file(path, config=config, header=header)
    from jax.experimental import multihost_utils

    # failure-flag convention (as in mesh_sort): a raise on one host
    # before a collective would strand the others in it, so every phase
    # reaches its collective and ships an ok/failed flag instead
    plan = None
    plan_err = None
    if jax.process_index() == 0:   # only the planner needs the file size
        try:
            n_spans = pipeline_span_count(path, jax.device_count(), config)
            plan = plan_spans_cached(path, header, config,
                                     num_spans=n_spans)
        except Exception as e:  # noqa: BLE001 — must reach the collective
            plan_err = e
    ok = np.asarray([0 if plan_err is not None else 1], np.int32)
    g_ok = np.asarray(multihost_utils.process_allgather(ok))
    if plan_err is not None:
        raise plan_err
    if int(g_ok.min()) == 0:
        raise RuntimeError("distributed flagstat: span planning failed "
                           "on host 0")
    spans = broadcast_plan(plan)
    mine = assign_spans(spans)
    mesh = make_mesh(devices=jax.local_devices())
    stat_err = None
    vec = np.full(len(FLAGSTAT_FIELDS), -1, np.int64)   # failure sentinel
    try:
        stats = flagstat_file(path, mesh=mesh, config=config,
                              header=header, spans=mine)
        vec = np.asarray([stats[k] for k in FLAGSTAT_FIELDS], np.int64)
    except Exception as e:  # noqa: BLE001 — must reach the collective
        stat_err = e
    g = np.asarray(multihost_utils.process_allgather(vec))
    if stat_err is not None:
        raise stat_err
    if (g < 0).any():
        raise RuntimeError("distributed flagstat failed on another host")
    return {k: int(v) for k, v in zip(FLAGSTAT_FIELDS, g.sum(axis=0))}


def retry_span(decode_fn, span: FileVirtualSpan, attempts: int = 3):
    """Span-level retry — the framework's failure-recovery unit."""
    last: Exception
    for _ in range(attempts):
        try:
            return decode_fn(span)
        except Exception as e:  # noqa: BLE001 — deliberate blanket retry
            last = e
    raise last
