"""The sharded decode pipeline: spans -> host inflate -> device SoA batches.

This is the TPU rebuild of the reference's read hot path (SURVEY.md section
3.2): where a map task ran ``BAMRecordReader.nextKeyValue()`` per record, a
mesh step consumes one *span batch* — per-device inflated bytes + record
offsets, static shapes — and unpacks/reduces on all devices at once:

    plan (once, host 0)                 hb/BAMInputFormat.getSplits
    fetch + inflate span (host threads) BlockCompressedInputStream + zlib JNI
    walk record offsets (host/native)   implicit in per-record decode
    unpack fields + compute (device)    htsjdk BAMRecordCodec.decode + mapper
    psum stats over the data axis       MR shuffle/reduce

Host stages for batch k+1 overlap device compute for batch k via a prefetch
thread pool (the HBM-feed analog of MapReduce's record-ahead buffering).
"""
from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import functools
import logging
import os
import threading
import time
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hadoop_bam_tpu.parallel.mesh import shard_map
from hadoop_bam_tpu.parallel.staging import (
    FeedPipeline, StagingRing, TileSpec, _block_in_flight, bucket_cap,
)

from hadoop_bam_tpu.config import (
    DEFAULT_CONFIG, HBamConfig, resolve_inflate_backend,
)
# plane gating lives in plan/executor.py (the ONE predicate table; the
# planroute lint rule PL101 keeps gate conditionals out of this module).
# _use_fused/_fused_stream_gate keep their historical names here for the
# span-level decoders and the existing import surface.
from hadoop_bam_tpu.plan.executor import (  # noqa: F401 — re-exports
    FLAGSTAT_DAG, PAYLOAD_DAG, _fused_stream_gate, _use_fused,
    host_backend_for, select_plane,
)
from hadoop_bam_tpu.plan.ir import SourceIR
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.ops import inflate as inflate_ops
from hadoop_bam_tpu.ops.flagstat import flagstat_from_columns
from hadoop_bam_tpu.ops.unpack_bam import (
    ALL_FIELDS, FLAGSTAT_PROJECTION, PREFIX, projection_ranges,
    projection_row_bytes, unpack_fixed_fields, unpack_fixed_fields_tile,
    unpack_projected_tile,
)
from hadoop_bam_tpu.resilience import chaos
from hadoop_bam_tpu.resilience.domains import (
    DemotionLadder, check_quarantine_gate, decode_ladder,
    quarantine_run_ok,
)
from hadoop_bam_tpu.split.planners import plan_bam_spans
from hadoop_bam_tpu.split.spans import FileVirtualSpan
from hadoop_bam_tpu.utils import errors as hberrors
from hadoop_bam_tpu.utils.errors import PlanError, classify_error
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.pools import (
    decode_pool, decode_pool_size, submit as pool_submit,
)
from hadoop_bam_tpu.utils.resilient import (
    QuarantineManifest, RetryPolicy, RetryingByteSource,
)
from hadoop_bam_tpu.utils.seekable import as_byte_source, scoped_byte_source

logger = logging.getLogger(__name__)


@dataclasses.dataclass(frozen=True)
class DecodeGeometry:
    """Static shapes of one device's slice of a span batch (jit contract)."""
    bytes_cap: int = 1 << 24       # inflated bytes per device per step (span mode)
    records_cap: int = 1 << 18     # record offsets per device per step
    tile_records: int = 1 << 18    # records per device per step (prefix-tile mode)

    def round_trip_bytes(self) -> int:
        return self.bytes_cap + 4 * self.records_cap


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class PayloadGeometry:
    """Static shapes of the tensor-batch feed (seq/qual payload tiles).

    Strides round up to 32 bytes — TRANSFER-compact, not lane-aligned:
    the host->device link is the scarce resource on every measured
    config (tunnel ~48 MB/s; PCIe hosts still pay per byte), while
    Mosaic pads the lane dimension in VMEM for free, so shipping
    128-byte-aligned rows only inflates H2D traffic (was 388 B/read
    for 151 bp reads; compact strides make it 260).  Reads longer than
    max_len are truncated on pack (full l_seq stays available in the
    prefix columns).
    """
    max_len: int = 160             # bases per read kept on device
    tile_records: int = 1 << 16    # records per device per step: each
                                   # dispatch costs ~100 ms on the
                                   # tunneled link, so fewer+larger
                                   # tiles win (measured +25%); 64k
                                   # reads/tile is ~17 MB staged
    block_n: int = 256             # Pallas record-tile height
    fixed_shape: bool = False      # True: the FINAL partial batch pads
                                   # to tile_records instead of
                                   # shrinking to a dispatch bucket —
                                   # for consumers that preallocate by
                                   # tile_records (costs padding
                                   # transfer on the last batch only)

    @property
    def seq_stride(self) -> int:
        return _round_up((self.max_len + 1) // 2, 32)

    @property
    def qual_stride(self) -> int:
        return _round_up(self.max_len, 32)


@dataclasses.dataclass
class HostSpanBatch:
    """Host-side decoded span group, ready to stack for n devices."""
    data: np.ndarray       # [n_dev, bytes_cap] uint8
    offsets: np.ndarray    # [n_dev, records_cap] int32
    n_records: np.ndarray  # [n_dev] int32
    voffsets: List[np.ndarray]  # per-device per-record virtual offsets


def _fetch_span_raw(src, span: FileVirtualSpan) -> Tuple[bytes, int, int]:
    """Fetch one span's compressed bytes: the whole blocks in
    [start_c, end_c) plus the block AT end_c when the span ends inside it
    (end_u > 0) — reading it up front folds it into one batched-inflate
    call instead of a per-block Python zlib + whole-buffer concatenate
    afterwards.  Returns (raw, end_block_size, next_c) where ``next_c`` is
    the compressed offset of the first block past the fetched bytes."""
    from hadoop_bam_tpu.formats import bgzf

    start_c, start_u = span.start
    end_c, end_u = span.end
    with METRICS.span("bam.fetch_wall", nbytes=max(end_c - start_c, 0)):
        raw = src.pread(start_c, max(end_c - start_c, 0))
        end_block_size = 0
        if end_u > 0 and end_c < src.size:
            head = src.pread(end_c, bgzf.MAX_BLOCK_SIZE)
            info = bgzf.parse_block_header(head, 0)
            end_block_size = info.block_size
            raw = raw + head[:end_block_size]
    next_c = (end_c + end_block_size) if raw else start_c
    return raw, end_block_size, next_c


def _decode_span_core(source, span: FileVirtualSpan,
                      check_crc: bool = False,
                      inflate_backend: str = "auto",
                      packed_walker=None,
                      want_voffs: bool = True,
                      ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 Optional[np.ndarray]]:
    """Fetch + inflate one span and walk its records (host stage).

    Returns (data, offsets, voffsets, rows) — unpadded; ``rows`` is the
    packed row tile when ``packed_walker`` is given (else None).  Only
    records *starting* inside the span are owned (reference reader
    contract); the final record may extend into the following blocks, which
    are fetched as needed.

    This is the TWO-PASS path (inflate the whole span to DRAM, then walk
    it again) — the byte-identity oracle the fused single-pass path
    (``_decode_span_fused``) is pinned against, and the fallback when the
    native library is unavailable or ``config.use_fused_decode`` is off.
    """
    from hadoop_bam_tpu.formats import bgzf

    src = as_byte_source(source)
    start_c, start_u = span.start
    end_c, end_u = span.end
    METRICS.count("pipeline.spans")

    raw, end_block_size, next_c = _fetch_span_raw(src, span)
    if raw:
        table = inflate_ops.block_table(raw)
        with METRICS.timer("pipeline.inflate"), \
                METRICS.span("bam.inflate_wall", nbytes=len(raw)):
            data, ubase = inflate_ops.inflate_span(raw, table,
                                                   backend=inflate_backend)
        METRICS.count("pipeline.blocks", int(table["isize"].size))
        METRICS.count("pipeline.inflated_bytes", int(data.size))
        if check_crc:
            # a separate third sweep over the inflated bytes — the fused
            # path folds this into its single visit for ~free
            with METRICS.timer("pipeline.crc"):
                inflate_ops.verify_crcs(raw, table, data, ubase)
        abs_coffs = table["coffset"] + start_c
    else:
        data = np.empty(0, dtype=np.uint8)
        ubase = np.empty(0, dtype=np.int64)
        abs_coffs = np.empty(0, dtype=np.int64)

    def extend_past(tail: int) -> None:
        """Fetch + inflate the following blocks until the record starting
        at ``tail`` (cut at the buffer end) is complete, accumulating in a
        chunk list with ONE final concatenate — per-block np.concatenate
        re-copied the whole span each iteration (quadratic on long
        multi-block record chains)."""
        nonlocal data, ubase, abs_coffs, next_c
        chunks: List[np.ndarray] = [data]
        new_bases: List[int] = []
        new_coffs: List[int] = []
        cur = data.size

        def fetch_block() -> None:
            nonlocal cur, next_c
            head = src.pread(next_c, bgzf.MAX_BLOCK_SIZE)
            info = bgzf.parse_block_header(head, 0)
            extra = bgzf.inflate_block(head, info, check_crc=check_crc)
            new_bases.append(cur)
            new_coffs.append(next_c)
            chunks.append(np.frombuffer(extra, np.uint8))
            cur += len(extra)
            next_c += info.block_size

        def read_bytes(pos: int, n: int) -> bytes:
            out = bytearray()
            base = 0
            for c in chunks:
                lo = pos - base
                if 0 <= lo < c.size and len(out) < n:
                    out += c[lo:lo + n - len(out)].tobytes()
                elif lo < 0 and len(out) < n:
                    out += c[:n - len(out)].tobytes()
                base += c.size
            return bytes(out)

        # the 4-byte block_size field itself may be cut
        while cur < tail + 4 and next_c < src.size:
            fetch_block()
        if cur >= tail + 4:
            bs = int.from_bytes(read_bytes(tail, 4), "little", signed=True)
            needed = tail + 4 + max(bs, 0)
            while cur < needed and next_c < src.size:
                fetch_block()
        if new_bases:
            ubase = np.concatenate([ubase, np.asarray(new_bases, np.int64)])
            abs_coffs = np.concatenate(
                [abs_coffs, np.asarray(new_coffs, np.int64)])
            data = np.concatenate(chunks)

    # 2. The span may end inside the block at end_c (already inflated as the
    #    final table entry): its first end_u inflated bytes still hold
    #    records owned by this span.
    if end_block_size:
        end_inflated = int(ubase[-1]) + end_u
    else:
        end_inflated = data.size

    # 3+4. Walk record boundaries; own records starting in
    #    [start_u, end_inflated).  If the walk's tail (first incomplete
    #    record) starts before end_inflated, an owned record is cut at the
    #    buffer end — append following blocks and re-walk until it completes
    #    (reference reader contract: the last record may extend past the
    #    split's end voffset).
    rows = None
    while True:
        with METRICS.timer("pipeline.walk"), \
                METRICS.span("bam.walk_wall"):
            if packed_walker is not None:
                rows, offs, tail = packed_walker(data, start_u, end_inflated)
            else:
                offs, tail = inflate_ops.walk_records(data, start=start_u)
        if tail < end_inflated and next_c < src.size:
            prev_size = data.size
            extend_past(tail)
            if data.size == prev_size:
                break  # no more bytes to fetch: truncated file
            continue
        break
    keep = int(np.searchsorted(offs, max(end_inflated, 1)))  # offs ascend
    offs = offs[:keep]
    if rows is not None:
        rows = rows[:keep]
    METRICS.count("pipeline.records", int(offs.size))

    # 5. Map record offsets back to packed virtual offsets.
    if offs.size and want_voffs:
        blk = np.searchsorted(ubase, offs, side="right") - 1
        voffs = (abs_coffs[blk].astype(np.uint64) << np.uint64(16)) | \
            (offs - ubase[blk]).astype(np.uint64)
    else:
        voffs = np.empty(0, dtype=np.uint64)
    return data, offs, voffs, rows


# ---------------------------------------------------------------------------
# Fused single-pass span decode (native hbam_fused_*: ops/inflate.py
# FusedSpanDecode).  One streamed native pass replaces the two-pass path's
# three DRAM sweeps (inflate -> walk re-read -> optional CRC sweep): each
# native worker inflates a run of decode_chunk_blocks BGZF blocks and the
# record walk + projection pack + CRC fold consume those bytes cache-hot.
# The two-pass _decode_span_core stays as the byte-identity oracle and the
# automatic fallback (no native library, non-native backends,
# config.use_fused_decode=False, and the rare cut-final-record span).
# ---------------------------------------------------------------------------

def _close_stream(item) -> None:
    """_iter_windowed cleanup hook: join a fused chunk stream's native
    workers; buffered results (plain arrays/tuples) need nothing."""
    close = getattr(item, "close", None)
    if close is not None:
        close()


def _flatten_span_stream(items) -> Iterator[Tuple[np.ndarray, ...]]:
    """Uniform FeedPipeline input from mixed decode results: buffered
    arrays/tuples pass through as one-span items; fused chunk streams
    flatten into their per-chunk tuples."""
    for item in items:
        if isinstance(item, np.ndarray):
            yield (item,)
        elif isinstance(item, tuple):
            yield item
        else:
            yield from item


def _stream_window(window: int) -> int:
    """Cap the in-flight window for STREAMED fused decode: each windowed
    span is a live multi-threaded native job (the pool task only fetches
    and starts it), so the pool-sized window that bounds buffered decodes
    would oversubscribe the host several-fold here."""
    return min(window, max(2, 2 * (os.cpu_count() or 1)))


def _fused_off(config: Optional[HBamConfig]) -> HBamConfig:
    """A config copy with the fused path disabled — the streamed paths'
    tail-extension fallback must run the two-pass oracle, not re-run the
    fused decode it just finished."""
    cfg = config if config is not None else DEFAULT_CONFIG
    return dataclasses.replace(cfg, use_fused_decode=False)


def _start_fused_span(src, span: FileVirtualSpan, mode: str, *,
                      sel=None, row_bytes: int = 0,
                      geometry: "Optional[PayloadGeometry]" = None,
                      check_crc: bool = False,
                      config: Optional[HBamConfig] = None):
    """Fetch one span and start its fused native decode job.

    The fetch runs HERE, on the caller's thread — transient I/O faults
    surface inside the decode_with_retry boundary even when the chunk
    stream is consumed later.  Returns (dec, end_inflated, next_c, table)
    or None for an empty span (the two-pass path disposes of those)."""
    raw, end_block_size, next_c = _fetch_span_raw(src, span)
    if not raw:
        return None
    table = inflate_ops.block_table(raw)
    isize = table["isize"]
    total = int(isize.sum())
    end_inflated = (total - int(isize[-1]) + span.end[1]) if end_block_size \
        else total
    cfg = config if config is not None else DEFAULT_CONFIG
    kwargs = {}
    if mode == "rows":
        kwargs = dict(sel=sel, row_stride=row_bytes)
    elif mode == "payload":
        kwargs = dict(max_len=geometry.max_len,
                      seq_stride=geometry.seq_stride,
                      qual_stride=geometry.qual_stride)
    dec = inflate_ops.FusedSpanDecode(
        raw, table, start=span.start[1], stop=end_inflated, mode=mode,
        check_crc=check_crc,
        chunk_blocks=max(1, int(cfg.decode_chunk_blocks)),
        **kwargs)
    return dec, end_inflated, next_c, table


def _fused_span_counts(dec, table, n: int) -> None:
    """Span bookkeeping on fused-decode success (the two-pass core counts
    these itself; a fused span that falls back must not double-count)."""
    METRICS.count("pipeline.spans")
    METRICS.count("pipeline.blocks", int(table["isize"].size))
    METRICS.count("pipeline.inflated_bytes", int(dec.data.size))
    METRICS.count("pipeline.records", n)


def _decode_span_fused(source, span: FileVirtualSpan, mode: str, *,
                       check_crc: bool = False, sel=None, row_bytes: int = 0,
                       geometry: "Optional[PayloadGeometry]" = None,
                       want_voffs: bool = True,
                       config: Optional[HBamConfig] = None):
    """Buffered fused decode of one span — the drop-in replacement for
    ``_decode_span_core`` + packed walker.

    Returns (data, offs, voffs, outs) with ``outs`` mode-dependent
    (rows / (prefix, seq, qual) / None), or **None** when this span needs
    the two-pass path: an empty span, or a final owned record extending
    past the span's inflated blocks (the tail-extension case — a record
    crossing the end block's boundary, well under 1% of spans; the oracle
    path re-decodes those whole for simplicity)."""
    src = as_byte_source(source)
    started = _start_fused_span(src, span, mode, sel=sel,
                                row_bytes=row_bytes, geometry=geometry,
                                check_crc=check_crc, config=config)
    if started is None:
        return None
    dec, end_inflated, next_c, table = started
    try:
        with METRICS.timer("pipeline.fused_decode"), \
                METRICS.span("bam.fused_decode_wall",
                             nbytes=int(dec.data.size)):
            n, tail = dec.run()
    except Exception:
        # counter parity with the two-pass path (which counts spans at
        # entry): a span that FAILED decode still counts as attempted —
        # the success/fallback paths count elsewhere, exactly once
        METRICS.count("pipeline.spans")
        raise
    if tail < end_inflated and next_c < src.size:
        return None             # cut final record: two-pass oracle path
    _fused_span_counts(dec, table, n)
    offs = dec.offsets[:n]
    if n and want_voffs:
        abs_coffs = table["coffset"] + span.start[0]
        blk = np.searchsorted(dec.ubase, offs, side="right") - 1
        voffs = (abs_coffs[blk].astype(np.uint64) << np.uint64(16)) | \
            (offs - dec.ubase[blk]).astype(np.uint64)
    else:
        voffs = np.empty(0, dtype=np.uint64)
    if mode == "rows":
        outs = dec.rows[:n]
    elif mode == "payload":
        outs = (dec.prefix[:n], dec.seq[:n], dec.qual[:n])
    else:
        outs = None
    return dec.data, offs, voffs, outs


class _FusedChunkStream:
    """One span's streamed fused decode: iterate for row-array tuples,
    ``close()`` to join the native workers deterministically (works even
    when iteration never started — the GC ``__del__`` backstop is for
    interpreter teardown, not the normal abandon path)."""

    __slots__ = ("_dec", "_gen")

    def __init__(self, dec, gen):
        self._dec = dec
        self._gen = gen

    def __iter__(self):
        return self._gen

    def close(self) -> None:
        self._gen.close()
        self._dec.finish(check=False)


def _iter_fused_span_chunks(src, span: FileVirtualSpan, mode: str, *,
                            sel=None, row_bytes: int = 0,
                            geometry: "Optional[PayloadGeometry]" = None,
                            check_crc: bool = False,
                            config: Optional[HBamConfig] = None,
                            fallback_fn: Optional[Callable] = None):
    """Streamed fused decode: start the span's native job NOW (fetch on
    the caller's thread, inside the retry boundary) and return an iterable
    of packed row-array TUPLES — mode "rows" yields ``(rows,)``, mode
    "payload" ``(prefix, seq, qual)`` — in record order, each yielded the
    moment the native walk publishes it.  Feeding these straight into the
    FeedPipeline means staging-ring tiles for dispatch start packing
    before the span's tail blocks are even inflated.

    The rare cut-final-record span completes through ``fallback_fn`` (the
    two-pass oracle, returning the whole span's packed arrays as a tuple):
    rows ``[n:]`` of its result are appended, so the concatenated stream
    stays byte-identical to the buffered paths.  Corruption raises from
    the iterator (the consumer side) — callers gate streaming off when
    ``skip_bad_spans`` needs span-granular quarantine."""
    src = as_byte_source(src)
    started = _start_fused_span(src, span, mode, sel=sel,
                                row_bytes=row_bytes, geometry=geometry,
                                check_crc=check_crc, config=config)

    def slices(lo: int, hi: int) -> Tuple[np.ndarray, ...]:
        if mode == "rows":
            return (dec.rows[lo:hi],)
        return (dec.prefix[lo:hi], dec.seq[lo:hi], dec.qual[lo:hi])

    if started is None:
        METRICS.count("pipeline.spans")     # empty span, still planned
        return iter(())
    dec, end_inflated, next_c, table = started
    src_size = src.size

    def gen():
        t_prev = time.perf_counter()
        try:
            # the consumption below IS the span's host decode (the
            # native waits are inflate+walk work): accrue it into the
            # same host_decode timer/walls the buffered paths use, with
            # fused_decode as the sub-stage, so the stage taxonomy keeps
            # meaning "all host decode work" under streaming
            with METRICS.timer("pipeline.host_decode"), \
                    METRICS.wall_timer("pipeline.host_decode_wall"), \
                    METRICS.timer("pipeline.fused_decode"), \
                    METRICS.span("bam.fused_decode_wall",
                                 nbytes=int(dec.data.size)):
                for lo, hi in dec.chunks():
                    now = time.perf_counter()
                    # per-chunk handoff latency: the stall a staging
                    # tile pays waiting for its next batch of rows
                    METRICS.observe("pipeline.decode_chunk_s",
                                    now - t_prev)
                    t_prev = now
                    yield slices(lo, hi)
                n, tail = dec.finish()
        except GeneratorExit:
            raise
        except Exception as e:  # noqa: BLE001 — counter parity only
            # streamed corruption raises on the consumer side, outside
            # decode_with_retry — keep the spans/corrupt_spans counters
            # in step with the buffered/two-pass paths (the fallback
            # path below goes through decode_with_retry, which counts
            # its own failures; success counts via _fused_span_counts)
            METRICS.count("pipeline.spans")
            if classify_error(e) == hberrors.CORRUPT:
                METRICS.count("pipeline.corrupt_spans")
            raise
        if tail < end_inflated and next_c < src_size:
            full = fallback_fn()
            rest = tuple(a[n:] for a in full)
            if rest[0].shape[0]:
                yield rest
        else:
            _fused_span_counts(dec, table, n)

    return _FusedChunkStream(dec, gen())


def decode_span_host(source, span: FileVirtualSpan, geometry: DecodeGeometry,
                     check_crc: bool = False,
                     inflate_backend: str = "auto",
                     config: Optional[HBamConfig] = None,
                     ) -> Tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """Span mode: full inflated bytes + offsets padded to geometry caps.

    Returns (data[bytes_cap], offsets[records_cap], n_records, voffsets[n]).
    """
    got = _decode_span_fused(source, span, "offsets", check_crc=check_crc,
                             config=config) \
        if _use_fused(config, inflate_backend) else None
    if got is not None:
        data, offs, voffs, _ = got
    else:
        data, offs, voffs, _ = _decode_span_core(source, span, check_crc,
                                                 inflate_backend)
    n = int(offs.size)
    g = geometry
    if data.size > g.bytes_cap or n > g.records_cap:
        # PlanError: a mis-sized plan is a configuration fault — the retry
        # policy must neither re-decode it nor skip_bad_spans-eat it
        raise PlanError(
            f"span exceeds geometry: {data.size}B/{n} records vs caps "
            f"{g.bytes_cap}B/{g.records_cap} — plan smaller spans")
    out_data = np.zeros(g.bytes_cap, dtype=np.uint8)
    out_data[:data.size] = data
    out_offs = np.zeros(g.records_cap, dtype=np.int32)
    out_offs[:n] = offs
    return out_data, out_offs, n, voffs


def _interval_mask(data: np.ndarray, offs: np.ndarray, header, intervals
                   ) -> np.ndarray:
    """Row keep-mask for interval filtering on the mesh decode paths
    (hb/BAMInputFormat's hadoopbam.bam.intervals record filter): overlap
    test on pos + CIGAR reference span via the columnar batch."""
    from hadoop_bam_tpu.formats.bam import BamBatch
    from hadoop_bam_tpu.split.intervals import batch_overlap_mask

    batch = BamBatch(data, offs.astype(np.int64), header=header)
    return batch_overlap_mask(batch, intervals, header)


def decode_span_prefix_host(source, span: FileVirtualSpan,
                            check_crc: bool = False,
                            inflate_backend: str = "auto",
                            projection: Tuple[str, ...] = ALL_FIELDS,
                            want_voffs: bool = True,
                            intervals=None, header=None,
                            config: Optional[HBamConfig] = None,
                            ) -> Tuple[np.ndarray, np.ndarray]:
    """Prefix mode: pack each owned record's projected columns densely.

    Returns (rows[n, row_bytes] uint8, voffsets[n]).  This is the columnar
    transfer layout: for fixed-field consumers (flagstat, filters, sort
    keys) only the projected bytes cross the host->device link — 36 B/record
    for the full fixed prefix, 11 B for the flagstat projection — instead of
    the whole inflated span (~250 B/record on 150 bp WGS data), and field
    extraction on device needs no gather, the tile is already dense.  With
    the native library, walk + pack is a single C++ pass over the inflated
    bytes.
    """
    from hadoop_bam_tpu.utils import native

    row_bytes = projection_row_bytes(projection)
    ranges = projection_ranges(projection)
    if _use_fused(config, inflate_backend):
        got = _decode_span_fused(source, span, "rows", check_crc=check_crc,
                                 sel=ranges, row_bytes=row_bytes,
                                 want_voffs=want_voffs, config=config)
        if got is not None:
            data, offs, voffs, rows = got
            if intervals and offs.size:
                keep = _interval_mask(data, offs, header, intervals)
                rows = rows[keep]
                if voffs.size:
                    voffs = voffs[keep]
            return rows, voffs
    use_native = native.available()

    def walker(data, start, end_limit):
        if use_native:
            stop = min(int(end_limit), data.size)
            cap = max(16, (stop - start) // 36 + 1)
            rows, offs, tail = native.walk_bam_packed(
                np.ascontiguousarray(data), start, cap, ranges, row_bytes,
                stop=stop)
            return rows, offs, tail
        offs, tail = inflate_ops.walk_records(data, start=start)
        return None, offs, tail

    data, offs, voffs, rows = _decode_span_core(
        source, span, check_crc, inflate_backend, packed_walker=walker,
        want_voffs=want_voffs)
    if rows is None:
        # NumPy fallback: gather the full prefix tile, then slice columns.
        if offs.size == 0:
            rows = np.empty((0, row_bytes), dtype=np.uint8)
        else:
            idx = offs[:, None] + np.arange(PREFIX, dtype=offs.dtype)[None, :]
            tile = data[idx]
            cols = []
            for off, width in ranges:
                cols.append(tile[:, off:off + width])
            rows = np.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]
    if intervals and offs.size:
        keep = _interval_mask(data, offs, header, intervals)
        rows = rows[keep]
        if voffs.size:
            voffs = voffs[keep]
    return rows, voffs


def decode_span_payload_host(source, span: FileVirtualSpan,
                             geometry: PayloadGeometry,
                             check_crc: bool = False,
                             inflate_backend: str = "auto",
                             want_voffs: bool = False,
                             intervals=None, header=None,
                             config: Optional[HBamConfig] = None,
                             ) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                        np.ndarray]:
    """Payload mode: pack prefix + 4-bit seq + qual into dense row tiles.

    Returns (prefix[n, 36], seq[n, seq_stride], qual[n, qual_stride],
    voffsets[n]) — the host half of the tensor-batch feed.  Native path is
    one C++ pass (hbam_walk_bam_payload); the fallback walks offsets and
    packs per record in NumPy.
    """
    from hadoop_bam_tpu.utils import native

    g = geometry
    if _use_fused(config, inflate_backend):
        got = _decode_span_fused(source, span, "payload",
                                 check_crc=check_crc, geometry=g,
                                 want_voffs=want_voffs, config=config)
        if got is not None:
            data, offs, voffs, (prefix, seq, qual) = got
            if intervals and offs.size:
                keep = _interval_mask(data, offs, header, intervals)
                prefix, seq, qual = prefix[keep], seq[keep], qual[keep]
                if voffs.size:
                    voffs = voffs[keep]
            return prefix, seq, qual, voffs
    use_native = native.available()
    out: Dict[str, np.ndarray] = {}

    def walker(data, start, end_limit):
        if not use_native:
            offs, tail = inflate_ops.walk_records(data, start=start)
            return None, offs, tail
        stop = min(int(end_limit), data.size)
        cap = max(16, (stop - start) // 36 + 1)
        prefix, seq, qual, offs, tail = native.walk_bam_payload(
            np.ascontiguousarray(data), start, cap, g.max_len,
            g.seq_stride, g.qual_stride, stop=stop)
        out["prefix"], out["seq"], out["qual"] = prefix, seq, qual
        # rows (= prefix) flows through the core's keep-truncation; seq/qual
        # are truncated identically below from the kept count
        return prefix, offs, tail

    data, offs, voffs, rows = _decode_span_core(
        source, span, check_crc, inflate_backend, packed_walker=walker,
        want_voffs=want_voffs)
    n = int(offs.size)

    def apply_intervals(prefix, seq, qual, voffs):
        if intervals and offs.size:
            keep = _interval_mask(data, offs, header, intervals)
            prefix, seq, qual = prefix[keep], seq[keep], qual[keep]
            if voffs.size:
                voffs = voffs[keep]
        return prefix, seq, qual, voffs

    if rows is not None:
        return apply_intervals(rows, out["seq"][:n], out["qual"][:n],
                               voffs)

    # NumPy fallback: per-record pack from the inflated span.
    prefix = np.zeros((n, PREFIX), dtype=np.uint8)
    seq = np.zeros((n, g.seq_stride), dtype=np.uint8)
    qual = np.zeros((n, g.qual_stride), dtype=np.uint8)
    for i in range(n):
        p = int(offs[i])
        prefix[i] = data[p:p + PREFIX]
        l_read_name = int(data[p + 12])
        n_cigar = int(data[p + 16]) | (int(data[p + 17]) << 8)
        l_seq = int.from_bytes(data[p + 20:p + 24].tobytes(), "little",
                               signed=True)
        bs = int.from_bytes(data[p:p + 4].tobytes(), "little", signed=True)
        seq_off = p + PREFIX + l_read_name + 4 * n_cigar
        nb = (l_seq + 1) // 2
        # same payload-bounds validation as the native walker: a corrupt
        # l_seq must fail loudly, not pack neighboring records' bytes
        if l_seq < 0 or (seq_off - p) + nb + l_seq > 4 + bs:
            raise ValueError("malformed BAM record chain")
        use = min(l_seq, g.max_len)
        seq[i, :(use + 1) // 2] = data[seq_off:seq_off + (use + 1) // 2]
        qual[i, :use] = data[seq_off + nb:seq_off + nb + use]
    return apply_intervals(prefix, seq, qual, voffs)


def stack_span_group(source, spans: Sequence[FileVirtualSpan], n_dev: int,
                     geometry: DecodeGeometry, check_crc: bool = False,
                     executor: Optional[cf.ThreadPoolExecutor] = None,
                     config: Optional[HBamConfig] = None,
                     ) -> HostSpanBatch:
    """Decode up to n_dev spans (threaded) and stack into device-batch shape;
    missing spans become empty shards (zero records)."""
    spans = list(spans)[:n_dev]
    results = [None] * n_dev

    def work(i):
        return decode_span_host(source, spans[i], geometry, check_crc,
                                config=config)

    if executor is None:
        outs = [work(i) for i in range(len(spans))]
    else:
        outs = list(executor.map(work, range(len(spans))))
    data = np.zeros((n_dev, geometry.bytes_cap), dtype=np.uint8)
    offsets = np.zeros((n_dev, geometry.records_cap), dtype=np.int32)
    counts = np.zeros((n_dev,), dtype=np.int32)
    voffs: List[np.ndarray] = [np.empty(0, dtype=np.uint64)] * n_dev
    for i, (d, o, n, v) in enumerate(outs):
        data[i], offsets[i], counts[i], voffs[i] = d, o, n, v
    return HostSpanBatch(data, offsets, counts, voffs)


# ---------------------------------------------------------------------------
# Device steps
# ---------------------------------------------------------------------------

_STEP_CACHE: Dict[Tuple, Callable] = {}


def make_flagstat_step(mesh: Mesh, axis: str = "data") -> Callable:
    """Jitted sharded step: (data [n,D], offsets [n,N], counts [n]) ->
    flagstat dict (replicated scalars, psum over the data axis).

    Cached per (mesh, axis): jax.jit keys on function identity, so rebuilding
    the closure per call would recompile every step (a silent 20-40s per-call
    tax on real TPUs)."""
    key = ("flagstat", tuple(mesh.devices.flat), mesh.axis_names, axis)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS

    def per_device(data, offsets, count):
        # shard_map gives [1, D] slices; drop the leading axis
        data, offsets, count = data[0], offsets[0], count[0]
        cols = unpack_fixed_fields(data, offsets)
        valid = jnp.arange(offsets.shape[0], dtype=jnp.int32) < count
        stats = flagstat_from_columns(cols, valid)
        # one stacked vector, not 16 scalars: a D2H sync per scalar costs
        # ~100ms each over remote-tunnel TPU links
        vec = jnp.stack([stats[k] for k in FLAGSTAT_FIELDS])
        return jax.lax.psum(vec, axis)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(axis)),
                   out_specs=P())
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def make_flagstat_tile_step(mesh: Mesh, axis: str = "data",
                            projection: Tuple[str, ...] = FLAGSTAT_PROJECTION
                            ) -> Callable:
    """Jitted sharded step over dense projected tiles: (tile [n, cap, row],
    counts [n]) -> psum'd flagstat vector.  No gather on device — the host
    packed the tile, so field extraction is strided slicing straight into
    the reductions."""
    key = ("flagstat_tile", tuple(mesh.devices.flat), mesh.axis_names, axis,
           projection)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS

    def per_device(tile, count):
        tile, count = tile[0], count[0]
        cols = unpack_projected_tile(tile, projection)
        valid = jnp.arange(tile.shape[0], dtype=jnp.int32) < count
        stats = flagstat_from_columns(cols, valid)
        vec = jnp.stack([stats[k] for k in FLAGSTAT_FIELDS])
        return jax.lax.psum(vec, axis)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis)),
                   out_specs=P())
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def make_unpack_step(mesh: Mesh, axis: str = "data") -> Callable:
    """Jitted sharded step returning sharded SoA columns + valid mask —
    the feed for downstream mesh compute (the 'mapper' input)."""
    key = ("unpack", tuple(mesh.devices.flat), mesh.axis_names, axis)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    def per_device(data, offsets, count):
        data, offsets, count = data[0], offsets[0], count[0]
        cols = unpack_fixed_fields(data, offsets)
        valid = jnp.arange(offsets.shape[0], dtype=jnp.int32) < count
        cols = dict(cols)
        cols["valid"] = valid
        return jax.tree.map(lambda a: a[None], cols)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(axis)),
                   out_specs=P(axis))
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


# ---------------------------------------------------------------------------
# End-to-end driver
# ---------------------------------------------------------------------------

def iter_span_groups(spans: Sequence[FileVirtualSpan], n_dev: int
                     ) -> Iterator[List[FileVirtualSpan]]:
    spans = list(spans)
    for i in range(0, len(spans), n_dev):
        yield spans[i:i + n_dev]


_ADD = jax.jit(jnp.add)


def parse_config_intervals(config: HBamConfig, header):
    """config.bam_intervals -> parsed Interval list (None when unset)."""
    if not config.bam_intervals:
        return None
    from hadoop_bam_tpu.split.intervals import parse_intervals
    return parse_intervals(config.bam_intervals,
                           header.ref_names if header else None)


def _span_retry_policy(config: HBamConfig) -> RetryPolicy:
    from hadoop_bam_tpu.utils.resilient import span_retry_policy
    return span_retry_policy(config)


def _resilient_source(path, config: HBamConfig):
    """What the decode stages should read through: the plain path, or a
    RetryingByteSource wrap when ``config.io_read_retries`` asks for
    read-level retries (backoff + per-read deadline under the span grain)."""
    r = int(config.io_read_retries or 0)
    if r <= 0 or not isinstance(path, (str, os.PathLike)):
        return path
    return RetryingByteSource(path, RetryPolicy(
        retries=r,
        backoff_base_s=float(config.retry_backoff_base_s),
        backoff_max_s=float(config.retry_backoff_max_s),
        deadline_s=config.io_read_deadline_s))


def decode_with_retry(fn: Callable, span: FileVirtualSpan,
                      config: HBamConfig,
                      quarantine: Optional[QuarantineManifest] = None,
                      policy: Optional[RetryPolicy] = None,
                      ladder: Optional[DemotionLadder] = None):
    """Span-level failure policy (SURVEY.md section 5), fault-classified.

    A span is a self-describing, idempotent unit of work — the retry
    mechanism is re-decoding it, as MapReduce re-runs a map task — but
    unlike the reference, failures are classified (utils/errors.py) and
    each class gets its own policy:

    - TRANSIENT: re-attempted up to ``config.span_retries`` times with
      jittered exponential backoff (``policy`` injectable, so tests assert
      the exact schedule without real sleeps);
    - CORRUPT: fails fast with ZERO re-decodes — a CRC mismatch or
      malformed record chain never heals, re-reading it only wastes the
      budget;
    - PLAN: always raised — a misconfigured run must not be retried or
      quietly skipped as if the data were bad.

    Once the policy is exhausted, ``skip_bad_spans`` decides between
    raising and quarantine+skip: the span is recorded in ``quarantine``
    (file, virtual-offset range, error class, attempts) and None returned.
    Counters: ``pipeline.bad_spans`` ticks ONLY on an actual skip;
    ``pipeline.transient_retries`` counts re-attempts;
    ``pipeline.corrupt_spans`` counts corrupt failures.  The manifest's
    circuit breaker (``config.max_bad_span_fraction``) raises
    CircuitBreakerError when the run has quarantined too much of its plan
    to stay meaningful.

    With a ``ladder`` (resilience/domains.py) the CORRUPT branch grows a
    demotion step and ``fn`` takes ``(span, plane)``: a span failing
    corrupt on plane P re-decodes at the next plane down — byte-identical
    but more battle-tested — instead of failing outright.  Blame is
    oracle-confirmed: only when the LOWER plane succeeds on the same span
    is the failure charged to P's fault domain (repeated charges open
    P's breaker, demoting the whole run until a half-open probe heals
    it); when every plane fails, the bytes — not the plane — are bad,
    no domain is charged, and the classic raise/quarantine applies."""
    if policy is None:
        policy = _span_retry_policy(config)
    last: Optional[BaseException] = None
    kind = hberrors.CORRUPT
    attempts = 0
    transient_tries = 0
    plane = ladder.host_plane() if ladder is not None else None
    blamed: List[Tuple[str, BaseException]] = []
    while attempts <= policy.retries + len(blamed):
        attempts += 1
        try:
            out = fn(span) if ladder is None else fn(span, plane)
            if ladder is not None:
                for bad_plane, exc in blamed:
                    # a lower plane just decoded these bytes: the upper
                    # plane's failure was plane-local — charge it
                    ladder.confirm_failure(bad_plane, exc)
                    METRICS.count("pipeline.span_demotions")
                ladder.record_success(plane)
            return out
        except Exception as e:  # noqa: BLE001 — policy boundary
            last = e
            kind = classify_error(e)
            if kind == hberrors.PLAN:
                raise
            if kind != hberrors.TRANSIENT:
                if ladder is not None:
                    nxt = ladder.next_lower(plane)
                    if nxt is not None and ladder.demotable(plane, e):
                        logger.warning(
                            "span %s failed on the %s plane (%s); "
                            "re-decoding on %s", span, plane, e, nxt)
                        blamed.append((plane, e))
                        plane = nxt
                        continue
                METRICS.count("pipeline.corrupt_spans")
                break
            if transient_tries < policy.retries:
                METRICS.count("pipeline.transient_retries")
                d = policy.delay(transient_tries)
                transient_tries += 1
                logger.debug("transient fault on span %s (attempt %d/%d), "
                             "retrying in %.3fs: %s", span, attempts,
                             policy.retries + 1, d, e)
                policy.sleep(d)
                continue
            break
    if config.skip_bad_spans:
        METRICS.count("pipeline.bad_spans")
        logger.warning("skipping bad span %s after %d attempt(s) [%s]: %s",
                       span, attempts, kind, last)
        if quarantine is not None:
            quarantine.add(span, last, kind, attempts)
            quarantine.check_circuit(config)  # may raise CircuitBreakerError
        return None
    raise last


# how long a QUEUED candidate's hard-timeout anchor is held, as a
# multiple of pool_task_timeout_s: long enough that a backlogged-but-
# healthy pool (queue waits of a few task durations) never false-fires,
# short enough that a fully-wedged pool — where re-submissions can
# never dequeue — still exhausts the budget and surfaces as
# TransientIOError instead of hanging forever
_QUEUED_GRACE = 8.0


def _iter_windowed(pool: cf.ThreadPoolExecutor, items: Sequence,
                   fn: Callable, window: int,
                   cleanup: Optional[Callable] = None,
                   config: Optional[HBamConfig] = None,
                   what: str = "span decode") -> Iterator:
    """Submit ``fn(item)`` to the pool with bounded in-flight futures and
    yield results in order.  Bounds host memory: at most ``window`` decoded
    spans exist at once (a plain list of futures would retain every span's
    rows for the whole run — concurrent.futures keeps results referenced).

    On early close (a consumer abandoning the stream), queued-but-unstarted
    futures are cancelled — the SHARED decode pool (utils/pools.py) never
    shuts down, so without the cancel an abandoned window of decodes would
    keep running to completion for nothing.  ``cleanup`` is called on
    results that already materialized but will never be yielded (the fused
    chunk streams hold live native jobs — closing them joins the workers
    instead of leaving that to GC).

    With a ``config``, the consumer grows the straggler + hang defense
    (jobs/speculate.py):

    - **speculation** (``config.speculative_decode``): a unit outliving
      the job's soft deadline — p95 of a decaying per-job latency
      histogram x ``straggler_multiplier`` — gets a second copy raced on
      the pool; the FIRST result wins and the loser is cancelled or
      reaped through ``cleanup`` (``jobs.speculative_launched`` /
      ``jobs.speculative_won``).  Safe because ``fn`` is an idempotent,
      side-effect-free span decode — the MapReduce speculative-execution
      contract.
    - **hard timeout** (``config.pool_task_timeout_s``): a future
      outliving it is abandoned (a wedged worker thread cannot be
      killed, only orphaned) and the item re-submitted, once per
      ``span_retries``; exhaustion surfaces ``TransientIOError`` into
      the caller's existing retry/breaker machinery instead of blocking
      forever (``pool.task_timeouts`` / ``jobs.timeout_resubmits``).
      The deadline covers ACTIVE wait on a runnable task — time spent
      queued behind a backlogged-but-healthy pool, or running
      overlapped before the consumer reached this entry, does not
      count (see ``_await``'s two-clock note).

    Without a config (or with both knobs off before any soft deadline
    exists) the await path is the plain blocking ``Future.result()``.
    """
    from collections import deque

    from hadoop_bam_tpu.utils.resilient import call_with_retry

    it = iter(items)
    dq: "deque[list]" = deque()        # entries: [item, fut, t0, spec'd]
    # transient SUBMISSION failures (a saturated executor, an injected
    # pool.submit chaos fault) retry briefly instead of killing the
    # whole driver run — the task itself has its own failure policy
    submit_policy = RetryPolicy(retries=3, backoff_base_s=0.01,
                                backoff_max_s=0.1)

    timeout_s = config.pool_task_timeout_s if config is not None else None
    timeout_s = float(timeout_s) if timeout_s else None
    max_resubmits = int(config.span_retries or 0) \
        if timeout_s is not None else 0
    latency = None
    if config is not None and bool(config.speculative_decode):
        from hadoop_bam_tpu.jobs.speculate import UnitLatency
        latency = UnitLatency.from_config(config)

    def _submit(item) -> cf.Future:
        # pools.submit, not pool.submit: the task carries the caller's
        # MetricsContext onto the worker thread and records its queue
        # wait + run into the pool.task_* histograms
        return call_with_retry(lambda: pool_submit(pool, fn, item),
                               submit_policy, what="decode pool submit",
                               counter="pool.submit_retries")

    def _reap(f: cf.Future) -> None:
        # done-callback: covers futures already finished AND ones
        # still running at teardown (fires on the worker thread when
        # they complete) without blocking this thread on .result()
        if f.cancelled():
            return
        try:
            cleanup(f.result())
        except Exception:  # noqa: BLE001 — best-effort teardown
            pass

    def _abandon(f: cf.Future) -> None:
        if not f.cancel() and cleanup is not None:
            f.add_done_callback(_reap)

    def _await(entry) -> object:
        """Resolve one entry under the defense policy (docstring)."""
        if timeout_s is None and latency is None:
            return entry[1].result()           # undefended fast path
        # candidates: [future, deadline anchor, is_speculative, submit
        # stamp]; the primary plus at most one speculative twin plus
        # timeout re-submissions.  Two clocks on purpose:
        # - the DEADLINE anchor starts when this await begins (a decode
        #   that ran overlapped while earlier entries were consumed is
        #   not "stuck") and is refreshed while the future is still
        #   queued — otherwise a healthy-but-backlogged pool would burn
        #   the hard-timeout budget on queue wait (re-submissions land
        #   at the back of the same queue) and the soft deadline would
        #   speculate on tasks that never started (a twin queued behind
        #   the original can only lose);
        # - the SUBMIT stamp feeds the latency histogram: turnaround,
        #   which can only over-estimate, keeps the p95-derived soft
        #   deadline conservative.
        now = time.perf_counter()
        # fields: [future, deadline anchor, is_spec, submit stamp,
        # first-observed-queued stamp (None until seen pending)]
        cands = [[entry[1], now, False, entry[2], None]]
        resubmits = 0
        while True:
            for c in list(cands):
                if not c[0].done():
                    continue
                try:
                    out = c[0].result()
                except Exception:  # noqa: BLE001 — policy boundary
                    # one copy failing while another runs must not kill
                    # the race — keep waiting on the survivor; but when
                    # the last candidate FAILS (vs times out), raise:
                    # the decode genuinely ran and failed, its own
                    # retry policy is spent, and burning the timeout
                    # re-submission budget on a known-failing span
                    # would just duplicate the failure
                    cands.remove(c)
                    if not cands:
                        raise
                    continue
                if latency is not None:
                    latency.observe(time.perf_counter() - c[3])
                if c[2]:
                    METRICS.count("jobs.speculative_won")
                for o in cands:
                    if o is not c:
                        _abandon(o[0])
                return out
            now = time.perf_counter()
            for c in cands:
                if not c[0].running() and not c[0].done():
                    if c[4] is None:
                        c[4] = now
                    # still queued: hold the deadline anchor — but only
                    # within a bounded grace.  Unbounded holding would
                    # make a FULLY-wedged pool (every worker stuck, so
                    # re-submissions never dequeue) immortal — the
                    # exact forever-hang this knob exists to end; a
                    # merely-backlogged pool drains within the grace
                    if timeout_s is None or \
                            now - c[4] <= timeout_s * _QUEUED_GRACE:
                        c[1] = now
            if timeout_s is not None:
                for c in list(cands):
                    if now - c[1] > timeout_s:
                        METRICS.count("pool.task_timeouts")
                        _abandon(c[0])
                        cands.remove(c)
            if not cands:
                if resubmits >= max_resubmits:
                    from hadoop_bam_tpu.utils.errors import (
                        TransientIOError,
                    )
                    raise TransientIOError(
                        f"{what} exceeded the {timeout_s:g}s "
                        f"pool_task_timeout_s deadline "
                        f"{resubmits + 1} time(s) — worker(s) presumed "
                        f"wedged") from None
                resubmits += 1
                METRICS.count("jobs.timeout_resubmits")
                t = time.perf_counter()
                cands.append([_submit(entry[0]), t, False, t, None])
                now = time.perf_counter()
            soft = latency.soft_deadline_s() if latency is not None \
                else None
            if soft is not None and not entry[3] and len(cands) == 1 \
                    and now - cands[0][1] > soft:
                entry[3] = True
                METRICS.count("jobs.speculative_launched")
                t = time.perf_counter()
                cands.append([_submit(entry[0]), t, True, t, None])
            # sleep until the nearest deadline (or a coarse slice that
            # keeps the undeadlined wait cheap), woken early by any
            # candidate completing
            waits = [0.25]
            if timeout_s is not None:
                waits += [c[1] + timeout_s - now for c in cands]
            if soft is not None and not entry[3]:
                waits += [cands[0][1] + soft - now]
            elif latency is not None and soft is None:
                waits += [float(latency.min_s)]
            cf.wait([c[0] for c in cands],
                    timeout=max(0.005, min(waits)),
                    return_when=cf.FIRST_COMPLETED)

    try:
        # entries: [item, future, submit stamp, speculated?] — the stamp
        # feeds latency.observe in _await (the straggler histogram)
        for item in it:
            dq.append([item, _submit(item), time.perf_counter(), False])
            if len(dq) >= window:
                break
        while dq:
            entry = dq.popleft()
            for item in it:
                dq.append([item, _submit(item), time.perf_counter(),
                           False])
                break
            yield _await(entry)
    finally:
        for entry in dq:
            _abandon(entry[1])


def _iter_prefix_tiles(row_arrays, cap: int, row_bytes: int = PREFIX
                       ) -> Iterator[Tuple[np.ndarray, int]]:
    """Repack a stream of per-span row arrays into [cap, row_bytes] tiles.

    Spans have data-dependent record counts; the jit contract wants static
    shapes.  Rather than padding each span to the worst case (the old span
    path's memset + transfer tax), concatenate across span boundaries and
    emit full tiles — only the final tile carries padding.

    This is the SERIAL tiler: the hot drivers feed through
    parallel/staging.FeedPipeline (in-place ring packing, no per-tile
    allocation); this stays as the reference implementation the
    byte-identity property tests compare the ring against."""
    from collections import deque

    # deque, not a list: parts.pop(0) is O(len) per pop, which turns a
    # many-small-span plan (thousands of parts per tile) quadratic
    parts: "deque[np.ndarray]" = deque()
    have = 0

    def emit(take: int) -> Tuple[np.ndarray, int]:
        nonlocal have
        # full tiles are fully overwritten — only the padded final tile
        # needs zeroing
        tile = (np.empty if take == cap else np.zeros)(
            (cap, row_bytes), dtype=np.uint8)
        filled = 0
        while filled < take:
            head = parts[0]
            k = min(take - filled, head.shape[0])
            tile[filled:filled + k] = head[:k]
            if k == head.shape[0]:
                parts.popleft()
            else:
                parts[0] = head[k:]
            filled += k
        have -= take
        return tile, take

    for prefix in row_arrays:
        if prefix.shape[0]:
            parts.append(prefix)
            have += prefix.shape[0]
        while have >= cap:
            yield emit(cap)
    if have:
        yield emit(have)


def _iter_tile_tuples(array_tuples, cap: int, specs: Sequence
                      ) -> Iterator[Tuple[Tuple[np.ndarray, ...], int]]:
    """Like _iter_prefix_tiles but over tuples of row arrays kept in
    lockstep (prefix/seq/qual/lengths share record order and counts).

    ``specs``: per-array spec — an int width (uint8 [cap, w] tile) or a
    (width_or_None, dtype) pair; width None means a 1-D [cap] tile.

    Serial tiler, like _iter_prefix_tiles: coverage still drives it, and
    the FeedPipeline byte-identity tests use it as the oracle."""
    from collections import deque

    norm = [(s, np.uint8) if isinstance(s, int) else tuple(s)
            for s in specs]
    # deque: parts.pop(0) was O(n^2) on many-small-span plans
    parts: "deque[Tuple[np.ndarray, ...]]" = deque()
    have = 0

    def emit(take: int) -> Tuple[Tuple[np.ndarray, ...], int]:
        nonlocal have
        alloc = np.empty if take == cap else np.zeros
        tiles = tuple(
            alloc((cap,) if w is None else (cap, w), dtype=dt)
            for w, dt in norm)
        filled = 0
        while filled < take:
            head = parts[0]
            m = min(take - filled, head[0].shape[0])
            for t, h in zip(tiles, head):
                t[filled:filled + m] = h[:m]
            if m == head[0].shape[0]:
                parts.popleft()
            else:
                parts[0] = tuple(h[m:] for h in head)
            filled += m
        have -= take
        return tiles, take

    for arrays in array_tuples:
        assert len(arrays) == len(norm)
        if arrays[0].shape[0]:
            parts.append(tuple(arrays))
            have += arrays[0].shape[0]
        while have >= cap:
            yield emit(cap)
    if have:
        yield emit(have)


# canonical home is parallel/staging.py (the FeedPipeline shares it);
# the alias keeps this module's historical import surface
_bucket_cap = bucket_cap


def iter_payload_tile_groups(path: str, spans: Sequence[FileVirtualSpan],
                             geometry: PayloadGeometry, n_dev: int,
                             config: HBamConfig = DEFAULT_CONFIG,
                             prefetch: int = 2,
                             header=None,
                             quarantine: Optional[QuarantineManifest] = None,
                             balance: bool = False,
                             emit_fn=None,
                             ) -> Iterator:
    """Stream payload tile groups ready for a device mesh: yields
    ([prefix, seq, qual] each [n_dev, rows, w] uint8, counts [n_dev]
    int32), where rows == geometry.tile_records for every full group and
    the FINAL partial group may shrink to a smaller bucket (_bucket_cap).
    The shared batching core of seq_stats_file and
    BamDataset.tensor_batches — shared decode pool with a bounded
    window, staging-ring group packing (parallel/staging.py: rows write
    in place, partial tiles zero only their own tail), span retry/skip
    per the config's failure policy.

    ``emit_fn(arrays, counts)``, when given, runs per group inside the
    FeedPipeline (its return value is yielded AND becomes the ring
    slot's in-flight transfer handle — see staging.FeedPipeline.stream);
    both in-repo consumers pass one.  Without it, the yielded arrays
    are caller-owned copies (the historical contract — this fallback
    only exists for external callers, so it pays the copy rather than
    hand out ring views that the packer will overwrite)."""
    cap = geometry.tile_records
    widths = (PREFIX, geometry.seq_stride, geometry.qual_stride)
    check_crc = bool(config.check_crc)
    intervals = parse_config_intervals(config, header)
    # same fast-fail quarantine gate as flagstat_file: a file whose last
    # run tripped the bad-span circuit sheds here while it is OPEN
    check_quarantine_gate(path, config)
    src = _resilient_source(path, config)
    spans = list(spans)
    if quarantine is not None and quarantine.total_spans is None:
        quarantine.total_spans = len(spans)
    pool = decode_pool(config)
    window = max(1, prefetch) * decode_pool_size(config)

    # the ONE routing decision (plan/executor.py), consumed here only
    # for host_backend and fused streaming: the payload family's DEVICE
    # route lives in _seq_stats_impl (which never reaches this
    # generator on the device plane) — tensor_batches consumers always
    # materialize host row tiles, so "device" rides the host planes in
    # this generator, "zlib"/"native" are honored as asked, and chunk
    # streaming follows the shared fused-stream gate
    decision = select_plane(SourceIR(path, "bam"), PAYLOAD_DAG, config,
                            intervals=intervals)
    host_backend = decision.host_backend
    # same demotion ladder as flagstat's host path: corrupt failures on
    # the native rung re-decode on zlib (byte-identical) and oracle-
    # confirmed blame opens the native domain's breaker
    ladder = decode_ladder(path, decision.backend, config) \
        if config.adaptive_planes else None

    # same chunk-streaming shape as flagstat_file: fused spans hand their
    # prefix/seq/qual chunks to the packer as the native walk lands them
    stream_fused = decision.stream_fused
    if stream_fused:
        window = _stream_window(window)

    def decode(span):
        def inner(s, plane=None):
            hb = host_backend if plane is None else plane
            if hb in ("auto", "native"):
                chaos.fire("decode.native", span=str(s))
            if stream_fused and hb in ("auto", "native"):
                return _iter_fused_span_chunks(
                    src, s, "payload", geometry=geometry,
                    check_crc=check_crc, config=config,
                    fallback_fn=lambda: decode_with_retry(
                        lambda s2: decode_span_payload_host(
                            src, s2, geometry, check_crc, header=header,
                            config=_fused_off(config))[:3],
                        s, config))
            prefix, seq, qual, _v = decode_span_payload_host(
                src, s, geometry, check_crc, hb,
                intervals=intervals, header=header,
                config=config if hb != "zlib" else _fused_off(config))
            return prefix, seq, qual
        with METRICS.timer("pipeline.host_decode"), \
                METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span("bam.host_decode_wall"):
            out = decode_with_retry(inner, span, config,
                                    quarantine=quarantine, ladder=ladder)
        return out if out is not None else (
            np.empty((0, PREFIX), np.uint8),
            np.empty((0, geometry.seq_stride), np.uint8),
            np.empty((0, geometry.qual_stride), np.uint8))

    stream = _flatten_span_stream(
        _iter_windowed(pool, spans, decode, window,
                       cleanup=_close_stream, config=config))
    # balance=True only for psum'd stats consumers (seq_stats_file);
    # tensor_batches keeps the serial row placement, so public batches
    # stay byte-stable across releases
    fp = FeedPipeline(n_dev, cap, [TileSpec((w,), np.uint8) for w in widths],
                      block_n=geometry.block_n,
                      fixed_shape=geometry.fixed_shape, balance=balance,
                      config=config, fmt="bam")
    if emit_fn is not None:
        yield from fp.stream(stream, emit_fn)
    else:
        for arrays, counts in fp.groups(stream):
            yield [a.copy() for a in arrays], counts.copy()
    # reached only when the whole span plan decoded without tripping the
    # bad-span circuit: heals a half-open quarantine gate
    quarantine_run_ok(path, config)


class _StatTotals:
    """Deferred 64-bit host accumulation of per-group device stat sums.

    ``add`` just enqueues the (f32 sums, i32 counts) device arrays —
    dispatch stays async so host decode overlaps device compute; ``drain``
    fetches them all at the end and reduces in float64/int64 (per-group
    device sums are exact; the running totals must be 64-bit)."""

    def __init__(self):
        self._pairs: List[Tuple] = []

    def add(self, fvec, ivec) -> None:
        self._pairs.append((fvec, ivec))

    def __bool__(self) -> bool:
        return bool(self._pairs)

    def drain(self) -> Tuple[np.ndarray, np.ndarray]:
        f0, i0 = self._pairs[0]
        tf = np.zeros(np.shape(f0), np.float64)
        ti = np.zeros(np.shape(i0), np.int64)
        with METRICS.span("pipeline.combine_wall", groups=len(self._pairs)):
            # ONE bulk device_get for every queued group (a per-group
            # fetch in the loop is a sync per group — DV901's territory)
            for f, i in jax.device_get(self._pairs):
                tf += f
                ti += i
        return tf, ti


def _payload_stats_tail(stats, valid, axis: str):
    """Shared psum tail of the payload-stats steps: (f32[2] mean sums,
    i32[1+16] n_reads + base_hist) — counts ride the int vector because
    f32 accumulation drifts past 2^24."""
    nonpad = valid.astype(jnp.float32)
    fvec = jnp.stack([(stats["gc"] * nonpad).sum(),
                      (stats["mean_qual"] * nonpad).sum()])
    ivec = jnp.concatenate([
        valid.astype(jnp.int32).sum()[None], stats["base_hist"]])
    return jax.lax.psum(fvec, axis), jax.lax.psum(ivec, axis)


def _attach_quarantine(result: Dict,
                       quarantine: Optional[QuarantineManifest]) -> Dict:
    """Attach the quarantine manifest to a driver's result dict.  Only when
    non-empty: clean runs keep their exact historical result shape, and
    dict-equality comparisons across runs/hosts stay valid."""
    if quarantine:
        result["quarantine"] = quarantine.to_dicts()
    return result


def _payload_stats_result(totals: _StatTotals) -> Dict[str, object]:
    from hadoop_bam_tpu.ops.seq_pallas import N_CODES
    if not totals:
        return {"n_reads": 0, "mean_gc": 0.0, "mean_qual": 0.0,
                "base_hist": np.zeros(N_CODES, np.int64)}
    tf, ti = totals.drain()
    n = max(float(ti[0]), 1.0)
    return {"n_reads": int(ti[0]), "mean_gc": float(tf[0] / n),
            "mean_qual": float(tf[1] / n), "base_hist": ti[1:]}


def make_seq_stats_step(mesh: Mesh, geometry: PayloadGeometry,
                        axis: str = "data") -> Callable:
    """Jitted sharded step over payload tiles: (prefix [n, cap, 36],
    seq [n, cap, SB], qual [n, cap, QB], counts [n]) -> psum'd
    (f32 [2] (sum_gc, sum_mean_qual), i32 [1 + 16] (n_reads, base_hist))
    pair — see _payload_stats_tail.

    Lengths come from the prefix tile's l_seq column on device, clipped to
    max_len (the pack truncates there); padding rows get length 0 via the
    count mask.  The per-tile compute is the Pallas fused kernel
    (ops/seq_pallas.py) — bases never materialise in HBM.
    """
    key = ("seq_stats", tuple(mesh.devices.flat), mesh.axis_names, axis,
           geometry)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.seq_pallas import seq_qual_stats

    # interpret mode keyed to the MESH's devices, not the default backend:
    # a virtual CPU mesh in a TPU-default process still needs the
    # interpreter
    interpret = mesh.devices.flat[0].platform != "tpu"

    def per_device(prefix, seq, qual, count):
        prefix, seq, qual, count = prefix[0], seq[0], qual[0], count[0]
        cols = unpack_projected_tile(prefix, ALL_FIELDS)
        valid = jnp.arange(prefix.shape[0], dtype=jnp.int32) < count
        lengths = jnp.where(valid,
                            jnp.minimum(cols["l_seq"], geometry.max_len), 0)
        stats = seq_qual_stats(seq, qual, lengths,
                               block_n=geometry.block_n,
                               interpret=interpret)
        return _payload_stats_tail(stats, valid, axis)

    # check_vma=False: pallas_call's out_shape has no varying-mesh-axes
    # annotation, which the default shard_map VMA check rejects
    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(axis), P(axis)),
                   out_specs=(P(), P()), check_vma=False)
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def stream_read_tensor_batches(spans, read_span_fn, config: HBamConfig,
                               mesh: Optional[Mesh],
                               geometry: "Optional[PayloadGeometry]",
                               tiles_fn=None,
                               quarantine: Optional[QuarantineManifest] = None,
                               fmt: str = "read",
                               ) -> Iterator[Dict]:
    """Shared tensor-batch generator for text/record read formats
    (FASTQ/QSEQ/CRAM): ``read_span_fn(span)`` returns a list of objects
    with ``.sequence``/``.quality`` attributes; yields sharded device
    batches {seq_packed, qual, lengths, n_records}.

    ``tiles_fn(span, geometry)``, when given, replaces the whole
    span->objects->tiles stage with a direct (seq, qual, lengths) tile
    producer — the columnar fast path (CRAM uses it to skip SAM record
    materialization entirely)."""
    from hadoop_bam_tpu.api.read_datasets import fragments_to_payload_tiles
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh()
    if geometry is None:
        geometry = PayloadGeometry()
    n_dev = int(np.prod(mesh.devices.shape))
    cap = geometry.tile_records
    sharding = NamedSharding(mesh, P("data"))
    spans = list(spans)
    if quarantine is not None and quarantine.total_spans is None:
        quarantine.total_spans = len(spans)
    pool = decode_pool(config)

    def decode(span):
        def inner(s):
            if tiles_fn is not None:
                return tiles_fn(s, geometry)
            return fragments_to_payload_tiles(
                read_span_fn(s), geometry.seq_stride,
                geometry.qual_stride, geometry.max_len)
        with METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span(f"{fmt}.host_decode_wall"):
            out = decode_with_retry(inner, span, config,
                                    quarantine=quarantine)
        return out if out is not None else (
            np.empty((0, geometry.seq_stride), np.uint8),
            np.empty((0, geometry.qual_stride), np.uint8),
            np.empty((0,), np.int32))

    stream = _iter_windowed(pool, spans, decode,
                            2 * decode_pool_size(config), config=config)
    specs = (geometry.seq_stride, geometry.qual_stride, (None, np.int32))
    fp = FeedPipeline(n_dev, cap, specs, block_n=geometry.block_n,
                      fixed_shape=geometry.fixed_shape, config=config,
                      fmt=fmt)

    def emit(arrays, counts) -> Dict:
        # the returned device dict doubles as the slot's in-flight
        # transfer handle (FeedPipeline.stream contract)
        return {
            "seq_packed": jax.device_put(arrays[0], sharding),
            "qual": jax.device_put(arrays[1], sharding),
            "lengths": jax.device_put(arrays[2], sharding),
            "n_records": jax.device_put(counts, sharding),
        }

    yield from fp.stream(stream, emit)


def make_read_stats_step(mesh: Mesh, geometry: PayloadGeometry,
                         axis: str = "data") -> Callable:
    """Like make_seq_stats_step (same (f32[2], i32[1+16]) return pair) but
    with explicit per-read lengths instead of a BAM prefix tile — the step
    for text read formats (FASTQ/QSEQ) whose payload tiles come from
    fragments_to_payload_tiles."""
    key = ("read_stats", tuple(mesh.devices.flat), mesh.axis_names, axis,
           geometry)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.seq_pallas import seq_qual_stats

    interpret = mesh.devices.flat[0].platform != "tpu"

    def per_device(seq, qual, lengths, count):
        seq, qual, lengths, count = seq[0], qual[0], lengths[0], count[0]
        valid = jnp.arange(seq.shape[0], dtype=jnp.int32) < count
        lengths = jnp.where(valid, lengths, 0)
        stats = seq_qual_stats(seq, qual, lengths,
                               block_n=geometry.block_n,
                               interpret=interpret)
        return _payload_stats_tail(stats, valid, axis)

    fn = shard_map(per_device, mesh=mesh, in_specs=(P(axis),) * 4,
                   out_specs=(P(), P()), check_vma=False)
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


# text read-format extensions recognized by the payload stats dispatch
# (single source of truth — the CLI imports these)
def pipeline_span_count(path, n_dev: int,
                        config: HBamConfig = DEFAULT_CONFIG) -> int:
    """Span count at the PIPELINE grain for a whole-file stats driver.

    config.split_size is the HDFS-style job grain (128 MiB default); a
    driver that used it directly would get one span for most files and
    serialize host tokenize against device dispatch end to end.  The
    pipeline grain is min(split_size, 4 MiB) — honoring a user split
    size configured SMALLER than the pipeline default (a memory bound)
    while still slicing big-grain configs fine enough to overlap.
    Sized via as_byte_source so non-local byte sources keep pipelining;
    unsizable sources fall back to one span per device.
    """
    grain = float(max(1, min(int(config.split_size), 4 << 20)))
    try:
        with scoped_byte_source(path) as src:
            size = src.size
    except Exception:  # noqa: BLE001 — planning must not fail the driver
        return n_dev
    return max(n_dev, int(np.ceil(size / grain)))


FASTQ_EXTS = (".fastq", ".fq", ".fastq.gz", ".fq.gz")
QSEQ_EXTS = (".qseq", ".qseq.gz")
TEXT_READ_EXTS = FASTQ_EXTS + QSEQ_EXTS
CRAM_EXTS = (".cram",)


def cram_seq_stats_file(path: str, mesh: Optional[Mesh] = None,
                        config: HBamConfig = DEFAULT_CONFIG,
                        geometry: Optional[PayloadGeometry] = None,
                        spans=None,
                        quarantine: Optional[QuarantineManifest] = None,
                        ) -> Dict[str, object]:
    """GC / quality / base stats over a CRAM — the CRAM member of the
    seq-stats driver family, fed by the columnar slice decoder
    (CramDataset.tensor_batches) through the same fused stats step as
    the BAM/FASTQ drivers."""
    from hadoop_bam_tpu.api.cram_dataset import open_cram
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh()
    if geometry is None:
        geometry = PayloadGeometry()
    ds = open_cram(path, config)
    if spans is None:
        # pipeline-grain spans so container decode overlaps dispatch
        # (the 128 MiB job grain would serialize them)
        n_dev = int(np.prod(mesh.devices.shape))
        with METRICS.span("cram.plan_wall"):
            spans = ds.spans(num_spans=pipeline_span_count(path, n_dev,
                                                           config))
    step = make_read_stats_step(mesh, geometry)
    totals = _StatTotals()
    if quarantine is None:
        quarantine = QuarantineManifest()
    for b in ds.tensor_batches(mesh=mesh, geometry=geometry, spans=spans,
                               quarantine=quarantine):
        with METRICS.span("cram.kernel_wall"):
            totals.add(*step(b["seq_packed"], b["qual"], b["lengths"],
                             b["n_records"]))
    return _attach_quarantine(_payload_stats_result(totals), quarantine)


def fastq_seq_stats_file(path: str, mesh: Optional[Mesh] = None,
                         config: HBamConfig = DEFAULT_CONFIG,
                         geometry: Optional[PayloadGeometry] = None,
                         spans=None,
                         prefetch: int = 2,
                         quarantine: Optional[QuarantineManifest] = None,
                         ) -> Dict[str, object]:
    """Distributed GC / quality / base stats over a FASTQ (or QSEQ) file —
    the text-format twin of seq_stats_file, through the same fused Pallas
    payload kernel."""
    from hadoop_bam_tpu.api.read_datasets import (
        fastq_text_to_payload_tiles, fragments_to_payload_tiles,
        open_fastq, open_qseq, qseq_text_to_payload_tiles,
    )
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if geometry is None:
        geometry = PayloadGeometry()
    cap = geometry.tile_records
    lower = path.lower()
    is_qseq = lower.endswith(QSEQ_EXTS)
    fmt = "qseq" if is_qseq else "fastq"
    ds = open_qseq(path, config) if is_qseq else open_fastq(path, config)
    # Vectorized tokenize (no per-read Python objects) whenever the config
    # doesn't force the object path: failed-QC filtering needs parsed
    # fields (qseq's filter column / fastq's name metadata).
    if is_qseq:
        fast_tiles = not config.qseq_filter_failed_qc
        qual_offset = config.qseq_base_quality_encoding.value
        text_to_tiles = qseq_text_to_payload_tiles
    else:
        fast_tiles = not config.fastq_filter_failed_qc
        qual_offset = config.fastq_base_quality_encoding.value
        text_to_tiles = fastq_text_to_payload_tiles
    if spans is None:
        with METRICS.span(f"{fmt}.plan_wall"):
            spans = ds.spans(
                num_spans=pipeline_span_count(path, n_dev, config))
    spans = list(spans)
    if quarantine is None:
        quarantine = QuarantineManifest()
    if quarantine.total_spans is None:
        quarantine.total_spans = len(spans)
    step = make_read_stats_step(mesh, geometry)
    sharding = NamedSharding(mesh, P("data"))
    pool = decode_pool(config)
    window = max(1, prefetch) * decode_pool_size(config)
    totals = _StatTotals()

    def decode(span):
        def inner(s):
            with METRICS.span(f"{fmt}.fetch_wall"):
                raw = ds.read_span_text(s) if fast_tiles \
                    else ds.read_span(s)
            with METRICS.span(f"{fmt}.tokenize_wall"):
                if fast_tiles:
                    return text_to_tiles(
                        raw, geometry.seq_stride, geometry.qual_stride,
                        geometry.max_len, qual_offset)
                return fragments_to_payload_tiles(
                    raw, geometry.seq_stride, geometry.qual_stride,
                    geometry.max_len)
        with METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span(f"{fmt}.host_decode_wall"):
            out = decode_with_retry(inner, span, config,
                                    quarantine=quarantine)
        return out if out is not None else (
            np.empty((0, geometry.seq_stride), np.uint8),
            np.empty((0, geometry.qual_stride), np.uint8),
            np.empty((0,), np.int32))

    stream = _iter_windowed(pool, spans, decode, window, config=config)
    # the shared feed: in-place ring packing replaces the old per-group
    # np.stack of freshly zero-padded shards, and each device only pays
    # copy work for its own rows (the per-device bucket-cap behavior the
    # BAM payload path already had); balance spreads the final partial
    # group over all shards (stats are psum'd, placement-invariant)
    specs = (geometry.seq_stride, geometry.qual_stride, (None, np.int32))
    fp = FeedPipeline(n_dev, cap, specs, block_n=geometry.block_n,
                      fixed_shape=geometry.fixed_shape, balance=True,
                      config=config, fmt=fmt)

    def dispatch(arrays, counts):
        args = [jax.device_put(a, sharding) for a in arrays]
        c = jax.device_put(counts, sharding)
        with METRICS.span(f"{fmt}.kernel_wall"):
            totals.add(*step(*args, c))  # async; drained once at the end
        return (*args, c)  # in-flight handles: the ring waits before reuse

    fp.feed(stream, dispatch)
    return _attach_quarantine(_payload_stats_result(totals), quarantine)


def seq_stats_file(path: str, mesh: Optional[Mesh] = None,
                   config: HBamConfig = DEFAULT_CONFIG,
                   geometry: Optional[PayloadGeometry] = None,
                   header: Optional[SAMHeader] = None,
                   spans: Optional[Sequence[FileVirtualSpan]] = None,
                   prefetch: int = 2,
                   quarantine: Optional[QuarantineManifest] = None,
                   ) -> Dict[str, object]:
    """Distributed sequence/quality stats over a whole BAM: mean GC
    fraction, mean per-read quality, and the 4-bit base-code histogram —
    computed by the fused Pallas payload kernel on every device of the
    mesh.  The payload analog of flagstat_file, and like it a thin plan
    builder over the one executor."""
    from hadoop_bam_tpu.plan import builders
    from hadoop_bam_tpu.plan import executor as plan_executor

    plan = builders.seq_stats_plan(path, config, geometry=geometry)
    return plan_executor.execute(plan, config=config, mesh=mesh,
                                 geometry=geometry, header=header,
                                 spans=spans, prefetch=prefetch,
                                 quarantine=quarantine)


def _seq_stats_impl(path: str, mesh: Optional[Mesh] = None,
                    config: HBamConfig = DEFAULT_CONFIG,
                    geometry: Optional[PayloadGeometry] = None,
                    header: Optional[SAMHeader] = None,
                    spans: Optional[Sequence[FileVirtualSpan]] = None,
                    prefetch: int = 2,
                    quarantine: Optional[QuarantineManifest] = None,
                    ) -> Dict[str, object]:
    """The payload-stats mesh-feed implementation (executor runner):
    iter_payload_tile_groups decode/pack under the shared routing
    decision, fused Pallas kernel per tile group, 64-bit host drain."""
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if geometry is None:
        geometry = PayloadGeometry()
    cap = geometry.tile_records
    assert cap % geometry.block_n == 0
    if header is None:
        header, _ = read_bam_header(path)

    # the same plane wrapper as _flagstat_impl: THE routing decision
    # (plan/executor.select_plane) with the ladder consulted last, the
    # device plane tried first when selected, and demotable device
    # faults falling through to the host path below with oracle-
    # confirmed blame recorded only after the host run completes
    intervals = parse_config_intervals(config, header)
    ladder = decode_ladder(path, resolve_inflate_backend(config), config) \
        if config.adaptive_planes else None
    device_blame: Optional[BaseException] = None
    decision = select_plane(SourceIR(path, "bam"), PAYLOAD_DAG, config,
                            intervals=intervals, ladder=ladder)
    if decision.plane == "device":
        check_quarantine_gate(path, config)
        try:
            out = _seq_stats_device_plane(path, mesh, config, header,
                                          geometry, spans, quarantine,
                                          prefetch=prefetch)
            if ladder is not None:
                ladder.record_success("device")
            quarantine_run_ok(path, config)
            return out
        except Exception as e:  # noqa: BLE001 — plane policy boundary
            if ladder is None or not ladder.demotable("device", e):
                raise
            logger.warning("device decode plane failed (%s: %s); "
                           "demoting to the host planes for %s",
                           type(e).__name__, e, path)
            device_blame = e

    if spans is None:
        span_bytes = 8 << 20
        src = as_byte_source(path)
        n_spans = max(n_dev, int(np.ceil(src.size / span_bytes)))
        src.close()
        from hadoop_bam_tpu.split.planners import plan_spans_cached
        with METRICS.span("bam.plan_wall", spans=n_spans):
            spans = plan_spans_cached(path, header, config,
                                      num_spans=n_spans)

    step = make_seq_stats_step(mesh, geometry)
    sharding = NamedSharding(mesh, P("data"))
    totals = _StatTotals()
    if quarantine is None:
        quarantine = QuarantineManifest()
    def emit(arrays, counts):
        # the group generator packs on its own thread (FeedPipeline);
        # this runs on the dispatch side of the double buffer, and the
        # returned device arrays are the slot's in-flight handles
        args = [jax.device_put(a, sharding) for a in arrays]
        c = jax.device_put(counts, sharding)
        with METRICS.span("bam.kernel_wall"):
            totals.add(*step(*args, c))   # async; drained once at the end
        return (*args, c)

    for _ in iter_payload_tile_groups(
            path, spans, geometry, n_dev, config, prefetch, header=header,
            quarantine=quarantine, balance=True, emit_fn=emit):
        pass
    result = _attach_quarantine(_payload_stats_result(totals), quarantine)
    if ladder is not None and device_blame is not None:
        # oracle confirmation: the host planes completed where the
        # device plane failed — blame the device domain (opens its
        # breaker after repeated confirmations)
        ladder.confirm_failure("device", device_blame)
    return result


# ---------------------------------------------------------------------------
# Device decode plane: the token-feed path (ops/inflate_device.py).
#
# Where the host planes inflate spans on CPU and ship packed ROW tiles, the
# device plane ships LZ77 TOKEN chunks: pool workers run the bit-serial
# native Huffman tokenize (the only unvectorizable half of inflate, CRC
# folded in when asked) and the mesh step does everything else — LZ77
# resolve, contiguous pack, the record walk (pointer doubling over the
# block_size chain) and the FIXED_FIELDS unpack — so the inflated bytes
# NEVER exist on the host on this path.  Chunks ride the existing
# StagingRing with per-slot in-flight handles: host tokenize of group k+1
# overlaps device resolve+unpack of group k.
#
# Spans whose final record is cut at the buffer end (and the remainder of
# spans wider than the block ladder) complete through a host FIXUP decode
# at drain time — the device reports each chunk's walk tail, and records
# starting in [tail, span end) go through the ordinary projected-row host
# path.  flagstat is the pilot driver; selection is config.inflate_backend
# ("auto" probes once per process — see config.resolve_inflate_backend).
# ---------------------------------------------------------------------------

# widest token chunk one device step takes: 64 BGZF blocks (~4 MiB
# inflated at the 64 KiB ladder rung).  Spans wider than this stream
# their first 64 blocks through the device and the rest through the
# host fixup, so the plane degrades gracefully instead of erroring.
DEVICE_PLANE_MAX_BLOCKS = 64
# compressed span grain the plane plans at when the caller didn't pin a
# plan: small enough that a span's token chunk fits the ladder with room
# to spare, big enough to amortize per-span Python overhead
DEVICE_PLANE_SPAN_BYTES = 512 << 10


@dataclasses.dataclass
class _TokenChunk:
    """One span's host-tokenized device-plane unit (<= MAX_BLOCKS blocks)."""
    tokens: np.ndarray     # [used, P] u32 LZ77 tokens
    n_tokens: np.ndarray   # [used] i32
    isize: np.ndarray      # [used] i32
    start: int             # record-walk start (inflated chunk coords)
    stop: int              # ownership limit (records starting < stop)
    used: int              # blocks tokenized for the device
    P: int                 # ladder rung (token pad == per-block bytes)
    n_blocks: int          # blocks in the WHOLE span (> used: host fixup)
    span: FileVirtualSpan
    ubase: np.ndarray      # [n_blocks+1] i64 inflated block starts
    abs_coffs: np.ndarray  # [n_blocks] i64 absolute compressed offsets

    def fixup_span(self, tail: int) -> FileVirtualSpan:
        """The host-decoded remainder: records starting in
        [tail, span end) — the cut final record, plus every block past
        the device chunk for over-wide spans."""
        blk = int(np.searchsorted(self.ubase[1:], tail, side="right"))
        blk = min(blk, self.n_blocks - 1)
        u = int(tail - self.ubase[blk])
        start_v = (int(self.abs_coffs[blk]) << 16) | u
        return FileVirtualSpan(self.span.path, start_v,
                               self.span.end_voffset)


def _tokenize_span_tokens(src, span: FileVirtualSpan,
                          check_crc: bool = False
                          ) -> Optional[_TokenChunk]:
    """Host half of the device plane for one span: fetch + block table +
    threaded native Huffman tokenize (CRC folded in when ``check_crc``).
    BGZF-level faults (DEFLATE corruption, ISIZE, CRC) raise BGZFError
    HERE, inside the retry boundary — exactly where the host planes
    raise them.  Returns None for an empty span."""
    from hadoop_bam_tpu.ops.inflate_device import ladder_pow2
    from hadoop_bam_tpu.utils import native

    src = as_byte_source(src)
    raw, end_block_size, _next_c = _fetch_span_raw(src, span)
    METRICS.count("pipeline.spans")
    if not raw:
        return None
    table = inflate_ops.block_table(raw)
    isize = table["isize"]
    n = int(isize.size)
    used = min(n, DEVICE_PLANE_MAX_BLOCKS)
    src_arr = np.frombuffer(raw, dtype=np.uint8)
    sub = isize[:used]
    P = ladder_pow2(max(16, int(sub.max())))
    with METRICS.span("bam.tokenize_wall", nbytes=len(raw), blocks=used):
        try:
            out = native.deflate_tokenize_batch(
                src_arr, table["cdata_off"][:used],
                table["cdata_len"][:used], P, 0, with_crc=check_crc)
        except ValueError as e:
            # same class as the host inflate backends: bad DEFLATE bytes
            # are BGZF-level corruption whichever plane finds them
            from hadoop_bam_tpu.formats import bgzf
            raise bgzf.BGZFError(str(e)) from e
    tokens, n_tokens, out_lens = out[:3]
    if not np.array_equal(out_lens, sub):
        from hadoop_bam_tpu.formats import bgzf
        bad = int(np.nonzero(out_lens != sub)[0][0])
        raise bgzf.BGZFError(
            f"ISIZE mismatch in block {bad}: tokenized "
            f"{int(out_lens[bad])}, footer says {int(sub[bad])}")
    if check_crc:
        expect = inflate_ops.footer_crcs(src_arr, table)[:used]
        mism = np.nonzero(out[3] != expect)[0]
        if mism.size:
            from hadoop_bam_tpu.formats import bgzf
            raise bgzf.BGZFError(
                f"CRC32 mismatch in block(s) {mism[:8].tolist()}")
    ub = np.zeros(n + 1, dtype=np.int64)
    np.cumsum(isize, out=ub[1:])
    if used == n and end_block_size:
        stop = int(ub[n]) - int(isize[-1]) + span.end[1]
    elif used == n:
        stop = int(ub[n])
    else:
        stop = int(ub[used])
    METRICS.count("pipeline.blocks", used)
    METRICS.count("pipeline.inflated_bytes", int(ub[used]))
    return _TokenChunk(tokens=tokens, n_tokens=n_tokens, isize=sub,
                       start=span.start[1], stop=stop, used=used, P=P,
                       n_blocks=n, span=span, ubase=ub,
                       abs_coffs=table["coffset"] + span.start[0])


def make_device_flagstat_step(mesh: Mesh, axis: str = "data") -> Callable:
    """Jitted sharded step over token chunks: (tokens [n, B, P] u32,
    n_tokens [n, B], isize [n, B], meta [n, 1, 2] (start, stop)) ->
    (psum'd flagstat vector, per-device n_all / tail / bad).  The whole
    decode — LZ77 resolve, contiguous pack, record walk, fixed-field
    unpack, flagstat reduce — happens in the one jitted call; only the
    16 counters and three walk scalars per device ever come back."""
    key = ("device_flagstat", tuple(mesh.devices.flat), mesh.axis_names,
           axis)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS
    from hadoop_bam_tpu.ops.inflate_device import resolve_walk_fields

    def per_device(tokens, n_tokens, isize, meta):
        tokens, n_tokens = tokens[0], n_tokens[0]
        isize, meta = isize[0], meta[0]
        cols, valid, n_all, tail, bad = resolve_walk_fields(
            tokens, n_tokens, isize, meta[0, 0], meta[0, 1])
        stats = flagstat_from_columns(cols, valid)
        vec = jnp.stack([stats[k] for k in FLAGSTAT_FIELDS])
        return (jax.lax.psum(vec, axis),
                n_all[None], tail[None], bad[None])

    # check_vma=False: the while_loops inside the resolve and the walk
    # have no varying-mesh-axes replication rule (same reason the Pallas
    # seq-stats step opts out)
    fn = shard_map(per_device, mesh=mesh, in_specs=(P(axis),) * 4,
                   out_specs=(P(), P(axis), P(axis), P(axis)),
                   check_vma=False)
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def _flagstat_device_plane(path: str, mesh: Mesh, config: HBamConfig,
                           header: SAMHeader,
                           spans: Optional[Sequence[FileVirtualSpan]],
                           quarantine: Optional[QuarantineManifest],
                           prefetch: int = 2) -> Dict[str, int]:
    """flagstat through the token-feed device decode plane.

    Pool workers tokenize spans (bam.tokenize_wall) while this thread
    packs token chunks into StagingRing slots and dispatches the fused
    resolve+walk+unpack step (bam.device_resolve_wall, stage timer
    pipeline.device_inflate) — tokenize of group k+1 overlaps device
    decode of group k, and the ring's per-slot in-flight handles keep a
    buffer from being overwritten while its transfer is still reading.
    Walk tails drain once at the end; cut final records and over-wide
    spans complete through the host projected-row fixup path."""
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS
    from hadoop_bam_tpu.ops.inflate_device import records_cap
    from hadoop_bam_tpu.ops.rans import _round_pow2
    from hadoop_bam_tpu.utils import native
    from hadoop_bam_tpu.utils.errors import CorruptDataError

    if not native.available():
        raise PlanError(
            "inflate_backend='device' needs the native tokenizer "
            "(hbam_deflate_tokenize_batch); native library unavailable")
    n_dev = int(np.prod(mesh.devices.shape))
    if spans is None:
        src0 = as_byte_source(path)
        n_spans = max(n_dev, int(np.ceil(src0.size
                                         / DEVICE_PLANE_SPAN_BYTES)))
        src0.close()
        from hadoop_bam_tpu.split.planners import plan_spans_cached
        with METRICS.span("bam.plan_wall", spans=n_spans):
            spans = plan_spans_cached(path, header, config,
                                      num_spans=n_spans)
    spans = list(spans)
    if quarantine is not None and quarantine.total_spans is None:
        quarantine.total_spans = len(spans)
    check_crc = bool(config.check_crc)
    step = make_device_flagstat_step(mesh)
    sharding = NamedSharding(mesh, P("data"))
    src = _resilient_source(path, config)
    pool = decode_pool(config)
    window = max(1, prefetch) * decode_pool_size(config)
    ring_slots = int(config.feed_ring_slots)
    # the ring is sized LAZILY to the ladder shapes the plan actually
    # produces (worst case [n_dev, 64, 65536] u32 is a quarter GB of
    # token staging on a wide mesh; a small-block plan needs a tiny
    # fraction of that).  Growing mints a fresh ring after draining the
    # old slots' in-flight handles — shapes only cross a ladder rung a
    # bounded number of times per run.
    ring_state: Dict[str, object] = {"ring": None, "B": 0, "P": 0}
    cancel = threading.Event()
    totals_vec = None
    pending: List[Tuple] = []          # (handles, chunks, records cap)

    def get_ring(B: int, Pg: int) -> StagingRing:
        ring = ring_state["ring"]
        if ring is not None and B <= ring_state["B"] \
                and Pg <= ring_state["P"]:
            return ring
        if ring is not None:
            for slot in ring.slots:
                if slot.in_flight is not None:
                    _block_in_flight(slot.in_flight)
                    slot.in_flight = None
        ring_state["B"] = max(B, int(ring_state["B"]))
        ring_state["P"] = max(Pg, int(ring_state["P"]))
        ring_state["ring"] = StagingRing(
            n_dev, int(ring_state["B"]),
            [TileSpec((int(ring_state["P"]),), np.uint32),  # tokens
             TileSpec((), np.int32),                        # n_tokens
             TileSpec((), np.int32),                        # isize
             TileSpec((2,), np.int32)],          # row 0: (start, stop)
            slots=ring_slots)
        return ring_state["ring"]

    def decode(span):
        def inner(s):
            return _tokenize_span_tokens(src, s, check_crc)
        with METRICS.timer("pipeline.host_decode"), \
                METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span("bam.host_decode_wall"):
            return decode_with_retry(inner, span, config,
                                     quarantine=quarantine)

    def dispatch_group(group: List[_TokenChunk]) -> None:
        nonlocal totals_vec
        B = max(_round_pow2(c.used, 8) for c in group)
        Pg = max(c.P for c in group)
        slot = get_ring(B, Pg).lease(cancel)
        if slot.in_flight is not None:
            # the slot's previous dispatch may still be transferring from
            # — or, on the CPU backend, COMPUTING OVER an alias of —
            # these buffers; the wait is time spent on device resolve of
            # an earlier group, so it accrues to the resolve wall
            with METRICS.timer("pipeline.device_inflate"), \
                    METRICS.span("bam.device_resolve_wall", wait=True), \
                    METRICS.span("staging.transfer_wait"):
                _block_in_flight(slot.in_flight)
            slot.in_flight = None
        tok, nt, isz, meta = slot.arrays
        for dev in range(n_dev):
            if dev < len(group):
                c = group[dev]
                tok[dev, :c.used, :c.P] = c.tokens
                nt[dev, :c.used] = c.n_tokens
                isz[dev, :c.used] = c.isize
                if c.used < B:
                    # stale token rows are inert under n_tokens == 0 and
                    # isize == 0; only the masks need zeroing
                    nt[dev, c.used:B] = 0
                    isz[dev, c.used:B] = 0
                meta[dev, 0, 0] = c.start
                meta[dev, 0, 1] = c.stop
            else:
                nt[dev, :B] = 0
                isz[dev, :B] = 0
                meta[dev, 0] = 0
        views = (tok[:, :B, :Pg], nt[:, :B], isz[:, :B], meta[:, :1])
        # chaos point at the shard_map step boundary: an injected fault
        # here models a device/runtime step failure — it unwinds the
        # whole device-plane run, which is exactly what the flagstat
        # ladder wrapper demotes on
        chaos.fire("device.step", blocks=int(sum(c.used for c in group)))
        with METRICS.timer("pipeline.device_inflate"), \
                METRICS.span("bam.device_resolve_wall",
                             blocks=int(sum(c.used for c in group))):
            args = [jax.device_put(v, sharding) for v in views]
            vec, n_all, tails, bad = step(*args)
            totals_vec = vec if totals_vec is None \
                else _ADD(totals_vec, vec)
        METRICS.count("pipeline.dispatch_bytes",
                      sum(int(v.nbytes) for v in views))
        # the slot's in-flight handle carries the step OUTPUTS, not just
        # the transferred inputs: a [:, :B, :P] view of a ring slot is a
        # CONTIGUOUS prefix, which CPU jax.device_put may zero-copy
        # alias — the resolve step would then still be reading the
        # buffer when the next group's pack overwrites it.  Waiting on
        # the outputs means the compute (hence every read of the
        # aliased memory) has finished before the slot is reused.
        slot.in_flight = (tuple(args), (vec, n_all, tails, bad))
        slot.release()
        pending.append(((n_all, tails, bad), list(group),
                        records_cap(B, Pg)))

    group: List[_TokenChunk] = []
    try:
        for chunk in _iter_windowed(pool, spans, decode, window,
                                    config=config):
            if chunk is None:
                continue
            group.append(chunk)
            if len(group) == n_dev:
                dispatch_group(group)
                group = []
        if group:
            dispatch_group(group)
    finally:
        cancel.set()

    # one bulk device_get drains every group's walk scalars (a per-group
    # fetch in the loop would sync the pipeline it exists to overlap);
    # the block accrues to the resolve wall — it IS waiting for the
    # device to finish the outstanding groups
    with METRICS.timer("pipeline.device_inflate"), \
            METRICS.span("bam.device_resolve_wall", drain=True):
        fetched = jax.device_get([p[0] for p in pending]) if pending \
            else []
    fix_spans: List[FileVirtualSpan] = []
    n_records = 0
    for (n_all, tails, bad), chunks, rec_cap in (
            (f, p[1], p[2]) for f, p in zip(fetched, pending)):
        for dev, c in enumerate(chunks):
            if int(bad[dev]):
                raise CorruptDataError(
                    f"malformed BAM record chain in span {c.span}")
            if int(n_all[dev]) > rec_cap:
                raise CorruptDataError(
                    f"record count {int(n_all[dev])} exceeds capacity "
                    f"{rec_cap} in span {c.span}")
            n_records += int(n_all[dev])
            tail = int(tails[dev])
            if tail < c.stop or c.used < c.n_blocks:
                fix_spans.append(c.fixup_span(tail))
    METRICS.count("pipeline.records", n_records)

    if fix_spans:
        # host fixup: the cut/remainder records go through the ordinary
        # projected-row plane and the cached flagstat tile step
        projection = FLAGSTAT_PROJECTION
        row_bytes = projection_row_bytes(projection)
        tile_step = make_flagstat_tile_step(mesh, projection=projection)

        def fix_rows():
            for fs in fix_spans:
                def inner(s):
                    return decode_span_prefix_host(
                        src, s, check_crc, "auto", projection,
                        want_voffs=False, header=header, config=config)[0]
                with METRICS.timer("pipeline.host_decode"), \
                        METRICS.wall_timer("pipeline.host_decode_wall"), \
                        METRICS.span("bam.host_decode_wall"):
                    rows = decode_with_retry(inner, fs, config,
                                             quarantine=quarantine)
                yield ((rows if rows is not None
                        else np.empty((0, row_bytes), np.uint8)),)

        fp = FeedPipeline(n_dev, 4096, (TileSpec((row_bytes,), np.uint8),),
                          balance=True, config=config, fmt="bam")

        def fix_dispatch(arrays, counts):
            nonlocal totals_vec
            t = jax.device_put(arrays[0], sharding)
            cc = jax.device_put(counts, sharding)
            with METRICS.span("bam.kernel_wall"):
                v = tile_step(t, cc)
                totals_vec = v if totals_vec is None \
                    else _ADD(totals_vec, v)
            return t, cc

        fp.feed(fix_rows(), fix_dispatch)

    if totals_vec is None:
        host = np.zeros(len(FLAGSTAT_FIELDS), dtype=np.int64)
    else:
        with METRICS.timer("pipeline.device_drain"), \
                METRICS.span("bam.combine_wall"):
            host = np.asarray(jax.device_get(totals_vec), dtype=np.int64)
    return _attach_quarantine(
        {k: int(host[i]) for i, k in enumerate(FLAGSTAT_FIELDS)},
        quarantine)


def make_device_seq_stats_step(mesh: Mesh, geometry: PayloadGeometry,
                               axis: str = "data") -> Callable:
    """Jitted sharded step over token chunks for the payload family:
    (tokens [n, B, P] u32, n_tokens [n, B], isize [n, B], meta [n, 1, 2])
    -> (psum'd f32 [2] / i32 [1+16] payload stat sums, per-device
    n_all / tail / bad).  Resolve + pack + record walk + segmented
    seq/qual gather + the fused Pallas payload kernel, all in one jitted
    call — the inflated bytes and the payload tiles never exist on the
    host."""
    key = ("device_seq_stats", tuple(mesh.devices.flat), mesh.axis_names,
           axis, geometry)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.inflate_device import resolve_walk_payload
    from hadoop_bam_tpu.ops.seq_pallas import seq_qual_stats

    interpret = mesh.devices.flat[0].platform != "tpu"

    def per_device(tokens, n_tokens, isize, meta):
        tokens, n_tokens = tokens[0], n_tokens[0]
        isize, meta = isize[0], meta[0]
        cols, seq, qual, valid, n_all, tail, bad = resolve_walk_payload(
            tokens, n_tokens, isize, meta[0, 0], meta[0, 1],
            max_len=geometry.max_len, seq_stride=geometry.seq_stride,
            qual_stride=geometry.qual_stride)
        # same length rule as make_seq_stats_step (clipped low too: a
        # corrupt negative l_seq must not reach the Pallas grid — the
        # drain raises on the walk's bad flag before stats are used)
        lengths = jnp.where(
            valid, jnp.clip(cols["l_seq"], 0, geometry.max_len), 0)
        # records_cap is a pow2 >= 16, block_n a pow2, so min divides
        stats = seq_qual_stats(
            seq, qual, lengths,
            block_n=min(geometry.block_n, seq.shape[0]),
            interpret=interpret)
        fvec, ivec = _payload_stats_tail(stats, valid, axis)
        return fvec, ivec, n_all[None], tail[None], bad[None]

    fn = shard_map(per_device, mesh=mesh, in_specs=(P(axis),) * 4,
                   out_specs=(P(), P(), P(axis), P(axis), P(axis)),
                   check_vma=False)
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def _seq_stats_device_plane(path: str, mesh: Mesh, config: HBamConfig,
                            header: SAMHeader,
                            geometry: PayloadGeometry,
                            spans: Optional[Sequence[FileVirtualSpan]],
                            quarantine: Optional[QuarantineManifest],
                            prefetch: int = 2) -> Dict[str, object]:
    """seq_stats through the token-feed device decode plane — the same
    overlap structure as ``_flagstat_device_plane`` (pool tokenize of
    group k+1 under device resolve of group k, StagingRing in-flight
    handles, one bulk scalar drain, host fixups for cut tails and
    over-wide spans), with the payload step in place of the flagstat
    reduce."""
    from hadoop_bam_tpu.ops.inflate_device import records_cap
    from hadoop_bam_tpu.ops.rans import _round_pow2
    from hadoop_bam_tpu.utils import native
    from hadoop_bam_tpu.utils.errors import CorruptDataError

    if not native.available():
        raise PlanError(
            "inflate_backend='device' needs the native tokenizer "
            "(hbam_deflate_tokenize_batch); native library unavailable")
    n_dev = int(np.prod(mesh.devices.shape))
    if spans is None:
        src0 = as_byte_source(path)
        n_spans = max(n_dev, int(np.ceil(src0.size
                                         / DEVICE_PLANE_SPAN_BYTES)))
        src0.close()
        from hadoop_bam_tpu.split.planners import plan_spans_cached
        with METRICS.span("bam.plan_wall", spans=n_spans):
            spans = plan_spans_cached(path, header, config,
                                      num_spans=n_spans)
    spans = list(spans)
    if quarantine is not None and quarantine.total_spans is None:
        quarantine.total_spans = len(spans)
    check_crc = bool(config.check_crc)
    step = make_device_seq_stats_step(mesh, geometry)
    sharding = NamedSharding(mesh, P("data"))
    src = _resilient_source(path, config)
    pool = decode_pool(config)
    window = max(1, prefetch) * decode_pool_size(config)
    ring_slots = int(config.feed_ring_slots)
    ring_state: Dict[str, object] = {"ring": None, "B": 0, "P": 0}
    cancel = threading.Event()
    totals = _StatTotals()
    pending: List[Tuple] = []          # (handles, chunks, records cap)

    def get_ring(B: int, Pg: int) -> StagingRing:
        ring = ring_state["ring"]
        if ring is not None and B <= ring_state["B"] \
                and Pg <= ring_state["P"]:
            return ring
        if ring is not None:
            for slot in ring.slots:
                if slot.in_flight is not None:
                    _block_in_flight(slot.in_flight)
                    slot.in_flight = None
        ring_state["B"] = max(B, int(ring_state["B"]))
        ring_state["P"] = max(Pg, int(ring_state["P"]))
        ring_state["ring"] = StagingRing(
            n_dev, int(ring_state["B"]),
            [TileSpec((int(ring_state["P"]),), np.uint32),  # tokens
             TileSpec((), np.int32),                        # n_tokens
             TileSpec((), np.int32),                        # isize
             TileSpec((2,), np.int32)],          # row 0: (start, stop)
            slots=ring_slots)
        return ring_state["ring"]

    def decode(span):
        def inner(s):
            return _tokenize_span_tokens(src, s, check_crc)
        with METRICS.timer("pipeline.host_decode"), \
                METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span("bam.host_decode_wall"):
            return decode_with_retry(inner, span, config,
                                     quarantine=quarantine)

    def dispatch_group(group: List[_TokenChunk]) -> None:
        B = max(_round_pow2(c.used, 8) for c in group)
        Pg = max(c.P for c in group)
        slot = get_ring(B, Pg).lease(cancel)
        if slot.in_flight is not None:
            with METRICS.timer("pipeline.device_inflate"), \
                    METRICS.span("bam.device_resolve_wall", wait=True), \
                    METRICS.span("staging.transfer_wait"):
                _block_in_flight(slot.in_flight)
            slot.in_flight = None
        tok, nt, isz, meta = slot.arrays
        for dev in range(n_dev):
            if dev < len(group):
                c = group[dev]
                tok[dev, :c.used, :c.P] = c.tokens
                nt[dev, :c.used] = c.n_tokens
                isz[dev, :c.used] = c.isize
                if c.used < B:
                    nt[dev, c.used:B] = 0
                    isz[dev, c.used:B] = 0
                meta[dev, 0, 0] = c.start
                meta[dev, 0, 1] = c.stop
            else:
                nt[dev, :B] = 0
                isz[dev, :B] = 0
                meta[dev, 0] = 0
        views = (tok[:, :B, :Pg], nt[:, :B], isz[:, :B], meta[:, :1])
        chaos.fire("device.step", blocks=int(sum(c.used for c in group)))
        with METRICS.timer("pipeline.device_inflate"), \
                METRICS.span("bam.device_resolve_wall",
                             blocks=int(sum(c.used for c in group))):
            args = [jax.device_put(v, sharding) for v in views]
            fvec, ivec, n_all, tails, bad = step(*args)
            totals.add(fvec, ivec)
        METRICS.count("pipeline.dispatch_bytes",
                      sum(int(v.nbytes) for v in views))
        # in-flight carries the step OUTPUTS: CPU device_put may
        # zero-copy alias the contiguous ring-prefix views (see
        # _flagstat_device_plane's dispatch for the full story)
        slot.in_flight = (tuple(args), (fvec, ivec, n_all, tails, bad))
        slot.release()
        pending.append(((n_all, tails, bad), list(group),
                        records_cap(B, Pg)))

    group: List[_TokenChunk] = []
    try:
        for chunk in _iter_windowed(pool, spans, decode, window,
                                    config=config):
            if chunk is None:
                continue
            group.append(chunk)
            if len(group) == n_dev:
                dispatch_group(group)
                group = []
        if group:
            dispatch_group(group)
    finally:
        cancel.set()

    with METRICS.timer("pipeline.device_inflate"), \
            METRICS.span("bam.device_resolve_wall", drain=True):
        fetched = jax.device_get([p[0] for p in pending]) if pending \
            else []
    fix_spans: List[FileVirtualSpan] = []
    n_records = 0
    for (n_all, tails, bad), chunks, rec_cap in (
            (f, p[1], p[2]) for f, p in zip(fetched, pending)):
        for dev, c in enumerate(chunks):
            if int(bad[dev]):
                raise CorruptDataError(
                    f"malformed BAM record chain in span {c.span}")
            if int(n_all[dev]) > rec_cap:
                raise CorruptDataError(
                    f"record count {int(n_all[dev])} exceeds capacity "
                    f"{rec_cap} in span {c.span}")
            n_records += int(n_all[dev])
            tail = int(tails[dev])
            if tail < c.stop or c.used < c.n_blocks:
                fix_spans.append(c.fixup_span(tail))
    METRICS.count("pipeline.records", n_records)

    if fix_spans:
        # host fixup: cut/remainder records go through the ordinary
        # payload host packer and the cached host payload step — the
        # same stats semantics, so totals merge exactly
        widths = (PREFIX, geometry.seq_stride, geometry.qual_stride)
        host_step = make_seq_stats_step(mesh, geometry)

        def fix_rows():
            for fs in fix_spans:
                def inner(s):
                    return decode_span_payload_host(
                        src, s, geometry, check_crc, "auto",
                        header=header, config=config)[:3]
                with METRICS.timer("pipeline.host_decode"), \
                        METRICS.wall_timer("pipeline.host_decode_wall"), \
                        METRICS.span("bam.host_decode_wall"):
                    out = decode_with_retry(inner, fs, config,
                                            quarantine=quarantine)
                yield out if out is not None else tuple(
                    np.empty((0, w), np.uint8) for w in widths)

        fp = FeedPipeline(n_dev, geometry.tile_records,
                          [TileSpec((w,), np.uint8) for w in widths],
                          block_n=geometry.block_n, balance=True,
                          config=config, fmt="bam")

        def fix_dispatch(arrays, counts):
            args = [jax.device_put(a, sharding) for a in arrays]
            cc = jax.device_put(counts, sharding)
            with METRICS.span("bam.kernel_wall"):
                totals.add(*host_step(*args, cc))
            return (*args, cc)

        fp.feed(fix_rows(), fix_dispatch)

    return _attach_quarantine(_payload_stats_result(totals), quarantine)


def flagstat_file(path: str, mesh: Optional[Mesh] = None,
                  config: HBamConfig = DEFAULT_CONFIG,
                  geometry: Optional[DecodeGeometry] = None,
                  header: Optional[SAMHeader] = None,
                  spans: Optional[Sequence[FileVirtualSpan]] = None,
                  prefetch: int = 2,
                  quarantine: Optional[QuarantineManifest] = None,
                  ) -> Dict[str, int]:
    """Distributed flagstat over a whole BAM — the minimum end-to-end slice
    (SURVEY.md section 7): plan -> shard -> inflate -> pack prefixes ->
    device reduce.

    A thin plan builder since the plan/execute layer landed: compiles to
    ``plan.builders.flagstat_plan`` and runs through the one executor
    (byte-identical to the inline path ``_flagstat_impl``, which the
    ``plan_overhead_pct`` bench row pins against this wrapper)."""
    from hadoop_bam_tpu.plan import builders
    from hadoop_bam_tpu.plan import executor as plan_executor

    plan = builders.flagstat_plan(path, config)
    return plan_executor.execute(plan, config=config, mesh=mesh,
                                 geometry=geometry, header=header,
                                 spans=spans, prefetch=prefetch,
                                 quarantine=quarantine)


def _flagstat_impl(path: str, mesh: Optional[Mesh] = None,
                   config: HBamConfig = DEFAULT_CONFIG,
                   geometry: Optional[DecodeGeometry] = None,
                   header: Optional[SAMHeader] = None,
                   spans: Optional[Sequence[FileVirtualSpan]] = None,
                   prefetch: int = 2,
                   quarantine: Optional[QuarantineManifest] = None,
                   ) -> Dict[str, int]:
    """The flagstat mesh-feed implementation (executor runner).

    Uses the columnar projected-tile path: host threads inflate spans and
    pack just the flagstat columns (11 B/record over the link instead of
    whole spans); the device sees dense tiles and reduces them with one
    psum'd step per tile group.  Transfers issue sequentially from one
    thread (axon tunnel links collapse under concurrent device_put
    streams); the host decode pool runs ``prefetch * n_workers`` spans
    ahead of the transfer loop, which bounds peak host memory.
    """
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS

    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if geometry is None:
        geometry = DecodeGeometry()
    cap = geometry.tile_records
    if header is None:
        header, _ = read_bam_header(path)

    # the upgraded quarantine circuit: a file whose last run tripped the
    # bad-span-fraction breaker fast-fails here while OPEN (retry_after
    # hint attached) instead of re-planning a doomed run; HALF_OPEN lets
    # this run through as the probe and a clean finish heals it
    check_quarantine_gate(path, config)
    intervals = parse_config_intervals(config, header)
    # the demotion ladder: plane-local faults demote device -> native ->
    # zlib mid-run with byte-identical results and heal back through
    # half-open probes (resilience/domains.py)
    ladder = decode_ladder(path, resolve_inflate_backend(config), config) \
        if config.adaptive_planes else None
    device_blame: Optional[BaseException] = None
    # THE routing decision (plan/executor.select_plane): device plane
    # when the token-feed DAG applies and every gate passes (the breaker
    # gate consumes a half-open probe slot, so select_plane consults it
    # last, only when the device path would actually run)
    decision = select_plane(SourceIR(path, "bam"), FLAGSTAT_DAG, config,
                            intervals=intervals, ladder=ladder)
    if decision.plane == "device":
        # the token-feed device decode plane (resolve+walk+unpack on the
        # mesh).  Interval filtering needs whole-span offsets and
        # skip_bad_spans needs span-granular quarantine — both fall back
        # to the host planes, same gating as fused chunk streaming.
        try:
            out = _flagstat_device_plane(path, mesh, config, header,
                                         spans, quarantine,
                                         prefetch=prefetch)
            if ladder is not None:
                ladder.record_success("device")
            quarantine_run_ok(path, config)
            return out
        except Exception as e:  # noqa: BLE001 — plane policy boundary
            if ladder is None or not ladder.demotable("device", e):
                raise
            # mid-run demotion: the device totals died with the
            # exception, so the host planes recompute from scratch —
            # byte-identical results, slower plane.  Blame lands on the
            # device domain only if the host run COMPLETES (oracle
            # confirmation, below); its breaker opening keeps later
            # runs on the host planes until a half-open probe heals.
            logger.warning("device decode plane failed (%s: %s); "
                           "demoting to the host planes for %s",
                           type(e).__name__, e, path)
            device_blame = e
    host_backend = decision.host_backend

    if spans is None:
        # Span size trades host-decode parallelism (smaller = more threads
        # busy) against per-span Python overhead; tiles repack across span
        # boundaries, so this does NOT couple to the device geometry.
        # 4 MiB measured best on a 1-CPU host (sweep in commit history).
        span_bytes = 4 << 20
        src = as_byte_source(path)
        n_spans = max(n_dev, int(np.ceil(src.size / span_bytes)))
        src.close()
        from hadoop_bam_tpu.split.planners import plan_spans_cached
        with METRICS.span("bam.plan_wall", spans=n_spans):
            spans = plan_spans_cached(path, header, config,
                                      num_spans=n_spans)

    projection = FLAGSTAT_PROJECTION
    row_bytes = projection_row_bytes(projection)
    step = make_flagstat_tile_step(mesh, projection=projection)
    sharding = NamedSharding(mesh, P("data"))
    spans = list(spans)
    if quarantine is None:
        quarantine = QuarantineManifest()
    if quarantine.total_spans is None:
        quarantine.total_spans = len(spans)
    src = _resilient_source(path, config)
    pool = decode_pool(config)
    window = max(1, prefetch) * decode_pool_size(config)
    totals_vec = None
    check_crc = bool(config.check_crc)

    # Chunk-streamed fused decode: each pool worker starts its span's
    # native job (fetch inside the retry boundary) and hands back a lazy
    # chunk iterator; the FeedPipeline's packer consumes row chunks the
    # moment the native walk publishes them, so staging tiles pack while
    # the span's tail is still inflating.  Gated off (in select_plane,
    # with the other routing gates) when skip_bad_spans needs
    # span-granular quarantine or when interval filtering needs the
    # whole span's offsets.
    stream_fused = decision.stream_fused
    if stream_fused:
        window = _stream_window(window)
    ranges = projection_ranges(projection)

    def decode(span):
        def inner(s, plane=None):
            # ladder-aware: decode_with_retry drives ``plane`` down the
            # demotion ladder on corrupt failures (None = static config
            # plane, the ladder-off path)
            hb = host_backend if plane is None else plane
            if hb in ("auto", "native"):
                # chaos point for plane-local native faults — fires
                # INSIDE the retry/ladder boundary, so injected faults
                # retry/demote exactly like real ones
                chaos.fire("decode.native", span=str(s))
            if stream_fused and hb in ("auto", "native"):
                # the tail-cut fallback runs LATER, on the consumer
                # thread: it re-reads the span, so it gets its own pass
                # through the retry policy (transients there must heal
                # exactly like the eager fetch's do)
                return _iter_fused_span_chunks(
                    src, s, "rows", sel=ranges, row_bytes=row_bytes,
                    check_crc=check_crc, config=config,
                    fallback_fn=lambda: decode_with_retry(
                        lambda s2: (decode_span_prefix_host(
                            src, s2, check_crc, host_backend, projection,
                            want_voffs=False, header=header,
                            config=_fused_off(config))[0],),
                        s, config))
            rows, _voffs = decode_span_prefix_host(
                src, s, check_crc, hb, projection,
                want_voffs=False, intervals=intervals, header=header,
                config=config if hb != "zlib" else _fused_off(config))
            return rows
        with METRICS.timer("pipeline.host_decode"), \
                METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span("bam.host_decode_wall"):
            out = decode_with_retry(inner, span, config,
                                    quarantine=quarantine, ladder=ladder)
        return out if out is not None \
            else np.empty((0, row_bytes), dtype=np.uint8)

    def row_stream():
        return _flatten_span_stream(
            _iter_windowed(pool, spans, decode, window,
                           cleanup=_close_stream, config=config))
    # Ring-staged groups + NO blocking between dispatches: the packer
    # thread writes rows straight into a leased [n_dev, cap, row] slot
    # (no per-group allocation, no np.stack, no pad memset) while THIS
    # thread issues device_put/step for the previous group — sequential
    # single-thread issue keeps the tunnel link from collapsing the way
    # concurrent multi-thread puts do, and the single device_get at the
    # end drains the whole async queue.  balance: the final partial
    # group spreads across all shards and shrinks to a dispatch bucket
    # — a file smaller than one full group otherwise lands entirely on
    # device 0 and ships n_dev*cap rows of padding (the 8-device
    # inverse-scaling tax); the bucket ladder bounds the extra jit
    # shapes at two.
    fp = FeedPipeline(n_dev, cap, (TileSpec((row_bytes,), np.uint8),),
                      balance=True, config=config, fmt="bam")

    def dispatch(arrays, counts):
        nonlocal totals_vec
        with METRICS.timer("pipeline.device_put"):
            t = jax.device_put(arrays[0], sharding)
            c = jax.device_put(counts, sharding)
        with METRICS.span("bam.kernel_wall"):
            vec = step(t, c)
            totals_vec = vec if totals_vec is None \
                else _ADD(totals_vec, vec)
        return t, c      # in-flight handles: the ring waits before reuse

    fp.feed(row_stream(), dispatch)
    if totals_vec is None:
        host = np.zeros(len(FLAGSTAT_FIELDS), dtype=np.int64)
    else:
        with METRICS.timer("pipeline.device_drain"), \
                METRICS.span("bam.combine_wall"):
            host = np.asarray(jax.device_get(totals_vec), dtype=np.int64)
    if ladder is not None and device_blame is not None:
        # the host planes completed the run the device plane could not:
        # oracle-confirmed plane-local fault — charge the device domain
        # (enough of these open its breaker; a half-open probe heals it)
        ladder.confirm_failure("device", device_blame)
    quarantine_run_ok(path, config)
    return _attach_quarantine(
        {k: int(host[i]) for i, k in enumerate(FLAGSTAT_FIELDS)}, quarantine)


# Coverage row layout: the fixed-field projection (offsets sourced from
# ops/unpack_bam.py::FIXED_FIELDS — ONE place owns the BAM field map; the
# high-position regression in test_cigar.py is what hand-copied offsets
# cost), then the cigar words.
_COVERAGE_PROJECTION = ("refid", "pos", "n_cigar", "flag")
_CIGAR_ROW_HDR = projection_row_bytes(_COVERAGE_PROJECTION)   # 12


def _cigar_row_bytes(max_cigar: int) -> int:
    return _CIGAR_ROW_HDR + 4 * max_cigar


def decode_span_cigar_rows(source, span: FileVirtualSpan, max_cigar: int,
                           check_crc: bool = False,
                           config: Optional[HBamConfig] = None) -> np.ndarray:
    """Host stage of the coverage path: inflate a span and pack one dense
    row per record — the (refid, pos, n_cigar, flag) projection + the
    cigar words, zero-padded to ``max_cigar`` ops.  ~268 B/record over
    the link instead of whole padded spans (the flagstat projected-tile
    idea applied to the one variable-length series coverage needs).

    Ops past ``max_cigar`` are dropped from the row; the row's n_cigar
    field keeps the FULL count so the driver can raise outside the
    span-retry boundary (a user-parameter error must not be retried or
    skip_bad_spans-eaten as corruption).
    """
    # coverage has no device plane (the cigar series is variable-length);
    # "device" rides the host planes, "zlib"/"native" are honored
    # (plan/executor owns the mapping)
    host_backend = host_backend_for(config)
    got = _decode_span_fused(source, span, "offsets", check_crc=check_crc,
                             want_voffs=False, config=config) \
        if _use_fused(config, host_backend) else None
    if got is not None:
        d, o, _voffs, _ = got      # fused: inflate+walk+CRC in one sweep
    else:
        d, o, _voffs, _ = _decode_span_core(source, span, check_crc,
                                            host_backend, want_voffs=False)
    c = o.size
    w = _cigar_row_bytes(max_cigar)
    rows = np.zeros((c, w), dtype=np.uint8)
    if c == 0:
        return rows
    o64 = o.astype(np.int64)
    dst = 0
    for src_off, width in projection_ranges(_COVERAGE_PROJECTION):
        rows[:, dst:dst + width] = \
            d[o64[:, None] + np.arange(src_off, src_off + width)]
        dst += width
    nc_off = _CIGAR_ROW_HDR - 4          # n_cigar u16 within the row
    n_cigar = (rows[:, nc_off].astype(np.int64)
               | (rows[:, nc_off + 1].astype(np.int64) << 8))
    l_read_name = d[o64 + 12].astype(np.int64)
    cigar_off = o64 + PREFIX + l_read_name
    # rows keep the FULL n_cigar value; ops past max_cigar are dropped
    # here and the DRIVER raises (outside the span-retry boundary, so a
    # user-parameter error is neither retried nor skip_bad_spans-eaten)
    byte_counts = 4 * np.minimum(n_cigar, max_cigar)
    total_b = int(byte_counts.sum())
    if total_b:
        starts_b = np.cumsum(byte_counts) - byte_counts
        flat_b = (np.arange(total_b, dtype=np.int64)
                  - np.repeat(starts_b, byte_counts))
        row_i = np.repeat(np.arange(c, dtype=np.int64), byte_counts)
        rows[row_i, _CIGAR_ROW_HDR + flat_b] = \
            d[np.repeat(cigar_off, byte_counts) + flat_b]
    return rows


def make_coverage_step(mesh: Mesh, window: int, max_cigar: int,
                       axis: str = "data") -> Callable:
    """Jitted sharded step: dense cigar-row tiles -> per-base window depth.

    Returns PER-DEVICE depth [n_dev, window] (no collective): the driver
    accumulates shard-locally across tile groups and reduces across
    devices once at the end, instead of paying a window-sized psum per
    dispatch."""
    key = ("coverage", tuple(mesh.devices.flat), mesh.axis_names, axis,
           window, max_cigar)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.cigar import window_coverage_from_tiles

    def per_device(tile, count, target_refid, win_start):
        tile, count = tile[0], count[0]
        cols = unpack_projected_tile(tile[:, :_CIGAR_ROW_HDR],
                                     _COVERAGE_PROJECTION)
        ops4 = tile[:, _CIGAR_ROW_HDR:].reshape(
            tile.shape[0], max_cigar, 4).astype(jnp.uint32)
        ops = (ops4[..., 0] | (ops4[..., 1] << 8) | (ops4[..., 2] << 16)
               | (ops4[..., 3] << 24))
        valid = jnp.arange(tile.shape[0], dtype=jnp.int32) < count
        depth = window_coverage_from_tiles(
            ops, cols["pos"], cols["refid"], cols["flag"], valid,
            target_refid, win_start, window)
        return depth[None]

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(), P()),
                   out_specs=P(axis))
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def coverage_file(path: str, region, mesh: Optional[Mesh] = None,
                  config: HBamConfig = DEFAULT_CONFIG,
                  header: Optional[SAMHeader] = None,
                  spans: Optional[Sequence[FileVirtualSpan]] = None,
                  max_cigar: int = 64, tile_records: int = 1 << 15,
                  prefetch: int = 2,
                  quarantine: Optional[QuarantineManifest] = None,
                  ) -> np.ndarray:
    """Distributed per-base aligned-base depth over a genomic window —
    the first analysis op past flagstat (SURVEY.md section 7 kernel (b)):
    plan -> shard -> inflate -> pack cigar rows -> device diff-scatter
    pileup -> psum.

    ``region`` is a samtools-style string ("chr20:1,000-2,000", 1-based
    inclusive) or an Interval.  Returns int32 depth, one entry per base.
    When a ``.bai`` sidecar exists the span plan is trimmed to the
    region's chunks; otherwise the whole file streams through and rows
    outside the region mask to zero on device.
    """
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.split.intervals import Interval, resolve_interval

    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if header is None:
        header, _ = read_bam_header(path)
    if not isinstance(region, Interval):
        region = resolve_interval(region, header.ref_names)
    if region.rname not in header.ref_names:
        raise ValueError(f"region reference {region.rname!r} not in header")
    target_refid = header.ref_names.index(region.rname)
    ref_len = header.ref_lengths[target_refid]
    end = min(region.end, ref_len)
    window = end - region.start + 1
    if window <= 0:
        raise ValueError(f"empty region {region}")
    if window > (1 << 26):
        raise ValueError(f"region spans {window} bases; cap is 2^26 — "
                         f"tile larger regions across calls")
    win_start = region.start - 1          # 0-based half-open window

    if spans is None:
        # pass the Interval OBJECT to the planner — round-tripping it
        # through the config string form would misparse contig names
        # that themselves contain ':' (GRCh38 HLA alts)
        from hadoop_bam_tpu.split.bai import plan_interval_spans
        with METRICS.span("bam.plan_wall"):
            spans = plan_interval_spans(path, [region], header)
            if spans is None:               # no .bai sidecar: whole file
                span_bytes = 4 << 20
                src = as_byte_source(path)
                n_spans = max(n_dev, int(np.ceil(src.size / span_bytes)))
                src.close()
                from hadoop_bam_tpu.split.planners import plan_spans_cached
                spans = plan_spans_cached(path, header, config,
                                          num_spans=n_spans)

    sharding = NamedSharding(mesh, P("data"))
    rep = NamedSharding(mesh, P())
    check_crc = bool(config.check_crc)
    row_w = _cigar_row_bytes(max_cigar)
    window_depth = None                   # [n_dev, window], device-sharded
    tref = jax.device_put(np.int32(target_refid), rep)
    wstart = jax.device_put(np.int32(win_start), rep)

    spans = list(spans)
    if quarantine is not None and quarantine.total_spans is None:
        quarantine.total_spans = len(spans)
    src = _resilient_source(path, config)
    pool = decode_pool(config)

    def decode(span):
        def inner(s):
            return decode_span_cigar_rows(src, s, max_cigar,
                                          check_crc, config=config)
        with METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span("bam.host_decode_wall"):
            out = decode_with_retry(inner, span, config,
                                    quarantine=quarantine)
        return out if out is not None else np.zeros((0, row_w),
                                                    np.uint8)

    stream = _iter_windowed(pool, spans, decode,
                            max(1, prefetch) * decode_pool_size(config),
                            config=config)
    # full-width ring tiles; dispatch slices each group down to its real
    # pow2-bucketed op width before it crosses the link (fixed_shape:
    # the HEIGHT never shrinks — the step is cached per (window, mc))
    # count_bytes=False: this dispatch ships a width-sliced cut of the
    # ring views, so it counts the real transferred bytes itself
    fp = FeedPipeline(n_dev, tile_records,
                      (TileSpec((row_w,), np.uint8),),
                      fixed_shape=True, count_bytes=False, config=config,
                      fmt="bam")

    def dispatch(arrays, counts):
        # most records carry far fewer ops than max_cigar; slice the
        # tile to the group's real op width (pow2-bucketed so the jit
        # cache stays small) before it crosses the link
        tiles = arrays[0]
        mc = 1
        nc_off = _CIGAR_ROW_HDR - 4
        for dev in range(n_dev):
            c = int(counts[dev])
            if c:
                t = tiles[dev]
                nc = (t[:c, nc_off].astype(np.int32)
                      | (t[:c, nc_off + 1].astype(np.int32) << 8))
                mc = max(mc, int(nc.max()))
        if mc > max_cigar:
            raise PlanError(
                f"record with {mc} cigar ops exceeds "
                f"max_cigar={max_cigar}; pass a larger max_cigar")
        mc = min(max_cigar, max(8, 1 << (mc - 1).bit_length()))
        w = _cigar_row_bytes(mc)
        step = make_coverage_step(mesh, window, mc)
        cut = tiles[:, :, :w]
        METRICS.count("pipeline.dispatch_bytes",
                      int(cut.nbytes) + int(counts.nbytes))
        t = jax.device_put(cut, sharding)
        c = jax.device_put(counts, sharding)
        with METRICS.span("bam.kernel_wall"):
            out = step(t, c, tref, wstart)
            nonlocal window_depth
            window_depth = out if window_depth is None else \
                window_depth + out    # shard-local add, no collective
        return t, c      # in-flight handles: the ring waits before reuse

    fp.feed(((r,) for r in stream), dispatch)
    if window_depth is None:
        return np.zeros(window, np.int32)
    # one cross-device reduce at the end instead of one psum per dispatch
    with METRICS.span("bam.combine_wall"):
        total = jnp.sum(window_depth, axis=0)
        return np.asarray(jax.device_get(total), dtype=np.int32)
