"""The sharded decode pipeline: spans -> host inflate -> device SoA batches.

This is the TPU rebuild of the reference's read hot path (SURVEY.md section
3.2): where a map task ran ``BAMRecordReader.nextKeyValue()`` per record, a
mesh step consumes one *span batch* — per-device inflated bytes + record
offsets, static shapes — and unpacks/reduces on all devices at once:

    plan (once, host 0)                 hb/BAMInputFormat.getSplits
    fetch + inflate span (host threads) BlockCompressedInputStream + zlib JNI
    walk record offsets (host/native)   implicit in per-record decode
    unpack fields + compute (device)    htsjdk BAMRecordCodec.decode + mapper
    psum stats over the data axis       MR shuffle/reduce

Host stages for batch k+1 overlap device compute for batch k via a prefetch
thread pool (the HBM-feed analog of MapReduce's record-ahead buffering).
"""
from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import functools
import threading
from typing import Callable, Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.bam import SAMHeader
from hadoop_bam_tpu.ops import inflate as inflate_ops
from hadoop_bam_tpu.ops.flagstat import flagstat_from_columns
from hadoop_bam_tpu.ops.unpack_bam import unpack_fixed_fields
from hadoop_bam_tpu.split.planners import plan_bam_spans
from hadoop_bam_tpu.split.spans import FileVirtualSpan
from hadoop_bam_tpu.utils.seekable import as_byte_source


@dataclasses.dataclass(frozen=True)
class DecodeGeometry:
    """Static shapes of one device's slice of a span batch (jit contract)."""
    bytes_cap: int = 1 << 24       # inflated bytes per device per step
    records_cap: int = 1 << 18     # record offsets per device per step

    def round_trip_bytes(self) -> int:
        return self.bytes_cap + 4 * self.records_cap


@dataclasses.dataclass
class HostSpanBatch:
    """Host-side decoded span group, ready to stack for n devices."""
    data: np.ndarray       # [n_dev, bytes_cap] uint8
    offsets: np.ndarray    # [n_dev, records_cap] int32
    n_records: np.ndarray  # [n_dev] int32
    voffsets: List[np.ndarray]  # per-device per-record virtual offsets


def decode_span_host(source, span: FileVirtualSpan, geometry: DecodeGeometry,
                     check_crc: bool = False,
                     inflate_backend: str = "auto",
                     ) -> Tuple[np.ndarray, np.ndarray, int, np.ndarray]:
    """Fetch + inflate one span and walk its records (host stage).

    Returns (data[bytes_cap], offsets[records_cap], n_records, voffsets[n]).
    Only records *starting* inside the span are owned (reference reader
    contract); the final record may extend into the following blocks, which
    are fetched as needed.
    """
    from hadoop_bam_tpu.formats import bgzf

    src = as_byte_source(source)
    start_c, start_u = span.start
    end_c, end_u = span.end

    # 1. Batched inflate of the whole blocks in [start_c, end_c).
    raw = src.pread(start_c, max(end_c - start_c, 0))
    if raw:
        table = inflate_ops.block_table(raw)
        data, ubase = inflate_ops.inflate_span(raw, table,
                                               backend=inflate_backend)
        if check_crc:
            inflate_ops.verify_crcs(raw, table, data, ubase)
        abs_coffs = table["coffset"] + start_c
        next_c = end_c
    else:
        data = np.empty(0, dtype=np.uint8)
        ubase = np.empty(0, dtype=np.int64)
        abs_coffs = np.empty(0, dtype=np.int64)
        next_c = start_c

    def append_block(coffset: int) -> int:
        """Inflate the block at ``coffset`` onto the buffer; returns its
        compressed size."""
        nonlocal data, ubase, abs_coffs
        head = src.pread(coffset, bgzf.MAX_BLOCK_SIZE)
        info = bgzf.parse_block_header(head, 0)
        extra = bgzf.inflate_block(head, info, check_crc=check_crc)
        ubase = np.append(ubase, data.size)
        abs_coffs = np.append(abs_coffs, coffset)
        data = np.concatenate([data, np.frombuffer(extra, np.uint8)])
        return info.block_size

    # 2. The span may end inside the block at end_c: its first end_u inflated
    #    bytes still hold records owned by this span.
    if end_u > 0 and end_c < src.size:
        end_inflated = data.size + end_u
        next_c = end_c + append_block(end_c)
    else:
        end_inflated = data.size

    # 3+4. Walk record boundaries; own records starting in
    #    [start_u, end_inflated).  If the walk's tail (first incomplete
    #    record) starts before end_inflated, an owned record is cut at the
    #    buffer end — append following blocks and re-walk until it completes
    #    (reference reader contract: the last record may extend past the
    #    split's end voffset).
    while True:
        offs, tail = inflate_ops.walk_records(data, start=start_u)
        if tail < end_inflated and next_c < src.size:
            next_c += append_block(next_c)
            continue
        break
    offs = offs[offs < max(end_inflated, 1)]

    # 5. Map record offsets back to packed virtual offsets.
    if offs.size:
        blk = np.searchsorted(ubase, offs, side="right") - 1
        voffs = (abs_coffs[blk].astype(np.uint64) << np.uint64(16)) | \
            (offs - ubase[blk]).astype(np.uint64)
    else:
        voffs = np.empty(0, dtype=np.uint64)

    n = int(offs.size)
    g = geometry
    if data.size > g.bytes_cap or n > g.records_cap:
        raise ValueError(
            f"span exceeds geometry: {data.size}B/{n} records vs caps "
            f"{g.bytes_cap}B/{g.records_cap} — plan smaller spans")
    out_data = np.zeros(g.bytes_cap, dtype=np.uint8)
    out_data[:data.size] = data
    out_offs = np.zeros(g.records_cap, dtype=np.int32)
    out_offs[:n] = offs
    return out_data, out_offs, n, voffs


def stack_span_group(source, spans: Sequence[FileVirtualSpan], n_dev: int,
                     geometry: DecodeGeometry, check_crc: bool = False,
                     executor: Optional[cf.ThreadPoolExecutor] = None,
                     ) -> HostSpanBatch:
    """Decode up to n_dev spans (threaded) and stack into device-batch shape;
    missing spans become empty shards (zero records)."""
    spans = list(spans)[:n_dev]
    results = [None] * n_dev

    def work(i):
        return decode_span_host(source, spans[i], geometry, check_crc)

    if executor is None:
        outs = [work(i) for i in range(len(spans))]
    else:
        outs = list(executor.map(work, range(len(spans))))
    data = np.zeros((n_dev, geometry.bytes_cap), dtype=np.uint8)
    offsets = np.zeros((n_dev, geometry.records_cap), dtype=np.int32)
    counts = np.zeros((n_dev,), dtype=np.int32)
    voffs: List[np.ndarray] = [np.empty(0, dtype=np.uint64)] * n_dev
    for i, (d, o, n, v) in enumerate(outs):
        data[i], offsets[i], counts[i], voffs[i] = d, o, n, v
    return HostSpanBatch(data, offsets, counts, voffs)


# ---------------------------------------------------------------------------
# Device steps
# ---------------------------------------------------------------------------

_STEP_CACHE: Dict[Tuple, Callable] = {}
_TRANSFER_LOCK = threading.Lock()


def make_flagstat_step(mesh: Mesh, axis: str = "data") -> Callable:
    """Jitted sharded step: (data [n,D], offsets [n,N], counts [n]) ->
    flagstat dict (replicated scalars, psum over the data axis).

    Cached per (mesh, axis): jax.jit keys on function identity, so rebuilding
    the closure per call would recompile every step (a silent 20-40s per-call
    tax on real TPUs)."""
    key = ("flagstat", tuple(mesh.devices.flat), mesh.axis_names, axis)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS

    def per_device(data, offsets, count):
        # shard_map gives [1, D] slices; drop the leading axis
        data, offsets, count = data[0], offsets[0], count[0]
        cols = unpack_fixed_fields(data, offsets)
        valid = jnp.arange(offsets.shape[0], dtype=jnp.int32) < count
        stats = flagstat_from_columns(cols, valid)
        # one stacked vector, not 16 scalars: a D2H sync per scalar costs
        # ~100ms each over remote-tunnel TPU links
        vec = jnp.stack([stats[k] for k in FLAGSTAT_FIELDS])
        return jax.lax.psum(vec, axis)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(axis)),
                   out_specs=P())
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def make_unpack_step(mesh: Mesh, axis: str = "data") -> Callable:
    """Jitted sharded step returning sharded SoA columns + valid mask —
    the feed for downstream mesh compute (the 'mapper' input)."""
    key = ("unpack", tuple(mesh.devices.flat), mesh.axis_names, axis)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    def per_device(data, offsets, count):
        data, offsets, count = data[0], offsets[0], count[0]
        cols = unpack_fixed_fields(data, offsets)
        valid = jnp.arange(offsets.shape[0], dtype=jnp.int32) < count
        cols = dict(cols)
        cols["valid"] = valid
        return jax.tree.map(lambda a: a[None], cols)

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis), P(axis), P(axis)),
                   out_specs=P(axis))
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


# ---------------------------------------------------------------------------
# End-to-end driver
# ---------------------------------------------------------------------------

def iter_span_groups(spans: Sequence[FileVirtualSpan], n_dev: int
                     ) -> Iterator[List[FileVirtualSpan]]:
    spans = list(spans)
    for i in range(0, len(spans), n_dev):
        yield spans[i:i + n_dev]


def flagstat_file(path: str, mesh: Optional[Mesh] = None,
                  config: HBamConfig = DEFAULT_CONFIG,
                  geometry: Optional[DecodeGeometry] = None,
                  header: Optional[SAMHeader] = None,
                  spans: Optional[Sequence[FileVirtualSpan]] = None,
                  prefetch: int = 2) -> Dict[str, int]:
    """Distributed flagstat over a whole BAM — the minimum end-to-end slice
    (SURVEY.md section 7): plan -> shard -> inflate -> unpack -> reduce."""
    from hadoop_bam_tpu.formats.bamio import read_bam_header
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if geometry is None:
        geometry = DecodeGeometry()
    if header is None:
        header, _ = read_bam_header(path)

    if spans is None:
        # Plan spans sized to the geometry: compressed spans inflate <= ~4x.
        span_bytes = max(geometry.bytes_cap // 4, 1 << 20)
        src = as_byte_source(path)
        n_spans = max(n_dev, int(np.ceil(src.size / span_bytes)))
        src.close()
        spans = plan_bam_spans(path, num_spans=n_spans, config=config,
                               header=header)

    step = make_flagstat_step(mesh)
    sharding = NamedSharding(mesh, P("data"))
    totals: Dict[str, int] = {}
    # separate pools: outer drives group pipelining, inner parallelizes the
    # per-span decode inside a group (sharing one pool could deadlock — outer
    # workers block on inner futures).  H2D transfers are SERIALIZED under a
    # lock and blocked on individually: concurrent async device_put streams
    # collapse ~80x on tunneled TPU links (measured 19 MB/s vs 1.5 GB/s).
    transfer_lock = _TRANSFER_LOCK
    with cf.ThreadPoolExecutor(max_workers=max(prefetch, 1)) as ex, \
            cf.ThreadPoolExecutor(max_workers=8) as inner:
        groups = list(iter_span_groups(spans, n_dev))
        pending = []
        gi = 0

        def submit(g):
            def work():
                batch = stack_span_group(path, g, n_dev, geometry,
                                         executor=inner)
                with transfer_lock:
                    out = (jax.device_put(batch.data, sharding),
                           jax.device_put(batch.offsets, sharding),
                           jax.device_put(batch.n_records, sharding))
                    for a in out:
                        a.block_until_ready()
                return out
            return ex.submit(work)

        add = jax.jit(jnp.add)
        totals_vec = None
        while gi < len(groups) and len(pending) < prefetch:
            pending.append(submit(groups[gi])); gi += 1
        while pending:
            data, offsets, counts = pending.pop(0).result()
            if gi < len(groups):
                pending.append(submit(groups[gi])); gi += 1
            vec = step(data, offsets, counts)
            # accumulate on device; transfer to host exactly once at the end
            totals_vec = vec if totals_vec is None else add(totals_vec, vec)
    from hadoop_bam_tpu.ops.flagstat import FLAGSTAT_FIELDS
    host = np.zeros(len(FLAGSTAT_FIELDS), dtype=np.int64) if totals_vec is None \
        else np.asarray(jax.device_get(totals_vec), dtype=np.int64)
    totals = {k: int(host[i]) for i, k in enumerate(FLAGSTAT_FIELDS)}
    return totals
