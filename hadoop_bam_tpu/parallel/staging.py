"""Staging rings + the shared host->device feed pipeline.

Before this module, every driver family (flagstat tiles, BAM payload
stats, FASTQ/QSEQ, CRAM, variant tensors) hand-rolled the same
emit/dispatch loop — and each emit allocated a fresh
``np.zeros((n_dev, cap, w))`` group tile, memset it, copied every
device's rows into it, then synchronously ``device_put`` + stepped it.
That loop is why the pipeline scaled *inversely* with device count:
host group-assembly work (memsets + copies, all O(n_dev)) grew with
every added device while the device waited, serialized behind it.

Two mechanisms replace it:

- **``StagingRing``** — a small ring of preallocated, reusable
  ``[n_dev, cap, w]`` group buffers.  Emit writes each device's rows in
  place; a partial tile zeroes only its own tail (rows
  ``[count, bucket)``), so a full group pays ZERO allocation and ZERO
  memset.  Slots are leased/released: a slot is handed back to the ring
  only after its dispatch completed, and the device arrays a dispatch
  creates ride the slot as IN-FLIGHT handles — the packer waits on
  them after re-leasing, before writing — so an asynchronous
  host->device transfer can never still be reading a buffer the packer
  overwrites (``jax.device_put`` may return before the DMA completes
  on real TPUs), and the dispatch thread never blocks for it.

- **``FeedPipeline``** — a packer thread assembles group *k+1* into one
  ring slot while the caller's thread dispatches group *k* from another
  (depth-2 double buffering).  All JAX calls stay on the caller's
  thread — transfers keep issuing sequentially from one thread, which
  the tunneled TPU link requires — while the packing memcpys overlap
  them.  ``feed()`` drives stats drivers to completion;
  ``stream()`` powers the generator-shaped ``tensor_batches`` APIs.

Wall-clock accounting rides along: ``pipeline.feed_wall`` (whole feed),
``pipeline.dispatch_wall`` (device-busy wall inside dispatch calls) and
the ``pipeline.dispatch_bytes`` counter feed the bench's
``overlap_efficiency`` ratio — the thread-summed ``METRICS.timer``
values cannot show overlap, the wall spans can.
"""
from __future__ import annotations

import collections
import contextvars
import dataclasses
import queue
import threading
import time
from typing import Callable, Iterable, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.utils.metrics import METRICS


def committed_device_put(array, sharding=None):
    """``jax.device_put`` that returns only after the host->device copy
    is COMPLETE.  Plain device_put may return while the DMA is still
    reading the host buffer (PJRT immutable-until-transfer-completes
    semantics on real TPUs); for a BORROWED ring-slot view that window
    is an aliasing hazard — the slot is released when dispatch returns
    and the packer may overwrite it mid-transfer.  Blocking on the
    RESULT bounds the wait to the transfer itself; compute steps
    launched afterwards stay async, and the packer keeps assembling the
    next group on its own thread throughout.  Every feed-path
    device_put of ring-backed memory must go through here
    (``jnp.asarray`` is outright forbidden: it aliases host memory on
    the CPU backend)."""
    import jax

    out = jax.device_put(array, sharding)
    jax.block_until_ready(out)
    return out


def bucket_cap(count: int, cap: int, block_n: int = 256) -> int:
    """Rows to actually dispatch for a partial tile of ``count`` records.

    Full tiles ship at ``cap``; the FINAL partial tile shrinks to the
    smallest bucket (~cap/16, ~cap/4, cap) that holds it, so a small
    file pays a kernel over ~its own rows instead of the full padded
    tile (the small-input dispatch floor: a 10k-read file inside a
    64k-row tile spent 6x its data in padding).  Buckets are rounded up
    to the Pallas record-block height ``block_n`` (the kernel asserts
    divisibility), and a fixed 3-step ladder bounds jit retraces at two
    extra shapes per step function."""
    for b in (cap // 16, cap // 4):
        b = -(-b // block_n) * block_n       # round up to a block multiple
        if b >= block_n and count <= b < cap:
            return b
    return cap


@dataclasses.dataclass(frozen=True)
class TileSpec:
    """Per-record layout of one array in a tile tuple: trailing shape
    (``()`` for 1-D series), dtype, and the padding value rows beyond a
    device's count are filled with (0 for byte tiles, -1 for dosage,
    NaN for qual columns)."""
    shape: Tuple[int, ...]
    dtype: object
    pad: object = 0

    @classmethod
    def normalize(cls, spec) -> "TileSpec":
        """Accept the legacy ``_iter_tile_tuples`` spec forms too: an int
        width (uint8 [cap, w]) or a (width_or_None, dtype) pair."""
        if isinstance(spec, TileSpec):
            return spec
        if isinstance(spec, (int, np.integer)):
            return cls((int(spec),), np.uint8, 0)
        w, dt = spec
        return cls(() if w is None else (int(w),), dt, 0)


class RingSlot:
    """One leased group buffer set: ``arrays[j]`` is
    [n_dev, cap, *specs[j].shape], ``counts`` is [n_dev] int32.

    ``in_flight`` carries the device arrays the last dispatch created
    from these buffers (any pytree); the packer blocks on them after
    re-leasing the slot and BEFORE writing — so an asynchronous
    host->device transfer can never still be reading a buffer the
    packer overwrites, without the dispatch thread ever waiting.

    ``pin()`` transfers the slot's buffers OUT of the ring permanently:
    a pinned slot's ``release`` parks it (never requeues it) and the
    ring MINTS a fresh replacement slot, so capacity is unchanged while
    the pinned buffers can never be re-leased and overwritten.  This is
    load-bearing on the CPU backend, where ``jax.device_put`` may
    ZERO-COPY alias a numpy buffer — a device array the serve tile
    cache retains would otherwise silently mutate when the ring reuses
    the slot (caught by the test_serve churn proof).  ``unpin()``
    relinquishes a parked slot (its buffers then live exactly as long
    as the device arrays referencing them) or, if called before
    release, cancels the pin so the slot recirculates normally."""
    __slots__ = ("arrays", "counts", "index", "in_flight", "pinned",
                 "parked", "_ring")

    def __init__(self, arrays: List[np.ndarray], counts: np.ndarray,
                 index: int, ring: "StagingRing"):
        self.arrays = arrays
        self.counts = counts
        self.index = index
        self.in_flight = None
        self.pinned = False
        self.parked = False
        self._ring = ring

    def pin(self) -> None:
        self.pinned = True

    def unpin(self) -> None:
        self._ring.unpin(self)

    def release(self) -> None:
        self._ring.release(self)


def _block_in_flight(handles) -> None:
    """Wait for every transfer handle in ``handles`` (a pytree of jax
    arrays, or anything exposing ``block_until_ready``)."""
    import jax

    for leaf in jax.tree_util.tree_leaves(handles):
        if hasattr(leaf, "block_until_ready"):
            leaf.block_until_ready()


class _Cancelled(Exception):
    """Internal: the other side of the pipeline stopped; unwind quietly."""


class StagingRing:
    """A ring of preallocated group buffers, leased and released.

    ``lease`` blocks until a slot is free (with a cancellation event so
    an aborted run can't deadlock the packer); ``release`` hands the
    slot back for reuse.  Buffers are allocated ONCE here — the lint
    rule PF501 exists to keep fresh per-emit group allocations from
    creeping back into the feed paths."""

    def __init__(self, n_dev: int, cap: int, specs: Sequence[TileSpec],
                 slots: int):
        self.n_dev, self.cap = int(n_dev), int(cap)
        self.specs = [TileSpec.normalize(s) for s in specs]
        self.n_slots = max(2, int(slots))
        self._free: "queue.Queue[RingSlot]" = queue.Queue()
        self._next_index = 0
        self.slots: List[RingSlot] = []
        for _ in range(self.n_slots):
            slot = self._fresh_slot()
            self.slots.append(slot)
            self._free.put(slot)

    def _fresh_slot(self) -> RingSlot:
        arrays = [
            np.full((self.n_dev, self.cap) + s.shape, s.pad, dtype=s.dtype)
            for s in self.specs
        ]
        slot = RingSlot(arrays, np.zeros(self.n_dev, np.int32),
                        self._next_index, self)
        self._next_index += 1
        return slot

    def lease(self, cancel: threading.Event) -> RingSlot:
        while True:
            try:
                return self._free.get(timeout=0.05)
            except queue.Empty:
                if cancel.is_set():
                    raise _Cancelled()

    def release(self, slot: RingSlot) -> None:
        if slot.pinned:
            # ownership transfer: the pinned buffers leave the ring FOR
            # GOOD (device arrays made from them may alias the memory on
            # the CPU backend — recycling would corrupt a cached tile);
            # a fresh replacement keeps ring capacity unchanged
            slot.parked = True
            replacement = self._fresh_slot()
            try:
                self.slots[self.slots.index(slot)] = replacement
            except ValueError:
                self.slots.append(replacement)
            self._free.put(replacement)
            return
        self._free.put(slot)

    def unpin(self, slot: RingSlot) -> None:
        """Relinquish a pinned slot.  Parked (already released): a
        replacement was minted at release time, so this only drops the
        ring's bookkeeping — the buffers live exactly as long as the
        device arrays referencing them, and are NEVER re-leased.  Not
        yet released: cancels the pin, the slot recirculates normally on
        release."""
        slot.pinned = False
        slot.parked = False


def _put(q: "queue.Queue", item, cancel: threading.Event) -> None:
    while True:
        try:
            q.put(item, timeout=0.05)
            return
        except queue.Full:
            if cancel.is_set():
                raise _Cancelled()


_SENTINEL = object()


class FeedPipeline:
    """The shared group-assembly + double-buffered dispatch engine.

    Construct with the mesh width, the tile cap, and per-array
    ``TileSpec``s, then either::

        fp.feed(span_arrays_stream, dispatch_fn)      # stats drivers

    or::

        for out in fp.stream(span_arrays_stream, emit_fn):  # datasets
            ...

    ``span_arrays_stream`` yields per-span TUPLES of row arrays in
    lockstep (axis 0 = records; empty spans allowed).  The pipeline
    repacks them across span boundaries into ring-slot group buffers —
    device ``i`` of a group holds rows ``[i*cap, (i+1)*cap)`` of the
    concatenated stream, exactly the tiling of the old serial
    ``_iter_*_tiles`` + emit path (byte-identical, pinned by tests).

    ``dispatch_fn(arrays, counts)`` / ``emit_fn(arrays, counts)`` run on
    the CALLER's thread with ``arrays[j]`` a ``[n_dev, bucket, w]`` view
    of a leased ring slot and ``counts`` the per-device row counts.
    The buffers are BORROWED: valid until the call returns (``feed``)
    or until the generator is advanced (``stream``) — consumers must
    ``device_put``/copy before then, never retain the views.  That
    borrow is what makes the ring safe: the slot is released (and can
    be overwritten by the packer) only after the consumer is done.
    """

    def __init__(self, n_dev: int, cap: int, specs: Sequence[TileSpec],
                 *, block_n: int = 256, fixed_shape: bool = False,
                 balance: bool = False,
                 ring_slots: Optional[int] = None,
                 dispatch_depth: Optional[int] = None,
                 config: Optional[HBamConfig] = None,
                 count_bytes: bool = True,
                 name: str = "pipeline",
                 fmt: Optional[str] = None):
        config = config if config is not None else DEFAULT_CONFIG
        self.n_dev, self.cap = int(n_dev), int(cap)
        self.specs = [TileSpec.normalize(s) for s in specs]
        self.block_n = int(block_n)
        self.fixed_shape = bool(fixed_shape)
        self.balance = bool(balance)
        self.ring_slots = int(ring_slots if ring_slots is not None
                              else getattr(config, "feed_ring_slots", 2))
        self.dispatch_depth = max(1, int(
            dispatch_depth if dispatch_depth is not None
            else getattr(config, "feed_dispatch_depth", 2)))
        # count_bytes=False: the dispatcher transfers a narrower slice
        # of the ring views (coverage's op-width cut) and counts its
        # own pipeline.dispatch_bytes — the view nbytes would overstate
        self.count_bytes = bool(count_bytes)
        self.name = name
        # driver-family taxonomy twin: with fmt="bam" the same walls
        # ALSO land under bam.feed_wall / bam.dispatch_wall, so every
        # driver family reports the same <fmt>.<stage> span set (the
        # shared pipeline.* keys keep the bench contract)
        self.fmt = fmt
        self.dispatches = 0
        self.dispatch_bytes = 0
        self._device_wall = 0.0
        self._total_wall = 0.0

    @property
    def overlap_efficiency(self) -> float:
        """Device-busy wall / total feed wall for the last run — the
        ratio the bench reports to prove the overlap is real (1.0 means
        the host never made the dispatch side wait)."""
        return (self._device_wall / self._total_wall
                if self._total_wall > 0 else 0.0)

    # -- packer side (its own thread) ---------------------------------------

    def _pack_loop(self, stream: Iterable[Tuple[np.ndarray, ...]],
                   q: "queue.Queue", cancel: threading.Event,
                   ring: StagingRing) -> None:
        it = iter(stream)
        parts: "collections.deque[Tuple[np.ndarray, ...]]" = \
            collections.deque()
        have = 0
        exhausted = False

        def pull_until(need: int) -> None:
            nonlocal exhausted, have
            while not exhausted and have < need:
                if cancel.is_set():
                    raise _Cancelled()
                try:
                    arrays = next(it)
                except StopIteration:
                    exhausted = True
                    return
                arrays = tuple(arrays)
                n = arrays[0].shape[0]
                if n:
                    parts.append(arrays)
                    have += n

        while True:
            # balance needs one group's worth buffered up front (the
            # tail split depends on the total); serial mode pulls
            # lazily so tensor_batches never holds an extra group of
            # decoded spans in memory
            pull_until(self.n_dev * self.cap if self.balance else 1)
            if not have:
                break
            slot = ring.lease(cancel)
            if slot.in_flight is not None:
                # the slot's previous dispatch may still be transferring
                # from these buffers: wait HERE, on the packer thread,
                # where the wait overlaps the consumer's next dispatch
                with METRICS.span("staging.transfer_wait"):
                    _block_in_flight(slot.in_flight)
                slot.in_flight = None
            t_pack = time.perf_counter()
            counts = slot.counts
            counts[:] = 0
            target = self.cap
            if self.balance and exhausted and have < self.n_dev * self.cap:
                # balanced tail (stats drivers): the serial fill order
                # would park the whole remainder on the first devices
                # and leave the rest idle — a small file on an 8-wide
                # mesh then pays one device's wall time AND a full-cap
                # padded transfer.  Spreading the tail evenly keeps
                # every shard busy and lets the bucket ladder shrink
                # the dispatch.  psum-invariant, so results are
                # unchanged; tensor_batches keeps the serial order
                # (balance=False) for byte-stable public batches.
                target = max(1, -(-have // self.n_dev))
            for dev in range(self.n_dev):
                filled = 0
                while filled < target:
                    if not parts:
                        pull_until(1)
                        if not parts:
                            break
                    head = parts[0]
                    k = min(target - filled, head[0].shape[0])
                    for dst, src in zip(slot.arrays, head):
                        dst[dev, filled:filled + k] = src[:k]
                    if k == head[0].shape[0]:
                        parts.popleft()
                    else:
                        parts[0] = tuple(h[k:] for h in head)
                    filled += k
                    have -= k
                counts[dev] = filled
                if not parts and exhausted:
                    break
            bucket = self.cap
            if not self.fixed_shape:
                # per-device bucket caps: the dispatch height is shared
                # (one shard_map step) but sized by the LARGEST shard,
                # so the final partial group shrinks to the smallest
                # bucket holding it (bucket_cap is monotonic in count,
                # so the max over devices equals bucket_cap(max count))
                bucket = max(bucket_cap(int(c), self.cap, self.block_n)
                             for c in counts)
            # zero ONLY the written tail: rows [count, bucket) per
            # device.  Rows past the bucket are never dispatched, and
            # rows under the count are fully overwritten — a full group
            # therefore pays no memset at all.
            for spec, dst in zip(self.specs, slot.arrays):
                for dev in range(self.n_dev):
                    c = int(counts[dev])
                    if c < bucket:
                        dst[dev, c:bucket] = spec.pad
            # pack span (packer thread): group assembly occupancy sits
            # next to the consumer thread's dispatch spans in the trace
            # — the double-buffer overlap made visible
            METRICS.add_wall("staging.pack", time.perf_counter() - t_pack,
                             t0=t_pack,
                             args={"rows": int(counts.sum()),
                                   "bucket": bucket})
            _put(q, (slot, bucket), cancel)

    # -- consumer side (the caller's thread) --------------------------------

    def _slots(self, stream: Iterable[Tuple[np.ndarray, ...]]
               ) -> Iterator[Tuple[RingSlot, Tuple[np.ndarray, ...]]]:
        """Yield leased ``(slot, bucket_views)`` pairs; the slot is
        released when the generator is advanced (or closed) — the
        depth-2 contract lives here."""
        ring = StagingRing(self.n_dev, self.cap, self.specs,
                           self.ring_slots)
        q: "queue.Queue" = queue.Queue(maxsize=max(1,
                                                   self.dispatch_depth - 1))
        cancel = threading.Event()
        errs: List[BaseException] = []

        def pack() -> None:
            try:
                self._pack_loop(stream, q, cancel, ring)
            except _Cancelled:
                return
            except BaseException as e:  # noqa: BLE001 — crosses the thread
                errs.append(e)
            try:
                _put(q, _SENTINEL, cancel)
            except _Cancelled:
                pass

        # the packer runs in a COPY of the caller's context so its spans
        # and walls land in the caller's MetricsContext, not the global
        ctx = contextvars.copy_context()
        packer = threading.Thread(target=lambda: ctx.run(pack),
                                  name="hbam-feed-pack", daemon=True)
        self._device_wall = 0.0
        self.dispatches = 0
        self.dispatch_bytes = 0
        t0 = time.perf_counter()
        packer.start()
        try:
            while True:
                item = q.get()
                if item is _SENTINEL:
                    break
                slot, bucket = item
                arrays = tuple(a[:, :bucket] for a in slot.arrays)
                try:
                    yield slot, arrays
                finally:
                    slot.release()
        finally:
            cancel.set()
            packer.join()
            self._total_wall = time.perf_counter() - t0
            METRICS.add_wall(f"{self.name}.feed_wall", self._total_wall,
                             t0=t0, args={"groups": self.dispatches})
            if self.fmt:
                METRICS.add_wall(f"{self.fmt}.feed_wall", self._total_wall)
        if errs:
            raise errs[0]

    def groups(self, stream: Iterable[Tuple[np.ndarray, ...]]
               ) -> Iterator[Tuple[Tuple[np.ndarray, ...], np.ndarray]]:
        """Yield borrowed ``(arrays, counts)`` group batches (valid until
        the generator is advanced).  NOTE: this pass-through path has no
        in-flight transfer tracking — a consumer that hands these views
        to jax itself must use ``committed_device_put`` (or copy first);
        ``stream``/``feed`` consumers get the tracking for free."""
        for slot, arrays in self._slots(stream):
            yield arrays, slot.counts

    def _account(self, arrays: Tuple[np.ndarray, ...], counts: np.ndarray,
                 dt: float, t0: Optional[float] = None) -> None:
        self._device_wall += dt
        self.dispatches += 1
        n = None
        if self.count_bytes:
            n = sum(int(a.nbytes) for a in arrays) + int(counts.nbytes)
            self.dispatch_bytes += n
            METRICS.count("pipeline.dispatch_bytes", n)
        METRICS.add_wall(f"{self.name}.dispatch_wall", dt, t0=t0,
                         args=None if n is None else {"bytes": n})
        if self.fmt:
            METRICS.add_wall(f"{self.fmt}.dispatch_wall", dt)
        # per-group dispatch latency distribution: the p99 here is the
        # stall a device feels when the host falls behind — invisible in
        # the summed dispatch_wall
        METRICS.observe("pipeline.dispatch_group_s", dt)

    def stream(self, span_stream: Iterable[Tuple[np.ndarray, ...]],
               emit_fn: Callable) -> Iterator:
        """Generator mode for ``tensor_batches``-shaped APIs: yields
        ``emit_fn(arrays, counts)`` per group.  The borrowed buffers
        stay valid until the generator is advanced for the NEXT group.
        ``emit_fn`` should ``jax.device_put`` the views (plain, NOT
        blocking) and RETURN the resulting device arrays (any pytree):
        the return value is attached to the ring slot as its in-flight
        transfer handle, and the packer waits on it before reusing the
        buffers — asynchronous transfers stay safe without the dispatch
        thread ever blocking."""
        for slot, arrays in self._slots(span_stream):
            t0 = time.perf_counter()
            out = emit_fn(arrays, slot.counts)
            self._account(arrays, slot.counts, time.perf_counter() - t0,
                          t0=t0)
            slot.in_flight = out
            yield out

    def feed(self, span_stream: Iterable[Tuple[np.ndarray, ...]],
             dispatch_fn: Callable) -> int:
        """Drive the whole stream through ``dispatch_fn`` (same handle
        contract as ``stream``: return the device arrays made from the
        borrowed buffers); returns the number of dispatched groups."""
        for _ in self.stream(span_stream, dispatch_fn):
            pass
        return self.dispatches
