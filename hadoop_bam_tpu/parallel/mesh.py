"""Device mesh construction.

The framework's parallelism is data parallelism over record-aligned spans
(SURVEY.md section 2.9): the mesh's ``data`` axis is the analog of the map
task pool.  Meshes are 1D by default; multi-axis shapes are accepted for
embedding this pipeline inside a larger training mesh (decode sharded along
one axis, the consumer model sharded along others).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding that splits the leading array dim across the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
