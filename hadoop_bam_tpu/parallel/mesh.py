"""Device mesh construction.

The framework's parallelism is data parallelism over record-aligned spans
(SURVEY.md section 2.9): the mesh's ``data`` axis is the analog of the map
task pool.  Meshes are 1D by default; multi-axis shapes are accepted for
embedding this pipeline inside a larger training mesh (decode sharded along
one axis, the consumer model sharded along others).
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# shard_map moved twice across jax releases (jax.experimental.shard_map ->
# jax.shard_map) and its replication-check kwarg was renamed (check_rep ->
# check_vma).  One shim here so every step builder works on any of them.
try:
    from jax import shard_map as _shard_map_impl          # jax >= 0.6
    _CHECK_KW = "check_vma"
except ImportError:                                       # jax 0.4.x / 0.5.x
    from jax.experimental.shard_map import shard_map as _shard_map_impl
    _CHECK_KW = "check_rep"


def shard_map(f, mesh: Mesh, in_specs, out_specs, check_vma=None):
    kwargs = {} if check_vma is None else {_CHECK_KW: check_vma}
    return _shard_map_impl(f, mesh=mesh, in_specs=in_specs,
                           out_specs=out_specs, **kwargs)


def make_mesh(shape: Optional[Tuple[int, ...]] = None,
              axis_names: Sequence[str] = ("data",),
              devices=None) -> Mesh:
    if devices is None:
        devices = jax.devices()
    if shape is None:
        shape = (len(devices),) + (1,) * (len(axis_names) - 1)
    n = int(np.prod(shape))
    if n > len(devices):
        raise ValueError(f"mesh shape {shape} needs {n} devices, "
                         f"have {len(devices)}")
    arr = np.asarray(devices[:n]).reshape(shape)
    return Mesh(arr, tuple(axis_names))


def data_sharding(mesh: Mesh, axis: str = "data") -> NamedSharding:
    """Sharding that splits the leading array dim across the data axis."""
    return NamedSharding(mesh, P(axis))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
