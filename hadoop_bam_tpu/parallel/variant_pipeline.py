"""The variant device feed: VCF/BCF spans -> typed column + dosage tiles ->
sharded mesh steps.

The variant-side mirror of parallel/pipeline.py's BAM columnar path
(reference scope: hb/VCFInputFormat.java + hb/VCFRecordReader.java +
hb/BCFRecordReader.java fed records to MapReduce one at a time; here span
readers feed a mesh batches of typed arrays).  Host threads parse spans into
``VariantBatch`` columns plus the ALT-dosage genotype matrix; devices see

    chrom [cap] i32, pos [cap] i32, flags [cap] u8 (bit0 PASS, bit1 SNP),
    dosage [cap, S_pad] i8, counts [] i32

and reduce with one psum'd step per tile group — variant counts, mean ALT
allele frequency, and per-sample call rates in a single pass.
"""
from __future__ import annotations

import dataclasses
import itertools
import logging
import struct
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hadoop_bam_tpu.parallel.mesh import shard_map
from hadoop_bam_tpu.parallel.staging import FeedPipeline

from hadoop_bam_tpu.config import (
    DEFAULT_CONFIG, HBamConfig, resolve_inflate_backend,
)
from hadoop_bam_tpu.formats.vcf import VariantBatch, VCFHeader
from hadoop_bam_tpu.parallel.pipeline import (
    _STEP_CACHE, _StatTotals, _iter_windowed, pipeline_span_count,
)
from hadoop_bam_tpu.resilience import chaos
from hadoop_bam_tpu.utils.metrics import METRICS
from hadoop_bam_tpu.utils.pools import decode_pool, decode_pool_size

logger = logging.getLogger(__name__)

# dispatch-bucket granularity for variant tiles (no Pallas block
# constraint on this path; 64 keeps the jit shape ladder tiny)
_VARIANT_BLOCK_N = 64


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class VariantGeometry:
    """Static shapes of one device's variant tile (jit contract).

    ``tile_records=None`` (the default) sizes the tile from the sample
    count: as many variants per step as keep the dosage tile within
    ~8 MB, clamped to [64, 65536].  Fewer, larger dispatches win on
    high-latency links (~100 ms per step issue measured on the tunnel),
    but a fixed 64k tile would be gigabytes for cohort-scale VCFs —
    the device step materializes int32 casts of the whole dosage tile.
    The floor is records-small on purpose: a 100k-sample cohort at the
    old 4096-record floor was a ~1.6 GB int32 tile, the very blow-up
    the byte budget exists to prevent (ADVICE r4).
    """
    tile_records: "Optional[int]" = None
    n_samples: int = 0             # from the header; padded to samples_pad

    def __post_init__(self):
        if self.tile_records is None:
            budget = (8 << 20) // max(1, self.samples_pad)
            object.__setattr__(
                self, "tile_records",
                max(64, min(1 << 16, _round_up(budget, 8))))

    @property
    def samples_pad(self) -> int:
        # transfer-compact (8-byte steps), not lane-aligned: a 3-sample
        # VCF padded to 128 lanes shipped 40x the dosage bytes over the
        # H2D link, which is the scarce resource on every measured
        # config; Mosaic/XLA pad the lane dim in VMEM for free
        return max(8, _round_up(self.n_samples, 8))


FLAG_PASS = 1
FLAG_SNP = 2


def pack_variant_tiles(batch: VariantBatch, geometry: VariantGeometry
                       ) -> Dict[str, np.ndarray]:
    """VariantBatch -> dense typed rows (unpadded; the group packer pads)."""
    n = len(batch)
    flags = (batch.is_pass.astype(np.uint8) * FLAG_PASS
             | batch.is_snp.astype(np.uint8) * FLAG_SNP)
    dosage = np.full((n, geometry.samples_pad), -1, dtype=np.int8)
    if geometry.n_samples:
        dosage[:, :geometry.n_samples] = batch.dosage_matrix()
    return {
        "chrom": batch.chrom.astype(np.int32),
        "pos": np.minimum(batch.pos, np.iinfo(np.int32).max
                          ).astype(np.int32),
        "flags": flags,
        "dosage": dosage,
    }


# Common diploid GT strings resolved by dict lookup — the fast path that
# skips per-field parsing for the overwhelming majority of genotypes.
_GT_DOSE = {b"0/0": 0, b"0|0": 0, b"0/1": 1, b"1/0": 1, b"0|1": 1,
            b"1|0": 1, b"1/1": 2, b"1|1": 2, b"./.": -1, b".|.": -1,
            b".": -1, b"0": 0, b"1": 1}

_SNP_ALTS = frozenset(b"ACGTN")


def pack_variant_tiles_from_text(text: bytes, header: VCFHeader,
                                 geometry: VariantGeometry
                                 ) -> Dict[str, np.ndarray]:
    """Text-VCF tokenizer for the stats/tensor path — the host-side 'VCF
    line tokenizer' kernel of SURVEY.md section 7.3(e).

    Dispatches to the NumPy grid tokenizer (newline/tab scans -> field
    boundary matrix -> one clamped gather per column; no per-line Python)
    and falls back to this scalar parse ONLY for rows the vectorized path
    flags as irregular (ALT wider than its gather, multi-digit or
    polyploid genotypes, non-digit POS).  Semantics match
    pack_variant_tiles (asserted by tests)."""
    cols, odd = _pack_variant_text_vectorized(text, header, geometry)
    if odd:
        # odd: (kept-row index, line start, line end) for irregular rows
        rows = np.asarray([r for r, _, _ in odd])
        patch = _pack_variant_tiles_from_text_scalar(
            b"\n".join(text[s:e] for _, s, e in odd) + b"\n",
            header, geometry)
        for k in cols:
            cols[k][rows] = patch[k]
    return cols


def _pack_variant_tiles_from_text_scalar(text: bytes, header: VCFHeader,
                                         geometry: VariantGeometry
                                         ) -> Dict[str, np.ndarray]:
    """Per-line reference tokenizer (the vectorized path's oracle and its
    irregular-row fallback)."""
    S = geometry.n_samples
    cap = text.count(b"\n") + 1
    chrom = np.empty(cap, np.int32)
    pos = np.empty(cap, np.int32)
    flags = np.empty(cap, np.uint8)
    dosage = np.full((cap, geometry.samples_pad), -1, np.int8)
    cmap: Dict[bytes, int] = {c.encode(): i
                              for i, c in enumerate(header.contigs)}
    n = 0
    for line in text.split(b"\n"):
        if not line or line[:1] == b"#":
            continue
        parts = line.split(b"\t")
        if len(parts) < 8:
            continue
        chrom[n] = cmap.get(parts[0], -1)
        pos[n] = int(parts[1])
        ref, alt, filt = parts[3], parts[4], parts[6]
        f = 0
        if filt == b"PASS":
            f |= FLAG_PASS
        if len(ref) == 1 and alt != b"." and all(
                len(a) == 1 and a[0] in _SNP_ALTS
                for a in alt.split(b",")):
            f |= FLAG_SNP
        flags[n] = f
        if S and len(parts) > 9 and parts[8][:2] == b"GT":
            row = dosage[n]
            for s, field in enumerate(parts[9:9 + S]):
                colon = field.find(b":")
                gt = field if colon < 0 else field[:colon]
                d = _GT_DOSE.get(gt)
                if d is None:  # polyploid / multi-allelic / malformed
                    d = 0
                    for a in gt.replace(b"|", b"/").split(b"/"):
                        if not a.isdigit():
                            d = -1
                            break
                        d += 1 if int(a) > 0 else 0
                row[s] = min(d, 127) if d >= 0 else -1
        n += 1
    return {"chrom": chrom[:n], "pos": pos[:n], "flags": flags[:n],
            "dosage": dosage[:n]}


def bcf_span_stat_columns(path: str, span, header: VCFHeader,
                          geometry: VariantGeometry,
                          is_bgzf: Optional[bool] = None
                          ) -> Dict[str, np.ndarray]:
    """One BCF span -> stats tile columns via the columnar decoder
    (formats/bcf_columns.py): the span walk frames records for free,
    one vectorized pass decodes them.  Spans the columnar path declines
    (pathological geometry) fall back to the record-serial scanner with
    identical output — the binary twin of the text tokenizer's
    vectorized/scalar split above."""
    from hadoop_bam_tpu.formats.bcf_columns import (
        decode_bcf_columns, stat_columns,
    )
    from hadoop_bam_tpu.split.vcf_planners import read_bcf_span_frames

    with METRICS.span("vcf.inflate_wall"):
        raw, starts = read_bcf_span_frames(path, span, is_bgzf)
    with METRICS.span("vcf.tokenize_wall"):
        cols = decode_bcf_columns(raw, header, geometry.samples_pad,
                                  starts=starts)
        if cols is not None:
            return stat_columns(cols)
        from hadoop_bam_tpu.formats.bcf import scan_variant_columns
        return scan_variant_columns(raw, header, geometry.samples_pad)


_ALT_W = 16            # widest ALT the vectorized SNP test gathers
_GT_W = 4              # widest genotype prefix gathered (covers "0/1:")
_POS_W = 10            # max decimal digits in a 31-bit position


def _pack_variant_text_vectorized(text: bytes, header: VCFHeader,
                                  geometry: VariantGeometry):
    """NumPy grid tokenizer: newline/tab scans -> per-line field-boundary
    matrix -> one clamped gather per column.  Returns (cols, odd) where
    ``odd`` lists (row, line_start, line_end) for rows needing the scalar
    fallback (wide ALT, unusual GT shapes, non-digit POS)."""
    S = geometry.n_samples
    buf = np.frombuffer(text, dtype=np.uint8)
    if buf.size == 0:
        return {"chrom": np.empty(0, np.int32),
                "pos": np.empty(0, np.int32),
                "flags": np.empty(0, np.uint8),
                "dosage": np.full((0, geometry.samples_pad), -1, np.int8),
                }, []
    nl = np.flatnonzero(buf == 0x0A)
    if nl.size == 0 or nl[-1] != buf.size - 1:
        nl = np.append(nl, buf.size)
    starts = np.empty(nl.size, dtype=np.int64)
    starts[0] = 0
    starts[1:] = nl[:-1] + 1
    ends = nl
    first = buf[np.minimum(starts, buf.size - 1)]
    keep = (ends > starts) & (first != ord("#"))

    tabs = np.flatnonzero(buf == 0x09)
    t0 = np.searchsorted(tabs, starts)
    t1 = np.searchsorted(tabs, ends)
    ntab = t1 - t0
    keep &= ntab >= 7                       # >= 8 fields, scalar parity
    starts, ends, t0, ntab = (a[keep] for a in (starts, ends, t0, ntab))
    n = starts.size
    cols = {"chrom": np.full(n, -1, np.int32),
            "pos": np.zeros(n, np.int32),
            "flags": np.zeros(n, np.uint8),
            "dosage": np.full((n, geometry.samples_pad), -1, np.int8)}
    if n == 0:
        return cols, []
    nf = 10 + S                             # fields we may need bounds for
    k = np.arange(nf - 1, dtype=np.int64)[None, :]
    tabm = tabs[np.minimum(t0[:, None] + k, tabs.size - 1)]
    tabm = np.where(k < ntab[:, None], tabm, ends[:, None])
    # field f occupies [fs[f], fe[f])
    fs = np.concatenate([starts[:, None], tabm + 1], axis=1)
    fe = np.concatenate([tabm, ends[:, None]], axis=1)
    fe = np.maximum(fe, fs)                 # past-the-last fields: empty
    odd = np.zeros(n, bool)

    def gather(f, width):
        """[n, width] bytes of field f, zero past its length, + lengths."""
        ln = fe[:, f] - fs[:, f]
        j = np.arange(width, dtype=np.int64)[None, :]
        g = buf[np.minimum(fs[:, f, None] + j, buf.size - 1)]
        return np.where(j < ln[:, None], g, 0), ln

    # CHROM: a span holds 1-2 distinct names, but a real header can carry
    # thousands of contigs — dedupe the gathered rows and dict-look-up
    # only the unique values (O(lines) + O(unique * lookup), not
    # O(lines * contigs))
    cmap = {c.encode(): i for i, c in enumerate(header.contigs)}
    cw = max((len(c) for c in header.contigs), default=1)
    cbytes, clen = gather(0, cw)
    # clen joins the key so a truncated long name can't alias a contig
    keyed = np.concatenate(
        [cbytes, np.minimum(clen, cw + 1)[:, None].astype(np.uint8)],
        axis=1)
    # hash-group the rows (a span holds ~1-2 distinct names; a real
    # header can carry thousands of contigs, so neither a per-contig
    # scan nor a lexicographic row-unique is acceptable): u64 scalar
    # unique + one vectorized verify against each group's representative
    weights = ((2 * np.arange(cw + 1, dtype=np.uint64) + 1)
               * np.uint64(0x9E3779B97F4A7C15))
    with np.errstate(over="ignore"):
        h = (keyed.astype(np.uint64) * weights[None, :]).sum(
            axis=1, dtype=np.uint64)
    _, first_idx, inv = np.unique(h, return_index=True,
                                  return_inverse=True)
    lut = np.full(first_idx.size, -1, np.int32)
    for ui, ri in enumerate(first_idx):
        ul = int(clen[ri])
        if ul <= cw:
            lut[ui] = cmap.get(cbytes[ri, :ul].tobytes(), -1)
    cols["chrom"] = lut[inv]
    # hash-collision rows (different bytes, same hash): re-look-up exactly
    mismatch = np.flatnonzero(
        ~(keyed == keyed[first_idx[inv]]).all(axis=1))
    for ri in mismatch:
        ul = int(clen[ri])
        cols["chrom"][ri] = cmap.get(cbytes[ri, :ul].tobytes(), -1) \
            if ul <= cw else -1

    # POS: fixed-width decimal parse (int64 accumulate; values past
    # int32 fall back so the scalar path raises the same OverflowError
    # the pre-vectorized tokenizer did on out-of-spec input)
    pb, plen = gather(1, _POS_W)
    digit = (pb >= 0x30) & (pb <= 0x39)
    j = np.arange(_POS_W, dtype=np.int64)[None, :]
    in_field = j < plen[:, None]
    odd |= (plen > _POS_W) | (plen == 0) | (digit != in_field).any(axis=1)
    scale = np.where(in_field, 10 ** np.maximum(
        plen[:, None] - 1 - j, 0), 0)
    pos64 = ((pb.astype(np.int64) - 0x30) * in_field * scale).sum(axis=1)
    odd |= pos64 > np.iinfo(np.int32).max
    cols["pos"] = np.minimum(pos64, np.iinfo(np.int32).max) \
        .astype(np.int32)

    # FILTER == PASS
    fb, flen = gather(6, 4)
    is_pass = (flen == 4) & (fb == np.frombuffer(b"PASS", np.uint8)) \
        .all(axis=1)

    # SNP: REF is 1 base; ALT is single bases joined by commas
    _rb, rlen = gather(3, 1)
    ab, alen = gather(4, _ALT_W)
    odd |= alen > _ALT_W
    ja = np.arange(_ALT_W, dtype=np.int64)[None, :]
    in_alt = ja < alen[:, None]
    snp_char = np.isin(ab, np.frombuffer(b"ACGTN", np.uint8))
    ok_even = (~in_alt | (ja % 2 == 1) | snp_char).all(axis=1)
    ok_odd = (~in_alt | (ja % 2 == 0) | (ab == ord(","))).all(axis=1)
    is_snp = (rlen == 1) & (alen % 2 == 1) & ok_even & ok_odd
    cols["flags"] = (is_pass.astype(np.uint8) * FLAG_PASS
                     | is_snp.astype(np.uint8) * FLAG_SNP)

    # genotypes: FORMAT (field 8) must start "GT"; per sample, dosage
    # from the first 1 or 3 characters of the GT subfield.  Wall-spanned
    # separately (vcf.dosage_pack_wall): the GT columns are the dominant
    # tokenizer cost on wide cohorts and the bench's vcf_stage_seconds
    # row wants them attributable
    if S:
        with METRICS.span("vcf.dosage_pack_wall"):
            gb8, glen8 = gather(8, 2)
            has_gt = (glen8 >= 2) & (gb8[:, 0] == ord("G")) \
                & (gb8[:, 1] == ord("T")) & (ntab >= 9)
            for s in range(S):
                f = 9 + s
                present = has_gt & (ntab >= f)  # field exists on the line
                sb, sln = gather(f, _GT_W)
                colon = np.where((sb == ord(":")) & (np.arange(_GT_W) <
                                                     sln[:, None]),
                                 np.arange(_GT_W), _GT_W).min(axis=1)
                gtlen = np.minimum(sln, colon)
                c0, c1, c2 = sb[:, 0], sb[:, 1], sb[:, 2]
                d0 = (c0 >= 0x30) & (c0 <= 0x39)
                d2 = (c2 >= 0x30) & (c2 <= 0x39)
                sep = (c1 == ord("/")) | (c1 == ord("|"))
                one = gtlen == 1
                tri = (gtlen == 3) & sep
                dot0, dot2 = c0 == ord("."), c2 == ord(".")
                val1 = np.where(d0, (c0 > 0x30).astype(np.int8),
                                np.int8(-1))
                val3 = np.where(d0 & d2,
                                ((c0 > 0x30).astype(np.int8)
                                 + (c2 > 0x30).astype(np.int8)),
                                np.int8(-1))
                # '.' anywhere -> missing (scalar: first non-digit allele
                # aborts to -1); handled by d0/d2 being False for '.'
                val = np.where(one, val1, np.where(tri, val3, np.int8(0)))
                regular = one | tri
                odd |= present & ~regular & (gtlen > 0)
                row_ok = present & regular
                cols["dosage"][row_ok, s] = val[row_ok]
    odd_rows = np.flatnonzero(odd)
    return cols, [(int(r), int(starts[r]), int(ends[r]))
                  for r in odd_rows]


def _iter_variant_tiles(cols_stream, cap: int, geometry: VariantGeometry
                        ) -> Iterator[Tuple[Dict[str, np.ndarray], int]]:
    """Repack a stream of per-span column dicts into cap-row tiles
    (cross-span concatenation; only the final tile is padded).

    The tile schema is taken from the first span's dict, so the feed
    accepts both the stats schema (chrom/pos/flags/dosage) and extended
    columnar dicts (e.g. formats/bcf_columns.py's rlen/qual/n_allele/
    n_fmt columns) without either side hard-coding the other.

    Serial tiler — the live drivers feed through the shared
    parallel/staging.FeedPipeline (via _variant_feed_specs below); this
    stays as the byte-identity oracle for its tests."""
    from collections import deque

    # deque: parts.pop(0) was O(n^2) on many-small-span plans
    parts: "deque[Dict[str, np.ndarray]]" = deque()
    have = 0
    proto: Dict[str, np.ndarray] = {}

    def empty_tile() -> Dict[str, np.ndarray]:
        out = {}
        for k, v in proto.items():
            shape = (cap,) + v.shape[1:]
            if k == "dosage":
                out[k] = np.full(shape, -1, v.dtype)
            elif k == "qual":
                out[k] = np.full(shape, np.nan, v.dtype)
            else:
                out[k] = np.zeros(shape, v.dtype)
        return out

    def emit(take: int) -> Tuple[Dict[str, np.ndarray], int]:
        nonlocal have
        tile = empty_tile()
        filled = 0
        while filled < take:
            head = parts[0]
            m = min(take - filled, head["chrom"].shape[0])
            for k in tile:
                tile[k][filled:filled + m] = head[k][:m]
            if m == head["chrom"].shape[0]:
                parts.popleft()
            else:
                parts[0] = {k: v[m:] for k, v in head.items()}
            filled += m
        have -= take
        return tile, take

    for cols in cols_stream:
        if not proto:
            proto = cols
        if cols["chrom"].shape[0]:
            parts.append(cols)
            have += cols["chrom"].shape[0]
        while have >= cap:
            yield emit(cap)
    if have:
        yield emit(have)


def _variant_feed_specs(proto: Dict[str, np.ndarray]):
    """Key order + TileSpecs for feeding schema-dict variant tiles
    through the shared FeedPipeline (parallel/staging.py).  The schema
    comes from the first span's dict — same genericity as
    _iter_variant_tiles — and pads mirror its empty_tile: -1 for
    dosage, NaN for qual, 0 elsewhere."""
    from hadoop_bam_tpu.parallel.staging import TileSpec

    keys = list(proto)
    specs = []
    for k in keys:
        v = proto[k]
        pad = -1 if k == "dosage" else (np.nan if k == "qual" else 0)
        specs.append(TileSpec(tuple(v.shape[1:]), v.dtype, pad))
    return keys, specs


def variant_feed(cols_stream, n_dev: int, cap: int,
                 config: HBamConfig = DEFAULT_CONFIG, **fp_kwargs):
    """Peek the first span's column dict for the tile schema and build
    the shared feed over it.  Returns ``(keys, fp, tuples)`` — or
    ``(None, None, None)`` for an empty stream — where ``tuples`` is
    the dict stream re-threaded as key-ordered array tuples for
    ``fp.feed``/``fp.stream``.  The one place the
    stats driver and VcfDataset.tensor_batches share their wiring, so
    schema handling cannot drift between them."""
    stream = iter(cols_stream)
    first = next(stream, None)
    if first is None:
        return None, None, None
    keys, specs = _variant_feed_specs(first)
    fp = FeedPipeline(n_dev, cap, specs, config=config, **fp_kwargs)
    tuples = (tuple(d[k] for k in keys)
              for d in itertools.chain([first], stream))
    return keys, fp, tuples


def make_variant_stats_step(mesh: Mesh, geometry: VariantGeometry,
                            axis: str = "data"):
    """Jitted sharded step: variant tiles -> psum'd stats vector
    [n_variants, n_snp, n_pass, sum_af, n_af] ++ per-sample called counts.

    AF per variant = sum(max(dosage,0)) / (2 * n_called) (diploid ALT
    frequency); variants with zero called samples are excluded from the AF
    mean (n_af counts the included ones).
    """
    key = ("variant_stats", tuple(mesh.devices.flat), mesh.axis_names, axis,
           geometry)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    def per_device(chrom, pos, flags, dosage, count):
        chrom, flags = chrom[0], flags[0]
        dosage, count = dosage[0], count[0]
        cap = flags.shape[0]
        valid = jnp.arange(cap, dtype=jnp.int32) < count
        # count-like quantities stay integer end to end (f32 accumulation
        # drifts past 2^24 — realistic for WGS-scale call sets)
        vi = valid.astype(jnp.int32)
        n_variants = vi.sum()
        n_snp = (valid & ((flags & FLAG_SNP) != 0)).sum().astype(jnp.int32)
        n_pass = (valid & ((flags & FLAG_PASS) != 0)).sum().astype(jnp.int32)
        d = dosage.astype(jnp.int32)
        called = (d >= 0) & valid[:, None]
        n_called = called.sum(axis=1)                           # [cap] i32
        alt_sum = jnp.where(called, d, 0).sum(axis=1
                                              ).astype(jnp.float32)
        has_calls = n_called > 0
        af = jnp.where(has_calls,
                       alt_sum / (2.0 * jnp.maximum(n_called, 1)
                                  .astype(jnp.float32)),
                       0.0)
        sum_af = (af * valid.astype(jnp.float32)).sum()
        n_af = (has_calls & valid).sum().astype(jnp.int32)
        per_sample_called = called.astype(jnp.int32).sum(axis=0)  # [S]
        ivec = jnp.concatenate([
            jnp.stack([n_variants, n_snp, n_pass, n_af]),
            per_sample_called,
        ])
        return (jax.lax.psum(sum_af[None], axis),
                jax.lax.psum(ivec, axis))

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis),) * 5, out_specs=(P(), P()))
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


# ---------------------------------------------------------------------------
# The variant device decode plane (ops/inflate_device.py token feed).
#
# Pool workers tokenize BGZF BCF spans (the bit-serial Huffman half);
# the mesh resolves + packs the span's bytes (LZ77 on device — no host
# inflate call anywhere on this route).  The serially dependent cursor
# walk over typed-value descriptors runs on the HOST against one bulk
# copy of the resolved buffer (formats/bcf_columns.decode_bcf_cursor_meta
# — lengths chase and flag derivation, a few bytes per record), while
# the BULK byte work rides the device-resident buffer: the [n, 24]
# fixed-prefix assembly (variant_prefix_device) and the grouped GT
# gathers -> dosage (variant_gt_dosage_device).  Cut tail records and
# over-wide spans complete through the host BCF oracle, exactly like
# the BAM device plane's fixups.
# ---------------------------------------------------------------------------


@jax.jit
def _variant_tile_stats(chrom, pos, flags, dosage, count):
    """Single-tile twin of make_variant_stats_step's per-device math
    (no psum — the device plane accumulates via _StatTotals): the SAME
    stat semantics, so device-plane and host-plane totals merge and
    compare exactly."""
    cap = flags.shape[0]
    valid = jnp.arange(cap, dtype=jnp.int32) < count
    vi = valid.astype(jnp.int32)
    n_variants = vi.sum()
    n_snp = (valid & ((flags & FLAG_SNP) != 0)).sum().astype(jnp.int32)
    n_pass = (valid & ((flags & FLAG_PASS) != 0)).sum().astype(jnp.int32)
    d = dosage.astype(jnp.int32)
    called = (d >= 0) & valid[:, None]
    n_called = called.sum(axis=1)
    alt_sum = jnp.where(called, d, 0).sum(axis=1).astype(jnp.float32)
    has_calls = n_called > 0
    af = jnp.where(has_calls,
                   alt_sum / (2.0 * jnp.maximum(n_called, 1)
                              .astype(jnp.float32)),
                   0.0)
    sum_af = (af * valid.astype(jnp.float32)).sum()
    n_af = (has_calls & valid).sum().astype(jnp.int32)
    per_sample_called = called.astype(jnp.int32).sum(axis=0)
    ivec = jnp.concatenate([
        jnp.stack([n_variants, n_snp, n_pass, n_af]), per_sample_called])
    return sum_af[None], ivec


def _round_pow2_min8(x: int) -> int:
    from hadoop_bam_tpu.ops.rans import _round_pow2
    return _round_pow2(max(int(x), 8), 8)


def _resolved_span_bytes(chunk) -> np.ndarray:
    """Resolve one token chunk on device and return (device buffer,
    host view of its first ``total`` bytes).  The ONE host sync per
    span on the variant device route — the cursor walk is serially
    dependent and must read real bytes; everything bulk (prefix tile,
    GT gathers) stays on the device buffer this function also returns.
    Module-level on purpose: the per-span loop calls it, and the single
    bulk copy is the approved sync shape (DV901)."""
    from hadoop_bam_tpu.ops.inflate_device import resolve_tokens_packed

    B = _round_pow2_min8(chunk.used)
    tokens, nt, isz = chunk.tokens, chunk.n_tokens, chunk.isize
    if B != chunk.used:
        tokens = np.vstack(
            [tokens, np.zeros((B - chunk.used, chunk.P), np.uint32)])
        nt = np.concatenate([nt, np.zeros(B - chunk.used, np.int32)])
        isz = np.concatenate([isz, np.zeros(B - chunk.used, np.int32)])
    buf_dev = resolve_tokens_packed(jnp.asarray(tokens), jnp.asarray(nt),
                                    jnp.asarray(isz))
    total = int(chunk.ubase[chunk.used])
    return buf_dev, np.asarray(buf_dev)[:total]


def _frame_span_records(hbuf: np.ndarray, start: int, stop: int
                        ) -> Tuple[np.ndarray, int]:
    """Record framing over a resolved span buffer with span ownership:
    the l_shared/l_indiv cursor chase from ``start``, keeping records
    whose FIRST byte is < ``stop`` (the same ownership rule the host
    span reader applies) and which complete within the buffer.  Returns
    (starts i64, tail) — ``tail`` is the first incomplete owned
    record's offset (== the walked end when every owned record
    completed), the host-fixup handoff point."""
    total = hbuf.shape[0]
    unpack = struct.Struct("<II").unpack_from
    starts: List[int] = []
    p = int(start)
    view = memoryview(hbuf)
    while p < stop:
        if p + 8 > total:
            break
        l_shared, l_indiv = unpack(view, p)
        end = p + 8 + l_shared + l_indiv
        if end > total:
            break
        starts.append(p)
        p = end
    return np.asarray(starts, np.int64), p


def _variant_stats_result(totals: _StatTotals,
                          header: VCFHeader) -> Dict[str, object]:
    """Shared result assembly for the host and device variant routes."""
    if not totals:
        return {"n_variants": 0, "n_snp": 0, "n_pass": 0, "mean_af": 0.0,
                "n_af": 0, "sample_callrate": np.zeros(header.n_samples)}
    tf, ints = totals.drain()
    sum_af = float(tf[0])
    n_variants = int(ints[0])
    callrate = (ints[4:4 + header.n_samples].astype(np.float64)
                / max(n_variants, 1)
                if header.n_samples else np.zeros(0))
    return {
        "n_variants": n_variants,
        "n_snp": int(ints[1]),
        "n_pass": int(ints[2]),
        "mean_af": float(sum_af / max(int(ints[3]), 1)),
        # the mean_af denominator (variants with computable AF): exposed
        # so multi-host combiners can weight means exactly
        "n_af": int(ints[3]),
        "sample_callrate": callrate,
    }


def _pad_cols_device(cols: Dict[str, np.ndarray], samples_pad: int):
    """Host column dict -> padded device tile tuple for
    _variant_tile_stats (the host-oracle fallback/fixup feed)."""
    n = int(cols["chrom"].shape[0])
    R = _round_pow2_min8(n)

    def pad(a, fill):
        out = np.full((R,) + a.shape[1:], fill, a.dtype)
        out[:n] = a
        return jnp.asarray(out)

    dosage = cols["dosage"]
    if dosage.shape[1] != samples_pad:
        wide = np.full((dosage.shape[0], samples_pad), -1, np.int8)
        wide[:, :dosage.shape[1]] = dosage[:, :samples_pad]
        dosage = wide
    return (pad(cols["chrom"], 0), pad(cols["pos"], 0),
            pad(cols["flags"], 0), pad(dosage, -1), jnp.int32(n))


def _variant_stats_device_plane(ds, mesh: Mesh, config: HBamConfig,
                                header: VCFHeader,
                                geometry: VariantGeometry,
                                spans, prefetch: int = 2
                                ) -> Dict[str, object]:
    """Variant stats through the token-feed device decode plane (module
    section comment above; BGZF BCF only — the caller gates)."""
    from hadoop_bam_tpu.formats.bcf_columns import decode_bcf_cursor_meta
    from hadoop_bam_tpu.ops.inflate_device import (
        variant_gt_dosage_device, variant_prefix_device,
    )
    from hadoop_bam_tpu.parallel.pipeline import (
        DEVICE_PLANE_SPAN_BYTES, _resilient_source, _tokenize_span_tokens,
        decode_with_retry,
    )
    from hadoop_bam_tpu.utils import native
    from hadoop_bam_tpu.utils.errors import PlanError
    from hadoop_bam_tpu.utils.seekable import as_byte_source

    if not native.available():
        raise PlanError(
            "inflate_backend='device' needs the native tokenizer "
            "(hbam_deflate_tokenize_batch); native library unavailable")
    n_dev = int(np.prod(mesh.devices.shape))
    if spans is None:
        src0 = as_byte_source(ds.path)
        n_spans = max(n_dev, int(np.ceil(src0.size
                                         / DEVICE_PLANE_SPAN_BYTES)))
        src0.close()
        with METRICS.span("vcf.plan_wall", spans=n_spans):
            spans = ds.spans(num_spans=n_spans)
    spans = list(spans)
    # the host oracle (read_bcf_span_frames -> BGZFReader) folds CRCs
    # unconditionally, so the device route must keep the same error
    # contract on CRC-only damage: the tokenize-time fold is always on
    # for the variant family, config.check_crc notwithstanding
    check_crc = True
    samples_pad = geometry.samples_pad
    src = _resilient_source(ds.path, config)
    pool = decode_pool(config)
    window = max(1, prefetch) * decode_pool_size(config)
    totals = _StatTotals()
    fix_spans = []
    n_records = 0

    def decode(span):
        # tokenize is metered inside _tokenize_span_tokens
        # (bam.tokenize_wall — the BGZF token stage, format-agnostic);
        # deliberately NOT under pipeline.host_decode_wall: no host
        # inflate happens on this route
        def inner(s):
            return _tokenize_span_tokens(src, s, check_crc)
        return decode_with_retry(inner, span, config)

    def host_cols(span):
        """The host-oracle decode of one (fixup) span, reduced with the
        same tile math — byte/value-identical merge."""
        def inner(s):
            return bcf_span_stat_columns(ds.path, s, header, geometry,
                                         True)
        with METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span("vcf.host_decode_wall"):
            return decode_with_retry(inner, span, config)

    for chunk in _iter_windowed(pool, spans, decode, window,
                                config=config):
        if chunk is None:
            continue
        # chaos point at the plane's dispatch boundary — the ladder
        # wrapper in _variant_stats_impl demotes on injected faults
        chaos.fire("device.step", blocks=int(chunk.used))
        with METRICS.timer("pipeline.device_inflate"), \
                METRICS.span("vcf.device_resolve_wall",
                             blocks=int(chunk.used)):
            buf_dev, hbuf = _resolved_span_bytes(chunk)
        starts, tail = _frame_span_records(hbuf, chunk.start,
                                           chunk.stop)
        meta = decode_bcf_cursor_meta(hbuf, header, samples_pad,
                                      starts=starts)
        if meta is None:
            # pathological geometry: the WHOLE span takes the host
            # oracle (same fallback the columnar host path has) — so
            # no device-tail fixup for this chunk, or its cut records
            # would count twice
            cols = host_cols(chunk.span)
            if cols is not None:
                totals.add(*_variant_tile_stats(
                    *_pad_cols_device(cols, samples_pad)))
            continue
        if tail < chunk.stop or chunk.used < chunk.n_blocks:
            fix_spans.append(chunk.fixup_span(tail))
        n = int(meta["n"])
        n_records += n
        if n == 0:
            continue
        R = _round_pow2_min8(n)
        s32 = np.zeros(R, np.int32)
        s32[:n] = meta["starts"]
        with METRICS.span("vcf.device_unpack_wall", rows=n):
            chrom_d, pos_d = variant_prefix_device(
                buf_dev, jnp.asarray(s32))
            flags = np.zeros(R, np.uint8)
            flags[:n] = meta["flags"]
            dosage_d = jnp.full((R, samples_pad), -1, jnp.int8)
            for rows, offs, width, cnt, ns in meta["gt_groups"]:
                R2 = _round_pow2_min8(rows.size)
                offs_p = np.zeros(R2, np.int32)
                offs_p[:rows.size] = offs
                d = variant_gt_dosage_device(
                    buf_dev, jnp.asarray(offs_p), width, cnt,
                    ns)[:rows.size]
                dosage_d = dosage_d.at[
                    jnp.asarray(rows.astype(np.int32))[:, None],
                    jnp.arange(ns)].set(d)
            totals.add(*_variant_tile_stats(
                chrom_d, pos_d, jnp.asarray(flags), dosage_d,
                jnp.int32(n)))
    METRICS.count("pipeline.records", n_records)

    for fs in fix_spans:
        cols = host_cols(fs)
        if cols is not None:
            totals.add(*_variant_tile_stats(
                *_pad_cols_device(cols, samples_pad)))
    return _variant_stats_result(totals, header)


def variant_stats_file(path: str, mesh: Optional[Mesh] = None,
                       config: HBamConfig = DEFAULT_CONFIG,
                       geometry: Optional[VariantGeometry] = None,
                       header: Optional[VCFHeader] = None,
                       spans=None,
                       prefetch: int = 2) -> Dict[str, object]:
    """Distributed variant stats over a whole VCF/BCF (any container the
    dispatcher recognises): variant/SNP/PASS counts, mean ALT allele
    frequency, and per-sample call rates, reduced over the mesh's data
    axis.  A thin plan builder over the one executor
    (plan/builders.py + plan/executor.py)."""
    from hadoop_bam_tpu.plan import builders
    from hadoop_bam_tpu.plan import executor as plan_executor

    plan = builders.variant_stats_plan(path, config, geometry=geometry)
    return plan_executor.execute(plan, config=config, mesh=mesh,
                                 geometry=geometry, header=header,
                                 spans=spans, prefetch=prefetch)


def _variant_stats_impl(path: str, mesh: Optional[Mesh] = None,
                        config: HBamConfig = DEFAULT_CONFIG,
                        geometry: Optional[VariantGeometry] = None,
                        header: Optional[VCFHeader] = None,
                        spans=None,
                        prefetch: int = 2) -> Dict[str, object]:
    """The variant-stats mesh-feed implementation (executor runner).

    Plane routing mirrors the BAM drivers: ``select_plane`` over the
    VARIANT_DAG picks the token-feed device route for a BGZF BCF source
    under the device backend; any device-route failure the PR-11 ladder
    calls demotable falls through to the host mesh feed below, and the
    device plane's blame is confirmed only after the host plane proves
    the bytes were fine."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.parallel.mesh import make_mesh
    from hadoop_bam_tpu.plan.executor import (
        SourceIR, VARIANT_DAG, select_plane,
    )
    from hadoop_bam_tpu.resilience.domains import (
        decode_ladder,
    )

    ds = open_vcf(path, config)
    if header is None:
        header = ds.header
    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if geometry is None:
        geometry = VariantGeometry(n_samples=header.n_samples)
    cap = geometry.tile_records

    fmt = "bcf" if path.lower().endswith(".bcf") else "vcf"
    ladder = None
    if config.adaptive_planes:
        ladder = decode_ladder(path, resolve_inflate_backend(config),
                               config)
    device_blame: Optional[BaseException] = None
    # a non-BGZF source can never take the device route: don't let the
    # decision consume the breaker's half-open probe for it
    decision = select_plane(
        SourceIR(path, fmt), VARIANT_DAG, config,
        ladder=ladder if ds._is_bgzf_bcf else None)
    if decision.plane == "device" and ds._is_bgzf_bcf:
        try:
            result = _variant_stats_device_plane(
                ds, mesh, config, header, geometry, spans,
                prefetch=prefetch)
            if ladder is not None:
                ladder.record_success("device")
            return result
        except Exception as e:  # noqa: BLE001 — demotion boundary
            if ladder is None or not ladder.demotable("device", e):
                raise
            logger.warning(
                "variant device plane failed (%s: %s); demoting to the "
                "host plane for this run", type(e).__name__, e)
            device_blame = e

    if spans is None:
        with METRICS.span("vcf.plan_wall"):
            spans = ds.spans(
                num_spans=pipeline_span_count(path, n_dev, config))
    step = make_variant_stats_step(mesh, geometry)
    sharding = NamedSharding(mesh, P("data"))
    pool = decode_pool(config)
    window = max(1, prefetch) * decode_pool_size(config)
    totals = _StatTotals()
    from hadoop_bam_tpu.parallel.pipeline import decode_with_retry

    def decode(span):
        def inner(s):
            # per-stage wall spans (Metrics.wall_timer: overlapping pool
            # threads union, so values are wall seconds, not thread-sums)
            # feed the bench's vcf_stage_seconds row
            with METRICS.span("vcf.inflate_wall"):
                text = ds.read_span_text(s)
            if text is not None:  # fast tokenizer, no record objects
                with METRICS.span("vcf.tokenize_wall"):
                    return pack_variant_tiles_from_text(text, header,
                                                        geometry)
            return bcf_span_stat_columns(ds.path, s, header, geometry,
                                         ds._is_bgzf_bcf)
        with METRICS.wall_timer("pipeline.host_decode_wall"), \
                METRICS.span("vcf.host_decode_wall"):
            out = decode_with_retry(inner, span, config)
        if out is not None:
            return out
        return pack_variant_tiles(VariantBatch([], header), geometry)

    stream = _iter_windowed(pool, spans, decode, window, config=config)
    # ring-fed groups (variant_feed peeks the schema): rows write in
    # place, a skewed device no longer makes the other seven copy its
    # padding, and the balanced FINAL group spreads over all shards and
    # shrinks to a dispatch bucket
    keys, fp, tuples = variant_feed(stream, n_dev, cap, config,
                                    block_n=_VARIANT_BLOCK_N,
                                    balance=True, fmt="vcf")
    if fp is not None:
        def dispatch(arrays, counts):
            with METRICS.span("vcf.dispatch_wall"):
                named = dict(zip(keys, arrays))
                args = [jax.device_put(named[k], sharding)
                        for k in ("chrom", "pos", "flags", "dosage")]
                c = jax.device_put(counts, sharding)
                totals.add(*step(*args, c))  # async; drained at the end
            return (*args, c)  # in-flight handles: the ring waits on them

        fp.feed(tuples, dispatch)
    result = _variant_stats_result(totals, header)
    if ladder is not None and device_blame is not None:
        # the host plane decoded the same file fine: the device failure
        # was plane-local — charge its fault domain (repeated charges
        # open the breaker and demote future runs up front)
        ladder.confirm_failure("device", device_blame)
    return result
