"""The variant device feed: VCF/BCF spans -> typed column + dosage tiles ->
sharded mesh steps.

The variant-side mirror of parallel/pipeline.py's BAM columnar path
(reference scope: hb/VCFInputFormat.java + hb/VCFRecordReader.java +
hb/BCFRecordReader.java fed records to MapReduce one at a time; here span
readers feed a mesh batches of typed arrays).  Host threads parse spans into
``VariantBatch`` columns plus the ALT-dosage genotype matrix; devices see

    chrom [cap] i32, pos [cap] i32, flags [cap] u8 (bit0 PASS, bit1 SNP),
    dosage [cap, S_pad] i8, counts [] i32

and reduce with one psum'd step per tile group — variant counts, mean ALT
allele frequency, and per-sample call rates in a single pass.
"""
from __future__ import annotations

import concurrent.futures as cf
import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hadoop_bam_tpu.config import DEFAULT_CONFIG, HBamConfig
from hadoop_bam_tpu.formats.vcf import VariantBatch, VCFHeader
from hadoop_bam_tpu.parallel.pipeline import (
    _STEP_CACHE, _StatTotals, _iter_windowed,
)


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@dataclasses.dataclass(frozen=True)
class VariantGeometry:
    """Static shapes of one device's variant tile (jit contract)."""
    tile_records: int = 1 << 14    # variants per device per step
    n_samples: int = 0             # from the header; padded to samples_pad

    @property
    def samples_pad(self) -> int:
        return max(128, _round_up(self.n_samples, 128))


FLAG_PASS = 1
FLAG_SNP = 2


def pack_variant_tiles(batch: VariantBatch, geometry: VariantGeometry
                       ) -> Dict[str, np.ndarray]:
    """VariantBatch -> dense typed rows (unpadded; the group packer pads)."""
    n = len(batch)
    flags = (batch.is_pass.astype(np.uint8) * FLAG_PASS
             | batch.is_snp.astype(np.uint8) * FLAG_SNP)
    dosage = np.full((n, geometry.samples_pad), -1, dtype=np.int8)
    if geometry.n_samples:
        dosage[:, :geometry.n_samples] = batch.dosage_matrix()
    return {
        "chrom": batch.chrom.astype(np.int32),
        "pos": np.minimum(batch.pos, np.iinfo(np.int32).max
                          ).astype(np.int32),
        "flags": flags,
        "dosage": dosage,
    }


# Common diploid GT strings resolved by dict lookup — the fast path that
# skips per-field parsing for the overwhelming majority of genotypes.
_GT_DOSE = {b"0/0": 0, b"0|0": 0, b"0/1": 1, b"1/0": 1, b"0|1": 1,
            b"1|0": 1, b"1/1": 2, b"1|1": 2, b"./.": -1, b".|.": -1,
            b".": -1, b"0": 0, b"1": 1}

_SNP_ALTS = frozenset(b"ACGTN")


def pack_variant_tiles_from_text(text: bytes, header: VCFHeader,
                                 geometry: VariantGeometry
                                 ) -> Dict[str, np.ndarray]:
    """Fast text-VCF tokenizer for the stats/tensor path: splits fields
    directly from bytes, never building VcfRecord objects — the host-side
    'VCF line tokenizer' kernel of SURVEY.md section 7.3(e).  ~5x the
    generic parse on typical multi-sample lines; semantics match
    pack_variant_tiles (asserted by tests)."""
    S = geometry.n_samples
    cap = text.count(b"\n") + 1
    chrom = np.empty(cap, np.int32)
    pos = np.empty(cap, np.int32)
    flags = np.empty(cap, np.uint8)
    dosage = np.full((cap, geometry.samples_pad), -1, np.int8)
    cmap: Dict[bytes, int] = {c.encode(): i
                              for i, c in enumerate(header.contigs)}
    n = 0
    for line in text.split(b"\n"):
        if not line or line[:1] == b"#":
            continue
        parts = line.split(b"\t")
        if len(parts) < 8:
            continue
        chrom[n] = cmap.get(parts[0], -1)
        pos[n] = int(parts[1])
        ref, alt, filt = parts[3], parts[4], parts[6]
        f = 0
        if filt == b"PASS":
            f |= FLAG_PASS
        if len(ref) == 1 and alt != b"." and all(
                len(a) == 1 and a[0] in _SNP_ALTS
                for a in alt.split(b",")):
            f |= FLAG_SNP
        flags[n] = f
        if S and len(parts) > 9 and parts[8][:2] == b"GT":
            row = dosage[n]
            for s, field in enumerate(parts[9:9 + S]):
                colon = field.find(b":")
                gt = field if colon < 0 else field[:colon]
                d = _GT_DOSE.get(gt)
                if d is None:  # polyploid / multi-allelic / malformed
                    d = 0
                    for a in gt.replace(b"|", b"/").split(b"/"):
                        if not a.isdigit():
                            d = -1
                            break
                        d += 1 if int(a) > 0 else 0
                row[s] = min(d, 127) if d >= 0 else -1
        n += 1
    return {"chrom": chrom[:n], "pos": pos[:n], "flags": flags[:n],
            "dosage": dosage[:n]}


def _iter_variant_tiles(cols_stream, cap: int, geometry: VariantGeometry
                        ) -> Iterator[Tuple[Dict[str, np.ndarray], int]]:
    """Repack a stream of per-span column dicts into cap-row tiles
    (cross-span concatenation; only the final tile is padded)."""
    parts: List[Dict[str, np.ndarray]] = []
    have = 0
    S = geometry.samples_pad

    def empty_tile() -> Dict[str, np.ndarray]:
        return {
            "chrom": np.zeros(cap, np.int32),
            "pos": np.zeros(cap, np.int32),
            "flags": np.zeros(cap, np.uint8),
            "dosage": np.full((cap, S), -1, np.int8),
        }

    def emit(take: int) -> Tuple[Dict[str, np.ndarray], int]:
        nonlocal have
        tile = empty_tile()
        filled = 0
        while filled < take:
            head = parts[0]
            m = min(take - filled, head["chrom"].shape[0])
            for k in tile:
                tile[k][filled:filled + m] = head[k][:m]
            if m == head["chrom"].shape[0]:
                parts.pop(0)
            else:
                parts[0] = {k: v[m:] for k, v in head.items()}
            filled += m
        have -= take
        return tile, take

    for cols in cols_stream:
        if cols["chrom"].shape[0]:
            parts.append(cols)
            have += cols["chrom"].shape[0]
        while have >= cap:
            yield emit(cap)
    if have:
        yield emit(have)


def make_variant_stats_step(mesh: Mesh, geometry: VariantGeometry,
                            axis: str = "data"):
    """Jitted sharded step: variant tiles -> psum'd stats vector
    [n_variants, n_snp, n_pass, sum_af, n_af] ++ per-sample called counts.

    AF per variant = sum(max(dosage,0)) / (2 * n_called) (diploid ALT
    frequency); variants with zero called samples are excluded from the AF
    mean (n_af counts the included ones).
    """
    key = ("variant_stats", tuple(mesh.devices.flat), mesh.axis_names, axis,
           geometry)
    if key in _STEP_CACHE:
        return _STEP_CACHE[key]

    def per_device(chrom, pos, flags, dosage, count):
        chrom, flags = chrom[0], flags[0]
        dosage, count = dosage[0], count[0]
        cap = flags.shape[0]
        valid = jnp.arange(cap, dtype=jnp.int32) < count
        # count-like quantities stay integer end to end (f32 accumulation
        # drifts past 2^24 — realistic for WGS-scale call sets)
        vi = valid.astype(jnp.int32)
        n_variants = vi.sum()
        n_snp = (valid & ((flags & FLAG_SNP) != 0)).sum().astype(jnp.int32)
        n_pass = (valid & ((flags & FLAG_PASS) != 0)).sum().astype(jnp.int32)
        d = dosage.astype(jnp.int32)
        called = (d >= 0) & valid[:, None]
        n_called = called.sum(axis=1)                           # [cap] i32
        alt_sum = jnp.where(called, d, 0).sum(axis=1
                                              ).astype(jnp.float32)
        has_calls = n_called > 0
        af = jnp.where(has_calls,
                       alt_sum / (2.0 * jnp.maximum(n_called, 1)
                                  .astype(jnp.float32)),
                       0.0)
        sum_af = (af * valid.astype(jnp.float32)).sum()
        n_af = (has_calls & valid).sum().astype(jnp.int32)
        per_sample_called = called.astype(jnp.int32).sum(axis=0)  # [S]
        ivec = jnp.concatenate([
            jnp.stack([n_variants, n_snp, n_pass, n_af]),
            per_sample_called,
        ])
        return (jax.lax.psum(sum_af[None], axis),
                jax.lax.psum(ivec, axis))

    fn = shard_map(per_device, mesh=mesh,
                   in_specs=(P(axis),) * 5, out_specs=(P(), P()))
    step = jax.jit(fn)
    _STEP_CACHE[key] = step
    return step


def variant_stats_file(path: str, mesh: Optional[Mesh] = None,
                       config: HBamConfig = DEFAULT_CONFIG,
                       geometry: Optional[VariantGeometry] = None,
                       header: Optional[VCFHeader] = None,
                       prefetch: int = 2) -> Dict[str, object]:
    """Distributed variant stats over a whole VCF/BCF (any container the
    dispatcher recognises): variant/SNP/PASS counts, mean ALT allele
    frequency, and per-sample call rates, reduced over the mesh's data
    axis."""
    from hadoop_bam_tpu.api.vcf_dataset import open_vcf
    from hadoop_bam_tpu.parallel.mesh import make_mesh

    ds = open_vcf(path, config)
    if header is None:
        header = ds.header
    if mesh is None:
        mesh = make_mesh()
    n_dev = int(np.prod(mesh.devices.shape))
    if geometry is None:
        geometry = VariantGeometry(n_samples=header.n_samples)
    cap = geometry.tile_records
    spans = ds.spans()
    step = make_variant_stats_step(mesh, geometry)
    sharding = NamedSharding(mesh, P("data"))
    n_workers = min(32, max(4, (os.cpu_count() or 4) * 4))
    window = max(1, prefetch) * n_workers
    totals = _StatTotals()
    with cf.ThreadPoolExecutor(max_workers=n_workers) as pool:
        from hadoop_bam_tpu.parallel.pipeline import decode_with_retry

        def decode(span):
            def inner(s):
                text = ds.read_span_text(s)
                if text is not None:  # fast tokenizer, no record objects
                    return pack_variant_tiles_from_text(text, header,
                                                        geometry)
                # BCF: binary fast scan — skips ID/INFO and non-GT FORMAT
                # fields entirely
                from hadoop_bam_tpu.formats.bcf import scan_variant_columns
                from hadoop_bam_tpu.split.vcf_planners import (
                    read_bcf_span_bytes,
                )
                raw = read_bcf_span_bytes(ds.path, s, ds._is_bgzf_bcf)
                return scan_variant_columns(raw, header,
                                            geometry.samples_pad)
            out = decode_with_retry(inner, span, config)
            if out is not None:
                return out
            return pack_variant_tiles(VariantBatch([], header), geometry)

        stream = _iter_windowed(pool, spans, decode, window)
        group: List[Dict[str, np.ndarray]] = []
        counts: List[int] = []

        def dispatch():
            cvec = np.zeros((n_dev,), dtype=np.int32)
            cvec[:len(counts)] = counts
            stacked = {}
            for k in group[0]:
                arrs = [g[k] for g in group]
                while len(arrs) < n_dev:
                    arrs.append(np.zeros_like(arrs[0]))
                stacked[k] = np.stack(arrs)
            args = [jax.device_put(stacked[k], sharding)
                    for k in ("chrom", "pos", "flags", "dosage")]
            c = jax.device_put(cvec, sharding)
            totals.add(*step(*args, c))   # async; drained once at the end
            group.clear()
            counts.clear()

        for tile, count in _iter_variant_tiles(stream, cap, geometry):
            group.append(tile)
            counts.append(count)
            if len(group) == n_dev:
                dispatch()
        if group:
            dispatch()
    if not totals:
        return {"n_variants": 0, "n_snp": 0, "n_pass": 0, "mean_af": 0.0,
                "sample_callrate": np.zeros(header.n_samples)}
    tf, ints = totals.drain()
    sum_af = float(tf[0])
    n_variants = int(ints[0])
    callrate = (ints[4:4 + header.n_samples].astype(np.float64)
                / max(n_variants, 1)
                if header.n_samples else np.zeros(0))
    return {
        "n_variants": n_variants,
        "n_snp": int(ints[1]),
        "n_pass": int(ints[2]),
        "mean_af": float(sum_af / max(int(ints[3]), 1)),
        "sample_callrate": callrate,
    }
