"""Adaptive arithmetic codec tests (formats/cram_arith.py, CRAM 3.1
block method 6).

Round-trips drive the decoder through the encoder's flag matrix
(order-0/1, RLE, PACK, STRIPE, CAT, EXT/bzip2, NOSZ and combinations);
block-level tests confirm real CRAM slice blocks using method 6 decode
end-to-end; corrupt streams must fail loudly.
"""
import random

import pytest

from hadoop_bam_tpu.formats.cram import ARITH, decompress_block_payload
from hadoop_bam_tpu.formats.cram_arith import (
    ARITH_CAT, ARITH_EXT, ARITH_NOSZ, ARITH_ORDER1, ARITH_PACK,
    ARITH_RLE, ARITH_STRIPE, ArithError, arith_decode, arith_encode,
)


def _qual_like(n, seed=7, alphabet=(2, 11, 25, 37, 40)):
    rng = random.Random(seed)
    out = bytearray()
    prev = rng.choice(alphabet)
    for _ in range(n):
        if rng.random() < 0.8:
            q = prev
        else:
            q = rng.choice(alphabet)
        out.append(q)
        prev = q
    return bytes(out)


FLAG_MATRIX = [
    0,
    ARITH_ORDER1,
    ARITH_RLE,
    ARITH_RLE | ARITH_ORDER1,
    ARITH_PACK,
    ARITH_PACK | ARITH_ORDER1,
    ARITH_PACK | ARITH_RLE,
    ARITH_STRIPE,
    ARITH_STRIPE | ARITH_ORDER1,
    ARITH_CAT,
    ARITH_EXT,
]


@pytest.mark.parametrize("flags", FLAG_MATRIX)
def test_roundtrip_flag_matrix(flags):
    data = _qual_like(4000)
    enc = arith_encode(data, flags)
    assert arith_decode(enc) == data


@pytest.mark.parametrize("flags", [0, ARITH_ORDER1, ARITH_RLE])
def test_roundtrip_nosz(flags):
    data = _qual_like(1500, seed=9)
    enc = arith_encode(data, flags | ARITH_NOSZ)
    assert arith_decode(enc, len(data)) == data
    with pytest.raises(ArithError):
        arith_decode(enc)              # NOSZ needs the external size


def test_roundtrip_edge_payloads():
    for data in (b"", b"A", b"A" * 10000, bytes(range(256)) * 5,
                 b"\x00" * 3000):
        for flags in (0, ARITH_ORDER1, ARITH_RLE, ARITH_PACK):
            assert arith_decode(arith_encode(data, flags)) == data


def test_adaptive_model_compresses_skew():
    data = b"\x05" * 9000 + _qual_like(1000)
    enc = arith_encode(data, ARITH_ORDER1)
    assert len(enc) < len(data) // 4


def test_rle_beats_order0_on_runs():
    data = b"".join(bytes([s]) * ln for s, ln in
                    zip([3, 9, 3, 40, 9] * 200, [30, 1, 25, 7, 40] * 200))
    rle = arith_encode(data, ARITH_RLE)
    assert arith_decode(rle) == data
    assert len(rle) < len(data) // 8


def test_block_dispatch_method6():
    """decompress_block_payload routes method 6 to the arith decoder —
    the last 3.1 method that previously raised."""
    data = _qual_like(2000, seed=21)
    enc = arith_encode(data, ARITH_ORDER1)
    assert decompress_block_payload(ARITH, enc, len(data)) == data


def test_full_cram31_file_with_arith_quality_blocks(tmp_path):
    """A 3.1 file whose quality blocks use method 6 reads end-to-end:
    encode a container normally, then transcode the QS block to arith."""
    import io

    from hadoop_bam_tpu.formats.bam import SAMHeader
    from hadoop_bam_tpu.formats.cram import (
        Block, read_container, scan_container_offsets,
    )
    from hadoop_bam_tpu.formats.cramio import CramWriter, read_cram
    from hadoop_bam_tpu.formats.sam import SamRecord

    hdr = SAMHeader.from_sam_text(
        "@HD\tVN:1.6\tSO:coordinate\n@SQ\tSN:c1\tLN:100000\n")
    recs = [SamRecord(qname=f"r{i}", flag=0, rname="c1", pos=1 + 5 * i,
                      mapq=60, cigar="20M", rnext="*", pnext=0, tlen=0,
                      seq="ACGTACGTACGTACGTACGT",
                      qual="".join(chr(33 + (i + j) % 40)
                                   for j in range(20)))
            for i in range(400)]
    sink = io.BytesIO()
    with CramWriter(sink, hdr, version=(3, 1)) as w:
        w.write_records(recs)
    data = bytearray(sink.getvalue())

    # rewrite every EXTERNAL block through arith method 6
    from hadoop_bam_tpu.formats.cram import (
        CORE_DATA, EXTERNAL_DATA, build_container, Container,
    )
    out = bytearray()
    pos = 0
    n_rewritten = 0
    from hadoop_bam_tpu.formats.cram import FileDefinition
    out += data[:FileDefinition.SIZE]
    pos = FileDefinition.SIZE
    while pos < len(data):
        cont, nxt = read_container(bytes(data), pos)
        if cont.header.is_eof:
            out += data[pos:nxt]
            pos = nxt
            continue
        blocks = []
        for blk in cont.blocks:
            if blk.content_type == EXTERNAL_DATA and len(blk.data) > 64:
                blocks.append(Block(blk.content_type, blk.content_id,
                                    blk.data, ARITH))
                n_rewritten += 1
            else:
                blocks.append(blk)
        h = cont.header
        out += build_container(
            blocks, ref_seq_id=h.ref_seq_id, start=h.start, span=h.span,
            n_records=h.n_records, record_counter=h.record_counter,
            bases=h.bases, landmarks=h.landmarks)
        pos = nxt
    assert n_rewritten > 0
    _, got = read_cram(bytes(out))
    assert [r.qual for r in got] == [r.qual for r in recs]
    assert [r.seq for r in got] == [r.seq for r in recs]


def test_corrupt_streams_fail_loudly():
    from hadoop_bam_tpu.formats.cram_codecs import RansError

    data = _qual_like(800)
    enc = bytearray(arith_encode(data, ARITH_ORDER1))
    # truncation inside the range-coder init surfaces as the normalized
    # codec error (RansError), never a bare IndexError
    with pytest.raises(RansError):
        arith_decode(bytes(enc[:4]))
    with pytest.raises(RansError):
        arith_decode(b"")
    bad = bytearray(enc)
    bad[1] ^= 0x7F                             # corrupt the size varint
    try:
        out = arith_decode(bytes(bad))
        assert len(out) != len(data)           # never silently right-sized
    except ValueError:
        pass


def test_desync_tripwire_exact_extent():
    """Decode must consume EXACTLY the compressed extent: trailing bytes
    after a valid stream (a desynced/garbage-padded block) raise the
    canonical CRAMError instead of silently decoding right-sized output."""
    from hadoop_bam_tpu.formats.cram import CRAMError
    from hadoop_bam_tpu.formats.cram_arith import ArithError

    data = _qual_like(600)
    for flags in (0, ARITH_ORDER1, ARITH_RLE, ARITH_PACK, ARITH_STRIPE):
        enc = arith_encode(data, flags)
        assert arith_decode(enc) == data           # exact extent: clean
        with pytest.raises(ArithError):
            arith_decode(enc + b"\x00\x01\x02")    # trailing garbage
    err = None
    try:
        arith_decode(arith_encode(data, 0) + b"\xff")
    except ArithError as e:
        err = e
    assert isinstance(err, CRAMError)              # block-boundary class
    assert "desync" in str(err)


def test_desync_tripwire_inside_stripe_substream():
    """A desync hidden inside one STRIPE sub-stream (its clen claims more
    bytes than its coder consumes) trips the sub-stream's own extent
    check rather than decoding shifted interleave columns."""
    from hadoop_bam_tpu.formats.cram_arith import ArithError
    from hadoop_bam_tpu.formats.cram_codecs_nx16 import (
        var_get_u32, var_put_u32,
    )

    data = _qual_like(4096)
    enc = bytes(arith_encode(data, ARITH_STRIPE))
    # parse the frame: flags, ulen varint, X, then X clen varints
    pos = 1
    ulen, pos = var_get_u32(enc, pos)
    x = enc[pos]
    pos += 1
    clens = []
    for _ in range(x):
        c, pos = var_get_u32(enc, pos)
        clens.append(c)
    subs = []
    for c in clens:
        subs.append(enc[pos:pos + c])
        pos += c
    assert pos == len(enc)
    # pad one garbage byte into sub-stream 0's claimed extent and rebuild
    subs[0] = subs[0] + b"\x5a"
    clens[0] += 1
    bad = bytearray(enc[:1])
    bad += var_put_u32(ulen)
    bad.append(x)
    for c in clens:
        bad += var_put_u32(c)
    for s in subs:
        bad += s
    with pytest.raises(ArithError):
        arith_decode(bytes(bad))
