"""Observability-layer tests (``pytest -m obs``): the trace ring,
log-bucketed histograms, contextvar-scoped MetricsContext isolation
(including across the shared decode pool), mesh-merge semantics,
exporters, and the Metrics concurrency edges the code previously only
commented about (reset racing an active wall span, nested same-name
spans, histogram merge associativity)."""
import json
import random
import threading
import time

import pytest

from hadoop_bam_tpu.obs import (
    Histogram, TraceRecorder, disable_tracing, enable_tracing,
    prometheus_text,
)
from hadoop_bam_tpu.utils.metrics import (
    METRICS, Metrics, MetricsContext, NullMetrics, base_metrics,
    current_metrics,
)

pytestmark = pytest.mark.obs


@pytest.fixture(autouse=True)
def _no_tracing_leak():
    """Every test starts and ends with tracing disabled (the default)."""
    disable_tracing()
    yield
    disable_tracing()


# ---------------------------------------------------------------------------
# histograms
# ---------------------------------------------------------------------------

def test_histogram_percentiles_within_bucket_error():
    h = Histogram()
    values = [0.001 * (i + 1) for i in range(1000)]   # 1ms..1s uniform
    for v in values:
        h.record(v)
    # log buckets are ~19% wide; allow one bucket of relative error
    for p, expect in ((50, 0.5), (95, 0.95), (99, 0.99)):
        got = h.percentile(p)
        assert expect * 0.75 <= got <= expect * 1.35, (p, got)
    s = h.summary()
    assert s["count"] == 1000
    assert s["max"] == pytest.approx(1.0)
    assert abs(s["mean"] - sum(values) / 1000) < 1e-9


def test_histogram_empty_and_single():
    h = Histogram()
    assert h.percentile(99) == 0.0 and h.summary()["count"] == 0
    h.record(0.25)
    # a single observation: every percentile is clamped to [min, max]
    assert h.percentile(1) == h.percentile(99) == pytest.approx(0.25,
                                                                rel=0.2)


def test_histogram_merge_associative_and_commutative():
    parts = []
    for seed in range(4):
        h = Histogram()
        r = random.Random(seed)
        for _ in range(500):
            h.record(r.lognormvariate(0.0, 3.0))
        parts.append(h)

    def combine(hs):
        out = Histogram()
        for h in hs:
            out.merge(Histogram.from_dict(h.to_dict()))   # detached
        return out.to_dict()

    left = combine([Histogram.from_dict(combine(parts[:2])), parts[2],
                    parts[3]])
    right = combine([parts[0], Histogram.from_dict(combine(parts[1:]))])
    shuffled = combine([parts[2], parts[0], parts[3], parts[1]])
    assert left == right == shuffled


def test_histogram_dict_round_trip():
    h = Histogram()
    for v in (1e-6, 0.5, 3.0, 3.0, 1e4):
        h.record(v)
    back = Histogram.from_dict(json.loads(json.dumps(h.to_dict())))
    assert back.to_dict() == h.to_dict()
    assert back.summary() == h.summary()


# ---------------------------------------------------------------------------
# trace ring
# ---------------------------------------------------------------------------

def test_trace_ring_bounds_and_drop_count():
    rec = TraceRecorder(capacity=32)
    for i in range(100):
        rec.complete(f"s{i}", float(i), 0.5)
    evs = rec.events()
    assert len(evs) == 32
    assert rec.dropped == 68
    # oldest surviving first, newest last
    assert evs[0][0] == "s68" and evs[-1][0] == "s99"
    doc = rec.chrome_trace()
    assert doc["otherData"]["dropped_events"] == 68


def test_chrome_trace_document_shape():
    rec = TraceRecorder()
    rec.complete("bam.inflate_wall", 1.0, 0.25, {"nbytes": 4096})
    doc = rec.chrome_trace(process_label="test", process_index=3)
    evs = doc["traceEvents"]
    meta = [e for e in evs if e["ph"] == "M"]
    spans = [e for e in evs if e["ph"] == "X"]
    assert any(e["name"] == "process_name" for e in meta)
    assert any(e["name"] == "thread_name" for e in meta)
    (ev,) = spans
    assert ev["pid"] == 3 and ev["dur"] == pytest.approx(0.25e6)
    assert ev["args"] == {"nbytes": 4096}
    assert ev["cat"] == "bam"
    json.dumps(doc)   # must be JSON-serializable as-is


def test_span_disabled_is_wall_timer_only():
    m = Metrics()
    with m.span("x.stage_wall", nbytes=1):
        time.sleep(0.002)
    assert m.wall_timers["x.stage_wall"] > 0
    assert m.wall_calls["x.stage_wall"] == 1


def test_span_enabled_records_ring_events_across_threads():
    rec = enable_tracing(1024)
    m = Metrics()

    def work(name):
        with m.span(name, part=name):
            time.sleep(0.002)

    ts = [threading.Thread(target=work, args=(f"pool.decode_{i}",))
          for i in range(3)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    with m.span("main.stage"):
        pass
    evs = rec.events()
    names = {e[0] for e in evs}
    assert {"pool.decode_0", "pool.decode_1", "pool.decode_2",
            "main.stage"} <= names
    assert len({e[3] for e in evs}) >= 2          # distinct thread ids
    assert any(e[5] == {"part": "pool.decode_1"} for e in evs)


def test_trace_save_is_loadable(tmp_path):
    rec = enable_tracing()
    with METRICS.span("query.resolve_wall"):
        pass
    out = rec.save(str(tmp_path / "t.json"))
    doc = json.load(open(out))
    assert any(e.get("name") == "query.resolve_wall"
               for e in doc["traceEvents"])


# ---------------------------------------------------------------------------
# Metrics.trace degradation (satellite: no bare import error in hot loops)
# ---------------------------------------------------------------------------

def test_trace_degrades_without_jax_profiler(monkeypatch):
    import sys
    monkeypatch.setitem(sys.modules, "jax", None)
    monkeypatch.setitem(sys.modules, "jax.profiler", None)
    m = Metrics()
    with m.trace("stage.t"):      # must not raise ImportError
        pass
    assert m.timer_calls["stage.t"] == 1


def test_trace_with_profiler_still_times():
    m = Metrics()
    with m.trace("stage.t2"):
        pass
    assert m.timer_calls["stage.t2"] == 1


# ---------------------------------------------------------------------------
# concurrency edges (satellite: the commented races, now pinned)
# ---------------------------------------------------------------------------

def test_reset_racing_active_wall_span_discards_cleanly():
    m = Metrics()
    cm = m.wall_timer("race.stage")
    cm.__enter__()
    m.reset()                      # races the open span
    cm.__exit__(None, None, None)  # must neither raise nor account
    assert "race.stage" not in m.wall_timers
    assert m._wall_active == {}
    # and a FRESH span after the reset accounts normally
    with m.wall_timer("race.stage"):
        pass
    assert m.wall_calls["race.stage"] == 1


def test_reset_race_does_not_corrupt_new_epoch_spans():
    m = Metrics()
    old = m.wall_timer("s")
    old.__enter__()
    m.reset()
    new = m.wall_timer("s")        # new-epoch span opens before old exits
    new.__enter__()
    old.__exit__(None, None, None)  # stale exit: discarded, not counted
    new.__exit__(None, None, None)
    assert m.wall_calls["s"] == 1


def test_nested_same_name_wall_spans_union_once():
    m = Metrics()
    t0 = time.perf_counter()
    with m.wall_timer("n.stage"):
        with m.wall_timer("n.stage"):
            time.sleep(0.004)
        time.sleep(0.002)
    outer = time.perf_counter() - t0
    assert m.wall_calls["n.stage"] == 1          # ONE union span
    assert m.wall_timers["n.stage"] == pytest.approx(outer, abs=0.05)
    assert m.wall_timers["n.stage"] >= 0.006 * 0.5


def test_overlapping_thread_spans_union_not_sum():
    m = Metrics()

    def work():
        with m.wall_timer("o.stage"):
            time.sleep(0.02)

    ts = [threading.Thread(target=work) for _ in range(4)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    # four ~20ms spans overlapping: the union must be far below the
    # 80ms thread-sum
    assert m.wall_timers["o.stage"] < 0.06


# ---------------------------------------------------------------------------
# MetricsContext isolation + pool propagation
# ---------------------------------------------------------------------------

def test_metrics_context_isolates_and_falls_back():
    base_before = base_metrics().get("ctx.ticks")
    with MetricsContext() as a:
        METRICS.count("ctx.ticks", 2)
        with MetricsContext() as b:               # nested
            METRICS.count("ctx.ticks", 5)
        assert current_metrics() is a
    assert a.get("ctx.ticks") == 2
    assert b.get("ctx.ticks") == 5
    assert base_metrics().get("ctx.ticks") == base_before   # untouched
    assert current_metrics() is base_metrics()


def test_two_threads_with_separate_contexts_do_not_smear():
    out = {}

    def run(name, n):
        with MetricsContext() as m:
            for _ in range(n):
                METRICS.count("smear.test")
            out[name] = m.get("smear.test")

    t1 = threading.Thread(target=run, args=("a", 3))
    t2 = threading.Thread(target=run, args=("b", 7))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert out == {"a": 3, "b": 7}


def test_pool_submit_carries_context_and_records_histograms():
    import concurrent.futures as cf

    from hadoop_bam_tpu.utils import pools

    pool = cf.ThreadPoolExecutor(max_workers=2)
    try:
        with MetricsContext() as m:
            futs = [pools.submit(pool, lambda i=i: METRICS.count(
                "pooled.work", i)) for i in (1, 2, 4)]
            for f in futs:
                f.result()
        assert m.get("pooled.work") == 7          # landed in the context
        assert base_metrics().get("pooled.work") == 0
        assert m.hist_summary("pool.task_wait_s")["count"] == 3
        assert m.hist_summary("pool.task_run_s")["count"] == 3
    finally:
        pool.shutdown()


def test_null_metrics_is_inert():
    with MetricsContext(NullMetrics()) as m:
        METRICS.count("null.tick")
        METRICS.observe("null.h", 1.0)
        with METRICS.span("null.span"):
            pass
        with METRICS.timer("null.t"):
            pass
    assert m.counters == {} and m.histograms == {}
    assert m.wall_timers == {} and m.timers == {}


# ---------------------------------------------------------------------------
# mesh-wide merge semantics
# ---------------------------------------------------------------------------

def _host(seed, wall):
    m = Metrics()
    r = random.Random(seed)
    m.count("pipeline.records", 100 * (seed + 1))
    with m.timer("pipeline.inflate"):
        pass
    m.timers["pipeline.inflate"] = 0.5 * (seed + 1)
    m.add_wall("pipeline.feed_wall", wall)
    for _ in range(200):
        m.observe("query.latency_s", r.lognormvariate(-3, 1))
    return m


def test_merge_dict_sums_counters_maxes_walls_merges_hists():
    hosts = [_host(0, 1.0), _host(1, 3.0), _host(2, 2.0)]
    merged = Metrics()
    for h in hosts:
        merged.merge_dict(h.to_dict())
    assert merged.get("pipeline.records") == 600
    assert merged.timers["pipeline.inflate"] == pytest.approx(3.0)
    # wall = slowest host, not the sum
    assert merged.wall_timers["pipeline.feed_wall"] == pytest.approx(3.0)
    assert merged.hist_summary("query.latency_s")["count"] == 600
    # fold-order invariance (the allgather gives no ordering guarantee):
    # bucket counts are exactly associative; the float `total` sum is
    # order-sensitive only at machine epsilon
    other = Metrics()
    for h in reversed(hosts):
        other.merge_dict(h.to_dict())
    a = other.to_dict()
    b = merged.to_dict()
    assert a["histograms"]["query.latency_s"]["buckets"] \
        == b["histograms"]["query.latency_s"]["buckets"]
    assert a["histograms"]["query.latency_s"]["total"] \
        == pytest.approx(b["histograms"]["query.latency_s"]["total"])
    for key in ("counters", "timers", "wall_timers", "wall_calls"):
        assert a[key] == b[key]


def test_merge_metrics_single_process_returns_detached_copy():
    from hadoop_bam_tpu.parallel.distributed import merge_metrics

    with MetricsContext() as m:
        METRICS.count("merge.tick", 4)
        merged = merge_metrics()
    assert merged.get("merge.tick") == 4
    merged.count("merge.tick")                    # mutating the copy...
    assert m.get("merge.tick") == 4               # ...not the original


# ---------------------------------------------------------------------------
# exporters
# ---------------------------------------------------------------------------

def test_prometheus_exposition_shape():
    m = _host(1, 2.0)
    text = prometheus_text(m, labels={"host": "h1"})
    assert '# TYPE hbam_pipeline_records_total counter' in text
    assert 'hbam_pipeline_records_total{host="h1"} 200' in text
    assert '# TYPE hbam_pipeline_feed_wall_seconds gauge' in text
    assert '# TYPE hbam_query_latency_s histogram' in text
    # cumulative buckets: the +Inf bucket equals _count
    lines = text.splitlines()
    inf = next(ln for ln in lines
               if ln.startswith("hbam_query_latency_s_bucket")
               and '+Inf' in ln)
    count = next(ln for ln in lines
                 if ln.startswith("hbam_query_latency_s_count"))
    assert inf.rsplit(" ", 1)[1] == count.rsplit(" ", 1)[1] == "200"
    # bucket counts are non-decreasing
    vals = [int(ln.rsplit(" ", 1)[1]) for ln in lines
            if ln.startswith("hbam_query_latency_s_bucket")]
    assert vals == sorted(vals)


def test_metrics_snapshot_file_round_trip(tmp_path):
    from hadoop_bam_tpu.obs import load_metrics_json, save_metrics_json

    m = _host(2, 1.5)
    path = save_metrics_json(m, str(tmp_path / "m.json"))
    back = Metrics.from_dict(load_metrics_json(path))
    assert back.to_dict() == m.to_dict()


# ---------------------------------------------------------------------------
# end to end: hbam query --trace / --metrics-json and `hbam metrics`
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def indexed_bam(tmp_path_factory):
    from fixtures import make_header, make_records

    from hadoop_bam_tpu.formats.bamio import BamWriter
    from hadoop_bam_tpu.split.bai import write_bai

    path = str(tmp_path_factory.mktemp("obs") / "o.bam")
    header = make_header(2)

    def key(r):
        rid = (header.ref_names.index(r.rname) if r.rname != "*"
               else 1 << 30)
        return (rid, r.pos)

    recs = sorted(make_records(header, 600, seed=3), key=key)
    with BamWriter(path, header) as w:
        for r in recs:
            w.write_sam_record(r)
    write_bai(path)
    return path


def test_cli_query_trace_and_metrics_json(indexed_bam, tmp_path, capsys):
    from hadoop_bam_tpu.tools import cli

    trace_path = str(tmp_path / "trace.json")
    snap_path = str(tmp_path / "snap.json")
    rc = cli.main(["query", indexed_bam, "chr1:1-5000", "chr2:1-2000",
                   "-c", "--trace", trace_path,
                   "--metrics-json", snap_path])
    assert rc == 0
    capsys.readouterr()

    doc = json.load(open(trace_path))
    names = {e["name"] for e in doc["traceEvents"] if e["ph"] == "X"}
    # the acceptance set: resolve -> chunk decode -> mesh filter, plus
    # the staging pack/dispatch underneath
    assert {"query.resolve_wall", "query.decode_wall",
            "query.filter_wall", "query.dispatch_wall",
            "staging.pack"} <= names

    snap = json.load(open(snap_path))
    assert snap["counters"]["query.requests"] == 2
    assert snap["histograms"]["query.latency_s"]["count"] >= 1
    assert snap["histograms"]["query.chunk_fetch_s"]["count"] >= 1

    # the metrics verb renders and exports the snapshot
    assert cli.main(["metrics", snap_path]) == 0
    out = capsys.readouterr().out
    assert "query.latency_s" in out and "counter query.requests = 2" in out
    assert cli.main(["metrics", snap_path, "--format",
                     "prometheus"]) == 0
    out = capsys.readouterr().out
    assert "# TYPE hbam_query_latency_s histogram" in out


def test_query_latency_histogram_records_per_batch(indexed_bam):
    from hadoop_bam_tpu.query import QueryEngine, QueryRequest

    with MetricsContext() as m:
        engine = QueryEngine()
        for region in ("chr1:1-2000", "chr1:2000-9000", "chr2:1-800"):
            engine.query_records([QueryRequest(indexed_bam, region)])
    lat = m.hist_summary("query.latency_s")
    assert lat["count"] == 3
    assert lat["p99"] >= lat["p50"] > 0
